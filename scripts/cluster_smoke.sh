#!/bin/bash
# CI smoke for the pod observability fabric on one host: a detached
# `bst serve` daemon hosts the telemetry relay collector, two local
# worker processes push into it (BST_TELEMETRY_RELAY + identity-only
# BST_PROCESS_ID ranks), and the daemon's aggregated live plane must
# show them: /metrics carries host/process_index-labeled series from
# BOTH ranks, /healthz flips to 503 naming the rank whose process is
# killed (and recovers when it restarts), `bst top --cluster` renders
# the per-host rows, and `bst trace-dump --cluster` folds every rank's
# live flight-recorder ring into one Perfetto file trace-report loads.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-cluster-smoke.XXXXXX)
SOCK="$WORK/bst.sock"
WORKER_PIDS=""
cleanup () {
    for pid in $WORKER_PIDS; do kill -9 "$pid" 2>/dev/null || true; done
    rm -rf "$WORK"
}
trap cleanup EXIT

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
# a silent rank flips the pod verdict after 2s (read per evaluation)
export BST_STALL_TIMEOUT_S=2

bst () { (cd "$REPO" && $PYTHON -m bigstitcher_spark_tpu.cli.main "$@"); }

# live-plane probe: prints "<status> <body>" even for non-200 answers;
# tolerates the consumer (grep -q) closing the pipe early
fetch () { $PYTHON -c '
import sys, urllib.request, urllib.error
try:
    with urllib.request.urlopen(sys.argv[1], timeout=10) as r:
        code, body = r.status, r.read().decode()
except urllib.error.HTTPError as e:
    code, body = e.code, e.read().decode()
try:
    print(code, body)
except BrokenPipeError:
    pass
' "$1"; }

retry () {  # retry <seconds> <command...>
    local deadline=$(( $(date +%s) + $1 )); shift
    until "$@"; do
        [ "$(date +%s)" -lt "$deadline" ] || return 1
        sleep 0.5
    done
}

free_port () { $PYTHON -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()'; }
PORT=$(free_port)
RPORT=$(free_port)
export BST_METRICS_PORT="$PORT"

echo '[smoke] starting daemon (collector + exporter) ...'
(bst serve --detach --socket "$SOCK" --slots 1 --idle-timeout 300 \
    --relay "127.0.0.1:$RPORT")

# a relayed worker: identity-only rank id, pushes heartbeats + metric
# snapshots until killed (the relay bring-up rides init_distributed)
cat > "$WORK/worker.py" <<'EOF'
import os, time
from bigstitcher_spark_tpu.parallel.distributed import init_distributed
init_distributed()
from bigstitcher_spark_tpu.observe import metrics, progress, relay, trace
assert relay.client() is not None, "worker did not become a push client"
rank = int(os.environ["BST_PROCESS_ID"])
metrics.counter("bst_io_read_bytes_total", op="smoke",
                path="native").inc(1000 + rank)
hb = progress.Heartbeat("smoke-stage", total=100000, every_s=0.0)
while True:
    with trace.span("barrier", stage="smoke"):
        hb.tick()
    time.sleep(0.05)
EOF

start_worker () {  # start_worker <rank> -> pid
    # the WHOLE backgrounded subshell redirects to the log, so the
    # command substitution capturing the pid never waits on the worker
    (
        cd "$REPO"
        export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
        export BST_TELEMETRY_RELAY="127.0.0.1:$RPORT"
        export BST_PROCESS_ID=$1 BST_RELAY_INTERVAL_S=0.2 BST_METRICS_PORT=0
        exec $PYTHON "$WORK/worker.py"
    ) > "$WORK/worker-$1.log" 2>&1 &
    echo $!
}

echo '[smoke] starting two relayed workers ...'
W0=$(start_worker 0); W1=$(start_worker 1)
WORKER_PIDS="$W0 $W1"

echo '[smoke] waiting for both ranks on the aggregated /metrics ...'
has_rank () { fetch "http://127.0.0.1:$PORT/metrics" | grep -q "process_index=\"$1\""; }
retry 90 has_rank 0
retry 90 has_rank 1
# each rank's own workload counter arrives host/process_index-labeled
# (retried: a rank's very first snapshot can predate its counter inc)
has_counter () {
    fetch "http://127.0.0.1:$PORT/metrics" | grep -q \
        "bst_io_read_bytes_total{host=\"[^\"]*\",process_index=\"$1\",op=\"smoke\",path=\"native\"} $2"
}
retry 30 has_counter 0 1000
retry 30 has_counter 1 1001

echo '[smoke] pod verdict healthy while both ranks beat ...'
fetch "http://127.0.0.1:$PORT/healthz" | grep -q '"ok": true'

echo '[smoke] cluster view:'
(bst top --cluster --once --socket "$SOCK")

echo '[smoke] killing rank 1 -> /healthz must flip 503 naming it ...'
kill -9 "$W1"
unhealthy () {  # 503 AND the silent-rank entry names process_index 1
    local body
    body=$(fetch "http://127.0.0.1:$PORT/healthz")
    echo "$body" | head -1 | grep -q '^503 ' \
        && echo "$body" | grep -q '"process_index": 1'
}
retry 30 unhealthy
echo '[smoke] restarting rank 1 -> /healthz must recover ...'
W1=$(start_worker 1)
WORKER_PIDS="$W0 $W1"
healthy () { fetch "http://127.0.0.1:$PORT/healthz" | head -1 | grep -q '^200 '; }
retry 90 healthy

echo '[smoke] cluster trace dump ...'
(bst trace-dump --cluster --socket "$SOCK" --out "$WORK/pod-trace.json")
test -s "$WORK/pod-trace.json"
(bst trace-report "$WORK/pod-trace.json" > "$WORK/trace-report.txt")
test -s "$WORK/trace-report.txt"

echo '[smoke] draining ...'
kill -9 $WORKER_PIDS 2>/dev/null || true
WORKER_PIDS=""
(bst serve --stop --socket "$SOCK")

echo '[smoke] ok'

#!/bin/bash
# CI smoke for the `bst serve` daemon on the CPU fallback: start a
# detached daemon on a scratch socket, submit one tiny affine fusion
# through it, list the job table, drain cleanly, and exit 0 only if every
# step did. The idle timeout guarantees a crashed client can never leak a
# resident daemon into the CI host.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-serve-smoke.XXXXXX)
SOCK="$WORK/bst.sock"
trap 'rm -rf "$WORK"' EXIT

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

# run from the repo so the package imports; every path below is absolute
bst () { (cd "$REPO" && $PYTHON -m bigstitcher_spark_tpu.cli.main "$@"); }

# live-exporter probe (python, not curl — curl is not on every CI host):
# prints the body, exits non-zero on a non-200 status
fetch () { $PYTHON -c '
import sys, urllib.request
with urllib.request.urlopen(sys.argv[1], timeout=10) as r:
    sys.stdout.write(r.read().decode())
' "$1"; }

# a free TCP port for the daemon's HTTP exporter
PORT=$($PYTHON -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()')
export BST_METRICS_PORT="$PORT"

echo '[smoke] building tiny fixture ...'
(cd "$REPO" && $PYTHON - "$WORK" <<'EOF'
import sys
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
make_synthetic_project(sys.argv[1] + "/proj", n_tiles=(2, 1, 1),
                       tile_size=(64, 64, 32), overlap=16, jitter=1.0,
                       n_beads_per_tile=20)
EOF
)

echo '[smoke] starting daemon ...'
(bst serve --detach --socket "$SOCK" --slots 1 \
    --idle-timeout 300)

echo '[smoke] submitting fusion ...'
(bst submit --socket "$SOCK" create-fusion-container \
     -x "$WORK/proj/dataset.xml" -o "$WORK/proj/fused.ome.zarr" \
     -s ZARR -d UINT16 --minIntensity 0 --maxIntensity 65535 && \
 bst submit --socket "$SOCK" affine-fusion -o "$WORK/proj/fused.ome.zarr")

echo '[smoke] job table:'
(bst jobs --socket "$SOCK")

echo '[smoke] live exporter ...'
# /healthz must answer 200 with ok:true on a healthy draining-free daemon
fetch "http://127.0.0.1:$PORT/healthz" | grep -q '"ok": true'
# /metrics must expose a declared bst_serve_* series with live values
fetch "http://127.0.0.1:$PORT/metrics" | grep -q '^bst_serve_jobs_submitted_total 2'
fetch "http://127.0.0.1:$PORT/metrics" | grep -q '^bst_process_uptime_seconds'
echo '[smoke] live view:'
(bst top --once --socket "$SOCK")
echo '[smoke] trace dump:'
(bst trace-dump --socket "$SOCK" --out "$WORK/live-trace.json")
test -s "$WORK/live-trace.json"

echo '[smoke] draining ...'
(bst serve --stop --socket "$SOCK")

echo '[smoke] ok'

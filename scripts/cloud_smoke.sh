#!/bin/bash
# CI smoke for the tiered storage IO engine over the in-repo S3-protocol
# fake (utils/s3_fake.py) with injected per-request latency:
#   1. resave the same tiny dataset onto the fake S3 root AND a plain
#      local root (the parity reference), and assert the resaved s0 is
#      bit-identical across the two;
#   2. affine-fuse over s3 with the async prefetcher + NVMe spill tier
#      under an undersized chunk LRU and assert the prefetcher actually
#      served consumer reads (prefetch hit bytes > 0);
#   3. rerun the same fusion warm in the same process and assert it read
#      ZERO chunk bytes from the remote store (memory LRU + disk tier
#      served everything);
#   4. assert both fused volumes are bitwise identical to the local-root
#      fusion.
# Exits 0 only if every assertion held.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-cloud-smoke.XXXXXX)
trap 'rm -rf "$WORK"' EXIT

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
# the fake accepts and ignores SigV4, but tensorstore's s3 driver
# insists on finding credentials before it signs anything
export AWS_ACCESS_KEY_ID=${AWS_ACCESS_KEY_ID:-smoke}
export AWS_SECRET_ACCESS_KEY=${AWS_SECRET_ACCESS_KEY:-smokesecret}

# cold leg + warm rerun must share one process: the decoded-chunk LRU
# and the run-scoped disk tier are process-lived, exactly like a
# `bst serve` daemon running two jobs back to back — so the whole
# sequence drives the real CLI commands through one interpreter
(cd "$REPO" && $PYTHON - "$WORK" <<'EOF'
import hashlib
import os
import sys

import numpy as np
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io import chunkcache, prefetch, uris
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, bump_remote_pin
from bigstitcher_spark_tpu.observe import metrics
from bigstitcher_spark_tpu.utils.s3_fake import S3FakeServer
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

work = sys.argv[1]
srv = S3FakeServer().start()          # latency stays 0 through resave
uris.set_s3_endpoint(srv.endpoint)
uris.set_s3_region("us-east-1")
runner = CliRunner()


def ok(args):
    r = runner.invoke(cli, args, catch_exceptions=False)
    assert r.exit_code == 0, r.output


def sha(uri, dataset):
    data = np.asarray(ChunkStore.open(uri).open_dataset(dataset).read_full())
    return hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()


proj = make_synthetic_project(os.path.join(work, "proj"),
                              n_tiles=(2, 1, 1), tile_size=(64, 64, 32),
                              overlap=16, jitter=0.0, n_beads_per_tile=10,
                              seed=7)
print("[smoke] resaving onto fake s3 + local parity root ...")
resave = ["--N5", "--blockSize", "16,16,16", "-ds", "1,1,1; 2,2,1"]
xml_s3 = os.path.join(work, "resaved-s3.xml")
xml_local = os.path.join(work, "resaved-local.xml")
local_n5 = os.path.join(work, "src.n5")
ok(["resave", "-x", proj.xml_path, "-xo", xml_s3,
    "-o", "s3://smoke/src.n5", *resave])
ok(["resave", "-x", proj.xml_path, "-xo", xml_local,
    "-o", local_n5, *resave])
s0 = "setup0/timepoint0/s0"
assert sha("s3://smoke/src.n5", s0) == sha(local_n5, s0), \
    "resaved s0 over the fake s3 differs from the local root"

fused_s3 = "s3://smoke/fused.zarr"
fused_local = os.path.join(work, "fused-local.zarr")
for uri, xml in ((fused_s3, xml_s3), (fused_local, xml_local)):
    ok(["create-fusion-container", "-x", xml, "-o", uri, "-s", "ZARR",
        "-d", "UINT16", "--blockSize", "32,32,32",
        "--minIntensity", "0", "--maxIntensity", "65535"])
ok(["affine-fusion", "-o", fused_local])
sha_local = sha(fused_local, "0")

# tiered engine on: prefetcher + disk tier under a chunk LRU sized far
# below the source working set, so spills (and the warm rerun's
# promotes) genuinely cross the disk tier
os.environ.update({"BST_PREFETCH_BYTES": str(64 << 20),
                   "BST_PREFETCH_THREADS": "4",
                   "BST_REMOTE_CACHE": "run",
                   "BST_DISK_TIER_BYTES": str(64 << 20),
                   "BST_DISK_TIER_DIR": os.path.join(work, "tier"),
                   "BST_CHUNK_CACHE_BYTES": str(128 << 10),
                   "BST_TILE_CACHE_BYTES": "0"})
prefetch.reset()
chunkcache.get_cache().clear()
bump_remote_pin()
srv.latency_s = 0.02

remote_read = metrics.counter("bst_io_remote_read_bytes_total")
pf_hit_bytes = metrics.counter("bst_io_prefetch_hit_bytes_total")
tier_hit_bytes = metrics.counter("bst_io_disktier_hit_bytes_total")

print("[smoke] cold fusion over s3 (prefetch + disk tier) ...")
ok(["affine-fusion", "-o", fused_s3])
prefetch.drain(timeout_s=10)
assert pf_hit_bytes.value > 0, \
    "prefetcher served no consumer reads on the cold leg"
print(f"[smoke]   prefetch hit bytes: {pf_hit_bytes.value}")

print("[smoke] warm rerun (must not touch the remote store) ...")
before = remote_read.value
tier_before = tier_hit_bytes.value
ok(["affine-fusion", "-o", fused_s3])
prefetch.drain(timeout_s=10)
leaked = remote_read.value - before
assert leaked == 0, \
    f"warm rerun re-read {leaked} chunk bytes from the remote store"
assert tier_hit_bytes.value > tier_before, \
    "warm rerun never promoted a chunk from the disk tier"
print(f"[smoke]   disk tier hit bytes: {tier_hit_bytes.value - tier_before}")

srv.latency_s = 0.0                    # parity readback untimed
assert sha(fused_s3, "0") == sha_local, \
    "fused output over the tiered s3 path differs from the local root"
srv.stop()
print("[smoke] parity ok: fused s3 == fused local, resaved s0 s3 == local")
EOF
)

echo '[smoke] PASS: prefetch hits > 0, warm rerun read 0 remote bytes,'
echo '[smoke]       fused + resaved outputs bit-identical to local root'

#!/bin/bash
# CI smoke for the closed telemetry loop on the CPU fallback:
#   1. record a real tiny-fusion run (telemetry + history) under a
#      deliberately starved chunk cache so the advisor has a genuine
#      bottleneck to find, and assert `bst tune advise` fires a rule;
#   2. run a 2-trial `bst tune run` and assert it writes a profile with
#      every trial recorded as a tune-trial history record;
#   3. replay a fusion under the stored profile via `bst tune apply`
#      and assert it exits cleanly.
# Exits 0 only if every step did.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-tune-smoke.XXXXXX)
HIST="$WORK/history"
trap 'rm -rf "$WORK"' EXIT

# 2 virtual devices, not the usual 8: this smoke's fixture is 64 tiny
# views and the per-view dispatch overhead of a wide virtual mesh on a
# small CI core count dominates the actual work
export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=2"

# run from the repo so the package imports; every path below is absolute
bst () { (cd "$REPO" && $PYTHON -m bigstitcher_spark_tpu.cli.main "$@"); }

echo '[smoke] building tiny fixture ...'
# 64 single-chunk tiles: enough chunk-cache traffic to clear the
# advisor's 64-lookup significance floor with a genuinely starved cache
(cd "$REPO" && $PYTHON - "$WORK" <<'EOF'
import sys
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
make_synthetic_project(sys.argv[1] + "/proj", n_tiles=(8, 8, 1),
                       tile_size=(16, 16, 8), overlap=4, jitter=0.0,
                       n_beads_per_tile=3)
EOF
)

echo '[smoke] recording a starved-cache fusion run ...'
bst create-fusion-container -x "$WORK/proj/dataset.xml" \
    -o "$WORK/proj/fused.ome.zarr" -s ZARR -d UINT16 \
    --minIntensity 0 --maxIntensity 65535
# a ~4-chunk cache (each 16x16x8 uint16 tile is one 4096-byte chunk):
# every lookup misses and almost every insert evicts, the exact thrash
# signature the chunk_cache_thrash rule looks for. The knob applies to
# this run only, not this shell's exported env — --telemetry-dir +
# BST_HISTORY_DIR close the recording loop.
BST_HISTORY_DIR="$HIST" BST_CHUNK_CACHE_BYTES=20000 \
    bst affine-fusion -o "$WORK/proj/fused.ome.zarr" \
    --telemetry-dir "$WORK/tel"

echo '[smoke] advising on the recorded run ...'
ADVICE=$(bst tune advise --history-dir "$HIST" --json)
echo "$ADVICE"
echo "$ADVICE" | grep -q '"rule"' \
    || { echo 'FAIL: advisor fired no rule on a starved-cache run'; exit 1; }

echo '[smoke] 2-trial autotune ...'
bst tune run --history-dir "$HIST" --workload tiny-fusion \
    --trials 1 --max-trials 2 --knob BST_WRITE_THREADS
test -f "$HIST/profiles.json" \
    || { echo 'FAIL: tune run wrote no profile store'; exit 1; }
bst tune list --history-dir "$HIST" | grep -q tiny-fusion \
    || { echo 'FAIL: stored profile not listed'; exit 1; }
TRIALS=$(bst history list --history-dir "$HIST" --tool tune-trial --json \
    | grep -c '"id"')
[ "$TRIALS" -ge 2 ] \
    || { echo "FAIL: expected >=2 tune-trial records, got $TRIALS"; exit 1; }

echo '[smoke] replaying a fusion under the stored profile ...'
bst tune apply --history-dir "$HIST" auto
bst tune apply --history-dir "$HIST" auto \
    affine-fusion -o "$WORK/proj/fused.ome.zarr"

echo '[smoke] OK'

#!/bin/bash
# CI smoke for the multi-host execution world on one machine: two REAL
# local CPU processes form a jax.distributed world (gloo collectives)
# with the cross-host block exchange on, run the streamed
# resave -> create(rank 0) -> fuse pipeline SPMD, and exit 0 only if
# - both ranks pulled remote-owned chunks over TCP
#   (bst_dag_xhost_bytes_total > 0 on the resaved edge),
# - the elided intermediate re-read ZERO container bytes,
# - the fused s0 volume is BITWISE identical across both ranks AND to a
#   single-process run of the same spec,
# - the global solve mesh spanned both processes and the default-on
#   pair split covered the task list exactly once.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-multihost-smoke.XXXXXX)
WORKER_PIDS=""
cleanup () {
    for pid in $WORKER_PIDS; do kill -9 "$pid" 2>/dev/null || true; done
    rm -rf "$WORK"
}
trap cleanup EXIT

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=4"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

free_port () { $PYTHON -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()'; }

COORD_PORT=$(free_port)
XPORT0=$(free_port)
XPORT1=$(free_port)

echo '[smoke] building tiny fixture ...'
(cd "$REPO" && $PYTHON - "$WORK" <<'EOF'
import sys
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
make_synthetic_project(sys.argv[1] + "/proj", n_tiles=(2, 1, 1),
                       tile_size=(64, 64, 32), overlap=16, jitter=1.0,
                       n_beads_per_tile=20, seed=7)
EOF
)

cat > "$WORK/worker.py" <<'EOF'
import hashlib, json, os, sys
import numpy as np
from bigstitcher_spark_tpu.parallel.distributed import init_distributed, world
joined = init_distributed()   # False in the single-process golden run
from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.dag.executor import run_pipeline
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
from bigstitcher_spark_tpu.ops import solve as OS
from bigstitcher_spark_tpu.parallel import pairsched

rank, pc = world()
assert joined or pc == 1, "worker failed to join the jax world"
proj = sys.argv[1]
xml = os.path.join(proj, "dataset.xml")
rexml = os.path.join(proj, "re.xml")

if pc > 1:
    # the global solve mesh must be auto-on and span both processes
    assert OS.global_enabled(), "BST_SOLVE_GLOBAL auto must follow the world"
    with config.overrides({"BST_SOLVE_SHARD": 1}):
        n, g = OS.solve_layout(64)
        ndev, nproc = OS.global_axis_span(n, g)
    assert g and nproc == pc, (n, g, ndev, nproc)
    # the default-on pair split covers the list exactly once
    assert pairsched.multihost_active()

tasks = [pairsched.PairTask(index=i, cost=float(1 + i % 4))
         for i in range(11)]
ran = []
vals = pairsched.run_pair_tasks(
    tasks, lambda t: (ran.append(t.index), t.index * 3)[1],
    stage="smoke")
assert vals == [i * 3 for i in range(11)], vals
assert len(ran) == 11 if pc == 1 else 0 < len(ran) < 11, ran

spec = {
    "name": "mh-smoke",
    "datasets": {
        "resaved": {"path": os.path.join(proj, "resaved.n5"),
                    "ephemeral": True},
        "fused": {"path": os.path.join(proj, "fused.n5")},
    },
    "stages": [
        {"id": "resave", "tool": "resave",
         "args": ["-x", xml, "-xo", rexml, "-o", "@resaved", "--N5",
                  "--blockSize", "32,32,16", "-ds", "1,1,1"],
         "writes": ["resaved"]},
        {"id": "create", "tool": "create-fusion-container",
         "args": ["-x", rexml, "-o", "@fused", "-s", "N5", "-d", "UINT16",
                  "--minIntensity", "0", "--maxIntensity", "65535",
                  "--blockSize", "32,32,16"],
         "after": ["resave"], "ranks": [0]},
        {"id": "fuse", "tool": "affine-fusion", "args": ["-o", "@fused"],
         "after": ["create"], "reads": ["resaved"], "writes": ["fused"]},
    ],
}
res = run_pipeline(spec, workdir=proj)
d = res.to_dict()
assert res.ok, d
edge = {e["edge"]: e for e in d["edges"]}["resaved"]
ds = ChunkStore.open(os.path.join(proj, "fused.n5")).open_dataset("ch0tp0/s0")
arr = ds.read((0, 0, 0), ds.shape)
print("RESULT " + json.dumps({
    "rank": rank, "world": pc,
    "xhost_bytes": int(edge["bytes_xhost"]),
    "reread": int(edge["bytes_reread"]),
    "local_pairs": len(ran),
    "s0_sha": hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()).hexdigest(),
}), flush=True)
EOF

echo '[smoke] launching 2-process world ...'
for RANK in 0 1; do
    env BST_COORDINATOR="127.0.0.1:$COORD_PORT" \
        BST_NUM_PROCESSES=2 BST_PROCESS_ID=$RANK \
        BST_DAG_EXCHANGE_ADDR="127.0.0.1:$XPORT0,127.0.0.1:$XPORT1" \
        $PYTHON "$WORK/worker.py" "$WORK/proj" \
        > "$WORK/rank$RANK.log" 2>&1 &
    WORKER_PIDS="$WORKER_PIDS $!"
done
FAIL=0
for pid in $WORKER_PIDS; do wait "$pid" || FAIL=1; done
WORKER_PIDS=""
if [ "$FAIL" != 0 ]; then
    echo '[smoke] a rank failed:'; tail -n 40 "$WORK"/rank*.log; exit 1
fi

echo '[smoke] running the single-process golden ...'
rm -rf "$WORK/golden" && mkdir -p "$WORK/golden"
(cd "$REPO" && $PYTHON - "$WORK/golden" <<'EOF'
import sys
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
make_synthetic_project(sys.argv[1] + "/proj", n_tiles=(2, 1, 1),
                       tile_size=(64, 64, 32), overlap=16, jitter=1.0,
                       n_beads_per_tile=20, seed=7)
EOF
)
env -u BST_NUM_PROCESSES -u BST_PROCESS_ID -u BST_COORDINATOR \
    -u BST_DAG_EXCHANGE_ADDR \
    $PYTHON "$WORK/worker.py" "$WORK/golden/proj" \
    > "$WORK/golden.log" 2>&1 || {
        echo '[smoke] golden run failed:'; tail -n 40 "$WORK/golden.log"
        exit 1
    }

echo '[smoke] verifying parity ...'
$PYTHON - "$WORK" <<'EOF'
import json, sys
work = sys.argv[1]
def report(path):
    for line in open(path):
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise SystemExit(f"no RESULT in {path}")
r0, r1 = report(f"{work}/rank0.log"), report(f"{work}/rank1.log")
g = report(f"{work}/golden.log")
assert (r0["world"], r1["world"], g["world"]) == (2, 2, 1)
for r in (r0, r1):
    assert r["xhost_bytes"] > 0, r      # chunks really crossed the wire
    assert r["reread"] == 0, r          # ... and were never re-decoded
assert r0["local_pairs"] + r1["local_pairs"] == 11, (r0, r1)
assert r0["s0_sha"] == r1["s0_sha"] == g["s0_sha"], (r0, r1, g)
print(f"[smoke] parity OK: {r0['xhost_bytes']} + {r1['xhost_bytes']} B "
      f"cross-host, 0 B re-read, pair split "
      f"{r0['local_pairs']}+{r1['local_pairs']}=11, "
      f"fused sha {r0['s0_sha'][:12]} == 1-process golden")
EOF

echo '[smoke] ok'

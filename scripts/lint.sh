#!/usr/bin/env bash
# Tier-1 static-analysis gate: fails on any non-baselined bst-lint finding.
# Same checks/baseline as tests/test_lint.py and `bst lint`; run from
# anywhere. Extra args pass through (e.g. --all, --check host-sync).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m bigstitcher_spark_tpu.cli.main lint --fail-on-new "$@"

#!/usr/bin/env bash
# Tier-1 static-analysis gate: fails on any non-baselined bst-lint finding.
# Same checks/baseline as tests/test_lint.py and `bst lint`; run from
# anywhere. Extra args pass through (e.g. --all, --check host-sync).
set -euo pipefail
cd "$(dirname "$0")/.."

# every concurrency check resolves by name: a typo'd or unregistered
# check name fails loudly here instead of silently scanning nothing
for check in lock-order blocking-under-lock thread-spawn \
             cancel-coverage socket-hygiene; do
  python -m bigstitcher_spark_tpu.cli.main lint --check "$check" \
    --fail-on-new >/dev/null
done

SECONDS=0
python -m bigstitcher_spark_tpu.cli.main lint --fail-on-new "$@"
echo "bst lint: full scan in ${SECONDS}s"

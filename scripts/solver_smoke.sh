#!/bin/bash
# CI smoke for the device-side global solvers on the CPU fallback:
# asserts (1) the device relax path is actually taken when enabled
# (bst_solve_device_ms_total grows, exactly one compiled while_loop call
# per relax), (2) it agrees with the numpy reference on the same graph,
# (3) BST_SOLVE_DEVICE=0 falls back cleanly to the host path without
# touching the device counters, and (4) the intensity CG path engages
# and matches the dense solve.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

echo '[smoke] device solver engage + parity + fallback ...'
(cd "$REPO" && $PYTHON - <<'EOF'
import numpy as np

from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.io.spimdata import ViewId
from bigstitcher_spark_tpu.models import solver as S
from bigstitcher_spark_tpu.models.intensity import smoothness_pairs
from bigstitcher_spark_tpu.observe import metrics as _metrics
from bigstitcher_spark_tpu.ops import models as M
from bigstitcher_spark_tpu.ops.intensity import (
    match_stats,
    solve_intensity_coefficients,
)

rng = np.random.default_rng(0)
tiles = [(ViewId(0, i),) for i in range(12)]
corners = np.array([[x, y, z] for x in (0, 100) for y in (0, 100)
                    for z in (0, 50)], float)
links = []
for i in range(len(tiles)):
    for j in (i + 1, i + 4):
        if j >= len(tiles) or (j == i + 1 and i % 4 == 3):
            continue
        shift = rng.uniform(-3, 3, 3)
        links.append(S.MatchLink(tiles[i], tiles[j], corners,
                                 corners + shift, np.full(8, 0.9)))
fixed = {tiles[0]}
params = S.SolverParams(model=M.AFFINE, regularization=M.RIGID)

ms = _metrics.counter("bst_solve_device_ms_total", stage="relax")

# 1) enabled (the default): the device path must be TAKEN
assert config.get_bool("BST_SOLVE_DEVICE"), "BST_SOLVE_DEVICE must default on"
before = ms.value
dev = S.relax(links, tiles, fixed, params)
assert ms.value > before, "device relax did not engage"
print(f"  device relax: {dev.iterations} sweeps, err {dev.error:.4g}")

# 2) parity with the numpy reference
with config.overrides({"BST_SOLVE_DEVICE": False}):
    before = ms.value
    ref = S.relax(links, tiles, fixed, params)
    # 3) clean fallback: numpy path, device counter untouched
    assert ms.value == before, "fallback still ran the device kernel"
assert dev.iterations == ref.iterations
np.testing.assert_allclose(dev.history, ref.history, rtol=1e-9, atol=1e-9)
for k in ref.corrections:
    np.testing.assert_allclose(dev.corrections[k], ref.corrections[k],
                               rtol=1e-7, atol=1e-9)
print("  numpy parity ok (identical sweep count, history to 1e-9)")

# 4) intensity CG engages and matches the dense solve
dims, n_views = (4, 4, 4), 2
C = int(np.prod(dims)) * n_views
matches = []
for _ in range(120):
    ca, cb = rng.integers(0, C, 2)
    if ca == cb:
        continue
    x = rng.uniform(100, 1000, 40)
    y = rng.uniform(0.8, 1.2) * x + rng.uniform(-20, 20)
    matches.append((int(ca), int(cb), *match_stats(x / 500, y / 500)))
smooth = smoothness_pairs(dims, n_views)
msi = _metrics.counter("bst_solve_device_ms_total", stage="intensity")
before = msi.value
cg = solve_intensity_coefficients(C, matches, 0.1, smooth_pairs=smooth)
assert msi.value > before, "intensity CG did not engage"
dense = solve_intensity_coefficients(C, matches, 0.1, smooth_pairs=smooth,
                                     backend="numpy")
np.testing.assert_allclose(cg, dense, rtol=1e-6, atol=1e-6)
print("  intensity CG parity ok")
EOF
)

echo '[smoke] solver smoke OK'

#!/bin/bash
# CI smoke for the `bst pipeline` streaming stage-DAG executor on the CPU
# fallback: build a tiny fixture, generate the canonical streamed
# resave -> create -> fuse -> downsample -> detect spec with
# `bst pipeline init`, run it end to end, and exit 0 only if every stage
# finished and the elided intermediate re-read zero container bytes.
set -euo pipefail

REPO=$(cd "$(dirname "$0")/.." && pwd)
PYTHON=${PYTHON:-python3}
WORK=$(mktemp -d /tmp/bst-pipeline-smoke.XXXXXX)
trap 'rm -rf "$WORK"' EXIT

export JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"

bst () { (cd "$REPO" && $PYTHON -m bigstitcher_spark_tpu.cli.main "$@"); }

echo '[smoke] building tiny fixture ...'
(cd "$REPO" && $PYTHON - "$WORK" <<'EOF'
import sys
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
make_synthetic_project(sys.argv[1] + "/proj", n_tiles=(2, 1, 1),
                       tile_size=(64, 64, 32), overlap=16, jitter=1.0,
                       n_beads_per_tile=20)
EOF
)

echo '[smoke] generating spec ...'
bst pipeline init "$WORK/pipeline.json" -x "$WORK/proj/dataset.xml"

echo '[smoke] dry-run plan:'
bst pipeline run --dryRun "$WORK/pipeline.json"

echo '[smoke] running streamed pipeline ...'
bst pipeline run --summary "$WORK/summary.json" "$WORK/pipeline.json"

echo '[smoke] verifying summary ...'
(cd "$REPO" && $PYTHON - "$WORK/summary.json" <<'EOF'
import json, sys
s = json.load(open(sys.argv[1]))
assert s["ok"], s
assert s["containers_elided"] >= 1, s
assert s["blocks_streamed"] > 0, s
assert s["bytes_reread"] == 0, s   # elided edge never re-read the container
print(f"[smoke] {s['blocks_streamed']} blocks streamed, "
      f"{s['bytes_elided']} B elided, {s['bytes_reread']} B re-read, "
      f"{s['containers_elided']} container(s) elided")
EOF
)

echo '[smoke] running streamed pipeline with the HBM handoff enabled ...'
export BST_DAG_HANDOFF_BYTES=$((1 << 30))
bst pipeline run --summary "$WORK/summary-handoff.json" "$WORK/pipeline.json"

echo '[smoke] verifying handoff summary ...'
(cd "$REPO" && $PYTHON - "$WORK/summary-handoff.json" <<'EOF'
import json, sys
s = json.load(open(sys.argv[1]))
assert s["ok"], s
# device-resident handoff traffic happened on at least one streamed edge,
# and no handoff edge (nor any other streamed edge) re-read the container
handoff = [e for e in s["edges"] if e.get("blocks_handoff", 0) > 0]
assert handoff, s["edges"]
assert s.get("blocks_handoff", 0) > 0, s
# ... and a consumer was actually SERVED device arrays on one of them
assert sum(e["bytes_handoff"] for e in handoff) > 0, handoff
for e in handoff:
    assert e["bytes_reread"] == 0, e
assert s["bytes_reread"] == 0, s
print(f"[smoke] handoff: {s['blocks_handoff']} blocks served from device "
      f"({sum(e['bytes_handoff'] for e in handoff)} B), "
      f"{sum(e['bytes_spilled'] for e in handoff)} B spilled, "
      f"0 B re-read on handoff edges")
EOF
)
unset BST_DAG_HANDOFF_BYTES

echo '[smoke] ok'

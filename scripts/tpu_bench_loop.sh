#!/bin/bash
# Patient TPU bench capture: retry the axon tunnel for hours (VERDICT r2 #1:
# "stop treating the bench as an end-of-round event"). Probes cheaply; when
# the tunnel answers, runs the full bench and saves the artifact to
# BENCH_TPU_${TAG}.json + the raw log. Does NOT git-commit (the operator does).
set -u
cd /root/repo
ATTEMPTS=${1:-150}
SLEEP=${2:-240}
TAG=${3:-r05}
# per-run telemetry (event log, metrics textfile, run manifest) rides along
# with every bench attempt; on capture the manifests are archived beside
# the BENCH json/log so the span/IO story of the recorded run is kept
TELEMETRY_DIR=${BST_TELEMETRY_DIR:-/tmp/bst_bench_telemetry_${TAG}}
archive_telemetry () {
  local dest="BENCH_TPU_${TAG}_telemetry"
  if ls "$TELEMETRY_DIR"/manifest-*.json >/dev/null 2>&1; then
    mkdir -p "$dest"
    cp "$TELEMETRY_DIR"/manifest-*.json "$TELEMETRY_DIR"/metrics-*.prom \
       "$TELEMETRY_DIR"/events-*.jsonl "$dest"/ 2>/dev/null
    echo "[loop $(date +%T)] telemetry archived to $dest"
  fi
}
for i in $(seq 1 "$ATTEMPTS"); do
  if timeout 150 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu', d; print('live', d[0].platform)" >/tmp/tpu_probe.log 2>&1; then
    echo "[loop $(date +%T)] tunnel live ($(tail -1 /tmp/tpu_probe.log)), running bench"
    # clear only the telemetry file patterns (never rm -rf an operator-
    # supplied BST_TELEMETRY_DIR that may hold unrelated prior runs)
    rm -f "$TELEMETRY_DIR"/manifest-*.json "$TELEMETRY_DIR"/metrics-*.prom \
          "$TELEMETRY_DIR"/events-*.jsonl 2>/dev/null
    timeout 5500 env BST_BENCH_TPU_ONLY=1 BST_BENCH_CHILD_TIMEOUT=2500 BST_TELEMETRY_DIR="$TELEMETRY_DIR" python bench.py >/tmp/bench_tpu_out.json 2>/tmp/bench_tpu_err.log
    rc=$?
    # capture only a real, non-fallback artifact: rc 0 plus one JSON line
    # holding the primary metric on a non-cpu platform (an empty stdout
    # with rc=0 — e.g. the bench tree getting SIGTERM'd — must not
    # become the record)
    if [ "$rc" -eq 0 ] && grep -q '"metric"' /tmp/bench_tpu_out.json \
        && ! grep -q '"platform": "cpu"' /tmp/bench_tpu_out.json; then
      if grep -q '"truncated"' /tmp/bench_tpu_out.json; then
        # a tunnel stall cut this attempt short mid-artifact: keep it (it
        # has a validated primary) but keep hunting for a complete one
        cp /tmp/bench_tpu_out.json "BENCH_TPU_${TAG}.json"
        cp /tmp/bench_tpu_err.log "BENCH_TPU_${TAG}.log"
        archive_telemetry
        echo "[loop $(date +%T)] truncated TPU artifact saved; retrying for a complete one"
      else
        cp /tmp/bench_tpu_out.json "BENCH_TPU_${TAG}.json"
        cp /tmp/bench_tpu_err.log "BENCH_TPU_${TAG}.log"
        archive_telemetry
        echo "[loop $(date +%T)] TPU BENCH CAPTURED:"
        cat "BENCH_TPU_${TAG}.json"
        exit 0
      fi
    else
      echo "[loop $(date +%T)] no TPU artifact (rc=$rc); stderr tail:"
      tail -5 /tmp/bench_tpu_err.log
    fi
  else
    echo "[loop $(date +%T)] tunnel unreachable (attempt $i/$ATTEMPTS)"
  fi
  sleep "$SLEEP"
done
echo "[loop] exhausted attempts without a TPU bench"
exit 1

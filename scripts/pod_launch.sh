#!/bin/bash
# Multi-host launcher for the block-writing stages (affine-fusion, resave,
# nonrigid-fusion, downsample) — the role the reference fills with
# flintstone/spark-janelia (src/main/scripts/flintstone-sge-example.sh:29-119).
#
# Every process runs the SAME bst command; jax.distributed wires them into
# one runtime and each takes its deterministic slice of the block grid
# (bigstitcher_spark_tpu/parallel/distributed.py). Output chunks are
# disjoint, so no cross-host traffic happens outside the stage barriers.
#
# Usage:
#   # all N processes on THIS machine (single node, N runtimes):
#   scripts/pod_launch.sh -n 4 -- affine-fusion -o /data/fused.zarr
#
#   # one process per host on a cluster (run on every host, ids 0..N-1):
#   scripts/pod_launch.sh -n 4 -c head-node:8476 -i $HOST_ID -- \
#       affine-fusion -o /shared/fused.zarr
#
#   # Cloud TPU pod slices: jax autodetects the topology — just export
#   # BST_DISTRIBUTED=1 and run `bst <tool> ...` on every worker
#   # (gcloud compute tpus tpu-vm ssh ... --worker=all --command="...").
#
# SLURM: sbatch with --ntasks=N and run
#   scripts/pod_launch.sh -n $SLURM_NTASKS -c $MASTER:8476 -i $SLURM_PROCID -- ...
set -euo pipefail

NUM=2
COORD=""
PID=""
while [[ $# -gt 0 ]]; do
  case "$1" in
    -n|--num-processes) NUM="$2"; shift 2 ;;
    -c|--coordinator)   COORD="$2"; shift 2 ;;
    -i|--process-id)    PID="$2"; shift 2 ;;
    --) shift; break ;;
    *) echo "unknown option $1 (expected -n/-c/-i -- <bst args>)"; exit 2 ;;
  esac
done
[[ $# -gt 0 ]] || { echo "missing bst command after --"; exit 2; }

BST=${BST:-"python -m bigstitcher_spark_tpu.cli.main"}

if [[ -z "$PID" ]]; then
  # local mode: all N processes on this machine against a local coordinator
  # (free port picked by binding, not guessed)
  if [[ -z "$COORD" ]]; then
    PORT=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1])
PY
)
    COORD="127.0.0.1:$PORT"
  fi
  echo "[pod_launch] $NUM local processes, coordinator $COORD"
  pids=()
  # a worker that dies leaves its peers blocked on the jax.distributed
  # barrier forever — fail fast: first nonzero exit kills the rest
  trap 'kill "${pids[@]}" 2>/dev/null' EXIT
  for i in $(seq 0 $((NUM - 1))); do
    BST_COORDINATOR="$COORD" BST_NUM_PROCESSES="$NUM" BST_PROCESS_ID="$i" \
      $BST "$@" > >(sed "s/^/[p$i] /") 2>&1 &
    pids+=($!)
  done
  remaining=$NUM
  while (( remaining > 0 )); do
    set +e
    wait -n
    rc=$?
    set -e
    if (( rc != 0 )); then
      echo "[pod_launch] a worker failed (rc=$rc); terminating the rest"
      kill "${pids[@]}" 2>/dev/null || true
      wait || true
      exit "$rc"
    fi
    remaining=$((remaining - 1))
  done
  trap - EXIT
  exit 0
fi

[[ -n "$COORD" ]] || { echo "-c coordinator required with -i"; exit 2; }
echo "[pod_launch] process $PID/$NUM, coordinator $COORD"
exec env BST_COORDINATOR="$COORD" BST_NUM_PROCESSES="$NUM" \
     BST_PROCESS_ID="$PID" $BST "$@"

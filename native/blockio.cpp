// blockio: native N5 chunk codec + file IO.
//
// Optional fast path for the chunk-store layer (SURVEY.md §2.3: the
// reference's only native surface is prebuilt codec libs — blosc/zstd/JHDF5;
// here the equivalent is a small C++ library doing N5 block encode/decode and
// GIL-free file writes, loaded via ctypes).
//
// N5 block format (default mode): big-endian
//   u16 mode (0 = default), u16 ndim, ndim x u32 block dims,
//   then the compressed payload; element order is first-axis-fastest
//   (Fortran w.r.t. the dims), values big-endian.
//
// All entry points are C ABI; buffers are caller-allocated. Every function
// returns a negative value on error. ctypes calls release the GIL, so a
// Python thread pool driving these runs truly parallel.

#include <zstd.h>

#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <cerrno>

namespace {

// ---------------------------------------------------------------------------
// LZ4 via dlopen (liblz4.so.1 ships without headers on this image) +
// lz4-java "LZ4Block" stream framing — the wire format of the reference's
// N5 Lz4Compression (util/N5Util.java:87-88; net.jpountz LZ4BlockOutputStream):
//   per chunk (<= 64 KiB of raw data):
//     magic "LZ4Block" (8) | token (1: method 0x10 raw / 0x20 lz4, low
//     nibble = log2(blockSize)-10) | compressedLen LE u32 | originalLen LE
//     u32 | xxhash32(seed 0x9747b28c) of the RAW chunk, LE u32 | payload
//   terminated by an empty frame (originalLen == 0).
// ---------------------------------------------------------------------------

typedef int (*lz4_compress_fn)(const char*, char*, int, int);
typedef int (*lz4_decompress_fn)(const char*, char*, int, int);
typedef int (*lz4_bound_fn)(int);
lz4_compress_fn p_lz4_compress = nullptr;
lz4_decompress_fn p_lz4_decompress = nullptr;
lz4_bound_fn p_lz4_bound = nullptr;

bool lz4_init() {
  static int state = 0;  // 0 = untried, 1 = ok, -1 = unavailable
  if (state == 0) {
    void* h = dlopen("liblz4.so.1", RTLD_NOW);
    if (!h) h = dlopen("liblz4.so", RTLD_NOW);
    if (h) {
      p_lz4_compress =
          reinterpret_cast<lz4_compress_fn>(dlsym(h, "LZ4_compress_default"));
      p_lz4_decompress =
          reinterpret_cast<lz4_decompress_fn>(dlsym(h, "LZ4_decompress_safe"));
      p_lz4_bound =
          reinterpret_cast<lz4_bound_fn>(dlsym(h, "LZ4_compressBound"));
    }
    state = (p_lz4_compress && p_lz4_decompress && p_lz4_bound) ? 1 : -1;
  }
  return state == 1;
}

// xxhash32 (public spec) — lz4-java checksums raw chunks with seed
// 0x9747b28c and writes the full 32-bit value little-endian.
const uint32_t XXH_P1 = 2654435761u, XXH_P2 = 2246822519u,
               XXH_P3 = 3266489917u, XXH_P4 = 668265263u, XXH_P5 = 374761393u;
const uint32_t LZ4JAVA_SEED = 0x9747b28cu;

inline uint32_t xxh_rotl(uint32_t x, int r) { return (x << r) | (x >> (32 - r)); }
inline uint32_t xxh_read_le(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

uint32_t xxhash32(const uint8_t* p, size_t len, uint32_t seed) {
  const uint8_t* end = p + len;
  uint32_t h;
  if (len >= 16) {
    uint32_t v1 = seed + XXH_P1 + XXH_P2, v2 = seed + XXH_P2, v3 = seed,
             v4 = seed - XXH_P1;
    const uint8_t* limit = end - 16;
    do {
      v1 = xxh_rotl(v1 + xxh_read_le(p) * XXH_P2, 13) * XXH_P1;
      p += 4;
      v2 = xxh_rotl(v2 + xxh_read_le(p) * XXH_P2, 13) * XXH_P1;
      p += 4;
      v3 = xxh_rotl(v3 + xxh_read_le(p) * XXH_P2, 13) * XXH_P1;
      p += 4;
      v4 = xxh_rotl(v4 + xxh_read_le(p) * XXH_P2, 13) * XXH_P1;
      p += 4;
    } while (p <= limit);
    h = xxh_rotl(v1, 1) + xxh_rotl(v2, 7) + xxh_rotl(v3, 12) + xxh_rotl(v4, 18);
  } else {
    h = seed + XXH_P5;
  }
  h += static_cast<uint32_t>(len);
  while (p + 4 <= end) {
    h = xxh_rotl(h + xxh_read_le(p) * XXH_P3, 17) * XXH_P4;
    p += 4;
  }
  while (p < end) {
    h = xxh_rotl(h + (*p) * XXH_P5, 11) * XXH_P1;
    ++p;
  }
  h ^= h >> 15;
  h *= XXH_P2;
  h ^= h >> 13;
  h *= XXH_P3;
  h ^= h >> 16;
  return h;
}

const char LZ4B_MAGIC[8] = {'L', 'Z', '4', 'B', 'l', 'o', 'c', 'k'};
const int64_t LZ4B_HEADER = 8 + 1 + 4 + 4 + 4;
const int64_t LZ4B_CHUNK = 65536;  // n5 Lz4Compression default blockSize
const uint8_t LZ4B_METHOD_RAW = 0x10, LZ4B_METHOD_LZ4 = 0x20;

// lz4-java token low nibble: ceil(log2(blockSize)) - 10 (blockSize in
// [64, 32 MiB] -> compressionLevel in [0, 15])
inline int64_t lz4b_chunk_size(int32_t level) {
  return (level >= 64 && level <= (1 << 25)) ? level : LZ4B_CHUNK;
}
inline uint8_t lz4b_token_level(int64_t chunk) {
  uint8_t lvl = 0;
  while ((int64_t(1) << (lvl + 10)) < chunk && lvl < 15) ++lvl;
  return lvl;
}

inline void put_u32_le(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v);
  p[1] = static_cast<uint8_t>(v >> 8);
  p[2] = static_cast<uint8_t>(v >> 16);
  p[3] = static_cast<uint8_t>(v >> 24);
}
inline uint32_t get_u32_le(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

int64_t lz4block_bound(int64_t raw) {
  // generous: covers the smallest legal chunk size (64 B -> ~33% frame
  // overhead when incompressible)
  return raw + raw / 2 + 1024;
}

// Encode raw -> LZ4Block stream (frames of ``chunk`` raw bytes). Returns
// bytes written or <0.
int64_t lz4block_encode(const uint8_t* raw, int64_t raw_len, uint8_t* out,
                        int64_t out_cap, int64_t chunk) {
  if (!lz4_init()) return -8;
  int64_t off = 0, pos = 0;
  while (pos < raw_len) {
    const int n = static_cast<int>(
        raw_len - pos < chunk ? raw_len - pos : chunk);
    const int bound = p_lz4_bound(n);
    if (off + LZ4B_HEADER + bound > out_cap) return -1;
    uint8_t* hdr = out + off;
    std::memcpy(hdr, LZ4B_MAGIC, 8);
    uint8_t* dst = hdr + LZ4B_HEADER;
    int clen = p_lz4_compress(reinterpret_cast<const char*>(raw + pos),
                              reinterpret_cast<char*>(dst), n, bound);
    uint8_t method = LZ4B_METHOD_LZ4;
    if (clen <= 0 || clen >= n) {  // incompressible: store raw
      std::memcpy(dst, raw + pos, static_cast<size_t>(n));
      clen = n;
      method = LZ4B_METHOD_RAW;
    }
    hdr[8] = static_cast<uint8_t>(method | lz4b_token_level(chunk));
    put_u32_le(hdr + 9, static_cast<uint32_t>(clen));
    put_u32_le(hdr + 13, static_cast<uint32_t>(n));
    put_u32_le(hdr + 17, xxhash32(raw + pos, static_cast<size_t>(n),
                                  LZ4JAVA_SEED));
    off += LZ4B_HEADER + clen;
    pos += n;
  }
  if (off + LZ4B_HEADER > out_cap) return -1;
  uint8_t* hdr = out + off;  // terminator frame
  std::memcpy(hdr, LZ4B_MAGIC, 8);
  hdr[8] = static_cast<uint8_t>(LZ4B_METHOD_RAW | lz4b_token_level(chunk));
  put_u32_le(hdr + 9, 0);
  put_u32_le(hdr + 13, 0);
  put_u32_le(hdr + 17, 0);
  return off + LZ4B_HEADER;
}

// Decode an LZ4Block stream into out (expected_raw bytes). Returns bytes
// decoded or <0.
int64_t lz4block_decode(const uint8_t* enc, int64_t enc_len, uint8_t* out,
                        int64_t expected_raw) {
  if (!lz4_init()) return -8;
  int64_t off = 0, pos = 0;
  while (pos < expected_raw) {
    if (off + LZ4B_HEADER > enc_len) return -2;
    const uint8_t* hdr = enc + off;
    if (std::memcmp(hdr, LZ4B_MAGIC, 8) != 0) return -2;
    const uint8_t method = hdr[8] & 0xf0;
    const int64_t clen = get_u32_le(hdr + 9);
    const int64_t rawn = get_u32_le(hdr + 13);
    const uint32_t check = get_u32_le(hdr + 17);
    off += LZ4B_HEADER;
    if (rawn == 0) break;  // premature terminator
    if (off + clen > enc_len || pos + rawn > expected_raw) return -2;
    if (method == LZ4B_METHOD_RAW) {
      if (clen != rawn) return -2;
      std::memcpy(out + pos, enc + off, static_cast<size_t>(rawn));
    } else if (method == LZ4B_METHOD_LZ4) {
      const int got = p_lz4_decompress(
          reinterpret_cast<const char*>(enc + off),
          reinterpret_cast<char*>(out + pos), static_cast<int>(clen),
          static_cast<int>(rawn));
      if (got != rawn) return -2;
    } else {
      return -2;
    }
    if (xxhash32(out + pos, static_cast<size_t>(rawn), LZ4JAVA_SEED) != check)
      return -9;  // checksum mismatch
    off += clen;
    pos += rawn;
  }
  return pos;
}

inline void put_u16_be(uint8_t* p, uint16_t v) {
  p[0] = static_cast<uint8_t>(v >> 8);
  p[1] = static_cast<uint8_t>(v);
}
inline void put_u32_be(uint8_t* p, uint32_t v) {
  p[0] = static_cast<uint8_t>(v >> 24);
  p[1] = static_cast<uint8_t>(v >> 16);
  p[2] = static_cast<uint8_t>(v >> 8);
  p[3] = static_cast<uint8_t>(v);
}
inline uint16_t get_u16_be(const uint8_t* p) {
  return static_cast<uint16_t>((p[0] << 8) | p[1]);
}
inline uint32_t get_u32_be(const uint8_t* p) {
  return (static_cast<uint32_t>(p[0]) << 24) | (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

// byte-swap a buffer of n elements of size es (2/4/8) into dst
void swap_bytes(const uint8_t* src, uint8_t* dst, size_t n, int es) {
  switch (es) {
    case 2:
      for (size_t i = 0; i < n; ++i) {
        dst[2 * i] = src[2 * i + 1];
        dst[2 * i + 1] = src[2 * i];
      }
      break;
    case 4:
      for (size_t i = 0; i < n; ++i) {
        dst[4 * i] = src[4 * i + 3];
        dst[4 * i + 1] = src[4 * i + 2];
        dst[4 * i + 2] = src[4 * i + 1];
        dst[4 * i + 3] = src[4 * i];
      }
      break;
    case 8:
      for (size_t i = 0; i < n; ++i)
        for (int b = 0; b < 8; ++b) dst[8 * i + b] = src[8 * i + 7 - b];
      break;
    default:
      break;
  }
}

bool mkdirs_for(const std::string& file_path) {
  // create every parent directory of file_path
  size_t pos = 0;
  while ((pos = file_path.find('/', pos + 1)) != std::string::npos) {
    std::string dir = file_path.substr(0, pos);
    if (dir.empty()) continue;
    if (mkdir(dir.c_str(), 0777) != 0 && errno != EEXIST) return false;
  }
  return true;
}



// Shared N5 header parse + decompress-to-contiguous-payload over an
// in-memory buffer (used by n5_decode_block AND the file readers). On
// success ``payload`` points into ``enc`` or ``tmp``; returns 0 or a
// negative error.
int64_t n5_parse_payload(const uint8_t* enc, int64_t len, int32_t elem_size,
                         int32_t compression, std::string& tmp,
                         const uint8_t** payload, uint32_t* dims_out,
                         int32_t* ndim_out) {
  if (len < 4) return -1;
  const uint16_t mode = get_u16_be(enc);
  if (mode > 1) return -3;  // varlength mode unsupported
  const int32_t ndim = get_u16_be(enc + 2);
  if (ndim <= 0 || ndim > 16) return -1;
  int64_t header = 4 + 4 * static_cast<int64_t>(ndim);
  if (mode == 1) header += 4;  // u32 actual element count (varmode)
  if (len < header) return -1;  // checked AFTER the varmode extension
  int64_t n_elem = 1;
  for (int32_t d = 0; d < ndim; ++d) {
    dims_out[d] = get_u32_be(enc + 4 + 4 * d);
    n_elem *= dims_out[d];
  }
  *ndim_out = ndim;
  const size_t raw = static_cast<size_t>(n_elem) * elem_size;
  if (compression == 0) {
    if (len - header < static_cast<int64_t>(raw)) return -1;
    *payload = enc + header;
    return 0;
  }
  tmp.resize(raw);
  if (compression == 2) {
    const int64_t dgot = lz4block_decode(
        enc + header, len - header, reinterpret_cast<uint8_t*>(&tmp[0]),
        static_cast<int64_t>(raw));
    if (dgot != static_cast<int64_t>(raw)) return dgot < 0 ? dgot : -2;
  } else {
    const size_t zgot = ZSTD_decompress(&tmp[0], raw, enc + header,
                                        static_cast<size_t>(len - header));
    if (ZSTD_isError(zgot) || zgot != raw) return -2;
  }
  *payload = reinterpret_cast<const uint8_t*>(tmp.data());
  return 0;
}

// File read + shared parse.
int64_t n5_load_payload(const char* path, int32_t elem_size,
                        int32_t compression, std::string& buf,
                        std::string& tmp, const uint8_t** payload,
                        uint32_t* dims_out, int32_t* ndim_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -7;
  std::fseek(f, 0, SEEK_END);
  const long len = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  buf.resize(static_cast<size_t>(len));
  const size_t got = std::fread(&buf[0], 1, static_cast<size_t>(len), f);
  std::fclose(f);
  if (got != static_cast<size_t>(len)) return -6;
  return n5_parse_payload(reinterpret_cast<const uint8_t*>(buf.data()), len,
                          elem_size, compression, tmp, payload, dims_out,
                          ndim_out);
}

}  // namespace

extern "C" {

// 1 when liblz4 could be loaded (lz4 codec usable), else 0.
int32_t lz4_available() { return lz4_init() ? 1 : 0; }

// Max encoded size for a block of raw_bytes payload (covers zstd AND the
// LZ4Block stream framing).
int64_t n5_encode_bound(int64_t raw_bytes, int32_t ndim) {
  const int64_t zb =
      static_cast<int64_t>(ZSTD_compressBound(static_cast<size_t>(raw_bytes)));
  const int64_t lb = lz4block_bound(raw_bytes);
  return 4 + 4 * static_cast<int64_t>(ndim) + (zb > lb ? zb : lb);
}

// Encode one N5 block. data: first-axis-fastest element order, NATIVE
// (little) endian, n_elem = prod(dims). elem_size in {1,2,4,8}.
// compression: 0 = raw, 1 = zstd(level). Returns encoded byte count or <0.
int64_t n5_encode_block(const uint8_t* data, int32_t elem_size,
                        const uint32_t* dims, int32_t ndim, int64_t n_elem,
                        int32_t compression, int32_t level, uint8_t* out,
                        int64_t out_cap) {
  const int64_t header = 4 + 4 * static_cast<int64_t>(ndim);
  if (out_cap < header) return -1;
  put_u16_be(out, 0);
  put_u16_be(out + 2, static_cast<uint16_t>(ndim));
  for (int32_t d = 0; d < ndim; ++d) put_u32_be(out + 4 + 4 * d, dims[d]);

  const size_t raw = static_cast<size_t>(n_elem) * elem_size;
  const uint8_t* payload = data;
  std::string swapped;
  if (elem_size > 1) {
    swapped.resize(raw);
    swap_bytes(data, reinterpret_cast<uint8_t*>(&swapped[0]),
               static_cast<size_t>(n_elem), elem_size);
    payload = reinterpret_cast<const uint8_t*>(swapped.data());
  }
  if (compression == 0) {
    if (out_cap < header + static_cast<int64_t>(raw)) return -1;
    std::memcpy(out + header, payload, raw);
    return header + static_cast<int64_t>(raw);
  }
  if (compression == 2) {  // lz4 (LZ4Block stream, reference N5 Lz4);
    // ``level`` carries the reference's Lz4 blockSize (N5Util.java:87-88)
    const int64_t got = lz4block_encode(payload, static_cast<int64_t>(raw),
                                        out + header, out_cap - header,
                                        lz4b_chunk_size(level));
    if (got < 0) return got;
    return header + got;
  }
  const size_t cap = static_cast<size_t>(out_cap - header);
  const size_t got = ZSTD_compress(out + header, cap, payload, raw, level);
  if (ZSTD_isError(got)) return -2;
  return header + static_cast<int64_t>(got);
}

// Decode one N5 block into out (native endian, first-axis-fastest).
// Returns number of elements decoded, or <0. dims_out must hold 16 u32.
int64_t n5_decode_block(const uint8_t* enc, int64_t enc_len, int32_t elem_size,
                        int32_t compression, uint8_t* out, int64_t out_cap,
                        uint32_t* dims_out, int32_t* ndim_out) {
  std::string tmp;
  const uint8_t* payload = nullptr;
  const int64_t rc = n5_parse_payload(enc, enc_len, elem_size, compression,
                                      tmp, &payload, dims_out, ndim_out);
  if (rc < 0) return rc;
  int64_t n_elem = 1;
  for (int32_t d = 0; d < *ndim_out; ++d) n_elem *= dims_out[d];
  const size_t raw = static_cast<size_t>(n_elem) * elem_size;
  if (out_cap < static_cast<int64_t>(raw)) return -1;
  if (elem_size > 1) {
    swap_bytes(payload, out, static_cast<size_t>(n_elem), elem_size);
  } else {
    std::memcpy(out, payload, raw);
  }
  return n_elem;
}

// Encode + write one block file (creates parent dirs). Returns bytes
// written or <0.
int64_t n5_write_block_file(const char* path, const uint8_t* data,
                            int32_t elem_size, const uint32_t* dims,
                            int32_t ndim, int64_t n_elem, int32_t compression,
                            int32_t level) {
  const int64_t cap = n5_encode_bound(n_elem * elem_size, ndim);
  std::string buf;
  buf.resize(static_cast<size_t>(cap));
  const int64_t enc = n5_encode_block(data, elem_size, dims, ndim, n_elem,
                                      compression, level,
                                      reinterpret_cast<uint8_t*>(&buf[0]), cap);
  if (enc < 0) return enc;
  std::string p(path);
  if (!mkdirs_for(p)) return -4;
  FILE* f = std::fopen(path, "wb");
  if (!f) return -5;
  const size_t wrote = std::fwrite(buf.data(), 1, static_cast<size_t>(enc), f);
  std::fclose(f);
  return wrote == static_cast<size_t>(enc) ? enc : -6;
}

// Encode + write one zarr (v2) chunk file. Zarr chunks are always FULL
// chunk_dims in C order with fill beyond the array edge; the source region
// is a strided view (strides in BYTES, same dim order as chunk_dims), so a
// logically-transposed numpy view writes without a Python-side copy.
// fill is the byte pattern for padding (elem_size bytes, normally zeros).
// compression: 0 = raw, 1 = zstd(level). Returns bytes written or <0.
int64_t zarr_write_chunk_file(const char* path, const uint8_t* data,
                              int32_t elem_size, const int64_t* strides,
                              const uint32_t* src_dims,
                              const uint32_t* chunk_dims, int32_t ndim,
                              const uint8_t* fill, int32_t compression,
                              int32_t level) {
  if (ndim <= 0 || ndim > 8) return -1;
  int64_t n_chunk = 1;
  for (int32_t d = 0; d < ndim; ++d) n_chunk *= chunk_dims[d];
  const size_t raw = static_cast<size_t>(n_chunk) * elem_size;
  std::string buf;
  buf.resize(raw);
  uint8_t* out = reinterpret_cast<uint8_t*>(&buf[0]);
  bool zero_fill = true;
  for (int32_t b = 0; b < elem_size; ++b) zero_fill &= (fill[b] == 0);
  bool full = true;
  for (int32_t d = 0; d < ndim; ++d) full &= (src_dims[d] == chunk_dims[d]);
  if (!full) {
    if (zero_fill) {
      std::memset(out, 0, raw);
    } else {
      for (int64_t i = 0; i < n_chunk; ++i)
        std::memcpy(out + i * elem_size, fill, elem_size);
    }
  }
  // assembly into disk (C) order. The caller passes a transposed VIEW, so
  // the source-dense axis is usually NOT the chunk-dense (last) axis —
  // tile the (src-dense, dst-dense) plane so both sides' cache lines are
  // reused (the untiled walk paid a miss per element on 3-D fusion slabs).
  int64_t chunk_stride[8];
  chunk_stride[ndim - 1] = elem_size;
  for (int32_t d = ndim - 2; d >= 0; --d)
    chunk_stride[d] = chunk_stride[d + 1] * chunk_dims[d + 1];

  auto copy_run = [&](const uint8_t* sp, uint8_t* dp, int64_t sstep,
                      int64_t dstep, int64_t n) {
    if (sstep == elem_size && dstep == elem_size) {
      std::memcpy(dp, sp, static_cast<size_t>(n) * elem_size);
      return;
    }
    switch (elem_size) {  // constant-size memcpy folds to one load/store
      case 1:
        for (int64_t i = 0; i < n; ++i) dp[i * dstep] = sp[i * sstep];
        break;
      case 2:
        for (int64_t i = 0; i < n; ++i)
          std::memcpy(dp + i * dstep, sp + i * sstep, 2);
        break;
      case 4:
        for (int64_t i = 0; i < n; ++i)
          std::memcpy(dp + i * dstep, sp + i * sstep, 4);
        break;
      case 8:
        for (int64_t i = 0; i < n; ++i)
          std::memcpy(dp + i * dstep, sp + i * sstep, 8);
        break;
      default:
        for (int64_t i = 0; i < n; ++i)
          std::memcpy(dp + i * dstep, sp + i * sstep, elem_size);
    }
  };

  // source-dense axis (smallest stride among size>1 axes)
  int32_t sa = ndim - 1;
  for (int32_t d = 0; d < ndim; ++d) {
    if (src_dims[d] > 1 &&
        (src_dims[sa] <= 1 ||
         std::llabs(strides[d]) < std::llabs(strides[sa])))
      sa = d;
  }
  const int32_t db = ndim - 1;  // chunk-dense axis (C order)
  const int64_t T = 64;
  uint32_t idx[8] = {0};
  if (sa != db && src_dims[sa] > 1 && src_dims[db] > 1) {
    // odometer over all axes except sa/db; tiled (sa, db) copies inside
    for (;;) {
      int64_t src_off = 0, dst_off = 0;
      for (int32_t d = 0; d < ndim; ++d) {
        if (d == sa || d == db) continue;
        src_off += static_cast<int64_t>(idx[d]) * strides[d];
        dst_off += static_cast<int64_t>(idx[d]) * chunk_stride[d];
      }
      const int64_t na = src_dims[sa], nb = src_dims[db];
      for (int64_t a0 = 0; a0 < na; a0 += T) {
        const int64_t ta = (na - a0) < T ? (na - a0) : T;
        for (int64_t b0 = 0; b0 < nb; b0 += T) {
          const int64_t tb = (nb - b0) < T ? (nb - b0) : T;
          for (int64_t b = 0; b < tb; ++b) {
            const int64_t so = src_off + a0 * strides[sa] +
                               (b0 + b) * strides[db];
            const int64_t dofs = dst_off + a0 * chunk_stride[sa] +
                                 (b0 + b) * chunk_stride[db];
            copy_run(data + so, out + dofs, strides[sa], chunk_stride[sa],
                     ta);
          }
        }
      }
      int32_t d = ndim - 1;
      for (; d >= 0; --d) {
        if (d == sa || d == db) continue;
        if (++idx[d] < src_dims[d]) break;
        idx[d] = 0;
      }
      if (d < 0) break;
    }
  } else {
    // source-dense == chunk-dense (or degenerate): plain inner runs
    const int64_t inner = src_dims[ndim - 1];
    for (;;) {
      int64_t src_off = 0, dst_off = 0;
      for (int32_t d = 0; d < ndim - 1; ++d) {
        src_off += static_cast<int64_t>(idx[d]) * strides[d];
        dst_off += static_cast<int64_t>(idx[d]) * chunk_stride[d];
      }
      copy_run(data + src_off, out + dst_off, strides[ndim - 1], elem_size,
               inner);
      int32_t d = ndim - 2;
      for (; d >= 0; --d) {
        if (++idx[d] < src_dims[d]) break;
        idx[d] = 0;
      }
      if (d < 0) break;
    }
  }
  std::string p(path);
  if (!mkdirs_for(p)) return -4;
  FILE* f = std::fopen(path, "wb");
  if (!f) return -5;
  int64_t wrote;
  if (compression == 0) {
    wrote = static_cast<int64_t>(std::fwrite(buf.data(), 1, raw, f));
    std::fclose(f);
    return wrote == static_cast<int64_t>(raw) ? wrote : -6;
  }
  std::string enc;
  enc.resize(ZSTD_compressBound(raw));
  const size_t got = ZSTD_compress(&enc[0], enc.size(), buf.data(), raw, level);
  if (ZSTD_isError(got)) {
    std::fclose(f);
    return -2;
  }
  wrote = static_cast<int64_t>(std::fwrite(enc.data(), 1, got, f));
  std::fclose(f);
  return wrote == static_cast<int64_t>(got) ? wrote : -6;
}


// Read + decode one block file and copy a REGION of it directly into a
// strided destination (the caller's output array), fusing the big-endian
// swap with the strided write — one pass instead of decode + swap pass +
// numpy strided-assembly pass. src_lo/copy_dims select the in-chunk region
// (chunk dim order, first-axis-fastest); dst_strides are byte strides of
// the destination for the same dims; ``expected_ndim`` guards the caller's
// array sizes against corrupt/mismatched chunk headers. Returns elements
// copied, <0 on error (-7: file missing, -10: ndim mismatch; 0 elements if
// the stored chunk doesn't reach src_lo).
int64_t n5_read_block_region(const char* path, int32_t elem_size,
                             int32_t compression, int32_t expected_ndim,
                             const uint32_t* src_lo,
                             const uint32_t* copy_dims, uint8_t* dst,
                             const int64_t* dst_strides, uint32_t* dims_out,
                             int32_t* ndim_out) {
  std::string buf, tmp;
  const uint8_t* payload = nullptr;
  const int64_t rc = n5_load_payload(path, elem_size, compression, buf, tmp,
                                     &payload, dims_out, ndim_out);
  if (rc < 0) return rc;
  const int32_t ndim = *ndim_out;
  if (ndim != expected_ndim || ndim > 8) return -10;
  // clip the copy region against the STORED chunk dims (edge chunks may be
  // smaller than the nominal block size)
  uint32_t cdims[8];
  int64_t total = 1;
  for (int32_t d = 0; d < ndim; ++d) {
    if (src_lo[d] >= dims_out[d]) return 0;
    const uint32_t avail = dims_out[d] - src_lo[d];
    cdims[d] = copy_dims[d] < avail ? copy_dims[d] : avail;
    total *= cdims[d];
  }
  // source strides (F-order: first axis fastest), in bytes
  int64_t sstr[8];
  sstr[0] = elem_size;
  for (int32_t d = 1; d < ndim; ++d)
    sstr[d] = sstr[d - 1] * dims_out[d - 1];
  int64_t src_base = 0;
  for (int32_t d = 0; d < ndim; ++d)
    src_base += static_cast<int64_t>(src_lo[d]) * sstr[d];
  auto copy_swapped = [&](const uint8_t* sp, uint8_t* dp, int64_t sstep,
                          int64_t dstep, int64_t n) {
    switch (elem_size) {
      case 1:
        for (int64_t i = 0; i < n; ++i) dp[i * dstep] = sp[i * sstep];
        break;
      case 2:
        for (int64_t i = 0; i < n; ++i) {
          uint8_t* q = dp + i * dstep;
          const uint8_t* s = sp + i * sstep;
          q[0] = s[1];
          q[1] = s[0];
        }
        break;
      case 4:
        for (int64_t i = 0; i < n; ++i) {
          uint8_t* q = dp + i * dstep;
          const uint8_t* s = sp + i * sstep;
          q[0] = s[3];
          q[1] = s[2];
          q[2] = s[1];
          q[3] = s[0];
        }
        break;
      default:
        for (int64_t i = 0; i < n; ++i) {
          uint8_t* q = dp + i * dstep;
          const uint8_t* s = sp + i * sstep;
          for (int b = 0; b < elem_size; ++b) q[b] = s[elem_size - 1 - b];
        }
    }
  };

  if (ndim == 3) {
    // 3-D fast path with cache tiling: axis 0 is source-dense, one of the
    // other axes is usually destination-dense (C-order outputs) — tile the
    // (0, dst-dense) plane so both sides' cache lines are reused instead of
    // one side missing on every element
    const int32_t zd = dst_strides[2] <= dst_strides[1] ? 2 : 1;
    const int32_t yd = zd == 2 ? 1 : 2;
    const int64_t T = 64;
    for (uint32_t y = 0; y < cdims[yd]; ++y) {
      for (uint32_t x0 = 0; x0 < cdims[0]; x0 += T) {
        const int64_t nx =
            (cdims[0] - x0) < T ? (cdims[0] - x0) : T;
        for (uint32_t z0 = 0; z0 < cdims[zd]; z0 += T) {
          const int64_t nz =
              (cdims[zd] - z0) < T ? (cdims[zd] - z0) : T;
          for (int64_t x = 0; x < nx; ++x) {
            const int64_t so = src_base + (x0 + x) * sstr[0] +
                               static_cast<int64_t>(y) * sstr[yd] +
                               static_cast<int64_t>(z0) * sstr[zd];
            const int64_t dofs = (x0 + x) * dst_strides[0] +
                                 static_cast<int64_t>(y) * dst_strides[yd] +
                                 static_cast<int64_t>(z0) * dst_strides[zd];
            copy_swapped(payload + so, dst + dofs, sstr[zd],
                         dst_strides[zd], nz);
          }
        }
      }
    }
    return total;
  }

  // generic odometer (ndim != 3): inner loop walks the source-dense axis 0
  uint32_t idx[8] = {0};
  const int64_t inner = cdims[0];
  for (;;) {
    int64_t so = src_base, dofs = 0;
    for (int32_t d = 1; d < ndim; ++d) {
      so += static_cast<int64_t>(idx[d]) * sstr[d];
      dofs += static_cast<int64_t>(idx[d]) * dst_strides[d];
    }
    copy_swapped(payload + so, dst + dofs, sstr[0], dst_strides[0], inner);
    int32_t d = 1;
    for (; d < ndim; ++d) {
      if (++idx[d] < cdims[d]) break;
      idx[d] = 0;
    }
    if (d >= ndim) break;
  }
  return total;
}

// Read + decode one block file. Returns elements decoded, <0 on error
// (-7: file missing).
int64_t n5_read_block_file(const char* path, int32_t elem_size,
                           int32_t compression, uint8_t* out, int64_t out_cap,
                           uint32_t* dims_out, int32_t* ndim_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -7;
  std::fseek(f, 0, SEEK_END);
  const long len = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf;
  buf.resize(static_cast<size_t>(len));
  const size_t got = std::fread(&buf[0], 1, static_cast<size_t>(len), f);
  std::fclose(f);
  if (got != static_cast<size_t>(len)) return -6;
  return n5_decode_block(reinterpret_cast<const uint8_t*>(buf.data()), len,
                         elem_size, compression, out, out_cap, dims_out,
                         ndim_out);
}

}  // extern "C"

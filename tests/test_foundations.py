"""Foundations: geometry, grid, chunk store, SpimData XML round-trip."""

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.dataset_io import (
    ViewLoader,
    best_mipmap_level,
    mipmap_transform,
)
from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
from bigstitcher_spark_tpu.utils.geometry import (
    Interval,
    affine_from_flat,
    apply_affine,
    concatenate,
    concatenate_all,
    invert_affine,
    scale_affine,
    transformed_interval,
    translation_affine,
)
from bigstitcher_spark_tpu.utils.grid import create_grid


class TestGeometry:
    def test_interval_basics(self):
        a = Interval((0, 0, 0), (9, 19, 29))
        assert a.shape == (10, 20, 30)
        assert a.num_elements == 6000
        b = Interval.from_shape((5, 5, 5), (8, 18, 28))
        assert a.overlaps(b)
        inter = a.intersect(b)
        assert inter.min == (8, 18, 28) and inter.max == (9, 19, 29)
        assert not a.overlaps(Interval((10, 0, 0), (12, 5, 5)))
        assert a.expand(2).min == (-2, -2, -2)

    def test_affine_compose_invert(self):
        t = translation_affine((5, -3, 2))
        s = scale_affine((2, 2, 4))
        # concatenate(a, b): b first
        m = concatenate(t, s)
        p = apply_affine(m, np.array([1.0, 1.0, 1.0]))
        np.testing.assert_allclose(p, [7, -1, 6])
        minv = invert_affine(m)
        np.testing.assert_allclose(
            apply_affine(minv, p), [1, 1, 1], atol=1e-12
        )

    def test_chain_order_outermost_first(self):
        # chain [T, S]: S applied first (innermost = calibration at list end)
        t = translation_affine((10, 0, 0))
        s = scale_affine((2, 1, 1))
        m = concatenate_all([t, s])
        np.testing.assert_allclose(apply_affine(m, np.array([3.0, 0, 0])), [16, 0, 0])

    def test_transformed_interval(self):
        box = Interval((0, 0, 0), (9, 9, 9))
        out = transformed_interval(translation_affine((2.5, 0, -1)), box)
        assert out.min == (2, 0, -1) and out.max == (12, 9, 8)


class TestGrid:
    def test_grid_cover_and_alignment(self):
        blocks = create_grid((100, 50, 30), (64, 64, 32), (32, 32, 16))
        # covers exactly
        total = sum(np.prod(b.size) for b in blocks)
        assert total == 100 * 50 * 30
        # offsets aligned to storage blocks
        for b in blocks:
            assert all(o % s == 0 for o, s in zip(b.offset, (32, 32, 16)))
            assert b.grid_pos == tuple(o // s for o, s in zip(b.offset, (32, 32, 16)))
        assert len(blocks) == 2 * 1 * 1

    def test_grid_rejects_misaligned(self):
        with pytest.raises(ValueError):
            create_grid((10, 10, 10), (48, 48, 48), (32, 32, 32))


class TestChunkStore:
    def test_n5_roundtrip(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "a.n5"), StorageFormat.N5)
        ds = store.create_dataset("g/data", (40, 30, 20), (16, 16, 16), "uint16")
        block = np.arange(16 * 16 * 16, dtype=np.uint16).reshape(16, 16, 16)
        ds.write(block, (16, 0, 0))
        back = store.open_dataset("g/data").read((16, 0, 0), (16, 16, 16))
        np.testing.assert_array_equal(back, block)
        assert store.open_dataset("g/data").shape == (40, 30, 20)

    def test_n5_attributes_nested(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "a.n5"), StorageFormat.N5)
        store.set_attribute("", "Bigstitcher-Spark/NumChannels", 3)
        store.set_attribute("", "Bigstitcher-Spark/Boundingbox_min", [0, 0, 0])
        assert store.get_attribute("", "Bigstitcher-Spark/NumChannels") == 3
        # reopen detects format
        store2 = ChunkStore.open(str(tmp_path / "a.n5"))
        assert store2.format == StorageFormat.N5
        assert store2.get_attribute("", "Bigstitcher-Spark/Boundingbox_min") == [0, 0, 0]

    def test_zarr_axis_reversal(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "a.zarr"), StorageFormat.ZARR)
        # logical xyzct 5-D, on-disk tczyx
        ds = store.create_dataset("0", (20, 10, 5, 2, 1), (8, 8, 4, 1, 1), "uint8")
        data = np.random.default_rng(0).integers(0, 255, (8, 8, 4, 1, 1), dtype=np.uint8)
        ds.write(data, (8, 0, 0, 1, 0))
        back = store.open_dataset("0").read((8, 0, 0, 1, 0), (8, 8, 4, 1, 1))
        np.testing.assert_array_equal(back, data)
        # on-disk zarr shape must be reversed (t,c,z,y,x)
        import json, os
        zarray = json.load(open(os.path.join(str(tmp_path / "a.zarr"), "0", ".zarray")))
        assert zarray["shape"] == [1, 2, 5, 10, 20]


class TestSpimData:
    def test_synthetic_roundtrip(self, synthetic_project):
        sd = SpimData.load(synthetic_project.xml_path)
        assert len(sd.setups) == 2
        assert sd.timepoints == [0]
        views = sd.view_ids()
        assert views == [ViewId(0, 0), ViewId(0, 1)]
        # model = nominal translation (grid) ∘ identity calibration
        m = sd.model(ViewId(0, 1))
        np.testing.assert_allclose(
            m[:, 3], synthetic_project.nominal_offsets[1], atol=1e-9
        )
        # save → load again, identical models
        sd.save(synthetic_project.xml_path)
        sd2 = SpimData.load(synthetic_project.xml_path)
        for v in views:
            np.testing.assert_allclose(sd.model(v), sd2.model(v))
        assert sd2.setups[1].attributes["tile"] == 1

    def test_view_loader(self, synthetic_project):
        sd = SpimData.load(synthetic_project.xml_path)
        loader = ViewLoader(sd)
        ds = loader.open(ViewId(0, 0))
        assert ds.shape == (96, 96, 48)
        img = ds.read_full()
        assert img.dtype == np.uint16
        assert img.max() > 500  # beads present
        # halo over-read pads with zeros
        block = loader.read_block(ViewId(0, 0), 0, (-8, 0, 0), (16, 16, 16))
        assert block[:8].max() == 0 and block[8:].max() > 0

    def test_stitching_results_roundtrip(self, synthetic_project, tmp_path):
        from bigstitcher_spark_tpu.io.spimdata import PairwiseStitchingResult
        from bigstitcher_spark_tpu.utils.geometry import translation_affine

        sd = SpimData.load(synthetic_project.xml_path)
        res = PairwiseStitchingResult(
            views_a=(ViewId(0, 0),), views_b=(ViewId(0, 1),),
            transform=translation_affine((1.5, -2.25, 0.75)),
            correlation=0.87, hash=123.5,
            bbox=Interval((0, 0, 0), (9, 9, 9)),
        )
        sd.stitching_results[res.pair_key] = res
        p = str(tmp_path / "out.xml")
        sd.save(p)
        sd2 = SpimData.load(p)
        r2 = sd2.stitching_results[res.pair_key]
        np.testing.assert_allclose(r2.transform, res.transform)
        assert r2.correlation == pytest.approx(0.87)
        assert r2.hash == pytest.approx(123.5)
        assert r2.bbox == res.bbox


class TestMipmap:
    def test_mipmap_transform(self):
        m = mipmap_transform((2, 2, 1))
        np.testing.assert_allclose(
            apply_affine(m, np.array([0.0, 0, 0])), [0.5, 0.5, 0]
        )

    def test_best_level(self):
        factors = [[1, 1, 1], [2, 2, 1], [4, 4, 2]]
        assert best_mipmap_level(factors, (1, 1, 1)) == 0
        assert best_mipmap_level(factors, (2, 2, 2)) == 1
        assert best_mipmap_level(factors, (4, 4, 4)) == 2
        assert best_mipmap_level(factors, (3.9, 4, 4)) == 1


def test_bzip2_xz_codecs(tmp_path):
    """bzip2 (N5+zarr) and xz (N5) codecs round-trip (N5Util.java:82-105)."""
    import numpy as np

    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    data = np.arange(16 * 16 * 8, dtype=np.uint16).reshape(16, 16, 8)
    for fmt, comps in ((StorageFormat.N5, ("bzip2", "xz")),
                       (StorageFormat.ZARR, ("bzip2",))):
        for comp in comps:
            store = ChunkStore.create(
                str(tmp_path / f"{fmt.value}_{comp}"), fmt)
            ds = store.create_dataset("d", data.shape, (8, 8, 8), "uint16",
                                      compression=comp)
            ds.write(data, (0, 0, 0))
            assert (store.open_dataset("d").read_full() == data).all()

"""Cloud-URI storage routing (VERDICT r3 item 3).

The reference reads/writes s3:// and gs:// roots everywhere via URITools +
n5-aws-s3 (util/N5Util.java:47-80, AbstractInfrastructure.java:20-27). Here
every root goes through tensorstore kvstore specs; these tests exercise the
URI routing with the in-process ``memory://`` driver (a stand-in transport:
the same code path builds s3/gcs specs) plus spec-construction unit tests
for s3/gs that need no network.
"""

import json

import numpy as np
import pytest

from bigstitcher_spark_tpu.io import uris
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat


class TestUriParsing:
    def test_split(self):
        assert uris.split_uri("s3://buck/a/b") == ("s3", "buck", "a/b")
        assert uris.split_uri("gs://buck/x") == ("gs", "buck", "x")
        assert uris.split_uri("memory://p/q") == ("memory", "", "p/q")
        assert uris.split_uri("/local/p") == ("file", "", "/local/p")
        assert uris.split_uri("file:///local/p") == ("file", "", "/local/p")

    def test_join_dirname_normpath(self):
        assert uris.join("s3://b/a", "c", "d") == "s3://b/a/c/d"
        assert uris.dirname("s3://b/a/c") == "s3://b/a"
        assert uris.normpath("s3://b/a/./x/../c") == "s3://b/a/c"

    def test_s3_spec_and_region(self):
        uris.set_s3_region(None)
        spec = uris.kvstore_spec("s3://mybucket/root", "ds/0")
        assert spec == {"driver": "s3", "bucket": "mybucket",
                        "path": "root/ds/0/"}
        uris.set_s3_region("eu-west-1")
        try:
            spec = uris.kvstore_spec("s3://mybucket/root")
            assert spec["aws_region"] == "eu-west-1"
        finally:
            uris.set_s3_region(None)

    def test_gs_spec(self):
        spec = uris.kvstore_spec("gs://bucket-name/proj", "x")
        assert spec == {"driver": "gcs", "bucket": "bucket-name",
                        "path": "proj/x/"}

    def test_unknown_scheme_raises(self):
        with pytest.raises(ValueError, match="scheme"):
            uris.kvstore_spec("ftp://x/y")

    def test_bucket_root_has_no_leading_slash(self):
        # a container rooted directly at the bucket must not prefix keys "/"
        assert uris.kvstore_spec("s3://mybucket")["path"] == ""
        assert uris.kvstore_spec("gs://bucket-name")["path"] == ""

    def test_file_scheme_is_local(self, tmp_path):
        # file:// URIs strip to plain local paths at every entry point
        p = tmp_path / "x.xml"
        p.write_text("<SpimData version='0.2'/>")
        assert not uris.has_scheme(f"file://{p}")
        assert uris.strip_file_scheme(f"file://{p}") == str(p)
        assert uris.read_bytes(f"file://{p}").startswith(b"<SpimData")
        store = ChunkStore.create(f"file://{tmp_path}/c.n5", StorageFormat.N5)
        assert store.is_local and store.root == str(tmp_path / "c.n5")


class TestMemoryStore:
    """Full container lifecycle through a non-file kvstore."""

    def test_n5_roundtrip(self):
        store = ChunkStore.create("memory://t1/c.n5", StorageFormat.N5)
        assert not store.is_local
        ds = store.create_dataset("g/s0", (40, 30, 20), (16, 16, 16), "uint16")
        data = np.arange(40 * 30 * 20, dtype=np.uint16).reshape(40, 30, 20)
        ds.write(data, (0, 0, 0))
        back = ChunkStore.open("memory://t1/c.n5")
        assert back.format == StorageFormat.N5
        got = back.open_dataset("g/s0").read_full()
        assert (got == data).all()
        assert back.is_dataset("g/s0")
        assert not back.is_dataset("g")
        assert back.exists("g/s0") and not back.exists("nope")
        assert back.list_children("g") == ["s0"]

    def test_attributes_roundtrip(self):
        store = ChunkStore.create("memory://t2/c.n5", StorageFormat.N5)
        store.set_attribute("/", "Bigstitcher-Spark/NumChannels", 3)
        store.set_attribute("/", "Bigstitcher-Spark/Boundingbox_min", [1, 2, 3])
        back = ChunkStore.open("memory://t2/c.n5")
        assert back.get_attribute("/", "Bigstitcher-Spark/NumChannels") == 3
        assert back.get_attribute("/", "Bigstitcher-Spark/Boundingbox_min") == [1, 2, 3]

    def test_remove(self):
        store = ChunkStore.create("memory://t3/c.n5", StorageFormat.N5)
        store.create_dataset("a/b", (8, 8, 8), (8, 8, 8), "uint8")
        assert store.exists("a/b")
        store.remove("a")
        assert not store.exists("a/b")

    def test_zarr_fusion_container_on_memory(self, tmp_path):
        """create-fusion-container -> open -> write through memory://."""
        from bigstitcher_spark_tpu.io.container import create_fusion_container
        from bigstitcher_spark_tpu.utils.geometry import Interval
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(24, 24, 12),
            overlap=4, n_beads_per_tile=5)
        bbox = Interval.from_shape((24, 24, 12))
        root = "memory://t4/fused.ome.zarr"
        create_fusion_container(
            root, StorageFormat.ZARR, proj.xml_path, 1, 1, bbox,
            data_type="uint16", block_size=(16, 16, 8),
            min_intensity=0.0, max_intensity=65535.0)
        store = ChunkStore.open(root)
        assert store.format == StorageFormat.ZARR
        assert store.get_attribute("/", "Bigstitcher-Spark/NumChannels") == 1
        ds = store.open_dataset("0")
        blk = np.full((16, 16, 8, 1, 1), 7, np.uint16)
        ds.write(blk, (0, 0, 0, 0, 0))
        got = ds.read((0, 0, 0, 0, 0), (16, 16, 8, 1, 1))
        assert (got == 7).all()

    def test_spimdata_xml_on_memory(self, tmp_path):
        """Project XML load/save through a cloud-style URI."""
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(16, 16, 8),
            overlap=4, n_beads_per_tile=3)
        sd = SpimData.load(proj.xml_path)
        sd.save("memory://t5/dataset.xml")
        back = SpimData.load("memory://t5/dataset.xml")
        assert back.view_ids() == sd.view_ids()
        assert back.setups.keys() == sd.setups.keys()
        # relative loader path resolves against the URI base
        assert back.resolve_loader_path().startswith("memory://t5/")


class TestRealS3Protocol:
    """Drive tensorstore's REAL s3 kvstore driver against the in-repo
    S3-protocol fake (r4 verdict weak #5: memory:// only exercised spec
    routing, never the actual s3 code path — auth resolution, request
    signing, list-after-write, range reads). Reference role:
    cloud/TestCloudFunctions.java:42-181 against actual S3."""

    @pytest.fixture()
    def s3(self, monkeypatch):
        import sys as _sys

        sys_path_added = False
        try:
            from s3_fake import S3FakeServer
        except ImportError:
            import os as _os

            _sys.path.insert(0, _os.path.dirname(__file__))
            sys_path_added = True
            from s3_fake import S3FakeServer
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "testsecret")
        srv = S3FakeServer().start()
        uris.set_s3_endpoint(srv.endpoint)
        uris.set_s3_region("us-east-1")
        yield srv
        uris.set_s3_endpoint(None)
        uris.set_s3_region(None)
        srv.stop()
        if sys_path_added:
            _sys.path.pop(0)

    def test_resave_then_fuse_end_to_end_over_s3(self, tmp_path, s3):
        from click.testing import CliRunner

        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.utils.testdata import (
            make_synthetic_project,
        )

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=16, jitter=0.0, n_beads_per_tile=10)
        runner = CliRunner()

        out_xml = str(tmp_path / "resaved.xml")
        r = runner.invoke(cli, [
            "resave", "-x", proj.xml_path, "-xo", out_xml,
            "-o", "s3://testbucket/resaved.n5", "--N5",
            "--blockSize", "24,24,24", "-ds", "1,1,1; 2,2,1",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert any(k.startswith("resaved.n5/") for k in s3.objects), (
            "resave wrote no objects through the s3 endpoint")

        r = runner.invoke(cli, [
            "create-fusion-container", "-x", out_xml,
            "-o", "s3://testbucket/fused.zarr", "-s", "ZARR", "-d", "UINT16",
            "--blockSize", "24,24,24",
            "--minIntensity", "0", "--maxIntensity", "65535",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["affine-fusion",
                                "-o", "s3://testbucket/fused.zarr"],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output

        # read the fused volume back THROUGH the s3 driver and check content
        store = ChunkStore.open("s3://testbucket/fused.zarr")
        vol = store.open_dataset("0").read_full()
        assert vol.std() > 0 and vol.max() > 0
        # the fake observed real signed traffic: puts, gets and a V2 list
        methods = {req.split()[0] for req in s3.requests}
        assert {"GET", "PUT"} <= methods
        assert any("list-type=2" in req for req in s3.requests), (
            "no ListObjectsV2 issued — list-after-write path unexercised")

    def test_s3_spec_matches_tensorstore_schema(self, s3):
        """kvstore_spec's s3 output must stay openable by tensorstore —
        fails if the generated spec drifts from what the driver accepts."""
        import tensorstore as ts

        from bigstitcher_spark_tpu.io.chunkstore import ts_context

        spec = uris.kvstore_spec("s3://testbucket/probe", "sub")
        assert spec["endpoint"] == s3.endpoint
        kv = ts.KvStore.open(spec, context=ts_context()).result()
        kv.write("k", b"v").result()
        assert kv.read("k").result().value == b"v"
        assert any(k.endswith("probe/sub/k") for k in s3.objects)

"""Native N5 block codec (native/blockio.cpp via ctypes): round trips and
bidirectional interop with the tensorstore N5 driver — the independent-decoder
check that guards the on-disk contract."""

import os

import numpy as np
import pytest

from bigstitcher_spark_tpu.io import native_blockio

pytestmark = pytest.mark.skipif(
    not native_blockio.available(), reason="native blockio not built"
)


def test_roundtrip_dtypes(tmp_path):
    rng = np.random.default_rng(0)
    for dtype, gen in (
        ("uint8", lambda s: rng.integers(0, 255, s).astype(np.uint8)),
        ("uint16", lambda s: rng.integers(0, 65535, s).astype(np.uint16)),
        ("float32", lambda s: rng.normal(size=s).astype(np.float32)),
        ("float64", lambda s: rng.normal(size=s)),
    ):
        for comp in ("zstd", "raw"):
            data = gen((17, 9, 5))
            p = str(tmp_path / f"{dtype}_{comp}" / "0" / "0" / "0")
            native_blockio.write_block(p, data, compression=comp)
            back = native_blockio.read_block(p, dtype, (17, 9, 5),
                                             compression=comp)
            np.testing.assert_array_equal(back, data)


def test_missing_block_returns_none(tmp_path):
    assert native_blockio.read_block(
        str(tmp_path / "nope"), np.uint16, (4, 4, 4)) is None


def test_interop_with_tensorstore(tmp_path):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    rng = np.random.default_rng(1)
    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (40, 30, 20), (16, 16, 16), "uint16")
    data = rng.integers(0, 65535, (40, 30, 20)).astype(np.uint16)

    # native writes (through Dataset.write fast path) -> tensorstore reads
    for ox in range(0, 40, 16):
        for oy in range(0, 30, 16):
            for oz in range(0, 20, 16):
                ds.write(data[ox:ox + 16, oy:oy + 16, oz:oz + 16],
                         (ox, oy, oz))
    np.testing.assert_array_equal(store.open_dataset("ds").read_full(), data)

    # tensorstore writes -> native reads
    os.environ["BST_NATIVE_IO"] = "0"
    try:
        ds2 = store.create_dataset("ds2", (16, 16, 16), (16, 16, 16), "uint16")
        ds2.write(data[:16, :16, :16], (0, 0, 0))
    finally:
        os.environ["BST_NATIVE_IO"] = "1"
    back = native_blockio.read_block(
        str(tmp_path / "t.n5" / "ds2" / "0" / "0" / "0"), np.uint16,
        (16, 16, 16))
    np.testing.assert_array_equal(back, data[:16, :16, :16])


def test_unaligned_write_falls_back(tmp_path):
    """Non-block-aligned writes must still work (tensorstore path)."""
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (32, 32, 32), (16, 16, 16), "uint16")
    data = np.arange(8 * 8 * 8, dtype=np.uint16).reshape(8, 8, 8)
    ds.write(data, (4, 4, 4))
    np.testing.assert_array_equal(ds.read((4, 4, 4), (8, 8, 8)), data)


class TestNativeZarrChunks:
    def test_round_trip_via_tensorstore(self, tmp_path):
        """Native zarr chunk writes must read back exactly through a fresh
        tensorstore open: zstd + raw codecs, edge chunks, 5-D slots."""
        import numpy as np

        from bigstitcher_spark_tpu.io import native_blockio
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

        if not native_blockio.has_zarr():
            import pytest

            pytest.skip("native lib not built")
        st = ChunkStore.create(str(tmp_path / "z.zarr"), StorageFormat.ZARR)
        ds = st.create_dataset("0", (130, 96, 40, 2, 2), (64, 64, 32, 1, 1),
                               "uint16")
        rng = np.random.default_rng(0)
        vol = rng.integers(0, 60000, (130, 96, 40), dtype=np.uint16)
        ds.write(vol[..., None, None], (0, 0, 0, 1, 0))
        ds2 = ChunkStore.open(str(tmp_path / "z.zarr")).open_dataset("0")
        got = np.asarray(ds2.read((0, 0, 0, 1, 0), (130, 96, 40, 1, 1)))
        np.testing.assert_array_equal(got[..., 0, 0], vol)
        assert np.asarray(ds2.read((0, 0, 0, 0, 0),
                                   (130, 96, 40, 1, 1))).max() == 0
        raw_ds = st.create_dataset("raw", (50, 40, 30), (32, 32, 16),
                                   "float32", compression="raw")
        v2 = rng.random((50, 40, 30)).astype(np.float32)
        raw_ds.write(v2, (0, 0, 0))
        got2 = ChunkStore.open(str(tmp_path / "z.zarr")
                               ).open_dataset("raw").read_full()
        np.testing.assert_array_equal(got2, v2)

    def test_native_matches_tensorstore_bytes_decoded(self, tmp_path):
        """A chunk written natively and one written by tensorstore must
        decode to the same values (codec parity, not byte equality)."""
        import os

        import numpy as np

        from bigstitcher_spark_tpu.io import native_blockio
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

        if not native_blockio.has_zarr():
            import pytest

            pytest.skip("native lib not built")
        rng = np.random.default_rng(3)
        v = rng.integers(0, 4000, (32, 24, 16), dtype=np.uint16)
        outs = {}
        for label, env in (("native", "1"), ("ts", "0")):
            os.environ["BST_NATIVE_IO"] = env
            try:
                st = ChunkStore.create(str(tmp_path / f"{label}.zarr"),
                                       StorageFormat.ZARR)
                ds = st.create_dataset("0", v.shape, (32, 24, 16), "uint16")
                ds.write(v, (0, 0, 0))
            finally:
                os.environ["BST_NATIVE_IO"] = "1"
            outs[label] = ChunkStore.open(
                str(tmp_path / f"{label}.zarr")).open_dataset("0").read_full()
        np.testing.assert_array_equal(outs["native"], outs["ts"])
        np.testing.assert_array_equal(outs["native"], v)


class TestLz4Codec:
    """N5 lz4 (lz4-java LZ4Block framing — the reference's Lz4Compression,
    util/N5Util.java:87-88): tensorstore has no n5 lz4 codec, so these
    datasets are served entirely by the native path."""

    pytestmark = pytest.mark.skipif(
        not native_blockio.has_lz4(), reason="liblz4 not available")

    def test_block_roundtrip(self, tmp_path):
        rng = np.random.RandomState(3)
        data = (rng.rand(40, 24, 16) * 500).astype(np.uint16)
        p = str(tmp_path / "ds" / "0" / "0" / "0")
        native_blockio.write_block(p, data, compression="lz4")
        back = native_blockio.read_block(p, np.uint16, (40, 24, 16),
                                         compression="lz4")
        np.testing.assert_array_equal(data, back)

    def test_frame_format_is_lz4block(self, tmp_path):
        """Independent check of the on-disk layout: N5 big-endian header,
        then lz4-java frames (magic, token, LE lengths, xxhash32 of the raw
        chunk) terminated by an empty frame — decodable without our code
        when the payload chunk is stored RAW (incompressible data)."""
        import struct

        rng = np.random.RandomState(7)
        # random bytes are incompressible -> stored with method RAW (0x10)
        data = rng.randint(0, 2**16, (8, 8, 4)).astype(np.uint16)
        p = str(tmp_path / "b")
        native_blockio.write_block(p, data, compression="lz4")
        raw = open(p, "rb").read()
        mode, ndim = struct.unpack(">HH", raw[:4])
        assert (mode, ndim) == (0, 3)
        dims = struct.unpack(">3I", raw[4:16])
        assert dims == (8, 8, 4)
        frame = raw[16:]
        assert frame[:8] == b"LZ4Block"
        token = frame[8]
        method = token & 0xF0
        clen, rawlen, check = struct.unpack("<iii", frame[9:21])
        assert rawlen == data.nbytes
        assert method in (0x10, 0x20)
        if method == 0x10:  # stored raw: payload is the big-endian elements
            assert clen == rawlen
            payload = np.frombuffer(frame[21:21 + clen], ">u2")
            np.testing.assert_array_equal(
                payload.astype(np.uint16),
                np.asfortranarray(data).ravel(order="F"))
        # terminator frame closes the stream
        term = frame[21 + clen:]
        assert term[:8] == b"LZ4Block"
        assert struct.unpack("<ii", term[9:17]) == (0, 0)

    def test_chunkstore_dataset_roundtrip(self, tmp_path):
        from bigstitcher_spark_tpu.io.chunkstore import (
            ChunkStore, StorageFormat,
        )

        store = ChunkStore.create(str(tmp_path / "c.n5"), StorageFormat.N5)
        ds = store.create_dataset("vol", (64, 48, 32), (32, 32, 32),
                                  "uint16", compression="lz4")
        rng = np.random.RandomState(11)
        data = (rng.rand(64, 48, 32) * 900).astype(np.uint16)
        for ox in (0, 32):
            for oy in (0, 32):
                ds.write(data[ox:ox + 32, oy:oy + min(32, 48 - oy)],
                         (ox, oy, 0))
        # reopen cold: geometry + data come purely from the native path
        ds2 = ChunkStore.open(str(tmp_path / "c.n5")).open_dataset("vol")
        assert ds2.dtype == np.uint16
        assert ds2.shape == (64, 48, 32)
        assert ds2.block_size == (32, 32, 32)
        np.testing.assert_array_equal(ds2.read_full(), data)
        np.testing.assert_array_equal(ds2.read((16, 8, 4), (20, 20, 20)),
                                      data[16:36, 8:28, 4:24])

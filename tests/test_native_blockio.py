"""Native N5 block codec (native/blockio.cpp via ctypes): round trips and
bidirectional interop with the tensorstore N5 driver — the independent-decoder
check that guards the on-disk contract."""

import os

import numpy as np
import pytest

from bigstitcher_spark_tpu.io import native_blockio

pytestmark = pytest.mark.skipif(
    not native_blockio.available(), reason="native blockio not built"
)


def test_roundtrip_dtypes(tmp_path):
    rng = np.random.default_rng(0)
    for dtype, gen in (
        ("uint8", lambda s: rng.integers(0, 255, s).astype(np.uint8)),
        ("uint16", lambda s: rng.integers(0, 65535, s).astype(np.uint16)),
        ("float32", lambda s: rng.normal(size=s).astype(np.float32)),
        ("float64", lambda s: rng.normal(size=s)),
    ):
        for comp in ("zstd", "raw"):
            data = gen((17, 9, 5))
            p = str(tmp_path / f"{dtype}_{comp}" / "0" / "0" / "0")
            native_blockio.write_block(p, data, compression=comp)
            back = native_blockio.read_block(p, dtype, (17, 9, 5),
                                             compression=comp)
            np.testing.assert_array_equal(back, data)


def test_missing_block_returns_none(tmp_path):
    assert native_blockio.read_block(
        str(tmp_path / "nope"), np.uint16, (4, 4, 4)) is None


def test_interop_with_tensorstore(tmp_path):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    rng = np.random.default_rng(1)
    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (40, 30, 20), (16, 16, 16), "uint16")
    data = rng.integers(0, 65535, (40, 30, 20)).astype(np.uint16)

    # native writes (through Dataset.write fast path) -> tensorstore reads
    for ox in range(0, 40, 16):
        for oy in range(0, 30, 16):
            for oz in range(0, 20, 16):
                ds.write(data[ox:ox + 16, oy:oy + 16, oz:oz + 16],
                         (ox, oy, oz))
    np.testing.assert_array_equal(store.open_dataset("ds").read_full(), data)

    # tensorstore writes -> native reads
    os.environ["BST_NATIVE_IO"] = "0"
    try:
        ds2 = store.create_dataset("ds2", (16, 16, 16), (16, 16, 16), "uint16")
        ds2.write(data[:16, :16, :16], (0, 0, 0))
    finally:
        os.environ["BST_NATIVE_IO"] = "1"
    back = native_blockio.read_block(
        str(tmp_path / "t.n5" / "ds2" / "0" / "0" / "0"), np.uint16,
        (16, 16, 16))
    np.testing.assert_array_equal(back, data[:16, :16, :16])


def test_unaligned_write_falls_back(tmp_path):
    """Non-block-aligned writes must still work (tensorstore path)."""
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (32, 32, 32), (16, 16, 16), "uint16")
    data = np.arange(8 * 8 * 8, dtype=np.uint16).reshape(8, 8, 8)
    ds.write(data, (4, 4, 4))
    np.testing.assert_array_equal(ds.read((4, 4, 4), (8, 8, 8)), data)


class TestNativeZarrChunks:
    def test_round_trip_via_tensorstore(self, tmp_path):
        """Native zarr chunk writes must read back exactly through a fresh
        tensorstore open: zstd + raw codecs, edge chunks, 5-D slots."""
        import numpy as np

        from bigstitcher_spark_tpu.io import native_blockio
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

        if not native_blockio.has_zarr():
            import pytest

            pytest.skip("native lib not built")
        st = ChunkStore.create(str(tmp_path / "z.zarr"), StorageFormat.ZARR)
        ds = st.create_dataset("0", (130, 96, 40, 2, 2), (64, 64, 32, 1, 1),
                               "uint16")
        rng = np.random.default_rng(0)
        vol = rng.integers(0, 60000, (130, 96, 40), dtype=np.uint16)
        ds.write(vol[..., None, None], (0, 0, 0, 1, 0))
        ds2 = ChunkStore.open(str(tmp_path / "z.zarr")).open_dataset("0")
        got = np.asarray(ds2.read((0, 0, 0, 1, 0), (130, 96, 40, 1, 1)))
        np.testing.assert_array_equal(got[..., 0, 0], vol)
        assert np.asarray(ds2.read((0, 0, 0, 0, 0),
                                   (130, 96, 40, 1, 1))).max() == 0
        raw_ds = st.create_dataset("raw", (50, 40, 30), (32, 32, 16),
                                   "float32", compression="raw")
        v2 = rng.random((50, 40, 30)).astype(np.float32)
        raw_ds.write(v2, (0, 0, 0))
        got2 = ChunkStore.open(str(tmp_path / "z.zarr")
                               ).open_dataset("raw").read_full()
        np.testing.assert_array_equal(got2, v2)

    def test_native_matches_tensorstore_bytes_decoded(self, tmp_path):
        """A chunk written natively and one written by tensorstore must
        decode to the same values (codec parity, not byte equality)."""
        import os

        import numpy as np

        from bigstitcher_spark_tpu.io import native_blockio
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

        if not native_blockio.has_zarr():
            import pytest

            pytest.skip("native lib not built")
        rng = np.random.default_rng(3)
        v = rng.integers(0, 4000, (32, 24, 16), dtype=np.uint16)
        outs = {}
        for label, env in (("native", "1"), ("ts", "0")):
            os.environ["BST_NATIVE_IO"] = env
            try:
                st = ChunkStore.create(str(tmp_path / f"{label}.zarr"),
                                       StorageFormat.ZARR)
                ds = st.create_dataset("0", v.shape, (32, 24, 16), "uint16")
                ds.write(v, (0, 0, 0))
            finally:
                os.environ["BST_NATIVE_IO"] = "1"
            outs[label] = ChunkStore.open(
                str(tmp_path / f"{label}.zarr")).open_dataset("0").read_full()
        np.testing.assert_array_equal(outs["native"], outs["ts"])
        np.testing.assert_array_equal(outs["native"], v)

"""Native N5 block codec (native/blockio.cpp via ctypes): round trips and
bidirectional interop with the tensorstore N5 driver — the independent-decoder
check that guards the on-disk contract."""

import os

import numpy as np
import pytest

from bigstitcher_spark_tpu.io import native_blockio

pytestmark = pytest.mark.skipif(
    not native_blockio.available(), reason="native blockio not built"
)


def test_roundtrip_dtypes(tmp_path):
    rng = np.random.default_rng(0)
    for dtype, gen in (
        ("uint8", lambda s: rng.integers(0, 255, s).astype(np.uint8)),
        ("uint16", lambda s: rng.integers(0, 65535, s).astype(np.uint16)),
        ("float32", lambda s: rng.normal(size=s).astype(np.float32)),
        ("float64", lambda s: rng.normal(size=s)),
    ):
        for comp in ("zstd", "raw"):
            data = gen((17, 9, 5))
            p = str(tmp_path / f"{dtype}_{comp}" / "0" / "0" / "0")
            native_blockio.write_block(p, data, compression=comp)
            back = native_blockio.read_block(p, dtype, (17, 9, 5),
                                             compression=comp)
            np.testing.assert_array_equal(back, data)


def test_missing_block_returns_none(tmp_path):
    assert native_blockio.read_block(
        str(tmp_path / "nope"), np.uint16, (4, 4, 4)) is None


def test_interop_with_tensorstore(tmp_path):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    rng = np.random.default_rng(1)
    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (40, 30, 20), (16, 16, 16), "uint16")
    data = rng.integers(0, 65535, (40, 30, 20)).astype(np.uint16)

    # native writes (through Dataset.write fast path) -> tensorstore reads
    for ox in range(0, 40, 16):
        for oy in range(0, 30, 16):
            for oz in range(0, 20, 16):
                ds.write(data[ox:ox + 16, oy:oy + 16, oz:oz + 16],
                         (ox, oy, oz))
    np.testing.assert_array_equal(store.open_dataset("ds").read_full(), data)

    # tensorstore writes -> native reads
    os.environ["BST_NATIVE_IO"] = "0"
    try:
        ds2 = store.create_dataset("ds2", (16, 16, 16), (16, 16, 16), "uint16")
        ds2.write(data[:16, :16, :16], (0, 0, 0))
    finally:
        os.environ["BST_NATIVE_IO"] = "1"
    back = native_blockio.read_block(
        str(tmp_path / "t.n5" / "ds2" / "0" / "0" / "0"), np.uint16,
        (16, 16, 16))
    np.testing.assert_array_equal(back, data[:16, :16, :16])


def test_unaligned_write_falls_back(tmp_path):
    """Non-block-aligned writes must still work (tensorstore path)."""
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    ds = store.create_dataset("ds", (32, 32, 32), (16, 16, 16), "uint16")
    data = np.arange(8 * 8 * 8, dtype=np.uint16).reshape(8, 8, 8)
    ds.write(data, (4, 4, 4))
    np.testing.assert_array_equal(ds.read((4, 4, 4), (8, 8, 8)), data)

"""Non-rigid fusion: control-grid fit golden tests, kernel vs affine parity
under identity deformation, and a misregistration-recovery pipeline test (the
capability SparkNonRigidFusion exists for: residual deformation after affine
registration is absorbed by the interest-point-driven warp)."""

import numpy as np
import pytest


class TestControlGrid:
    def test_reproduces_global_affine(self):
        from bigstitcher_spark_tpu.ops.nonrigid import fit_control_grid

        rng = np.random.default_rng(0)
        A = np.array([[1.02, 0.03, 0.0, 5.0],
                      [-0.02, 0.99, 0.01, -3.0],
                      [0.0, 0.01, 1.01, 2.0]])
        targets = rng.uniform(0, 100, (60, 3))
        vw = targets @ A[:, :3].T + A[:, 3]
        grid = fit_control_grid(targets, vw, np.zeros(3), (5, 5, 5), 25.0)
        # every vertex model must equal the global affine
        models = grid.reshape(-1, 3, 4)
        np.testing.assert_allclose(models, np.broadcast_to(A, models.shape),
                                   atol=1e-3)

    def test_local_deformation(self):
        """Vertices near a locally-shifted cluster adopt that shift; far
        vertices keep the other cluster's (IDW falls off with distance)."""
        from bigstitcher_spark_tpu.ops.nonrigid import fit_control_grid

        rng = np.random.default_rng(1)
        t_lo = rng.uniform(2, 28, (40, 3))
        t_hi = rng.uniform(72, 98, (40, 3))
        targets = np.concatenate([t_lo, t_hi])
        shift = np.zeros((80, 3))
        shift[40:, 0] = 4.0  # the far cluster is shifted +4 in x
        vw = targets + shift
        grid = fit_control_grid(targets, vw, np.zeros(3), (11, 11, 11), 10.0)
        # vertex (1,1,1)=10px: near low cluster -> deformation there ~0
        m = grid[1, 1, 1].reshape(3, 4)
        pred = m[:, :3] @ np.array([10.0, 10, 10]) + m[:, 3]
        assert abs(pred[0] - 10.0) < 0.6
        # vertex (9,9,9)=90px: near high cluster -> shift ~4 in x
        m = grid[9, 9, 9].reshape(3, 4)
        pred = m[:, :3] @ np.array([90.0, 90, 90]) + m[:, 3]
        assert abs(pred[0] - 94.0) < 0.6

    def test_few_points_fallback(self):
        from bigstitcher_spark_tpu.ops.nonrigid import fit_control_grid

        grid = fit_control_grid(
            np.array([[10.0, 10, 10], [20.0, 20, 20]]),
            np.array([[12.0, 10, 10], [22.0, 20, 20]]),
            np.zeros(3), (3, 3, 3), 10.0,
        )
        m = grid[0, 0, 0].reshape(3, 4)
        np.testing.assert_allclose(m[:, :3], np.eye(3))
        np.testing.assert_allclose(m[:, 3], [2.0, 0, 0])


class TestNonrigidKernel:
    def test_identity_grid_matches_direct_sampling(self):
        from bigstitcher_spark_tpu.ops.nonrigid import nonrigid_fuse_block

        rng = np.random.default_rng(2)
        patch = rng.uniform(0, 1000, (40, 40, 40)).astype(np.float32)
        gdims = (5, 5, 5)
        grids = np.zeros((1, *gdims, 12), np.float32)
        grids[..., 0] = grids[..., 5] = grids[..., 10] = 1.0
        ident = np.hstack([np.eye(3), np.zeros((3, 1))]).astype(np.float32)
        fused, wsum = nonrigid_fuse_block(
            patch[None], grids, ident[None], np.zeros((1, 3), np.float32),
            np.full((1, 3), 40.0, np.float32), np.zeros((1, 3), np.float32),
            np.full((1, 3), 1e-6, np.float32), np.ones(1, np.float32),
            np.zeros(3, np.float32), np.zeros(3, np.float32),
            np.full(3, 10.0, np.float32),
            block_shape=(32, 32, 32), fusion_type="AVG",
        )
        # fp rounding in the coefficient interpolation perturbs sampling
        # coordinates by ~1e-6 px; with O(1e3) local gradients that is ~1e-3
        # absolute — not bit-exactness (SURVEY §7 float-determinism note)
        np.testing.assert_allclose(np.asarray(fused), patch[:32, :32, :32],
                                   atol=0.5)

    def test_constant_translation_grid_shifts_sampling(self):
        from bigstitcher_spark_tpu.ops.nonrigid import nonrigid_fuse_block

        rng = np.random.default_rng(3)
        patch = rng.uniform(0, 1000, (40, 40, 40)).astype(np.float32)
        gdims = (5, 5, 5)
        grids = np.zeros((1, *gdims, 12), np.float32)
        grids[..., 0] = grids[..., 5] = grids[..., 10] = 1.0
        grids[..., 3] = 3.0  # world -> view-world: +3 in x
        ident = np.hstack([np.eye(3), np.zeros((3, 1))]).astype(np.float32)
        fused, _ = nonrigid_fuse_block(
            patch[None], grids, ident[None], np.zeros((1, 3), np.float32),
            np.full((1, 3), 40.0, np.float32), np.zeros((1, 3), np.float32),
            np.full((1, 3), 1e-6, np.float32), np.ones(1, np.float32),
            np.zeros(3, np.float32), np.zeros(3, np.float32),
            np.full(3, 10.0, np.float32),
            block_shape=(32, 32, 32), fusion_type="AVG",
        )
        np.testing.assert_allclose(np.asarray(fused), patch[3:35, :32, :32],
                                   atol=0.5)


class TestNonrigidPipeline:
    def test_recovers_misregistration(self, tmp_path):
        """Tiles registered at their (wrong) nominal offsets: affine fusion
        double-images beads in the overlap; non-rigid fusion driven by
        matched interest points must re-align them (bead residual < 1 px)."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points, save_detections,
        )
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_interest_points, save_matches,
        )
        from bigstitcher_spark_tpu.models.nonrigid_fusion import (
            build_unique_points, fuse_nonrigid_volume,
        )
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box
        from bigstitcher_spark_tpu.ops.dog import dog_block, localize_quadratic

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(96, 96, 48),
            overlap=40, jitter=3.0, seed=13, n_beads_per_tile=40,
        )
        sd = SpimData.load(proj.xml_path)
        views = sorted(sd.registrations)
        loader = ViewLoader(sd)
        dets = detect_interest_points(
            sd, loader, views,
            DetectionParams(downsample_xy=1, downsample_z=1,
                            block_size=(96, 96, 48)),
            progress=False,
        )
        store = InterestPointStore(str(tmp_path / "proj" / "interestpoints.n5"))
        dparams = DetectionParams()
        save_detections(sd, store, dets, dparams)
        mparams = MatchingParams(ransac_min_inliers=5, ransac_iterations=2000,
                                 model="TRANSLATION", regularization="NONE")
        res = match_interest_points(sd, views, mparams, store, progress=False)
        save_matches(sd, store, res, mparams, views)

        unique = build_unique_points(sd, store, views, ["beads"])
        assert all(len(unique.targets[v]) > 0 for v in views)

        bbox = maximal_bounding_box(sd, views, None)
        cstore = ChunkStore.create(str(tmp_path / "fused.n5"), StorageFormat.N5)
        out = cstore.create_dataset("fused", bbox.shape, (64, 64, 48), "float32")
        stats = fuse_nonrigid_volume(
            sd, loader, views, unique, out, bbox,
            block_size=(64, 64, 48), block_scale=(1, 1, 1), cpd=10.0,
            out_dtype="float32", min_intensity=0.0, max_intensity=1.0,
        )
        assert stats.voxels == bbox.num_elements
        vol = out.read_full()

        # detect beads in the fused volume; each true bead inside the fused
        # bbox must appear exactly once within <1px of SOME detection whose
        # position matches the correspondence-averaged truth
        dogv, mask = dog_block(vol, np.float32(vol.min()),
                               np.float32(vol.max()), np.float32(0.01), 1.8)
        coords = np.argwhere(np.asarray(mask))
        subs, _ = localize_quadratic(np.asarray(dogv), coords)
        fused_pts = subs + np.array(bbox.min)

        # the warp aligns each correspondence at the AVERAGE of the views'
        # (jittered) world positions: expected = bead + mean registration error
        drift = 0.5 * ((proj.nominal_offsets[0] - proj.true_offsets[0])
                       + (proj.nominal_offsets[1] - proj.true_offsets[1]))
        checked = 0
        for bead in proj.bead_positions:
            # consider beads well inside the overlap region of both tiles
            in0 = np.all((bead - proj.true_offsets[0] >= 8)
                         & (bead - proj.true_offsets[0] <= [88, 88, 40]))
            in1 = np.all((bead - proj.true_offsets[1] >= 8)
                         & (bead - proj.true_offsets[1] <= [88, 88, 40]))
            if not (in0 and in1):
                continue
            expect = bead + drift
            d = np.linalg.norm(fused_pts - expect, axis=1)
            near = np.sort(d)[:2]
            assert near[0] < 1.5, f"bead {bead} unmatched (nearest {near[0]:.2f})"
            # no double image: second detection must be a DIFFERENT bead, far
            assert near[1] > 4.0, f"bead {bead} double-imaged ({near})"
            checked += 1
        assert checked >= 3

"""Tiered storage IO engine (PR 19): async prefetch overlap, LRU→disk
spill/promote, budget-0 inertness (the exact pre-tier code paths),
generation-bump invalidation reaching the disk tier, and
multipart-parallel uploads surviving injected transient failures without
a partial chunk."""

import numpy as np
import pytest

from bigstitcher_spark_tpu import profiling
from bigstitcher_spark_tpu.io import chunkcache, disktier, prefetch
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.observe import metrics

CHUNK = (16, 16, 8)          # chunk bytes: 16*16*8 * 2 = 4096
CHUNK_BYTES = 16 * 16 * 8 * 2


@pytest.fixture(autouse=True)
def _fresh_tiers(monkeypatch):
    monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(64 << 20))
    prefetch.reset()
    chunkcache.get_cache().clear()
    disktier.get_tier().clear()
    yield
    prefetch.reset()
    prefetch.drain(5.0)
    chunkcache.get_cache().clear()
    disktier.get_tier().clear()


def _delta(baseline, prefix):
    d = metrics.get_registry().snapshot_delta(baseline)
    return {k.replace(prefix, ""): int(v) for k, v in d.items()
            if k.startswith(prefix) and isinstance(v, (int, float))}


def _make_n5(tmp_path, name="c", shape=(64, 64, 8)):
    store = ChunkStore.create(str(tmp_path / f"{name}.n5"), StorageFormat.N5)
    ds = store.create_dataset("a", shape, CHUNK, "uint16")
    data = (np.arange(int(np.prod(shape))).reshape(shape)
            % 60000).astype(np.uint16)
    ds.write(data, (0, 0, 0))
    chunkcache.get_cache().clear()   # drop anything staged by the write
    disktier.get_tier().clear()
    return store, ds, data


class TestPrefetchOverlap:
    """Submitted boxes decode on worker threads into the shared LRU, and
    the consumer's later read is pure cache hits — trace-asserted via the
    io.prefetch span and the read-path byte attribution."""

    def test_prefetch_then_read_hits_cache(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_PREFETCH_BYTES", str(64 << 20))
        monkeypatch.setenv("BST_PREFETCH_THREADS", "2")
        _, ds, data = _make_n5(tmp_path)

        profiling.enable(True)
        profiling.get().reset()
        base = metrics.get_registry().snapshot()
        try:
            prefetch.submit_boxes([(ds, (0, 0, 0), (32, 32, 8))])
            assert prefetch.drain(15.0), "prefetch pool failed to drain"
            spans = profiling.get().stats()
        finally:
            profiling.enable(False)
            profiling.get().reset()

        # the fetch ran off the consumer path, under its own span, and
        # attributed its own traffic to the prefetch byte counter
        assert "io.prefetch" in spans
        d = _delta(base, "bst_io_prefetch_")
        assert d["bytes_total"] == 4 * CHUNK_BYTES
        st = prefetch.stats()
        assert st["tracked_entries"] == 4

        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (32, 32, 8))
        assert np.array_equal(got, data[:32, :32])
        cc = _delta(base, "bst_chunk_cache_")
        assert cc["hits_total"] == 4 and cc.get("misses_total", 0) == 0
        pf = _delta(base, "bst_io_prefetch_")
        # consumption hook: every prefetched chunk was credited as a hit
        assert pf["hit_total"] == 4
        assert pf["hit_bytes_total"] == 4 * CHUNK_BYTES
        # nothing re-decoded from the container on the consumer's read
        io = metrics.get_registry().snapshot_delta(base)
        assert not io.get('bst_io_read_bytes_total{path="native"}')
        assert not io.get('bst_io_read_bytes_total{path="tensorstore"}')
        assert io.get('bst_io_read_bytes_total{path="cache"}') == \
            4 * CHUNK_BYTES

    def test_budget_pacing_untracks_oldest_as_misses(self, tmp_path,
                                                     monkeypatch):
        # budget of 2 chunks, prefetch 4: the pool must untrack the
        # oldest overshoot as wasted read-ahead, not wedge
        monkeypatch.setenv("BST_PREFETCH_BYTES", str(2 * CHUNK_BYTES))
        monkeypatch.setenv("BST_PREFETCH_THREADS", "2")
        _, ds, _ = _make_n5(tmp_path)
        base = metrics.get_registry().snapshot()
        prefetch.submit_boxes([(ds, (0, 0, 0), (32, 32, 8))])
        assert prefetch.drain(15.0)
        d = _delta(base, "bst_io_prefetch_")
        assert d["miss_total"] >= 2           # overshoot counted as waste
        assert prefetch.stats()["tracked_bytes"] <= 2 * CHUNK_BYTES


class TestDiskSpillPromote:
    def test_spill_then_promote_bit_identical(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(3 * CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_BYTES", str(64 << 20))
        monkeypatch.setenv("BST_DISK_TIER_DIR", str(tmp_path / "tier"))
        _, ds, data = _make_n5(tmp_path)

        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (64, 64, 8))      # 16 chunks, 3-chunk LRU
        assert np.array_equal(got, data)
        d = _delta(base, "bst_io_disktier_")
        assert d["spill_bytes_total"] >= 13 * CHUNK_BYTES
        st = disktier.get_tier().stats()
        assert st["entries"] >= 13

        # second pass is served from memory + disk: bit-identical, zero
        # container re-decode
        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (64, 64, 8))
        assert np.array_equal(got, data)
        d = metrics.get_registry().snapshot_delta(base)
        assert not d.get('bst_io_read_bytes_total{path="native"}')
        assert not d.get('bst_io_read_bytes_total{path="tensorstore"}')
        assert _delta(base, "bst_io_disktier_")["hit_bytes_total"] > 0

    def test_tier_is_inclusive_promote_leaves_disk_copy(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_BYTES", str(64 << 20))
        monkeypatch.setenv("BST_DISK_TIER_DIR", str(tmp_path / "tier"))
        _, ds, data = _make_n5(tmp_path, shape=(32, 16, 8))   # 2 chunks
        ds.read((0, 0, 0), (32, 16, 8))            # chunk 0 spills
        tier = disktier.get_tier()
        assert tier.stats()["entries"] == 1

        # promote chunk 0 back (evicts chunk 1); the disk copy must stay —
        # a write invalidates both tiers, so it is still current
        got = ds.read((0, 0, 0), (16, 16, 8))
        assert np.array_equal(got, data[:16, :16])
        assert tier.stats()["entries"] >= 1

        # bounce back and forth: every read stays bit-identical and the
        # re-evicted promoted chunk skips the rewrite (spill bytes flat)
        base = metrics.get_registry().snapshot()
        for _ in range(3):
            assert np.array_equal(ds.read((16, 0, 0), (16, 16, 8)),
                                  data[16:32, :16])
            assert np.array_equal(ds.read((0, 0, 0), (16, 16, 8)),
                                  data[:16, :16])
        d = metrics.get_registry().snapshot_delta(base)
        assert not d.get('bst_io_read_bytes_total{path="native"}')
        assert not d.get('bst_io_read_bytes_total{path="tensorstore"}')
        assert not d.get("bst_io_disktier_spill_bytes_total")

    def test_disk_budget_evicts_oldest(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_BYTES", str(2 * CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_DIR", str(tmp_path / "tier"))
        _, ds, data = _make_n5(tmp_path)
        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (64, 64, 8))      # 16 chunks through a
        assert np.array_equal(got, data)           # 2-chunk disk budget
        st = disktier.get_tier().stats()
        assert st["entries"] <= 2 and st["bytes"] <= 2 * CHUNK_BYTES
        assert _delta(base, "bst_io_disktier_")["evict_bytes_total"] > 0


class TestBudgetZeroInertness:
    """BST_PREFETCH_BYTES=0 / BST_DISK_TIER_BYTES=0 / BST_REMOTE_CACHE=off
    must restore the exact pre-tier code paths."""

    def test_prefetch_zero_budget_is_a_noop(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_PREFETCH_BYTES", "0")
        _, ds, _ = _make_n5(tmp_path)
        base = metrics.get_registry().snapshot()
        prefetch.submit_boxes([(ds, (0, 0, 0), (64, 64, 8))])
        st = prefetch.stats()
        assert st["queued"] == 0 and st["tracked_entries"] == 0
        assert not any(_delta(base, "bst_io_prefetch_").values())

    def test_disk_tier_zero_budget_never_spills(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(2 * CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_BYTES", "0")
        monkeypatch.setenv("BST_DISK_TIER_DIR", str(tmp_path / "tier"))
        _, ds, data = _make_n5(tmp_path)
        assert np.array_equal(ds.read((0, 0, 0), (64, 64, 8)), data)
        assert disktier.get_tier().stats()["entries"] == 0
        assert not (tmp_path / "tier").exists()
        # evicted chunks really are gone: the re-read decodes again
        base = metrics.get_registry().snapshot()
        ds.read((0, 0, 0), (16, 16, 8))
        assert _delta(base, "bst_chunk_cache_")["misses_total"] == 1

    def test_remote_cache_off_restores_bypass(self, tmp_path, monkeypatch):
        _, ds, _ = _make_n5(tmp_path)
        assert ds._cacheable()                     # local: always eligible
        # make the same dataset look like a remote object store
        monkeypatch.setattr(ds.store, "is_local", False)
        monkeypatch.setattr(ds.store, "is_remote_object", True, raising=False)
        monkeypatch.setenv("BST_REMOTE_CACHE", "run")
        assert ds._cacheable()
        monkeypatch.setenv("BST_REMOTE_CACHE", "off")
        assert not ds._cacheable()                 # historical bypass
        assert ds.prefetch_box((0, 0, 0), (16, 16, 8)) == []


class TestInvalidationThroughDisk:
    def test_write_invalidates_spilled_chunks(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(2 * CHUNK_BYTES))
        monkeypatch.setenv("BST_DISK_TIER_BYTES", str(64 << 20))
        monkeypatch.setenv("BST_DISK_TIER_DIR", str(tmp_path / "tier"))
        _, ds, data = _make_n5(tmp_path)
        assert np.array_equal(ds.read((0, 0, 0), (64, 64, 8)), data)
        tier = disktier.get_tier()
        assert tier.stats()["entries"] >= 14       # most chunks on disk

        patch = np.full(CHUNK, 7, np.uint16)
        ds.write(patch, (0, 0, 0))                 # bumps the generation
        expect = data.copy()
        expect[:16, :16, :8] = patch

        # a stale disk entry for chunk (0,0,0) would serve the OLD bytes
        got = ds.read((0, 0, 0), (64, 64, 8))
        assert np.array_equal(got, expect)
        assert (got[:16, :16, :8] == 7).all()


class TestMultipartUpload:
    @pytest.fixture()
    def s3(self, monkeypatch):
        import os as _os
        import sys as _sys

        from bigstitcher_spark_tpu.io import uris

        sys_path_added = False
        try:
            from s3_fake import S3FakeServer
        except ImportError:
            _sys.path.insert(0, _os.path.dirname(__file__))
            sys_path_added = True
            from s3_fake import S3FakeServer
        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "test")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "testsecret")
        srv = S3FakeServer().start()
        uris.set_s3_endpoint(srv.endpoint)
        uris.set_s3_region("us-east-1")
        yield srv
        uris.set_s3_endpoint(None)
        uris.set_s3_region(None)
        srv.stop()
        if sys_path_added:
            _sys.path.pop(0)

    def test_retry_on_injected_failure_no_partial_chunk(self, tmp_path, s3,
                                                        monkeypatch):
        from bigstitcher_spark_tpu.io import chunkstore

        monkeypatch.setenv("BST_UPLOAD_THREADS", "8")
        store = ChunkStore.create("s3://upbkt/c.n5", StorageFormat.N5)
        ds = store.create_dataset("a", (64, 64, 8), CHUNK, "uint16")
        data = (np.arange(64 * 64 * 8).reshape(64, 64, 8)
                % 60000).astype(np.uint16)

        calls = {"n": 0, "failed": 0}
        real_upload = chunkstore._upload_one

        def flaky_upload(dset, sel, part):
            calls["n"] += 1
            if calls["failed"] < 2:
                calls["failed"] += 1
                raise OSError("injected transient upload failure")
            real_upload(dset, sel, part)

        monkeypatch.setattr(chunkstore, "_upload_one", flaky_upload)
        profiling.enable(True)
        profiling.get().reset()
        base = metrics.get_registry().snapshot()
        try:
            ds.write(data, (0, 0, 0))              # 16 parts, 2 injected
            spans = profiling.get().stats()        # failures, retried
        finally:
            profiling.enable(False)
            profiling.get().reset()

        assert calls["failed"] == 2
        assert calls["n"] == 16 + 2                # every part + 2 retries
        assert "io.upload" in spans
        d = metrics.get_registry().snapshot_delta(base)
        assert d.get("bst_io_remote_write_bytes_total", 0) >= data.nbytes

        # read back THROUGH the s3 driver, bypassing the decoded cache:
        # every chunk is complete and bit-identical (no partial part)
        chunkcache.get_cache().clear()
        monkeypatch.setenv("BST_REMOTE_CACHE", "off")
        assert np.array_equal(ds.read_full(), data)

    def test_single_thread_keeps_one_ts_write(self, tmp_path, s3,
                                              monkeypatch):
        from bigstitcher_spark_tpu.io import chunkstore

        monkeypatch.setenv("BST_UPLOAD_THREADS", "1")
        store = ChunkStore.create("s3://upbkt2/c.n5", StorageFormat.N5)
        ds = store.create_dataset("a", (32, 32, 8), CHUNK, "uint16")
        data = np.ones((32, 32, 8), np.uint16)

        def boom(dset, sel, part):
            raise AssertionError("multipart path taken with 1 thread")

        monkeypatch.setattr(chunkstore, "_upload_one", boom)
        ds.write(data, (0, 0, 0))                  # single ts write fallback
        monkeypatch.setenv("BST_REMOTE_CACHE", "off")
        assert np.array_equal(ds.read_full(), data)

"""Re-export shim: the in-process S3-protocol fake moved into the
package (bigstitcher_spark_tpu/utils/s3_fake.py) so the bench's
``measure_cloud`` extra and scripts/cloud_smoke.sh share the fixture
with the test suite. Import from here in tests as before."""

from bigstitcher_spark_tpu.utils.s3_fake import S3FakeServer

__all__ = ["S3FakeServer"]

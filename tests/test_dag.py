"""The `bst pipeline` streaming stage-DAG executor: spec validation, the
block-exchange registry (gating, handoff, release-on-finish), the
failure-cone + ephemeral-container lifecycle, and the tier-1 acceptance
E2E — a streamed resave->fuse->downsample->detect pipeline bit-identical
to the staged one-shot CLI sequence with ZERO container re-reads of the
elided intermediate (counted by the bst_dag_* metrics)."""

import json
import os
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.dag import (
    PipelineSpec,
    SpecError,
    example_spec,
    run_pipeline,
)
from bigstitcher_spark_tpu.dag import stream
from bigstitcher_spark_tpu.io.chunkstore import (
    ChunkStore,
    StorageFormat,
    _DAG_HOOKS,
)
from bigstitcher_spark_tpu.observe import metrics


def _mk_project(tmp_path, name="proj", **kw):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    spec = dict(n_tiles=(2, 1, 1), tile_size=(64, 64, 32), overlap=16,
                jitter=1.0, n_beads_per_tile=20, seed=7)
    spec.update(kw)
    return make_synthetic_project(str(tmp_path / name), **spec).xml_path


def _small_blocks(spec):
    """Shrink the example spec's containers to 32^2 x 16 blocks so the
    tiny fixtures stream tens of blocks instead of one."""
    for s in spec["stages"]:
        if s["id"] == "resave":
            s["args"] += ["--blockSize", "32,32,16", "-ds", "1,1,1; 2,2,1"]
        if s["id"] == "create":
            s["args"] += ["--blockSize", "32,32,16"]
    return spec


# -- spec validation ---------------------------------------------------------


class TestSpec:
    def test_unknown_tool_rejected(self):
        with pytest.raises(SpecError, match="unservable"):
            PipelineSpec.from_dict(
                {"stages": [{"id": "a", "tool": "no-such-tool"}]})
        with pytest.raises(SpecError, match="unservable"):
            PipelineSpec.from_dict(
                {"stages": [{"id": "a", "tool": "pipeline"}]})

    def test_cycle_rejected(self):
        with pytest.raises(SpecError, match="cycle"):
            PipelineSpec.from_dict({"stages": [
                {"id": "a", "tool": "config", "after": ["b"]},
                {"id": "b", "tool": "config", "after": ["a"]}]})

    def test_stream_edges_participate_in_cycle_check(self):
        with pytest.raises(SpecError, match="cycle"):
            PipelineSpec.from_dict({
                "datasets": {"x": {}, "y": {}},
                "stages": [
                    {"id": "a", "tool": "config", "writes": ["x"],
                     "reads": ["y"]},
                    {"id": "b", "tool": "config", "writes": ["y"],
                     "reads": ["x"]}]})

    def test_undeclared_refs_rejected(self):
        with pytest.raises(SpecError, match="undeclared dataset"):
            PipelineSpec.from_dict({"stages": [
                {"id": "a", "tool": "config", "reads": ["ghost"]}]})
        with pytest.raises(SpecError, match="undeclared dataset"):
            PipelineSpec.from_dict({"stages": [
                {"id": "a", "tool": "config", "args": ["-o", "@ghost"]}]})
        with pytest.raises(SpecError, match="unknown stage"):
            PipelineSpec.from_dict({"stages": [
                {"id": "a", "tool": "config", "after": ["ghost"]}]})

    def test_dataset_needs_a_producer(self):
        with pytest.raises(SpecError, match="no producer"):
            PipelineSpec.from_dict({
                "datasets": {"x": {}},
                "stages": [{"id": "a", "tool": "config", "reads": ["x"]}]})

    def test_duplicate_stage_ids_rejected(self):
        with pytest.raises(SpecError, match="duplicate"):
            PipelineSpec.from_dict({"stages": [
                {"id": "a", "tool": "config"},
                {"id": "a", "tool": "config"}]})

    def test_resolution_and_substitution(self, tmp_path):
        spec = PipelineSpec.from_dict({
            "datasets": {"eph": {"ephemeral": True},
                         "kept": {"path": "out.n5"}},
            "stages": [{"id": "a", "tool": "config",
                        "args": ["-o", "@eph", "-k", "@kept",
                                 "-w", "@workdir/x"],
                        "writes": ["eph", "kept"]}]})
        spec.resolve(str(tmp_path), keep_intermediates=False, run_id="r1")
        args = spec.stages[0].args
        assert args[1].startswith("memory://bst-dag-r1/")
        assert args[3] == str(tmp_path / "out.n5")
        assert args[5] == str(tmp_path / "x")
        assert spec.datasets["eph"].elided
        # keep-intermediates materializes at the declared path instead
        spec2 = PipelineSpec.from_dict({
            "datasets": {"eph": {"ephemeral": True, "path": "mid.n5"}},
            "stages": [{"id": "a", "tool": "config", "args": ["@eph"],
                        "writes": ["eph"]}]})
        spec2.resolve(str(tmp_path), keep_intermediates=True, run_id="r2")
        assert spec2.stages[0].args[0] == str(tmp_path / "mid.n5")
        assert not spec2.datasets["eph"].elided

    def test_example_spec_validates(self, tmp_path):
        d = example_spec(str(tmp_path / "dataset.xml"))
        spec = PipelineSpec.from_dict(d)
        assert {s.id for s in spec.stages} == \
            {"resave", "create", "fuse", "downsample", "detect"}
        # downsample streams from fuse; detect barriers on resave's XML
        fuse = next(s for s in spec.stages if s.id == "downsample")
        assert spec.stream_parents(fuse) == {"fuse"}
        detect = next(s for s in spec.stages if s.id == "detect")
        assert "resave" in spec.barrier_parents(detect)
        assert spec.stream_parents(detect) == {"resave"}


# -- the block-exchange registry --------------------------------------------


class TestStreamRegistry:
    def _edge_env(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "edge.n5"),
                                  StorageFormat.N5)
        ds = store.create_dataset("s0", (64, 32, 16), (16, 16, 16),
                                  "uint16")
        prod = stream.StageToken("prod", "t")
        cons = stream.StageToken("cons", "t")
        edge = stream.EdgeState("e", store.root, {prod}, {cons})
        reg = stream.registry()
        reg.register([edge])
        return reg, store, ds, prod, cons, edge

    def test_gate_blocks_until_publish_and_serves_from_handoff(
            self, tmp_path):
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        got = {}
        try:
            def consume():
                with stream.stage_scope(cons):
                    got["data"] = ds.read((0, 0, 0), (32, 32, 16))

            th = threading.Thread(target=consume)
            th.start()
            time.sleep(0.3)
            assert th.is_alive(), "consumer must block on unwritten blocks"
            data = (np.arange(64 * 32 * 16, dtype=np.uint16)
                    .reshape(64, 32, 16))
            with stream.stage_scope(prod):
                ds.write(data, (0, 0, 0))
            th.join(timeout=20)
            assert not th.is_alive()
            assert np.array_equal(got["data"], data[:32])
            assert edge.blocks_published == 8     # 4x2x1 chunk grid
            assert edge.bytes_elided > 0          # served by the handoff
            assert edge.bytes_reread == 0
        finally:
            reg.unregister([edge])
        assert _DAG_HOOKS[0] is None              # last edge uninstalled

    def test_gate_releases_when_producers_finish(self, tmp_path):
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        try:
            done = threading.Event()

            def consume():
                with stream.stage_scope(cons):
                    ds.read((48, 0, 0), (16, 16, 16))  # never written
                done.set()

            th = threading.Thread(target=consume)
            th.start()
            time.sleep(0.3)
            assert not done.is_set()
            reg.stage_finished(prod)   # fusion's "empty block" case
            th.join(timeout=20)
            assert done.is_set()
        finally:
            reg.unregister([edge])

    def test_producer_reads_pass_ungated(self, tmp_path):
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        try:
            with stream.stage_scope(prod):
                out = ds.read((0, 0, 0), (16, 16, 16))  # no deadlock
            assert out.shape == (16, 16, 16)
        finally:
            reg.unregister([edge])

    def test_consumer_release_frees_exchange(self, tmp_path):
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        try:
            data = np.ones((64, 32, 16), np.uint16)
            with stream.stage_scope(prod):
                ds.write(data, (0, 0, 0))
            assert metrics.gauge("bst_dag_exchange_bytes").value > 0
            reg.stage_finished(cons)   # consumer ends without reading all
            assert metrics.gauge("bst_dag_exchange_bytes").value == 0
        finally:
            reg.unregister([edge])


# -- executor: failure cone + ephemeral lifecycle ----------------------------


class TestExecutor:
    def test_failure_cancels_cone_independent_branch_finishes(
            self, tmp_path):
        res = run_pipeline({
            "name": "cone",
            "datasets": {"x": {"ephemeral": True, "stream": False}},
            "stages": [
                {"id": "solo", "tool": "config", "args": []},
                {"id": "bad", "tool": "downsample",
                 "args": ["-i", str(tmp_path / "missing.n5"),
                          "-di", "s0", "-ds", "2,2,1"],
                 "writes": ["x"]},
                {"id": "child", "tool": "config", "args": [],
                 "reads": ["x"]},
                {"id": "grandchild", "tool": "config", "args": [],
                 "after": ["child"]},
            ]}, workdir=str(tmp_path))
        states = {r["id"]: r["state"] for r in res.stages}
        assert not res.ok
        assert states == {"solo": "done", "bad": "failed",
                          "child": "cancelled", "grandchild": "cancelled"}
        assert _DAG_HOOKS[0] is None   # hooks uninstalled even on failure

    def test_ephemeral_cleaned_on_success_and_failure(self, tmp_path):
        xml = _mk_project(tmp_path)
        proj = os.path.dirname(xml)
        # disk-backed ephemeral + a failing consumer: the half-written
        # tree must not survive the run
        res = run_pipeline({
            "name": "cleanup",
            "datasets": {"resaved": {"ephemeral": True,
                                     "backing": "disk"}},
            "stages": [
                {"id": "resave", "tool": "resave",
                 "args": ["-x", xml, "-xo",
                          os.path.join(proj, "re.xml"),
                          "-o", "@resaved", "--N5",
                          "-ds", "1,1,1"],
                 "writes": ["resaved"]},
                {"id": "bad", "tool": "downsample",
                 "args": ["-i", str(tmp_path / "missing.n5"),
                          "-di", "s0", "-ds", "2,2,1"],
                 "after": ["resave"], "reads": ["resaved"],
                 "writes": ["resaved"]},
            ]}, workdir=str(tmp_path))
        assert not res.ok
        leftovers = [d for d in os.listdir(tmp_path)
                     if d.startswith(".bst-dag-tmp-")]
        assert leftovers == [], leftovers

    def test_keep_intermediates_materializes_on_disk(self, tmp_path):
        xml = _mk_project(tmp_path)
        proj = os.path.dirname(xml)
        res = run_pipeline({
            "name": "keep",
            "datasets": {"resaved": {
                "ephemeral": True,
                "path": os.path.join(proj, "kept-resaved.n5")}},
            "stages": [
                {"id": "resave", "tool": "resave",
                 "args": ["-x", xml, "-xo",
                          os.path.join(proj, "kept.xml"),
                          "-o", "@resaved", "--N5", "-ds", "1,1,1"],
                 "writes": ["resaved"]},
            ]}, workdir=str(tmp_path), keep_intermediates=True)
        assert res.ok, res.to_dict()
        assert res.containers_elided == 0
        kept = os.path.join(proj, "kept-resaved.n5")
        assert res.kept_intermediates == [kept]
        assert ChunkStore.open(kept).is_dataset("setup0/timepoint0/s0")


# -- acceptance E2E ----------------------------------------------------------


class TestStreamedParity:
    def _staged(self, runner, xml):
        proj = os.path.dirname(xml)
        rexml = os.path.join(proj, "pipeline-resaved.xml")
        cmds = [
            ["resave", "-x", xml, "-xo", rexml,
             "-o", f"{proj}/pipeline-resaved.n5", "--N5",
             "--blockSize", "32,32,16", "-ds", "1,1,1; 2,2,1"],
            ["create-fusion-container", "-x", rexml,
             "-o", f"{proj}/pipeline-fused.n5", "-s", "N5", "-d", "UINT16",
             "--minIntensity", "0", "--maxIntensity", "65535",
             "--blockSize", "32,32,16"],
            ["affine-fusion", "-o", f"{proj}/pipeline-fused.n5"],
            ["downsample", "-i", f"{proj}/pipeline-fused.n5",
             "-di", "ch0tp0/s0", "-ds", "2,2,1"],
            ["detect-interestpoints", "-x", rexml, "-l", "beads",
             "-s", "1.8", "-t", "0.008", "-dsxy", "1", "-dsz", "1"],
        ]
        for args in cmds:
            r = runner.invoke(cli, args, catch_exceptions=False)
            assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"

    def test_streamed_pipeline_bit_identical_and_zero_rereads(
            self, tmp_path):
        """Acceptance: the streamed resave->fuse->downsample->detect
        pipeline produces bit-identical fused volumes, pyramid levels and
        interest points vs the staged one-shot CLI sequence, the resaved
        intermediate is elided to memory and its consumers re-read ZERO
        container bytes (bst_dag_* counted), and the elided container is
        cleaned up."""
        xml = _mk_project(tmp_path, "streamed")
        proj = os.path.dirname(xml)
        spec = _small_blocks(example_spec(xml))
        reread = metrics.counter("bst_dag_bytes_reread_total")
        elided_ctr = metrics.counter("bst_dag_containers_elided_total")
        r0, c0 = reread.value, elided_ctr.value
        res = run_pipeline(spec, workdir=str(tmp_path))
        assert res.ok, res.to_dict()
        summary = res.to_dict()
        # zero container reads of ANY streamed edge this run...
        assert reread.value - r0 == 0
        # ...and per-edge: the elided intermediate specifically
        by_edge = {e["edge"]: e for e in summary["edges"]}
        assert by_edge["resaved"]["elided"]
        assert by_edge["resaved"]["bytes_reread"] == 0
        assert by_edge["resaved"]["bytes_elided"] > 0
        assert by_edge["resaved"]["blocks_streamed"] > 0
        assert by_edge["fused"]["blocks_streamed"] > 0
        assert elided_ctr.value - c0 == 1
        # the elided container never touched disk and is gone from memory
        assert not os.path.exists(os.path.join(proj, "pipeline-resaved.n5"))
        eph_root = by_edge["resaved"]["root"]
        assert eph_root.startswith("memory://")
        assert not ChunkStore(eph_root, StorageFormat.N5).exists(
            "setup0/timepoint0/s0")

        # staged one-shot sequence on an identical project (same seed)
        xml_d = _mk_project(tmp_path, "staged")
        proj_d = os.path.dirname(xml_d)
        self._staged(CliRunner(), xml_d)

        for name in ("ch0tp0/s0", "ch0tp0/s1"):
            a = ChunkStore.open(
                f"{proj}/pipeline-fused.n5").open_dataset(name).read_full()
            b = ChunkStore.open(
                f"{proj_d}/pipeline-fused.n5").open_dataset(name).read_full()
            assert np.array_equal(a, b), name

        from bigstitcher_spark_tpu.io.interestpoints import \
            InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData

        sa = SpimData.load(os.path.join(proj, "pipeline-resaved.xml"))
        sb = SpimData.load(os.path.join(proj_d, "pipeline-resaved.xml"))
        ia, ib = (InterestPointStore.for_project(sa),
                  InterestPointStore.for_project(sb))
        for v in sa.view_ids():
            pa, _ = ia.load_points(v, "beads")
            pb, _ = ib.load_points(v, "beads")
            assert len(pa) and np.array_equal(pa, pb)

    def test_pipeline_run_cli(self, tmp_path):
        """`bst pipeline init` + `bst pipeline run --summary` round trip
        (the CLI face of the executor; the heavy parity is above)."""
        xml = _mk_project(tmp_path)
        runner = CliRunner()
        spec_path = str(tmp_path / "p.json")
        r = runner.invoke(cli, ["pipeline", "init", spec_path, "-x", xml],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output
        spec = json.load(open(spec_path))
        json.dump(_small_blocks(spec), open(spec_path, "w"))
        summary_path = str(tmp_path / "summary.json")
        r = runner.invoke(cli, ["pipeline", "run", "--summary",
                                summary_path, spec_path],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output
        summary = json.load(open(summary_path))
        assert summary["ok"] and summary["containers_elided"] == 1
        assert summary["bytes_reread"] == 0
        # dry-run prints the plan without executing
        r = runner.invoke(cli, ["pipeline", "run", "--dryRun", spec_path],
                          catch_exceptions=False)
        assert r.exit_code == 0 and "streams-from=fuse" in r.output

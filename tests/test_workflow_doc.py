"""WORKFLOW.md must stay runnable: extract its ``bst ...`` commands and run
them in order against the generated example project. Any drift between the
documented pipeline and the CLI breaks this test."""

import os
import re
import shlex

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli

DOC = os.path.join(os.path.dirname(__file__), "..", "WORKFLOW.md")


def doc_commands():
    """All ``bst ...`` commands from WORKFLOW.md's code fences, in order."""
    text = open(DOC).read()
    cmds = []
    for block in re.findall(r"```bash\n(.*?)```", text, re.S):
        joined = block.replace("\\\n", " ")
        for line in joined.splitlines():
            line = line.split("#")[0].strip()
            if line.startswith("bst "):
                cmds.append(shlex.split(line)[1:])
    return cmds


def test_workflow_runs(tmp_path, monkeypatch):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    monkeypatch.chdir(tmp_path)
    make_synthetic_project("example", n_tiles=(2, 2, 1),
                           tile_size=(96, 96, 32), overlap=24,
                           jitter=2.0, n_beads_per_tile=40)
    cmds = doc_commands()
    assert len(cmds) >= 14, f"expected the full pipeline, got {len(cmds)}"
    runner = CliRunner()
    for args in cmds:
        r = runner.invoke(cli, args, catch_exceptions=False)
        assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"

    # the pipeline must actually have registered + fused the tiles
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
    from bigstitcher_spark_tpu.io.spimdata import SpimData

    ds = ChunkStore.open("example/fused.ome.zarr").open_dataset("0")
    vol = np.asarray(ds.read((0, 0, 0, 0, 0), (*ds.shape[:3], 1, 1)))
    assert vol.std() > 0
    nr = ChunkStore.open("example/nonrigid.ome.zarr").open_dataset("0")
    nvol = np.asarray(nr.read((0, 0, 0, 0, 0), (*nr.shape[:3], 1, 1)))
    assert nvol.std() > 0
    sd = SpimData.load("example/resaved.xml")
    # clear-registrations --keep 1 ran last: back to one transform per view
    assert all(len(ch) == 1 for ch in sd.registrations.values())

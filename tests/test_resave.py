"""resave + downsample tools (reference: SparkResaveN5, SparkDownsample;
test model follows the reference's CLI-level end-to-end pattern,
TestSparkResave.java:30-38, on the synthetic fixture)."""

import os

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId


def test_resave_cli_roundtrip(synthetic_project, tmp_path):
    proj = synthetic_project
    out = str(tmp_path / "resaved.n5")
    xml_out = str(tmp_path / "resaved.xml")
    runner = CliRunner()
    res = runner.invoke(cli, [
        "resave", "-x", proj.xml_path, "-xo", xml_out, "-o", out, "--N5",
        "--blockSize", "32,32,16", "-ds", "1,1,1; 2,2,1",
        "--threads", "2",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output

    # new project points at the new container and images round-trip
    sd2 = SpimData.load(xml_out)
    assert sd2.resolve_loader_path() == out
    loader2 = ViewLoader(sd2)
    sd1 = SpimData.load(proj.xml_path)
    loader1 = ViewLoader(sd1)
    for v in sd1.view_ids():
        a = loader1.open(v, 0).read_full()
        b = loader2.open(v, 0).read_full()
        np.testing.assert_array_equal(a, b)
        # level 1 = 2,2,1 average of level 0
        lvl1 = loader2.open(v, 1).read_full()
        assert lvl1.shape == (a.shape[0] // 2, a.shape[1] // 2, a.shape[2])
    # registrations survive
    assert sd2.registrations.keys() == sd1.registrations.keys()


def test_resave_auto_pyramid(synthetic_project, tmp_path):
    from bigstitcher_spark_tpu.models.resave import propose_pyramid

    sd = SpimData.load(synthetic_project.xml_path)
    pyr = propose_pyramid(sd, sd.view_ids())
    assert pyr[0] == [1, 1, 1]
    assert len(pyr) >= 2  # 96x96x48 tiles halve at least once
    for prev, cur in zip(pyr, pyr[1:]):
        assert all(c % p == 0 for p, c in zip(prev, cur))


def test_resave_rejects_non_divisible_pyramid(synthetic_project, tmp_path):
    runner = CliRunner()
    res = runner.invoke(cli, [
        "resave", "-x", synthetic_project.xml_path,
        "-xo", str(tmp_path / "o.xml"), "-o", str(tmp_path / "o.n5"), "--N5",
        "-ds", "1,1,1; 2,2,1; 3,3,1",
    ])
    assert res.exit_code != 0
    assert "not an exact multiple" in str(res.exception)


def test_downsample_thin_axis_clamped_level(tmp_path):
    """A level dim clamped to 1 must edge-replicate, not crash
    (downsample_read pads past the source extent)."""
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.models.downsample_driver import (
        downsample_write_block,
    )
    from bigstitcher_spark_tpu.utils.grid import create_grid

    store = ChunkStore.create(str(tmp_path / "t.n5"), StorageFormat.N5)
    src = store.create_dataset("s0", (8, 8, 1), (8, 8, 1), "uint16")
    src.write(np.arange(64, dtype=np.uint16).reshape(8, 8, 1), (0, 0, 0))
    dims = [max(1, s // 2) for s in src.shape]  # z floors to 0 -> clamped to 1
    dst = store.create_dataset("s1", dims, (8, 8, 1), "uint16")
    for blk in create_grid(dims, dims):
        downsample_write_block(src, dst, blk, (2, 2, 2))
    out = dst.read_full()
    exp = np.arange(64).reshape(8, 8).astype(np.float64)
    exp = 0.25 * (exp[0::2, 0::2] + exp[1::2, 0::2] + exp[0::2, 1::2]
                  + exp[1::2, 1::2])
    np.testing.assert_allclose(out[..., 0], np.round(exp), atol=1.0)


def test_downsample_continues_absolute_factors(synthetic_project):
    """Starting at s1 (factors 2,2,1 in a resaved project) must stamp
    absolute, not relative, downsamplingFactors on new levels."""
    import os

    sd = SpimData.load(synthetic_project.xml_path)
    container = sd.resolve_loader_path()
    store = ChunkStore.open(container)
    store.set_attribute("setup0/timepoint0/s0", "downsamplingFactors",
                        [2, 2, 1])
    runner = CliRunner()
    res = runner.invoke(cli, [
        "downsample", "-i", container, "-di", "setup0/timepoint0/s0",
        "-ds", "2,2,2", "-do", "setup0/timepoint0/sx",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output
    assert store.get_attribute("setup0/timepoint0/sx", "downsamplingFactors") \
        == [4, 4, 2]


def test_downsample_registers_setup_factors(synthetic_project):
    """New BDV-layout levels must appear in the setup-level factor list so
    ViewLoader/best_mipmap_level can discover them."""
    sd = SpimData.load(synthetic_project.xml_path)
    container = sd.resolve_loader_path()
    runner = CliRunner()
    res = runner.invoke(cli, [
        "downsample", "-i", container, "-di", "setup1/timepoint0/s0",
        "-ds", "2,2,1; 2,2,2",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output
    store = ChunkStore.open(container)
    factors = store.get_attribute("setup1", "downsamplingFactors")
    assert [2, 2, 1] in factors and [4, 4, 2] in factors
    loader = ViewLoader(SpimData.load(synthetic_project.xml_path))
    assert loader.num_levels(1) == 3


def test_downsample_rejects_5d(tmp_path):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat

    store = ChunkStore.create(str(tmp_path / "c.zarr"), StorageFormat.ZARR)
    store.create_dataset("0", (16, 16, 8, 1, 1), (16, 16, 8, 1, 1), "uint16")
    runner = CliRunner()
    res = runner.invoke(cli, [
        "downsample", "-i", str(tmp_path / "c.zarr"), "-di", "0",
        "-ds", "2,2,1", "-do", "1",
    ])
    assert res.exit_code != 0
    assert "5-D" in res.output


def test_downsample_cli(synthetic_project, tmp_path):
    sd = SpimData.load(synthetic_project.xml_path)
    container = sd.resolve_loader_path()
    runner = CliRunner()
    res = runner.invoke(cli, [
        "downsample", "-i", container,
        "-di", "setup0/timepoint0/s0",
        "-ds", "2,2,1; 2,2,2",
        "--threads", "2",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output

    store = ChunkStore.open(container)
    s0 = store.open_dataset("setup0/timepoint0/s0").read_full()
    s1 = store.open_dataset("setup0/timepoint0/s1").read_full()
    s2 = store.open_dataset("setup0/timepoint0/s2").read_full()
    assert s1.shape == (s0.shape[0] // 2, s0.shape[1] // 2, s0.shape[2])
    assert s2.shape == (s1.shape[0] // 2, s1.shape[1] // 2, s1.shape[2] // 2)
    # numerics: pairwise averaging along x/y for s1
    expected = s0.astype(np.float64)
    expected = 0.5 * (expected[0::2] + expected[1::2])
    expected = 0.5 * (expected[:, 0::2] + expected[:, 1::2])
    np.testing.assert_allclose(
        s1.astype(np.float64), np.round(expected), atol=1.0
    )
    assert store.get_attribute("setup0/timepoint0/s2", "downsamplingFactors") \
        == [4, 4, 2]

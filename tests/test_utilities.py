"""Utility tools: clear-interestpoints, clear-registrations, transform-points,
split-images (reference ClearInterestPoints / ClearRegistrations /
TransformPoints / SplitDatasets semantics)."""

import numpy as np
import pytest
from click.testing import CliRunner


@pytest.fixture()
def project(tmp_path):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(96, 96, 48),
        overlap=24, jitter=2.0, seed=3, n_beads_per_tile=20,
    )


def test_clear_registrations_remove_and_keep(project):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId

    sd = SpimData.load(project.xml_path)
    assert len(sd.registrations[ViewId(0, 0)]) == 2
    runner = CliRunner()
    # --remove 1 drops the LAST-applied (list head: the grid translation)
    res = runner.invoke(cli, ["clear-registrations", "-x", project.xml_path,
                              "--remove", "1"])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(project.xml_path)
    chain = sd.registrations[ViewId(0, 0)]
    assert len(chain) == 1
    assert chain[0].name == "calibration"
    # --keep 0 empties the chain
    res = runner.invoke(cli, ["clear-registrations", "-x", project.xml_path,
                              "--keep", "0"])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(project.xml_path)
    assert sd.registrations[ViewId(0, 0)] == []
    # exactly one of keep/remove required
    assert runner.invoke(cli, ["clear-registrations", "-x", project.xml_path]
                         ).exit_code != 0


def test_clear_interestpoints(project):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.interestpoints import (
        CorrespondingPoint, InterestPointStore,
    )
    from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId

    sd = SpimData.load(project.xml_path)
    store = InterestPointStore.for_project(sd)
    v0, v1 = ViewId(0, 0), ViewId(0, 1)
    from bigstitcher_spark_tpu.io.interestpoints import register_points_in_xml

    for v in (v0, v1):
        grp = store.save_points(v, "beads", np.random.rand(10, 3) * 50)
        register_points_in_xml(sd, v, "beads", "test", grp)
    store.save_correspondences(v0, "beads",
                               [CorrespondingPoint(0, v1, "beads", 1)])
    sd.save(project.xml_path)

    runner = CliRunner()
    res = runner.invoke(cli, ["clear-interestpoints", "-x", project.xml_path,
                              "--onlyCorrespondences"])
    assert res.exit_code == 0, res.output
    assert store.load_correspondences(v0, "beads") == []
    ids, _ = store.load_points(v0, "beads")
    assert len(ids) == 10  # points kept

    res = runner.invoke(cli, ["clear-interestpoints", "-x", project.xml_path])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(project.xml_path)
    assert v0 not in sd.interest_points
    ids, _ = store.load_points(v0, "beads")
    assert len(ids) == 0


def test_transform_points(project, tmp_path):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
    from bigstitcher_spark_tpu.utils.geometry import apply_affine

    sd = SpimData.load(project.xml_path)
    expect = apply_affine(sd.model(ViewId(0, 1)), np.array([[10.0, 20.0, 5.0]]))
    runner = CliRunner()
    res = runner.invoke(cli, ["transform-points", "-x", project.xml_path,
                              "-vi", "0,1", "-p", "10,20,5"])
    assert res.exit_code == 0, res.output
    got = [float(v) for v in res.output.strip().split("-> ")[1].split(",")]
    np.testing.assert_allclose(got, expect[0], atol=1e-9)

    csv_in = tmp_path / "pts.csv"
    csv_in.write_text("10,20,5\n1,2,3\n")
    csv_out = tmp_path / "out.csv"
    res = runner.invoke(cli, ["transform-points", "-x", project.xml_path,
                              "-vi", "0,1", "--csvIn", str(csv_in),
                              "--csvOut", str(csv_out)])
    assert res.exit_code == 0, res.output
    rows = [[float(v) for v in line.split(",")]
            for line in csv_out.read_text().strip().splitlines()]
    np.testing.assert_allclose(rows[0], expect[0], atol=1e-9)


class TestSplitImages:
    def test_split_geometry_and_reads(self, project, tmp_path):
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
        from bigstitcher_spark_tpu.models.splitting import split_images
        from bigstitcher_spark_tpu.utils.geometry import apply_affine

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        new_sd = split_images(sd, loader, (64, 64, 48), (16, 16, 8))
        # 96x96 tile with 64-size/16-overlap: starts [0,32] per xy axis -> 4 subtiles
        assert len(new_sd.setups) == 2 * 4
        out_xml = str(tmp_path / "split.xml")
        new_sd.save(out_xml)
        rt = SpimData.load(out_xml)
        assert rt.split_info == new_sd.split_info

        # data: sub-view read must equal the source crop
        new_loader = ViewLoader(rt)
        src_img = loader.open(ViewId(0, 0)).read_full()
        for setup, (src, off) in sorted(rt.split_info.items())[:4]:
            if src != 0:
                continue
            sub = new_loader.open(ViewId(0, setup)).read_full()
            sl = tuple(slice(o, o + s) for o, s in zip(off, sub.shape))
            np.testing.assert_array_equal(sub, src_img[sl])
            # geometry: sub-view pixel p maps to the same world point as
            # source pixel p+off
            w_sub = apply_affine(rt.model(ViewId(0, setup)),
                                 np.array([[1.0, 2.0, 3.0]]))
            w_src = apply_affine(sd.model(ViewId(0, 0)),
                                 np.array([[1.0 + off[0], 2.0 + off[1],
                                            3.0 + off[2]]]))
            np.testing.assert_allclose(w_sub, w_src, atol=1e-9)

    def test_fake_interest_points_glue(self, project, tmp_path):
        """Fake points must give the solver exact links: solving the split
        project with jittered sub-tile positions must snap them back."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.splitting import split_images

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        store = InterestPointStore(str(tmp_path / "ip.n5"))
        new_sd = split_images(
            sd, loader, (64, 64, 48), (16, 16, 8),
            fake_interest_points=True, fip_error=0.0, fip_store=store,
        )
        views = sorted(new_sd.registrations)
        with_ips = [v for v in views if "splitPoints" in
                    new_sd.interest_points.get(v, {})]
        assert len(with_ips) == len(views)
        # correspondences are symmetric and world-consistent
        c0 = store.load_correspondences(with_ips[0], "splitPoints")
        assert len(c0) > 0
        from bigstitcher_spark_tpu.utils.geometry import apply_affine

        ids, locs = store.load_points(with_ips[0], "splitPoints")
        lut = dict(zip(ids.astype(int), locs))
        for c in c0[:20]:
            oids, olocs = store.load_points(c.other_view, c.other_label)
            olut = dict(zip(oids.astype(int), olocs))
            wa = apply_affine(new_sd.model(with_ips[0]), lut[c.id])
            wb = apply_affine(new_sd.model(c.other_view), olut[c.other_id])
            np.testing.assert_allclose(wa, wb, atol=1e-6)


def test_cli_split(project, tmp_path):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.spimdata import SpimData

    runner = CliRunner()
    out_xml = str(tmp_path / "split.xml")
    res = runner.invoke(cli, ["split-images", "-x", project.xml_path,
                              "--xmlout", out_xml,
                              "-s", "64,64,48", "-o", "16,16,8"])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(out_xml)
    assert len(sd.setups) == 8
    assert len(sd.split_info) == 8


def test_env_diagnostics_command():
    """`bst env` prints runtime diagnostics without touching any project."""
    from click.testing import CliRunner

    from bigstitcher_spark_tpu.cli.main import cli

    r = CliRunner().invoke(cli, ["env"], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    assert "native codec:" in r.output
    assert "backend:" in r.output


def test_serve_container_cors(tmp_path):
    """serve-container's HTTP server exposes container files with the CORS
    header browser viewers (neuroglancer) require."""
    import json
    import threading
    import urllib.request

    from bigstitcher_spark_tpu.cli.utility_tools import make_container_server

    root = tmp_path / "fused.zarr"
    (root / "0").mkdir(parents=True)
    meta = {"zarr_format": 2}
    (root / "0" / ".zarray").write_text(json.dumps(meta))
    srv = make_container_server(str(root), 0)
    host, port = srv.server_address
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(
                f"http://{host}:{port}/0/.zarray", timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Access-Control-Allow-Origin"] == "*"
            assert json.loads(resp.read()) == meta
    finally:
        srv.shutdown()
        srv.server_close()
        t.join(timeout=10)

"""Multi-host scale-out skeleton (VERDICT r3 item 6; SURVEY §2.5).

Real multi-host can't run here, so these tests check the pieces the launch
recipe relies on: the deterministic work partition covers the grid exactly
once at any world size, degenerates at world_size=1, and the production
fusion driver composed over a faked 2-process world writes exactly the full
volume (each process its disjoint slice) — the reference's executor model
(flintstone-sge-example.sh:29-119) without Spark.
"""

import numpy as np
import pytest

from bigstitcher_spark_tpu.parallel.distributed import (
    init_distributed, partition_items, world,
)


class TestPartition:
    def test_covers_exactly_once(self):
        items = list(range(103))
        for count in (1, 2, 3, 8):
            slices = [partition_items(items, i, count) for i in range(count)]
            merged = sorted(x for s in slices for x in s)
            assert merged == items
            # balanced to within one item
            sizes = [len(s) for s in slices]
            assert max(sizes) - min(sizes) <= 1

    def test_world_size_one_is_identity(self):
        items = ["a", "b", "c"]
        assert partition_items(items, 0, 1) == items

    def test_current_process_defaults(self):
        # single-process runtime: jax world is (0, 1) -> identity
        assert world() == (0, 1)
        assert partition_items([1, 2, 3]) == [1, 2, 3]

    def test_bad_index_raises(self):
        with pytest.raises(ValueError, match="world size"):
            partition_items([1], 5, 2)

    def test_init_noop_without_config(self, monkeypatch):
        for k in ("BST_COORDINATOR", "BST_NUM_PROCESSES", "BST_PROCESS_ID"):
            monkeypatch.delenv(k, raising=False)
        assert init_distributed() is False


class TestFusedGridAcrossProcesses:
    def test_two_fake_processes_write_full_volume(self, tmp_path, monkeypatch):
        """Run the sharded fusion driver twice with a faked 2-process world;
        the union of writes must equal the single-process output exactly."""
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box
        import bigstitcher_spark_tpu.parallel.mesh as mesh_mod

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
            overlap=8, jitter=1.0, seed=7, n_beads_per_tile=8)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        bbox = maximal_bounding_box(sd, views)

        def fuse(name, fake_world=None):
            if fake_world is not None:
                monkeypatch.setattr(
                    "bigstitcher_spark_tpu.parallel.distributed.world",
                    lambda: fake_world)
            store = ChunkStore.create(str(tmp_path / f"{name}.n5"),
                                      StorageFormat.N5)
            ds = store.create_dataset("f", bbox.shape, (16, 16, 8), "uint16")
            fuse_volume(sd, loader, views, ds, bbox, block_size=(16, 16, 8),
                        block_scale=(1, 1, 1), out_dtype="uint16", devices=2)
            return ds

        single = fuse("single").read_full()
        # two fake processes write into the SAME container
        store = ChunkStore.create(str(tmp_path / "multi.n5"), StorageFormat.N5)
        ds = store.create_dataset("f", bbox.shape, (16, 16, 8), "uint16")
        for pi in (0, 1):
            monkeypatch.setattr(
                "bigstitcher_spark_tpu.parallel.distributed.world",
                lambda pi=pi: (pi, 2))
            fuse_volume(sd, loader, views, ds, bbox, block_size=(16, 16, 8),
                        block_scale=(1, 1, 1), out_dtype="uint16", devices=2)
        multi = ds.read_full()
        assert single.std() > 0
        assert (multi == single).all()

    def test_fake_single_process_slice_is_partial(self, tmp_path, monkeypatch):
        """Process 0 of 2 alone must NOT cover the full grid (proves the
        partition actually prunes work rather than duplicating it)."""
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

        proj = make_synthetic_project(
            str(tmp_path / "proj2"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
            overlap=8, jitter=0.0, seed=8, n_beads_per_tile=8)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        bbox = maximal_bounding_box(sd, views)
        monkeypatch.setattr(
            "bigstitcher_spark_tpu.parallel.distributed.world",
            lambda: (0, 2))
        store = ChunkStore.create(str(tmp_path / "part.n5"), StorageFormat.N5)
        ds = store.create_dataset("f", bbox.shape, (16, 16, 8), "uint16")
        stats = fuse_volume(sd, loader, views, ds, bbox,
                            block_size=(16, 16, 8), block_scale=(1, 1, 1),
                            out_dtype="uint16", devices=2)
        assert 0 < stats.voxels < int(np.prod(bbox.shape))


class TestRealTwoProcessRun:
    """REAL multi-host integration (r4 verdict weak #4): two OS processes
    boot jax.distributed against a coordinator, run the production fusion
    CLI over partitioned grids, and cross the sync_global_devices barrier —
    no monkeypatched world. The union of the two processes' disjoint chunk
    writes must equal a single-process run exactly."""

    def test_two_os_processes_fuse_disjoint_slices(self, tmp_path):
        import os
        import socket
        import subprocess
        import sys

        from click.testing import CliRunner

        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 2, 1), tile_size=(64, 64, 32),
            overlap=16, jitter=0.0, n_beads_per_tile=15)
        xml = proj.xml_path

        def make_container(path):
            r = CliRunner().invoke(cli, [
                "create-fusion-container", "-x", xml, "-o", path, "-s", "N5",
                "-d", "UINT16", "--blockSize", "32,32,16",
                "--minIntensity", "0", "--maxIntensity", "65535",
            ], catch_exceptions=False)
            assert r.exit_code == 0, r.output

        ref = str(tmp_path / "ref.n5")
        multi = str(tmp_path / "multi.n5")
        make_container(ref)
        make_container(multi)

        r = CliRunner().invoke(cli, ["affine-fusion", "-o", ref,
                                     "--blockScale", "1,1,1"],
                               catch_exceptions=False)
        assert r.exit_code == 0, r.output

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        base_env = dict(os.environ)
        base_env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "",  # 1 local CPU device per process
            "BST_COORDINATOR": f"127.0.0.1:{port}",
            "BST_NUM_PROCESSES": "2",
        })
        procs = []
        for pid in range(2):
            env = dict(base_env)
            env["BST_PROCESS_ID"] = str(pid)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "bigstitcher_spark_tpu.cli.main",
                 "affine-fusion", "-o", multi, "--blockScale", "1,1,1"],
                env=env, cwd=repo, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        outs = [p.communicate(timeout=420)[0] for p in procs]
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"process failed:\n{out}"

        import numpy as np

        ref_vol = ChunkStore.open(ref).open_dataset("ch0tp0/s0").read_full()
        multi_vol = ChunkStore.open(multi).open_dataset(
            "ch0tp0/s0").read_full()
        assert ref_vol.std() > 0
        np.testing.assert_array_equal(ref_vol, multi_vol)


class TestPodLaunchScript:
    def test_local_mode_two_processes(self, tmp_path):
        """scripts/pod_launch.sh -n 2 (local mode) must drive the fusion CLI
        through a real 2-process jax.distributed run and exit 0."""
        import os
        import subprocess

        from click.testing import CliRunner

        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=16, jitter=0.0, n_beads_per_tile=10)
        out = str(tmp_path / "fused.n5")
        r = CliRunner().invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path, "-o", out,
            "-s", "N5", "-d", "UINT16", "--blockSize", "24,24,24",
            "--minIntensity", "0", "--maxIntensity", "65535",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "XLA_FLAGS": ""})
        # own session so a timeout can kill the whole process group (the
        # workers are grandchildren of the bash wrapper)
        proc = subprocess.Popen(
            ["bash", os.path.join(repo, "scripts", "pod_launch.sh"),
             "-n", "2", "--",
             "affine-fusion", "-o", out, "--blockScale", "1,1,1"],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, start_new_session=True)
        try:
            out_txt, _ = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            import signal

            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            raise
        assert proc.returncode == 0, out_txt
        vol = ChunkStore.open(out).open_dataset("ch0tp0/s0").read_full()
        assert vol.std() > 0

"""The bench's timing methodology is itself load-bearing evidence (the
r4 verdict's only hard ask was trustworthy TPU measurements), so the
sync/drift primitives get their own tests: a silent regression here
would re-open the enqueue-ack hole where kernel metrics measured
dispatch latency instead of compute (see bench._tiny_fetch)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from bigstitcher_spark_tpu import profiling


class TestDeviceSync:
    def test_returns_input_and_blocks(self):
        x = jnp.arange(8.0) * 2.0
        assert profiling.device_sync(x) is x
        np.testing.assert_allclose(np.asarray(x)[0], 0.0)

    def test_pytree_and_scalars(self):
        tree = {"a": jnp.ones((2, 3)), "b": (jnp.float32(3.0), "not-an-array")}
        assert profiling.device_sync(tree) is tree

    def test_empty_leaf_skipped(self):
        profiling.device_sync(jnp.zeros((0, 3)))  # must not raise


class TestTinyFetch:
    def test_syncs_first_nonempty_leaf(self):
        out = (jnp.zeros((0,)), jnp.arange(4))
        got = bench._tiny_fetch(out)  # returns the synced non-empty leaf
        np.testing.assert_array_equal(np.asarray(got), [0, 1, 2, 3])

    def test_raises_when_nothing_to_sync(self):
        with pytest.raises(ValueError, match="no non-empty array leaf"):
            bench._tiny_fetch((jnp.zeros((0,)), "x"))


class TestKernelRate:
    def test_measures_real_work(self):
        x = jax.device_put(np.random.rand(256, 256).astype(np.float32))
        f = jax.jit(lambda x: x @ x)
        bench._tiny_fetch(f(x))  # warm
        per = bench._kernel_rate(lambda: f(x), reps=5)
        assert per > 0
        # sanity ceiling: 5 reps of a 256^2 matmul cannot take a minute
        assert per < 60

    def test_noise_fallback_is_conservative(self):
        # a dispatch whose cost is far below timer noise must not produce
        # an absurd rate: the fallback keeps the k=reps total's constant
        x = jnp.float32(1.0)
        f = jax.jit(lambda x: x + 1)
        bench._tiny_fetch(f(x))
        per = bench._kernel_rate(lambda: f(x), reps=5)
        assert per >= 1e-9


class TestSalvagePartial:
    """The parent's salvage of a killed child's checkpoint is what turns a
    tunnel hang into a truncated-but-valid artifact instead of an empty
    BENCH file — it must accept only checkpoints with a real primary."""

    def test_salvages_checkpoint_with_primary(self, tmp_path):
        p = tmp_path / "partial.json"
        p.write_text(json.dumps({"metric": "affine_fusion_voxels_per_sec",
                                 "value": 123.0, "extra_metrics": []}))
        line = bench._salvage_partial(str(p), "tpu attempt 1")
        got = json.loads(line)
        assert got["partial"] is True and got["value"] == 123.0

    def test_rejects_truncated_json(self, tmp_path):
        p = tmp_path / "partial.json"
        p.write_text('{"metric": "affine_f')
        assert bench._salvage_partial(str(p), "x") is None

    def test_rejects_checkpoint_without_value(self, tmp_path):
        p = tmp_path / "partial.json"
        p.write_text(json.dumps({"metric": "m", "value": 0}))
        assert bench._salvage_partial(str(p), "x") is None

    def test_rejects_missing_file(self, tmp_path):
        assert bench._salvage_partial(str(tmp_path / "nope.json"), "x") is None


class TestBaselineDrift:
    def _with_cache(self, monkeypatch, tmp_path, cache):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(cache))
        monkeypatch.setattr(bench, "BASELINE_FILE", str(p))

    def test_same_key_drift_flagged(self, monkeypatch, tmp_path):
        self._with_cache(monkeypatch, tmp_path, {
            "dog": {"key": "k1", "previous_key": "k1",
                    "vox_per_sec": 100.0, "previous_vox_per_sec": 500.0}})
        flags = bench._baseline_drift_flags()
        assert flags["dog"]["ratio"] == pytest.approx(0.2)

    def test_fixture_change_not_misreported_as_drift(self, monkeypatch,
                                                     tmp_path):
        self._with_cache(monkeypatch, tmp_path, {
            "dog": {"key": "k2", "previous_key": "k1",
                    "vox_per_sec": 100.0, "previous_vox_per_sec": 500.0}})
        assert bench._baseline_drift_flags() == {}

    def test_small_drift_not_flagged(self, monkeypatch, tmp_path):
        self._with_cache(monkeypatch, tmp_path, {
            "dog": {"key": "k1", "previous_key": "k1",
                    "vox_per_sec": 120.0, "previous_vox_per_sec": 100.0}})
        assert bench._baseline_drift_flags() == {}

    def test_corrupt_cache_tolerated(self, monkeypatch, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"dog": {"key": ')  # truncated by a mid-write kill
        monkeypatch.setattr(bench, "BASELINE_FILE", str(p))
        assert bench._baseline_cache_load() == {}
        assert bench._baseline_drift_flags() == {}

"""Pod-scale observability fabric: the cross-host telemetry relay
(observe/relay.py) and its aggregated live plane.

Acceptance contract (ISSUE 15): with >=2 processes relayed into one
collector, mid-run the rank-0 /metrics serves host/process_index-labeled
series from every rank; /healthz returns 503 naming the silent host when
one rank's heartbeat stops and recovers on resume; and a cluster
trace-dump writes ONE barrier-aligned Perfetto file loadable by
`bst trace-report`. Backpressure: a deliberately slow or absent
collector must never block (or meaningfully slow) a producing rank —
the bounded queue drops and counts (`bst_relay_dropped_total`), and the
client reconnects cleanly after a collector restart. Relay off must be
zero-overhead.

Collectors bind ephemeral 127.0.0.1 ports; the end-to-end test runs two
REAL worker subprocesses through the `init_distributed` bring-up path.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import click
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.observe import (
    events, history, httpexport, metrics, progress, relay, trace,
)
from bigstitcher_spark_tpu.serve import client as serve_client
from bigstitcher_spark_tpu.serve.daemon import Daemon

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _cli_ok(runner, args):
    r = runner.invoke(cli, args, catch_exceptions=False)
    assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"
    return r


def _wait_for(cond, timeout=20.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def collector():
    col = relay.RelayCollector("127.0.0.1", 0).start()
    yield col
    col.stop()


def _mk_client(port, host, pi, pc=2, interval_s=0.1, **kw):
    return relay.RelayClient(f"127.0.0.1:{port}", host=host,
                             process_index=pi, process_count=pc,
                             interval_s=interval_s, **kw).start()


class _FakeRank:
    """A raw-socket push client driven line by line — the protocol-level
    test surface (silence, bye, malformed lines)."""

    def __init__(self, port, host="fake", pi=1, pc=2, pid=None):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=5)
        self.identity = {"host": host, "process_index": pi,
                         "process_count": pc}
        self.send({"t": "hello", **self.identity,
                   "pid": pid if pid is not None else os.getpid()})

    def send(self, msg: dict) -> None:
        self.sock.sendall((json.dumps(msg) + "\n").encode())

    def snap(self, **payload) -> None:
        self.send({"t": "snap", "payload": payload})

    def close(self) -> None:
        self.sock.close()


# -- backpressure / loss accounting (satellite) ------------------------------


class TestBackpressure:
    def test_absent_collector_never_blocks_producer(self):
        """No collector listening: every offer returns immediately, the
        bounded queue fills, and further messages drop and COUNT."""
        port = _free_port()   # nothing listens here
        c = relay.RelayClient(f"127.0.0.1:{port}", host="h", process_index=1,
                              process_count=2, interval_s=0.05,
                              queue_max=16)
        c.start()

        def drops():
            return (metrics.counter("bst_relay_dropped_total",
                                    reason="queue").value
                    + metrics.counter("bst_relay_dropped_total",
                                      reason="conn").value)

        try:
            d0 = drops()
            t0 = time.perf_counter()
            for i in range(5000):
                c.offer({"t": "event", "rec": {"type": "block.fail",
                                               "i": i}})
            dt = time.perf_counter() - t0
            # 5000 enqueue attempts against a 16-slot queue + a
            # connection-refused sender: pure put_nowait on this side,
            # far under a second even on a loaded CI host
            assert dt < 2.0, f"offer() blocked: {dt:.2f}s for 5000 msgs"
            # every message accounted as a drop (queue-full at offer
            # time, or dequeued and dropped as unconnectable)
            _wait_for(lambda: drops() - d0 >= 5000,
                      what="loss accounting of all 5000 messages")
        finally:
            c.stop()

    def test_slow_collector_never_blocks_producer(self):
        """A collector that accepts but never reads: the TCP buffer
        fills, the relay thread wedges in send — and the producing side
        still never blocks (drops count instead)."""
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        held = []
        stop = threading.Event()

        def hold():
            srv.settimeout(0.5)
            while not stop.is_set():
                try:
                    conn, _ = srv.accept()
                    held.append(conn)   # accepted, never read
                except OSError:
                    continue

        th = threading.Thread(target=hold, daemon=True)
        th.start()
        big = "x" * 65536
        c = relay.RelayClient(f"127.0.0.1:{srv.getsockname()[1]}",
                              host="h", process_index=1, process_count=2,
                              interval_s=0.02, queue_max=8)
        c.start()
        try:
            _wait_for(lambda: c.connected.is_set(), what="client connect")
            q0 = metrics.counter("bst_relay_dropped_total",
                                 reason="queue").value
            worst = 0.0
            for i in range(2000):
                t0 = time.perf_counter()
                c.offer({"t": "event", "rec": {"type": "block.fail",
                                               "blob": big}})
                worst = max(worst, time.perf_counter() - t0)
            assert worst < 0.5, f"a single offer stalled {worst:.2f}s"
            # the relay thread is wedged in send -> the BOUNDED QUEUE
            # fills -> the queue-full drop path specifically engages
            _wait_for(lambda: metrics.counter(
                "bst_relay_dropped_total", reason="queue").value > q0,
                what="bounded-queue drop accounting")
        finally:
            c.stop(timeout=2)
            stop.set()
            srv.close()
            for conn in held:
                conn.close()

    def test_clean_reconnect_after_collector_restart(self):
        col = relay.RelayCollector("127.0.0.1", 0).start()
        port = col.port
        c = _mk_client(port, "h", 1)
        try:
            _wait_for(lambda: any(r["connected"]
                                  for r in col.cluster_status()["ranks"]),
                      what="first connect")
            r0 = metrics.counter("bst_relay_reconnects_total").value
            col.stop()
            _wait_for(lambda: not c.connected.is_set(),
                      what="client notices the dead collector")
            # restart on the SAME port (SO_REUSEADDR)
            col = relay.RelayCollector("127.0.0.1", port).start()
            row = _wait_for(
                lambda: next((r for r in col.cluster_status()["ranks"]
                              if r["connected"]), None),
                what="reconnect")
            assert row["host"] == "h" and row["process_index"] == 1
            assert metrics.counter(
                "bst_relay_reconnects_total").value > r0
            # snapshots flow again on the new connection
            _wait_for(lambda: (next(
                (r for r in col.cluster_status()["ranks"]), {})
                .get("process")) is not None, what="fresh snapshot")
        finally:
            c.stop()
            col.stop()


# -- the aggregated plane ----------------------------------------------------


class TestClusterPlane:
    def test_labeled_metrics_cluster_rows_and_health(self, collector):
        """Acceptance core, in-process: two relayed ranks surface as
        host/process_index-labeled series on /metrics, rows on /cluster,
        and a healthy pod verdict on /healthz."""
        exp = httpexport.start(0)
        c1 = _mk_client(collector.port, "hostA", 0)
        c2 = _mk_client(collector.port, "hostB", 1)
        metrics.counter("bst_io_read_bytes_total", op="relay-test",
                        path="synthetic").inc(4242)
        try:
            series = re.compile(
                r'bst_io_read_bytes_total\{host="host[AB]",'
                r'process_index="[01]",op="relay-test",'
                r'path="synthetic"\} \d+')

            def scraped():
                code, body = _get(exp.url + "/metrics")
                return (code == 200
                        and 'host="hostA",process_index="0"' in body
                        and 'host="hostB",process_index="1"' in body
                        and series.search(body) and body)

            # a real workload series rode the relay, labeled per rank
            body = _wait_for(scraped, what="labeled series on /metrics")
            code, body = _get(exp.url + "/cluster")
            assert code == 200
            doc = json.loads(body)
            hosts = {(r["host"], r["process_index"])
                     for r in doc["ranks"]}
            assert hosts == {("hostA", 0), ("hostB", 1)}
            assert doc["collector"]["connected"] == 2
            code, body = _get(exp.url + "/healthz")
            assert code == 200
            assert json.loads(body)["cluster"]["ranks"] == 2
        finally:
            c1.stop()
            c2.stop()
            httpexport.stop()

    def test_silent_rank_flips_healthz_naming_host_and_recovers(
            self, collector, monkeypatch):
        """Acceptance: a rank whose heartbeat stops past
        BST_STALL_TIMEOUT_S -> 503 naming the host; resuming heartbeats
        recovers 200. A cleanly-finished (bye) rank never flags."""
        monkeypatch.setenv("BST_STALL_TIMEOUT_S", "1")
        exp = httpexport.start(0)
        live = _FakeRank(collector.port, host="silent-host", pi=1)
        finished = _FakeRank(collector.port, host="done-host", pi=0)
        try:
            live.snap()
            finished.snap()
            finished.send({"t": "bye"})
            finished.close()
            assert _get(exp.url + "/healthz")[0] == 200
            # go silent: no snaps past the timeout
            code, body = _wait_for(
                lambda: (lambda cb: cb if cb[0] == 503 else None)(
                    _get(exp.url + "/healthz")),
                what="503 on silence")
            doc = json.loads(body)
            silent = doc["cluster"]["silent_ranks"]
            assert [s["host"] for s in silent] == ["silent-host"]
            assert silent[0]["process_index"] == 1
            # the finished rank never reads as silent
            assert all(s["host"] != "done-host" for s in silent)
            # resume -> recovery
            live.snap()
            code, _ = _wait_for(
                lambda: (lambda cb: cb if cb[0] == 200 else None)(
                    _get(exp.url + "/healthz")),
                what="recovery on resume")
            assert code == 200
            # watchdog off releases any stall verdict entirely
            monkeypatch.setenv("BST_STALL_TIMEOUT_S", "0")
            time.sleep(1.2)
            assert _get(exp.url + "/healthz")[0] == 200
        finally:
            live.close()
            httpexport.stop()

    def test_warn_events_ride_the_relay(self, collector):
        c = _mk_client(collector.port, "hostE", 1)
        try:
            _wait_for(lambda: any(r["connected"] for r in
                                  collector.cluster_status()["ranks"]),
                      what="connect")
            events.emit("retry.round", stage="relay-test", round=1)
            events.emit("stage.progress", stage="x", done=1, total=2)
            row = _wait_for(
                lambda: next((r for r in
                              collector.cluster_status()["ranks"]
                              if "retry.round" in (r.get("events") or [])),
                             None),
                what="forwarded warn event")
            # per-block progress spam deliberately does NOT ride the
            # event path (it ships with the periodic snapshot instead)
            assert "stage.progress" not in row["events"]
        finally:
            c.stop()

    def test_progress_rides_the_snapshot(self, collector):
        c = _mk_client(collector.port, "hostP", 1)
        try:
            hb = progress.Heartbeat("relay-stage", total=4, every_s=0.0)
            hb.tick(2)
            row = _wait_for(
                lambda: next(
                    (r for r in collector.cluster_status()["ranks"]
                     if (r.get("progress") or {}).get("stage")
                     == "relay-stage"), None),
                what="progress in snapshot")
            assert row["progress"]["done"] == 2
            assert row["progress"]["total"] == 4
            hb.finish()
            _wait_for(
                lambda: (next(
                    (r for r in collector.cluster_status()["ranks"]), {})
                    .get("progress") or {}).get("finished"),
                what="finished progress row")
        finally:
            c.stop()

    def test_garbage_lines_do_not_kill_the_handler(self, collector):
        """The relay port is unauthenticated TCP: valid-JSON-but-not-
        object lines (and non-JSON noise) must be ignored, not crash
        the connection handler."""
        snaps0 = metrics.counter("bst_relay_recv_total",
                                 type="snap").value
        fr = _FakeRank(collector.port, host="noisy", pi=1)
        try:
            fr.sock.sendall(b"null\n[1]\n\"x\"\nnot json at all\n")
            fr.snap(marker=1)
            # the snap AFTER the garbage still processes on the same
            # (uncrashed) handler, and the rank stays connected
            _wait_for(lambda: metrics.counter(
                "bst_relay_recv_total", type="snap").value > snaps0,
                what="snap processed after garbage")
            row = next(r for r in collector.cluster_status()["ranks"]
                       if r["host"] == "noisy")
            assert row["connected"]
        finally:
            fr.close()

    def test_idle_read_timeout_keeps_connection(self, collector):
        """The client socket's timeout exists for the WRITER (a wedged
        sendall must eventually error); the reader idling past it — the
        collector is silent except for trace pulls — must NOT tear a
        healthy connection down and reconnect-flap."""
        c = _mk_client(collector.port, "idle-host", 1)
        try:
            _wait_for(lambda: c.connected.is_set(), what="connect")
            with c._sock_lock:
                s0 = c._sock
                s0.settimeout(0.1)   # idle-read timeouts fire fast now
                # half-open (no FIN/RST) peers are caught by keepalive
                # probes, not by read-timeout teardown
                assert s0.getsockopt(socket.SOL_SOCKET,
                                     socket.SO_KEEPALIVE) == 1
            r0 = metrics.counter("bst_relay_reconnects_total").value
            time.sleep(0.8)          # several timeout windows, all idle
            assert c.connected.is_set()
            with c._sock_lock:
                assert c._sock is s0, \
                    "an idle read timeout dropped a healthy connection"
            assert metrics.counter(
                "bst_relay_reconnects_total").value == r0
            row = next(r for r in collector.cluster_status()["ranks"]
                       if r["host"] == "idle-host")
            assert row["connected"]
            # the COLLECTOR side of the same mostly-idle connection
            # needs the keepalive hardening too: its handler blocks in
            # a plain read, so a no-FIN dead worker would otherwise
            # stay a phantom connected rank (stalling cluster dumps)
            # until TCP retransmission gives up
            with collector._lock:
                conn = next(r["conn"] for r in collector._ranks.values()
                            if r["host"] == "idle-host")
            assert conn.getsockopt(socket.SOL_SOCKET,
                                   socket.SO_KEEPALIVE) == 1
        finally:
            c.stop()

    def test_metrics_families_contiguous_and_typed(self, collector):
        """The aggregated /metrics must stay VALID Prometheus
        exposition: each metric family exactly once, contiguous, under
        a single TYPE comment — duplicate or split families are
        rejected by promtool/OpenMetrics parsers."""
        exp = httpexport.start(0)
        c1 = _mk_client(collector.port, "hostA", 0)
        c2 = _mk_client(collector.port, "hostB", 1)
        metrics.counter("bst_io_read_bytes_total", op="fmt-test",
                        path="synthetic").inc(1)
        try:
            def scraped():
                code, body = _get(exp.url + "/metrics")
                return (code == 200
                        and 'host="hostA",process_index="0"' in body
                        and 'host="hostB",process_index="1"' in body
                        and body)

            body = _wait_for(scraped, what="aggregated scrape")
            types = {}
            for line in body.splitlines():
                if line.startswith("# TYPE "):
                    _, _, name, typ = line.split()
                    assert name not in types, f"duplicate TYPE: {name}"
                    types[name] = typ

            def family(name):
                for suf in ("_bucket", "_sum", "_count"):
                    if (name.endswith(suf) and types.get(name[:-len(suf)])
                            in ("histogram", "summary")):
                        return name[:-len(suf)]
                return name

            closed, current = set(), None
            for line in body.splitlines():
                if not line or line.startswith("#"):
                    continue
                name = family(line.split("{", 1)[0].split(" ", 1)[0])
                if name != current:
                    assert name not in closed, \
                        f"family {name} split into separate groups"
                    if current is not None:
                        closed.add(current)
                    current = name
                assert name in types, f"series {name} lacks a TYPE line"
        finally:
            c1.stop()
            c2.stop()
            httpexport.stop()

    def test_colliding_identity_ranks_dedupe_in_metrics(self, collector):
        """Two ranks claiming the same (host, process_index) but
        different process_count occupy distinct collector rows; the
        merged /metrics must carry ONE labeled copy (the freshest), not
        duplicate identical-label samples."""
        a = _FakeRank(collector.port, host="dup-host", pi=0, pc=1)
        b = _FakeRank(collector.port, host="dup-host", pi=0, pc=2)
        snaps0 = metrics.counter("bst_relay_recv_total",
                                 type="snap").value
        try:
            a.snap(prom="# TYPE x_total counter\nx_total 1\n")
            _wait_for(lambda: metrics.counter(
                "bst_relay_recv_total", type="snap").value > snaps0,
                what="first colliding snap")
            time.sleep(0.02)   # strictly newer last_seen for b
            b.snap(prom="# TYPE x_total counter\nx_total 2\n")
            _wait_for(lambda: metrics.counter(
                "bst_relay_recv_total", type="snap").value > snaps0 + 1,
                what="second colliding snap")
            body = collector.metrics_render(
                metrics.get_registry().render_prometheus())
            lines = [l for l in body.splitlines()
                     if l.startswith('x_total{host="dup-host"')]
            assert lines == \
                ['x_total{host="dup-host",process_index="0"} 2']
            # an EVENT from the stale rank touches last_seen but must
            # not let its older snapshot win back the identity
            ev0 = metrics.counter("bst_relay_recv_total",
                                  type="event").value
            a.send({"t": "event", "rec": {"type": "retry.round"}})
            _wait_for(lambda: metrics.counter(
                "bst_relay_recv_total", type="event").value > ev0,
                what="stale rank's event")
            body = collector.metrics_render(
                metrics.get_registry().render_prometheus())
            lines = [l for l in body.splitlines()
                     if l.startswith('x_total{host="dup-host"')]
            assert lines == \
                ['x_total{host="dup-host",process_index="0"} 2']
        finally:
            a.close()
            b.close()

    def test_self_hosting_rank_ring_not_duplicated(self, collector,
                                                   tmp_path, monkeypatch):
        """A hosting rank that also pushes to itself over loopback
        (ensure_started) must contribute its flight-recorder ring ONCE
        to a cluster dump — the direct local export, not a second
        pulled copy of the same ring."""
        monkeypatch.setenv("BST_PROCESS_ID", "0")
        monkeypatch.setenv("BST_NUM_PROCESSES", "2")
        me = _mk_client(collector.port, socket.gethostname(), 0)
        other = _mk_client(collector.port, "other-host", 1)
        try:
            _wait_for(lambda: collector.cluster_status()["collector"]
                      ["connected"] == 2, what="both connected")
            with trace.span("barrier", stage="self-dedup"):
                pass
            out = str(tmp_path / "self-dedup-trace.json")
            res = collector.cluster_trace_dump(out, timeout_s=10)
            # only the non-self rank was pulled; the local ring rode in
            # exactly once via the direct export
            assert res["local_ring"] and res["asked"] == 1
            assert res["ranks"] == 1 and res["missing"] == 0
            assert res["traces"] == 2, \
                "self rank's ring written twice into the merge"
        finally:
            me.stop()
            other.stop()

    def test_same_host_rank0_worker_still_pulled(self, collector,
                                                 tmp_path):
        """The self-ring dedup must identify the self-CONNECTION (pid),
        not the (host, process_index) pair: a separately-launched
        same-host worker claiming process_index 0 (identity-only rank
        against a daemon-hosted collector) is NOT this process's ring
        and must still be asked for its trace."""
        own = not trace.enabled()
        if own:
            trace.configure()
        fr = _FakeRank(collector.port, host=socket.gethostname(), pi=0,
                       pid=os.getpid() + 1)
        try:
            _wait_for(lambda: any(r["connected"] for r in
                                  collector.cluster_status()["ranks"]),
                      what="worker connect")
            out = str(tmp_path / "same-host-trace.json")
            res = collector.cluster_trace_dump(out, timeout_s=1.0)
            # the worker was ASKED (a fake rank never answers, so it
            # reports missing) instead of silently deduped away
            assert res["asked"] == 1 and res["missing"] == 1
            assert res["local_ring"] and res["traces"] == 1
        finally:
            fr.close()
            if own:
                trace.reset()

    def test_cluster_trace_dump_merges_and_loads(self, collector,
                                                 tmp_path):
        c1 = _mk_client(collector.port, "hostA", 0)
        c2 = _mk_client(collector.port, "hostB", 1)
        try:
            _wait_for(lambda: collector.cluster_status()["collector"]
                      ["connected"] == 2, what="both connected")
            with trace.span("barrier", stage="relay-test"):
                pass
            out = str(tmp_path / "pod-trace.json")
            res = collector.cluster_trace_dump(out, timeout_s=10)
            assert res["path"] == out and os.path.exists(out)
            assert res["ranks"] == 2 and res["missing"] == 0
            from bigstitcher_spark_tpu.analysis.tracereport import (
                build_report, load_events,
            )
            evs, meta = load_events(out)
            build_report(evs, meta)   # must not raise
            doc = json.load(open(out))
            assert doc["bst"]["schema"] == "bst-merged-trace/1"
            # the recorder kept recording through the pull
            assert trace.stats()["enabled"]
        finally:
            c1.stop()
            c2.stop()


# -- daemon integration + CLI -------------------------------------------------


class TestDaemonCluster:
    @pytest.fixture()
    def daemon(self, tmp_path):
        d = Daemon(str(tmp_path / "bst.sock"), slots=1,
                   jobs_root=str(tmp_path / "jobs"), metrics_port=0,
                   relay="127.0.0.1:0").start()
        try:
            yield d
        finally:
            if not d.wait(timeout=0):
                d.shutdown(drain=False, wait=True)

    def test_daemon_hosts_collector_and_cli_cluster_surfaces(
            self, daemon, tmp_path):
        col = relay.collector()
        assert col is not None, "daemon did not host the collector"
        c = _mk_client(col.port, "worker-host", 1)
        runner = CliRunner()
        try:
            _wait_for(lambda: col.cluster_status()["collector"]
                      ["connected"] == 1, what="worker connect")
            # ping/status carry the collector summary
            pong = serve_client.ping(daemon.socket_path)
            assert pong["relay"] == f"127.0.0.1:{col.port}"
            st = serve_client.status(daemon.socket_path)
            assert st["relay"]["connected"] == 1
            # bst top --cluster over the socket AND over HTTP
            out = _cli_ok(runner, ["top", "--cluster", "--once",
                                   "--socket", daemon.socket_path]).output
            assert "worker-host" in out and "live" in out
            out = _cli_ok(runner, [
                "top", "--cluster", "--once",
                "--url", f"http://127.0.0.1:{daemon.metrics_port}"]).output
            assert "worker-host" in out
            # bst trace-dump --cluster -> merged file -> trace-report
            dump = str(tmp_path / "cluster-trace.json")
            out = _cli_ok(runner, ["trace-dump", "--cluster",
                                   "--socket", daemon.socket_path,
                                   "--out", dump]).output
            assert dump in out and "rank ring(s)" in out
            _cli_ok(runner, ["trace-report", dump])
            doc = json.load(open(dump))
            assert doc["bst"]["schema"] == "bst-merged-trace/1"
        finally:
            c.stop()

    def test_drain_releases_collector_address(self, tmp_path):
        d = Daemon(str(tmp_path / "a.sock"), slots=1,
                   jobs_root=str(tmp_path / "ja"), relay="127.0.0.1:0")
        d.start()
        port = relay.collector().port
        d.shutdown(drain=True, wait=True)
        assert relay.collector() is None
        # the address is free again for the next daemon
        d2 = Daemon(str(tmp_path / "b.sock"), slots=1,
                    jobs_root=str(tmp_path / "jb"),
                    relay=f"127.0.0.1:{port}")
        d2.start()
        try:
            assert relay.collector().port == port
        finally:
            d2.shutdown(drain=True, wait=True)

    def test_cluster_ops_without_collector_are_clean_errors(self,
                                                            tmp_path):
        d = Daemon(str(tmp_path / "bst.sock"), slots=1,
                   jobs_root=str(tmp_path / "jobs")).start()
        runner = CliRunner()
        try:
            r = runner.invoke(cli, ["top", "--cluster", "--once",
                                    "--socket", d.socket_path])
            assert r.exit_code != 0 and "no relay collector" in r.output
            r = runner.invoke(cli, ["trace-dump", "--cluster",
                                    "--socket", d.socket_path])
            assert r.exit_code != 0 and "no relay collector" in r.output
        finally:
            d.shutdown(drain=True, wait=True)


# -- end to end: real worker processes (acceptance) ---------------------------


_WORKER = """
import os, sys, time
from bigstitcher_spark_tpu.parallel.distributed import init_distributed

init_distributed()   # relay bring-up rides beside initialize
from bigstitcher_spark_tpu.observe import metrics, progress, relay, trace

assert relay.client() is not None, "worker did not become a push client"
rank = int(os.environ["BST_PROCESS_ID"])
metrics.counter("bst_io_read_bytes_total", op="e2e",
                path="native").inc(1000 + rank)
hb = progress.Heartbeat("e2e-stage", total=1000, every_s=0.0)
print("WORKER-READY", flush=True)
while True:
    with trace.span("barrier", stage="e2e"):
        hb.tick()
    time.sleep(0.05)
"""


class TestEndToEnd:
    def _spawn_worker(self, tmp_path, rank: int, port: int):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "BST_TELEMETRY_RELAY": f"127.0.0.1:{port}",
            # identity-only rank id: no BST_COORDINATOR/NUM_PROCESSES,
            # so these are independent local processes, not a jax world
            "BST_PROCESS_ID": str(rank),
            "BST_RELAY_INTERVAL_S": "0.2",
        })
        env.pop("BST_NUM_PROCESSES", None)
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def test_two_process_pod_plane(self, tmp_path, monkeypatch):
        """Acceptance, end to end with REAL processes: labeled /metrics
        from every rank mid-run, 503 naming the killed rank's host, 200
        again after it resumes, one merged cluster trace."""
        monkeypatch.setenv("BST_STALL_TIMEOUT_S", "2")
        col = relay.RelayCollector("127.0.0.1", 0).start()
        exp = httpexport.start(0)
        hostname = socket.gethostname()
        workers = {}
        try:
            for rank in (0, 1):
                workers[rank] = self._spawn_worker(tmp_path, rank,
                                                   col.port)

            def both_reporting():
                """Each rank's own workload counter, host/rank-labeled —
                NOT just any labeled line (the collector's self-row
                carries process_index=0 labels before worker 0's first
                counter-bearing snapshot lands)."""
                code, body = _get(exp.url + "/metrics")
                if code != 200:
                    return None
                for rank in (0, 1):
                    if not re.search(
                            rf'bst_io_read_bytes_total\{{'
                            rf'host="{hostname}",process_index="{rank}",'
                            rf'op="e2e",path="native"\}} {1000 + rank}',
                            body):
                        return None
                return body

            body = _wait_for(both_reporting, timeout=90,
                             what="labeled counters from both ranks")
            # rank 0 of a multi-process world tried to HOST the already-
            # owned address and fell back to pushing — both must be rows
            doc = json.loads(_get(exp.url + "/cluster")[1])
            assert {r["process_index"] for r in doc["ranks"]
                    if r["connected"]} == {0, 1}
            assert _get(exp.url + "/healthz")[0] == 200

            # kill rank 1 (no bye): its heartbeat stops -> 503 names it
            workers[1].kill()
            workers[1].wait(timeout=30)
            code, body = _wait_for(
                lambda: (lambda cb: cb if cb[0] == 503 else None)(
                    _get(exp.url + "/healthz")),
                timeout=30, what="503 after kill")
            silent = json.loads(body)["cluster"]["silent_ranks"]
            assert [(s["host"], s["process_index"]) for s in silent] == \
                [(hostname, 1)]

            # resume the rank -> pod health recovers
            workers[1] = self._spawn_worker(tmp_path, 1, col.port)
            _wait_for(
                lambda: _get(exp.url + "/healthz")[0] == 200,
                timeout=90, what="recovery after restart")

            # cluster flight-recorder pull: every rank's live ring folds
            # into ONE Perfetto file, mid-run, loadable by trace-report
            out = str(tmp_path / "pod-trace.json")
            res = col.cluster_trace_dump(out, timeout_s=30)
            assert res["ranks"] == 2 and res["missing"] == 0
            from bigstitcher_spark_tpu.analysis.tracereport import (
                build_report, load_events,
            )
            evs, meta = load_events(out)
            report = build_report(evs, meta)
            assert report   # renders
            doc = json.load(open(out))
            assert doc["bst"]["process_count"] >= 2
            names = {e.get("name") for e in doc["traceEvents"]}
            assert "barrier" in names   # the workers' recorded spans
        finally:
            for w in workers.values():
                if w.poll() is None:
                    w.kill()
                w.wait(timeout=30)
            httpexport.stop()
            col.stop()


# -- relay OFF: zero overhead, byte-identical --------------------------------


class TestRelayOff:
    def test_ensure_started_is_noop_without_knob(self, monkeypatch):
        monkeypatch.delenv("BST_TELEMETRY_RELAY", raising=False)
        assert relay.ensure_started() is None
        assert relay.client() is None and relay.collector() is None
        assert not events._taps, "no tap may be installed with relay off"

    def test_progress_latest_stays_off(self):
        hb = progress.Heartbeat("off-stage", total=2, every_s=0.0)
        hb.tick(2)
        hb.finish()
        assert progress.latest() is None

    def test_metrics_render_unchanged_without_collector(self):
        """No relay -> /metrics is exactly the local registry render
        (no cluster section, no host/process_index labels injected)."""
        exp = httpexport.start(0)
        try:
            code, body = _get(exp.url + "/metrics")
            assert code == 200
            assert "relay-aggregated" not in body
            assert 'host="' not in body
            assert 'process_index="' not in body
        finally:
            httpexport.stop()

    def test_broken_metrics_render_falls_back_to_local(self):
        """A metrics_render provider that raises OR returns a non-str
        must degrade the scrape to the host-local render, never cost
        /metrics a 500."""
        exp = httpexport.start(0)
        try:
            for bad in (lambda text: None,
                        lambda text: (_ for _ in ()).throw(RuntimeError)):
                httpexport.set_cluster_providers(metrics_render=bad)
                code, body = _get(exp.url + "/metrics")
                assert code == 200
                assert "bst_http_requests_total" in body
        finally:
            httpexport.clear_cluster_providers()
            httpexport.stop()

    def test_rank0_hosts_and_registers_itself(self, monkeypatch):
        """Knob-driven pod mode: the hosting rank 0 also pushes into
        its own collector over loopback, so /cluster and the pod
        verdict cover rank 0, not only ranks 1..N-1."""
        port = _free_port()
        monkeypatch.setenv("BST_TELEMETRY_RELAY", f"127.0.0.1:{port}")
        monkeypatch.setenv("BST_PROCESS_ID", "0")
        monkeypatch.setenv("BST_NUM_PROCESSES", "4")
        got = relay.ensure_started()
        try:
            assert isinstance(got, relay.RelayCollector)
            assert relay.client() is not None
            row = _wait_for(lambda: next(
                (r for r in got.cluster_status()["ranks"]
                 if r["connected"] and r["process_index"] == 0), None),
                what="rank-0 self row")
            assert row["host"] == socket.gethostname()
        finally:
            relay.stop()

    def test_rank0_host_fallback_when_address_owned(self, monkeypatch,
                                                    collector):
        """Rank 0 of a multi-process world tries to HOST the relay
        address; when a daemon on this host already owns it, the bind
        fails and the rank falls back to pushing."""
        monkeypatch.setenv("BST_TELEMETRY_RELAY",
                           f"127.0.0.1:{collector.port}")
        monkeypatch.setenv("BST_PROCESS_ID", "0")
        monkeypatch.setenv("BST_NUM_PROCESSES", "2")
        got = relay.ensure_started()
        try:
            assert isinstance(got, relay.RelayClient)
            assert relay.collector() is None   # module collector unset:
            #      the fixture's instance owns the port, not the global
            _wait_for(lambda: any(
                r["connected"] and r["process_index"] == 0
                for r in collector.cluster_status()["ranks"]),
                what="fallback client connect")
        finally:
            relay.stop()


# -- satellites ---------------------------------------------------------------


class TestMetricsHostKnob:
    def test_default_binds_loopback(self):
        exp = httpexport.start(0)
        try:
            assert exp._server.server_address[0] == "127.0.0.1"
        finally:
            httpexport.stop()

    def test_knob_widens_the_bind(self, monkeypatch):
        monkeypatch.setenv("BST_METRICS_HOST", "0.0.0.0")
        exp = httpexport.start(0)
        try:
            assert exp._server.server_address[0] == "0.0.0.0"
            # the convenience url still answers locally
            assert _get(exp.url + "/healthz")[0] == 200
        finally:
            httpexport.stop()


def _fake_manifest(directory, pi, pc, *, tool="affine-fusion", seconds,
                   span_s, read_bytes):
    os.makedirs(directory, exist_ok=True)
    doc = {
        "schema": "bst-run-manifest/1", "tool": tool, "argv": [],
        "params": {}, "world": {"process_index": pi, "process_count": pc},
        "device": {}, "started_at": "2026-08-04T00:00:00",
        "seconds": seconds, "status": "ok",
        "spans": {"fusion.kernel": {"count": 3, "total_s": span_s,
                                    "max_s": span_s, "min_s": 0.01}},
        "metrics": {"bst_io_read_bytes_total"
                    '{op="x",path="y"}': read_bytes},
        "stages": [{"stage": "fusion", "done": 8, "total": 8}],
        "events_file": None,
    }
    path = os.path.join(directory, f"manifest-{pi:05d}-of-{pc:05d}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return path


class TestPodHistory:
    def test_telemetry_merge_appends_pod_record(self, tmp_path,
                                                monkeypatch):
        """Satellite: with BST_HISTORY_DIR set, `bst telemetry-merge`
        appends the merged POD manifest to the history store, and two
        pod records diff via `bst perf-diff`."""
        hist = str(tmp_path / "hist")
        monkeypatch.setenv("BST_HISTORY_DIR", hist)
        runner = CliRunner()
        for tag, span_s, nbytes in (("a", 0.05, 10 << 20),
                                    ("b", 0.50, 80 << 20)):
            d = str(tmp_path / f"tel-{tag}")
            for pi in (0, 1):
                _fake_manifest(d, pi, 2, seconds=1.0 + span_s,
                               span_s=span_s, read_bytes=nbytes)
            out = _cli_ok(runner, ["telemetry-merge", d]).output
            assert "recorded in history as" in out
        entries = history.list_records(hist)
        assert len(entries) == 2
        assert all(e["tool"] == "affine-fusion" and e["status"] == "ok"
                   for e in entries)
        assert all(e["id"].startswith("pod-") for e in entries)
        rec = history.load_record(entries[0]["id"], hist)
        # the merged record carries the SUMMED span/metric surface
        assert rec["spans"]["fusion.kernel"]["count"] == 6
        assert rec["world"]["process_count"] == 2
        out = _cli_ok(runner, ["perf-diff", "--last", "2",
                               "--threshold", "50"]).output
        assert "REGRESSION" in out and "fusion.kernel" in out

    def test_manifestless_merge_records_unknown_not_ok(self, tmp_path):
        """A pod run that died on every rank before finalize (event
        logs only, zero manifests) must not enter the history as a
        healthy 'ok' baseline."""
        hist = str(tmp_path / "h")
        rid = history.record_merged_report(
            {"processes": [], "process_count": 2, "wall_clock_s": 0.0,
             "spans": {}, "metrics": {}, "stages": []},
            directory=hist)
        rec = history.load_record(rid, hist)
        assert rec["status"] == "unknown"

    def test_merge_without_history_dir_is_unchanged(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.delenv("BST_HISTORY_DIR", raising=False)
        d = str(tmp_path / "tel")
        _fake_manifest(d, 0, 1, seconds=1.0, span_s=0.1,
                       read_bytes=1 << 20)
        out = _cli_ok(CliRunner(), ["telemetry-merge", d]).output
        assert "recorded in history" not in out

"""The live observability plane: embedded HTTP exporter (/metrics,
/healthz, /status, /jobs), the serve daemon's stall watchdog, on-demand
flight-recorder dumps, `bst top`, and the manifest history store +
`bst perf-diff` regression diff.

Acceptance contract (ISSUE 13): with a daemon running a fusion job,
/healthz answers 200 and live /metrics shows a nonzero bst_serve_* gauge
mid-job; an artificially wedged job flips /healthz non-200 and `bst
jobs` shows `stalled` within BST_STALL_TIMEOUT_S; `bst trace-dump`
mid-job produces a Perfetto JSON the trace-report path loads; and two
recorded runs diff via `bst perf-diff` with a regression threshold
flagging an injected slowdown.

Daemons run IN-PROCESS on tmp-path sockets with OS-assigned exporter
ports (metrics_port=0), so the suite never collides on a fixed port.
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import click
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import observe, profiling
from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.observe import events, history, httpexport, metrics
from bigstitcher_spark_tpu.serve import client
from bigstitcher_spark_tpu.serve.daemon import Daemon


def _get(url: str, timeout: float = 10.0):
    """(status_code, body) — non-200 responses return, never raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _cli_ok(runner, args):
    r = runner.invoke(cli, args, catch_exceptions=False)
    assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"
    return r


@pytest.fixture()
def daemon(tmp_path):
    """In-process daemon with an ephemeral live-exporter port."""
    d = Daemon(str(tmp_path / "bst.sock"), slots=2,
               jobs_root=str(tmp_path / "jobs"), metrics_port=0).start()
    try:
        yield d
    finally:
        if not d.wait(timeout=0):
            d.shutdown(drain=False, wait=True)


@pytest.fixture()
def wedge_tool():
    """A temporary CLI tool that runs without ever emitting progress —
    the artificial wedge the stall watchdog must flag. It polls the
    ambient cancel token, so `bst cancel` (and daemon teardown) always
    unwinds it."""
    @click.command("wedge")
    @click.option("--seconds", type=float, default=60.0)
    def wedge_cmd(seconds):
        from bigstitcher_spark_tpu.utils import cancel

        t0 = time.time()
        while time.time() - t0 < seconds:
            cancel.check()
            time.sleep(0.02)

    cli.add_command(wedge_cmd, "wedge")
    yield "wedge"
    cli.commands.pop("wedge", None)


# -- the exporter alone ------------------------------------------------------


class TestHttpExporter:
    def test_endpoints_and_process_gauges(self):
        exp = httpexport.start(0)
        try:
            base = exp.url
            code, body = _get(base + "/metrics")
            assert code == 200
            assert "bst_process_uptime_seconds" in body
            assert re.search(r"^bst_process_threads \d+$", body, re.M)
            code, body = _get(base + "/healthz")
            assert code == 200 and json.loads(body)["ok"] is True
            code, body = _get(base + "/status")
            assert code == 200
            st = json.loads(body)
            assert st["process"]["pid"] == os.getpid()
            assert st["process"]["uptime_s"] >= 0
            code, body = _get(base + "/jobs")
            assert code == 200 and json.loads(body)["jobs"] == []
            code, _ = _get(base + "/nope")
            assert code == 404
        finally:
            httpexport.stop()

    def test_knob_zero_is_off(self, monkeypatch):
        monkeypatch.setenv("BST_METRICS_PORT", "0")
        assert httpexport.ensure_started() is None
        monkeypatch.delenv("BST_METRICS_PORT")
        assert httpexport.ensure_started() is None

    def test_unhealthy_provider_flips_healthz(self):
        exp = httpexport.start(0)
        try:
            httpexport.set_providers(
                health=lambda: (False, {"ok": False, "why": "test"}))
            code, body = _get(exp.url + "/healthz")
            assert code == 503 and json.loads(body)["ok"] is False
        finally:
            httpexport.clear_providers()
            httpexport.stop()

    def test_live_scrape_races_running_jobs(self):
        """Satellite: a /metrics render racing concurrent metric updates
        (and concurrent NEW-series creation, the registry-mutation case)
        must never throw or emit a torn series."""
        reg = metrics.MetricsRegistry()
        stop = threading.Event()
        errors: list[BaseException] = []

        def writer(i):
            try:
                c = reg.counter("hammer_ops_total", job=f"j{i}")
                h = reg.histogram("hammer_wait_seconds", job=f"j{i}")
                g = reg.gauge("hammer_depth")
                n = 0
                while not stop.is_set():
                    c.inc(3)
                    h.observe(0.01 * (n % 7))
                    g.set(n % 5)
                    n += 1
                    if n % 50 == 0:   # mint fresh series mid-render
                        reg.counter("hammer_ops_total", job=f"j{i}-{n}")
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

        line_re = re.compile(
            r'[a-zA-Z_:][\w:]*(\{[^}]*\})? -?[\d.e+-]+(e[+-]?\d+)?$')

        def scraper():
            try:
                for _ in range(150):
                    text = reg.render_prometheus()
                    for line in text.strip().splitlines():
                        assert line.startswith("#") or line_re.fullmatch(
                            line), f"torn line: {line!r}"
                    snap = reg.snapshot_delta(reg.snapshot())
                    for v in snap.values():
                        assert isinstance(v, (int, float, dict))
            except BaseException as e:   # noqa: BLE001
                errors.append(e)

        writers = [threading.Thread(target=writer, args=(i,))
                   for i in range(2)]
        scrapers = [threading.Thread(target=scraper) for _ in range(3)]
        for t in writers + scrapers:
            t.start()
        for t in scrapers:
            t.join()
        stop.set()
        for t in writers:
            t.join()
        assert not errors, errors
        # histograms stayed internally consistent: +Inf bucket == _count
        text = reg.render_prometheus()
        counts = dict(re.findall(
            r'hammer_wait_seconds_count\{job="(j\d+)"\} (\d+)', text))
        infs = dict(re.findall(
            r'hammer_wait_seconds_bucket\{job="(j\d+)",le="\+Inf"\} (\d+)',
            text))
        for job, c in counts.items():
            assert infs[job] == c


# -- daemon: live scrape, watchdog, trace dump, top --------------------------


def _mk_project(tmp_path, name="proj", **kw):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    spec = dict(n_tiles=(2, 1, 1), tile_size=(64, 64, 32), overlap=16,
                jitter=1.0, n_beads_per_tile=20, seed=7)
    spec.update(kw)
    return make_synthetic_project(str(tmp_path / name), **spec).xml_path


class TestDaemonLive:
    def test_live_metrics_and_healthz_mid_fusion(self, tmp_path, daemon):
        """Acceptance: while the daemon runs a fusion job, a live
        /metrics scrape shows a nonzero bst_serve_* gauge and /healthz
        answers 200."""
        sock = daemon.socket_path
        base = f"http://127.0.0.1:{daemon.metrics_port}"
        xml = _mk_project(tmp_path)
        proj = os.path.dirname(xml)
        res = client.submit(sock, "create-fusion-container",
                            ["-x", xml, "-o", f"{proj}/fused.zarr",
                             "-s", "ZARR", "-d", "UINT16",
                             "--blockSize", "16,16,16",
                             "--minIntensity", "0",
                             "--maxIntensity", "65535"])
        assert res["exit_code"] == 0
        result = {}

        def go():
            result["r"] = client.submit(
                sock, "affine-fusion",
                ["-o", f"{proj}/fused.zarr", "--blockScale", "1,1,1"])

        th = threading.Thread(target=go)
        th.start()
        seen_active = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and th.is_alive():
            code, body = _get(base + "/metrics")
            assert code == 200
            m = re.search(r"^bst_serve_active_jobs (\d+)$", body, re.M)
            if m and int(m.group(1)) >= 1:
                seen_active = int(m.group(1))
                hcode, hbody = _get(base + "/healthz")
                assert hcode == 200, hbody
                assert json.loads(hbody)["active"] >= 1
                break
            time.sleep(0.02)
        th.join(timeout=300)
        assert result["r"]["exit_code"] == 0, result["r"]
        assert seen_active and seen_active >= 1, \
            "never scraped a live nonzero bst_serve_active_jobs"

    def test_wedged_job_stalls_healthz_and_recovers(self, tmp_path,
                                                    monkeypatch,
                                                    wedge_tool):
        """Acceptance: a job whose progress never advances flips
        /healthz non-200 and shows `stalled` in `bst jobs` within
        BST_STALL_TIMEOUT_S; trace-dump works mid-job; cancelling the
        job recovers health."""
        monkeypatch.setenv("BST_STALL_TIMEOUT_S", "1")
        d = Daemon(str(tmp_path / "bst.sock"), slots=1,
                   jobs_root=str(tmp_path / "jobs"), metrics_port=0)
        d.start()
        try:
            sock = d.socket_path
            base = f"http://127.0.0.1:{d.metrics_port}"
            jid = client.submit(sock, wedge_tool, ["--seconds", "120"],
                                follow=False)["job"]
            stalled_row = None
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                rows = [j for j in client.list_jobs(sock)["jobs"]
                        if j["id"] == jid]
                if rows and rows[0].get("stalled"):
                    stalled_row = rows[0]
                    break
                time.sleep(0.1)
            assert stalled_row, "watchdog never flagged the wedged job"
            assert stalled_row["stalled_for_s"] >= 1
            code, body = _get(base + "/healthz")
            assert code == 503
            assert jid in json.loads(body)["stalled_jobs"]
            code, body = _get(base + "/metrics")
            assert re.search(r"^bst_serve_jobs_stalled 1$", body, re.M)
            # the warn event landed on the JOB's scoped sink
            logs = [os.path.join(d.jobs_root, jid, f)
                    for f in os.listdir(os.path.join(d.jobs_root, jid))
                    if f.startswith("events-job-")]
            assert logs
            stall_events = [rec for rec in events.iter_events(logs[0])
                            if rec.get("type") == "job.stall"]
            assert stall_events and "BST_STALL_TIMEOUT_S" in \
                stall_events[0]["message"]
            # the human surfaces agree
            runner = CliRunner()
            out = _cli_ok(runner, ["jobs", "--socket", sock]).output
            assert "STALLED" in out
            out = _cli_ok(runner, ["top", "--once", "--socket",
                                   sock]).output
            assert "STALLED" in out and "stalled 1" in out

            # acceptance: on-demand flight-recorder dump MID-JOB, loadable
            # by the existing trace-report path, recorder left running
            dump_path = str(tmp_path / "live-trace.json")
            out = _cli_ok(runner, ["trace-dump", "--socket", sock,
                                   "--out", dump_path]).output
            assert dump_path in out
            from bigstitcher_spark_tpu.analysis.tracereport import (
                build_report, load_events,
            )
            evs, meta = load_events(dump_path)
            build_report(evs, meta)   # must not raise
            doc = json.load(open(dump_path))
            assert doc["bst"]["schema"] == "bst-trace/1"
            names = {e.get("name") for e in doc["traceEvents"]}
            assert "serve.submit" in names
            from bigstitcher_spark_tpu.observe import trace as _trace
            assert _trace.stats()["enabled"], \
                "trace-dump must not stop the recorder"

            # disabling the watchdog live (knob read per sweep) must
            # RELEASE the stall state, not freeze a stale 503
            monkeypatch.setenv("BST_STALL_TIMEOUT_S", "0")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if _get(base + "/healthz")[0] == 200:
                    break
                time.sleep(0.1)
            assert _get(base + "/healthz")[0] == 200, \
                "disabled watchdog froze the stalled state"
            monkeypatch.setenv("BST_STALL_TIMEOUT_S", "1")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if _get(base + "/healthz")[0] == 503:
                    break
                time.sleep(0.1)
            assert _get(base + "/healthz")[0] == 503

            # cancel -> progress bookkeeping clears -> health recovers
            client.cancel(sock, jid)
            deadline = time.monotonic() + 20
            recovered = False
            while time.monotonic() < deadline:
                code, _ = _get(base + "/healthz")
                if code == 200:
                    recovered = True
                    break
                time.sleep(0.1)
            assert recovered, "healthz never recovered after cancel"
            # the gauge follows on the watchdog's next sweep
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                code, body = _get(base + "/metrics")
                if re.search(r"^bst_serve_jobs_stalled 0$", body, re.M):
                    break
                time.sleep(0.1)
            assert re.search(r"^bst_serve_jobs_stalled 0$", body, re.M)
        finally:
            if not d.wait(timeout=0):
                d.shutdown(drain=False, wait=True)

    def test_status_op_ping_and_jobs_agree(self, daemon):
        """Satellite: uptime/process gauges come from ONE place —
        /status (the status op) and `bst jobs --json` report the same
        shape, and ping carries the exporter port."""
        st = client.status(daemon.socket_path)
        via_jobs = client.list_jobs(daemon.socket_path)["daemon"]
        assert set(st) == set(via_jobs)
        for d in (st, via_jobs):
            assert d["process"]["pid"] == os.getpid()
            assert d["uptime_s"] >= 0
            assert "inflight" in d and "dag" in d and "trace" in d
        pong = client.ping(daemon.socket_path)
        assert pong["metrics_port"] == daemon.metrics_port
        assert pong["uptime_s"] >= 0
        # the /status HTTP endpoint serves the same document
        code, body = _get(f"http://127.0.0.1:{daemon.metrics_port}/status")
        assert code == 200 and set(json.loads(body)) == set(st)

    def test_top_over_http_url(self, daemon):
        runner = CliRunner()
        out = _cli_ok(runner, [
            "top", "--once",
            "--url", f"http://127.0.0.1:{daemon.metrics_port}"]).output
        assert "bst serve pid" in out and "slots 2" in out

    def test_serve_surface_tools_not_submittable(self, daemon):
        for tool in ("top", "trace-dump"):
            with pytest.raises(RuntimeError, match="unservable"):
                client.submit(daemon.socket_path, tool, [])


# -- history store + perf-diff ----------------------------------------------


@pytest.fixture()
def _clean_observe():
    yield
    if observe.active():
        observe.finalize(tool="test-cleanup")
    events.close()


def _record_run(tmp_path, tag, sleep_s, extra_bytes, hist):
    """One telemetry-dir'd run with an injected span duration + byte
    traffic; records into ``hist`` via the finalize hook."""
    profiling.get().reset()
    observe.configure(str(tmp_path / f"tel-{tag}"))
    with profiling.span("fusion.kernel"):
        time.sleep(sleep_s)
    metrics.counter("bst_io_read_bytes_total", op="hist-test",
                    path="synthetic").inc(extra_bytes)
    return observe.finalize(tool="demo")


class TestHistoryPerfDiff:
    def test_finalize_records_and_diff_flags_slowdown(self, tmp_path,
                                                      monkeypatch,
                                                      _clean_observe):
        """Acceptance: two recorded runs diff cleanly; the injected
        slowdown (6x span time, 6x bytes) is flagged at a 50%%
        threshold, and the reverse direction is clean."""
        hist = str(tmp_path / "hist")
        monkeypatch.setenv("BST_HISTORY_DIR", hist)
        _record_run(tmp_path, "a", 0.05, 10 << 20, hist)
        _record_run(tmp_path, "b", 0.30, 60 << 20, hist)
        entries = history.list_records(hist)
        assert len(entries) == 2
        assert all(e["tool"] == "demo" and e["status"] == "ok"
                   for e in entries)

        runner = CliRunner()
        out = _cli_ok(runner, ["history", "list"]).output
        assert entries[0]["id"] in out and entries[1]["id"] in out

        rec = json.loads(_cli_ok(
            runner, ["history", "show", entries[0]["id"]]).output)
        assert rec["tool"] == "demo" and "spans" in rec and "metrics" in rec

        out = _cli_ok(runner, ["perf-diff", "--last", "2",
                               "--threshold", "50"]).output
        assert "REGRESSION" in out and "fusion.kernel" in out
        rep = json.loads(_cli_ok(
            runner, ["perf-diff", "--last", "2", "--threshold", "50",
                     "--json"]).output)
        kinds = {r["kind"] for r in rep["regressions"]}
        assert "span" in kinds and "bytes" in kinds
        # explicit ids work too, and the reverse diff is regression-free
        rep2 = json.loads(_cli_ok(
            runner, ["perf-diff", entries[1]["id"], entries[0]["id"],
                     "--threshold", "50", "--json"]).output)
        assert rep2["regressions"] == []
        # CI-gate exit code
        r = runner.invoke(cli, ["perf-diff", "--last", "2",
                                "--threshold", "50",
                                "--fail-on-regression"])
        assert r.exit_code == 2

    def test_history_add_imports_manifests(self, tmp_path, _clean_observe):
        # a run recorded WITHOUT the knob set...
        observe.configure(str(tmp_path / "tel"))
        observe.finalize(tool="demo")
        hist = str(tmp_path / "hist2")
        assert not os.path.exists(os.path.join(hist, "index.jsonl"))
        runner = CliRunner()
        # ...imports later, by telemetry dir
        out = _cli_ok(runner, ["history", "add", str(tmp_path / "tel"),
                               "--history-dir", hist]).output
        rid = out.strip()
        assert rid
        entries = history.list_records(hist)
        assert [e["id"] for e in entries] == [rid]
        rec = history.load_record(rid, hist)
        assert rec["tool"] == "demo"

    def test_jobrun_manifests_record_with_job_label(self, tmp_path,
                                                    monkeypatch):
        hist = str(tmp_path / "hist3")
        monkeypatch.setenv("BST_HISTORY_DIR", hist)
        jr = observe.JobRun("jtest", str(tmp_path / "job"), tool="config")
        with jr:
            pass
        jr.finalize(status="ok")
        entries = history.list_records(hist)
        assert len(entries) == 1 and entries[0]["job"] == "jtest"

    def test_cache_ratio_regression(self):
        a = {"id": "a", "seconds": 1.0, "spans": {}, "metrics": {
            "bst_chunk_cache_hits_total": 90,
            "bst_chunk_cache_misses_total": 10}}
        b = {"id": "b", "seconds": 1.0, "spans": {}, "metrics": {
            "bst_chunk_cache_hits_total": 10,
            "bst_chunk_cache_misses_total": 90}}
        rep = history.diff(a, b, threshold_pct=20.0)
        assert any(r["kind"] == "cache" for r in rep["regressions"])
        assert history.diff(b, a, threshold_pct=20.0)["regressions"] == []

    def test_histogram_metrics_flatten_into_diff(self):
        a = {"id": "a", "seconds": 1.0, "spans": {},
             "metrics": {"bst_serve_wait_seconds":
                         {"count": 2, "sum": 0.5}}}
        rep = history.diff(a, a)
        assert rep["regressions"] == []

    def test_missing_history_dir_is_a_clean_error(self, monkeypatch):
        monkeypatch.delenv("BST_HISTORY_DIR", raising=False)
        runner = CliRunner()
        r = runner.invoke(cli, ["perf-diff", "x", "y"])
        assert r.exit_code != 0 and "history dir" in r.output
        r = runner.invoke(cli, ["history", "list"])
        assert r.exit_code != 0 and "history dir" in r.output

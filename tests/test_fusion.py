"""Fusion kernel: golden checks against an independent numpy resampler, and
end-to-end fusion of the synthetic project against the known global phantom."""

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.container import (
    create_fusion_container,
    estimate_multires_pyramid,
    read_container_meta,
)
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
from bigstitcher_spark_tpu.models.affine_fusion import (
    BlendParams,
    fuse_volume,
)
from bigstitcher_spark_tpu.ops import fusion as F
from bigstitcher_spark_tpu.utils.geometry import Interval
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project


def np_trilinear(patch, pts):
    """Independent trilinear reference."""
    out = np.zeros(len(pts))
    for i, p in enumerate(pts):
        p0 = np.floor(p).astype(int)
        f = p - p0
        acc = 0.0
        for dx in (0, 1):
            for dy in (0, 1):
                for dz in (0, 1):
                    xi = np.clip(p0[0] + dx, 0, patch.shape[0] - 1)
                    yi = np.clip(p0[1] + dy, 0, patch.shape[1] - 1)
                    zi = np.clip(p0[2] + dz, 0, patch.shape[2] - 1)
                    w = (
                        (f[0] if dx else 1 - f[0])
                        * (f[1] if dy else 1 - f[1])
                        * (f[2] if dz else 1 - f[2])
                    )
                    acc += w * patch[xi, yi, zi]
        out[i] = acc
    return out


def _identity_inputs(patch, v=1):
    vb = F.bucket_views(v)
    shape = patch.shape
    patches = np.zeros((vb, *shape), np.float32)
    patches[0] = patch
    affines = np.zeros((vb, 3, 4), np.float32)
    affines[:, :, :3] = np.eye(3)
    offsets = np.zeros((vb, 3), np.float32)
    img_dims = np.tile(np.array(shape, np.float32), (vb, 1))
    borders = np.zeros((vb, 3), np.float32)
    ranges = np.ones((vb, 3), np.float32)
    valid = np.zeros((vb,), np.float32)
    valid[0] = 1
    return patches, affines, offsets, img_dims, borders, ranges, valid


class TestKernel:
    def test_identity_avg(self):
        rng = np.random.default_rng(0)
        patch = rng.uniform(0, 100, (8, 8, 8)).astype(np.float32)
        args = _identity_inputs(patch)
        fused, wsum = F.fuse_block(*args, block_shape=(8, 8, 8), fusion_type="AVG")
        np.testing.assert_allclose(np.asarray(fused), patch, rtol=1e-5)
        assert np.all(np.asarray(wsum) == 1.0)

    def test_subpixel_translation_matches_numpy(self):
        rng = np.random.default_rng(1)
        patch = rng.uniform(0, 100, (10, 9, 8)).astype(np.float32)
        args = list(_identity_inputs(patch))
        shift = np.array([0.5, 0.25, 0.75], np.float32)
        args[1][0, :, 3] = shift  # affine translation
        fused, _ = F.fuse_block(*args, block_shape=(6, 6, 6), fusion_type="AVG")
        coords = np.stack(
            np.meshgrid(*[np.arange(6)] * 3, indexing="ij"), -1
        ).reshape(-1, 3)
        expected = np_trilinear(patch, coords + shift).reshape(6, 6, 6)
        np.testing.assert_allclose(np.asarray(fused), expected, rtol=1e-4)

    def test_outside_is_masked(self):
        patch = np.ones((8, 8, 8), np.float32) * 50
        args = list(_identity_inputs(patch))
        args[1][0, :, 3] = [-4, 0, 0]  # half the block samples before image start
        fused, wsum = F.fuse_block(*args, block_shape=(8, 8, 8), fusion_type="AVG")
        wsum = np.asarray(wsum)
        assert np.all(wsum[:4] == 0)  # x<4 maps to lpos<0
        assert np.all(wsum[4:] == 1)
        assert np.all(np.asarray(fused)[:4] == 0)

    def test_blend_weight_ramp(self):
        # single view, blending: weight must rise cosine-like from the border
        patch = np.ones((16, 16, 16), np.float32)
        args = list(_identity_inputs(patch))
        args[5] = np.full((1, 3), 4.0, np.float32)  # blend range 4
        fused, wsum = F.fuse_block(
            *args, block_shape=(16, 16, 16), fusion_type="AVG_BLEND"
        )
        w = np.asarray(wsum)[:, 8, 8]
        assert w[0] == pytest.approx(0.0, abs=1e-6)  # at border
        assert w[2] == pytest.approx(0.5 * (np.cos(0.5 * np.pi) + 1), rel=1e-4)
        assert w[8] == pytest.approx(1.0)
        # two-sided product in the corner
        wc = np.asarray(wsum)[2, 2, 8]
        assert wc == pytest.approx(w[2] * w[2], rel=1e-4)

    def test_two_view_avg_blend_smooth(self):
        # two constant views of different value overlapping: AVG_BLEND must
        # interpolate smoothly between 10 and 30 along x
        v = 2
        vb = F.bucket_views(v)
        shape = (32, 8, 8)
        patches = np.zeros((vb, *shape), np.float32)
        patches[0] = 10.0
        patches[1] = 30.0
        affines = np.zeros((vb, 3, 4), np.float32)
        affines[:, :, :3] = np.eye(3)
        affines[1, 0, 3] = -16.0  # view B starts at x=16 in block coords
        offsets = np.zeros((vb, 3), np.float32)
        img_dims = np.tile(np.array(shape, np.float32), (vb, 1))
        borders = np.zeros((vb, 3), np.float32)
        ranges = np.full((vb, 3), 8.0, np.float32)
        ranges[:, 1:] = 0.001  # only blend along x
        valid = np.array([1, 1] + [0] * (vb - 2), np.float32)
        fused, wsum = F.fuse_block(
            patches, affines, offsets, img_dims, borders, ranges, valid,
            block_shape=(48, 8, 8), fusion_type="AVG_BLEND",
        )
        line = np.asarray(fused)[:, 4, 4]
        assert line[8] == pytest.approx(10.0, rel=1e-4)   # only view A
        assert line[40] == pytest.approx(30.0, rel=1e-4)  # only view B
        mid = line[16:31]
        assert np.all(np.diff(mid) >= -1e-4)  # monotone transition
        assert line[23] == pytest.approx(20.0, abs=2.0)   # near middle

    def test_max_and_wins(self):
        vb = 2
        patches = np.zeros((vb, 4, 4, 4), np.float32)
        patches[0] = 5
        patches[1] = 9
        affines = np.zeros((vb, 3, 4), np.float32)
        affines[:, :, :3] = np.eye(3)
        offsets = np.zeros((vb, 3), np.float32)
        img_dims = np.full((vb, 3), 4.0, np.float32)
        borders = np.zeros((vb, 3), np.float32)
        ranges = np.ones((vb, 3), np.float32)
        valid = np.ones((vb,), np.float32)
        a = (patches, affines, offsets, img_dims, borders, ranges, valid)
        fused, _ = F.fuse_block(*a, block_shape=(4, 4, 4), fusion_type="MAX_INTENSITY")
        assert np.all(np.asarray(fused) == 9)
        fused, _ = F.fuse_block(*a, block_shape=(4, 4, 4), fusion_type="FIRST_WINS")
        assert np.all(np.asarray(fused) == 5)
        fused, _ = F.fuse_block(*a, block_shape=(4, 4, 4), fusion_type="LAST_WINS")
        assert np.all(np.asarray(fused) == 9)

    def test_convert_intensity(self):
        block = np.array([0.0, 0.5, 1.0, 2.0], np.float32)
        out = np.asarray(
            F.convert_intensity(block, np.float32(0), np.float32(1), out_dtype="uint8")
        )
        np.testing.assert_array_equal(out, [0, 128, 255, 255])
        out16 = np.asarray(
            F.convert_intensity(block, np.float32(0), np.float32(2), out_dtype="uint16")
        )
        np.testing.assert_array_equal(out16, [0, 16384, 32768, 65535])


class TestPyramidProposal:
    def test_estimate(self):
        ds = estimate_multires_pyramid((512, 512, 128))
        assert ds[0] == [1, 1, 1]
        assert ds[1] == [2, 2, 2]
        assert all(len(d) == 3 for d in ds)
        # small volume -> single level
        assert estimate_multires_pyramid((32, 32, 16)) == [[1, 1, 1]]


class TestEndToEnd:
    def test_container_roundtrip(self, tmp_path):
        bbox = Interval((0, 0, 0), (99, 89, 49))
        meta = create_fusion_container(
            str(tmp_path / "fused.n5"), StorageFormat.N5, "in.xml",
            num_timepoints=2, num_channels=3, bbox=bbox,
            data_type="uint16", block_size=(32, 32, 16),
            downsamplings=[[1, 1, 1], [2, 2, 1]],
        )
        store = ChunkStore.open(str(tmp_path / "fused.n5"))
        back = read_container_meta(store)
        assert back.fusion_format == "N5"
        assert back.bbox == bbox
        assert back.num_channels == 3 and back.num_timepoints == 2
        assert len(back.mr_infos) == 6
        assert back.mr_infos[0][1].dataset == "ch0tp0/s1"
        assert back.mr_infos[0][1].absoluteDownsampling == [2, 2, 1]
        assert store.is_dataset("ch2tp1/s0")

    def test_fuse_two_tiles_matches_phantom(self, tmp_path):
        # jitter=0: XML offsets == true offsets, so fusion must reproduce
        # the global phantom (up to per-tile noise) in the fused volume.
        proj = make_synthetic_project(
            str(tmp_path / "p"), n_tiles=(2, 1, 1), jitter=0.0, seed=3,
        )
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        # bounding box = union of transformed views
        from bigstitcher_spark_tpu.utils.geometry import transformed_interval

        boxes = [
            transformed_interval(sd.model(v), Interval.from_shape(sd.view_size(v)))
            for v in views
        ]
        bbox = boxes[0]
        for b in boxes[1:]:
            bbox = bbox.union(b)
        out = ChunkStore.create(str(tmp_path / "fused.n5"), StorageFormat.N5)
        ds = out.create_dataset("fused/s0", bbox.shape, (64, 64, 32), "float32")
        stats = fuse_volume(
            sd, loader, views, ds, bbox, block_size=(64, 64, 32),
            block_scale=(1, 1, 1), fusion_type="AVG_BLEND",
            out_dtype="float32", min_intensity=0, max_intensity=1,
        )
        assert stats.voxels == bbox.num_elements
        fused = ds.read_full()
        # compare at bead positions that are strictly inside the fused volume
        from bigstitcher_spark_tpu.utils.testdata import make_bead_volume

        assert fused.max() > 500
        # interior means: global average intensity close between fused & tiles
        t0 = loader.open(ViewId(0, 0)).read_full().astype(np.float32)
        inner = fused[8:88, 8:88, 8:40]
        assert abs(float(np.median(inner)) - float(np.median(t0))) < 5.0
        # coverage: every voxel inside the union box that belongs to some view
        assert float((fused == 0).mean()) < 0.15

    def test_fuse_into_zarr5d(self, tmp_path):
        proj = make_synthetic_project(
            str(tmp_path / "p"), n_tiles=(1, 1, 1), jitter=0.0, seed=4,
        )
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        bbox = Interval.from_shape(sd.view_size(ViewId(0, 0)))
        meta = create_fusion_container(
            str(tmp_path / "f.zarr"), StorageFormat.ZARR, proj.xml_path,
            num_timepoints=1, num_channels=1, bbox=bbox, data_type="uint16",
            block_size=(48, 48, 24),
        )
        store = ChunkStore.open(str(tmp_path / "f.zarr"))
        ds = store.open_dataset("0")
        stats = fuse_volume(
            sd, loader, sd.view_ids(), ds, bbox, block_size=(48, 48, 24),
            block_scale=(1, 1, 1), out_dtype="uint16",
            min_intensity=0.0, max_intensity=65535.0, zarr_ct=(0, 0),
        )
        fused = ds.read((0, 0, 0, 0, 0), (*bbox.shape, 1, 1))[..., 0, 0]
        src = loader.open(ViewId(0, 0)).read_full()
        # single view, identity transform, no blending at interior: exact match
        inner = (slice(45, 50), slice(45, 50), slice(20, 28))
        np.testing.assert_allclose(
            fused[inner].astype(float), src[inner].astype(float), atol=1.0
        )


class TestSeparableDiagonalKernel:
    def test_sep_matches_gather_on_diagonal_affines(self):
        """The no-gather separable kernel must reproduce the gather kernel
        for diagonal block->patch affines (the --preserveAnisotropy case)."""
        import numpy as np

        from bigstitcher_spark_tpu.ops import fusion as F

        rng = np.random.default_rng(6)
        V, P, B = 3, (40, 36, 28), (24, 24, 16)
        patches = rng.random((V, *P)).astype(np.float32) * 900
        affines = np.zeros((V, 3, 4), np.float32)
        diags = rng.uniform(0.6, 1.7, (V, 3)).astype(np.float32)
        ts = rng.uniform(-3, 6, (V, 3)).astype(np.float32)
        for i in range(3):
            affines[:, i, i] = diags[:, i]
        affines[:, :, 3] = ts
        offsets = rng.uniform(0, 4, (V, 3)).astype(np.float32)
        img_dims = np.tile(np.array(P, np.float32) * 1.4, (V, 1))
        borders = np.zeros((V, 3), np.float32)
        ranges = np.full((V, 3), 9.0, np.float32)
        valid = np.ones(V, np.float32)

        for ftype in ("AVG_BLEND", "MAX_INTENSITY", "FIRST_WINS"):
            g_f, g_w = F.fuse_block(
                patches, affines, offsets, img_dims, borders, ranges, valid,
                block_shape=B, fusion_type=ftype)
            s_f, s_w = F.fuse_block_sep(
                patches, diags, ts, offsets, img_dims, borders, ranges,
                valid, block_shape=B, fusion_type=ftype)
            np.testing.assert_allclose(np.asarray(s_f).reshape(B),
                                       np.asarray(g_f), atol=2e-3)
            np.testing.assert_allclose(np.asarray(s_w).reshape(B),
                                       np.asarray(g_w), atol=2e-4)

    def test_sep_matches_gather_on_mirrored_diagonals(self):
        """Negative (mirrored) diagonal entries must also agree: the
        per-block bucketing routes mirrored-diagonal views to the sep
        kernel (is_diagonal does not require positive entries), so the
        edge-clamped interpolation matrices must handle reversed axes
        (ADVICE r4 — previously untested)."""
        import numpy as np

        from bigstitcher_spark_tpu.ops import fusion as F

        rng = np.random.default_rng(6)
        V, P, B = 3, (40, 36, 28), (24, 24, 16)
        patches = rng.random((V, *P)).astype(np.float32) * 900
        affines = np.zeros((V, 3, 4), np.float32)
        diags = rng.uniform(0.6, 1.7, (V, 3)).astype(np.float32)
        diags[0, 1] *= -1.0  # mirrored y on view 0
        diags[2, 0] *= -1.0  # mirrored x on view 2
        ts = rng.uniform(-3, 6, (V, 3)).astype(np.float32)
        ts[0, 1] += P[1]  # keep mirrored sampling inside the patch
        ts[2, 0] += P[0]
        for i in range(3):
            affines[:, i, i] = diags[:, i]
        affines[:, :, 3] = ts
        offsets = rng.uniform(0, 4, (V, 3)).astype(np.float32)
        img_dims = np.tile(np.array(P, np.float32) * 1.4, (V, 1))
        borders = np.zeros((V, 3), np.float32)
        ranges = np.full((V, 3), 9.0, np.float32)
        valid = np.ones(V, np.float32)

        for ftype in ("AVG_BLEND", "MAX_INTENSITY", "FIRST_WINS"):
            g_f, g_w = F.fuse_block(
                patches, affines, offsets, img_dims, borders, ranges, valid,
                block_shape=B, fusion_type=ftype)
            s_f, s_w = F.fuse_block_sep(
                patches, diags, ts, offsets, img_dims, borders, ranges,
                valid, block_shape=B, fusion_type=ftype)
            np.testing.assert_allclose(np.asarray(s_f).reshape(B),
                                       np.asarray(g_f), atol=2e-3)
            np.testing.assert_allclose(np.asarray(s_w).reshape(B),
                                       np.asarray(g_w), atol=2e-4)

    def test_anisotropy_fusion_routes_to_sep(self, tmp_path):
        """--preserveAnisotropy over translation-registered tiles: the
        per-block path must take the separable kernel and agree with the
        gather kernel's result."""
        import numpy as np

        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.affine_fusion import (
            FusionStats, fuse_volume,
        )
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box
        from bigstitcher_spark_tpu.models import affine_fusion as AF

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=16, jitter=1.5, seed=8, n_beads_per_tile=10)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        af = 2.0  # anisotropy factor -> diagonal (1,1,1/af) scaling
        from bigstitcher_spark_tpu.models.affine_fusion import (
            anisotropy_transform,
        )

        bbox = maximal_bounding_box(sd, views, anisotropy_transform(af))
        outs = {}
        for label, sep_enabled in (("sep", True), ("gather", False)):
            st = ChunkStore.create(str(tmp_path / f"{label}.n5"),
                                   StorageFormat.N5)
            ds = st.create_dataset("f", bbox.shape, (32, 32, 16), "float32")
            stats = FusionStats()
            orig = AF._ViewPlan.is_diagonal
            if not sep_enabled:  # force the gather path for the comparison
                AF._ViewPlan.is_diagonal = property(lambda self: False)
            try:
                stats = fuse_volume(
                    sd, loader, views, ds, bbox, block_size=(32, 32, 16),
                    block_scale=(1, 1, 1), anisotropy_factor=af,
                    out_dtype="float32", min_intensity=0.0, max_intensity=1.0,
                    device_resident=False, devices=1)
            finally:
                AF._ViewPlan.is_diagonal = orig
            if sep_enabled:
                assert any("sep" in str(k) for k in stats.compile_keys), \
                    stats.compile_keys
            outs[label] = ds.read_full()
        np.testing.assert_allclose(outs["sep"], outs["gather"], atol=2e-3)
        assert outs["sep"].std() > 0

    def test_composite_handles_anisotropy(self, tmp_path):
        """The whole-volume device-resident path must now accept diagonal
        (preserveAnisotropy) views and match the per-block result."""
        import numpy as np

        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models import affine_fusion as AF
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=16, jitter=1.5, seed=8, n_beads_per_tile=10)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        af = 2.0
        aniso = AF.anisotropy_transform(af)
        bbox = maximal_bounding_box(sd, views, aniso)
        cp = AF.plan_composite_volume(sd, loader, views, bbox, aniso,
                                      AF.BlendParams())
        assert cp is not None and "sep" in cp.kinds, cp and cp.kinds
        tiles = AF.upload_composite_tiles(loader, cp)
        vol = np.asarray(AF.dispatch_composite(
            cp, tiles, "AVG_BLEND", "float32", False, 0.0, 1.0))

        st = ChunkStore.create(str(tmp_path / "blk.n5"), StorageFormat.N5)
        ds = st.create_dataset("f", bbox.shape, (32, 32, 16), "float32")
        AF.fuse_volume(sd, loader, views, ds, bbox, block_size=(32, 32, 16),
                       block_scale=(1, 1, 1), anisotropy_factor=af,
                       out_dtype="float32", min_intensity=0.0,
                       max_intensity=1.0, device_resident=False, devices=1)
        blk = ds.read_full()
        np.testing.assert_allclose(vol, blk, atol=3e-3)
        assert vol.std() > 0


class TestPatchDtype:
    """The lossless transport decision: native integer width when every
    (view, level) shares one, float32 otherwise; probes memoized on the
    loader (models/affine_fusion.patch_dtype)."""

    class _FakeLoader:
        def __init__(self, dtypes):
            self._dtypes = dtypes
            self.opens = 0

        def open(self, view, level):
            self.opens += 1
            import types
            return types.SimpleNamespace(dtype=self._dtypes[(view, level)])

    def test_uniform_uint16_and_memoization(self):
        from bigstitcher_spark_tpu.models.affine_fusion import patch_dtype

        ld = self._FakeLoader({("a", 0): np.uint16, ("b", 0): np.uint16})
        assert patch_dtype(ld, [("a", 0), ("b", 0)]) == np.dtype(np.uint16)
        n = ld.opens
        assert patch_dtype(ld, [("a", 0), ("b", 0)]) == np.dtype(np.uint16)
        assert ld.opens == n  # second call fully memoized

    def test_mixed_or_wide_dtypes_fall_back_to_float32(self):
        from bigstitcher_spark_tpu.models.affine_fusion import patch_dtype

        mixed = self._FakeLoader({("a", 0): np.uint16, ("b", 0): np.uint8})
        assert patch_dtype(mixed, [("a", 0), ("b", 0)]) == np.dtype(np.float32)
        wide = self._FakeLoader({("a", 0): np.uint32})
        assert patch_dtype(wide, [("a", 0)]) == np.dtype(np.float32)
        flt = self._FakeLoader({("a", 0): np.float32})
        assert patch_dtype(flt, [("a", 0)]) == np.dtype(np.float32)

    def test_big_endian_normalized(self):
        from bigstitcher_spark_tpu.models.affine_fusion import patch_dtype

        ld = self._FakeLoader({("a", 0): np.dtype(">u2")})
        d = patch_dtype(ld, [("a", 0)])
        assert d == np.dtype(np.uint16) and d.byteorder in "=|<"


class TestTpuLoweringSafety:
    def test_composite_kernel_lowers_scatter_free(self, tmp_path):
        """The composite fusion kernel must not emit HLO scatter ops:
        .at[win].add on static windows lowers to scatter, which serializes
        on TPU — the exact cliff r4's verdict flagged as untestable from
        CPU runs. Pin the property at the HLO level so it cannot regress."""
        import numpy as np

        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models import affine_fusion as AF
        from bigstitcher_spark_tpu.ops import fusion as F
        from bigstitcher_spark_tpu.utils.testdata import (
            make_synthetic_project,
        )
        from bigstitcher_spark_tpu.utils.viewselect import (
            maximal_bounding_box,
        )

        proj = make_synthetic_project(
            str(tmp_path / "p"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
            overlap=8, jitter=0.0, n_beads_per_tile=5)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sd.view_ids()
        bbox = maximal_bounding_box(sd, views)
        cp = AF.plan_composite_volume(sd, loader, views, bbox, None,
                                      AF.BlendParams())
        assert cp is not None
        tiles = AF.upload_composite_tiles(loader, cp)
        for ftype in ("AVG_BLEND", "MAX_INTENSITY", "FIRST_WINS"):
            fuser = F.make_translation_composite(
                cp.out_shape, cp.windows, cp.n_offs, pad=cp.pad,
                fusion_type=ftype, out_dtype="uint16", masks=False,
                with_coeffs=False, kinds=cp.kinds)
            low = fuser.lower(
                tiles, cp.fracs, cp.img_dims, cp.borders, cp.ranges,
                cp.inside_offs, np.float32(0), np.float32(65535),
                cp.diags, cp.offs)
            hlo = low.compiler_ir(dialect="hlo").as_hlo_text()
            n_scatter = sum(1 for ln in hlo.splitlines()
                            if " scatter(" in ln)
            assert n_scatter == 0, (
                f"{ftype}: composite kernel emits {n_scatter} scatter ops")

    def test_dog_kernel_has_no_volume_scatter(self):
        """The DoG detection kernel may keep tiny (K,3) index scatters from
        the localizer, but no full-volume ones (the old core-mask
        .at[].set)."""
        import functools

        import jax
        import numpy as np

        from bigstitcher_spark_tpu.ops import dog as D

        fn = functools.partial(
            jax.jit, static_argnames=("sigma", "find_max", "find_min", "k",
                                      "halo", "rel"))(D.dog_block_topk_impl)
        shape = (64, 64, 64)
        low = fn.lower(np.zeros(shape, np.uint16), np.float32(0),
                       np.float32(1), np.float32(0.008),
                       np.zeros(3, np.int32), 1.8, True, False, 1024, 8,
                       (1, 1, 1))
        hlo = low.compiler_ir(dialect="hlo").as_hlo_text()
        vol = int(np.prod(shape))
        for ln in hlo.splitlines():
            if " scatter(" not in ln:
                continue
            shape_txt = ln.split("=")[1].strip().split(" ")[0]
            dims = shape_txt.split("[")[1].split("]")[0]
            n = int(np.prod([int(x) for x in dims.split(",") if x]))
            assert n < vol // 8, f"volume-sized scatter in DoG kernel: {ln[:120]}"

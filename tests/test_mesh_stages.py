"""Multi-device (virtual 8-CPU mesh) parity for the remaining sharded stages:
detection, downsample, resave pyramid, and nonrigid fusion must each produce
identical output on the 8-device mesh and on a single device (VERDICT r2 #2 —
the TPU replacements of the Spark maps at
SparkInterestPointDetection.java:448-660, SparkDownsample.java:141-177,
SparkResaveN5.java:278-415, SparkNonRigidFusion.java:313-435)."""

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData


@pytest.fixture(scope="module")
def project(tmp_path_factory):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(
        str(tmp_path_factory.mktemp("mesh_stages") / "proj"),
        n_tiles=(2, 2, 1), tile_size=(48, 48, 24), overlap=12,
        jitter=2.0, seed=17, block_size=(16, 16, 8), n_beads_per_tile=15,
    )


def test_mesh_has_8_devices():
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide the 8-device mesh"


def test_detection_sharded_equals_single(project):
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, detect_interest_points,
    )

    sd = SpimData.load(project.xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    params = DetectionParams(downsample_xy=1, downsample_z=1,
                             block_size=(32, 32, 16))
    multi = detect_interest_points(sd, loader, views, params, progress=False,
                                   devices=8)
    single = detect_interest_points(sd, loader, views, params, progress=False,
                                    devices=1)
    assert sum(len(d.points) for d in multi) > 0
    for dm, ds in zip(multi, single):
        assert dm.view == ds.view
        # sharded and unsharded compilations tile the blur GEMMs
        # differently -> f32 accumulation-order noise (SURVEY §7: tolerance,
        # not bit-exactness, for float comparisons)
        np.testing.assert_allclose(dm.points, ds.points, atol=1e-4)
        np.testing.assert_allclose(dm.values, ds.values, rtol=1e-4,
                                   atol=1e-7)


def _make_volume_dataset(tmp_path, name, seed):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 60000, (48, 40, 24)).astype(np.uint16)
    store = ChunkStore.create(str(tmp_path / f"{name}.n5"), StorageFormat.N5)
    src = store.create_dataset("s0", data.shape, (16, 16, 8), "uint16")
    src.write(data, (0, 0, 0))
    return store, src, data


def test_downsample_sharded_equals_single(tmp_path):
    from bigstitcher_spark_tpu.models.downsample_driver import (
        _convert_to_dtype, read_padded, run_sharded_downsample,
    )
    from bigstitcher_spark_tpu.utils.grid import create_grid

    store, src, data = _make_volume_dataset(tmp_path, "vol", 3)
    rel = (2, 2, 2)
    dims = [s // f for s, f in zip(src.shape, rel)]
    outs = {}
    for label, n_dev in (("multi", 8), ("single", 1)):
        dst = store.create_dataset(f"s1_{label}", dims, (16, 16, 8), "uint16")

        def read_job(blk):
            return read_padded(src.read, src.shape,
                               [o * f for o, f in zip(blk.offset, rel)],
                               [s * f for s, f in zip(blk.size, rel)])

        def write_job(blk, out, dst=dst):
            dst.write(_convert_to_dtype(out, dst.dtype), blk.offset)

        run_sharded_downsample(create_grid(dims, (16, 16, 8)), read_job,
                               write_job, rel, devices=n_dev)
        outs[label] = dst.read_full()
    # golden: plain numpy 2x2x2 average
    ref = data.reshape(24, 2, 20, 2, 12, 2).mean(axis=(1, 3, 5))
    ref = np.clip(np.round(ref), 0, 65535).astype(np.uint16)
    np.testing.assert_array_equal(outs["multi"], outs["single"])
    np.testing.assert_array_equal(outs["multi"], ref)


def test_resave_pyramid_sharded_equals_single(project, tmp_path):
    from bigstitcher_spark_tpu.models.resave import resave

    sd = SpimData.load(project.xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    pyr = [[1, 1, 1], [2, 2, 2]]
    vols = {}
    for label, n_dev in (("multi", 8), ("single", 1)):
        out = str(tmp_path / f"resave_{label}.n5")
        resave(sd, loader, views, out, StorageFormat.N5,
               block_size=(16, 16, 8), block_scale=(2, 2, 1),
               downsamplings=pyr, devices=n_dev)
        store = ChunkStore.open(out)
        vols[label] = [
            store.open_dataset(f"setup{v.setup}/timepoint{v.timepoint}/s1"
                               ).read_full()
            for v in views
        ]
    for m, s in zip(vols["multi"], vols["single"]):
        assert m.std() > 0
        np.testing.assert_array_equal(m, s)


def test_nonrigid_sharded_equals_single(tmp_path):
    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, detect_interest_points, save_detections,
    )
    from bigstitcher_spark_tpu.models.matching import (
        MatchingParams, match_interest_points, save_matches,
    )
    from bigstitcher_spark_tpu.models.nonrigid_fusion import (
        build_unique_points, fuse_nonrigid_volume,
    )
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(64, 64, 32),
        overlap=24, jitter=2.0, seed=19, n_beads_per_tile=25,
    )
    sd = SpimData.load(proj.xml_path)
    views = sorted(sd.registrations)
    loader = ViewLoader(sd)
    dets = detect_interest_points(
        sd, loader, views,
        DetectionParams(downsample_xy=1, downsample_z=1,
                        block_size=(64, 64, 32)),
        progress=False,
    )
    store = InterestPointStore(str(tmp_path / "proj" / "interestpoints.n5"))
    save_detections(sd, store, dets, DetectionParams())
    mparams = MatchingParams(ransac_min_inliers=5, ransac_iterations=2000,
                             model="TRANSLATION", regularization="NONE")
    res = match_interest_points(sd, views, mparams, store, progress=False)
    save_matches(sd, store, res, mparams, views)
    unique = build_unique_points(sd, store, views, ["beads"])

    bbox = maximal_bounding_box(sd, views, None)
    vols = {}
    for label, n_dev in (("multi", 8), ("single", 1)):
        cstore = ChunkStore.create(str(tmp_path / f"nr_{label}.n5"),
                                   StorageFormat.N5)
        out = cstore.create_dataset("fused", bbox.shape, (32, 32, 16),
                                    "uint16")
        stats = fuse_nonrigid_volume(
            sd, loader, views, unique, out, bbox,
            block_size=(32, 32, 16), block_scale=(1, 1, 1), cpd=10.0,
            out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
            devices=n_dev,
        )
        assert stats.voxels == bbox.num_elements
        vols[label] = out.read_full()
    assert vols["multi"].std() > 0
    np.testing.assert_array_equal(vols["multi"], vols["single"])

"""Device-side global solvers (ops/solve.py): exact-parity suite.

The contract of the device-solver PR: the jit-compiled relaxation — one
``lax.while_loop`` per ``relax()`` call — tracks the numpy reference path
through the mpicbg convergence state (same iteration count, same error
history to ≤1e-6 documented tolerance, in practice ~1e-12 relative), the
iterative drop-worst-link loop removes the IDENTICAL link sequence, a
masked-link re-solve is bitwise equal to a rebuilt-link-list solve, the
psum-sharded layout is bitwise equal to the single-device one, repeated
solves hit warm compile buckets, and the relax inner loop performs zero
per-iteration host transfers (trace-asserted). The intensity coefficient
CG gets the same treatment against the dense normal-equations solve.
"""

import numpy as np
import pytest

from bigstitcher_spark_tpu import config, profiling
from bigstitcher_spark_tpu.io.spimdata import ViewId
from bigstitcher_spark_tpu.models import solver as S
from bigstitcher_spark_tpu.models.intensity import smoothness_pairs
from bigstitcher_spark_tpu.observe import metrics as _metrics, trace
from bigstitcher_spark_tpu.ops import models as M
from bigstitcher_spark_tpu.ops.intensity import (
    match_stats,
    solve_intensity_coefficients,
)


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()
    yield
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()


def _graph(n=(4, 3), jitter=3.0, seed=0, tile=(100, 100, 50), step=80.0):
    """Synthetic tile-grid link graph: truth-consistent 8-corner links
    (the stitching-source shape) with jittered nominal positions."""
    rng = np.random.default_rng(seed)
    tiles = [(ViewId(0, i),) for i in range(n[0] * n[1])]
    truth = {i: np.array([(i % n[0]) * step, (i // n[0]) * step, 0.0])
             for i in range(len(tiles))}
    nom = {i: truth[i] + (rng.uniform(-jitter, jitter, 3) if i else 0.0)
           for i in truth}
    corners = np.array([[x, y, z] for x in (0, tile[0]) for y in (0, tile[1])
                        for z in (0, tile[2])], float)
    links = []
    for i in range(len(tiles)):
        for j in (i + 1, i + n[0]):
            if j >= len(tiles):
                continue
            if j == i + 1 and (i % n[0]) == n[0] - 1:
                continue
            shift = (truth[i] - nom[i]) - (truth[j] - nom[j])
            links.append(S.MatchLink(tiles[i], tiles[j], corners,
                                     corners + shift, np.full(8, 0.9)))
    return tiles, links


def _assert_same_result(a: S.SolveResult, b: S.SolveResult,
                        rtol=1e-9, atol=1e-9, exact=False):
    assert a.iterations == b.iterations
    if exact:
        np.testing.assert_array_equal(a.history, b.history)
    else:
        np.testing.assert_allclose(a.history, b.history, rtol=rtol,
                                   atol=atol)
    assert set(a.corrections) == set(b.corrections)
    for k in a.corrections:
        if exact:
            np.testing.assert_array_equal(a.corrections[k],
                                          b.corrections[k])
        else:
            np.testing.assert_allclose(a.corrections[k], b.corrections[k],
                                       rtol=1e-7, atol=atol)
    assert set(a.link_errors) == set(b.link_errors)
    for k in a.link_errors:
        np.testing.assert_allclose(a.link_errors[k], b.link_errors[k],
                                   rtol=1e-7, atol=atol)


# ------------------------------------------------------- relax parity


class TestRelaxParity:
    COMBOS = [
        (M.TRANSLATION, M.NONE),
        (M.RIGID, M.NONE),
        (M.AFFINE, M.NONE),
        (M.AFFINE, M.RIGID),
        (M.RIGID, M.TRANSLATION),
        (M.TRANSLATION, M.IDENTITY),
    ]

    @pytest.mark.parametrize("model,reg", COMBOS)
    def test_device_matches_numpy(self, model, reg):
        tiles, links = _graph()
        fixed = {tiles[0]}
        pn = S.SolverParams(model=model, regularization=reg,
                            backend="numpy")
        pd = S.SolverParams(model=model, regularization=reg,
                            backend="device")
        rn = S.relax(links, tiles, fixed, pn)
        rd = S.relax(links, tiles, fixed, pd)
        # same compiled-convergence semantics: identical sweep count and
        # an error history that tracks to f64 noise (documented ≤1e-6)
        _assert_same_result(rn, rd, rtol=1e-9, atol=1e-9)

    def test_knob_selects_backend(self, monkeypatch):
        params = S.SolverParams()
        assert S._resolve_backend(params) == "device"
        monkeypatch.setenv("BST_SOLVE_DEVICE", "0")
        assert S._resolve_backend(params) == "numpy"
        # explicit params win over the knob
        assert S._resolve_backend(
            S.SolverParams(backend="device")) == "device"
        with config.overrides({"BST_SOLVE_DEVICE": True}):
            assert S._resolve_backend(params) == "device"

    def test_empty_links_identity(self):
        tiles, _ = _graph(n=(2, 1))
        res = S.relax([], tiles, {tiles[0]},
                      S.SolverParams(backend="device"))
        for k in tiles:
            np.testing.assert_array_equal(res.corrections[k][:, :3],
                                          np.eye(3))
        assert res.iterations == 0


class TestIterativeParity:
    def _bad_graph(self):
        tiles, links = _graph()
        corners = links[0].p
        links.append(S.MatchLink(tiles[0], tiles[5], corners,
                                 corners + np.array([80.0, -60.0, 40.0]),
                                 np.full(8, 0.8)))
        return tiles, links

    def test_drops_identical_link_sequence(self):
        tiles, links = self._bad_graph()
        fixed = {tiles[0]}
        pn = S.SolverParams(model=M.TRANSLATION,
                            method="ONE_ROUND_ITERATIVE", backend="numpy")
        pd = S.SolverParams(model=M.TRANSLATION,
                            method="ONE_ROUND_ITERATIVE", backend="device")
        rn = S.solve_iterative(links, tiles, fixed, pn, verbose=False)
        rd = S.solve_iterative(links, tiles, fixed, pd, verbose=False)
        assert len(rn.removed_links) >= 1
        assert rn.removed_links == rd.removed_links
        for k in rn.corrections:
            np.testing.assert_allclose(rd.corrections[k],
                                       rn.corrections[k], rtol=1e-7,
                                       atol=1e-9)

    def test_dropped_links_metric(self):
        tiles, links = self._bad_graph()
        c = _metrics.counter("bst_solve_links_dropped_total")
        before = c.value
        S.solve_iterative(links, tiles, {tiles[0]},
                          S.SolverParams(model=M.TRANSLATION,
                                         method="ONE_ROUND_ITERATIVE",
                                         backend="device"), verbose=False)
        assert c.value >= before + 1

    def test_masked_resolve_equals_rebuilt(self):
        """Re-entering the compiled fn with a zeroed link-weight mask must
        equal rebuilding the link list from scratch BITWISE — the property
        that lets solve_iterative skip per-drop re-traces."""
        tiles, links = self._bad_graph()
        fixed = {tiles[0]}
        pd = S.SolverParams(model=M.TRANSLATION, backend="device")
        state = S._DeviceRelax(links, tiles, fixed, pd)
        mask = np.ones(len(links))
        mask[-1] = 0.0
        masked = state.solve(mask)
        rebuilt = S.relax(links[:-1], tiles, fixed, pd)
        _assert_same_result(masked, rebuilt, exact=True)


class TestShardedParity:
    def test_sharded_equals_single_device_bitwise(self):
        """Rows grouped by owner tile: per-tile segment moments accumulate
        entirely on one device in single-device row order, psum adds exact
        zeros — the collective layout changes NOTHING, bit for bit."""
        tiles, links = _graph(n=(6, 4))
        fixed = {tiles[0]}
        pd = S.SolverParams(model=M.AFFINE, regularization=M.RIGID,
                            backend="device")
        single = S.relax(links, tiles, fixed, pd)
        with config.overrides({"BST_SOLVE_SHARD": 1}):
            sharded = S.relax(links, tiles, fixed, pd)
        _assert_same_result(single, sharded, exact=True)

    def test_shard_threshold_respected(self):
        tiles, links = _graph(n=(3, 2))
        pd = S.SolverParams(backend="device")
        with config.overrides({"BST_SOLVE_SHARD": 10 ** 9}):
            st = S._DeviceRelax(links, tiles, {tiles[0]}, pd)
            assert st.problem.n_shards == 1
        with config.overrides({"BST_SOLVE_SHARD": 1}):
            st = S._DeviceRelax(links, tiles, {tiles[0]}, pd)
            assert st.problem.n_shards > 1
        with config.overrides({"BST_SOLVE_SHARD": 0}):
            st = S._DeviceRelax(links, tiles, {tiles[0]}, pd)
            assert st.problem.n_shards == 1


class TestCompileBuckets:
    def test_warm_hit_on_repeat(self):
        tiles, links = _graph(seed=7)
        pd = S.SolverParams(model=M.RIGID, backend="device")
        warm = _metrics.counter("bst_compiled_fn_warm_hits_total")
        S.relax(links, tiles, {tiles[0]}, pd)
        before = warm.value
        # same shape bucket (same grid) — must hit the warm compiled fn
        S.relax(links, tiles, {tiles[0]}, pd)
        assert warm.value > before

    def test_iterative_resolves_share_one_bucket(self):
        """The drop-worst-link loop re-enters ONE compiled fn: every
        re-solve after the first is a warm hit."""
        tiles, links = _graph()
        corners = links[0].p
        links.append(S.MatchLink(tiles[0], tiles[5], corners,
                                 corners + np.array([80.0, -60.0, 40.0]),
                                 np.full(8, 0.8)))
        warm = _metrics.counter("bst_compiled_fn_warm_hits_total")
        cold = _metrics.counter("bst_compiled_fn_cold_builds_total")
        pd = S.SolverParams(model=M.TRANSLATION,
                            method="ONE_ROUND_ITERATIVE", backend="device")
        S.solve_iterative(links, tiles, {tiles[0]}, pd, verbose=False)
        w0, c0 = warm.value, cold.value
        res = S.solve_iterative(links, tiles, {tiles[0]}, pd,
                                verbose=False)
        assert len(res.removed_links) >= 1  # ≥2 relax calls ran
        assert cold.value == c0             # zero new compile buckets
        assert warm.value >= w0 + 2


class TestSingleWhileLoop:
    def test_one_relax_span_many_iterations(self):
        """The acceptance trace assertion: a relax() that iterates N ≫ 1
        times records exactly ONE solve.relax span (one compiled
        while_loop call) and one solve.reduce fetch — no per-iteration
        host round trips on the solver hot path."""
        trace.configure(buffer_bytes=1 << 20)
        tiles, links = _graph()
        pd = S.SolverParams(model=M.TRANSLATION, regularization=M.IDENTITY,
                            backend="device")
        res = S.relax(links, tiles, {tiles[0]}, pd)
        assert res.iterations > 10  # genuinely iterative solve
        snap = trace.snapshot()
        relax_b = [e for e in snap if e["name"] == "solve.relax"
                   and e["ph"] == "B"]
        reduce_b = [e for e in snap if e["name"] == "solve.reduce"
                    and e["ph"] == "B"]
        assert len(relax_b) == 1
        assert len(reduce_b) == 1
        # nothing else on the timeline: the solve never touches the mesh
        # drain or per-pair dispatch machinery mid-iteration
        other = {e["name"] for e in snap
                 if e["name"] not in ("solve.relax", "solve.reduce")}
        assert not other, other

    def test_iteration_metric_counts_sweeps(self):
        tiles, links = _graph()
        c = _metrics.counter("bst_solve_iterations_total")
        before = c.value
        res = S.relax(links, tiles, {tiles[0]},
                      S.SolverParams(backend="device"))
        assert c.value == before + res.iterations
        ms = _metrics.counter("bst_solve_device_ms_total", stage="relax")
        assert ms.value > 0


# ------------------------------------------------------- warm start


class TestDirectTranslations:
    def _dense_reference(self, links, index, fixed_idx, T):
        A = np.zeros((T, T))
        B = np.zeros((T, 3))
        for lk in links:
            ia, ib = index[lk.key_a], index[lk.key_b]
            wsum = float(lk.w.sum())
            s = ((lk.q - lk.p) * lk.w[:, None]).sum(0) / max(wsum, 1e-12)
            A[ia, ia] += wsum; A[ib, ib] += wsum
            A[ia, ib] -= wsum; A[ib, ia] -= wsum
            B[ia] += wsum * s; B[ib] -= wsum * s
        anchor = fixed_idx if len(fixed_idx) else np.arange(1)
        A[anchor, :] = 0.0
        A[anchor, anchor] = 1.0
        B[anchor] = 0.0
        iso = np.diag(A) == 0
        A[iso, iso] = 1.0
        return np.linalg.solve(A, B)

    def test_sparse_assembly_matches_dense(self):
        tiles, links = _graph(n=(5, 4), seed=3)
        index = {k: i for i, k in enumerate(tiles)}
        T = len(tiles)
        for fixed_idx in (np.array([0]), np.array([2, 7]),
                          np.array([], int)):
            sparse = S._direct_translations(links, index, fixed_idx, T)
            dense = self._dense_reference(links, index, fixed_idx, T)
            np.testing.assert_allclose(sparse, dense, rtol=1e-9, atol=1e-9)

    def test_isolated_tiles_stay_at_zero(self):
        tiles, links = _graph(n=(2, 1), seed=4)
        tiles = tiles + [(ViewId(0, 99),)]  # no links touch it
        index = {k: i for i, k in enumerate(tiles)}
        out = S._direct_translations(links, index, np.array([0]),
                                     len(tiles))
        np.testing.assert_array_equal(out[-1], 0.0)

    def test_no_dense_tt_allocation(self, monkeypatch):
        """The O(T²) guard: the warm start must never build a (T, T)
        ndarray again (the million-tile motivation of the rework)."""
        tiles, links = _graph(n=(6, 5), seed=5)
        index = {k: i for i, k in enumerate(tiles)}
        T = len(tiles)
        real_zeros = np.zeros

        def guarded(shape, *a, **k):
            if isinstance(shape, tuple) and len(shape) == 2 \
                    and shape[0] == T and shape[1] == T:
                raise AssertionError("dense (T,T) allocation in warm start")
            return real_zeros(shape, *a, **k)

        monkeypatch.setattr(np, "zeros", guarded)
        S._direct_translations(links, index, np.array([0]), T)


# ------------------------------------------------------- intensity CG


class TestIntensityDevice:
    def _system(self, seed=0, n_views=3, dims=(4, 4, 4), n_matches=300):
        rng = np.random.default_rng(seed)
        ncell = int(np.prod(dims))
        C = ncell * n_views
        matches = []
        for _ in range(n_matches):
            ca, cb = rng.integers(0, C, 2)
            if ca == cb:
                continue
            x = rng.uniform(100, 1000, 50)
            a, b = rng.uniform(0.8, 1.2), rng.uniform(-20, 20)
            y = a * x + b + rng.normal(0, 5, 50)
            matches.append((int(ca), int(cb),
                            *match_stats(x / 500, y / 500)))
        return C, matches, smoothness_pairs(dims, n_views)

    def test_cg_matches_dense_solve(self):
        C, matches, smooth = self._system()
        dense = solve_intensity_coefficients(C, matches, 0.1,
                                             smooth_pairs=smooth,
                                             backend="numpy")
        dev = solve_intensity_coefficients(C, matches, 0.1,
                                           smooth_pairs=smooth,
                                           backend="device")
        # documented tolerance: CG converges to the direct solve ≤1e-6
        np.testing.assert_allclose(dev, dense, rtol=1e-6, atol=1e-6)

    def test_sharded_matches_single(self):
        C, matches, smooth = self._system(seed=1)
        dev = solve_intensity_coefficients(C, matches, 0.1,
                                           smooth_pairs=smooth,
                                           backend="device")
        with config.overrides({"BST_SOLVE_SHARD": 1}):
            sh = solve_intensity_coefficients(C, matches, 0.1,
                                              smooth_pairs=smooth,
                                              backend="device")
        np.testing.assert_allclose(sh, dev, rtol=1e-8, atol=1e-8)

    def test_unmatched_cells_solve_to_identity(self):
        out = solve_intensity_coefficients(16, [], 0.1, backend="device")
        np.testing.assert_allclose(out[:, 0], 1.0)
        np.testing.assert_allclose(out[:, 1], 0.0)

    def test_device_metrics_and_spans(self):
        trace.configure(buffer_bytes=1 << 20)
        C, matches, smooth = self._system(seed=2, n_matches=100)
        ms = _metrics.counter("bst_solve_device_ms_total",
                              stage="intensity")
        before = ms.value
        solve_intensity_coefficients(C, matches, 0.1, smooth_pairs=smooth,
                                     backend="device")
        assert ms.value > before
        names = [e["name"] for e in trace.snapshot() if e["ph"] == "B"]
        assert names.count("solve.relax") == 1
        assert names.count("solve.reduce") == 1


class TestSmoothnessPairs:
    def _reference_loop(self, dims, n_views):
        ncell = int(np.prod(dims))
        smooth = []
        strides = (dims[1] * dims[2], dims[2], 1)
        for vi in range(n_views):
            b = vi * ncell
            for cx in range(dims[0]):
                for cy in range(dims[1]):
                    for cz in range(dims[2]):
                        c = (cx * dims[1] + cy) * dims[2] + cz
                        for d, n_d in enumerate(dims):
                            if (c // strides[d]) % n_d + 1 < n_d:
                                smooth.append((b + c, b + c + strides[d]))
        return smooth

    @pytest.mark.parametrize("dims,n_views", [
        ((8, 8, 8), 2), ((3, 4, 5), 3), ((1, 1, 1), 2), ((2, 1, 3), 1),
    ])
    def test_same_pair_set_as_reference_loop(self, dims, n_views):
        new = smoothness_pairs(dims, n_views)
        old = self._reference_loop(dims, n_views)
        assert len(new) == len(old)
        assert set(map(tuple, new.tolist())) == set(old)


# ------------------------------------------------------- pipeline round


def test_registration_pipeline_detect_match_solve(tmp_path):
    """The dag/spec.py registration round: detect → match → solve as ONE
    streamed pipeline job, the solver barrier-gated on the matcher's
    stored correspondences, optimized registrations written to the XML."""
    from bigstitcher_spark_tpu.dag import (
        PipelineSpec,
        registration_spec,
        run_pipeline,
    )
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(80, 80, 40),
        overlap=28, jitter=2.0, seed=6, n_beads_per_tile=35,
    )
    d = registration_spec(proj.xml_path)
    # small-fixture matcher settings (the spec's defaults target real data)
    d["stages"][1]["args"] += ["--ransacMinNumInliers", "5",
                               "--ransacIterations", "2000"]
    spec = PipelineSpec.from_dict(d)
    res = run_pipeline(spec, workdir=str(tmp_path))
    assert res.ok, res.stages
    assert [s["state"] for s in res.stages] == ["done"] * 3
    sd = SpimData.load(proj.xml_path)
    chain = sd.registrations[ViewId(0, 1)]
    assert any("[ip]" in t.name for t in chain), [t.name for t in chain]
    # the solve recovered the jittered offset: both tiles end up on the
    # true grid up to the fixed tile's shared residual
    resid = {v.setup: sd.model(v)[:, 3] - proj.true_offsets[v.setup]
             for v in sd.view_ids()}
    np.testing.assert_allclose(resid[1], resid[0], atol=0.5)


def test_registration_spec_validates_and_inits(tmp_path):
    from click.testing import CliRunner

    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.dag import PipelineSpec, registration_spec
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
        overlap=16, seed=1, n_beads_per_tile=10,
    )
    spec = PipelineSpec.from_dict(registration_spec(proj.xml_path))
    by_id = {s.id: s for s in spec.stages}
    assert spec.barrier_parents(by_id["solve"]) == {"match"}
    assert spec.barrier_parents(by_id["match"]) == {"detect"}
    out = str(tmp_path / "reg.json")
    res = CliRunner().invoke(cli, [
        "pipeline", "init", out, "-x", proj.xml_path, "--registration",
        "--label", "beads"])
    assert res.exit_code == 0, res.output
    loaded = PipelineSpec.load(out)
    assert [s.tool for s in loaded.stages] == [
        "detect-interestpoints", "match-interestpoints", "solver"]

"""Phase-correlation stitching: kernel golden tests + ground-truth recovery
on the synthetic tiled project (reference: SparkPairwiseStitching; the
synthetic grid with known true/nominal offsets replaces the S3 fixture)."""

import numpy as np
import jax.numpy as jnp
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData
from bigstitcher_spark_tpu.models.stitching import (
    StitchingParams,
    build_groups,
    plan_pairs,
    stitch_all_pairs,
)
from bigstitcher_spark_tpu.ops.phasecorr import pad_to, stitch_crops


def _smooth_noise(shape, seed=0, sigma=2.0):
    from scipy.ndimage import gaussian_filter

    rng = np.random.default_rng(seed)
    return gaussian_filter(
        rng.normal(100, 20, shape).astype(np.float32), sigma
    )


def test_kernel_integer_shift():
    base = _smooth_noise((80, 80, 40))
    d = np.array([5, -3, 2])
    a = base[10:58, 10:58, 8:32]
    b = base[10 - d[0]:58 - d[0], 10 - d[1]:58 - d[1], 8 - d[2]:32 - d[2]]
    P = (64, 64, 32)
    s, r = stitch_crops(pad_to(a, P), pad_to(b, P),
                        jnp.array(a.shape, jnp.int32),
                        jnp.array(b.shape, jnp.int32))
    assert np.allclose(np.asarray(s), d, atol=0.3)
    assert float(r) > 0.95


def test_kernel_subpixel_shift():
    from scipy.ndimage import shift as ndshift

    base = _smooth_noise((80, 80, 40))
    d = np.array([2.3, -1.7, 0.5])
    a = base[10:58, 10:58, 8:32]
    b = ndshift(base, d, order=3)[10:58, 10:58, 8:32]
    P = (64, 64, 32)
    s, r = stitch_crops(pad_to(a, P), pad_to(b, P),
                        jnp.array(a.shape, jnp.int32),
                        jnp.array(b.shape, jnp.int32))
    assert np.allclose(np.asarray(s), d, atol=0.35)


def test_kernel_rejects_noise():
    a = _smooth_noise((48, 48, 24), seed=1)
    b = _smooth_noise((48, 48, 24), seed=2)
    P = (64, 64, 32)
    s, r = stitch_crops(pad_to(a, P), pad_to(b, P),
                        jnp.array(a.shape, jnp.int32),
                        jnp.array(b.shape, jnp.int32))
    assert float(r) < 0.5


@pytest.fixture(scope="module")
def stitch_project(tmp_path_factory):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(
        str(tmp_path_factory.mktemp("stitch") / "proj"),
        n_tiles=(2, 2, 1), tile_size=(96, 96, 48), overlap=28,
        jitter=3.0, seed=3, n_beads_per_tile=60,
    )


def test_pair_planning(stitch_project):
    sd = SpimData.load(stitch_project.xml_path)
    groups = build_groups(sd, sd.view_ids())
    assert len(groups) == 4  # 2x2 tiles, 1 channel
    pairs = plan_pairs(sd, groups)
    # 4 edge-adjacent + 2 diagonal corner overlaps
    assert len(pairs) >= 4


def test_stitching_recovers_ground_truth(stitch_project):
    proj = stitch_project
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)
    results = stitch_all_pairs(sd, loader, sd.view_ids(),
                               StitchingParams(downsampling=(1, 1, 1)))
    assert len(results) >= 4
    checked = 0
    for res in results:
        sa = res.views_a[0].setup
        sb = res.views_b[0].setup
        e_a = proj.true_offsets[sa] - proj.nominal_offsets[sa]
        e_b = proj.true_offsets[sb] - proj.nominal_offsets[sb]
        expected = e_a - e_b  # c_A - c_B convention
        shift = res.transform[:, 3]
        if res.correlation > 0.5:  # diagonal corner overlaps may be tiny
            np.testing.assert_allclose(shift, expected, atol=0.75)
            checked += 1
    assert checked >= 4


def test_uint16_transport_is_bit_identical():
    """The lossless h2d downcast (integral float32 crops sent as uint16,
    cast back on device) must produce exactly the same peaks, and must
    not engage for fractional crops (channel averages)."""
    from bigstitcher_spark_tpu.models.stitching import _as_uint16_lossless
    from bigstitcher_spark_tpu.ops.phasecorr import pcm_peaks_batch

    rng = np.random.RandomState(1)
    crop = rng.randint(0, 60000, (2, 16, 64, 64)).astype(np.float32)
    ext = np.tile(np.array([16, 64, 64], np.int32), (2, 1))
    pk_f = np.asarray(pcm_peaks_batch(jnp.asarray(crop), jnp.asarray(crop),
                                      jnp.asarray(ext), jnp.asarray(ext),
                                      5, 0.25))
    t = _as_uint16_lossless(crop)
    assert t is not None and t.dtype == np.uint16
    pk_u = np.asarray(pcm_peaks_batch(jnp.asarray(t), jnp.asarray(t),
                                      jnp.asarray(ext), jnp.asarray(ext),
                                      5, 0.25))
    np.testing.assert_array_equal(pk_f, pk_u)
    assert _as_uint16_lossless(crop + 0.5) is None      # fractional
    assert _as_uint16_lossless(crop - 1e6) is None      # negative
    assert _as_uint16_lossless(crop + 1e6) is None      # out of range


def test_segmented_pipeline_matches_single_segment(stitch_project):
    """A tiny inflight_bytes budget forces one segment per chunk (max
    round-trips); results must be identical to the default single-segment
    run — the segmentation is a scheduling choice, not a math change.
    Pinned to one device: with the mesh spread each device drains its own
    segments, so the global sync count stops being the budget's signal."""
    from bigstitcher_spark_tpu import profiling

    proj = stitch_project
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)

    def run_counting_segments(params):
        profiling.enable(True)
        profiling.get().reset()
        try:
            res = stitch_all_pairs(sd, loader, sd.view_ids(), params,
                                   devices=1)
        finally:
            profiling.enable(False)
        segs = profiling.get().stats()["stitching.kernel_sync"].count
        return res, segs

    one, segs_one = run_counting_segments(
        StitchingParams(downsampling=(1, 1, 1)))
    many, segs_many = run_counting_segments(
        StitchingParams(downsampling=(1, 1, 1), inflight_bytes=1))
    # the scheduling must actually differ, or this test compares a run
    # against itself
    assert segs_many > segs_one >= 1
    assert len(one) == len(many)
    key = lambda r: r.pair_key
    for a, b in zip(sorted(one, key=key), sorted(many, key=key)):
        assert key(a) == key(b)
        np.testing.assert_allclose(a.transform, b.transform, atol=1e-12)
        np.testing.assert_allclose(a.correlation, b.correlation, atol=1e-12)


def test_stitching_downsampled_still_recovers(stitch_project):
    proj = stitch_project
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)
    results = stitch_all_pairs(sd, loader, sd.view_ids(),
                               StitchingParams(downsampling=(2, 2, 1)))
    good = 0
    for res in results:
        sa, sb = res.views_a[0].setup, res.views_b[0].setup
        expected = ((proj.true_offsets[sa] - proj.nominal_offsets[sa])
                    - (proj.true_offsets[sb] - proj.nominal_offsets[sb]))
        if res.correlation > 0.5:
            np.testing.assert_allclose(res.transform[:, 3], expected, atol=1.5)
            good += 1
    assert good >= 4


def test_stitching_reads_stored_mipmap_level(tmp_path):
    """With a stored 2,2,1 level and ds=2,2,1 the crops come from s1
    (residual 1,1,1) and ground truth is still recovered."""
    from unittest import mock

    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(96, 96, 48),
        overlap=28, jitter=3.0, seed=5,
        downsampling_factors=((1, 1, 1), (2, 2, 1)),
    )
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)
    levels_read = []
    orig = ViewLoader.read_block

    def spy(self, view, level, offset, shape, pad_value=0.0):
        levels_read.append(level)
        return orig(self, view, level, offset, shape, pad_value)

    with mock.patch.object(ViewLoader, "read_block", spy):
        results = stitch_all_pairs(sd, loader, sd.view_ids(),
                                   StitchingParams(downsampling=(2, 2, 1)))
    assert levels_read and all(lv == 1 for lv in levels_read)
    (res,) = results
    sa, sb = res.views_a[0].setup, res.views_b[0].setup
    expected = ((proj.true_offsets[sa] - proj.nominal_offsets[sa])
                - (proj.true_offsets[sb] - proj.nominal_offsets[sb]))
    np.testing.assert_allclose(res.transform[:, 3], expected, atol=1.5)


def test_stitching_cli_writes_results(stitch_project):
    runner = CliRunner()
    res = runner.invoke(cli, [
        "stitching", "-x", stitch_project.xml_path, "-ds", "1,1,1",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output
    sd = SpimData.load(stitch_project.xml_path)
    assert len(sd.stitching_results) >= 4
    for res_ in sd.stitching_results.values():
        assert res_.hash != 0.0
        assert res_.correlation > 0.3


class TestNonEqualTransformPath:
    """Rendered-overlap stitching when linear parts differ
    (computeStitchingNonEqualTransformations role,
    SparkPairwiseStitching.java:259-267): one tile registered with a small
    z-rotation, content generated with a known world translation error —
    the rendered path must recover that error (VERDICT r3 item 5)."""

    @pytest.fixture(scope="class")
    def rotated_project(self, tmp_path_factory):
        from scipy.ndimage import affine_transform

        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import create_bdv_view_datasets
        from bigstitcher_spark_tpu.io.spimdata import (
            AttributeEntity, ImageLoader, SpimData as SD, ViewId, ViewSetup,
            ViewTransform,
        )
        from bigstitcher_spark_tpu.utils.geometry import translation_affine
        from bigstitcher_spark_tpu.utils.testdata import make_bead_volume

        out = tmp_path_factory.mktemp("rotproj")
        world, _ = make_bead_volume((120, 96, 40), n_beads=160, seed=21)
        tile_size = (72, 96, 40)
        theta = np.deg2rad(3.0)
        rot = np.array([[np.cos(theta), -np.sin(theta), 0.0],
                        [np.sin(theta), np.cos(theta), 0.0],
                        [0.0, 0.0, 1.0]])
        t_b = np.array([44.0, 0.0, 0.0])
        err = np.array([2.0, -1.0, 1.0])  # world error baked into B's content

        # view A: identity registration, exact content
        img_a = world[:tile_size[0], :, :]
        # view B content sampled at M_B_true(p) = rot @ p + t_b + err
        img_b = affine_transform(world, rot, offset=t_b + err,
                                 output_shape=tile_size, order=1)
        noise = np.random.default_rng(3).normal(0, 4.0, tile_size)

        store = ChunkStore.create(str(out / "dataset.n5"), StorageFormat.N5)
        sd = SD()
        sd.image_loader = ImageLoader(format="bdv.n5", path="dataset.n5")
        sd.timepoints = [0]
        sd.attributes["illumination"][0] = AttributeEntity(0, "0")
        sd.attributes["angle"][0] = AttributeEntity(0, "0")
        sd.attributes["channel"][0] = AttributeEntity(0, "0")
        for tid in (0, 1):
            sd.attributes["tile"][tid] = AttributeEntity(tid, str(tid))
        for sid, img in ((0, img_a), (1, img_b)):
            sd.setups[sid] = ViewSetup(
                id=sid, name=f"tile{sid}", size=tile_size,
                attributes={"illumination": 0, "channel": 0, "tile": sid,
                            "angle": 0})
            ds = create_bdv_view_datasets(store, sid, 0, tile_size,
                                          (32, 32, 16), "uint16")
            arr = np.clip(img + noise, 0, 65535).astype(np.uint16)
            ds[0].write(arr, (0, 0, 0))
        sd.registrations[ViewId(0, 0)] = [
            ViewTransform("identity", translation_affine((0, 0, 0)))]
        m_b = np.hstack([rot, t_b.reshape(3, 1)])
        sd.registrations[ViewId(0, 1)] = [ViewTransform("rigid", m_b)]
        xml = str(out / "dataset.xml")
        sd.save(xml)
        return xml, err

    def test_rendered_path_recovers_known_error(self, rotated_project):
        xml, err = rotated_project
        sd = SpimData.load(xml)
        loader = ViewLoader(sd)
        from bigstitcher_spark_tpu.models.stitching import _extract_pair_job

        groups = build_groups(sd, sd.view_ids())
        pairs = plan_pairs(sd, groups)
        assert len(pairs) == 1
        job = _extract_pair_job(sd, loader, *pairs[0],
                                StitchingParams(downsampling=(1, 1, 1)))
        assert job is not None and job.linear is None, \
            "rotation must route to the rendered (non-equal-transform) path"
        results = stitch_all_pairs(sd, loader, sd.view_ids(),
                                   StitchingParams(downsampling=(1, 1, 1)))
        assert len(results) == 1
        res = results[0]
        assert res.correlation > 0.5
        # rendered A(w)=W(w), rendered B(w)=W(w+err): expected S = -err
        # (c_A - c_B convention, same as the equal-transform tests above)
        np.testing.assert_allclose(res.transform[:, 3], -err, atol=1.0)

    def test_rendered_path_downsampled(self, rotated_project):
        xml, err = rotated_project
        sd = SpimData.load(xml)
        loader = ViewLoader(sd)
        results = stitch_all_pairs(sd, loader, sd.view_ids(),
                                   StitchingParams(downsampling=(2, 2, 1)))
        assert len(results) == 1
        assert results[0].correlation > 0.5
        np.testing.assert_allclose(results[0].transform[:, 3], -err, atol=2.0)


class TestResultFilters:
    """Link filters (FilteredStitchingResults: Correlation, AbsoluteShift,
    ShiftMagnitude — SparkPairwiseStitching.java:347-382)."""

    @staticmethod
    def _mk(shift, r):
        from bigstitcher_spark_tpu.io.spimdata import (
            PairwiseStitchingResult, ViewId,
        )
        from bigstitcher_spark_tpu.utils.geometry import translation_affine

        return PairwiseStitchingResult(
            views_a=(ViewId(0, 0),), views_b=(ViewId(0, 1),),
            transform=translation_affine(shift), correlation=r, hash=0.5)

    def test_min_r_filter(self):
        from bigstitcher_spark_tpu.models.stitching import filter_results

        res = [self._mk((1, 0, 0), 0.9), self._mk((2, 0, 0), 0.2)]
        kept = filter_results(res, StitchingParams(min_r=0.5))
        assert len(kept) == 1 and kept[0].correlation == 0.9

    def test_max_shift_filters(self):
        from bigstitcher_spark_tpu.models.stitching import filter_results

        res = [self._mk((1.0, 1.0, 0.0), 0.9),
               self._mk((11.0, 0.0, 0.0), 0.9),  # per-axis only (norm 11 < 12)
               self._mk((8.0, 8.0, 8.0), 0.9)]   # magnitude only (8*sqrt3 > 12)
        kept = filter_results(
            res, StitchingParams(max_shift=(10.0, 10.0, 10.0),
                                 max_shift_total=12.0))
        assert len(kept) == 1
        assert tuple(kept[0].transform[:, 3]) == (1.0, 1.0, 0.0)

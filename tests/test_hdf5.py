"""bdv.hdf5 input loader + HDF5 fusion container (VERDICT r3 item 4).

The reference ingests HDF5-backed BigStitcher projects through bdv
imgloaders (SparkResaveN5.java:107-457) and creates BDV-HDF5 fusion
containers (CreateFusionContainer.java:462-487), restricted to local
storage (:141-145). These tests build a classic BDV-HDF5 project
(t{TTTTT}/s{SS}/{L}/cells + resolutions/subdivisions), read it back,
resave it to N5, and fuse into an HDF5 container.
"""

import os

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.chunkstore import Hdf5Store, StorageFormat
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import ImageLoader, SpimData
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project


@pytest.fixture(scope="module")
def hdf5_project(tmp_path_factory):
    """Synthetic project converted to a classic BDV-HDF5 container."""
    root = tmp_path_factory.mktemp("h5proj")
    proj = make_synthetic_project(
        str(root / "proj"), n_tiles=(2, 1, 1), tile_size=(32, 24, 12),
        overlap=8, jitter=1.0, seed=3, n_beads_per_tile=10)
    sd = SpimData.load(proj.xml_path)
    n5_loader = ViewLoader(sd)
    h5path = str(root / "proj" / "dataset.h5")
    store = Hdf5Store(h5path, mode="w")
    for v in sd.view_ids():
        img = n5_loader.open(v, 0).read_full()
        store.put_array(f"s{v.setup:02d}/resolutions",
                        np.asarray([[1.0, 1.0, 1.0]]))
        store.put_array(f"s{v.setup:02d}/subdivisions",
                        np.asarray([[16, 16, 8]], np.int32))
        ds = store.create_dataset(
            f"t{v.timepoint:05d}/s{v.setup:02d}/0/cells",
            img.shape, (16, 16, 8), img.dtype, compression="gzip")
        ds.write(img, (0, 0, 0))
    store.close()
    sd.image_loader = ImageLoader(format="bdv.hdf5", path="dataset.h5")
    sd.save()
    return proj, h5path


def test_hdf5_loader_reads_back(hdf5_project):
    proj, h5path = hdf5_project
    sd = SpimData.load(proj.xml_path)
    assert sd.image_loader.format == "bdv.hdf5"
    loader = ViewLoader(sd)
    assert loader.is_hdf5
    for v in sd.view_ids():
        img = loader.open(v, 0).read_full()
        assert img.shape == tuple(sd.view_size(v))
        assert img.std() > 0
        assert loader.downsampling_factors(v.setup) == [[1, 1, 1]]
    # halo over-read pads with zeros
    blk = loader.read_block(sd.view_ids()[0], 0, (-4, 0, 0), (8, 8, 8))
    assert (blk[:4] == 0).all() and blk[4:].std() > 0


def test_resave_from_hdf5(hdf5_project, tmp_path):
    """resave ingests a bdv.hdf5 project and rewrites it as bdv.n5
    (the reference's legacy-input entry point, SparkResaveN5.java:107-457)."""
    from click.testing import CliRunner

    proj, h5path = hdf5_project
    sd_in = SpimData.load(proj.xml_path)
    loader_in = ViewLoader(sd_in)
    originals = {v: loader_in.open(v, 0).read_full() for v in sd_in.view_ids()}

    from bigstitcher_spark_tpu.cli.main import cli

    out_xml = str(tmp_path / "resaved.xml")
    r = CliRunner().invoke(cli, [
        "resave", "-x", proj.xml_path, "-xo", out_xml,
        "-o", str(tmp_path / "resaved.n5"), "--N5",
        "-ds", "1,1,1", "--blockSize", "16,16,8",
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    sd_out = SpimData.load(out_xml)
    assert sd_out.image_loader.format == "bdv.n5"
    loader_out = ViewLoader(sd_out)
    for v, img in originals.items():
        assert (loader_out.open(v, 0).read_full() == img).all()


def test_fuse_to_hdf5(hdf5_project, tmp_path):
    """create-fusion-container -s HDF5 + affine-fusion round trip; output
    agrees with the same fusion into an N5 container."""
    from click.testing import CliRunner

    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.container import (
        open_container, read_container_meta,
    )

    proj, _ = hdf5_project
    runner = CliRunner()
    h5out = str(tmp_path / "fused.h5")
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", h5out,
        "-s", "HDF5", "-d", "UINT16", "--blockSize", "16,16,8",
        "--minIntensity", "0", "--maxIntensity", "65535",
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["affine-fusion", "-o", h5out],
                      catch_exceptions=False)
    assert r.exit_code == 0, r.output

    n5out = str(tmp_path / "fused.n5")
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", n5out,
        "-s", "N5", "-d", "UINT16", "--blockSize", "16,16,8",
        "--minIntensity", "0", "--maxIntensity", "65535",
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["affine-fusion", "-o", n5out],
                      catch_exceptions=False)
    assert r.exit_code == 0, r.output

    h5store = open_container(h5out)
    meta = read_container_meta(h5store)
    assert meta.fusion_format == "HDF5"
    got = h5store.open_dataset(meta.mr_infos[0][0].dataset).read_full()
    n5store = open_container(n5out)
    meta5 = read_container_meta(n5store)
    want = n5store.open_dataset(meta5.mr_infos[0][0].dataset).read_full()
    assert got.std() > 0
    assert (got == want).all()


def test_bdv_hdf5_container_layout(hdf5_project, tmp_path):
    """--bdv HDF5 containers use the classic BDV cell layout + tables."""
    from click.testing import CliRunner

    from bigstitcher_spark_tpu.cli.main import cli

    proj, _ = hdf5_project
    out = str(tmp_path / "bdv.h5")
    r = CliRunner().invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "HDF5", "-d", "UINT16", "--bdv",
        "--blockSize", "16,16,8",
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    store = Hdf5Store(out, mode="r")
    assert store.exists("t00000/s00/0/cells")
    assert store.get_array("s00/resolutions").shape[1] == 3
    assert store.get_array("s00/subdivisions").tolist()[0] == [16, 16, 8]
    # the companion XML points at the hdf5 loader
    sd = SpimData.load(out + ".xml")
    assert sd.image_loader.format == "bdv.hdf5"


def test_hdf5_is_local_only():
    with pytest.raises(ValueError, match="local-only"):
        Hdf5Store("s3://bucket/x.h5")

"""CLI flag parity: every ACTIVE option spelling of the reference's picocli
surface must be accepted by the corresponding tool here (extracted from the
reference @Option declarations, commented-out options excluded — e.g.
--firstTileWins and the Solver mapback options are disabled upstream).

A reference user's scripts must run unchanged (drop-in goal, SURVEY.md §7).
"""

import pytest

from bigstitcher_spark_tpu.cli.main import cli

# tool -> active reference option spellings (source files under
# /root/reference/src/main/java/net/preibisch/bigstitcher/spark/)
REFERENCE_OPTIONS = {
    # SparkAffineFusion.java
    "affine-fusion": (
        "-o --n5Path -s --storage --masks -f --fusion -t --timepointIndex "
        "-c --channelIndex --angleId --tileId --illuminationId --channelId "
        "--timepointId -vi --prefetch"
    ),
    # CreateFusionContainer.java
    "create-fusion-container": (
        "-o --outputPath -s --storage -c --compression -cl "
        "--compressionLevel -ch --numChannels -tp --numTimepoints -d "
        "--dataType --minIntensity --maxIntensity --bdv -xo --xmlout -b "
        "--boundingBox --multiRes -ds --downsampling --preserveAnisotropy "
        "--anisotropyFactor"
    ),
    # SparkResaveN5.java
    "resave": "-xo --xmlout --N5 -ds --downsampling -c --compression -cl "
              "--compressionLevel -o --n5Path",
    # SparkInterestPointDetection.java
    "detect-interestpoints": (
        "-l --label -s --sigma -t --threshold --type --localization "
        "--overlappingOnly --onlyCompareOverlapTiles --storeIntensities "
        "-i0 --minIntensity -i1 --maxIntensity --prefetch --keepTemporaryN5 "
        "--maxSpots --maxSpotsPerOverlap --medianFilter -dsxy --downsampleXY "
        "-dsz --downsampleZ"
    ),
    # SparkGeometricDescriptorMatching.java
    "match-interestpoints": (
        "-l --label -m --method -s --significance -sr --searchRadius -r "
        "--redundancy -n --numNeighbors --clearCorrespondences "
        "--matchAcrossLabels -ipfr --interestpointsForReg -vr --viewReg "
        "--interestPointMergeDistance --groupIllums --groupChannels "
        "--groupTiles --splitTimepoints -rit --ransacIterations -rme "
        "--ransacMaxError -rmir --ransacMinInlierRatio -rmni "
        "--ransacMinNumInliers -rmc --ransacMultiConsensus -ime "
        "--icpMaxError -iit --icpIterations --icpUseRANSAC"
    ),
    # SparkPairwiseStitching.java
    "stitching": (
        "-ds --downsampling -p --peaksToCheck --disableSubpixelResolution "
        "--minR --maxR --maxShiftX --maxShiftY --maxShiftZ --maxShiftTotal "
        "--channelCombine --illumCombine"
    ),
    # Solver.java (mapback options are commented out upstream)
    "solver": (
        "-s --sourcePoints --groupIllums --groupChannels --groupTiles "
        "--splitTimepoints -l --label -lw --labelweights --method "
        "--relativeThreshold --absoluteThreshold --maxError --maxIterations "
        "--maxPlateauwidth --disableFixedViews -fv --fixedViews"
    ),
    # SparkNonRigidFusion.java
    "nonrigid-fusion": (
        "-o --n5Path -d --n5Dataset --bdv -xo --xmlout -s --storage -b "
        "--boundingBox -ip --interestPoints -p --dataType --minIntensity "
        "--maxIntensity"
    ),
    # SparkIntensityMatching.java
    "match-intensities": (
        "--numCoefficients --renderScale -o --outputPath --minThreshold "
        "--maxThreshold --minNumCandidates --method --numIterations "
        "--maxEpsilon --minInlierRatio --minNumInliers --maxTrust"
    ),
    # IntensitySolver.java
    "solve-intensities": (
        "--numCoefficients --matchesPath --maxIterations -o "
        "--intensityN5Path -s --intensityN5Storage --intensityN5Group "
        "--intensityN5Dataset"
    ),
    # SparkDownsample.java
    "downsample": "-i --n5PathIn -di --n5DatasetIn -do --n5DatasetsOut "
                  "-s --storage -ds --downsampling",
    # SplitDatasets.java
    "split-images": (
        "-xo --xmlout -tis --targetImageSize -to --targetOverlap "
        "--disableOptimization -fip --fakeInterestPoints --fipDensity "
        "--fipMinNumPoints --fipMaxNumPoints --fipError "
        "--fipExclusionRadius --assignIlluminations --displayResult"
    ),
    # TransformPoints.java
    "transform-points": "-vi --csvIn -p --csvOut",
    # ClearInterestPoints.java
    "clear-interestpoints": "--correspondencesOnly",
    # ClearRegistrations.java
    "clear-registrations": "--keep --remove",
}

# shared infrastructure options (AbstractInfrastructure / AbstractBasic)
SHARED = "--dryRun --s3Region"


@pytest.mark.parametrize("tool", sorted(REFERENCE_OPTIONS))
def test_reference_options_accepted(tool):
    cmd = cli.commands[tool]
    ours = set()
    for p in cmd.params:
        ours.update(p.opts)
        ours.update(p.secondary_opts)
    missing = [o for o in REFERENCE_OPTIONS[tool].split() if o not in ours]
    assert not missing, f"{tool} missing reference options: {missing}"


@pytest.mark.parametrize("tool", sorted(REFERENCE_OPTIONS))
def test_shared_infrastructure_options(tool):
    if tool in ("transform-points", "clear-registrations", "downsample",
                "split-images", "inspect-interestpoints"):
        pytest.skip("minimal per-reference surface")
    cmd = cli.commands[tool]
    ours = set()
    for p in cmd.params:
        ours.update(p.opts)
        ours.update(p.secondary_opts)
    missing = [o for o in SHARED.split() if o not in ours]
    assert not missing, f"{tool} missing shared options: {missing}"

"""Fused multiscale epilogue + per-device direct chunk writes (ROADMAP
item 3).

Acceptance contract of the single-drain PR: epilogue-produced pyramid
levels are BIT-IDENTICAL to the container-reread ``downsample_pyramid_level``
path (all rel-factor shapes, incl. anisotropic and thin-axis edge-pad);
with the epilogue on, the full-res volume crosses the wire exactly once
(trace-counted) and total D2H stays within 1.2x of the full-res-only
drain; in sharded fusion the driver thread performs zero ``fusion.write``
spans — every write is attributed to a device worker track, each device
writes only its own disjoint chunks, and write-generations stay
consistent.
"""

import os
import threading

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import profiling
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.container import (
    create_fusion_container,
    epilogue_written,
    read_container_meta,
)
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData
from bigstitcher_spark_tpu.models.affine_fusion import (
    PyramidLevel,
    eligible_epilogue_levels,
    fuse_volume,
)
from bigstitcher_spark_tpu.models.downsample_driver import (
    downsample_pyramid_level,
)
from bigstitcher_spark_tpu.observe import trace
from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()
    yield
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()


@pytest.fixture(scope="module")
def project(tmp_path_factory):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(
        str(tmp_path_factory.mktemp("epi") / "proj"),
        n_tiles=(2, 2, 1), tile_size=(48, 48, 24), overlap=12,
        jitter=2.0, seed=13, block_size=(16, 16, 8), n_beads_per_tile=15,
    )


def _setup(project):
    sd = SpimData.load(project.xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    return sd, loader, views, bbox


def _container(path, xml, bbox, steps, block=(16, 16, 8)):
    create_fusion_container(
        str(path), StorageFormat.ZARR, xml, 1, 1, bbox,
        data_type="uint16", block_size=block, downsamplings=steps,
        min_intensity=0.0, max_intensity=65535.0)
    store = ChunkStore.open(str(path))
    return store, read_container_meta(store).mr_infos[0]


def _pyramid(store, mr):
    return [PyramidLevel(
        ds=store.open_dataset(mr[lvl].dataset.strip("/")),
        rel=tuple(int(v) for v in mr[lvl].relativeDownsampling[:3]),
        abs_factor=tuple(int(v) for v in mr[lvl].absoluteDownsampling[:3]),
        dims=tuple(int(v) for v in mr[lvl].dimensions[:3]),
    ) for lvl in range(1, len(mr))]


def _fuse(sd, loader, views, bbox, store, mr, *, pyramid=False, **kw):
    ds = store.open_dataset(mr[0].dataset.strip("/"))
    return fuse_volume(
        sd, loader, views, ds, bbox, block_size=(16, 16, 8),
        block_scale=(2, 2, 1), out_dtype="uint16", min_intensity=0.0,
        max_intensity=65535.0, zarr_ct=(0, 0),
        pyramid=_pyramid(store, mr) if pyramid else None, **kw)


def _reread_levels(store, mr, start=1):
    for lvl in range(start, len(mr)):
        downsample_pyramid_level(store, mr[lvl - 1], mr[lvl], True, (0, 0))


def _reread_reference(tmp_path, name, xml, bbox, steps, src_store, src_mr,
                      block=(16, 16, 8)):
    """Reference container: the epilogue run's OWN s0 copied over (bit
    cheap), then every level recomputed by the container-reread driver —
    the exact flow the epilogue replaces, on identical input."""
    store, mr = _container(tmp_path / name, xml, bbox, steps, block=block)
    s0 = src_store.open_dataset(src_mr[0].dataset.strip("/")).read_full()
    store.open_dataset(mr[0].dataset.strip("/")).write(s0, (0,) * 5)
    _reread_levels(store, mr)
    return store, mr


def _levels_equal(store_a, mr_a, store_b, mr_b):
    for lvl in range(len(mr_a)):
        a = store_a.open_dataset(mr_a[lvl].dataset.strip("/")).read_full()
        b = store_b.open_dataset(mr_b[lvl].dataset.strip("/")).read_full()
        assert a.shape == b.shape
        assert (a == b).all(), f"level {lvl} diverged"
        assert a.std() > 0 or lvl == 0, f"level {lvl} empty"


ANISO_STEPS = [[1, 1, 1], [2, 2, 1], [4, 4, 2]]


class TestEpilogueParity:
    def test_composite_bit_identical_anisotropic(self, project, tmp_path,
                                                 monkeypatch):
        """Whole-volume composite epilogue vs the container-reread path,
        anisotropic rel factors (2,2,1)+(2,2,2), odd level dims."""
        monkeypatch.setenv("BST_WRITE_THREADS", "3")  # knob-path exercise
        sd, loader, views, bbox = _setup(project)
        s1, mr1 = _container(tmp_path / "epi.zarr", project.xml_path, bbox,
                             ANISO_STEPS)
        st = _fuse(sd, loader, views, bbox, s1, mr1, pyramid=True, devices=1)
        assert st.pyramid_levels == 2
        assert st.pyramid_voxels == sum(
            int(np.prod(mr1[i].dimensions[:3])) for i in (1, 2))
        assert any("composite" in str(k) for k in st.compile_keys)

        s2, mr2 = _reread_reference(tmp_path, "ref.zarr", project.xml_path,
                                    bbox, ANISO_STEPS, s1, mr1)
        _levels_equal(s1, mr1, s2, mr2)

    def test_composite_thin_axis_edge_pad(self, tmp_path_factory, tmp_path):
        """A level window wider than the axis triggers the read_padded
        edge-replication rule — the device epilogue must reproduce it."""
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path_factory.mktemp("thin") / "proj"),
            n_tiles=(2, 1, 1), tile_size=(32, 32, 6), overlap=8,
            jitter=0.0, seed=7, block_size=(16, 16, 4), n_beads_per_tile=8)
        sd, loader, views, bbox = _setup(proj)
        steps = [[1, 1, 1], [2, 2, 8]]  # z window (8) > z extent (~6)
        assert bbox.shape[2] < 8
        s1, mr1 = _container(tmp_path / "thin_epi.zarr", proj.xml_path,
                             bbox, steps, block=(16, 16, 4))
        st = fuse_volume(
            sd, loader, views,
            s1.open_dataset(mr1[0].dataset.strip("/")), bbox,
            block_size=(16, 16, 4), block_scale=(2, 2, 1),
            out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
            zarr_ct=(0, 0), pyramid=_pyramid(s1, mr1), devices=1)
        assert st.pyramid_levels == 1
        s2, mr2 = _reread_reference(tmp_path, "thin_ref.zarr",
                                    proj.xml_path, bbox, steps, s1, mr1,
                                    block=(16, 16, 4))
        _levels_equal(s1, mr1, s2, mr2)

    def test_sharded_prefix_plus_fallback_bit_identical(self, project,
                                                        tmp_path):
        """Sharded per-block epilogue materializes the chunk-aligned
        prefix (level 1 here); the reread fallback tops up the rest from
        the materialized level — everything bit-identical to the pure
        reread flow."""
        import jax

        assert len(jax.devices()) >= 8
        sd, loader, views, bbox = _setup(project)
        s1, mr1 = _container(tmp_path / "sh.zarr", project.xml_path, bbox,
                             ANISO_STEPS)
        st = _fuse(sd, loader, views, bbox, s1, mr1, pyramid=True, devices=8)
        # level 1 sub-blocks align with (16,16,8) chunks; level 2's (8,8,4)
        # pieces would straddle them -> the prefix stops there
        assert st.pyramid_levels == 1
        assert st.pyramid_voxels > 0
        _reread_levels(s1, mr1, start=1 + st.pyramid_levels)

        s2, mr2 = _reread_reference(tmp_path, "sh_ref.zarr",
                                    project.xml_path, bbox, ANISO_STEPS,
                                    s1, mr1)
        _levels_equal(s1, mr1, s2, mr2)

    def test_sharded_ineligible_factors_fall_back_whole(self, project,
                                                        tmp_path):
        """Factors that do not divide the compute block produce NO epilogue
        prefix; the reread fallback alone fills the pyramid."""
        sd, loader, views, bbox = _setup(project)
        steps = [[1, 1, 1], [3, 3, 3]]
        s1, mr1 = _container(tmp_path / "odd.zarr", project.xml_path, bbox,
                             steps)
        st = _fuse(sd, loader, views, bbox, s1, mr1, pyramid=True, devices=8)
        assert st.pyramid_levels == 0
        assert st.pyramid_voxels == 0
        _reread_levels(s1, mr1)
        lvl = s1.open_dataset(mr1[1].dataset.strip("/")).read_full()
        assert list(lvl.shape[:3]) == [int(v) for v in
                                       mr1[1].dimensions[:3]]
        assert lvl.std() > 0

    def test_eligibility_rules(self, project, tmp_path):
        sd, loader, views, bbox = _setup(project)
        store, mr = _container(tmp_path / "elig.zarr", project.xml_path,
                               bbox, ANISO_STEPS)
        pyr = _pyramid(store, mr)
        # compute block (32,32,8): level 1 (2,2,1) divides and aligns with
        # the (16,16,8) chunks; level 2 (4,4,2) divides but its (8,8,4)
        # piece straddles chunks -> prefix of 1
        assert len(eligible_epilogue_levels(pyr, (32, 32, 8),
                                            bbox.shape)) == 1
        # a factor wider than the axis is never block-local
        thin = [PyramidLevel(ds=pyr[0].ds, rel=(2, 2, 64),
                             abs_factor=(2, 2, 64), dims=(43, 43, 1))]
        assert eligible_epilogue_levels(thin, (32, 32, 64),
                                        bbox.shape) == []


class TestSingleDrain:
    def test_one_full_res_d2h_pass_trace_counted(self, project, tmp_path):
        """Tier-1 acceptance: with the epilogue on, exactly ONE full-res
        pass crosses the wire under ``fusion.d2h`` (slab nbytes sum to the
        volume exactly), the pyramid rides as ``fusion.epilogue.*``, and
        total fusion D2H stays <= 1.2x the full-res-only drain."""
        from bigstitcher_spark_tpu.observe import metrics

        sd, loader, views, bbox = _setup(project)
        steps = [[1, 1, 1], [2, 2, 2], [4, 4, 4]]
        store, mr = _container(tmp_path / "drain.zarr", project.xml_path,
                               bbox, steps)
        trace.configure(buffer_bytes=8 << 20)
        base = metrics.get_registry().snapshot()
        st = _fuse(sd, loader, views, bbox, store, mr, pyramid=True,
                   devices=1)
        assert st.pyramid_levels == 2
        delta = metrics.get_registry().snapshot_delta(base)
        snap = trace.snapshot()

        full_bytes = int(np.prod(bbox.shape)) * 2  # uint16
        d2h = [e for e in snap if e["name"] == "fusion.d2h"
               and e["ph"] == "B"]
        assert sum(e["nbytes"] for e in d2h) == full_bytes
        epi_d2h = [e for e in snap if e["name"] == "fusion.epilogue.d2h"
                   and e["ph"] == "B"]
        epi_bytes = sum(e["nbytes"] for e in epi_d2h)
        assert 0 < epi_bytes <= 0.2 * full_bytes
        assert any(e["name"] == "fusion.epilogue.write" for e in snap)
        # the registry agrees with the trace: one full-res pass + pyramid
        xfer = next(v for k, v in delta.items()
                    if k.startswith("bst_xfer_d2h_bytes_total"))
        assert xfer <= 1.2 * full_bytes
        epi_counter = sum(v for k, v in delta.items()
                          if k.startswith("bst_epilogue_d2h_bytes_total"))
        assert epi_counter == epi_bytes

    def test_cli_pyramid_skips_downsample_reread(self, project, tmp_path):
        """End to end: ``affine-fusion --pyramid`` materializes every level
        in the drain, marks them, and the downsample stage runs ZERO work
        — no full-res container re-read. A later run WITHOUT --pyramid
        revokes the marks so downsample recomputes."""
        from bigstitcher_spark_tpu.cli.main import cli

        out = str(tmp_path / "cli_fused.ome.zarr")
        runner = CliRunner()
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", project.xml_path, "-o", out,
            "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
            "--minIntensity", "0", "--maxIntensity", "65535",
            "-ds", "1,1,1", "-ds", "2,2,2",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, [
            "affine-fusion", "-o", out, "--pyramid", "--devices", "1",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "epilogue: 1 pyramid level(s)" in r.output

        store = ChunkStore.open(out)
        mr = read_container_meta(store).mr_infos[0]
        assert epilogue_written(store, mr[1].dataset, (0, 0))
        lvl = store.open_dataset(mr[1].dataset.strip("/")).read_full()
        assert lvl.std() > 0

        # without --pyramid the marks are revoked and downsample recomputes
        r = runner.invoke(cli, [
            "affine-fusion", "-o", out, "--devices", "1",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert not epilogue_written(store, mr[1].dataset, (0, 0))
        lvl2 = store.open_dataset(mr[1].dataset.strip("/")).read_full()
        assert (lvl2 == lvl).all()   # reread path == epilogue path

    def test_downsample_cmd_skip_existing(self, project, tmp_path):
        """``bst downsample --skip-existing`` skips steps whose output
        already exists with matching dims + factors."""
        from bigstitcher_spark_tpu.cli.main import cli

        sd, loader, views, bbox = _setup(project)
        root = str(tmp_path / "plain.n5")
        store = ChunkStore.create(root, StorageFormat.N5)
        ds = store.create_dataset("vol/s0", bbox.shape, (16, 16, 8),
                                  "uint16")
        ds.write(np.random.default_rng(3).integers(
            0, 1000, size=tuple(bbox.shape)).astype(np.uint16), (0, 0, 0))
        runner = CliRunner()
        args = ["downsample", "-i", root, "-di", "vol/s0",
                "-ds", "2,2,2", "--skip-existing"]
        r = runner.invoke(cli, args, catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "skipped" not in r.output
        first = store.open_dataset("vol/s1").read_full()
        r = runner.invoke(cli, args, catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "skipped" in r.output
        assert (store.open_dataset("vol/s1").read_full() == first).all()


class TestPerDeviceDirectWrites:
    def test_driver_thread_writes_nothing_devices_own_disjoint_chunks(
            self, project, tmp_path):
        """Sharded fusion under --trace: every ``fusion.write`` span sits
        on a device worker track (device-attributed, off the driver
        thread), each device wrote only its own disjoint blocks, every
        block was written exactly once, and the dataset's write-generation
        advanced exactly once per write."""
        from bigstitcher_spark_tpu.io import chunkcache

        sd, loader, views, bbox = _setup(project)
        store, mr = _container(tmp_path / "direct.zarr", project.xml_path,
                               bbox, [[1, 1, 1]])
        ds = store.open_dataset(mr[0].dataset.strip("/"))
        gen0 = chunkcache.get_cache().generation(ds._cache_key())
        driver_tid = threading.get_ident()
        trace.configure(buffer_bytes=8 << 20)
        st = fuse_volume(
            sd, loader, views, ds, bbox, block_size=(16, 16, 8),
            block_scale=(2, 2, 1), out_dtype="uint16", min_intensity=0.0,
            max_intensity=65535.0, zarr_ct=(0, 0), devices=8)
        snap = trace.snapshot()

        writes = [e for e in snap if e["name"] == "fusion.write"
                  and e["ph"] == "B"]
        n_blocks = st.blocks - st.skipped_empty
        assert len(writes) == n_blocks > 1
        assert all(e.get("device") is not None for e in writes), \
            "a fusion.write ran without device attribution"
        assert all(e["tid"] != driver_tid for e in writes), \
            "the driver thread performed a write"
        per_dev: dict = {}
        for e in writes:
            per_dev.setdefault(e["device"], set()).add(tuple(e["item"]))
        assert len(per_dev) > 1, "writes did not spread over devices"
        all_items = [tuple(e["item"]) for e in writes]
        assert len(set(all_items)) == len(all_items)  # disjoint ownership
        # d2h also attributed per device
        d2h = [e for e in snap if e["name"] == "mesh.d2h" and e["ph"] == "B"]
        assert d2h and all(e.get("device") is not None for e in d2h)
        # write-generations: one bump per write op, nothing lost or doubled
        gen1 = chunkcache.get_cache().generation(ds._cache_key())
        assert gen1 - gen0 == n_blocks

    def test_hdf5_keeps_single_writer_driver_drain(self, project, tmp_path):
        """h5py containers must keep the driver-drained single-writer path
        — and still produce output identical to the zarr run."""
        from bigstitcher_spark_tpu.io.chunkstore import Hdf5Store

        sd, loader, views, bbox = _setup(project)
        h5 = Hdf5Store(str(tmp_path / "direct.h5"))
        ds = h5.create_dataset("fused", bbox.shape, (16, 16, 8), "uint16")
        driver_tid = threading.get_ident()
        trace.configure(buffer_bytes=8 << 20)
        fuse_volume(sd, loader, views, ds, bbox, block_size=(16, 16, 8),
                    block_scale=(2, 2, 1), out_dtype="uint16",
                    min_intensity=0.0, max_intensity=65535.0, devices=8)
        writes = [e for e in trace.snapshot()
                  if e["name"] == "fusion.write" and e["ph"] == "B"]
        assert writes
        assert all(e.get("device") is None for e in writes)

        store, mr = _container(tmp_path / "zref.zarr", project.xml_path,
                               bbox, [[1, 1, 1]])
        zds = store.open_dataset(mr[0].dataset.strip("/"))
        fuse_volume(sd, loader, views, zds, bbox, block_size=(16, 16, 8),
                    block_scale=(2, 2, 1), out_dtype="uint16",
                    min_intensity=0.0, max_intensity=65535.0,
                    zarr_ct=(0, 0), devices=8)
        assert (ds.read_full()
                == zds.read_full()[..., 0, 0]).all()
        h5.close()

"""CLI-level end-to-end: create-fusion-container → affine-fusion, the
reference's own test pattern (TestSparkAffineFusion.java:8-36) on the
synthetic fixture instead of the S3 dataset."""

import json
import os

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
from bigstitcher_spark_tpu.io.container import read_container_meta
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project


def test_container_then_fusion_zarr(tmp_path):
    proj = make_synthetic_project(
        str(tmp_path / "p"), n_tiles=(2, 2, 1), jitter=0.0, seed=7,
        tile_size=(80, 80, 40), overlap=20,
    )
    out = str(tmp_path / "fused.ome.zarr")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "ZARR", "-d", "UINT16", "--blockSize", "64,64,32",
        "--minIntensity", "0", "--maxIntensity", "3000",
        "-ds", "1,1,1", "-ds", "2,2,2",
    ])
    assert r.exit_code == 0, r.output
    store = ChunkStore.open(out)
    meta = read_container_meta(store)
    assert meta.fusion_format == "OME-ZARR"
    # NGFF multiscales present
    ms = store.get_attributes("")["multiscales"]
    assert ms[0]["version"] == "0.4"
    assert [a["name"] for a in ms[0]["axes"]] == ["t", "c", "z", "y", "x"]

    r = runner.invoke(cli, [
        "affine-fusion", "-o", out, "--fusionType", "AVG_BLEND",
        "--blockScale", "1,1,1",
    ])
    assert r.exit_code == 0, r.output
    ds = store.open_dataset("0")
    full = ds.read((0, 0, 0, 0, 0), (*meta.bbox.shape, 1, 1))[..., 0, 0]
    assert full.dtype == np.uint16
    assert full.max() > 1000  # beads visible after rescale to [0,3000]
    assert (full > 0).mean() > 0.8  # near-full coverage (uniform background>0)
    # pyramid level written
    lvl1 = store.open_dataset("1")
    l1 = lvl1.read((0, 0, 0, 0, 0), (*lvl1.shape[:3], 1, 1))[..., 0, 0]
    assert l1.max() > 500


def test_fusion_masks_mode(tmp_path):
    proj = make_synthetic_project(
        str(tmp_path / "p"), n_tiles=(2, 1, 1), jitter=0.0, seed=8,
    )
    out = str(tmp_path / "mask.n5")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "N5", "-d", "UINT8", "--blockSize", "64,64,32",
    ])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["affine-fusion", "-o", out, "--masks",
                            "--blockScale", "1,1,1"])
    assert r.exit_code == 0, r.output
    store = ChunkStore.open(out)
    meta = read_container_meta(store)
    m = store.open_dataset("ch0tp0/s0").read_full()
    assert set(np.unique(m)) <= {0, 255}
    assert (m == 255).mean() > 0.8


def test_dry_run_writes_nothing(tmp_path):
    proj = make_synthetic_project(str(tmp_path / "p"), n_tiles=(1, 1, 1))
    out = str(tmp_path / "dry.n5")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out, "--dryRun",
    ])
    assert r.exit_code == 0, r.output
    assert not os.path.exists(out)


class TestBdvAppend:
    """Fusing into an EXISTING BDV project: a second create-fusion-container
    + affine-fusion run with the same --xmlout appends new ViewSetups (next
    setup/channel ids) instead of overwriting the project
    (BDVSparkInstantiateViewSetup.java:57-112; VERDICT r3 item 8)."""

    def test_two_sequential_fusions_accumulate(self, tmp_path):
        from click.testing import CliRunner

        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
            overlap=8, jitter=1.0, seed=4, n_beads_per_tile=8)
        runner = CliRunner()
        out = str(tmp_path / "fused.n5")
        xml_out = str(tmp_path / "fused.xml")

        def run_round():
            r = runner.invoke(cli, [
                "create-fusion-container", "-x", proj.xml_path, "-o", out,
                "-s", "N5", "-d", "UINT16", "--bdv", "--xmlout", xml_out,
                "--blockSize", "16,16,8",
                "--minIntensity", "0", "--maxIntensity", "65535",
            ], catch_exceptions=False)
            assert r.exit_code == 0, r.output
            r = runner.invoke(cli, ["affine-fusion", "-o", out],
                              catch_exceptions=False)
            assert r.exit_code == 0, r.output
            return r.output

        run_round()
        sd1 = SpimData.load(xml_out)
        assert sorted(sd1.setups) == [0]

        run_round()
        sd2 = SpimData.load(xml_out)
        # second fusion appended setup 1 with channel 1
        assert sorted(sd2.setups) == [0, 1]
        assert sd2.setups[1].attributes["channel"] == 1
        assert ViewId(0, 1) in sd2.registrations

        # both fused volumes are present in the one container and identical
        loader = ViewLoader(sd2)
        img0 = loader.open(ViewId(0, 0), 0).read_full()
        img1 = loader.open(ViewId(0, 1), 0).read_full()
        assert img0.std() > 0
        assert (img0 == img1).all()  # same input views fused twice
        store = ChunkStore.open(out)
        assert store.is_dataset("setup0/timepoint0/s0")
        assert store.is_dataset("setup1/timepoint0/s0")

    def test_append_refuses_foreign_project_xml(self, tmp_path):
        """--xmlout pointing at a project whose loader references a DIFFERENT
        container must be rejected, not silently corrupted."""
        from click.testing import CliRunner

        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(24, 24, 12),
            overlap=4, n_beads_per_tile=5)
        runner = CliRunner()
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path,
            "-o", str(tmp_path / "other.n5"), "-s", "N5", "-d", "UINT16",
            "--bdv", "--xmlout", proj.xml_path,  # the INPUT project XML!
            "--blockSize", "16,16,8",
        ])
        assert r.exit_code != 0
        assert "refusing to append" in r.output


class TestMultiChannelTimepointFusion:
    """Multi-channel multi-timepoint OME-ZARR fusion (a BASELINE.md config):
    every (channel, timepoint) volume must land in its own 5-D slot
    (mrInfos[c + t*numChannels] indexing, SparkAffineFusion.java:426-441),
    and --channelIndex/--timepointIndex restrict processing to one slot."""

    @pytest.fixture(scope="class")
    def mc_project(self, tmp_path_factory):
        return make_synthetic_project(
            str(tmp_path_factory.mktemp("mc") / "proj"),
            n_tiles=(2, 1, 1), tile_size=(32, 32, 16), overlap=8,
            jitter=1.0, seed=6, n_beads_per_tile=8,
            n_channels=2, n_timepoints=2)

    def test_each_slot_filled_with_its_channel(self, mc_project, tmp_path):
        runner = CliRunner()
        out = str(tmp_path / "fused.ome.zarr")
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", mc_project.xml_path, "-o", out,
            "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
            "--minIntensity", "0", "--maxIntensity", "65535",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, ["affine-fusion", "-o", out],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output
        ds = ChunkStore.open(out).open_dataset("0")
        assert ds.shape[3:] == (2, 2)  # (x,y,z,c,t)
        vols = {}
        for c in range(2):
            for t in range(2):
                v = ds.read((0, 0, 0, c, t), (*ds.shape[:3], 1, 1))[..., 0, 0]
                assert v.std() > 0, f"slot c{c} t{t} empty"
                vols[(c, t)] = v.astype(np.float64)
        # testdata makes channel 1 ~15% brighter; same data across timepoints
        assert vols[(1, 0)].mean() > 1.05 * vols[(0, 0)].mean()
        assert np.array_equal(vols[(0, 0)], vols[(0, 1)])

    def test_channel_timepoint_index_selects_one_slot(self, mc_project,
                                                      tmp_path):
        runner = CliRunner()
        out = str(tmp_path / "sel.ome.zarr")
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", mc_project.xml_path, "-o", out,
            "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
            "--minIntensity", "0", "--maxIntensity", "65535",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, [
            "affine-fusion", "-o", out,
            "--channelIndex", "1", "--timepointIndex", "0",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        ds = ChunkStore.open(out).open_dataset("0")
        filled = ds.read((0, 0, 0, 1, 0), (*ds.shape[:3], 1, 1))
        empty = ds.read((0, 0, 0, 0, 0), (*ds.shape[:3], 1, 1))
        assert filled.std() > 0
        assert empty.std() == 0


class TestCompressionLevel:
    def test_cl_reaches_codec_metadata(self, tmp_path):
        import json
        import os

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=3)
        runner = CliRunner()
        out = str(tmp_path / "c.n5")
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path, "-o", out,
            "-s", "N5", "-d", "UINT16", "--blockSize", "16,16,8",
            "-c", "gzip", "-cl", "9",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        attrs = json.load(open(os.path.join(out, "ch0tp0", "s0", "attributes.json")))
        assert attrs["compression"]["type"] == "gzip"
        assert attrs["compression"]["level"] == 9

    def test_zarr_level(self, tmp_path):
        import json
        import os

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=3)
        out = str(tmp_path / "c.ome.zarr")
        r = CliRunner().invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path, "-o", out,
            "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
            "-c", "zstd", "-cl", "7",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        meta = json.load(open(os.path.join(out, "0", ".zarray")))
        assert meta["compressor"]["level"] == 7


class TestNonrigidDirectOutput:
    def test_direct_output_creates_container(self, tmp_path):
        """SparkNonRigidFusion writes straight to an N5/ZARR (no
        create-fusion-container step): -o <fresh> -x <xml> -p <dtype>."""
        import numpy as np

        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points, save_detections,
        )
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_interest_points, save_matches,
        )

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=24, jitter=2.0, seed=31, n_beads_per_tile=25)
        sd = SpimData.load(proj.xml_path)
        views = sorted(sd.registrations)
        loader = ViewLoader(sd)
        dets = detect_interest_points(
            sd, loader, views,
            DetectionParams(downsample_xy=1, downsample_z=1,
                            block_size=(48, 48, 24)),
            progress=False)
        store = InterestPointStore.for_project(sd)
        save_detections(sd, store, dets, DetectionParams())
        mparams = MatchingParams(ransac_min_inliers=5,
                                 ransac_iterations=2000,
                                 model="TRANSLATION", regularization="NONE")
        save_matches(sd, store,
                     match_interest_points(sd, views, mparams, store,
                                           progress=False),
                     mparams, views)
        sd.save()

        out = str(tmp_path / "direct.ome.zarr")
        r = CliRunner().invoke(cli, [
            "nonrigid-fusion", "-o", out, "-x", proj.xml_path,
            "-p", "FLOAT32", "-s", "ZARR", "-ip", "beads",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "direct output: created container" in r.output
        ds = ChunkStore.open(out).open_dataset("0")
        vol = np.asarray(ds.read((0, 0, 0, 0, 0), (*ds.shape[:3], 1, 1)))
        assert vol.std() > 0

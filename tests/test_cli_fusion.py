"""CLI-level end-to-end: create-fusion-container → affine-fusion, the
reference's own test pattern (TestSparkAffineFusion.java:8-36) on the
synthetic fixture instead of the S3 dataset."""

import json
import os

import numpy as np
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
from bigstitcher_spark_tpu.io.container import read_container_meta
from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project


def test_container_then_fusion_zarr(tmp_path):
    proj = make_synthetic_project(
        str(tmp_path / "p"), n_tiles=(2, 2, 1), jitter=0.0, seed=7,
        tile_size=(80, 80, 40), overlap=20,
    )
    out = str(tmp_path / "fused.ome.zarr")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "ZARR", "-d", "UINT16", "--blockSize", "64,64,32",
        "--minIntensity", "0", "--maxIntensity", "3000",
        "-ds", "1,1,1", "-ds", "2,2,2",
    ])
    assert r.exit_code == 0, r.output
    store = ChunkStore.open(out)
    meta = read_container_meta(store)
    assert meta.fusion_format == "OME-ZARR"
    # NGFF multiscales present
    ms = store.get_attributes("")["multiscales"]
    assert ms[0]["version"] == "0.4"
    assert [a["name"] for a in ms[0]["axes"]] == ["t", "c", "z", "y", "x"]

    r = runner.invoke(cli, [
        "affine-fusion", "-o", out, "--fusionType", "AVG_BLEND",
        "--blockScale", "1,1,1",
    ])
    assert r.exit_code == 0, r.output
    ds = store.open_dataset("0")
    full = ds.read((0, 0, 0, 0, 0), (*meta.bbox.shape, 1, 1))[..., 0, 0]
    assert full.dtype == np.uint16
    assert full.max() > 1000  # beads visible after rescale to [0,3000]
    assert (full > 0).mean() > 0.8  # near-full coverage (uniform background>0)
    # pyramid level written
    lvl1 = store.open_dataset("1")
    l1 = lvl1.read((0, 0, 0, 0, 0), (*lvl1.shape[:3], 1, 1))[..., 0, 0]
    assert l1.max() > 500


def test_fusion_masks_mode(tmp_path):
    proj = make_synthetic_project(
        str(tmp_path / "p"), n_tiles=(2, 1, 1), jitter=0.0, seed=8,
    )
    out = str(tmp_path / "mask.n5")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "N5", "-d", "UINT8", "--blockSize", "64,64,32",
    ])
    assert r.exit_code == 0, r.output
    r = runner.invoke(cli, ["affine-fusion", "-o", out, "--masks",
                            "--blockScale", "1,1,1"])
    assert r.exit_code == 0, r.output
    store = ChunkStore.open(out)
    meta = read_container_meta(store)
    m = store.open_dataset("ch0tp0/s0").read_full()
    assert set(np.unique(m)) <= {0, 255}
    assert (m == 255).mean() > 0.8


def test_dry_run_writes_nothing(tmp_path):
    proj = make_synthetic_project(str(tmp_path / "p"), n_tiles=(1, 1, 1))
    out = str(tmp_path / "dry.n5")
    runner = CliRunner()
    r = runner.invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out, "--dryRun",
    ])
    assert r.exit_code == 0, r.output
    assert not os.path.exists(out)

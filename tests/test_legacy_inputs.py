"""Legacy TIFF input + niche utilities (closing the last SURVEY §2 rows):
spimreconstruction TIFF-stack loader feeding resave
(SparkResaveN5.java:107-457 ingests any bdv imgloader), the
interestpoints.n5 debug printer (SpimData2Util.java:49-162), and the
acquisition-order SetupIDMapper (SetupIDMapper.java:36-107).
"""

import os
import xml.etree.ElementTree as ET

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId


@pytest.fixture(scope="module")
def tiff_project(tmp_path_factory):
    """Two-tile project stored as multi-page TIFF stacks + classic
    spimreconstruction ImageLoader XML."""
    from PIL import Image

    from bigstitcher_spark_tpu.io.spimdata import (
        AttributeEntity, ImageLoader, SpimData as SD, ViewSetup, ViewTransform,
    )
    from bigstitcher_spark_tpu.utils.geometry import translation_affine

    root = tmp_path_factory.mktemp("tiffproj")
    size = (40, 32, 10)  # xyz
    rng = np.random.default_rng(5)
    stacks = {}
    # angle NAMES (degrees) differ from ids: the pattern must substitute
    # the entity name, StackImgLoaderIJ semantics
    angle_names = {0: "45", 1: "90"}
    for a in (0, 1):
        vol = rng.integers(50, 4000, size=size).astype(np.uint16)
        stacks[a] = vol
        pages = [Image.fromarray(vol[:, :, z].T) for z in range(size[2])]
        pages[0].save(str(root / f"spim_TL0_Angle{angle_names[a]}.tif"),
                      save_all=True, append_images=pages[1:])

    sd = SD()
    raw = ET.Element("ImageLoader", format="spimreconstruction", version="0.1")
    ET.SubElement(raw, "imagedirectory", type="relative").text = "."
    ET.SubElement(raw, "filePattern").text = "spim_TL{t}_Angle{a}.tif"
    sd.image_loader = ImageLoader(format="spimreconstruction", raw=raw)
    sd.timepoints = [0]
    sd.attributes["illumination"][0] = AttributeEntity(0, "0")
    sd.attributes["channel"][0] = AttributeEntity(0, "0")
    sd.attributes["tile"][0] = AttributeEntity(0, "0")
    for a in (0, 1):
        sd.attributes["angle"][a] = AttributeEntity(a, angle_names[a])
        sd.setups[a] = ViewSetup(
            id=a, name=f"angle{a}", size=size,
            attributes={"illumination": 0, "channel": 0, "tile": 0, "angle": a})
        sd.registrations[ViewId(0, a)] = [
            ViewTransform("grid", translation_affine((a * 30.0, 0, 0)))]
    xml = str(root / "dataset.xml")
    sd.save(xml)
    return xml, stacks


class TestTiffLoader:
    def test_reads_stacks(self, tiff_project):
        xml, stacks = tiff_project
        sd = SpimData.load(xml)
        assert sd.image_loader.format == "spimreconstruction"
        loader = ViewLoader(sd)
        for a in (0, 1):
            img = loader.open(ViewId(0, a), 0).read_full()
            assert (img == stacks[a]).all()
        # boxed read + halo padding
        blk = loader.read_block(ViewId(0, 0), 0, (-2, 0, 0), (6, 6, 4))
        assert (blk[:2] == 0).all() and blk[2:].std() > 0

    def test_resave_from_tiff(self, tiff_project, tmp_path):
        """resave ingests the TIFF project and rewrites it as bdv.n5 — the
        reference's legacy-dataset entry point."""
        xml, stacks = tiff_project
        out_xml = str(tmp_path / "resaved.xml")
        r = CliRunner().invoke(cli, [
            "resave", "-x", xml, "-xo", out_xml,
            "-o", str(tmp_path / "resaved.n5"), "--N5",
            "-ds", "1,1,1", "--blockSize", "16,16,8",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        sd = SpimData.load(out_xml)
        assert sd.image_loader.format == "bdv.n5"
        loader = ViewLoader(sd)
        for a in (0, 1):
            assert (loader.open(ViewId(0, a), 0).read_full() == stacks[a]).all()


class TestInspectInterestpoints:
    def test_prints_layout(self, tmp_path):
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import InterestPointLookup
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        sd = SpimData.load(proj.xml_path)
        store = InterestPointStore.for_project(sd)
        v = ViewId(0, 0)
        pts = np.array([[1.0, 2, 3], [4, 5, 6], [7, 8, 9]])
        store.save_points(v, "beads", pts, ids=np.arange(3, dtype=np.uint64))
        sd.interest_points.setdefault(v, {})["beads"] = InterestPointLookup(
            label="beads", params="DOG test",
            path="tpId_0_viewSetupId_0/beads")
        sd.save()
        r = CliRunner().invoke(cli, ["inspect-interestpoints", "-x",
                                     proj.xml_path], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "3 points" in r.output
        assert "beads" in r.output
        assert "TOTAL: 3 points" in r.output


class TestSetupIdMapper:
    def test_mapping_formula(self):
        from bigstitcher_spark_tpu.utils.viewselect import keller_mirror_scope_map

        m = keller_mirror_scope_map(8, 3, parallel_rows=4)
        assert sorted(m) == list(range(24))
        assert sorted(m.values()) == list(range(24))
        # first acquired: row 0, rightmost column (col=2) -> old id
        # row*cols + (cols-1-col) = 0*3 + 0 = 0; then row 4 same column
        assert m[0] == 0
        assert m[4 * 3 + 0] == 1

    def test_refuses_after_detection(self, tmp_path):
        """Remapping after interest points exist would re-attach n5 groups
        to the wrong tiles — must refuse loudly."""
        from bigstitcher_spark_tpu.io.spimdata import InterestPointLookup

        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        sd = SpimData.load(proj.xml_path)
        sd.interest_points.setdefault(ViewId(0, 0), {})["beads"] = (
            InterestPointLookup(label="beads",
                                path="tpId_0_viewSetupId_0/beads"))
        with pytest.raises(ValueError, match="before detection"):
            sd.remap_setup_ids({0: 1, 1: 0})

    def test_cli_remaps_project(self, tmp_path):
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import keller_mirror_scope_map

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 2, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        out_xml = str(tmp_path / "remapped.xml")
        r = CliRunner().invoke(cli, [
            "map-setup-ids", "-x", proj.xml_path, "-xo", out_xml,
            "--rows", "2", "--columns", "2", "--parallelRows", "1",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        sd0 = SpimData.load(proj.xml_path)
        sd = SpimData.load(out_xml)
        mapping = keller_mirror_scope_map(2, 2, 1)
        assert sorted(sd.setups) == sorted(sd0.setups)
        for old, new in mapping.items():
            assert sd.setups[new].name == sd0.setups[old].name
            np.testing.assert_array_equal(
                sd.model(ViewId(0, new)), sd0.model(ViewId(0, old)))

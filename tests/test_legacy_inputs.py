"""Legacy TIFF input + niche utilities (closing the last SURVEY §2 rows):
spimreconstruction TIFF-stack loader feeding resave
(SparkResaveN5.java:107-457 ingests any bdv imgloader), the
interestpoints.n5 debug printer (SpimData2Util.java:49-162), and the
acquisition-order SetupIDMapper (SetupIDMapper.java:36-107).
"""

import os
import xml.etree.ElementTree as ET

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId


@pytest.fixture(scope="module")
def tiff_project(tmp_path_factory):
    """Two-tile project stored as multi-page TIFF stacks + classic
    spimreconstruction ImageLoader XML."""
    from PIL import Image

    from bigstitcher_spark_tpu.io.spimdata import (
        AttributeEntity, ImageLoader, SpimData as SD, ViewSetup, ViewTransform,
    )
    from bigstitcher_spark_tpu.utils.geometry import translation_affine

    root = tmp_path_factory.mktemp("tiffproj")
    size = (40, 32, 10)  # xyz
    rng = np.random.default_rng(5)
    stacks = {}
    # angle NAMES (degrees) differ from ids: the pattern must substitute
    # the entity name, StackImgLoaderIJ semantics
    angle_names = {0: "45", 1: "90"}
    for a in (0, 1):
        vol = rng.integers(50, 4000, size=size).astype(np.uint16)
        stacks[a] = vol
        pages = [Image.fromarray(vol[:, :, z].T) for z in range(size[2])]
        pages[0].save(str(root / f"spim_TL0_Angle{angle_names[a]}.tif"),
                      save_all=True, append_images=pages[1:])

    sd = SD()
    raw = ET.Element("ImageLoader", format="spimreconstruction", version="0.1")
    ET.SubElement(raw, "imagedirectory", type="relative").text = "."
    ET.SubElement(raw, "filePattern").text = "spim_TL{t}_Angle{a}.tif"
    sd.image_loader = ImageLoader(format="spimreconstruction", raw=raw)
    sd.timepoints = [0]
    sd.attributes["illumination"][0] = AttributeEntity(0, "0")
    sd.attributes["channel"][0] = AttributeEntity(0, "0")
    sd.attributes["tile"][0] = AttributeEntity(0, "0")
    for a in (0, 1):
        sd.attributes["angle"][a] = AttributeEntity(a, angle_names[a])
        sd.setups[a] = ViewSetup(
            id=a, name=f"angle{a}", size=size,
            attributes={"illumination": 0, "channel": 0, "tile": 0, "angle": a})
        sd.registrations[ViewId(0, a)] = [
            ViewTransform("grid", translation_affine((a * 30.0, 0, 0)))]
    xml = str(root / "dataset.xml")
    sd.save(xml)
    return xml, stacks


class TestTiffLoader:
    def test_reads_stacks(self, tiff_project):
        xml, stacks = tiff_project
        sd = SpimData.load(xml)
        assert sd.image_loader.format == "spimreconstruction"
        loader = ViewLoader(sd)
        for a in (0, 1):
            img = loader.open(ViewId(0, a), 0).read_full()
            assert (img == stacks[a]).all()
        # boxed read + halo padding
        blk = loader.read_block(ViewId(0, 0), 0, (-2, 0, 0), (6, 6, 4))
        assert (blk[:2] == 0).all() and blk[2:].std() > 0

    def test_resave_from_tiff(self, tiff_project, tmp_path):
        """resave ingests the TIFF project and rewrites it as bdv.n5 — the
        reference's legacy-dataset entry point."""
        xml, stacks = tiff_project
        out_xml = str(tmp_path / "resaved.xml")
        r = CliRunner().invoke(cli, [
            "resave", "-x", xml, "-xo", out_xml,
            "-o", str(tmp_path / "resaved.n5"), "--N5",
            "-ds", "1,1,1", "--blockSize", "16,16,8",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        sd = SpimData.load(out_xml)
        assert sd.image_loader.format == "bdv.n5"
        loader = ViewLoader(sd)
        for a in (0, 1):
            assert (loader.open(ViewId(0, a), 0).read_full() == stacks[a]).all()


class TestInspectInterestpoints:
    def test_prints_layout(self, tmp_path):
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import InterestPointLookup
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        sd = SpimData.load(proj.xml_path)
        store = InterestPointStore.for_project(sd)
        v = ViewId(0, 0)
        pts = np.array([[1.0, 2, 3], [4, 5, 6], [7, 8, 9]])
        store.save_points(v, "beads", pts, ids=np.arange(3, dtype=np.uint64))
        sd.interest_points.setdefault(v, {})["beads"] = InterestPointLookup(
            label="beads", params="DOG test",
            path="tpId_0_viewSetupId_0/beads")
        sd.save()
        r = CliRunner().invoke(cli, ["inspect-interestpoints", "-x",
                                     proj.xml_path], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "3 points" in r.output
        assert "beads" in r.output
        assert "TOTAL: 3 points" in r.output


class TestSetupIdMapper:
    def test_mapping_formula(self):
        from bigstitcher_spark_tpu.utils.viewselect import keller_mirror_scope_map

        m = keller_mirror_scope_map(8, 3, parallel_rows=4)
        assert sorted(m) == list(range(24))
        assert sorted(m.values()) == list(range(24))
        # first acquired: row 0, rightmost column (col=2) -> old id
        # row*cols + (cols-1-col) = 0*3 + 0 = 0; then row 4 same column
        assert m[0] == 0
        assert m[4 * 3 + 0] == 1

    def test_refuses_after_detection(self, tmp_path):
        """Remapping after interest points exist would re-attach n5 groups
        to the wrong tiles — must refuse loudly."""
        from bigstitcher_spark_tpu.io.spimdata import InterestPointLookup

        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        sd = SpimData.load(proj.xml_path)
        sd.interest_points.setdefault(ViewId(0, 0), {})["beads"] = (
            InterestPointLookup(label="beads",
                                path="tpId_0_viewSetupId_0/beads"))
        with pytest.raises(ValueError, match="before detection"):
            sd.remap_setup_ids({0: 1, 1: 0})

    def test_cli_remaps_project(self, tmp_path):
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        from bigstitcher_spark_tpu.utils.viewselect import keller_mirror_scope_map

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 2, 1), tile_size=(24, 24, 12),
            overlap=8, n_beads_per_tile=5)
        out_xml = str(tmp_path / "remapped.xml")
        r = CliRunner().invoke(cli, [
            "map-setup-ids", "-x", proj.xml_path, "-xo", out_xml,
            "--rows", "2", "--columns", "2", "--parallelRows", "1",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        sd0 = SpimData.load(proj.xml_path)
        sd = SpimData.load(out_xml)
        mapping = keller_mirror_scope_map(2, 2, 1)
        assert sorted(sd.setups) == sorted(sd0.setups)
        for old, new in mapping.items():
            assert sd.setups[new].name == sd0.setups[old].name
            np.testing.assert_array_equal(
                sd.model(ViewId(0, new)), sd0.model(ViewId(0, old)))


@pytest.fixture(scope="module")
def czi_project(tmp_path_factory):
    """Two-tile, two-channel project stored as one CZI file (scenes = tiles)
    + filemap2 ImageLoader XML — the reference's Zeiss-acquisition entry
    point (FileMapImgLoaderLOCI2 / bioformats)."""
    from bigstitcher_spark_tpu.io.czi import write_czi
    from bigstitcher_spark_tpu.io.spimdata import (
        AttributeEntity, ImageLoader, SpimData as SD, ViewSetup, ViewTransform,
    )
    from bigstitcher_spark_tpu.utils.geometry import translation_affine

    root = tmp_path_factory.mktemp("cziproj")
    size = (36, 28, 6)  # xyz
    rng = np.random.default_rng(7)
    vols = {}
    views = []
    for tile in (0, 1):
        for ch in (0, 1):
            vol = rng.integers(50, 4000, size=size).astype(np.uint16)
            vols[(tile, ch)] = vol
            views.append({"data": vol, "scene": tile, "channel": ch})
    czi_path = str(root / "acq.czi")
    write_czi(czi_path, views)

    sd = SD()
    raw = ET.Element("ImageLoader", format="spimreconstruction.filemap2",
                     version="0.1")
    files = ET.SubElement(raw, "files")
    setup = 0
    setup_of = {}
    for tile in (0, 1):
        for ch in (0, 1):
            ET.SubElement(files, "FileMapping", view_setup=str(setup),
                          timepoint="0", file="acq.czi", series=str(tile),
                          channel=str(ch))
            setup_of[(tile, ch)] = setup
            setup += 1
    sd.image_loader = ImageLoader(format="spimreconstruction.filemap2", raw=raw)
    sd.timepoints = [0]
    sd.attributes["illumination"][0] = AttributeEntity(0, "0")
    sd.attributes["angle"][0] = AttributeEntity(0, "0")
    for ch in (0, 1):
        sd.attributes["channel"][ch] = AttributeEntity(ch, str(ch))
    for tile in (0, 1):
        sd.attributes["tile"][tile] = AttributeEntity(tile, str(tile))
    for (tile, ch), s in setup_of.items():
        sd.setups[s] = ViewSetup(
            id=s, name=f"tile{tile}ch{ch}", size=size,
            attributes={"illumination": 0, "channel": ch, "tile": tile,
                        "angle": 0})
        sd.registrations[ViewId(0, s)] = [
            ViewTransform("grid", translation_affine((tile * 30.0, 0, 0)))]
    xml = str(root / "dataset.xml")
    sd.save(xml)
    return xml, vols, setup_of


class TestCziLoader:
    def test_czi_round_trip(self, tmp_path):
        """Reader parity with the writer across dtypes and dimensions."""
        from bigstitcher_spark_tpu.io.czi import CziFile, write_czi

        rng = np.random.default_rng(1)
        v8 = rng.integers(0, 255, (20, 16, 4), dtype=np.uint8)
        vf = rng.random((10, 8, 2)).astype(np.float32)
        path = str(tmp_path / "t.czi")
        write_czi(path, [{"data": v8, "scene": 0},
                         {"data": vf, "scene": 0, "channel": 1}])
        with CziFile(path) as cz:
            assert cz.scenes() == [0]
            np.testing.assert_array_equal(cz.read_volume(0, 0), v8)
            np.testing.assert_array_equal(cz.read_volume(0, 1), vf)

    def test_reads_views(self, czi_project):
        xml, vols, setup_of = czi_project
        sd = SpimData.load(xml)
        assert sd.image_loader.format == "spimreconstruction.filemap2"
        loader = ViewLoader(sd)
        for (tile, ch), s in setup_of.items():
            ds = loader.open(ViewId(0, s), 0)
            assert ds.dtype == np.dtype("uint16")
            assert (ds.read_full() == vols[(tile, ch)]).all()
        blk = loader.read_block(ViewId(0, 0), 0, (-2, 0, 0), (6, 6, 4))
        assert (blk[:2] == 0).all() and blk[2:].std() > 0

    def test_resave_from_czi(self, czi_project, tmp_path):
        """resave ingests the CZI project and rewrites it as bdv.n5."""
        xml, vols, setup_of = czi_project
        out_xml = str(tmp_path / "resaved.xml")
        r = CliRunner().invoke(cli, [
            "resave", "-x", xml, "-xo", out_xml,
            "-o", str(tmp_path / "resaved.n5"), "--N5",
            "-ds", "1,1,1", "--blockSize", "16,16,8",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        sd = SpimData.load(out_xml)
        assert sd.image_loader.format == "bdv.n5"
        loader = ViewLoader(sd)
        for (tile, ch), s in setup_of.items():
            got = loader.open(ViewId(0, s), 0).read_full()
            assert (got == vols[(tile, ch)]).all()

    def test_single_timepoint_file_at_later_timepoint(self, tmp_path):
        """One CZI per timepoint (in-file T=0): the mapping resolves the
        project timepoint to the file, the loader maps to the file's only T."""
        from bigstitcher_spark_tpu.io.czi import write_czi
        from bigstitcher_spark_tpu.io.spimdata import (
            AttributeEntity, ImageLoader, SpimData as SD, ViewSetup,
            ViewTransform,
        )
        from bigstitcher_spark_tpu.utils.geometry import identity_affine

        size = (12, 10, 3)
        rng = np.random.default_rng(3)
        vols = {t: rng.integers(0, 4000, size, dtype=np.uint16)
                for t in (0, 5)}
        for t, vol in vols.items():
            write_czi(str(tmp_path / f"tp{t}.czi"), [{"data": vol}])

        sd = SD()
        raw = ET.Element("ImageLoader", format="spimreconstruction.filemap2")
        files = ET.SubElement(raw, "files")
        for t in vols:
            ET.SubElement(files, "FileMapping", view_setup="0",
                          timepoint=str(t), file=f"tp{t}.czi", series="0",
                          channel="0")
        sd.image_loader = ImageLoader(format="spimreconstruction.filemap2",
                                      raw=raw)
        sd.timepoints = sorted(vols)
        for attr in ("illumination", "channel", "tile", "angle"):
            sd.attributes[attr][0] = AttributeEntity(0, "0")
        sd.setups[0] = ViewSetup(id=0, name="v0", size=size, attributes={
            "illumination": 0, "channel": 0, "tile": 0, "angle": 0})
        for t in vols:
            sd.registrations[ViewId(t, 0)] = [
                ViewTransform("id", identity_affine())]
        xml = str(tmp_path / "dataset.xml")
        sd.save(xml)
        loader = ViewLoader(SpimData.load(xml))
        for t, vol in vols.items():
            np.testing.assert_array_equal(
                loader.open(ViewId(t, 0), 0).read_full(), vol)

    def test_dual_illumination(self, tmp_path):
        """Subblocks varying in I must not silently overlay; the loader
        selects by the view setup's illumination attribute."""
        from bigstitcher_spark_tpu.io.czi import CziFile, write_czi
        from bigstitcher_spark_tpu.io.spimdata import (
            AttributeEntity, ImageLoader, SpimData as SD, ViewSetup,
            ViewTransform,
        )
        from bigstitcher_spark_tpu.utils.geometry import identity_affine

        size = (10, 8, 2)
        rng = np.random.default_rng(9)
        vols = {i: rng.integers(0, 4000, size, dtype=np.uint16) for i in (0, 1)}
        path = str(tmp_path / "dual.czi")
        write_czi(path, [{"data": vols[i], "illumination": i} for i in (0, 1)])
        with CziFile(path) as cz:
            with pytest.raises(NotImplementedError, match="'I'"):
                cz.read_volume(0, 0)
            np.testing.assert_array_equal(
                cz.read_volume(0, 0, illumination=1), vols[1])

        sd = SD()
        raw = ET.Element("ImageLoader", format="spimreconstruction.filemap2")
        files = ET.SubElement(raw, "files")
        for i in (0, 1):
            ET.SubElement(files, "FileMapping", view_setup=str(i),
                          timepoint="0", file="dual.czi", series="0",
                          channel="0")
        sd.image_loader = ImageLoader(format="spimreconstruction.filemap2",
                                      raw=raw)
        sd.timepoints = [0]
        for attr in ("channel", "tile", "angle"):
            sd.attributes[attr][0] = AttributeEntity(0, "0")
        for i in (0, 1):
            sd.attributes["illumination"][i] = AttributeEntity(i, str(i))
            sd.setups[i] = ViewSetup(id=i, name=f"illum{i}", size=size,
                attributes={"illumination": i, "channel": 0, "tile": 0,
                            "angle": 0})
            sd.registrations[ViewId(0, i)] = [
                ViewTransform("id", identity_affine())]
        xml = str(tmp_path / "dataset.xml")
        sd.save(xml)
        loader = ViewLoader(SpimData.load(xml))
        for i in (0, 1):
            np.testing.assert_array_equal(
                loader.open(ViewId(0, i), 0).read_full(), vols[i])

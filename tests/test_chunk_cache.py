"""Decoded-chunk LRU cache (io/chunkcache.py + Dataset.read integration):
hit/miss/evict accounting, metadata-signature and write invalidation,
byte-budget LRU eviction order, cross-reader sharing, the cache-off env
toggle, and an end-to-end affine-fusion run proving overlapping halo
reads decode each chunk once (and produce bit-identical output either
way)."""

import json
import os
import shutil

import numpy as np
import pytest

from bigstitcher_spark_tpu.io import chunkcache
from bigstitcher_spark_tpu.io.chunkstore import (
    ChunkStore, Hdf5Store, StorageFormat,
)
from bigstitcher_spark_tpu.observe import metrics

CHUNK = (16, 16, 8)          # chunk bytes: 16*16*8 * 2 = 4096
CHUNK_BYTES = 16 * 16 * 8 * 2


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(64 << 20))
    chunkcache.get_cache().clear()
    yield
    chunkcache.get_cache().clear()


def _delta(baseline, prefix="bst_chunk_cache_"):
    d = metrics.get_registry().snapshot_delta(baseline)
    return {k.replace(prefix, ""): int(v) for k, v in d.items()
            if k.startswith(prefix) and isinstance(v, (int, float))}


def _make_n5(tmp_path, name="c", shape=(64, 64, 8)):
    store = ChunkStore.create(str(tmp_path / f"{name}.n5"), StorageFormat.N5)
    ds = store.create_dataset("a", shape, CHUNK, "uint16")
    data = (np.arange(int(np.prod(shape))).reshape(shape)
            % 60000).astype(np.uint16)
    ds.write(data, (0, 0, 0))
    chunkcache.get_cache().clear()   # drop anything staged by the write
    return store, ds, data


class TestAccounting:
    def test_hit_miss_evict_counters(self, tmp_path):
        _, ds, data = _make_n5(tmp_path)
        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (32, 32, 8))          # 4 chunks, all cold
        d = _delta(base)
        assert np.array_equal(got, data[:32, :32])
        assert d["misses_total"] == 4 and d["hits_total"] == 0
        assert d["miss_bytes_total"] == 4 * CHUNK_BYTES

        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (32, 32, 8))          # same box, all warm
        d = _delta(base)
        assert np.array_equal(got, data[:32, :32])
        assert d["hits_total"] == 4 and d.get("misses_total", 0) == 0
        assert d["hit_bytes_total"] == 4 * CHUNK_BYTES

    def test_partial_overlap_mixes_hits_and_misses(self, tmp_path):
        _, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (16, 16, 8))                # chunk (0,0,0) only
        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (32, 16, 8))          # chunks (0..1,0,0)
        d = _delta(base)
        assert np.array_equal(got, data[:32, :16])
        assert d["hits_total"] == 1 and d["misses_total"] == 1

    def test_io_read_records_cache_path(self, tmp_path):
        _, ds, _ = _make_n5(tmp_path)
        ds.read((0, 0, 0), (16, 16, 8))
        base = metrics.get_registry().snapshot()
        ds.read((0, 0, 0), (16, 16, 8))
        d = metrics.get_registry().snapshot_delta(base)
        assert d.get('bst_io_read_bytes_total{path="cache"}') == CHUNK_BYTES
        assert not d.get('bst_io_read_bytes_total{path="native"}')
        assert not d.get('bst_io_read_bytes_total{path="tensorstore"}')


class TestEviction:
    def test_lru_eviction_order_under_byte_budget(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(3 * CHUNK_BYTES))
        _, ds, _ = _make_n5(tmp_path)
        for cx in range(4):                            # touch chunks 0..3
            ds.read((16 * cx, 0, 0), (16, 16, 8))
        st = chunkcache.get_cache().stats()
        assert st["entries"] == 3                      # budget held
        assert st["bytes"] <= 3 * CHUNK_BYTES

        base = metrics.get_registry().snapshot()
        ds.read((0, 0, 0), (16, 16, 8))                # chunk 0: evicted (LRU)
        assert _delta(base)["misses_total"] == 1
        base = metrics.get_registry().snapshot()
        ds.read((48, 0, 0), (16, 16, 8))               # chunk 3: newest, hit
        d = _delta(base)
        assert d["hits_total"] == 1 and d.get("misses_total", 0) == 0

    def test_oversize_box_never_blows_budget(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", str(2 * CHUNK_BYTES))
        _, ds, data = _make_n5(tmp_path)
        got = ds.read((0, 0, 0), (64, 64, 8))          # 16 chunks through a
        assert np.array_equal(got, data)               # 2-chunk budget
        assert chunkcache.get_cache().stats()["bytes"] <= 2 * CHUNK_BYTES


class TestInvalidation:
    def test_write_invalidates_only_affected_chunks(self, tmp_path):
        _, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (32, 32, 8))                # 4 chunks cached
        ds.write(np.zeros(CHUNK, np.uint16), (0, 0, 0))
        base = metrics.get_registry().snapshot()
        got = ds.read((0, 0, 0), (32, 32, 8))
        d = _delta(base)
        assert (got[:16, :16] == 0).all()
        assert np.array_equal(got[16:, 16:], data[16:32, 16:32])
        assert d["misses_total"] == 1 and d["hits_total"] == 3

    def test_metadata_signature_invalidation(self, tmp_path):
        store, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (16, 16, 8))
        # out-of-band mutation (no Dataset.write hook runs, as another
        # PROCESS would do it): copy a chunk file with different content
        # over chunk (0,0,0) and bump the metadata signature the way an
        # external recreate would
        donor = store.create_dataset("donor", (16, 16, 8), CHUNK, "uint16")
        donor.write(np.full(CHUNK, 7, np.uint16), (0, 0, 0))
        shutil.copy(os.path.join(store._kvpath("donor"), "0", "0", "0"),
                    os.path.join(store._kvpath("a"), "0", "0", "0"))
        attrs = os.path.join(store._kvpath("a"), "attributes.json")
        st = os.stat(attrs)
        os.utime(attrs, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000_000))
        got = ds.read((0, 0, 0), (16, 16, 8))
        assert (got == 7).all()                        # stale entry orphaned

    def test_recreate_dataset_invalidates(self, tmp_path):
        store, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (16, 16, 8))
        ds2 = store.create_dataset("a", (64, 64, 8), CHUNK, "uint16",
                                   delete_existing=True)
        ds2.write(np.ones((64, 64, 8), np.uint16), (0, 0, 0))
        assert (ds2.read((0, 0, 0), (16, 16, 8)) == 1).all()

    def test_store_remove_invalidates(self, tmp_path):
        store, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (16, 16, 8))
        store.remove("a")
        assert chunkcache.get_cache().stats()["entries"] == 0

    def test_generation_bumps_even_with_cache_disabled(self, tmp_path,
                                                       monkeypatch):
        _, ds, _ = _make_n5(tmp_path)
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", "0")
        g0 = chunkcache.get_cache().generation(ds._cache_key())
        ds.write(np.zeros(CHUNK, np.uint16), (0, 0, 0))
        assert chunkcache.get_cache().generation(ds._cache_key()) > g0


class TestSharing:
    def test_cross_reader_sharing(self, tmp_path):
        store, ds, data = _make_n5(tmp_path)
        ds.read((0, 0, 0), (32, 32, 8))
        other = ChunkStore.open(str(tmp_path / "c.n5")).open_dataset("a")
        base = metrics.get_registry().snapshot()
        got = other.read((0, 0, 0), (32, 32, 8))
        d = _delta(base)
        assert np.array_equal(got, data[:32, :32])
        assert d["hits_total"] == 4 and d.get("misses_total", 0) == 0


class TestDrivers:
    def test_zarr_reads_through_cache(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "z.zarr"),
                                  StorageFormat.ZARR)
        ds = store.create_dataset("a", (48, 48, 8), CHUNK, "uint16")
        data = (np.arange(48 * 48 * 8).reshape(48, 48, 8)
                % 60000).astype(np.uint16)
        ds.write(data, (0, 0, 0))
        chunkcache.get_cache().clear()
        ds.read((5, 5, 1), (40, 40, 6))
        base = metrics.get_registry().snapshot()
        got = ds.read((5, 5, 1), (40, 40, 6))
        d = _delta(base)
        assert np.array_equal(got, data[5:45, 5:45, 1:7])
        assert d["hits_total"] == 9 and d.get("misses_total", 0) == 0

    def test_hdf5_reads_through_cache(self, tmp_path):
        h = Hdf5Store(str(tmp_path / "f.h5"))
        ds = h.create_dataset("x", (32, 32, 8), CHUNK, "uint16")
        data = np.random.default_rng(3).integers(
            0, 1000, (32, 32, 8)).astype(np.uint16)
        ds.write(data, (0, 0, 0))
        chunkcache.get_cache().clear()
        ds.read((1, 1, 1), (30, 30, 6))
        base = metrics.get_registry().snapshot()
        got = ds.read((1, 1, 1), (30, 30, 6))
        d = _delta(base)
        assert np.array_equal(got, data[1:31, 1:31, 1:7])
        assert d["hits_total"] == 4 and d.get("misses_total", 0) == 0
        h.close()


class TestToggle:
    def test_cache_off_bypasses_and_matches(self, tmp_path, monkeypatch):
        _, ds, data = _make_n5(tmp_path)
        on = ds.read((3, 3, 0), (40, 40, 8))
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", "0")
        base = metrics.get_registry().snapshot()
        off = ds.read((3, 3, 0), (40, 40, 8))
        d = _delta(base)
        assert np.array_equal(on, off)                 # bit-identical
        assert not d.get("hits_total") and not d.get("misses_total")


class TestEndToEndFusion:
    def test_fusion_decode_count_drops_and_output_identical(self, tmp_path):
        """Per-block affine fusion over overlapping halos: cache-on must
        decode strictly fewer chunks than cache-off, report a non-zero hit
        rate, and write a bit-identical container."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
        from bigstitcher_spark_tpu.utils.testdata import (
            make_synthetic_project,
        )
        from bigstitcher_spark_tpu.utils.viewselect import (
            maximal_bounding_box,
        )

        proj = make_synthetic_project(str(tmp_path / "proj"), jitter=0.0)
        sd = SpimData.load(proj.xml_path)
        views = sd.view_ids()
        bbox = maximal_bounding_box(sd, views)

        def run(tag):
            loader = ViewLoader(sd)        # fresh per run: no dataset memo
            out_root = str(tmp_path / f"fused_{tag}.n5")
            shutil.rmtree(out_root, ignore_errors=True)
            store = ChunkStore.create(out_root, StorageFormat.N5)
            out = store.create_dataset("fused", bbox.shape, (32, 32, 16),
                                       "uint16")
            base = metrics.get_registry().snapshot()
            fuse_volume(sd, loader, views, out, bbox,
                        block_size=(32, 32, 16), block_scale=(1, 1, 1),
                        out_dtype="uint16", min_intensity=0.0,
                        max_intensity=65535.0, devices=1,
                        device_resident=False)
            delta = metrics.get_registry().snapshot_delta(base)
            decode_bytes = sum(
                int(v) for k, v in delta.items()
                if k.startswith("bst_io_read_bytes_total")
                and "cache" not in k and isinstance(v, (int, float)))
            return out.read_full(), decode_bytes, delta

        os.environ["BST_CHUNK_CACHE_BYTES"] = "0"
        try:
            vol_off, bytes_off, _ = run("off")
        finally:
            os.environ["BST_CHUNK_CACHE_BYTES"] = str(64 << 20)
        chunkcache.get_cache().clear()
        vol_on, bytes_on, delta_on = run("on")

        assert np.array_equal(vol_on, vol_off)         # bit-identical
        hits = int(delta_on.get("bst_chunk_cache_hits_total", 0))
        assert hits > 0, json.dumps(delta_on, default=str)
        # overlapping halos re-decoded the same chunks with the cache off;
        # with it on, decode traffic (non-cache read bytes) must shrink
        assert bytes_on < bytes_off, (bytes_on, bytes_off)

"""DoG interest-point detection: kernel-level golden tests on synthetic beads
(the unit-test strategy SURVEY.md §4 calls for — the reference itself only
smoke-tests) plus project-level round trips through the CLI + store."""

import numpy as np
import pytest
from click.testing import CliRunner


def _bead_volume(shape, positions, sigma=1.8, amp=1000.0, bg=100.0):
    vol = np.full(shape, bg, np.float32)
    r = int(np.ceil(4 * sigma))
    ax = np.arange(-r, r + 1, dtype=np.float32)
    for p in positions:
        ip = np.round(p).astype(int)
        fr = np.asarray(p) - ip
        gx = np.exp(-((ax - fr[0]) ** 2) / (2 * sigma**2))
        gy = np.exp(-((ax - fr[1]) ** 2) / (2 * sigma**2))
        gz = np.exp(-((ax - fr[2]) ** 2) / (2 * sigma**2))
        blob = amp * gx[:, None, None] * gy[None, :, None] * gz[None, None, :]
        vol[ip[0] - r:ip[0] + r + 1, ip[1] - r:ip[1] + r + 1,
            ip[2] - r:ip[2] + r + 1] += blob
    return vol


class TestDogKernel:
    def test_single_bead_subpixel(self):
        from bigstitcher_spark_tpu.ops.dog import dog_block, localize_quadratic

        true = np.array([24.3, 25.7, 22.5])
        vol = _bead_volume((48, 48, 48), [true])
        dog, mask = dog_block(vol, np.float32(0.0), np.float32(1200.0),
                              np.float32(0.005), 1.8)
        dog, mask = np.asarray(dog), np.asarray(mask)
        coords = np.argwhere(mask)
        assert len(coords) == 1
        sub, vals = localize_quadratic(dog, coords)
        assert np.linalg.norm(sub[0] - true) < 0.35
        assert vals[0] > 0.005

    def test_threshold_rejects_noise(self):
        from bigstitcher_spark_tpu.ops.dog import dog_block

        rng = np.random.default_rng(3)
        vol = rng.normal(100.0, 2.0, (40, 40, 40)).astype(np.float32)
        _, mask = dog_block(vol, np.float32(0.0), np.float32(1000.0),
                            np.float32(0.008), 1.8)
        assert int(np.asarray(mask).sum()) == 0

    def test_minima_detection(self):
        from bigstitcher_spark_tpu.ops.dog import dog_block

        true = np.array([20.0, 20.0, 20.0])
        vol = 2000.0 - _bead_volume((40, 40, 40), [true], bg=0.0)
        _, mask = dog_block(vol, np.float32(0.0), np.float32(2000.0),
                            np.float32(0.005), 1.8,
                            find_max=False, find_min=True)
        coords = np.argwhere(np.asarray(mask))
        assert len(coords) == 1
        assert np.linalg.norm(coords[0] - true) <= 1.0

    def test_blocked_equals_whole(self):
        """Halo correctness: detections from a blocked run must equal the
        single-volume run (the reference's ±1px-halo seamlessness invariant,
        SparkInterestPointDetection.java:412-422)."""
        from bigstitcher_spark_tpu.ops.dog import dog_block, dog_halo

        rng = np.random.default_rng(7)
        pos = rng.uniform(10, 86, (25, 3))
        vol = _bead_volume((96, 96, 96), pos)
        _, mask_full = dog_block(vol, np.float32(0.0), np.float32(1200.0),
                                 np.float32(0.005), 1.8)
        full_set = {tuple(c) for c in np.argwhere(np.asarray(mask_full))}

        halo = dog_halo(1.8)
        got = set()
        for off in [(0, 0, 0), (48, 0, 0), (0, 48, 0), (48, 48, 0),
                    (0, 0, 48), (48, 0, 48), (0, 48, 48), (48, 48, 48)]:
            lo = np.maximum(np.array(off) - halo, 0)
            hi = np.minimum(np.array(off) + 48 + halo, 96)
            pad_lo = halo - (np.array(off) - lo)
            block = vol[lo[0]:hi[0], lo[1]:hi[1], lo[2]:hi[2]]
            block = np.pad(block, [(int(halo - (off[d] - lo[d])),
                                    int(halo - (hi[d] - off[d] - 48)))
                                   for d in range(3)], mode="reflect")
            _, m = dog_block(block, np.float32(0.0), np.float32(1200.0),
                             np.float32(0.005), 1.8,
                             origin=np.array(off, np.int32) - halo)
            m = np.asarray(m)
            core = m[halo:halo + 48, halo:halo + 48, halo:halo + 48]
            for c in np.argwhere(core):
                got.add(tuple(c + np.array(off)))
        # interior detections must agree exactly; allow border-artifact
        # differences within the blur radius of the volume edge
        interior = {c for c in full_set if all(halo <= v < 96 - halo for v in c)}
        assert interior <= got
        extra = got - full_set
        assert all(any(v < halo or v >= 96 - halo for v in c) for c in extra)


class TestDetectionPipeline:
    @pytest.fixture(scope="class")
    def project(self, tmp_path_factory):
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        return make_synthetic_project(
            str(tmp_path_factory.mktemp("det") / "proj"),
            n_tiles=(2, 1, 1), tile_size=(96, 96, 48), overlap=24,
            jitter=2.0, seed=5, n_beads_per_tile=30,
        )

    def test_detect_recovers_beads(self, project):
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points,
        )

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        params = DetectionParams(downsample_xy=1, downsample_z=1,
                                 block_size=(64, 64, 64))
        dets = detect_interest_points(sd, loader, views, params, progress=False)
        assert len(dets) == 2
        for det in dets:
            off = project.true_offsets[det.view.setup]
            local_beads = project.bead_positions - off
            inside = np.all(
                (local_beads >= 6) & (local_beads <= np.array([96, 96, 48]) - 7),
                axis=1,
            )
            local_beads = local_beads[inside]
            assert len(det.points) >= 0.7 * len(local_beads)
            # every expected bead has a detection within 1 px
            d = np.linalg.norm(
                local_beads[:, None, :] - det.points[None, :, :], axis=2
            )
            matched = (d.min(axis=1) < 1.0).mean()
            assert matched > 0.8

    def test_downsampled_coords_corrected(self, project):
        """Detection at ds=2,2,1 must return full-res coordinates matching
        the ds=1 run (correctForDownsampling)."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points,
        )

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)[:1]
        full = detect_interest_points(
            sd, loader, views,
            DetectionParams(downsample_xy=1, downsample_z=1,
                            block_size=(64, 64, 64)),
            progress=False,
        )[0]
        ds = detect_interest_points(
            sd, loader, views,
            DetectionParams(downsample_xy=2, downsample_z=1, sigma=1.3,
                            block_size=(64, 64, 64)),
            progress=False,
        )[0]
        assert len(ds.points) > 0
        d = np.linalg.norm(
            full.points[:, None, :] - ds.points[None, :, :], axis=2
        )
        # most downsampled detections coincide with a full-res one (<1.5px)
        assert (d.min(axis=0) < 1.5).mean() > 0.7

    def test_overlapping_only_and_store(self, project, tmp_path):
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points, save_detections,
        )

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        params = DetectionParams(
            downsample_xy=1, downsample_z=1, overlapping_only=True,
            store_intensities=True, block_size=(64, 64, 64),
        )
        dets = detect_interest_points(sd, loader, views, params, progress=False)
        # tiles are 96 wide with ~24 overlap: view 0's overlap is x>~70
        for det, xlim in zip(dets, (60.0, 36.0)):
            assert len(det.points) > 0
            if det.view.setup == 0:
                assert np.all(det.points[:, 0] >= xlim)
            else:
                assert np.all(det.points[:, 0] <= xlim)
            assert det.intensities is not None
            assert np.all(det.intensities > 100.0)  # beads are above background

        store = InterestPointStore(str(tmp_path / "ip.n5"))
        save_detections(sd, store, dets, params)
        for det in dets:
            ids, locs = store.load_points(det.view, params.label)
            assert len(ids) == len(det.points)
            np.testing.assert_allclose(locs, det.points, atol=1e-9)
            assert det.view in sd.interest_points
            assert "beads" in sd.interest_points[det.view]

    def test_max_spots(self, project):
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points,
        )

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)[:1]
        dets = detect_interest_points(
            sd, loader, views,
            DetectionParams(downsample_xy=1, downsample_z=1, max_spots=5,
                            block_size=(64, 64, 64)),
            progress=False,
        )
        assert len(dets[0].points) == 5


def test_cli_detect(tmp_path):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(64, 64, 32),
        overlap=16, jitter=0.0, seed=2, n_beads_per_tile=15,
    )
    runner = CliRunner()
    res = runner.invoke(cli, [
        "detect-interestpoints", "-x", proj.xml_path,
        "-dsxy", "1", "-dsz", "1", "--blockSize", "64,64,32",
        "--label", "beads",
    ])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(proj.xml_path)
    assert ViewId(0, 0) in sd.interest_points
    store = InterestPointStore.for_project(sd)
    ids, locs = store.load_points(ViewId(0, 0), "beads")
    assert len(ids) > 5


def test_topk_truncation_warns_and_keeps_strongest(tmp_path):
    """When a block holds more extrema than the device compaction budget,
    the K strongest survive and a warning reports the truncation."""
    import warnings

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, detect_interest_points,
    )
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(1, 1, 1), tile_size=(64, 64, 32),
        overlap=8, n_beads_per_tile=25, seed=11)
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)
    params_full = DetectionParams(downsample_xy=1, downsample_z=1,
                                  block_size=(64, 64, 32))
    full = detect_interest_points(sd, loader, sd.view_ids(), params_full,
                                  progress=False)
    n_full = len(full[0].points)
    k = 4
    assert n_full >= 2 * k, "fixture must over-fill the truncation budget"
    params_small = DetectionParams(downsample_xy=1, downsample_z=1,
                                   block_size=(64, 64, 32),
                                   max_candidates_per_block=k)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        trunc = detect_interest_points(sd, loader, sd.view_ids(),
                                       params_small, progress=False)
    assert any("strongest" in str(x.message) for x in w)
    assert len(trunc[0].points) == k
    # the kept spots are among the strongest of the full set (selection is
    # by |raw response| BEFORE subpixel refinement, so exact rank can shift
    # within near-ties)
    cutoff = np.sort(np.abs(full[0].values))[-(2 * k):][0]
    assert (np.abs(trunc[0].values) >= cutoff * 0.98).all()


def test_blur_strategies_agree_on_core():
    """The FFT transfer-function DoG (CPU default) and the Toeplitz-GEMM
    blur chain (TPU default) must agree on the halo core to float rounding —
    they apply the same truncated discrete kernels with different edge
    topologies (circular vs reflect), which only differ inside the halo."""
    import numpy as np

    from bigstitcher_spark_tpu.ops.dog import (
        DOG_K, _blur_separable, _dog_response_fft, dog_halo,
        gaussian_kernel_1d,
    )

    rng = np.random.default_rng(4)
    x = rng.random((48, 40, 32)).astype(np.float32)
    s1 = 1.8
    k1 = gaussian_kernel_1d(s1)
    k2 = gaussian_kernel_1d(s1 * DOG_K)
    gemm = np.asarray(_blur_separable(x, [k1] * 3)
                      - _blur_separable(x, [k2] * 3))
    fft = np.asarray(_dog_response_fft(x, k1, k2))
    h = dog_halo(s1)
    core = (slice(h, -h),) * 3
    np.testing.assert_allclose(fft[core], gemm[core], atol=2e-6)


def test_flat_view_with_degenerate_bounds_detects_nothing():
    """min_intensity == max_intensity (data-derived bounds on a blank or
    saturated tile) must yield ZERO detections: the folded normalization
    scale gates to 0 instead of amplifying blur roundoff by 1/1e-20
    (r5 review finding)."""
    import numpy as np

    from bigstitcher_spark_tpu.ops.dog import dog_block

    flat = np.full((32, 32, 32), 12345, np.uint16)
    dog, mask = dog_block(flat, np.float32(12345), np.float32(12345),
                          np.float32(0.008), 1.8)
    assert int(np.asarray(mask).sum()) == 0
    assert float(np.abs(np.asarray(dog)).max()) == 0.0

"""Megafusion acceptance: HBM handoff edges in the streaming executor +
fused per-block detect+extract programs.

Tier-1 coverage demanded by the PR: fused detect+extract bitwise-equal to
the staged two-pass path (including zero-peak and tail blocks, with the
one-compiled-dispatch trace assertion), handoff-on vs handoff-off pipeline
bit-identity, spill-under-tiny-budget correctness, and the zero-D2H
trace-counter assertion on a handoff edge.
"""

import os
import numpy as np
import pytest

from bigstitcher_spark_tpu import profiling
from bigstitcher_spark_tpu.dag import example_spec, run_pipeline
from bigstitcher_spark_tpu.dag import stream
from bigstitcher_spark_tpu.io.chunkstore import (
    ChunkStore,
    StorageFormat,
    _DAG_HOOKS,
)
from bigstitcher_spark_tpu.observe import metrics, trace


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()
    yield
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()


def _mk_project(root, **kw):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    spec = dict(n_tiles=(2, 1, 1), tile_size=(64, 64, 32), overlap=16,
                jitter=1.0, n_beads_per_tile=20, seed=7)
    spec.update(kw)
    return make_synthetic_project(str(root), **spec).xml_path


def _small_blocks(spec):
    for s in spec["stages"]:
        if s["id"] == "resave":
            s["args"] += ["--blockSize", "32,32,16", "-ds", "1,1,1; 2,2,1"]
        if s["id"] == "create":
            s["args"] += ["--blockSize", "32,32,16"]
    return spec


# -- fused detect+extract ----------------------------------------------------


class TestFusedDetectExtract:
    def _batch(self, shape, halo, zero_first=True):
        """A block batch including one zero-peak (all-flat) block; peaks
        are planted inside the halo-masked core so they survive top-K."""
        rng = np.random.default_rng(3)
        blocks = rng.random((4, *shape), np.float32) * 0.2
        for b in range(1 if zero_first else 0, 4):
            for _ in range(8):
                p = tuple(rng.integers(halo + 2, s - halo - 2)
                          for s in shape)
                blocks[(b, *p)] += 5.0
        if zero_first:
            blocks[0] = 0.0
        import jax.numpy as jnp

        lo = jnp.zeros(4, jnp.float32)
        hi = jnp.ones(4, jnp.float32)
        thr = jnp.full(4, 0.005, jnp.float32)
        org = jnp.zeros((4, 3), jnp.int32)
        return jnp.asarray(blocks), lo, hi, thr, org

    @pytest.mark.parametrize("shape", [(40, 40, 28), (26, 40, 22)])
    def test_fused_bitwise_equals_staged(self, shape):
        # the cramped tail shape's core is too small for peaks to stay
        # distinct under DoG smoothing; descriptor-validity (needs pool+1
        # separated peaks) is asserted on the roomy shape only
        expect_dvalid = shape == (40, 40, 28)
        """One fused program vs the staged two-dispatch path: all seven
        outputs bitwise identical, on a full-size and a tail-size block
        shape, with a zero-peak block in the batch."""
        from bigstitcher_spark_tpu.models.detection import (
            _make_dog_kernel_cached,
        )
        from bigstitcher_spark_tpu.ops.dog import dog_halo

        halo = dog_halo(1.8)
        args = self._batch(shape, halo)
        fused_k = _make_dog_kernel_cached(
            1, 1.8, True, False, 64, halo, (1, 1, 1), (3, 1, True))
        staged_k = _make_dog_kernel_cached(
            1, 1.8, True, False, 64, halo, (1, 1, 1), (3, 1, False))

        profiling.enable(True)
        profiling.get().reset()
        fused = [np.asarray(o) for o in fused_k(*args)]
        st = profiling.get().stats()
        assert st["detection.kernel"].count == 1
        assert "detection.extract" not in st  # ONE compiled dispatch

        profiling.get().reset()
        staged = [np.asarray(o) for o in staged_k(*args)]
        st = profiling.get().stats()
        assert st["detection.kernel"].count == 1
        assert st["detection.extract"].count == 1

        assert len(fused) == len(staged) == 7
        for f, s in zip(fused, staged):
            assert f.dtype == s.dtype and np.array_equal(f, s)
        # the zero-peak block produced no valid peaks and no descriptors
        assert not fused[3][0].any() and not fused[6][0].any()
        assert np.isfinite(fused[5]).all()
        # the planted peaks were detected ...
        assert fused[3][1:].any()
        if expect_dvalid:  # ... and produced descriptor-valid points
            assert fused[6][1:].any()

    def test_detect_interest_points_fused_vs_staged(self, tmp_path,
                                                    monkeypatch):
        """Model-level parity over a real synthetic project: points,
        values, descriptors and validity bitwise identical between
        BST_FUSED_DETECT=1 and =0; fused runs dispatch zero standalone
        extract programs."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams,
            detect_interest_points,
        )

        xml = _mk_project(tmp_path / "proj")
        sd = SpimData.load(xml)
        loader = ViewLoader(sd)
        # one block per view (tail-shape bitwise parity is pinned by
        # test_fused_bitwise_equals_staged above — extra shape buckets
        # here would only recompile both kernel variants per shape)
        params = DetectionParams(downsample_xy=1, block_size=(64, 64, 32),
                                 extract_descriptors=True,
                                 max_candidates_per_block=64)

        def run():
            profiling.enable(True)
            profiling.get().reset()
            dets = detect_interest_points(sd, loader, sd.view_ids(), params,
                                          progress=False)
            return dets, profiling.get().stats()

        monkeypatch.setenv("BST_FUSED_DETECT", "1")
        fused, st_f = run()
        monkeypatch.setenv("BST_FUSED_DETECT", "0")
        staged, st_s = run()

        assert st_f["detection.kernel"].count > 0
        assert "detection.extract" not in st_f
        assert st_s["detection.extract"].count > 0

        assert len(fused) == len(staged) > 0
        some_points = False
        for a, b in zip(fused, staged):
            assert np.array_equal(a.points, b.points)
            assert np.array_equal(a.values, b.values)
            assert a.descriptors is not None and b.descriptors is not None
            assert np.array_equal(a.descriptors, b.descriptors)
            assert np.array_equal(a.descriptor_valid, b.descriptor_valid)
            assert len(a.descriptors) == len(a.points)
            some_points |= len(a.points) > 0
        assert some_points


# -- the HBM handoff edge ----------------------------------------------------


class TestHandoffEdge:
    def _edge_env(self, tmp_path):
        store = ChunkStore.create(str(tmp_path / "edge.n5"),
                                  StorageFormat.N5)
        ds = store.create_dataset("s0", (64, 32, 16), (16, 16, 16),
                                  "uint16")
        prod = stream.StageToken("prod", "t")
        cons = stream.StageToken("cons", "t")
        edge = stream.EdgeState("e", store.root, {prod}, {cons})
        reg = stream.registry()
        reg.register([edge])
        return reg, store, ds, prod, cons, edge

    def test_device_publish_serves_device_with_zero_d2h(self, tmp_path,
                                                        monkeypatch):
        """A device-published block is served to the consumer as a DEVICE
        array: the D2H transfer counter does not move and the edge rereads
        zero container bytes."""
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("BST_DAG_HANDOFF_BYTES", str(1 << 30))
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        d2h = metrics.counter("bst_xfer_d2h_bytes_total")
        hb = metrics.counter("bst_dag_handoff_blocks_total")
        served = metrics.counter("bst_dag_handoff_bytes_served_total")
        data = (np.arange(64 * 32 * 16, dtype=np.uint16)
                .reshape(64, 32, 16))
        try:
            d0, h0, s0 = d2h.value, hb.value, served.value
            with stream.stage_scope(prod):
                assert ds.write_device(jnp.asarray(data), (0, 0, 0))
            assert hb.value - h0 == 8          # 4x2x1 chunk grid, all HBM
            with stream.stage_scope(cons):
                out = ds.read_device((0, 0, 0), (32, 32, 16))
            assert isinstance(out, jax.Array)
            assert served.value - s0 > 0
            assert d2h.value - d0 == 0         # ZERO D2H on the edge
            assert edge.bytes_reread == 0
            assert edge.blocks_handoff == 8
            assert np.array_equal(np.asarray(out), data[:32])
        finally:
            reg.unregister([edge])
        assert _DAG_HOOKS[0] is None
        # unregister flushed the unconsumed device blocks to the container
        assert np.array_equal(
            store.open_dataset("s0").read((0, 0, 0), (64, 32, 16)), data)

    def test_tiny_budget_spills_and_stays_correct(self, tmp_path,
                                                  monkeypatch):
        """Under a budget smaller than the published set the oldest chunks
        spill to the host tier; a host consumer still reads exact bytes
        and backpressure accounting stays balanced."""
        import jax.numpy as jnp

        # room for ~2 of the 8 uint16 16^3 chunks
        monkeypatch.setenv("BST_DAG_HANDOFF_BYTES", str(2 * 16 ** 3 * 2))
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        spill = metrics.counter("bst_dag_handoff_spill_bytes_total")
        data = (np.arange(64 * 32 * 16, dtype=np.uint16)
                .reshape(64, 32, 16))
        try:
            sp0 = spill.value
            with stream.stage_scope(prod):
                assert ds.write_device(jnp.asarray(data), (0, 0, 0))
            assert spill.value - sp0 > 0       # budget pressure spilled
            with stream.stage_scope(cons):
                out = ds.read((0, 0, 0), (64, 32, 16))
            assert np.array_equal(out, data)
            assert edge.bytes_reread == 0      # spills land in the LRU
            assert edge.blocks_published == 8
        finally:
            reg.unregister([edge])

    def test_handoff_off_is_inert(self, tmp_path, monkeypatch):
        """BST_DAG_HANDOFF_BYTES=0: write_device refuses, producers take
        the host path bit-identically (the off semantics the knob
        documents)."""
        import jax.numpy as jnp

        monkeypatch.setenv("BST_DAG_HANDOFF_BYTES", "0")
        reg, store, ds, prod, cons, edge = self._edge_env(tmp_path)
        try:
            assert not stream.handoff_active()
            data = np.ones((16, 16, 16), np.uint16)
            with stream.stage_scope(prod):
                assert not ds.write_device(jnp.asarray(data), (0, 0, 0))
                ds.write(data, (0, 0, 0))
            with stream.stage_scope(cons):
                assert ds.read_device((0, 0, 0), (16, 16, 16)) is None
                out = ds.read((0, 0, 0), (16, 16, 16))
            assert np.array_equal(out, data)
            assert edge.blocks_handoff == 0
        finally:
            reg.unregister([edge])


# -- streamed pipeline: handoff on/off/tiny bit-identity ---------------------


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory):
    """The streamed pipeline with the handoff OFF: the bit-exactness
    reference the on/tiny runs are compared against (off-vs-staged parity
    is test_dag's acceptance test)."""
    root = tmp_path_factory.mktemp("handoff-off")
    xml = _mk_project(root / "proj")
    spec = _small_blocks(example_spec(xml))
    os.environ.pop("BST_DAG_HANDOFF_BYTES", None)
    res = run_pipeline(spec, workdir=str(root))
    assert res.ok, res.to_dict()
    return os.path.dirname(xml)


def _run_with_budget(tmp_path_factory, name, budget, monkeypatch):
    root = tmp_path_factory.mktemp(name)
    xml = _mk_project(root / "proj")
    spec = _small_blocks(example_spec(xml))
    monkeypatch.setenv("BST_DAG_HANDOFF_BYTES", str(budget))
    res = run_pipeline(spec, workdir=str(root))
    assert res.ok, res.to_dict()
    return os.path.dirname(xml), res.to_dict()


def _assert_outputs_equal(proj_a, proj_b):
    for name in ("ch0tp0/s0", "ch0tp0/s1"):
        a = ChunkStore.open(
            f"{proj_a}/pipeline-fused.n5").open_dataset(name).read_full()
        b = ChunkStore.open(
            f"{proj_b}/pipeline-fused.n5").open_dataset(name).read_full()
        assert np.array_equal(a, b), name

    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.io.spimdata import SpimData

    sa = SpimData.load(os.path.join(proj_a, "pipeline-resaved.xml"))
    sb = SpimData.load(os.path.join(proj_b, "pipeline-resaved.xml"))
    ia, ib = (InterestPointStore.for_project(sa),
              InterestPointStore.for_project(sb))
    for v in sa.view_ids():
        pa, _ = ia.load_points(v, "beads")
        pb, _ = ib.load_points(v, "beads")
        assert len(pa) and np.array_equal(pa, pb)


class TestHandoffPipelineParity:
    def test_handoff_on_bit_identical_with_handoff_traffic(
            self, reference_run, tmp_path_factory, monkeypatch):
        """Same spec, BST_DAG_HANDOFF_BYTES=1G: outputs bit-identical to
        the off run, with real handoff traffic (blocks served from device)
        and zero container rereads on every streamed edge."""
        hb = metrics.counter("bst_dag_handoff_blocks_total")
        h0 = hb.value
        proj, summary = _run_with_budget(tmp_path_factory, "handoff-on",
                                         1 << 30, monkeypatch)
        assert hb.value - h0 > 0
        by_edge = {e["edge"]: e for e in summary["edges"]}
        assert by_edge["fused"]["blocks_handoff"] > 0
        # the consumer was actually SERVED device arrays (not merely
        # published-then-spilled): the zero-copy path end to end
        assert by_edge["fused"]["bytes_handoff"] > 0
        for e in summary["edges"]:
            assert e["bytes_reread"] == 0, e
        _assert_outputs_equal(proj, reference_run)

    def test_tiny_budget_spills_bit_identical(self, reference_run,
                                              tmp_path_factory,
                                              monkeypatch):
        """A 256 KB budget forces constant spilling; the pipeline output
        must not change by a bit."""
        spill = metrics.counter("bst_dag_handoff_spill_bytes_total")
        sp0 = spill.value
        proj, summary = _run_with_budget(tmp_path_factory, "handoff-tiny",
                                         256 << 10, monkeypatch)
        assert spill.value - sp0 > 0
        _assert_outputs_equal(proj, reference_run)


# -- tune advisor ------------------------------------------------------------


class TestHandoffAdvisor:
    def test_fires_when_off_with_streamed_traffic(self):
        from bigstitcher_spark_tpu.tune.advisor import advise_record

        rec = {"seconds": 10.0, "metrics": {
            "bst_dag_blocks_streamed_total": 64,
            "bst_dag_bytes_elided_total": 512 << 20,
        }}
        d = [x for x in advise_record(rec) if x.rule == "dag_handoff_miss"]
        assert d and d[0].knob == "BST_DAG_HANDOFF_BYTES"
        v = int(d[0].suggested_value)
        assert (64 << 20) <= v <= (8 << 30)
        assert d[0].evidence["blocks_streamed"] == 64

    def test_fires_when_undersized(self):
        from bigstitcher_spark_tpu.tune.advisor import advise_record

        rec = {"seconds": 10.0,
               "params": {"overrides":
                          {"BST_DAG_HANDOFF_BYTES": str(128 << 20)}},
               "metrics": {
                   "bst_dag_blocks_streamed_total": 64,
                   "bst_dag_handoff_blocks_total": 40,
                   "bst_dag_handoff_bytes_served_total": 200 << 20,
                   "bst_dag_handoff_spill_bytes_total": 120 << 20,
               }}
        d = [x for x in advise_record(rec) if x.rule == "dag_handoff_miss"]
        assert d and int(d[0].suggested_value) == 256 << 20
        assert d[0].evidence["spill_bytes"] == 120 << 20

    def test_silent_when_healthy_or_insignificant(self):
        from bigstitcher_spark_tpu.tune.advisor import advise_record

        healthy = {"seconds": 10.0,
                   "params": {"overrides":
                              {"BST_DAG_HANDOFF_BYTES": str(1 << 30)}},
                   "metrics": {
                       "bst_dag_blocks_streamed_total": 64,
                       "bst_dag_handoff_blocks_total": 64,
                       "bst_dag_handoff_bytes_served_total": 400 << 20,
                   }}
        assert not [x for x in advise_record(healthy)
                    if x.rule == "dag_handoff_miss"]
        noise = {"seconds": 10.0, "metrics": {
            "bst_dag_blocks_streamed_total": 3}}
        assert not [x for x in advise_record(noise)
                    if x.rule == "dag_handoff_miss"]

    def test_knob_is_tunable_for_tune_run(self):
        from bigstitcher_spark_tpu import config

        k = config.KNOBS["BST_DAG_HANDOFF_BYTES"]
        assert k.tunable is not None
        assert k.tunable.lo and k.tunable.hi

"""Direct tests of the shared sharded work loop (parallel/mesh.py):
ordering, the early-dispatch device double-buffering, and its
interaction with the retry path — a consume failure in a batch whose
successor was already dispatched must still retry cleanly and deliver
every item's correct output exactly once to a successful consume.
Reference failure model: RetryTrackerSpark.java:28-61 (resubmit ≤5)."""

from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np
import pytest

from bigstitcher_spark_tpu.parallel.mesh import run_sharded_batches
from bigstitcher_spark_tpu.parallel.retry import RetryError


def _kernel(x):
    return x * 2.0


def _kernel_two_outputs(x):
    return x * 2.0, x + 1.0


class TestRunShardedBatches:
    def _run(self, n_items, consume, kernel=_kernel, per_dev=1):
        items = list(range(n_items))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(
                items,
                build=lambda it: (np.full((4,), float(it), np.float32),),
                kernel=jax.jit(kernel),
                consume=consume,
                n_dev=1,
                pool=pool,
                per_dev=per_dev,
            )

    def test_every_item_consumed_with_its_own_output(self):
        got = {}

        def consume(it, out):
            got[it] = np.asarray(out).copy()

        self._run(7, consume, per_dev=2)
        assert sorted(got) == list(range(7))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_multi_output_kernels(self):
        got = {}

        def consume(it, a, b):
            got[it] = (np.asarray(a).copy(), np.asarray(b).copy())

        self._run(5, consume, kernel=_kernel_two_outputs, per_dev=2)
        for it, (a, b) in got.items():
            np.testing.assert_allclose(a, np.full((4,), 2.0 * it))
            np.testing.assert_allclose(b, np.full((4,), it + 1.0))

    def test_consume_failure_retries_without_duplicate_or_loss(self):
        # fail item 2's consume ONCE, on a run long enough that item 3's
        # batch has been early-dispatched by the time 2 drains: the retry
        # must re-run batch 2 only, and every item lands exactly once
        got = {}
        fails = {"n": 0}

        def consume(it, out):
            if it == 2 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient write failure")
            assert it not in got, f"item {it} consumed twice"
            got[it] = np.asarray(out).copy()

        self._run(6, consume)
        assert sorted(got) == list(range(6))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))
        assert fails["n"] == 1

    def test_transient_build_failure_recovers(self):
        # whether the failing build is first hit by a neighbour's early
        # dispatch (swallowed, re-staged by its own batch) or by its own
        # batch (retried), every item must land exactly once with its data
        fails = {"n": 0}
        got = {}

        def build(it):
            if it == 3 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient read failure")
            return (np.full((4,), float(it), np.float32),)

        def consume(it, out):
            assert it not in got
            got[it] = np.asarray(out).copy()

        items = list(range(6))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(items, build=build, kernel=jax.jit(_kernel),
                                consume=consume, n_dev=1, pool=pool)
        assert sorted(got) == items
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_persistent_failure_raises_retry_error(self):
        def consume(it, out):
            raise RuntimeError("disk full")

        with pytest.raises(RetryError):
            self._run(2, consume)

"""Direct tests of the shared sharded work loop (parallel/mesh.py):
ordering, the early-dispatch device double-buffering, and its
interaction with the retry path — a consume failure in a batch whose
successor was already dispatched must still retry cleanly and deliver
every item's correct output exactly once to a successful consume.
Reference failure model: RetryTrackerSpark.java:28-61 (resubmit ≤5)."""

from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np
import pytest

from bigstitcher_spark_tpu.parallel.mesh import run_sharded_batches
from bigstitcher_spark_tpu.parallel.retry import RetryError


def _kernel(x):
    return x * 2.0


def _kernel_two_outputs(x):
    return x * 2.0, x + 1.0


class TestRunShardedBatches:
    def _run(self, n_items, consume, kernel=_kernel, per_dev=1):
        items = list(range(n_items))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(
                items,
                build=lambda it: (np.full((4,), float(it), np.float32),),
                kernel=jax.jit(kernel),
                consume=consume,
                n_dev=1,
                pool=pool,
                per_dev=per_dev,
            )

    def test_every_item_consumed_with_its_own_output(self):
        got = {}

        def consume(it, out):
            got[it] = np.asarray(out).copy()

        self._run(7, consume, per_dev=2)
        assert sorted(got) == list(range(7))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_multi_output_kernels(self):
        got = {}

        def consume(it, a, b):
            got[it] = (np.asarray(a).copy(), np.asarray(b).copy())

        self._run(5, consume, kernel=_kernel_two_outputs, per_dev=2)
        for it, (a, b) in got.items():
            np.testing.assert_allclose(a, np.full((4,), 2.0 * it))
            np.testing.assert_allclose(b, np.full((4,), it + 1.0))

    def test_consume_failure_retries_without_duplicate_or_loss(self):
        # fail item 2's consume ONCE, on a run long enough that item 3's
        # batch has been early-dispatched by the time 2 drains: the retry
        # must re-run batch 2 only, and every item lands exactly once
        got = {}
        fails = {"n": 0}

        def consume(it, out):
            if it == 2 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient write failure")
            assert it not in got, f"item {it} consumed twice"
            got[it] = np.asarray(out).copy()

        self._run(6, consume)
        assert sorted(got) == list(range(6))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))
        assert fails["n"] == 1

    def test_transient_build_failure_recovers(self):
        # whether the failing build is first hit by a neighbour's early
        # dispatch (swallowed, re-staged by its own batch) or by its own
        # batch (retried), every item must land exactly once with its data
        fails = {"n": 0}
        got = {}

        def build(it):
            if it == 3 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient read failure")
            return (np.full((4,), float(it), np.float32),)

        def consume(it, out):
            assert it not in got
            got[it] = np.asarray(out).copy()

        items = list(range(6))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(items, build=build, kernel=jax.jit(_kernel),
                                consume=consume, n_dev=1, pool=pool)
        assert sorted(got) == items
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_persistent_failure_raises_retry_error(self):
        def consume(it, out):
            raise RuntimeError("disk full")

        with pytest.raises(RetryError):
            self._run(2, consume)


class TestInflightWindow:
    """The byte-budgeted dispatch window (BST_INFLIGHT_BYTES /
    utils.devicemem): the ledger must never exceed budget + one batch
    (the current batch always dispatches), a generous budget must let the
    loop run multiple batches ahead, and a starved budget must degrade to
    strict one-batch-at-a-time without losing items."""

    def _run(self, n_items, consume, build=None, per_dev=1):
        from bigstitcher_spark_tpu.utils import devicemem

        devicemem._HIGHWATER.set(0)
        devicemem._INFLIGHT.set(0)
        items = list(range(n_items))
        build = build or (
            lambda it: (np.full((1024,), float(it), np.float32),))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(
                items, build=build, kernel=jax.jit(_kernel), consume=consume,
                n_dev=1, pool=pool, per_dev=per_dev, workspace_mult=1.0,
            )
        return devicemem._HIGHWATER.value

    def test_highwater_never_exceeds_budget_plus_current(self, monkeypatch):
        batch_bytes = 1024 * 4                         # one item per batch
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(2 * batch_bytes))
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.02)   # give later builds time to stage
            got[it] = np.asarray(out).copy()

        hw = self._run(8, consume)
        assert sorted(got) == list(range(8))
        # budget (2 batches) + the always-dispatched current batch
        assert hw <= 3 * batch_bytes, hw

    def test_generous_budget_runs_ahead(self, monkeypatch):
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(1 << 30))
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.02)
            got[it] = np.asarray(out).copy()

        hw = self._run(8, consume)
        assert sorted(got) == list(range(8))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((1024,), 2.0 * it))
        assert hw >= 2 * 1024 * 4, hw                  # >= 2 batches in flight

    def test_starved_budget_still_completes(self, monkeypatch):
        monkeypatch.setenv("BST_INFLIGHT_BYTES", "1")
        got = {}

        def consume(it, out):
            got[it] = np.asarray(out).copy()

        hw = self._run(6, consume, per_dev=2)
        assert sorted(got) == list(range(6))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((1024,), 2.0 * it))

    def test_retry_restages_inside_window(self, monkeypatch):
        # a consume failure while successors are dispatched ahead must
        # retry cleanly: every item lands exactly once, ledger drains to 0
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(1 << 30))
        from bigstitcher_spark_tpu.utils import devicemem

        fails = {"n": 0}
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.01)
            if it == 2 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient write failure")
            assert it not in got, f"item {it} consumed twice"
            got[it] = np.asarray(out).copy()

        self._run(8, consume)
        assert sorted(got) == list(range(8)) and fails["n"] == 1
        assert devicemem._INFLIGHT.value == 0

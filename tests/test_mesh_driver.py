"""Direct tests of the shared sharded work loop (parallel/mesh.py):
ordering, the early-dispatch device double-buffering, and its
interaction with the retry path — a consume failure in a batch whose
successor was already dispatched must still retry cleanly and deliver
every item's correct output exactly once to a successful consume.
Reference failure model: RetryTrackerSpark.java:28-61 (resubmit ≤5)."""

from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np
import pytest

from bigstitcher_spark_tpu.parallel.mesh import run_sharded_batches
from bigstitcher_spark_tpu.parallel.retry import RetryError


def _kernel(x):
    return x * 2.0


def _kernel_two_outputs(x):
    return x * 2.0, x + 1.0


class TestRunShardedBatches:
    def _run(self, n_items, consume, kernel=_kernel, per_dev=1):
        items = list(range(n_items))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(
                items,
                build=lambda it: (np.full((4,), float(it), np.float32),),
                kernel=jax.jit(kernel),
                consume=consume,
                n_dev=1,
                pool=pool,
                per_dev=per_dev,
            )

    def test_every_item_consumed_with_its_own_output(self):
        got = {}

        def consume(it, out):
            got[it] = np.asarray(out).copy()

        self._run(7, consume, per_dev=2)
        assert sorted(got) == list(range(7))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_multi_output_kernels(self):
        got = {}

        def consume(it, a, b):
            got[it] = (np.asarray(a).copy(), np.asarray(b).copy())

        self._run(5, consume, kernel=_kernel_two_outputs, per_dev=2)
        for it, (a, b) in got.items():
            np.testing.assert_allclose(a, np.full((4,), 2.0 * it))
            np.testing.assert_allclose(b, np.full((4,), it + 1.0))

    def test_consume_failure_retries_without_duplicate_or_loss(self):
        # fail item 2's consume ONCE, on a run long enough that item 3's
        # batch has been early-dispatched by the time 2 drains: the retry
        # must re-run batch 2 only, and every item lands exactly once
        got = {}
        fails = {"n": 0}

        def consume(it, out):
            if it == 2 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient write failure")
            assert it not in got, f"item {it} consumed twice"
            got[it] = np.asarray(out).copy()

        self._run(6, consume)
        assert sorted(got) == list(range(6))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))
        assert fails["n"] == 1

    def test_transient_build_failure_recovers(self):
        # whether the failing build is first hit by a neighbour's early
        # dispatch (swallowed, re-staged by its own batch) or by its own
        # batch (retried), every item must land exactly once with its data
        fails = {"n": 0}
        got = {}

        def build(it):
            if it == 3 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient read failure")
            return (np.full((4,), float(it), np.float32),)

        def consume(it, out):
            assert it not in got
            got[it] = np.asarray(out).copy()

        items = list(range(6))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(items, build=build, kernel=jax.jit(_kernel),
                                consume=consume, n_dev=1, pool=pool)
        assert sorted(got) == items
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((4,), 2.0 * it))

    def test_persistent_failure_raises_retry_error(self):
        def consume(it, out):
            raise RuntimeError("disk full")

        with pytest.raises(RetryError):
            self._run(2, consume)


class TestInflightWindow:
    """The byte-budgeted dispatch window (BST_INFLIGHT_BYTES /
    utils.devicemem): the ledger must never exceed budget + one batch
    (the current batch always dispatches), a generous budget must let the
    loop run multiple batches ahead, and a starved budget must degrade to
    strict one-batch-at-a-time without losing items."""

    def _run(self, n_items, consume, build=None, per_dev=1):
        from bigstitcher_spark_tpu.utils import devicemem

        devicemem._HIGHWATER.set(0)
        devicemem._INFLIGHT.set(0)
        items = list(range(n_items))
        build = build or (
            lambda it: (np.full((1024,), float(it), np.float32),))
        with ThreadPoolExecutor(4) as pool:
            run_sharded_batches(
                items, build=build, kernel=jax.jit(_kernel), consume=consume,
                n_dev=1, pool=pool, per_dev=per_dev, workspace_mult=1.0,
            )
        return devicemem._HIGHWATER.value

    def test_highwater_never_exceeds_budget_plus_current(self, monkeypatch):
        batch_bytes = 1024 * 4                         # one item per batch
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(2 * batch_bytes))
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.02)   # give later builds time to stage
            got[it] = np.asarray(out).copy()

        hw = self._run(8, consume)
        assert sorted(got) == list(range(8))
        # budget (2 batches) + the always-dispatched current batch
        assert hw <= 3 * batch_bytes, hw

    def test_generous_budget_runs_ahead(self, monkeypatch):
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(1 << 30))
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.02)
            got[it] = np.asarray(out).copy()

        hw = self._run(8, consume)
        assert sorted(got) == list(range(8))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((1024,), 2.0 * it))
        assert hw >= 2 * 1024 * 4, hw                  # >= 2 batches in flight

    def test_starved_budget_still_completes(self, monkeypatch):
        monkeypatch.setenv("BST_INFLIGHT_BYTES", "1")
        got = {}

        def consume(it, out):
            got[it] = np.asarray(out).copy()

        hw = self._run(6, consume, per_dev=2)
        assert sorted(got) == list(range(6))
        for it, out in got.items():
            np.testing.assert_allclose(out, np.full((1024,), 2.0 * it))

    def test_retry_restages_inside_window(self, monkeypatch):
        # a consume failure while successors are dispatched ahead must
        # retry cleanly: every item lands exactly once, ledger drains to 0
        monkeypatch.setenv("BST_EARLY_DISPATCH", "1")
        monkeypatch.setenv("BST_INFLIGHT_BYTES", str(1 << 30))
        from bigstitcher_spark_tpu.utils import devicemem

        fails = {"n": 0}
        got = {}
        import time

        def consume(it, out):
            time.sleep(0.01)
            if it == 2 and fails["n"] == 0:
                fails["n"] += 1
                raise RuntimeError("transient write failure")
            assert it not in got, f"item {it} consumed twice"
            got[it] = np.asarray(out).copy()

        self._run(8, consume)
        assert sorted(got) == list(range(8)) and fails["n"] == 1
        assert devicemem._INFLIGHT.value == 0


class TestPairScheduler:
    """The pair-work mesh scheduler (parallel/pairsched.py): cost-weighted
    placement balance, per-device in-flight windows, result ordering, and
    poisoned-device re-dispatch."""

    def test_cost_weighted_placement_bounded_spread(self):
        # a synthetic skewed bucket distribution (two huge buckets + a
        # long tail) must balance within the greedy-LPT bound:
        # max_load - min_load <= max single task cost
        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, assign_tasks,
        )

        rng = np.random.default_rng(3)
        costs = [1000.0, 700.0] + list(rng.integers(1, 60, 30).astype(float))
        tasks = [PairTask(index=i, cost=c) for i, c in enumerate(costs)]
        bins = assign_tasks(tasks, 4)
        loads = [sum(t.cost for t in b) for b in bins]
        assert max(loads) - min(loads) <= max(costs)
        placed = sorted(t.index for b in bins for t in b)
        assert placed == list(range(len(tasks)))  # exactly once each

    def test_zero_cost_tasks_still_spread(self):
        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, assign_tasks,
        )

        bins = assign_tasks([PairTask(index=i, cost=0.0) for i in range(8)], 8)
        assert all(len(b) == 1 for b in bins)

    def test_results_in_task_order_all_devices_used(self):
        import jax

        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, run_pair_tasks,
        )

        seen = set()

        def run(t):
            seen.add(str(jax.config.jax_default_device))
            return t.index * 2

        n = 24
        out = run_pair_tasks(
            [PairTask(index=i, cost=1.0 + i % 3) for i in range(n)],
            run, stage="sched-order-test")
        assert out == [2 * i for i in range(n)]
        assert len(seen) == len(jax.local_devices())

    def test_per_device_window_never_exceeds_budget(self, monkeypatch):
        # drain-mode: each device's dispatched-but-undrained bytes must
        # stay within its budget + segmentation slack (two half-budget
        # segments in flight)
        import threading

        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, run_pair_tasks,
        )

        nb = 1024
        budget = 4 * nb
        monkeypatch.setenv("BST_PAIR_INFLIGHT_BYTES", str(budget))
        lock = threading.Lock()
        cur: dict[str, int] = {}
        peak: dict[str, int] = {}

        def dispatch(t):
            name = threading.current_thread().name
            with lock:
                cur[name] = cur.get(name, 0) + nb
                peak[name] = max(peak.get(name, 0), cur[name])
            return t.index

        def drain(tasks, handles):
            name = threading.current_thread().name
            with lock:
                cur[name] = cur.get(name, 0) - nb * len(tasks)
            return [h * 3 for h in handles]

        n = 64
        out = run_pair_tasks(
            [PairTask(index=i, cost=1.0, nbytes=nb) for i in range(n)],
            dispatch, drain, stage="sched-window-test")
        assert out == [3 * i for i in range(n)]
        assert peak and max(peak.values()) <= budget + nb

    def test_pair_budget_splits_process_knob_across_workers(self,
                                                            monkeypatch):
        # BST_INFLIGHT_BYTES is process-wide: N workers split it;
        # BST_PAIR_INFLIGHT_BYTES is per device: taken verbatim
        from bigstitcher_spark_tpu.utils.devicemem import pair_budget_bytes

        monkeypatch.delenv("BST_PAIR_INFLIGHT_BYTES", raising=False)
        monkeypatch.setenv("BST_INFLIGHT_BYTES", "8000")
        assert pair_budget_bytes(None, 8) == 1000
        assert pair_budget_bytes(None, 1) == 8000
        monkeypatch.setenv("BST_PAIR_INFLIGHT_BYTES", "500")
        assert pair_budget_bytes(None, 8) == 500

    def test_batched_drain_failure_isolates_to_offender(self):
        # a host-side error in a batched segment drain must fall back to
        # per-task drains: healthy neighbours keep their device results
        # (no recompute), only the offending task re-dispatches
        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, run_pair_tasks,
        )

        n_dispatch = {"n": 0}
        single_fails = {"n": 0}

        def dispatch(t):
            n_dispatch["n"] += 1
            return t.index

        def drain(tasks, handles):
            if len(tasks) > 1 and any(t.index == 3 for t in tasks):
                raise RuntimeError("bad pair in the batch")
            if (len(tasks) == 1 and tasks[0].index == 3
                    and single_fails["n"] == 0):
                single_fails["n"] += 1
                raise RuntimeError("bad pair, isolated")
            return [h * 2 for h in handles]

        n = 8
        out = run_pair_tasks(
            [PairTask(index=i, cost=1.0, nbytes=100) for i in range(n)],
            dispatch, drain, n_devices=1, stage="sched-drainfail-test")
        assert out == [2 * i for i in range(n)]
        # 8 originals + exactly ONE re-dispatch (task 3); the other 7
        # were salvaged from the failed segment without device recompute
        assert n_dispatch["n"] == n + 1
        assert single_fails["n"] == 1

    def test_multihost_partitions_pairs_processes_first(self, monkeypatch):
        # pairs split across PROCESSES first (cost-aware LPT), local
        # devices second; the allgather merge hands every rank the FULL
        # result list (simulate rank 1 by answering the gather with the
        # complementary slice's results)
        from bigstitcher_spark_tpu.parallel import distributed
        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, run_pair_tasks,
        )

        monkeypatch.setattr(distributed, "world", lambda: (0, 2))
        other = set(distributed.partition_indices_weighted(
            [1.0] * 7, 1, 2))

        def fake_gather(payload):
            assert payload[0] == "ok"
            return [payload, ("ok", {i: i * 10 for i in other})]

        monkeypatch.setattr(distributed, "allgather_object", fake_gather)
        out = run_pair_tasks(
            [PairTask(index=i, cost=1.0) for i in range(7)],
            lambda t: t.index * 10, stage="sched-mh-test", multihost=True)
        assert out == [i * 10 for i in range(7)]

    def test_poisoned_device_redispatches(self):
        # a device whose every call fails must degrade capacity, not kill
        # the run: its tasks re-dispatch onto the other devices
        import jax

        from bigstitcher_spark_tpu.observe import metrics
        from bigstitcher_spark_tpu.parallel.pairsched import (
            PairTask, run_pair_tasks,
        )

        if len(jax.local_devices()) < 2:
            pytest.skip("needs >= 2 devices")
        poisoned = jax.local_devices()[0]

        def run(t):
            if jax.config.jax_default_device == poisoned:
                raise RuntimeError("poisoned device call")
            return t.index

        ctr = metrics.counter("bst_pair_redispatch_total",
                              stage="sched-poison-test")
        before = ctr.value
        out = run_pair_tasks(
            [PairTask(index=i, cost=1.0) for i in range(16)],
            run, stage="sched-poison-test")
        assert out == list(range(16))
        assert ctr.value > before

"""Observability layer: metrics registry, JSONL event log, run manifests,
and the end-to-end ``--telemetry-dir`` CLI path.

Marker-free on purpose — tier-1 covers the telemetry path on CPU (the
acceptance contract of the observability PR): a tiny affine-fusion run
with ``--telemetry-dir`` must leave an event log, a Prometheus textfile
and a manifest whose block counts and byte totals match the output
container.
"""

import json
import os
import re
import threading

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import observe
from bigstitcher_spark_tpu.observe import events, manifest, metrics, progress


@pytest.fixture(autouse=True)
def _clean_observe_state():
    """Telemetry state is process-global; never leak it between tests."""
    yield
    if observe.active():
        observe.finalize(tool="test-cleanup")
    events.close()


class TestMetricsRegistry:
    def test_counter_thread_safety(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("t_ops_total", stage="x")
        n_threads, per_thread = 8, 5000

        def work():
            for _ in range(per_thread):
                c.inc()

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * per_thread

    def test_labels_make_distinct_series(self):
        reg = metrics.MetricsRegistry()
        a = reg.counter("io_bytes_total", path="native")
        b = reg.counter("io_bytes_total", path="tensorstore")
        assert a is not b
        a.inc(10)
        b.inc(1)
        snap = reg.snapshot()
        assert snap['io_bytes_total{path="native"}'] == 10
        assert snap['io_bytes_total{path="tensorstore"}'] == 1
        # same (name, labels) -> same handle
        assert reg.counter("io_bytes_total", path="native") is a

    def test_type_conflict_rejected(self):
        reg = metrics.MetricsRegistry()
        reg.counter("thing")
        with pytest.raises(ValueError):
            reg.gauge("thing")

    def test_reset_keeps_handles_valid(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("n_total")
        c.inc(5)
        reg.reset()
        assert c.value == 0
        c.inc(2)
        assert reg.snapshot()["n_total"] == 2

    def test_snapshot_delta(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("bytes_total")
        g = reg.gauge("level")
        c.inc(100)
        g.set(3)
        base = reg.snapshot()
        c.inc(42)
        g.set(7)
        delta = reg.snapshot_delta(base)
        assert delta["bytes_total"] == 42
        assert delta["level"] == 7  # gauges report current value

    def test_snapshot_delta_histogram_series(self):
        """Dict-valued Histogram series (the PR 11 wait histogram) must
        diff per-field — count and sum each baseline-subtracted, never
        the raw current dict and never a numeric subtraction crash."""
        reg = metrics.MetricsRegistry()
        h = reg.histogram("wait_seconds")
        h.observe(0.5)
        h.observe(2.0)
        base = reg.snapshot()
        h.observe(10.0)
        delta = reg.snapshot_delta(base)
        assert delta["wait_seconds"] == {"count": 1, "sum": 10.0}
        # a histogram series born AFTER the baseline counts from zero
        h2 = reg.histogram("wait_seconds", kind="new")
        h2.observe(1.0)
        delta = reg.snapshot_delta(base)
        assert delta['wait_seconds{kind="new"}'] == {"count": 1,
                                                     "sum": 1.0}
        # an idle histogram deltas to an explicit zero, not a stale total
        assert reg.snapshot_delta(reg.snapshot())["wait_seconds"] == {
            "count": 0, "sum": 0.0}
        # labeled siblings diff independently
        h.observe(3.0)
        delta = reg.snapshot_delta(base)
        assert delta["wait_seconds"] == {"count": 2, "sum": 13.0}
        assert delta['wait_seconds{kind="new"}']["count"] == 1

    def test_prometheus_textfile_format(self):
        reg = metrics.MetricsRegistry()
        reg.counter("bst_io_read_bytes_total", path="native").inc(4096)
        reg.gauge("bst_inflight").set(2)
        h = reg.histogram("bst_barrier_seconds", buckets=(0.1, 1.0),
                          name="s0")
        h.observe(0.05)
        h.observe(0.5)
        h.observe(30.0)
        text = reg.render_prometheus()
        assert "# TYPE bst_io_read_bytes_total counter" in text
        assert '\nbst_io_read_bytes_total{path="native"} 4096' in text
        assert "# TYPE bst_inflight gauge" in text
        assert "# TYPE bst_barrier_seconds histogram" in text
        # cumulative buckets + +Inf + _sum/_count, labels preserved
        assert re.search(
            r'bst_barrier_seconds_bucket\{le="0\.1",name="s0"\} 1', text)
        assert re.search(
            r'bst_barrier_seconds_bucket\{le="\+Inf",name="s0"\} 3', text)
        assert re.search(r'bst_barrier_seconds_count\{name="s0"\} 3', text)
        # every sample line is `name{labels} value` or `# ...`
        for line in text.strip().splitlines():
            assert line.startswith("#") or re.fullmatch(
                r'[a-zA-Z_:][\w:]*(\{[^}]*\})? -?[\d.e+-]+', line), line


class TestEventLog:
    def test_round_trip(self, tmp_path):
        d = str(tmp_path / "tel")
        events.configure(d)
        try:
            events.emit("stage.start", stage="fusion", total=12)
            events.emit("block.ok", stage="fusion",
                        bytes=np.int64(4096), offset=np.array([0, 0, 0]))
            events.emit("drops.none.fields", empty=None)
        finally:
            path = events.close()
        assert path is not None
        assert os.path.basename(path) == "events-00000-of-00001.jsonl"
        recs = list(events.iter_events(path))
        assert [r["type"] for r in recs] == [
            "stage.start", "block.ok", "drops.none.fields"]
        assert all("ts" in r for r in recs)
        assert recs[0]["total"] == 12
        assert recs[1]["bytes"] == 4096  # numpy scalars serialize as numbers
        assert recs[1]["offset"] == [0, 0, 0]
        assert "empty" not in recs[2]

    def test_disabled_is_noop(self, tmp_path):
        assert not events.enabled()
        events.emit("never", x=1)  # must not raise or create files
        assert events.path() is None

    def test_append_not_truncate(self, tmp_path):
        d = str(tmp_path / "tel")
        events.configure(d)
        events.emit("a")
        p = events.close()
        events.configure(d)
        events.emit("b")
        assert events.close() == p
        assert [r["type"] for r in events.iter_events(p)] == ["a", "b"]


class TestRetryTelemetry:
    def test_exception_breakdown_in_retry_error(self):
        from bigstitcher_spark_tpu.parallel.retry import (
            RetryError, run_with_retry,
        )

        def boom(it):
            if it % 2:
                raise ValueError(f"odd {it}")
            raise TypeError(f"even {it}")

        with pytest.raises(RetryError) as ei:
            run_with_retry([1, 2, 3], boom, max_retries=2, delay_s=0.0,
                           label="t-block", verbose=False)
        msg = str(ei.value)
        assert "failure breakdown across rounds" in msg
        # 2 odd + 1 even items x 3 rounds (initial + 2 retries)
        assert "ValueError x6" in msg
        assert "TypeError x3" in msg
        assert "first error:" in msg

    def test_retry_events_and_recovery(self, tmp_path):
        from bigstitcher_spark_tpu.parallel.retry import run_with_retry

        observe.configure(str(tmp_path / "tel"), profile=False)
        flaky = {"left": 2}

        def sometimes(it):
            if it == 3 and flaky["left"] > 0:
                flaky["left"] -= 1
                raise OSError("transient")

        rounds = run_with_retry([1, 2, 3], sometimes, max_retries=5,
                                delay_s=0.0, label="t-retry", verbose=False)
        assert rounds == 2
        observe.finalize(tool="t")
        path = os.path.join(str(tmp_path / "tel"),
                            "events-00000-of-00001.jsonl")
        types = [r["type"] for r in events.iter_events(path)]
        assert types.count("block.fail") == 2
        assert types.count("retry.round") == 2
        assert "stage.start" in types and "stage.end" in types
        end = [r for r in events.iter_events(path)
               if r["type"] == "stage.end"][0]
        assert end["done"] == 3 and end["total"] == 3
        assert end["retry_rounds"] == 2


class TestProfilerReport:
    def test_report_uses_snapshot(self):
        from bigstitcher_spark_tpu import profiling

        p = profiling.Profiler()
        p.record("stage.a", 0.5)
        p.record("stage.a", 1.5)
        p.record("stage.hot", 5.0)
        rep = p.report()
        assert "stage.a" in rep
        # count, total, mean, min, max
        assert re.search(
            r"stage\.a\s+2\s+2\.000\s+1\.000\s+0\.500\s+1\.500", rep)
        # sorted by total_s DESC: the hot span is the FIRST data line
        assert rep.splitlines()[1].startswith("stage.hot")


class TestManifestMerge:
    def _fake_process(self, d, pi, pc, write_bytes, fail_events=0):
        events.configure(d)
        # monkey-free: emit through the real writer under a forced world
        events.emit("stage.start", stage="fusion", total=8)
        for _ in range(fail_events):
            events.emit("block.fail", stage="fusion",
                        exception="TimeoutError", error="t/o", round=0)
        events.close()
        return manifest.write_manifest(
            d, tool="affine-fusion", argv=["bst"], params={"o": "x"},
            world=(pi, pc), started_at=0.0, seconds=10.0 + pi,
            status="ok", error=None,
            spans={"fusion.kernel": {"count": 4, "total_s": 2.0,
                                     "max_s": 1.0}},
            metrics_delta={'bst_io_write_bytes_total{path="native"}':
                           write_bytes},
            stages=[{"stage": "affine-fusion", "done": 4, "total": 4,
                     "seconds": 10.0 + pi, "voxels": 1000}],
            events_file=None,
        )

    def test_merge_across_processes(self, tmp_path):
        d = str(tmp_path / "tel")
        os.makedirs(d)
        # two per-process manifest files must not collide
        p0 = self._fake_process(d, 0, 2, write_bytes=1000, fail_events=1)
        p1 = self._fake_process(d, 1, 2, write_bytes=500, fail_events=2)
        assert os.path.basename(p0) != os.path.basename(p1)

        report = manifest.merge_run(d)
        assert len(report["processes"]) == 2
        assert report["process_count"] == 2
        assert report["wall_clock_s"] == 11.0  # slowest process
        m = report["metrics"]
        assert m['bst_io_write_bytes_total{path="native"}'] == 1500
        s = {r["stage"]: r for r in report["stages"]}
        assert s["affine-fusion"]["done"] == 8  # summed across processes
        assert s["affine-fusion"]["voxels"] == 2000
        assert report["spans"]["fusion.kernel"]["count"] == 8
        assert report["spans"]["fusion.kernel"]["max_s"] == 1.0
        assert report["failures_by_exception"] == {"TimeoutError": 3}

    def test_merge_empty_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            manifest.merge_run(str(tmp_path))


class TestCliTelemetryEndToEnd:
    def test_affine_fusion_telemetry_dir(self, tmp_path):
        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.io.container import read_container_meta
        from bigstitcher_spark_tpu.utils.testdata import (
            make_synthetic_project,
        )

        proj = make_synthetic_project(
            str(tmp_path / "p"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
            overlap=8, jitter=0.0, seed=11, n_beads_per_tile=6)
        out = str(tmp_path / "fused.ome.zarr")
        tel = str(tmp_path / "telemetry")
        runner = CliRunner()
        r = runner.invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path, "-o", out,
            "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
            "--minIntensity", "0", "--maxIntensity", "65535",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        r = runner.invoke(cli, [
            "affine-fusion", "-o", out, "--blockScale", "1,1,1",
            "--telemetry-dir", tel,
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert not observe.active()  # finalized when the command closed

        # --- manifest ---
        mpath = os.path.join(tel, "manifest-00000-of-00001.json")
        with open(mpath) as f:
            man = json.load(f)
        assert man["schema"] == manifest.SCHEMA
        assert man["tool"] == "affine-fusion"
        assert man["status"] == "ok"
        assert man["params"]["output"] == out
        assert man["world"] == {"process_index": 0, "process_count": 1}
        assert man["device"]["platform"] == "cpu"

        # --- block counts match the output container's grid ---
        store = ChunkStore.open(out)
        meta = read_container_meta(store)
        shape = meta.bbox.shape
        bs = meta.block_size
        expected_blocks = int(np.prod(
            [-(-int(s) // int(b)) for s, b in zip(shape, bs)]))
        fusion_stages = [s for s in man["stages"]
                         if s["stage"] == "affine-fusion"]
        assert len(fusion_stages) == 1
        st = fusion_stages[0]
        assert st["blocks"] == expected_blocks
        voxels = int(np.prod(shape))
        assert st["voxels"] == voxels
        assert st["seconds"] > 0 and st["voxels_per_s"] > 0

        # --- byte totals match the container ---
        ds = store.open_dataset("0")
        container_bytes = int(np.prod(ds.shape)) * ds.dtype.itemsize
        assert container_bytes == voxels * 2  # uint16, c=t=1
        written = sum(v for k, v in man["metrics"].items()
                      if k.startswith("bst_io_write_bytes_total"))
        assert written == container_bytes
        read = sum(v for k, v in man["metrics"].items()
                   if k.startswith("bst_io_read_bytes_total"))
        assert read > 0  # source patches were read through the IO layer

        # --- span table rode along (configure enables the profiler) ---
        assert any(k.startswith("fusion.") for k in man["spans"])

        # --- event log round-trips ---
        epath = os.path.join(tel, man["events_file"])
        recs = list(events.iter_events(epath))
        types = {r["type"] for r in recs}
        assert {"run.start", "run.end", "stage.start", "stage.end",
                "stage.summary", "io.write"} <= types
        io_w = sum(r["bytes"] for r in recs if r["type"] == "io.write")
        assert io_w == container_bytes

        # --- metrics textfile ---
        prom = open(os.path.join(tel, "metrics-00000-of-00001.prom")).read()
        assert "# TYPE bst_io_write_bytes_total counter" in prom
        assert "bst_stage_items_done_total" in prom

        # --- merge tool folds the single-process run ---
        r = runner.invoke(cli, ["telemetry-merge", tel],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output
        with open(os.path.join(tel, "merged-report.json")) as f:
            merged = json.load(f)
        assert merged["schema"] == manifest.MERGED_SCHEMA
        assert merged["processes"][0]["tool"] == "affine-fusion"
        assert merged["metrics"] == man["metrics"]
        assert "affine-fusion" in [s["stage"] for s in merged["stages"]]

    def test_telemetry_default_off(self, tmp_path):
        """Without --telemetry-dir nothing is configured and no telemetry
        files appear (the zero-overhead default)."""
        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.utils.testdata import (
            make_synthetic_project,
        )

        proj = make_synthetic_project(
            str(tmp_path / "p"), n_tiles=(1, 1, 1), tile_size=(24, 24, 12),
            overlap=4, n_beads_per_tile=3)
        out = str(tmp_path / "c.n5")
        r = CliRunner().invoke(cli, [
            "create-fusion-container", "-x", proj.xml_path, "-o", out,
            "-s", "N5", "-d", "UINT16", "--blockSize", "16,16,8",
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert not observe.active()
        assert not events.enabled()
        assert not any(f.startswith(("events-", "manifest-", "metrics-"))
                       for f in os.listdir(str(tmp_path)))

"""Multi-device (virtual 8-CPU mesh) sharding of the PAIR-parallel stages:
stitching phase correlation, descriptor matching and intensity matching must
produce EXACTLY the output of the single-device path when their pair work
spreads over the mesh (parallel/pairsched.py — the round-5 VERDICT's first
open item: these stages ran batched + pipelined but on one device).

Exactness is by construction: seeds attach to the task index, placement
never enters the math, and one host's devices run identical XLA programs.
The 3x3 tile grid yields ~20 overlapping pairs — uneven shape buckets
(x-adjacent / y-adjacent / diagonal crops) and more tasks than devices, so
the greedy placement must land work on all 8; the tail tests run with fewer
pairs than devices."""

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData


def _dispatch_devices(delta, stage):
    """Device labels of ``bst_pair_dispatch_total`` series that moved."""
    return {
        k for k, v in delta.items()
        if k.startswith("bst_pair_dispatch_total")
        and f'stage="{stage}"' in k and v > 0
    }


@pytest.fixture(scope="module")
def grid_project(tmp_path_factory):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    # smooth_field gives every overlap region intensity dynamic range (the
    # intensity matcher needs non-constant samples to fit real lines)
    return make_synthetic_project(
        str(tmp_path_factory.mktemp("pairshard") / "proj"),
        n_tiles=(3, 3, 1), tile_size=(32, 32, 16), overlap=12,
        jitter=2.0, seed=23, block_size=(16, 16, 16),
        n_beads_per_tile=12, smooth_field=25.0,
    )


@pytest.fixture(scope="module")
def grid_sd(grid_project):
    sd = SpimData.load(grid_project.xml_path)
    return sd, ViewLoader(sd), sd.view_ids()


@pytest.fixture(scope="module")
def point_store(grid_sd, tmp_path_factory):
    """Synthetic interest points: one world-space bead cloud projected into
    every view's pixel space — matching then has true correspondences in
    every overlap without running detection."""
    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.utils.geometry import invert_affine
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd, _, views = grid_sd
    bbox = maximal_bounding_box(sd, views)
    rng = np.random.default_rng(7)
    # modest cloud: enough for candidates in every overlap while keeping
    # the per-device RANSAC pad-size spectrum (and compile count) small
    world = rng.uniform(np.array(bbox.min, np.float64),
                        np.array(bbox.max, np.float64), (250, 3))
    store = InterestPointStore(
        str(tmp_path_factory.mktemp("pairshard_ips") / "ips.n5"))
    for v in views:
        inv = invert_affine(sd.model(v))
        px = world @ inv[:, :3].T + inv[:, 3]
        size = np.array(sd.view_size(v), np.float64)
        inside = np.all((px >= 1) & (px <= size - 2), axis=1)
        store.save_points(v, "beads", px[inside])
    return store


def _snapshot():
    from bigstitcher_spark_tpu.observe import metrics

    return metrics.get_registry().snapshot()


def _delta(base):
    from bigstitcher_spark_tpu.observe import metrics

    return metrics.get_registry().snapshot_delta(base)


def test_stitching_sharded_equals_single_all_devices(grid_sd):
    from bigstitcher_spark_tpu.models.stitching import (
        StitchingParams, stitch_all_pairs,
    )

    sd, loader, views = grid_sd
    # batch_size=1: one scheduler task per pair; uneven buckets arise from
    # the x/y/diagonal overlap shapes
    params = StitchingParams(min_overlap_px=8, batch_size=1)
    base = _snapshot()
    multi = stitch_all_pairs(sd, loader, views, params, progress=False,
                             devices=8)
    assert len(_dispatch_devices(_delta(base), "stitching")) == 8
    single = stitch_all_pairs(sd, loader, views, params, progress=False,
                              devices=1)
    assert len(multi) == len(single) >= 8
    for a, b in zip(multi, single):
        assert a.pair_key == b.pair_key
        np.testing.assert_array_equal(a.transform, b.transform)
        assert a.correlation == b.correlation


def test_matching_sharded_equals_single_all_devices(grid_sd, point_store):
    from bigstitcher_spark_tpu.models.matching import (
        MatchingParams, match_interest_points,
    )

    sd, _, views = grid_sd
    params = MatchingParams(model="TRANSLATION", regularization="NONE",
                            ransac_min_inliers=4, ransac_iterations=250)
    base = _snapshot()
    multi = match_interest_points(sd, views, params, point_store,
                                  progress=False, devices=8)
    assert len(_dispatch_devices(_delta(base), "matching")) == 8
    single = match_interest_points(sd, views, params, point_store,
                                   progress=False, devices=1)
    assert len(multi) == len(single) >= 8
    assert sum(len(r.ids_a) for r in multi) > 0, "no correspondences found"
    for a, b in zip(multi, single):
        assert (a.view_a, a.view_b) == (b.view_a, b.view_b)
        np.testing.assert_array_equal(a.ids_a, b.ids_a)
        np.testing.assert_array_equal(a.ids_b, b.ids_b)
        assert a.n_candidates == b.n_candidates
        if a.model is None:
            assert b.model is None
        else:
            np.testing.assert_array_equal(a.model, b.model)


def test_intensity_sharded_equals_single_all_devices(grid_sd):
    from bigstitcher_spark_tpu.models.intensity import (
        IntensityParams, match_intensities,
    )

    sd, loader, views = grid_sd
    params = IntensityParams(coefficients=(2, 2, 2), render_scale=0.5,
                             min_num_candidates=20, min_samples_per_cell=5,
                             min_num_inliers=5, ransac_iterations=300,
                             max_samples_per_cell=256)
    base = _snapshot()
    multi = match_intensities(sd, loader, views, params, progress=False,
                              devices=8)
    assert len(_dispatch_devices(_delta(base), "intensity")) == 8
    single = match_intensities(sd, loader, views, params, progress=False,
                               devices=1)
    assert len(multi) == len(single) > 0
    for a, b in zip(multi, single):
        assert (a.view_a, a.view_b, a.cell_a, a.cell_b) == \
            (b.view_a, b.view_b, b.cell_a, b.cell_b)
        assert a.stats == b.stats
        assert a.fit == b.fit


def test_tail_fewer_pairs_than_devices(grid_sd):
    """Tail workloads smaller than the device count: a 3-view strip has 2-3
    overlapping pairs on 8 devices — placement must leave devices idle (not
    crash or duplicate) and outputs must still equal the single-device
    path."""
    from bigstitcher_spark_tpu.models.stitching import (
        StitchingParams, stitch_all_pairs,
    )

    sd, loader, views = grid_sd
    strip = views[:3]
    params = StitchingParams(min_overlap_px=8, batch_size=1)
    multi = stitch_all_pairs(sd, loader, strip, params, progress=False,
                             devices=8)
    single = stitch_all_pairs(sd, loader, strip, params, progress=False,
                              devices=1)
    assert 1 <= len(multi) < 8
    assert len(multi) == len(single)
    for a, b in zip(multi, single):
        assert a.pair_key == b.pair_key
        np.testing.assert_array_equal(a.transform, b.transform)
        assert a.correlation == b.correlation


def test_retry_redispatches_poisoned_stitching_dispatch(grid_sd,
                                                        monkeypatch):
    """A poisoned device call inside the stitching dispatch (first call on
    device 0 dies) must re-dispatch that bucket onto another device and
    still deliver every pair's result exactly once."""
    import jax

    from bigstitcher_spark_tpu.models import stitching as S
    from bigstitcher_spark_tpu.models.stitching import (
        StitchingParams, stitch_all_pairs,
    )

    sd, loader, views = grid_sd
    params = StitchingParams(min_overlap_px=8, batch_size=1)
    poisoned = jax.local_devices()[0]
    real = S._dispatch_bucket
    fails = {"n": 0}

    def flaky(jobs, shp, p):
        if jax.config.jax_default_device == poisoned and fails["n"] == 0:
            fails["n"] += 1
            raise RuntimeError("poisoned device call")
        return real(jobs, shp, p)

    monkeypatch.setattr(S, "_dispatch_bucket", flaky)
    multi = stitch_all_pairs(sd, loader, views, params, progress=False,
                             devices=8)
    monkeypatch.setattr(S, "_dispatch_bucket", real)
    single = stitch_all_pairs(sd, loader, views, params, progress=False,
                              devices=1)
    assert fails["n"] == 1, "the poisoned dispatch was never exercised"
    assert len(multi) == len(single)
    for a, b in zip(multi, single):
        assert a.pair_key == b.pair_key
        np.testing.assert_array_equal(a.transform, b.transform)
        assert a.correlation == b.correlation

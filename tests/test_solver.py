"""Solver: model-fit golden tests + tile-graph convergence on synthetic
grids with known ground truth (exceeds the reference's manual smoke tests,
per SURVEY.md §4 implication)."""

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.io.spimdata import (
    PairwiseStitchingResult,
    SpimData,
    ViewId,
    registration_hash,
)
from bigstitcher_spark_tpu.models import solver as S
from bigstitcher_spark_tpu.ops import models as M
from bigstitcher_spark_tpu.utils.geometry import (
    Interval,
    translation_affine,
)


# ---------------------------------------------------------------- model fits

def test_fit_translation():
    rng = np.random.default_rng(0)
    p = rng.uniform(0, 100, (20, 3))
    t = np.array([3.0, -2.0, 5.5])
    m = M.fit_translation(p, p + t)
    np.testing.assert_allclose(m[:, 3], t, atol=1e-10)
    np.testing.assert_allclose(m[:, :3], np.eye(3), atol=1e-12)


def test_fit_rigid_recovers_rotation():
    rng = np.random.default_rng(1)
    p = rng.uniform(0, 100, (30, 3))
    ang = 0.3
    r = np.array([[np.cos(ang), -np.sin(ang), 0],
                  [np.sin(ang), np.cos(ang), 0],
                  [0, 0, 1.0]])
    t = np.array([5.0, 1.0, -2.0])
    q = p @ r.T + t
    m = M.fit_rigid(p, q)
    np.testing.assert_allclose(m[:, :3], r, atol=1e-9)
    np.testing.assert_allclose(m[:, 3], t, atol=1e-8)
    # determinant must stay +1 even for reflective noise
    assert np.isclose(np.linalg.det(m[:, :3]), 1.0)


def test_fit_affine_recovers_full_affine():
    rng = np.random.default_rng(2)
    p = rng.uniform(0, 50, (40, 3))
    a = np.array([[1.1, 0.05, 0.0, 3.0],
                  [-0.02, 0.95, 0.01, -1.0],
                  [0.0, 0.03, 1.02, 7.0]])
    q = p @ a[:, :3].T + a[:, 3]
    m = M.fit_affine(p, q)
    np.testing.assert_allclose(m, a, atol=1e-8)


def test_fit_weighted_ignores_zero_weight_outliers():
    rng = np.random.default_rng(3)
    p = rng.uniform(0, 100, (25, 3))
    t = np.array([1.0, 2.0, 3.0])
    q = p + t
    q[0] += 500  # outlier
    w = np.ones(25)
    w[0] = 0.0
    m = M.fit_translation(p, q, w)
    np.testing.assert_allclose(m[:, 3], t, atol=1e-10)


def test_fit_interpolated_identity_shrinks():
    rng = np.random.default_rng(4)
    p = rng.uniform(0, 10, (10, 3))
    t = np.array([4.0, 0.0, 0.0])
    m = M.fit_interpolated(M.TRANSLATION, M.IDENTITY, 0.5, p, p + t)
    np.testing.assert_allclose(m[:, 3], t * 0.5, atol=1e-10)


def test_fit_batched_matches_single():
    rng = np.random.default_rng(5)
    p = rng.uniform(0, 100, (4, 30, 3))
    q = p + rng.uniform(-5, 5, (4, 1, 3))
    batched = M.fit_rigid(p, q)
    for i in range(4):
        single = M.fit_rigid(p[i], q[i])
        np.testing.assert_allclose(batched[i], single, atol=1e-9)


# ------------------------------------------------------- synthetic tile graph

def _grid_project(n=(3, 2), tile=(100, 100, 50), overlap=20, jitter=4.0, seed=0):
    """SpimData with an n[0] x n[1] tile grid: nominal registrations are
    perturbed from truth; stitching results encode the true relative shifts
    (c_A - c_B = S convention)."""
    from bigstitcher_spark_tpu.io.spimdata import (
        AttributeEntity,
        ViewSetup,
        ViewTransform,
    )

    rng = np.random.default_rng(seed)
    sd = SpimData()
    sd.timepoints = [0]
    sd.attributes["illumination"][0] = AttributeEntity(0, "0")
    sd.attributes["angle"][0] = AttributeEntity(0, "0")
    sd.attributes["channel"][0] = AttributeEntity(0, "0")
    step = (tile[0] - overlap, tile[1] - overlap)
    true_off, nominal = {}, {}
    sid = 0
    for ty in range(n[1]):
        for tx in range(n[0]):
            truth = np.array([tx * step[0], ty * step[1], 0.0])
            nom = truth + (rng.uniform(-jitter, jitter, 3) if sid else 0.0)
            sd.attributes["tile"][sid] = AttributeEntity(sid, str(sid))
            sd.setups[sid] = ViewSetup(
                id=sid, name=f"t{sid}", size=tile,
                attributes={"illumination": 0, "channel": 0, "tile": sid,
                            "angle": 0},
            )
            sd.registrations[ViewId(0, sid)] = [
                ViewTransform("grid", translation_affine(nom))
            ]
            true_off[sid], nominal[sid] = truth, nom
            sid += 1

    def add_link(a, b, shift=None, r=0.9):
        va, vb = (ViewId(0, a),), (ViewId(0, b),)
        if shift is None:
            # wanted: c_A - c_B = (true_a - nom_a) - (true_b - nom_b)
            shift = (true_off[a] - nominal[a]) - (true_off[b] - nominal[b])
        res = PairwiseStitchingResult(
            va, vb, translation_affine(shift), r,
            hash=registration_hash([sd.model(va[0])], [sd.model(vb[0])]),
            bbox=Interval((0, 0, 0), (overlap - 1, tile[1] - 1, tile[2] - 1)),
        )
        sd.stitching_results[res.pair_key] = res

    for ty in range(n[1]):
        for tx in range(n[0]):
            i = ty * n[0] + tx
            if tx + 1 < n[0]:
                add_link(i, i + 1)
            if ty + 1 < n[1]:
                add_link(i, i + n[0])
    return sd, true_off, nominal, add_link


def _check_recovered(sd, result, true_off, nominal, atol=0.05):
    """After applying corrections, every tile's position must equal truth up
    to one global translation (the fixed tile's residual)."""
    resid = {}
    for key, corr in result.corrections.items():
        sid = key[0].setup
        new_pos = corr[:, 3] + nominal[sid]
        resid[sid] = new_pos - true_off[sid]
    base = resid[min(resid)]
    for sid, r in resid.items():
        np.testing.assert_allclose(r, base, atol=atol,
                                   err_msg=f"tile {sid} not aligned")


def test_solver_recovers_grid_translation():
    sd, truth, nominal, _ = _grid_project(n=(3, 2), seed=1)
    params = S.SolverParams(source="STITCHING", model=M.TRANSLATION)
    result = S.solve(sd, sd.view_ids(), params, verbose=False)
    assert result.error < 0.01
    _check_recovered(sd, result, truth, nominal)


def test_solver_fixed_view_stays_identity():
    sd, truth, nominal, _ = _grid_project(n=(2, 2), seed=2)
    params = S.SolverParams(source="STITCHING", model=M.TRANSLATION,
                            fixed_views=[ViewId(0, 0)])
    result = S.solve(sd, sd.view_ids(), params, verbose=False)
    key0 = next(k for k in result.corrections if k[0].setup == 0)
    np.testing.assert_allclose(result.corrections[key0][:, 3], 0, atol=1e-12)
    _check_recovered(sd, result, truth, nominal)


def test_solver_iterative_drops_bad_link():
    sd, truth, nominal, add_link = _grid_project(n=(4, 3), seed=3)
    # corrupt one (diagonal) link badly
    add_link(0, 5, shift=np.array([80.0, -60.0, 40.0]), r=0.8)
    params = S.SolverParams(source="STITCHING", model=M.TRANSLATION,
                            method="ONE_ROUND_ITERATIVE")
    result = S.solve(sd, sd.view_ids(), params, verbose=False)
    assert len(result.removed_links) >= 1
    _check_recovered(sd, result, truth, nominal, atol=0.1)


def test_solver_two_round_places_disconnected_component():
    sd, truth, nominal, _ = _grid_project(n=(2, 1), seed=4)
    # add two islands (no links): tiles 2,3 share a link but connect to nothing
    from bigstitcher_spark_tpu.io.spimdata import AttributeEntity, ViewSetup, ViewTransform

    for sid, pos in ((2, (0.0, 200.0, 0.0)), (3, (80.0, 200.0, 0.0))):
        sd.attributes["tile"][sid] = AttributeEntity(sid, str(sid))
        sd.setups[sid] = ViewSetup(
            id=sid, name=f"t{sid}", size=(100, 100, 50),
            attributes={"illumination": 0, "channel": 0, "tile": sid, "angle": 0},
        )
        sd.registrations[ViewId(0, sid)] = [
            ViewTransform("grid", translation_affine(pos))
        ]
    va, vb = (ViewId(0, 2),), (ViewId(0, 3),)
    island_shift = np.array([2.0, 0.0, 0.0])
    res = PairwiseStitchingResult(
        va, vb, translation_affine(island_shift), 0.9,
        hash=registration_hash([sd.model(va[0])], [sd.model(vb[0])]),
        bbox=Interval((80, 200, 0), (99, 299, 49)),
    )
    sd.stitching_results[res.pair_key] = res

    params = S.SolverParams(source="STITCHING", model=M.TRANSLATION,
                            method="TWO_ROUND_SIMPLE")
    result = S.solve(sd, sd.view_ids(), params, verbose=False)
    c2 = result.corrections[next(k for k in result.corrections if k[0].setup == 2)]
    c3 = result.corrections[next(k for k in result.corrections if k[0].setup == 3)]
    # island internal constraint satisfied...
    np.testing.assert_allclose(c2[:, 3] - c3[:, 3], island_shift, atol=0.01)
    # ...and the island stays centered on its metadata position
    np.testing.assert_allclose(c2[:, 3] + c3[:, 3], 0, atol=0.01)


def test_solver_skips_stale_links():
    sd, truth, nominal, _ = _grid_project(n=(2, 1), seed=5)
    # perturb a registration AFTER stitching: its links are now stale
    sd.registrations[ViewId(0, 1)][0].affine[:, 3] += 10.0
    tiles = S.build_tiles(sd, sd.view_ids(), S.SolverParams())
    links = S.matches_from_stitching(sd, tiles, verbose=False)
    assert links == []


def test_solver_rigid_recovers_rotation():
    """Rigid model: links encode a consistent rotation correction for tile 1."""
    sd, truth, nominal, _ = _grid_project(n=(2, 1), jitter=0.0, seed=6)
    ang = 0.05
    rot = np.array([[np.cos(ang), -np.sin(ang), 0],
                    [np.sin(ang), np.cos(ang), 0], [0, 0, 1.0]])
    # overwrite the link: tile1's content is rotated by R about origin
    # => correction for tile1 should be R^-1-ish... we just demand convergence
    va, vb = (ViewId(0, 0),), (ViewId(0, 1),)
    box = Interval((80, 0, 0), (99, 99, 49))
    corners = np.array([[x, y, z] for x in (80, 100) for y in (0, 100)
                        for z in (0, 50)], float)
    # constraint: M0(p) = M1(q) with M0 = I  =>  q = R^-1 p
    q = corners @ rot  # R^-1 = R.T; p @ (R.T).T = p @ R
    res = PairwiseStitchingResult(va, vb, translation_affine((0, 0, 0)), 0.9)
    sd.stitching_results = {}
    links = [S.MatchLink((va[0],), (vb[0],), corners, q, np.ones(len(corners)))]
    params = S.SolverParams(model=M.RIGID, fixed_views=[ViewId(0, 0)])
    out = S.relax(links, [(va[0],), (vb[0],)], {(va[0],)}, params)
    np.testing.assert_allclose(out.corrections[(vb[0],)][:, :3], rot, atol=1e-6)
    assert out.error < 1e-6


def test_store_corrections_preconcatenates():
    sd, truth, nominal, _ = _grid_project(n=(2, 1), seed=7)
    params = S.SolverParams(source="STITCHING", model=M.TRANSLATION)
    result = S.solve(sd, sd.view_ids(), params, verbose=False)
    n_before = len(sd.registrations[ViewId(0, 1)])
    S.store_corrections(sd, result, params)
    chain = sd.registrations[ViewId(0, 1)]
    assert len(chain) == n_before + 1
    assert "stitching" in chain[0].name
    # model() now includes the correction as the OUTERMOST transform
    key1 = next(k for k in result.corrections if k[0].setup == 1)
    expected = result.corrections[key1][:, 3] + nominal[1]
    np.testing.assert_allclose(sd.model(ViewId(0, 1))[:, 3], expected, atol=1e-9)


# ------------------------------------------------------------ end-to-end CLI

@pytest.fixture(scope="module")
def stitched_project(tmp_path_factory):
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.models.stitching import (
        StitchingParams,
        filter_results,
        stitch_all_pairs,
        store_results,
    )
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path_factory.mktemp("solve") / "proj"),
        n_tiles=(2, 2, 1), tile_size=(96, 96, 48), overlap=28,
        jitter=3.0, seed=11, n_beads_per_tile=60,
    )
    sd = SpimData.load(proj.xml_path)
    loader = ViewLoader(sd)
    results = stitch_all_pairs(sd, loader, sd.view_ids(),
                               StitchingParams(downsampling=(1, 1, 1)),
                               progress=False)
    # tiny corner overlaps produce unreliable links; filter hard on r the way
    # a real workflow would (minR is a CLI knob in reference + here)
    store_results(sd, filter_results(results, StitchingParams(min_r=0.8),
                                     verbose=False))
    sd.save()
    return proj


def test_solver_cli_end_to_end(stitched_project):
    proj = stitched_project
    runner = CliRunner()
    res = runner.invoke(cli, [
        "solver", "-x", proj.xml_path, "-s", "STITCHING",
        "-tm", "TRANSLATION", "--method", "ONE_ROUND_ITERATIVE",
    ], catch_exceptions=False)
    assert res.exit_code == 0, res.output
    sd = SpimData.load(proj.xml_path)
    # after solving, every tile's world position should match truth up to
    # the global offset of the fixed tile
    resid = {}
    for v in sd.view_ids():
        resid[v.setup] = sd.model(v)[:, 3] - proj.true_offsets[v.setup]
    base = resid[0]
    for sid, r in resid.items():
        np.testing.assert_allclose(r, base, atol=0.8,
                                   err_msg=f"setup {sid} misaligned: {r - base}")

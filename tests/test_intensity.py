"""Intensity matching + solving: kernel golden tests, pipeline consistency on
a deliberately miscalibrated synthetic project, and coefficient application
in the fusion kernel (reference SparkIntensityMatching / IntensitySolver /
BlkAffineFusion.initWithIntensityCoefficients)."""

import numpy as np
import pytest
from click.testing import CliRunner


class TestIntensityKernels:
    def test_linefit_ransac(self):
        from bigstitcher_spark_tpu.ops.intensity import match_cells_ransac

        rng = np.random.default_rng(0)
        x = rng.uniform(0, 1, 200).astype(np.float32)
        y = 0.6 * x + 0.1 + rng.normal(0, 0.004, 200).astype(np.float32)
        y[:40] = rng.uniform(0, 1, 40)  # 20% outliers
        fits = match_cells_ransac([x], [y], epsilon=0.02, iterations=500)
        assert fits[0] is not None
        a, b, n = fits[0]
        assert abs(a - 0.6) < 0.05
        assert abs(b - 0.1) < 0.03
        assert n > 140

    def test_histogram_match(self):
        from bigstitcher_spark_tpu.ops.intensity import match_cells_histogram

        rng = np.random.default_rng(1)
        x = rng.uniform(0.2, 0.8, 500)
        y = 1.5 * x - 0.1
        fits = match_cells_histogram([x], [rng.permutation(y)])
        a, b, _ = fits[0]
        assert abs(a - 1.5) < 0.05
        assert abs(b + 0.1) < 0.05

    def test_solve_consistency(self):
        from bigstitcher_spark_tpu.ops.intensity import (
            match_stats, solve_intensity_coefficients,
        )

        rng = np.random.default_rng(2)
        x = rng.uniform(10, 100, 500)
        y = 0.5 * x - 5.0  # cell 1 reads half as bright
        sol = solve_intensity_coefficients(
            2, [(0, 1, *match_stats(x, y))], lam=1e-4,
        )
        # corrected values must agree: s0*x + o0 == s1*y + o1
        lhs = sol[0, 0] * x + sol[0, 1]
        rhs = sol[1, 0] * y + sol[1, 1]
        np.testing.assert_allclose(lhs, rhs, atol=0.5)
        # regularization keeps the mean map near identity (gauge fixing)
        assert 0.5 < sol[:, 0].mean() < 1.5


class TestIntensityPipeline:
    @pytest.fixture(scope="class")
    def project(self, tmp_path_factory):
        """2-tile project where tile 1's stored data is rescaled
        (i -> 1.4*i + 30): the miscalibration the tools must recover."""
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
        import os

        proj = make_synthetic_project(
            str(tmp_path_factory.mktemp("intensity") / "proj"),
            n_tiles=(2, 1, 1), tile_size=(96, 96, 48), overlap=40,
            jitter=0.0, seed=21, n_beads_per_tile=30,
            smooth_field=600.0,  # dynamic range everywhere: line fits need it
        )
        store = ChunkStore.open(
            os.path.join(os.path.dirname(proj.xml_path), "dataset.n5"))
        ds = store.open_dataset("setup1/timepoint0/s0")
        img = ds.read_full().astype(np.float64)
        ds.write(np.clip(1.4 * img + 30, 0, 65535).astype(np.uint16), (0, 0, 0))
        return proj

    def test_match_solve_consistency(self, project):
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
        from bigstitcher_spark_tpu.models.intensity import (
            IntensityParams, match_intensities, solve_intensities,
        )

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        # this test pins the match->solve equalization math, so the optional
        # candidate filters are neutralized: min_threshold=0 keeps the
        # fixture's informative dark samples, max_trust=inf disables the
        # mpicbg-style trim (its behavior has its own test below)
        params = IntensityParams(coefficients=(2, 2, 2), render_scale=0.5,
                                 min_threshold=0.0,
                                 max_trust=float("inf"))
        matches = match_intensities(sd, loader, views, params, progress=False)
        assert len(matches) > 0
        coeffs = solve_intensities(matches, views, params.coefficients,
                                   lam=0.01, progress=False)
        # the fitted pairwise relation y ~= a*x+b must be equalized:
        # f0(x) ~= f1(1.4x + 30) for typical intensities
        c0 = coeffs[ViewId(0, 0)].reshape(-1, 2).mean(axis=0)
        c1 = coeffs[ViewId(0, 1)].reshape(-1, 2).mean(axis=0)
        for i in (100.0, 500.0, 2000.0):
            lhs = c0[0] * i + c0[1]
            rhs = c1[0] * (1.4 * i + 30.0) + c1[1]
            assert abs(lhs - rhs) / max(lhs, 1.0) < 0.12, (i, lhs, rhs)

    def test_cli_and_corrected_fusion(self, project, tmp_path):
        """CLI round trip + fused output: with correction, the two sides of
        the overlap seam must agree much better than without."""
        from bigstitcher_spark_tpu.cli.main import cli
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
        from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
        from bigstitcher_spark_tpu.models.intensity import IntensityStore
        from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

        runner = CliRunner()
        res = runner.invoke(cli, [
            "match-intensities", "-x", project.xml_path,
            "--coefficients", "2,2,2", "--renderScale", "0.5",
        ])
        assert res.exit_code == 0, res.output
        res = runner.invoke(cli, [
            "solve-intensities", "-x", project.xml_path, "--lambda", "0.01",
        ])
        assert res.exit_code == 0, res.output

        sd = SpimData.load(project.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        istore = IntensityStore.for_project(sd)
        coeffs = {v: istore.load_coefficients(v).astype(np.float32)
                  for v in views}
        assert all(c is not None for c in coeffs.values())

        bbox = maximal_bounding_box(sd, views, None)
        outs = {}
        for name, cf in (("raw", None), ("corrected", coeffs)):
            cstore = ChunkStore.create(str(tmp_path / f"{name}.n5"),
                                       StorageFormat.N5)
            ds = cstore.create_dataset("f", bbox.shape, (64, 64, 48), "float32")
            fuse_volume(sd, loader, views, ds, bbox, block_size=(64, 64, 48),
                        block_scale=(1, 1, 1), fusion_type="FIRST_WINS",
                        out_dtype="float32", min_intensity=0.0,
                        max_intensity=1.0, coefficients=cf)
            outs[name] = ds.read_full()

        # seam: columns just left/right of the boundary between the region
        # covered by view 0 (FIRST_WINS) and view 1 only
        x_seam = 96 - bbox.min[0]  # view 0 ends here in output coords
        left = {k: v[x_seam - 3:x_seam, 8:88, 8:40].mean()
                for k, v in outs.items()}
        right = {k: v[x_seam + 1:x_seam + 4, 8:88, 8:40].mean()
                 for k, v in outs.items()}
        jump_raw = abs(left["raw"] - right["raw"]) / right["raw"]
        jump_cor = abs(left["corrected"] - right["corrected"]) / right["corrected"]
        assert jump_raw > 0.15          # the miscalibration is visible
        assert jump_cor < jump_raw / 3  # correction removes most of it


class TestCandidateFilters:
    """The reference's matching filters (SparkIntensityMatching.java:51-77):
    intensity thresholds, minNumCandidates, minNumInliers, maxTrust."""

    def _pair_project(self, tmp_path, corrupt_fraction=0.0, seed=3):
        """Two tiles whose shared content is a wide-dynamic-range ramp;
        tile 1 stores 2*i + 10 (+ optional salt corruption). A ramp keeps
        the per-cell line fit well-conditioned."""
        import os

        import numpy as np

        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(48, 48, 24),
            overlap=24, jitter=0.0, seed=seed, n_beads_per_tile=10)
        store = ChunkStore.open(
            os.path.join(os.path.dirname(proj.xml_path), "dataset.n5"))
        rng = np.random.default_rng(seed)
        # world-consistent ramp: value = 40*(world_x+y+z) sampled per tile
        offsets = {0: 0.0, 1: 24.0}  # tile 1 starts at world x=24
        ramp = {}
        for s, x0 in offsets.items():
            xs = np.arange(48) + x0
            ramp[s] = (40.0 * (xs[:, None, None] + np.arange(48)[None, :, None]
                               + np.arange(24)[None, None, :]))
        ds0 = store.open_dataset("setup0/timepoint0/s0")
        ds0.write(np.clip(ramp[0], 0, 65535).astype(np.uint16), (0, 0, 0))
        ds1 = store.open_dataset("setup1/timepoint0/s0")
        out = 2.0 * ramp[1] + 10
        if corrupt_fraction:
            mask = rng.random(out.shape) < corrupt_fraction
            out[mask] = rng.uniform(0, 60000, int(mask.sum()))
        ds1.write(np.clip(out, 0, 65535).astype(np.uint16), (0, 0, 0))
        return proj

    def test_max_threshold_discards_bright_samples(self, tmp_path):
        import numpy as np

        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.intensity import (
            IntensityParams, match_intensities,
        )

        proj = self._pair_project(tmp_path)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        base = IntensityParams(coefficients=(1, 1, 1), render_scale=1.0,
                               min_threshold=0.0)
        m_all = match_intensities(sd, loader, views, base, progress=False)
        # a max threshold below the data range kills every candidate
        cut = IntensityParams(coefficients=(1, 1, 1), render_scale=1.0,
                              min_threshold=0.0, max_threshold=0.5)
        m_cut = match_intensities(sd, loader, views, cut, progress=False)
        assert len(m_all) > 0 and len(m_cut) == 0
        # stats sample count respects minNumCandidates
        many = IntensityParams(coefficients=(1, 1, 1), render_scale=1.0,
                               min_threshold=0.0, min_num_candidates=10**9)
        assert match_intensities(sd, loader, views, many,
                                 progress=False) == []
        n = m_all[0].stats[0]
        assert n >= 10

    def test_max_trust_resists_corruption(self, tmp_path):
        """With 15% of view-1 pixels replaced by junk, the trust-trimmed fit
        must stay close to the true line (2.0, 10)."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.intensity import (
            IntensityParams, match_intensities,
        )

        proj = self._pair_project(tmp_path, corrupt_fraction=0.15, seed=7)
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        views = sorted(sd.registrations)
        params = IntensityParams(coefficients=(1, 1, 1), render_scale=1.0,
                                 min_threshold=0.0, max_trust=3.0)
        ms = match_intensities(sd, loader, views, params, progress=False)
        assert len(ms) == 1
        a, b = ms[0].fit
        assert abs(a - 2.0) < 0.1, (a, b)
        assert abs(b - 10.0) < 60.0, (a, b)

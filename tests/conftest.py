"""Test harness: run JAX on a virtual 8-device CPU mesh (the analogue of the
reference's Spark `local[N]` testing mode, SURVEY.md §4). Must run before any
jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def synthetic_project(tmp_path):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(str(tmp_path / "proj"))

"""Test harness: run JAX on a virtual 8-device CPU mesh (the analogue of the
reference's Spark `local[N]` testing mode, SURVEY.md §4). Must run before any
jax import.

The suite FORCES CPU: the axon TPU tunnel admits one client at a time, so
on-TPU pytest runs serialize against anything else using the chip and every
kernel pays a remote compile. Correctness is platform-independent (matmul
precision is pinned to 'highest' at package import); TPU validation happens
via bench.py and targeted drives. Set BST_TEST_TPU=1 to opt in to the real
chip.
"""

import os

if not os.environ.get("BST_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # empty guard skips the axon sitecustomize PJRT registration, whose
    # client creation would block on a busy tunnel
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    # The env vars alone are NOT enough: the axon sitecustomize imports jax
    # at interpreter startup with JAX_PLATFORMS=axon already latched into
    # jax.config, so without this update the whole suite silently targets
    # the one-client TPU tunnel (slow remote compiles, cross-process
    # blocking). Must happen before any backend is initialized.
    import jax

    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def synthetic_project(tmp_path):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(str(tmp_path / "proj"))

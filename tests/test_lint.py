"""Tier-1 gate for the AST invariant analyzer (``bst lint``) and the
runtime-config registry.

Three layers: (1) the live package must produce ZERO non-baselined
findings (and the baseline must not hide ops/models host-sync bugs);
(2) the analyzer itself is tested against fixture snippets with known
violations per check, a clean fixture, and suppression comments;
(3) doc drift — every ``BST_*`` name in README/WORKFLOW/PERF exists in
the config registry and vice versa."""

import os
import shutil
import textwrap
from pathlib import Path

import pytest

from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.analysis import (
    baseline_counts,
    default_baseline_path,
    default_root,
    load_baseline,
    new_findings,
    run_lint,
)

REPO = Path(__file__).resolve().parent.parent


def _write_tree(root: Path, files: dict[str, str]) -> Path:
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src).lstrip("\n"), encoding="utf-8")
    return root


# -- layer 1: the live package ---------------------------------------------


class TestPackageIsClean:
    def test_zero_new_findings(self):
        findings = run_lint(default_root())
        baseline = load_baseline(default_baseline_path())
        new = new_findings(findings, baseline)
        assert not new, "new bst-lint findings:\n" + "\n".join(
            f.render() for f in new)

    def test_baseline_hides_no_ops_models_host_sync(self):
        # the ISSUE's contract: host-sync findings in ops/ and models/
        # are FIXED, never baselined away
        baseline = load_baseline(default_baseline_path())
        bad = [k for k in baseline
               if k.startswith(("host-sync|ops/", "host-sync|models/"))]
        assert not bad, bad

    def test_inserted_violations_fail(self, tmp_path):
        # the enforcement proof: copy the package, insert a raw
        # os.environ["BST_X"] read and an unlocked mutation of a
        # lock-guarded dict, and the scan must produce new findings
        src = default_root()
        dst = tmp_path / "pkg"
        shutil.copytree(src, dst, ignore=shutil.ignore_patterns(
            "__pycache__", "*.pyc"))
        uris = dst / "io" / "uris.py"
        uris.write_text(uris.read_text(encoding="utf-8") + (
            "\n\ndef _sneaky():\n"
            "    import os\n"
            "    return os.environ[\"BST_X\"]\n"), encoding="utf-8")
        progress = dst / "observe" / "progress.py"
        progress.write_text(progress.read_text(encoding="utf-8") + (
            "\n\ndef _unlocked_drop():\n"
            "    _records.clear()\n"), encoding="utf-8")
        solver = dst / "models" / "solver.py"
        solver.write_text(solver.read_text(encoding="utf-8") + (
            "\n\ndef _sneaky_spawn():\n"
            "    import threading\n"
            "    return threading.Thread(target=print)\n"), encoding="utf-8")
        client = dst / "serve" / "client.py"
        client.write_text(client.read_text(encoding="utf-8") + (
            "\n\ndef _sneaky_close(addr):\n"
            "    import socket\n"
            "    s = socket.create_connection(addr)\n"
            "    s.close()\n"), encoding="utf-8")
        findings = run_lint(dst)
        new = new_findings(findings, load_baseline(default_baseline_path()))
        checks = {f.check for f in new}
        assert "config-registry" in checks, [f.render() for f in new]
        assert "lock-discipline" in checks, [f.render() for f in new]
        assert "thread-spawn" in checks, [f.render() for f in new]
        assert "socket-hygiene" in checks, [f.render() for f in new]


# -- layer 2: the analyzer against known fixtures --------------------------


class TestHostSyncCheck:
    def test_known_violations(self, tmp_path):
        _write_tree(tmp_path, {"ops/mod.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np


            def bad(x):
                y = jnp.sum(x)
                z = float(y)                      # line 8
                a = np.asarray(jnp.fft.rfftn(x))  # line 9
                if y > 0:                         # line 10
                    pass
                v = y.item()                      # line 12
                return z, a, v
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "host-sync"]
        assert sorted(f.line for f in fs) == [8, 9, 10, 12]

    def test_drain_points_are_clean(self, tmp_path):
        _write_tree(tmp_path, {"ops/mod.py": """
            import jax
            import jax.numpy as jnp
            import numpy as np


            def good(x):
                y = jnp.sum(x)
                z = float(jax.device_get(y))
                a = np.asarray(jax.device_get(jnp.fft.rfftn(x)))
                r = jnp.dot(x, x).block_until_ready()
                n = int(x.shape[0])          # .shape never syncs
                return z, a, n, np.asarray(r)
            """})
        assert [f for f in run_lint(tmp_path) if f.check == "host-sync"] == []

    def test_ops_kernel_results_are_sources(self, tmp_path):
        # the ADVICE r5 bug class: np.asarray on a kernel-layer result
        _write_tree(tmp_path, {"models/driver.py": """
            import numpy as np
            from ..ops import fusion as F


            def drive(p):
                fused, wsum = F.fuse_block(p)
                return np.asarray(fused), np.asarray(wsum)
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "host-sync"]
        assert len(fs) == 2 and all(f.line == 7 for f in fs)

    def test_outside_ops_models_not_scanned(self, tmp_path):
        _write_tree(tmp_path, {"cli/tool.py": """
            import jax.numpy as jnp


            def show(x):
                return float(jnp.sum(x))    # CLI boundary: fetch is fine
            """})
        assert [f for f in run_lint(tmp_path) if f.check == "host-sync"] == []


class TestLockDisciplineCheck:
    def test_unlocked_mutation(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import threading

            _LOCK = threading.Lock()
            _STATE = {}


            def locked(k, v):
                with _LOCK:
                    _STATE[k] = v


            def unlocked(k, v):
                _STATE[k] = v               # line 13


            def drop_locked(k):
                _STATE.pop(k)               # *_locked: caller holds it
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "lock-discipline"]
        assert [f.line for f in fs] == [13]

    def test_instance_state(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []        # __init__ is exempt

                def add(self, x):
                    with self._lock:
                        self._items.append(x)

                def sneak(self, x):
                    self._items.append(x)   # line 14
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "lock-discipline"]
        assert [f.line for f in fs] == [14]


class TestLockOrderCheck:
    def test_two_lock_inversion_is_a_cycle(self, tmp_path):
        # the old single-file A->B/B->A heuristic, now a graph cycle
        _write_tree(tmp_path, {"mod.py": """
            import threading

            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()


            def one():
                with LOCK_A:
                    with LOCK_B:
                        pass


            def two():
                with LOCK_B:
                    with LOCK_A:
                        pass
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "lock-order"]
        assert len(fs) == 1 and "potential deadlock" in fs[0].message
        assert "--graph lock-order" in fs[0].message

    def test_three_lock_interprocedural_cycle(self, tmp_path):
        # A->B and B->C are direct nestings; C->A only exists one call
        # level deep (three() calls take_a() under LOCK_C) — the planted
        # cycle the per-pair heuristic could never see
        _write_tree(tmp_path, {"mod.py": """
            import threading

            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()
            LOCK_C = threading.Lock()


            def one():
                with LOCK_A:
                    with LOCK_B:
                        pass


            def two():
                with LOCK_B:
                    with LOCK_C:
                        pass


            def three():
                with LOCK_C:
                    take_a()


            def take_a():
                with LOCK_A:
                    pass
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "lock-order"]
        assert len(fs) == 1, [f.render() for f in fs]
        assert "LOCK_A" in fs[0].message and "LOCK_C" in fs[0].message

    def test_one_way_ordering_is_clean(self, tmp_path):
        # a consistent global order (cache -> tier, never back) is the
        # live package's shape and must not be flagged
        _write_tree(tmp_path, {"mod.py": """
            import threading


            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()

                def drop(self, tier):
                    with self._lock:
                        tier.keys()


            class Tier:
                def __init__(self):
                    self._lock = threading.Lock()

                def keys(self):
                    with self._lock:
                        return []
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "lock-order"] == []

    def test_condition_aliases_to_its_lock(self, tmp_path):
        # Condition(self._lock) IS self._lock: entering the condition in
        # one method and the lock in another around the same second lock
        # inverts the order — one node, real 2-cycle
        _write_tree(tmp_path, {"mod.py": """
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._side_lock = threading.Lock()

                def a(self):
                    with self._cv:
                        with self._side_lock:
                            pass

                def b(self):
                    with self._side_lock:
                        with self._lock:
                            pass
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "lock-order"]
        assert len(fs) == 1, [f.render() for f in fs]

    def test_dot_export_lists_edges(self, tmp_path):
        from bigstitcher_spark_tpu.analysis import (
            lock_graph_dot,
            parse_package,
        )

        _write_tree(tmp_path, {"mod.py": """
            import threading

            LOCK_A = threading.Lock()
            LOCK_B = threading.Lock()


            def one():
                with LOCK_A:
                    with LOCK_B:
                        pass
            """})
        ctxs, _sup, _err = parse_package(tmp_path)
        dot = lock_graph_dot(ctxs)
        assert dot.startswith("digraph lock_order")
        assert "LOCK_A" in dot and "->" in dot


class TestBlockingUnderLockCheck:
    def test_recv_and_queue_get_under_lock(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import threading


            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = None

                def bad_recv(self, sock):
                    with self._lock:
                        data = sock.recv(4096)      # line 11
                    return data

                def bad_get(self):
                    with self._lock:
                        return self._q.get()        # line 16

                def ok_nowait(self):
                    with self._lock:
                        return self._q.get_nowait()

                def ok_outside(self, sock):
                    with self._lock:
                        pending = True
                    return sock.recv(4096)
            """})
        fs = [f for f in run_lint(tmp_path)
              if f.check == "blocking-under-lock"]
        assert sorted(f.line for f in fs) == [11, 16]

    def test_helper_one_call_deep(self, tmp_path):
        # the exchange.py shape: the blocking sendall hides one call
        # level down in a module helper, flagged at the call site
        _write_tree(tmp_path, {"mod.py": """
            import threading

            _LOCK = threading.Lock()


            def _send_line(sock, data):
                sock.sendall(data)


            def bad(sock, data):
                with _LOCK:
                    _send_line(sock, data)          # line 12
            """})
        fs = [f for f in run_lint(tmp_path)
              if f.check == "blocking-under-lock"]
        assert [f.line for f in fs] == [12]

    def test_long_sleep_and_subprocess(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import subprocess
            import threading
            import time

            _LOCK = threading.Lock()


            def bad():
                with _LOCK:
                    time.sleep(5.0)                          # line 10
                    subprocess.run(["ls"], check=False)      # line 11


            def ok_tick():
                with _LOCK:
                    time.sleep(0.01)    # sub-threshold tick
            """})
        fs = [f for f in run_lint(tmp_path)
              if f.check == "blocking-under-lock"]
        assert sorted(f.line for f in fs) == [10, 11]


class TestThreadSpawnCheck:
    def test_raw_spawns_flagged(self, tmp_path):
        _write_tree(tmp_path, {"models/worker.py": """
            import threading
            from concurrent.futures import ThreadPoolExecutor


            def spawn(fn):
                t = threading.Thread(target=fn)     # line 6
                pool = ThreadPoolExecutor(4)        # line 7
                return t, pool
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "thread-spawn"]
        assert sorted(f.line for f in fs) == [6, 7]
        assert all("ctx" in f.message.lower() for f in fs)

    def test_utils_threads_is_the_sanctioned_home(self, tmp_path):
        _write_tree(tmp_path, {"utils/threads.py": """
            import threading


            def ctx_thread(fn, name=None):
                return threading.Thread(target=fn, name=name, daemon=True)
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "thread-spawn"] == []

    def test_ctx_thread_calls_are_clean(self, tmp_path):
        _write_tree(tmp_path, {"dag/runner.py": """
            from ..utils.threads import ctx_thread


            def start(fn):
                return ctx_thread(fn, name="worker")
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "thread-spawn"] == []


class TestCancelCoverageCheck:
    def test_poll_free_worker_loop_flagged(self, tmp_path):
        _write_tree(tmp_path, {"dag/pump.py": """
            from ..utils.threads import ctx_thread


            class Pump:
                def start(self):
                    ctx_thread(self._loop, name="pump")

                def _loop(self):
                    while True:                     # line 9
                        self.step()

                def step(self):
                    pass
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "cancel-coverage"]
        assert [f.line for f in fs] == [9]
        assert "cancel" in fs[0].message

    def test_stop_flag_poll_is_clean(self, tmp_path):
        _write_tree(tmp_path, {"serve/pump.py": """
            import threading
            from ..utils.threads import ctx_thread


            class Pump:
                def __init__(self):
                    self._stop = threading.Event()

                def start(self):
                    ctx_thread(self._loop, name="pump")

                def _loop(self):
                    while True:
                        if self._stop.is_set():
                            return
                        self.step()

                def step(self):
                    pass
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "cancel-coverage"] == []

    def test_non_worker_and_out_of_scope_loops_clean(self, tmp_path):
        _write_tree(tmp_path, {
            # not a thread target: a main-thread convergence loop
            "models/solve.py": """
                def iterate(step):
                    while True:
                        if step():
                            break
                """,
            # a worker loop, but io/ is outside the policed dirs
            "io/pump.py": """
                from ..utils.threads import ctx_thread


                def start():
                    ctx_thread(_loop)


                def _loop():
                    while True:
                        pass
                """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "cancel-coverage"] == []


class TestSocketHygieneCheck:
    def test_shutdown_less_close_flagged(self, tmp_path):
        _write_tree(tmp_path, {"net/conn.py": """
            import socket


            def leak(addr):
                s = socket.create_connection(addr)
                s.close()                           # line 6


            def clean(addr):
                s = socket.create_connection(addr)
                s.shutdown(socket.SHUT_RDWR)
                s.close()


            def helper_clean(addr):
                s = socket.create_connection(addr)
                _shutdown_close(s)


            def _shutdown_close(sock):
                sock.shutdown(socket.SHUT_RDWR)
                sock.close()
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "socket-hygiene"]
        assert [f.line for f in fs] == [6]
        assert "shutdown" in fs[0].message

    def test_accepted_conn_param_flagged(self, tmp_path):
        # the daemon/relay handler shape: the socket arrives as a
        # parameter, recognized by annotation or sock/conn naming
        _write_tree(tmp_path, {"net/handler.py": """
            import socket


            def handle(conn: socket.socket):
                f = conn.makefile("rb")
                f.close()
                conn.close()                        # line 7
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "socket-hygiene"]
        assert [f.line for f in fs] == [7]

    def test_listener_and_utils_exempt(self, tmp_path):
        _write_tree(tmp_path, {
            "net/server.py": """
                import socket


                def serve(port):
                    srv = socket.socket()
                    srv.bind(("", port))
                    srv.listen(4)
                    srv.close()     # listener: shutdown is meaningless
                """,
            "utils/sockets.py": """
                import socket


                def quick(addr):
                    s = socket.create_connection(addr)
                    s.close()       # utils/-level helper: exempt
                """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "socket-hygiene"] == []


class TestConfigRegistryCheck:
    def test_raw_reads_flagged(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import os


            def f():
                a = os.environ.get("BST_FOO")        # line 5
                b = os.environ["BST_BAR"]            # line 6
                c = os.getenv("HOME")                # non-BST: fine
                d = __import__("os").environ.get("BST_BAZ")  # line 8
                return a, b, c, d
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "config-registry"]
        assert sorted(f.line for f in fs) == [5, 6, 8]

    def test_undeclared_knob_flagged(self, tmp_path):
        _write_tree(tmp_path, {
            "config.py": """
                KNOBS = {}


                def _knob(name, kind, default, doc):
                    KNOBS[name] = (kind, default, doc)


                _knob("BST_REAL", "str", None, "declared")
                """,
            "mod.py": """
                from . import config


                def f():
                    return config.get_str("BST_TYPO")   # line 5
                """})
        fs = [f for f in run_lint(tmp_path) if f.check == "config-registry"]
        assert [f.line for f in fs] == [5]
        assert "BST_TYPO" in fs[0].message

    def test_config_py_itself_exempt(self, tmp_path):
        _write_tree(tmp_path, {"config.py": """
            import os


            def raw_value(name):
                return os.environ.get(name)
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "config-registry"] == []


class TestEnvMutationCheck:
    def test_raw_env_mutation_in_serve_flagged(self, tmp_path):
        # the serve contract: a daemon job configuring itself by mutating
        # the process env would leak into every concurrent job — the check
        # points straight at config.overrides()
        _write_tree(tmp_path, {"serve/daemon.py": """
            import os


            def run_job(overrides):
                os.environ["BST_INFLIGHT_BYTES"] = "1000"       # line 5
                os.environ.setdefault("BST_PAIR_SHARD", "0")    # line 6
                os.environ.pop("BST_WRITE_THREADS", None)       # line 7
                del os.environ["BST_TILE_CACHE_BYTES"]          # line 8
                os.environ.update({"BST_TRACE": "1"})           # line 9
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "env-mutation"]
        assert sorted(f.line for f in fs) == [5, 6, 7, 8, 9]
        assert all("config.overrides" in f.message for f in fs)

    def test_config_py_not_exempt(self, tmp_path):
        # unlike config-registry, even the registry module may not WRITE
        _write_tree(tmp_path, {"config.py": """
            import os


            def bad(name, value):
                os.environ[name] = value     # dynamic name: not BST_-provable
                os.environ["BST_X"] = value  # line 6
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "env-mutation"]
        assert [f.line for f in fs] == [6]

    def test_reads_and_non_bst_writes_are_clean(self, tmp_path):
        _write_tree(tmp_path, {"config.py": """
            import os


            def fine():
                a = os.environ.get("BST_FOO")
                os.environ["JAX_PLATFORMS"] = "cpu"
                return a
            """})
        assert [f for f in run_lint(tmp_path)
                if f.check == "env-mutation"] == []


class TestMetricNameCheck:
    FILES = {
        "observe/metric_names.py": """
            METRICS = {
                "bst_good_total": "a declared counter",
            }
            """,
    }

    def test_unregistered_and_dynamic(self, tmp_path):
        _write_tree(tmp_path, {**self.FILES, "mod.py": """
            from observe import metrics as _metrics

            C = _metrics.counter("bst_good_total")
            D = _metrics.counter("bst_typo_total")     # line 4


            def g(name):
                return _metrics.gauge(name)            # line 8: dynamic
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "metric-name"]
        assert sorted(f.line for f in fs) == [4, 8]

    def test_duplicate_declaration(self, tmp_path):
        _write_tree(tmp_path, {"observe/metric_names.py": """
            METRICS = {
                "bst_twice_total": "one",
                "bst_twice_total": "two",
            }
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "metric-name"]
        assert len(fs) == 1 and "more than once" in fs[0].message


class TestSpanNameCheck:
    FILES = {
        "observe/metric_names.py": """
            SPANS = {
                "fusion.kernel": "a declared span",
            }
            """,
    }

    def test_unregistered_and_dynamic(self, tmp_path):
        _write_tree(tmp_path, {**self.FILES, "mod.py": """
            from observe import trace as _trace
            import profiling


            def f(stage):
                with profiling.span("fusion.kernel"):
                    pass
                with profiling.span("fusion.typo"):      # line 8
                    pass
                _trace.instant("stage." + stage)         # line 10: dynamic
                _trace.record("B", "fusion.missing")     # line 11
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "span-name"]
        assert sorted(f.line for f in fs) == [8, 10, 11]

    def test_duplicate_declaration(self, tmp_path):
        _write_tree(tmp_path, {"observe/metric_names.py": """
            SPANS = {
                "span.twice": "one",
                "span.twice": "two",
            }
            """})
        fs = [f for f in run_lint(tmp_path) if f.check == "span-name"]
        assert len(fs) == 1 and "more than once" in fs[0].message

    def test_declaring_modules_exempt(self, tmp_path):
        # trace.py/profiling.py manipulate names as data; only CALL sites
        # elsewhere are checked
        _write_tree(tmp_path, {**self.FILES, "observe/trace.py": """
            def span(name):
                return record("B", name)
            """, "profiling.py": """
            def span(name, dynamic=str):
                return dynamic(name)
            """})
        assert not [f for f in run_lint(tmp_path)
                    if f.check == "span-name"]


class TestSuppressionAndBaseline:
    def test_clean_fixture_zero_findings(self, tmp_path):
        _write_tree(tmp_path, {
            "ops/k.py": """
                import jax
                import jax.numpy as jnp


                def kernel(x):
                    return jnp.sum(x * 2.0)


                def drain(x):
                    return jax.device_get(kernel(x))
                """,
            "store.py": """
                import threading

                _LOCK = threading.Lock()
                _CACHE = {}


                def put(k, v):
                    with _LOCK:
                        _CACHE[k] = v
                """})
        assert run_lint(tmp_path) == []

    def test_suppression_same_line_and_line_above(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import os


            def f():
                a = os.environ.get("BST_A")  # bst-lint: off=config-registry
                # bst-lint: off (reason documented here)
                b = os.environ.get("BST_B")
                c = os.environ.get("BST_C")  # wrong check name:
                # stays flagged
                return a, b, c
            """})
        fs = run_lint(tmp_path)
        assert [f.line for f in fs] == [8]

    def test_suppression_is_per_check(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import os


            def f():
                return os.environ.get("BST_A")  # bst-lint: off=host-sync
            """})
        assert [f.check for f in run_lint(tmp_path)] == ["config-registry"]

    def test_baseline_counts_admit_legacy_only(self, tmp_path):
        _write_tree(tmp_path, {"mod.py": """
            import os


            def f():
                return os.environ.get("BST_A")
            """})
        fs = run_lint(tmp_path)
        assert len(fs) == 1
        baseline = baseline_counts(fs)
        assert new_findings(fs, baseline) == []
        # a second identical occurrence is NEW relative to count 1
        assert len(new_findings(fs + fs, baseline)) == 1


# -- layer 3: config registry behavior + doc drift -------------------------


class TestConfigRegistry:
    def test_call_time_reads(self, monkeypatch):
        monkeypatch.delenv("BST_CHUNK_CACHE_BYTES", raising=False)
        assert config.get_bytes("BST_CHUNK_CACHE_BYTES") == 1 << 30
        monkeypatch.setenv("BST_CHUNK_CACHE_BYTES", "2e9")
        assert config.get_bytes("BST_CHUNK_CACHE_BYTES") == int(2e9)
        assert config.source("BST_CHUNK_CACHE_BYTES") == "env"

    def test_bool_explicit_falsy_rule(self, monkeypatch):
        for raw, want in [("0", False), ("false", False), ("off", False),
                          ("no", False), ("1", True), ("true", True),
                          ("2", True)]:
            monkeypatch.setenv("BST_PAIR_SHARD", raw)
            assert config.get_bool("BST_PAIR_SHARD") is want, raw

    def test_unparseable_falls_back_to_default(self, monkeypatch):
        monkeypatch.setenv("BST_BENCH_RUNS", "not-a-number")
        assert config.get_int("BST_BENCH_RUNS") == 5
        assert config.source("BST_BENCH_RUNS") == "default"

    def test_undeclared_name_raises(self):
        with pytest.raises(KeyError):
            config.get("BST_NOT_A_KNOB")

    def test_uris_read_env_at_call_time(self, monkeypatch):
        # the io/uris.py import-time-snapshot bug: env set AFTER import
        # must be visible (and the setter must still override)
        from bigstitcher_spark_tpu.io import uris

        monkeypatch.setattr(uris, "_S3_REGION", [uris._UNSET])
        monkeypatch.setenv("BST_S3_REGION", "eu-central-1")
        assert uris.get_s3_region() == "eu-central-1"
        spec = uris.kvstore_spec("s3://bucket/root")
        assert spec["aws_region"] == "eu-central-1"
        uris.set_s3_region("us-west-2")
        assert uris.get_s3_region() == "us-west-2"
        uris.set_s3_region(None)    # explicit clear beats the env
        assert uris.get_s3_region() is None
        monkeypatch.setattr(uris, "_S3_ENDPOINT", [uris._UNSET])
        monkeypatch.setenv("BST_S3_ENDPOINT", "http://127.0.0.1:9000")
        assert uris.get_s3_endpoint() == "http://127.0.0.1:9000"

    def test_resolve_covers_every_knob(self):
        rows = config.resolve()
        assert {r["name"] for r in rows} == set(config.KNOBS)
        assert all(r["doc"] for r in rows)


class TestDocDrift:
    DOCS = ("README.md", "WORKFLOW.md", "PERF.md")

    def _doc_names(self):
        import re

        names: set[str] = set()
        for doc in self.DOCS:
            text = (REPO / doc).read_text(encoding="utf-8")
            names |= set(re.findall(r"\bBST_[A-Z0-9_]+\b", text))
        return names

    def test_every_doc_name_is_declared(self):
        undeclared = self._doc_names() - set(config.KNOBS)
        assert not undeclared, (
            f"docs mention undeclared knobs: {sorted(undeclared)} — "
            f"declare them in bigstitcher_spark_tpu/config.py or fix "
            f"the docs")

    def test_every_knob_is_documented(self):
        undocumented = set(config.KNOBS) - self._doc_names()
        assert not undocumented, (
            f"knobs missing from {self.DOCS}: {sorted(undocumented)} — "
            f"add them to the README configuration table")

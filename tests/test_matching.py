"""Descriptor matching + RANSAC + ICP: golden tests on synthetic clouds with
known transforms, plus the detect -> match -> solve pipeline on the synthetic
project (the IP-source registration path the reference exercises via
match-interestpoints + solver, SURVEY.md §3.4/§3.5)."""

import os

import numpy as np
import pytest
from click.testing import CliRunner


def _cloud(n=80, seed=0, lo=0.0, hi=200.0):
    return np.random.default_rng(seed).uniform(lo, hi, (n, 3))


def _rot(deg, axis=2):
    a = np.deg2rad(deg)
    c, s = np.cos(a), np.sin(a)
    m = np.eye(3)
    i, j = [(1, 2), (0, 2), (0, 1)][axis]
    m[i, i], m[i, j], m[j, i], m[j, j] = c, -s, s, c
    return m


class TestDescriptorMatching:
    def test_translation_invariant_match(self):
        from bigstitcher_spark_tpu.ops.descriptors import match_candidates

        a = _cloud(60, seed=1)
        b = a + np.array([30.0, -12.0, 7.0])
        cand = match_candidates(a, b, method="PRECISE_TRANSLATION")
        assert len(cand) >= 0.8 * len(a)
        correct = (cand[:, 0] == cand[:, 1]).mean()
        assert correct > 0.95

    def test_rotation_invariant_match(self):
        """Local-frame descriptors keep matching under a LARGE rotation
        (where raw-offset SSD has lost all signal) and feed a rigid RANSAC
        that recovers the rotation."""
        from bigstitcher_spark_tpu.ops.descriptors import (
            match_candidates, ransac,
        )

        a = _cloud(60, seed=2)
        R = _rot(70) @ _rot(40, axis=0)
        t = np.array([5.0, 8.0, -3.0])
        b = a @ R.T + t
        cand = match_candidates(a, b, method="FAST_ROTATION")
        assert len(cand) >= 0.7 * len(a)
        assert (cand[:, 0] == cand[:, 1]).mean() > 0.9
        res = ransac(a[cand[:, 0]], b[cand[:, 1]], "RIGID", "NONE", 0.0,
                     epsilon=1.0, iterations=1000, min_inliers=5)
        assert res is not None
        model, _ = res
        np.testing.assert_allclose(model[:, :3], R, atol=1e-3)
        np.testing.assert_allclose(model[:, 3], t, atol=0.1)

    def test_ransac_rejects_outliers(self):
        from bigstitcher_spark_tpu.ops.descriptors import ransac

        rng = np.random.default_rng(3)
        a = _cloud(100, seed=3)
        t = np.array([12.0, -5.0, 9.0])
        b = a + t + rng.normal(0, 0.3, a.shape)
        # 30% outliers
        n_out = 30
        b[:n_out] = rng.uniform(0, 200, (n_out, 3))
        res = ransac(a, b, "TRANSLATION", "NONE", 0.0,
                     epsilon=3.0, iterations=2000)
        assert res is not None
        model, inliers = res
        assert inliers[n_out:].mean() > 0.95
        assert inliers[:n_out].mean() < 0.1
        np.testing.assert_allclose(model[:, 3], t, atol=0.2)

    def test_ransac_affine(self):
        from bigstitcher_spark_tpu.ops.descriptors import ransac

        rng = np.random.default_rng(4)
        a = _cloud(150, seed=4)
        A = np.hstack([_rot(10) * 1.05, np.array([[4.0], [-2.0], [1.0]])])
        b = a @ A[:, :3].T + A[:, 3] + rng.normal(0, 0.2, a.shape)
        b[:20] = rng.uniform(0, 200, (20, 3))
        res = ransac(a, b, "AFFINE", "NONE", 0.0, epsilon=2.0, iterations=3000)
        assert res is not None
        model, inliers = res
        np.testing.assert_allclose(model, A, atol=0.1)

    def test_icp_converges(self):
        from bigstitcher_spark_tpu.ops.descriptors import icp

        a = _cloud(80, seed=5)
        t = np.array([1.5, -1.0, 0.8])  # within icp max_distance basin
        b = a + t
        res = icp(a, b, "TRANSLATION", "NONE", 0.0, max_distance=4.0)
        assert res is not None
        model, pairs = res
        np.testing.assert_allclose(model[:, 3], t, atol=0.05)
        assert (pairs[:, 0] == pairs[:, 1]).mean() > 0.95


class TestMatchingPipeline:
    @pytest.fixture(scope="class")
    def matched_project(self, tmp_path_factory):
        """detect + match on a jittered 2x2 grid; shared by the tests below."""
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.detection import (
            DetectionParams, detect_interest_points, save_detections,
        )
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_interest_points, save_matches,
        )
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path_factory.mktemp("match") / "proj"),
            n_tiles=(2, 2, 1), tile_size=(96, 96, 48), overlap=32,
            jitter=3.0, seed=9, n_beads_per_tile=40,
        )
        sd = SpimData.load(proj.xml_path)
        views = sorted(sd.registrations)
        dets = detect_interest_points(
            sd, ViewLoader(sd), views,
            DetectionParams(downsample_xy=1, downsample_z=1,
                            block_size=(96, 96, 48)),
            progress=False,
        )
        store = InterestPointStore.for_project(sd)
        dparams = DetectionParams()
        save_detections(sd, store, dets, dparams)
        mparams = MatchingParams(ransac_min_inliers=5,
                                 ransac_iterations=2000)
        results = match_interest_points(sd, views, mparams, store,
                                        progress=False)
        save_matches(sd, store, results, mparams, views)
        sd.save(proj.xml_path)
        return proj, results

    def test_matches_link_same_beads(self, matched_project):
        """Each correspondence must map to the same global bead (<2px)."""
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData

        proj, results = matched_project
        sd = SpimData.load(proj.xml_path)
        store = InterestPointStore.for_project(sd)
        checked = 0
        for r in results:
            if len(r.ids_a) == 0:
                continue
            ids_a, locs_a = store.load_points(r.view_a, "beads")
            ids_b, locs_b = store.load_points(r.view_b, "beads")
            la = {int(i): p for i, p in zip(ids_a, locs_a)}
            lb = {int(i): p for i, p in zip(ids_b, locs_b)}
            offa = proj.true_offsets[r.view_a.setup]
            offb = proj.true_offsets[r.view_b.setup]
            dists = []
            for ia, ib in zip(r.ids_a.astype(int), r.ids_b.astype(int)):
                ga = la[ia] + offa   # TRUE global position
                gb = lb[ib] + offb
                dists.append(np.linalg.norm(ga - gb))
                checked += 1
            dists = np.array(dists)
            # all within RANSAC epsilon; the bulk pixel-exact
            assert dists.max() < 5.0
            assert np.median(dists) < 1.0
        assert checked >= 20

    def test_solver_ip_source_recovers_offsets(self, matched_project):
        """detect -> match -> solver(IP) recovers the true tile offsets
        (the reference's interest-point registration pipeline end-to-end)."""
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.models.solver import (
            SolverParams, solve, store_corrections,
        )

        proj, _ = matched_project
        sd = SpimData.load(proj.xml_path)
        views = sorted(sd.registrations)
        params = SolverParams(source="IP", model="TRANSLATION",
                              labels=["beads"])
        res = solve(sd, views, params, verbose=False)
        assert res.error < 1.0
        store_corrections(sd, res, params)
        # after storing, view models must place beads consistently:
        # residual = (model_v(local_bead)) vs true global, up to a GLOBAL shift
        deltas = []
        for v in views:
            m = sd.model(v)
            true_off = proj.true_offsets[v.setup]
            # model maps local -> world; truth maps local -> local+true_off
            deltas.append(m[:, 3] - true_off)
        deltas = np.array(deltas)
        spread = np.abs(deltas - deltas.mean(axis=0)).max()
        assert spread < 1.0, f"tile placement spread {spread}"

    def test_correspondence_roundtrip(self, matched_project):
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId

        proj, results = matched_project
        sd = SpimData.load(proj.xml_path)
        store = InterestPointStore.for_project(sd)
        corrs = store.load_correspondences(ViewId(0, 0), "beads")
        assert len(corrs) > 0
        # symmetry: every correspondence appears mirrored on the other view
        for c in corrs[:10]:
            back = store.load_correspondences(c.other_view, c.other_label)
            assert any(
                b.id == c.other_id and b.other_id == c.id
                and b.other_view == ViewId(0, 0)
                for b in back
            )


class TestGroupedMatching:
    def test_merge_min_distance(self):
        from bigstitcher_spark_tpu.models.matching import merge_min_distance

        pts = np.array([
            [0.0, 0.0, 0.0], [50.0, 0.0, 0.0],      # view 0
            [0.2, 0.1, 0.0], [80.0, 0.0, 0.0],      # view 1: dup of p0 + new
            [50.1, 0.0, 0.1], [0.1, 0.0, 0.1],      # view 2: dups of p1, p0
        ])
        view_of = np.array([0, 0, 1, 1, 2, 2])
        keep = merge_min_distance(view_of, pts, radius=5.0)
        assert keep.tolist() == [True, True, False, True, False, False]
        # radius 0 disables merging
        assert merge_min_distance(view_of, pts, radius=0.0).all()

    @pytest.fixture(scope="class")
    def two_channel_project(self, tmp_path_factory):
        """2 tiles x 2 channels with SYNTHETIC interest points: each channel
        sees a disjoint half of the global bead set (deterministic, and the
        realistic case where grouping helps — each channel alone has too few
        points in the overlap)."""
        from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData
        from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

        proj = make_synthetic_project(
            str(tmp_path_factory.mktemp("grouped") / "proj"),
            n_tiles=(2, 1, 1), tile_size=(96, 96, 48), overlap=40,
            jitter=2.0, seed=21, n_beads_per_tile=120, n_channels=2,
        )
        sd = SpimData.load(proj.xml_path)
        views = sorted(sd.registrations)
        store = InterestPointStore.for_project(sd)
        beads = proj.bead_positions
        for v in views:
            ch = sd.setups[v.setup].attributes["channel"]
            sel = beads[ch::2]  # channel 0 -> even beads, channel 1 -> odd
            local = sel - proj.true_offsets[v.setup]
            size = np.array(sd.view_size(v), float)
            inside = np.all((local >= 1) & (local <= size - 2), axis=1)
            pts = local[inside]
            path = store.save_points(v, "beads", pts)
            from bigstitcher_spark_tpu.models.detection import (
                register_points_in_xml,
            )
            register_points_in_xml(sd, v, "beads", "synthetic", path)
        sd.save(proj.xml_path)
        return proj, sd, store, views

    def test_group_channels_matches_both_channels(self, two_channel_project):
        """--groupChannels pools both channels per tile; the split-back
        produces correspondences for views of BOTH channels
        (SparkGeometricDescriptorMatching.java:343-503)."""
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_interest_points, save_matches,
        )

        proj, sd, store, views = two_channel_project
        params = MatchingParams(
            group_channels=True, method="PRECISE_TRANSLATION",
            interest_points_for_overlap_only=True,
            ransac_min_inliers=5, ransac_iterations=2000,
        )
        results = match_interest_points(sd, views, params, store,
                                        progress=False)
        assert results, "no grouped match results"
        channels_covered = {
            sd.setups[r.view_a.setup].attributes["channel"] for r in results
        } | {
            sd.setups[r.view_b.setup].attributes["channel"] for r in results
        }
        assert channels_covered == {0, 1}
        # correspondences stay within one channel here (disjoint bead sets)
        for r in results:
            assert (sd.setups[r.view_a.setup].attributes["channel"]
                    == sd.setups[r.view_b.setup].attributes["channel"])
        # every correspondence links the same physical bead (<2 px in truth)
        for r in results:
            ids_a, locs_a = store.load_points(r.view_a, "beads")
            ids_b, locs_b = store.load_points(r.view_b, "beads")
            la = {int(i): p for i, p in zip(ids_a, locs_a)}
            lb = {int(i): p for i, p in zip(ids_b, locs_b)}
            offa = proj.true_offsets[r.view_a.setup]
            offb = proj.true_offsets[r.view_b.setup]
            d = [np.linalg.norm((la[int(ia)] + offa) - (lb[int(ib)] + offb))
                 for ia, ib in zip(r.ids_a, r.ids_b)]
            assert np.median(d) < 1.5
        save_matches(sd, store, results, params,  views)

    def test_split_timepoints_individual_policy_warns(self, two_channel_project):
        """--splitTimepoints + the default TIMEPOINTS_INDIVIDUALLY policy
        yields zero pairs; plan_group_pairs must say so instead of silently
        matching nothing (ADVICE r2 low, VERDICT r3 item 9)."""
        import warnings

        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, build_match_groups, plan_group_pairs,
        )

        proj, sd, store, views = two_channel_project
        # fake a second timepoint so there are two per-timepoint groups
        params = MatchingParams(split_timepoints=True)
        groups = build_match_groups(sd, views, params)
        groups = [groups[0], tuple(
            type(v)(timepoint=v.timepoint + 1, setup=v.setup)
            for v in groups[0])]
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            pairs = plan_group_pairs(sd, groups, params)
        assert pairs == []
        assert any("splitTimepoints" in str(x.message) for x in w)

    def test_merge_distance_drops_cross_view_duplicates(
            self, two_channel_project):
        """Points duplicated across a group's member views within the merge
        radius collapse to one pooled point (countBefore >>> countAfter)."""
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, build_match_groups, merge_min_distance,
        )
        from bigstitcher_spark_tpu.utils.geometry import apply_affine

        proj, sd, store, views = two_channel_project
        params = MatchingParams(group_channels=True)
        groups = build_match_groups(sd, views, params)
        assert len(groups) == 2 and all(len(g) == 2 for g in groups)
        g = groups[0]
        view_of, pts = [], []
        for k, v in enumerate(g):
            ids, locs = store.load_points(v, "beads")
            w = apply_affine(sd.model(v), locs)
            view_of.append(np.full(len(ids), k, np.int32))
            pts.append(w)
        # duplicate view 0's cloud as if view 1 re-detected the same beads
        view_of.append(np.full(len(pts[0]), 1, np.int32))
        pts.append(pts[0] + 0.3)
        view_of = np.concatenate(view_of)
        pts = np.concatenate(pts)
        keep = merge_min_distance(view_of, pts, 5.0)
        n0 = int((view_of == 0).sum())
        # all injected duplicates dropped, non-duplicate points kept
        assert keep.sum() == len(pts) - n0

    def test_cli_grouped_flags(self, two_channel_project):
        from bigstitcher_spark_tpu.cli.main import cli

        proj, _, _, _ = two_channel_project
        runner = CliRunner()
        res = runner.invoke(cli, [
            "match-interestpoints", "-x", proj.xml_path, "--groupChannels",
            "--interestPointMergeDistance", "0",
            "--ransacMinNumInliers", "5", "--ransacIterations", "2000",
            "--dryRun",
        ], catch_exceptions=False)
        assert res.exit_code == 0, res.output
        assert "grouped" in res.output


def test_cli_match(tmp_path):
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.io.spimdata import SpimData, ViewId
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "proj"), n_tiles=(2, 1, 1), tile_size=(80, 80, 40),
        overlap=28, jitter=2.0, seed=6, n_beads_per_tile=35,
    )
    runner = CliRunner()
    res = runner.invoke(cli, [
        "detect-interestpoints", "-x", proj.xml_path,
        "-dsxy", "1", "-dsz", "1", "--blockSize", "80,80,40",
    ])
    assert res.exit_code == 0, res.output
    res = runner.invoke(cli, [
        "match-interestpoints", "-x", proj.xml_path,
        "--ransacMinNumInliers", "5", "--ransacIterations", "2000",
    ])
    assert res.exit_code == 0, res.output
    sd = SpimData.load(proj.xml_path)
    store = InterestPointStore.for_project(sd)
    assert len(store.load_correspondences(ViewId(0, 0), "beads")) > 0


class TestTiledMatching:
    """Row/column-tiled kNN + ratio test + chunked RANSAC: large point
    clouds must run in bounded memory (the reference handles them with
    KD-trees; dense (N,N)/(Da,Db) matrices OOM at 1e5 — VERDICT r3 item 7),
    and the tiled kernels must agree exactly with the dense ones."""

    def test_knn_tiled_equals_dense(self, monkeypatch):
        import bigstitcher_spark_tpu.ops.descriptors as D

        rng = np.random.default_rng(4)
        pts = rng.uniform(0, 100, (500, 3)).astype(np.float32)
        dense = np.asarray(D.knn_indices(pts, 4))
        monkeypatch.setattr(D, "_TILE_ENTRIES", 1 << 10)  # force tiny tiles
        D._knn_kernel.clear_cache()
        tiled = np.asarray(D.knn_indices(pts, 4))
        assert (dense == tiled).all()

    def test_ratio_test_tiled_equals_dense(self, monkeypatch):
        import bigstitcher_spark_tpu.ops.descriptors as D

        rng = np.random.default_rng(5)
        pts_a = rng.uniform(0, 300, (800, 3)).astype(np.float32)
        pts_b = (pts_a + np.array([2.0, -1.0, 0.5])
                 + rng.normal(0, 0.1, pts_a.shape)).astype(np.float32)
        dense = D.match_candidates(pts_a, pts_b, method=D.RGLDM)
        monkeypatch.setattr(D, "_TILE_ENTRIES", 1 << 12)
        tiled = D.match_candidates(pts_a, pts_b, method=D.RGLDM)
        assert len(dense) > 400
        assert np.array_equal(dense, tiled)

    def test_chunked_ransac_recovers_translation(self):
        """M large enough to force the iteration-chunked scorer (a dense
        (10k, M) error matrix would be multiple GB)."""
        import bigstitcher_spark_tpu.ops.descriptors as D

        rng = np.random.default_rng(2)
        m = 40000
        a = rng.uniform(0, 500, (m, 3))
        t = np.array([3.2, -1.7, 0.9])
        b = a + t + rng.normal(0, 0.3, a.shape)
        b[:m // 4] = rng.uniform(0, 500, (m // 4, 3))  # 25% outliers
        res = D.ransac(a.astype(np.float32), b.astype(np.float32),
                       model_kind="TRANSLATION", reg_kind="NONE",
                       iterations=2000)
        assert res is not None
        model, inl = res
        np.testing.assert_allclose(model[:, 3], t, atol=0.05)
        assert inl.sum() >= 0.7 * (m - m // 4)

    @pytest.mark.skipif(not os.environ.get("BST_BIG_TESTS"),
                        reason="1e5-point soak (minutes on 1 CPU core); "
                               "set BST_BIG_TESTS=1 to run")
    def test_1e5_point_match_bounded_memory(self):
        import bigstitcher_spark_tpu.ops.descriptors as D

        rng = np.random.default_rng(6)
        n = 100_000
        pts_a = rng.uniform(0, 4000, (n, 3)).astype(np.float32)
        pts_b = (pts_a + np.array([5.0, -3.0, 2.0])
                 + rng.normal(0, 0.05, pts_a.shape)).astype(np.float32)
        cand = D.match_candidates(pts_a, pts_b, method=D.RGLDM)
        assert len(cand) > n // 4


class TestMultiConsensusRansac:
    """--ransacMultiConsensus (-rmc): a pair whose correspondences follow
    TWO distinct transforms yields both consensus sets
    (RANSACParameters multiconsensus, SparkGeometricDescriptorMatching.java:145-146)."""

    def test_two_translations_both_found(self):
        from bigstitcher_spark_tpu.ops.descriptors import ransac, ransac_multi

        rng = np.random.default_rng(8)
        a1 = rng.uniform(0, 150, (60, 3))
        a2 = rng.uniform(0, 150, (60, 3))
        t1 = np.array([5.0, -2.0, 1.0])
        t2 = np.array([-12.0, 7.0, -4.0])
        cand_a = np.concatenate([a1, a2])
        cand_b = np.concatenate([a1 + t1, a2 + t2])
        noise = rng.normal(0, 0.2, cand_b.shape)
        cand_b = cand_b + noise

        single = ransac(cand_a, cand_b, "TRANSLATION", "NONE", 0.0,
                        epsilon=3.0, iterations=2000)
        assert single is not None
        _, inl = single
        assert inl.sum() <= 65  # single consensus captures only one cluster

        sets = ransac_multi(cand_a, cand_b, "TRANSLATION", "NONE", 0.0,
                            epsilon=3.0, iterations=2000)
        assert len(sets) == 2
        found = sorted(tuple(np.round(m[:, 3]).astype(int)) for m, _ in sets)
        assert found == sorted([tuple(np.round(t).astype(int))
                                for t in (t1, t2)])
        union = np.zeros(len(cand_a), bool)
        for _, mask in sets:
            union |= mask
        assert union.sum() > 100  # both clusters covered
        # masks are disjoint (inliers removed between rounds)
        assert (sets[0][1] & sets[1][1]).sum() == 0

    def test_match_pair_union(self):
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_pair,
        )

        rng = np.random.default_rng(9)
        # two spatially separated clusters so local descriptors stay clean
        a = np.concatenate([rng.uniform(0, 200, (40, 3)),
                            rng.uniform(400, 600, (40, 3))])
        t1 = np.array([4.0, -3.0, 2.0])
        t2 = np.array([-15.0, 9.0, -5.0])
        b = np.concatenate([a[:40] + t1, a[40:] + t2])
        params = MatchingParams(method="PRECISE_TRANSLATION",
                                model="TRANSLATION", regularization="NONE",
                                ransac_min_inliers=10,
                                ransac_iterations=2000,
                                ransac_multi_consensus=True)
        pairs, model, n_cand = match_pair(a, b, params)
        # both halves matched (single consensus would keep only one half)
        assert (pairs[:, 0] < 40).sum() > 20
        assert (pairs[:, 0] >= 40).sum() > 20


class TestReferenceOptionParity:
    """Residual CLI-surface options closed in round 4: searchRadius,
    matchAcrossLabels label tasks, icpUseRANSAC, viewReg."""

    def test_search_radius_limits_world_distance(self):
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_pair,
        )

        rng = np.random.default_rng(12)
        a = rng.uniform(0, 200, (50, 3))
        b = a + np.array([40.0, 0.0, 0.0]) + rng.normal(0, 0.1, a.shape)
        base = MatchingParams(method="PRECISE_TRANSLATION",
                              model="TRANSLATION", regularization="NONE",
                              ransac_min_inliers=5, ransac_iterations=1000)
        pairs, _, _ = match_pair(a, b, base)
        assert len(pairs) > 20  # matches exist at distance ~40
        import dataclasses

        tight = dataclasses.replace(base, search_radius=10.0)
        pairs2, _, _ = match_pair(a, b, tight)
        assert len(pairs2) == 0  # all correspondences are ~40 px apart

    def test_label_pairs_tasks(self):
        from bigstitcher_spark_tpu.models.matching import MatchingParams

        p = MatchingParams(label="beads", labels=("nuclei",))
        assert p.label_pairs() == [("beads", "beads"), ("nuclei", "nuclei")]
        p2 = MatchingParams(label="beads", labels=("nuclei",),
                            match_across_labels=True)
        # BOTH directions of the cross combo: view pairs are unordered, so
        # (beads of A vs nuclei of B) and (nuclei of A vs beads of B) are
        # distinct tasks
        assert ("beads", "nuclei") in p2.label_pairs()
        assert ("nuclei", "beads") in p2.label_pairs()
        assert len(p2.label_pairs()) == 4

    def test_icp_use_ransac_drops_outliers(self):
        from bigstitcher_spark_tpu.ops.descriptors import icp

        rng = np.random.default_rng(13)
        a = rng.uniform(0, 200, (60, 3))
        t = np.array([1.0, -0.5, 0.5])
        b = a + t
        # contaminate: 10 points of A get a DIFFERENT consistent shift that
        # lands within max_distance, dragging the plain-ICP fit off
        b[:10] = a[:10] + np.array([-2.5, 2.5, 0.0])
        plain = icp(a, b, "TRANSLATION", "NONE", 0.0, max_distance=4.0)
        assert plain is not None
        err_plain = np.abs(plain[0][:, 3] - t).max()
        res = icp(a, b, "TRANSLATION", "NONE", 0.0, max_distance=4.0,
                  use_ransac=True, ransac_epsilon=1.0, seed=3)
        assert res is not None
        model, pairs = res
        np.testing.assert_allclose(model[:, 3], t, atol=0.05)
        # RANSAC filtering excluded the contaminated block from the fit
        assert np.abs(model[:, 3] - t).max() < err_plain
        assert (pairs[:, 0] >= 10).all()

    def test_grouped_rejects_multi_label(self):
        from bigstitcher_spark_tpu.models.matching import (
            MatchingParams, match_interest_points,
        )

        with pytest.raises(ValueError, match="single label"):
            match_interest_points(
                None, [], MatchingParams(group_tiles=True,
                                         labels=("nuclei",)), store=object())

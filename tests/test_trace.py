"""Timeline flight recorder (observe/trace.py) + ``bst trace-report``.

The acceptance contract of the tracing PR: a ``--trace`` affine-fusion
run produces a Perfetto-loadable trace whose begin/end events pair up,
with one d2h and one write interval per output block on the per-block
path; the report computes overlap percentages and a named critical path
on a hand-built trace with KNOWN answers; ring overflow keeps the newest
events and counts drops; and with tracing off nothing records while the
span aggregates still work (the zero-overhead gate).
"""

import json
import os
import threading

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import profiling
from bigstitcher_spark_tpu.observe import trace
from bigstitcher_spark_tpu.analysis.tracereport import (
    build_intervals,
    build_report,
    load_events,
    render_report,
)


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """The recorder is process-global; never leak it between tests."""
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()
    yield
    trace.reset()
    profiling.enable(False)
    profiling.get().reset()


def _pairing_ok(events):
    """Every B has a matching E per (pid, tid, name) series."""
    counts = {}
    for ev in events:
        if ev.get("ph") in ("B", "E"):
            key = (ev.get("pid", 0), ev.get("tid", 0), ev.get("name"))
            b, e = counts.get(key, (0, 0))
            counts[key] = (b + (ev["ph"] == "B"), e + (ev["ph"] == "E"))
    return all(b == e for b, e in counts.values()), counts


class TestRecorder:
    def test_off_by_default_records_nothing(self):
        assert not trace.enabled()
        trace.record("B", "fusion.kernel")
        trace.instant("io.read", nbytes=10)
        with trace.span("fusion.write"):
            pass
        s = trace.stats()
        assert s["recorded"] == 0 and s["buffered"] == 0

    def test_span_aggregates_unchanged_when_tracing_off(self):
        # the zero-overhead gate: profiling on, tracing off — the span
        # table fills while the flight recorder records NOTHING
        profiling.enable(True)
        with profiling.span("fusion.kernel", item=(0, 0, 0), nbytes=64):
            pass
        stats = profiling.get().stats()
        assert stats["fusion.kernel"].count == 1
        assert trace.stats()["recorded"] == 0

    def test_trace_without_profiling_leaves_aggregates_empty(self):
        trace.configure(buffer_bytes=1 << 20)
        with profiling.span("fusion.kernel"):
            pass
        assert profiling.get().stats() == {}
        assert trace.stats()["recorded"] == 2  # the B and the E

    def test_begin_end_pairing_across_threads(self):
        trace.configure(buffer_bytes=1 << 20)

        def work(i):
            with trace.span("pair.dispatch", device=i % 2, item=i):
                with trace.span("fusion.kernel", item=i):
                    pass
            trace.instant("io.read", nbytes=i)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        snap = trace.snapshot()
        assert len(snap) == 8 * 5  # 2 B/E pairs + 1 instant per thread
        ok, counts = _pairing_ok(
            [{"ph": e["ph"], "tid": e["tid"], "name": e["name"]}
             for e in snap])
        assert ok, counts

    def test_overflow_keeps_newest_and_counts_drops(self):
        trace.configure(buffer_bytes=0)  # clamps to _MIN_CAPACITY events
        cap = trace.stats()["capacity_events"]
        n = cap + 36
        for i in range(n):
            trace.instant("io.read", item=i)
        s = trace.stats()
        assert s["recorded"] == n
        assert s["buffered"] == cap
        assert s["dropped"] == 36
        items = [e["item"] for e in trace.snapshot()]
        assert items == list(range(36, n))  # oldest 36 gone, newest kept

    def test_reset_stops_recording(self):
        trace.configure(buffer_bytes=1 << 20)
        trace.instant("io.read")
        trace.reset()
        assert not trace.enabled()
        trace.instant("io.read")
        assert trace.stats()["recorded"] == 0

    def test_thread_names_reset_between_runs(self):
        # OS thread idents recycle: a stale first-run name must not label
        # a later run's tracks
        trace.configure(buffer_bytes=1 << 20)
        t = threading.Thread(target=lambda: trace.instant("io.read"),
                             name="first-run-writer")
        t.start(); t.join()
        doc = trace.export(0, 1)
        assert any("first-run-writer" in (e.get("args") or {}).get(
            "name", "") for e in doc["traceEvents"] if e["ph"] == "M")
        trace.configure(buffer_bytes=1 << 20)
        trace.instant("io.read")
        doc = trace.export(0, 1)
        assert not any("first-run-writer" in (e.get("args") or {}).get(
            "name", "") for e in doc["traceEvents"] if e["ph"] == "M")


class TestExport:
    def test_perfetto_document_structure(self):
        trace.configure(buffer_bytes=1 << 20)
        with trace.span("fusion.kernel", device=2, item=[0, 0, 0],
                        nbytes=4096):
            pass
        with trace.span("fusion.write", item=[0, 0, 0], nbytes=2048):
            pass
        trace.instant("pair.redispatch", device=2, item=7)
        doc = trace.export(0, 1)
        evs = doc["traceEvents"]
        # metadata names the tracks: the process, device 2's track, and
        # the host thread's track
        meta = [e for e in evs if e["ph"] == "M"]
        names = {(e["name"], e.get("tid")) for e in meta}
        assert ("process_name", None) in names
        dev_tids = [e["tid"] for e in meta if e["name"] == "thread_name"
                    and "device 2" in e["args"]["name"]]
        assert len(dev_tids) == 1
        # device-attributed events ride the device track
        kernel_b = next(e for e in evs
                        if e.get("name") == "fusion.kernel"
                        and e["ph"] == "B")
        assert kernel_b["tid"] == dev_tids[0]
        assert kernel_b["args"]["bytes"] == 4096
        assert kernel_b["args"]["item"] == [0, 0, 0]
        # host event on a small host-thread track, instants flagged
        write_b = next(e for e in evs
                       if e.get("name") == "fusion.write"
                       and e["ph"] == "B")
        assert write_b["tid"] != kernel_b["tid"]
        inst = next(e for e in evs if e["ph"] == "i")
        assert inst["s"] == "t"
        # timestamps are microseconds, monotonic non-decreasing per track
        assert doc["bst"]["recorded"] == 5
        assert doc["bst"]["dropped"] == 0
        # round-trips through JSON (Perfetto-loadable)
        json.loads(json.dumps(doc))

    def test_finalize_resolution_and_idempotence(self, tmp_path,
                                                 monkeypatch):
        # explicit configure(path=) wins
        p = str(tmp_path / "explicit.json")
        trace.configure(buffer_bytes=1 << 20, path=p)
        trace.instant("io.read")
        assert trace.finalize() == p
        assert os.path.exists(p)
        assert not trace.enabled()
        assert trace.finalize() is None  # idempotent
        assert trace.last_path() == p

        # the BST_TRACE_PATH knob beats the dir hint
        p2 = str(tmp_path / "knob.json")
        monkeypatch.setenv("BST_TRACE_PATH", p2)
        trace.configure(buffer_bytes=1 << 20)
        trace.instant("io.read")
        assert trace.finalize(dir_hint=str(tmp_path / "tel")) == p2
        monkeypatch.delenv("BST_TRACE_PATH")

        # dir hint: the per-process telemetry name
        trace.configure(buffer_bytes=1 << 20)
        trace.instant("io.read")
        out = trace.finalize(dir_hint=str(tmp_path / "tel"))
        assert out == str(tmp_path / "tel" / "trace-00000-of-00001.json")
        with open(out) as f:
            doc = json.load(f)
        assert doc["bst"]["schema"] == trace.SCHEMA


def _ev(ph, name, ts_s, tid=1, pid=0, **args):
    return {"name": name, "cat": name.split(".")[0], "ph": ph,
            "ts": ts_s * 1e6, "pid": pid, "tid": tid, "args": args}


def _synthetic_events():
    """Two per-block chains with KNOWN numbers. Block A (the critical
    path): kernel 0-1s, d2h 1-2s, write 1.5-3s, ends at 3.0s. Block B
    rides a second track and finishes by 0.9s; its category intervals
    are disjoint from A's, so every union below is a plain sum."""
    a, b = [0, 0, 0], [16, 0, 0]
    return [
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 1,
         "args": {"name": "writer-0"}},
        _ev("B", "fusion.kernel", 0.0, item=a),
        _ev("E", "fusion.kernel", 1.0, item=a),
        _ev("B", "fusion.kernel", 0.2, tid=2, item=b),
        _ev("E", "fusion.kernel", 0.5, tid=2, item=b),
        _ev("B", "fusion.d2h", 0.6, tid=2, item=b),
        _ev("E", "fusion.d2h", 0.7, tid=2, item=b),
        _ev("B", "fusion.write", 0.7, tid=2, item=b),
        _ev("E", "fusion.write", 0.9, tid=2, item=b),
        _ev("B", "fusion.d2h", 1.0, item=a),
        _ev("E", "fusion.d2h", 2.0, item=a),
        _ev("B", "fusion.write", 1.5, item=a),
        _ev("E", "fusion.write", 3.0, item=a),
    ]


class TestSyntheticReport:
    def test_known_overlap_and_decomposition(self):
        rep = build_report(_synthetic_events())
        fusion = rep["stages"]["fusion"]
        assert fusion["wall_s"] == 3.0
        assert fusion["compute_s"] == 1.0   # [0,1] u [0.2,0.5]
        assert fusion["d2h_s"] == pytest.approx(1.1)   # [0.6,0.7]+[1.0,2.0]
        assert fusion["write_s"] == pytest.approx(1.7)  # [0.7,0.9]+[1.5,3.0]
        ov = fusion["overlap"]["d2h_write"]
        assert ov["seconds"] == pytest.approx(0.5)   # [1.5,2.0]
        assert ov["pct_of_d2h"] == pytest.approx(45.5)   # 0.5/1.1
        assert ov["pct_of_write"] == pytest.approx(29.4)  # 0.5/1.7
        assert fusion["idle_s"] == 0.0  # busy union covers [0,3]

    def test_known_critical_path(self):
        rep = build_report(_synthetic_events(), top=3)
        cp = rep["critical_path"]
        assert cp["stage"] == "fusion"
        assert cp["item"] == [0, 0, 0]        # block A ends last (3.0s)
        assert cp["total_s"] == 3.0
        segs = [s["name"] for s in cp["segments"]]
        assert segs == ["fusion.kernel", "fusion.d2h", "fusion.write"]
        top = rep["top_blocking"]
        assert top[0]["name"] == "fusion.write"   # 1.5s
        assert top[0]["seconds"] == pytest.approx(1.5)

    def test_tracks_and_idle_gaps(self):
        rep = build_report(_synthetic_events())
        tracks = {t["name"]: t for t in rep["tracks"]}
        w = tracks["writer-0"]   # tid 1: [0,1] [1,2] [1.5,3] -> busy 3.0
        assert w["busy_s"] == 3.0 and w["util_pct"] == 100.0
        t2 = tracks["tid 2"]     # [0.2,0.5] [0.6,0.9]: one 0.1s gap
        assert t2["busy_s"] == pytest.approx(0.6)
        assert t2["largest_gaps"][0]["seconds"] == pytest.approx(0.1)

    def test_report_stable_under_event_reordering(self):
        evs = _synthetic_events()
        # interleave tracks differently: stable pairing is per (pid, tid,
        # name), so shuffling ACROSS series must not change the report
        reordered = ([e for e in evs if e.get("tid") == 2]
                     + [e for e in evs if e.get("tid") != 2])
        assert build_report(evs) == build_report(reordered)

    def test_unmatched_begin_dropped_not_invented(self):
        evs = _synthetic_events()[:-1]   # ring overflow tore an E off
        rep = build_report(evs)
        assert rep["intervals"] == 5
        assert "write_s" not in rep["stages"]["fusion"] or \
            rep["stages"]["fusion"]["write_s"] == pytest.approx(0.2)

    def test_render_names_the_numbers(self):
        txt = render_report(build_report(_synthetic_events()))
        assert "overlap d2h<->write: 0.500s" in txt
        assert "critical path [fusion item [0, 0, 0]]" in txt
        assert "top blocking segments:" in txt
        assert "fusion.write 1.500s" in txt


class TestMergeTraces:
    def _doc(self, pi, pc, events):
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "bst": {"schema": trace.SCHEMA, "process_index": pi,
                        "process_count": pc, "recorded": len(events),
                        "dropped": 0}}

    def test_barrier_alignment(self, tmp_path):
        # process 1's clock runs 4s AHEAD; the shared barrier exit is the
        # anchor that pulls its events back onto process 0's timeline
        p0 = [_ev("B", "barrier", 0.9, pid=0, stage="fusion"),
              _ev("E", "barrier", 1.0, pid=0, stage="fusion"),
              _ev("B", "fusion.kernel", 1.1, pid=0),
              _ev("E", "fusion.kernel", 1.6, pid=0)]
        p1 = [_ev("B", "barrier", 4.8, pid=1, stage="fusion"),
              _ev("E", "barrier", 5.0, pid=1, stage="fusion"),
              _ev("B", "fusion.kernel", 5.1, pid=1),
              _ev("E", "fusion.kernel", 5.4, pid=1)]
        for pi, evs in ((0, p0), (1, p1)):
            with open(tmp_path / trace.trace_name(pi, 2), "w") as f:
                json.dump(self._doc(pi, 2, evs), f)
        out = trace.merge_traces(str(tmp_path))
        with open(out) as f:
            doc = json.load(f)
        assert doc["bst"]["clock_offsets_us"]["1"] == pytest.approx(-4e6)
        k1 = [e for e in doc["traceEvents"]
              if e["pid"] == 1 and e["name"] == "fusion.kernel"]
        assert [e["ts"] for e in k1] == [pytest.approx(1.1e6),
                                         pytest.approx(1.4e6)]

    def test_alignment_survives_differential_overflow(self, tmp_path):
        # process 0's ring dropped its FIRST barrier; occurrences index
        # from the tail (newest events win overflow), so the surviving
        # last barriers still pair — and the merged doc sums the drop
        # counts so trace-report can flag the truncation
        p0 = [_ev("B", "barrier", 10.9, pid=0, stage="bst"),
              _ev("E", "barrier", 11.0, pid=0, stage="bst")]
        p1 = [_ev("B", "barrier", 4.9, pid=1, stage="bst"),
              _ev("E", "barrier", 5.0, pid=1, stage="bst"),
              _ev("B", "barrier", 14.9, pid=1, stage="bst"),
              _ev("E", "barrier", 15.0, pid=1, stage="bst")]
        for pi, evs, dropped in ((0, p0, 7), (1, p1, 0)):
            doc = self._doc(pi, 2, evs)
            doc["bst"]["dropped"] = dropped
            with open(tmp_path / trace.trace_name(pi, 2), "w") as f:
                json.dump(doc, f)
        out = trace.merge_traces(str(tmp_path))
        with open(out) as f:
            doc = json.load(f)
        # last barrier of p1 (15.0s) aligns to last of p0 (11.0s): -4s,
        # NOT the -(5-11)=+6s a head-indexed pairing would compute
        assert doc["bst"]["clock_offsets_us"]["1"] == pytest.approx(-4e6)
        assert doc["bst"]["dropped"] == 7
        assert doc["bst"]["recorded"] == 6
        assert doc["bst"]["unaligned_processes"] == []

    def test_unalignable_process_is_named(self, tmp_path):
        # process 1 recorded no barrier exits at all (single-host run, or
        # its whole ring overflowed past the last barrier): its events
        # merge unshifted and the metadata names it so telemetry-merge
        # can warn instead of silently presenting skewed clocks
        p0 = [_ev("B", "barrier", 0.9, pid=0, stage="bst"),
              _ev("E", "barrier", 1.0, pid=0, stage="bst")]
        p1 = [_ev("B", "fusion.kernel", 5.1, pid=1),
              _ev("E", "fusion.kernel", 5.4, pid=1)]
        for pi, evs in ((0, p0), (1, p1)):
            with open(tmp_path / trace.trace_name(pi, 2), "w") as f:
                json.dump(self._doc(pi, 2, evs), f)
        out = trace.merge_traces(str(tmp_path))
        with open(out) as f:
            doc = json.load(f)
        assert doc["bst"]["unaligned_processes"] == [1]
        assert doc["bst"]["clock_offsets_us"]["1"] == 0.0

    def test_empty_dir_returns_none(self, tmp_path):
        assert trace.merge_traces(str(tmp_path)) is None


@pytest.fixture()
def fused_project(tmp_path):
    """A prepared 2-tile fusion container + its project."""
    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    proj = make_synthetic_project(
        str(tmp_path / "p"), n_tiles=(2, 1, 1), tile_size=(32, 32, 16),
        overlap=8, jitter=0.0, seed=11, n_beads_per_tile=6)
    out = str(tmp_path / "fused.ome.zarr")
    r = CliRunner().invoke(cli, [
        "create-fusion-container", "-x", proj.xml_path, "-o", out,
        "-s", "ZARR", "-d", "UINT16", "--blockSize", "16,16,8",
        "--minIntensity", "0", "--maxIntensity", "65535",
    ], catch_exceptions=False)
    assert r.exit_code == 0, r.output
    return proj, out


class TestEndToEnd:
    def test_per_block_d2h_and_write_intervals(self, fused_project,
                                               tmp_path):
        # the per-block driver path: exactly one d2h and one write
        # interval PER OUTPUT BLOCK, each carrying its block offset
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
        from bigstitcher_spark_tpu.io.container import read_container_meta
        from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
        from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
        from bigstitcher_spark_tpu.io.spimdata import SpimData

        proj, out = fused_project
        sd = SpimData.load(proj.xml_path)
        loader = ViewLoader(sd)
        store = ChunkStore.open(out)
        meta = read_container_meta(store)
        ds = store.open_dataset("0")
        trace.configure(buffer_bytes=8 << 20)
        stats = fuse_volume(
            sd, loader, sd.view_ids(), ds, meta.bbox,
            block_size=tuple(meta.block_size), block_scale=(1, 1, 1),
            fusion_type="AVG_BLEND", out_dtype="uint16",
            min_intensity=0, max_intensity=65535, zarr_ct=(0, 0),
            devices=1, device_resident=False,
        )
        snap = trace.snapshot()
        ivs, _ = build_intervals(trace.export(0, 1)["traceEvents"])
        n_blocks = stats.blocks - stats.skipped_empty
        assert n_blocks > 1
        for name in ("fusion.d2h", "fusion.write"):
            mine = [iv for iv in ivs if iv["name"] == name]
            assert len(mine) == n_blocks, name
            items = {tuple(iv["args"]["item"]) for iv in mine}
            assert len(items) == n_blocks   # one per DISTINCT block
            assert all(iv["args"]["bytes"] > 0 for iv in mine)
        ok, counts = _pairing_ok(
            [{"ph": e["ph"], "tid": e["tid"], "name": e["name"]}
             for e in snap])
        assert ok, counts

    def test_cli_trace_to_report(self, fused_project, tmp_path):
        from bigstitcher_spark_tpu.cli.main import cli

        _, out = fused_project
        tel = str(tmp_path / "tel")
        runner = CliRunner()
        r = runner.invoke(cli, [
            "affine-fusion", "-o", out, "--blockScale", "1,1,1",
            "--devices", "1", "--trace", "--telemetry-dir", tel,
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert not trace.enabled()   # finalized with the command

        # the trace archived next to the manifest, and the manifest
        # points at it
        tpath = os.path.join(tel, "trace-00000-of-00001.json")
        assert os.path.exists(tpath)
        with open(os.path.join(tel,
                               "manifest-00000-of-00001.json")) as f:
            assert json.load(f)["trace_file"] == os.path.basename(tpath)

        # Perfetto-loadable: valid JSON, B/E pairing, named tracks
        with open(tpath) as f:
            doc = json.load(f)
        evs = doc["traceEvents"]
        ok, counts = _pairing_ok(evs)
        assert ok, counts
        assert any(e["ph"] == "M" and e["name"] == "thread_name"
                   for e in evs)
        assert any(e.get("name") == "fusion.write" for e in evs)

        # the report: decomposition + d2h<->write overlap + a critical
        # path, from the same directory the CLI points users at
        r = runner.invoke(cli, ["trace-report", tel],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert "d2h" in r.output and "write" in r.output
        assert "overlap d2h<->write:" in r.output
        assert "critical path [" in r.output
        events, meta = load_events(tel)
        rep = build_report(events, meta)
        assert rep["stages"]["fusion"]["d2h_s"] > 0
        assert rep["stages"]["fusion"]["write_s"] > 0
        assert rep["critical_path"] is not None

    def test_no_trace_flag_records_nothing(self, fused_project, tmp_path):
        # zero-overhead acceptance: same run WITHOUT --trace — span
        # aggregates fill as before, the flight recorder stays empty
        from bigstitcher_spark_tpu.cli.main import cli

        _, out = fused_project
        tel = str(tmp_path / "tel2")
        r = CliRunner().invoke(cli, [
            "affine-fusion", "-o", out, "--blockScale", "1,1,1",
            "--devices", "1", "--telemetry-dir", tel,
        ], catch_exceptions=False)
        assert r.exit_code == 0, r.output
        assert trace.stats()["recorded"] == 0
        assert not os.path.exists(
            os.path.join(tel, "trace-00000-of-00001.json"))
        with open(os.path.join(tel,
                               "manifest-00000-of-00001.json")) as f:
            man = json.load(f)
        assert "trace_file" not in man
        assert any(k.startswith("fusion.") for k in man["spans"])

"""The `bst serve` daemon: job queue scheduling, per-job config/telemetry
isolation, E2E parity with the one-shot CLI path, warm-cache amortization,
concurrency under shared byte windows, and mid-run cancellation.

Daemons run IN-PROCESS on a tmp-path Unix socket (no subprocesses, so the
jit caches the suite already warmed stay warm and the tests stay fast);
the detach/foreground plumbing is exercised by scripts/serve_smoke.sh and
the WORKFLOW doc test."""

import json
import os
import threading
import time

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.observe import events, metrics
from bigstitcher_spark_tpu.serve import client
from bigstitcher_spark_tpu.serve.daemon import Daemon
from bigstitcher_spark_tpu.serve.jobs import Job, JobQueue


@pytest.fixture()
def daemon(tmp_path):
    """In-process daemon on a tmp socket; always shut down (and stdout
    restored) even when the test body fails."""
    d = Daemon(str(tmp_path / "bst.sock"), slots=2,
               jobs_root=str(tmp_path / "jobs")).start()
    try:
        yield d
    finally:
        if not d.wait(timeout=0):
            d.shutdown(drain=False, wait=True)


def _mk_project(tmp_path, name="proj", **kw):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    spec = dict(n_tiles=(2, 2, 1), tile_size=(96, 96, 32), overlap=24,
                jitter=2.0, n_beads_per_tile=40, seed=7)
    spec.update(kw)
    return make_synthetic_project(str(tmp_path / name), **spec).xml_path


def _read_vol(path, dataset="0"):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore

    ds = ChunkStore.open(path).open_dataset(dataset)
    size = tuple(ds.shape[:3]) + (1,) * (len(ds.shape) - 3)
    return np.asarray(ds.read((0,) * len(ds.shape), size)).squeeze()


def _cli_ok(runner, args):
    r = runner.invoke(cli, args, catch_exceptions=False)
    assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"
    return r


# -- queue scheduling (pure, no daemon) -------------------------------------


class TestJobQueue:
    def _job(self, jid, **kw):
        return Job(id=jid, tool="config", args=[], **kw)

    def test_priority_strictly_first(self):
        q = JobQueue(slots=1)
        q.submit(self._job("a", priority=0))
        q.submit(self._job("b", priority=5))
        q.submit(self._job("c", priority=1))
        order = [q.take(0, timeout=1).id for _ in range(3)]
        assert order == ["b", "c", "a"]

    def test_fair_share_within_priority(self):
        q = JobQueue(slots=1)
        # alice has already consumed runtime; bob has not
        ja = self._job("a1", share="alice")
        q.submit(ja)
        taken = q.take(0, timeout=1)
        q.finish(taken, "done", exit_code=0)
        assert q.share_runtime()["alice"] >= 0.0
        q.submit(self._job("a2", share="alice"))
        q.submit(self._job("b1", share="bob"))
        # bob's accumulated runtime (0) < alice's -> bob first despite FIFO
        assert q.take(0, timeout=1).id == "b1"

    def test_lpt_plan_spreads_cost_over_slots(self):
        q = JobQueue(slots=2)
        for jid, cost in (("big", 10.0), ("m1", 4.0), ("m2", 3.0),
                          ("s1", 2.0)):
            q.submit(self._job(jid, cost=cost))
        plan = q.plan()
        assert sorted(len(b) for b in plan) == [1, 3]
        # LPT: the heaviest job sits alone, the rest pack the other slot
        loads = [sum({"big": 10, "m1": 4, "m2": 3, "s1": 2}[j] for j in b)
                 for b in plan]
        assert max(loads) - min(loads) <= 10.0

    def test_cancel_queued_is_terminal(self):
        q = JobQueue(slots=1)
        q.submit(self._job("a"))
        job = q.cancel("a")
        assert job.state == "cancelled" and q.depth() == 0
        assert q.take(0, timeout=0.1) is None

    def test_close_rejects_and_cancels_queued(self):
        q = JobQueue(slots=1)
        q.submit(self._job("a"))
        doomed = q.close()
        assert [j.id for j in doomed] == ["a"]
        with pytest.raises(RuntimeError):
            q.submit(self._job("b"))

    def test_after_waits_for_parent_then_releases(self):
        q = JobQueue(slots=1)
        q.submit(self._job("parent"))
        child = self._job("child", after=["parent"])
        q.submit(child)
        assert q.waiting_on("child") == {"parent"}
        assert q.depth() == 2
        taken = q.take(0, timeout=1)
        assert taken.id == "parent"
        # child must not be runnable while the parent is still open
        assert q.take(0, timeout=0.1) is None
        q.finish(taken, "done", exit_code=0)
        assert q.waiting_on("child") is None
        assert q.take(0, timeout=1).id == "child"

    def test_after_parent_failure_cancels_cascade(self):
        q = JobQueue(slots=1)
        q.submit(self._job("parent"))
        q.submit(self._job("child", after=["parent"]))
        q.submit(self._job("grandchild", after=["child"]))
        taken = q.take(0, timeout=1)
        cascaded = q.finish(taken, "failed", exit_code=1)
        assert {j.id for j in cascaded} == {"child", "grandchild"}
        states = {j.id: j.state for j in q.jobs()}
        assert states["child"] == "cancelled"
        assert states["grandchild"] == "cancelled"
        assert q.depth() == 0

    def test_after_terminal_parent_at_submit(self):
        q = JobQueue(slots=1)
        q.submit(self._job("ok"))
        q.finish(q.take(0, timeout=1), "done", exit_code=0)
        # DONE parent: runnable immediately
        q.submit(self._job("a", after=["ok"]))
        assert q.take(0, timeout=1).id == "a"
        q.finish(q.get("a"), "failed", exit_code=1)
        # FAILED parent: cancelled on the spot
        doomed = self._job("b", after=["a"])
        q.submit(doomed)
        assert doomed.state == "cancelled"
        with pytest.raises(KeyError, match="unknown job"):
            q.submit(self._job("c", after=["no-such-job"]))

    def test_cancel_waiting_job_and_close_cancels_waiting(self):
        q = JobQueue(slots=2)
        q.submit(self._job("p1"))
        q.submit(self._job("w1", after=["p1"]))
        assert q.cancel("w1").state == "cancelled"
        q.submit(self._job("w2", after=["p1"]))
        doomed = q.close()
        assert {j.id for j in doomed} == {"p1", "w2"}

    def test_finished_history_is_bounded(self):
        from bigstitcher_spark_tpu.serve.jobs import MAX_FINISHED_JOBS

        q = JobQueue(slots=1)
        for i in range(MAX_FINISHED_JOBS + 50):
            q.submit(self._job(f"j{i}"))
            q.finish(q.take(0, timeout=1), "done", exit_code=0)
        ids = {j.id for j in q.jobs()}
        assert len(ids) == MAX_FINISHED_JOBS
        assert "j0" not in ids                      # oldest aged out
        assert f"j{MAX_FINISHED_JOBS + 49}" in ids  # newest kept


# -- per-job config isolation (the override layer itself) -------------------


class TestConfigOverrides:
    def test_undeclared_override_rejected(self):
        with pytest.raises(KeyError):
            config.validate_overrides({"BST_NOT_A_KNOB": "1"})

    def test_override_masks_env_and_restores(self, monkeypatch):
        monkeypatch.setenv("BST_WRITE_THREADS", "5")
        assert config.get_int("BST_WRITE_THREADS") == 5
        with config.overrides({"BST_WRITE_THREADS": 2}):
            assert config.get_int("BST_WRITE_THREADS") == 2
            assert config.source("BST_WRITE_THREADS") == "override"
            with config.overrides({"BST_WRITE_THREADS": None}):
                # None masks back to the declared default, not the env
                assert config.get_int("BST_WRITE_THREADS") == 8
                assert config.source("BST_WRITE_THREADS") == "default"
        assert config.get_int("BST_WRITE_THREADS") == 5
        assert os.environ["BST_WRITE_THREADS"] == "5"

    def test_interleaved_threads_see_only_their_own(self):
        """Two 'jobs' with conflicting overrides, running interleaved on
        two threads, each observe only their own values at every step."""
        barrier = threading.Barrier(2, timeout=10)
        seen: dict[str, list[int]] = {"a": [], "b": []}
        errors: list = []

        def job(label, value):
            try:
                with config.overrides({"BST_WRITE_THREADS": value}):
                    for _ in range(4):
                        barrier.wait()       # force interleaving
                        seen[label].append(
                            config.get_int("BST_WRITE_THREADS"))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ta = threading.Thread(target=job, args=("a", 3))
        tb = threading.Thread(target=job, args=("b", 7))
        ta.start(); tb.start(); ta.join(); tb.join()
        assert not errors
        assert seen["a"] == [3, 3, 3, 3]
        assert seen["b"] == [7, 7, 7, 7]
        assert "BST_WRITE_THREADS" not in os.environ

    def test_worker_threads_inherit_overrides(self):
        from bigstitcher_spark_tpu.utils.threads import CtxThreadPool

        with config.overrides({"BST_WRITE_THREADS": 11}):
            with CtxThreadPool(max_workers=2) as pool:
                vals = list(pool.map(
                    lambda _: config.get_int("BST_WRITE_THREADS"),
                    range(4)))
        assert vals == [11, 11, 11, 11]


# -- per-job event logs -----------------------------------------------------


class TestPerJobEventLogs:
    def test_two_jobs_write_separate_files(self, tmp_path):
        events.open_job("jx", str(tmp_path / "jx"))
        events.open_job("jy", str(tmp_path / "jy"))
        barrier = threading.Barrier(2, timeout=10)

        def run(label):
            tok = events.activate_job(label)
            try:
                for i in range(3):
                    barrier.wait()
                    events.emit("log", message=f"{label}-{i}")
            finally:
                events.deactivate_job(tok)

        ta = threading.Thread(target=run, args=("jx",))
        tb = threading.Thread(target=run, args=("jy",))
        ta.start(); tb.start(); ta.join(); tb.join()
        px = events.close_job("jx")
        py = events.close_job("jy")
        assert os.path.basename(px).startswith("events-job-jx-")
        assert os.path.basename(py).startswith("events-job-jy-")
        msgs_x = [r["message"] for r in events.iter_events(px)]
        msgs_y = [r["message"] for r in events.iter_events(py)]
        assert msgs_x == ["jx-0", "jx-1", "jx-2"]
        assert msgs_y == ["jy-0", "jy-1", "jy-2"]

    def test_outside_job_scope_falls_back_to_default(self, tmp_path):
        events.configure(str(tmp_path / "default"))
        events.open_job("jz", str(tmp_path / "jz"))
        events.emit("log", message="default-scope")
        tok = events.activate_job("jz")
        events.emit("log", message="job-scope")
        events.deactivate_job(tok)
        pz = events.close_job("jz")
        pd = events.close()
        assert [r["message"] for r in events.iter_events(pz)] == ["job-scope"]
        assert [r["message"] for r in events.iter_events(pd)] == \
            ["default-scope"]


# -- daemon E2E -------------------------------------------------------------


class TestDaemonE2E:
    def test_three_sequential_jobs_match_one_shot_cli(self, tmp_path,
                                                      daemon):
        """Acceptance E2E: fusion + downsample + detection served by one
        resident daemon are bit-identical to the one-shot CLI path, and
        the second same-shape fusion job hits the warm compiled-fn
        bucket (no recompile)."""
        sock = daemon.socket_path
        xml = _mk_project(tmp_path, "proj")
        proj = os.path.dirname(xml)
        runner = CliRunner()

        def served(tool, args):
            res = client.submit(sock, tool, args)
            assert res["state"] == "done" and res["exit_code"] == 0, res
            return res

        cargs = ["-s", "ZARR", "-d", "UINT16", "--minIntensity", "0",
                 "--maxIntensity", "65535"]
        served("create-fusion-container",
               ["-x", xml, "-o", f"{proj}/fused.ome.zarr", *cargs])
        r1 = served("affine-fusion", ["-o", f"{proj}/fused.ome.zarr"])
        served("downsample", ["-i", f"{proj}/dataset.n5",
                              "-di", "setup0/timepoint0/s0",
                              "-ds", "2,2,1"])
        served("detect-interestpoints",
               ["-x", xml, "-l", "beads", "-s", "1.8", "-t", "0.008",
                "-dsxy", "1", "-dsz", "1"])
        # second same-shape fusion: the resident process must reuse the
        # compiled-fn bucket (the amortized-compile win of `bst serve`)
        r2 = served("affine-fusion", ["-o", f"{proj}/fused.ome.zarr"])
        assert r2["warm_compile_hits"] > 0
        assert r1["warm_compile_hits"] == 0

        # one-shot CLI path on an identical project (same seed)
        xml_d = _mk_project(tmp_path, "direct")
        proj_d = os.path.dirname(xml_d)
        _cli_ok(runner, ["create-fusion-container", "-x", xml_d,
                         "-o", f"{proj_d}/fused.ome.zarr", *cargs])
        _cli_ok(runner, ["affine-fusion", "-o", f"{proj_d}/fused.ome.zarr"])
        _cli_ok(runner, ["downsample", "-i", f"{proj_d}/dataset.n5",
                         "-di", "setup0/timepoint0/s0", "-ds", "2,2,1"])
        _cli_ok(runner, ["detect-interestpoints", "-x", xml_d,
                         "-l", "beads", "-s", "1.8", "-t", "0.008",
                         "-dsxy", "1", "-dsz", "1"])

        assert np.array_equal(_read_vol(f"{proj}/fused.ome.zarr"),
                              _read_vol(f"{proj_d}/fused.ome.zarr"))
        assert np.array_equal(
            _read_vol(f"{proj}/dataset.n5", "setup0/timepoint0/s1"),
            _read_vol(f"{proj_d}/dataset.n5", "setup0/timepoint0/s1"))
        from bigstitcher_spark_tpu.io.interestpoints import \
            InterestPointStore
        from bigstitcher_spark_tpu.io.spimdata import SpimData

        sd, sd_d = SpimData.load(xml), SpimData.load(xml_d)
        ips = InterestPointStore.for_project(sd)
        ips_d = InterestPointStore.for_project(sd_d)
        for v in sd.view_ids():
            pts, _ = ips.load_points(v, "beads")
            pts_d, _ = ips_d.load_points(v, "beads")
            assert len(pts) and np.array_equal(pts, pts_d)

        # per-job observability: each job left its own manifest + log
        for res in (r1, r2):
            d = res["telemetry_dir"]
            files = os.listdir(d)
            assert any(f.startswith("manifest-") for f in files), files
            assert any(f.startswith("events-job-") for f in files), files
            man = json.load(open(os.path.join(
                d, next(f for f in files if f.startswith("manifest-")))))
            assert man["tool"] == "affine-fusion"
            assert man["status"] == "ok"
            assert any(s.get("stage") == "affine-fusion"
                       for s in man["stages"])

    def test_output_log_and_override_isolation_through_daemon(
            self, tmp_path, daemon):
        """Two `bst config` jobs with conflicting overrides, back-to-back
        and interleaved: each job's captured output shows only its own
        values, and the daemon's environment never changes."""
        sock = daemon.socket_path

        def seen_value(res):
            out = open(os.path.join(res["telemetry_dir"],
                                    "output.log")).read()
            rows = {r["name"]: r for r in json.loads(out)}
            return (rows["BST_WRITE_THREADS"]["value"],
                    rows["BST_WRITE_THREADS"]["source"])

        r3 = client.submit(sock, "config", ["--json"],
                           overrides={"BST_WRITE_THREADS": "3"})
        r7 = client.submit(sock, "config", ["--json"],
                           overrides={"BST_WRITE_THREADS": "7"})
        assert seen_value(r3) == (3, "override")
        assert seen_value(r7) == (7, "override")
        # interleaved: both in flight on the two slots at once
        results = {}

        def go(key, val):
            results[key] = client.submit(
                sock, "config", ["--json"],
                overrides={"BST_WRITE_THREADS": val})

        ta = threading.Thread(target=go, args=("a", "3"))
        tb = threading.Thread(target=go, args=("b", "7"))
        ta.start(); tb.start(); ta.join(); tb.join()
        assert seen_value(results["a"]) == (3, "override")
        assert seen_value(results["b"]) == (7, "override")
        assert "BST_WRITE_THREADS" not in os.environ

    def test_bad_submissions_rejected(self, daemon):
        sock = daemon.socket_path
        with pytest.raises(RuntimeError, match="unknown or unservable"):
            client.submit(sock, "no-such-tool", [])
        with pytest.raises(RuntimeError, match="unknown or unservable"):
            client.submit(sock, "submit", ["config"])   # no recursion
        with pytest.raises(RuntimeError, match="undeclared knob"):
            client.submit(sock, "config", [],
                          overrides={"BST_TYPO": "1"})
        with pytest.raises(RuntimeError, match="daemon-owned"):
            client.submit(sock, "config", ["--telemetry-dir", "/tmp/x"])
        with pytest.raises(RuntimeError, match="daemon-owned"):
            # the fused --flag=value spelling must not slip past the guard
            client.submit(sock, "config", ["--telemetry-dir=/tmp/x"])

    def test_failed_job_isolated_daemon_survives(self, tmp_path, daemon):
        sock = daemon.socket_path
        bad = client.submit(sock, "affine-fusion",
                            ["-o", str(tmp_path / "nope.zarr")])
        assert bad["state"] == "failed" and bad["exit_code"] != 0
        ok = client.submit(sock, "config", [])
        assert ok["state"] == "done" and ok["exit_code"] == 0
        listing = client.list_jobs(sock)
        states = {j["id"]: j["state"] for j in listing["jobs"]}
        assert set(states.values()) == {"failed", "done"}

    def test_submit_after_chains_and_cancels_on_failure(self, tmp_path,
                                                        daemon):
        """The `bst submit --after` dependency edges: a child waits for
        its parent's success and starts only afterwards; a child of a
        failing parent is cancelled without ever running."""
        sock = daemon.socket_path
        acc = client.submit(sock, "config", [], follow=False)
        child = client.submit(sock, "config", [], after=[acc["job"]])
        assert child["state"] == "done" and child["exit_code"] == 0
        # parent that fails -> dependent cancelled, never runs
        bad = client.submit(sock, "affine-fusion",
                            ["-o", str(tmp_path / "nope.zarr")],
                            follow=False)
        doomed = client.submit(sock, "config", [], after=[bad["job"]])
        assert doomed["state"] == "cancelled"
        assert doomed.get("exit_code") is None
        states = {j["id"]: j for j in client.list_jobs(sock)["jobs"]}
        assert states[bad["job"]]["state"] == "failed"
        # unknown parent is a protocol error
        with pytest.raises(RuntimeError, match="unknown job"):
            client.submit(sock, "config", [], after=["zzz"])

    def test_pipeline_through_daemon(self, tmp_path, daemon):
        """`bst submit --pipeline`: a whole spec runs as one daemon job
        (stages chain in-process on the daemon's warm caches)."""
        sock = daemon.socket_path
        spec = {"name": "served", "stages": [
            {"id": "a", "tool": "config", "args": []},
            {"id": "b", "tool": "config", "args": [], "after": ["a"]}]}
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(spec))
        res = client.submit(sock, "pipeline", ["run", str(spec_path)])
        assert res["state"] == "done" and res["exit_code"] == 0, res
        out = open(os.path.join(res["telemetry_dir"],
                                "output.log")).read()
        assert "pipeline served:" in out
        # the CLI spelling: bst submit --pipeline <spec>
        runner = CliRunner()
        r = runner.invoke(cli, ["submit", "--socket", sock, "--quiet",
                                "--pipeline", str(spec_path)],
                          catch_exceptions=False)
        assert r.exit_code == 0, r.output

    def test_jobs_and_cancel_cli_commands(self, tmp_path, daemon):
        runner = CliRunner()
        sock = daemon.socket_path
        client.submit(sock, "config", [])
        r = _cli_ok(runner, ["jobs", "--socket", sock, "--json"])
        payload = json.loads(r.output)
        assert payload["daemon"]["slots"] == 2
        assert payload["jobs"][0]["tool"] == "config"
        assert "chunk_cache" in payload["daemon"]
        r = _cli_ok(runner, ["jobs", "--socket", sock])
        assert "compiled-fn warm" in r.output
        r = runner.invoke(cli, ["cancel", "--socket", sock, "zzz"])
        assert r.exit_code != 0     # unknown job id -> ClickException


class TestDaemonConcurrency:
    def test_concurrent_jobs_complete_within_byte_budget(self, tmp_path,
                                                         daemon):
        """Acceptance: two jobs submitted together both complete; the
        shared in-flight high-water gauge never exceeds the single-job
        budget because the daemon splits the derived windows per slot."""
        from bigstitcher_spark_tpu.utils.devicemem import \
            dispatch_budget_bytes

        sock = daemon.socket_path
        xml = _mk_project(tmp_path, "proj")
        proj = os.path.dirname(xml)
        cargs = ["-s", "ZARR", "-d", "UINT16", "--minIntensity", "0",
                 "--maxIntensity", "65535"]
        for out in ("outA", "outB"):
            res = client.submit(sock, "create-fusion-container",
                                ["-x", xml, "-o", f"{proj}/{out}.zarr",
                                 "--blockSize", "32,32,32", *cargs])
            assert res["exit_code"] == 0
        base = dispatch_budget_bytes()
        hw = metrics.gauge("bst_inflight_bytes_highwater")
        hw.set(0)   # fresh high-water for this window-sharing assertion
        results = {}

        # small compute blocks => every batch fits well inside its job's
        # split window, so the windows GATE (the ledger's must-dispatch
        # head-batch rule can only exceed a budget when one batch alone
        # is bigger than the whole budget)
        def go(out):
            results[out] = client.submit(
                sock, "affine-fusion",
                ["-o", f"{proj}/{out}.zarr", "--blockScale", "1,1,1"])

        ta = threading.Thread(target=go, args=("outA",))
        tb = threading.Thread(target=go, args=("outB",))
        ta.start(); tb.start(); ta.join(); tb.join()
        assert results["outA"]["state"] == "done"
        assert results["outB"]["state"] == "done"
        assert np.array_equal(_read_vol(f"{proj}/outA.zarr"),
                              _read_vol(f"{proj}/outB.zarr"))
        assert hw.value <= base, (hw.value, base)

    def test_cancel_mid_run_leaves_other_job_intact(self, tmp_path,
                                                    daemon):
        """Acceptance: of two concurrent fusions, cancelling one mid-run
        (at its first stage heartbeat) leaves the other's output
        bit-identical to the direct CLI run."""
        sock = daemon.socket_path
        # the doomed job gets a LARGE grid of tiny blocks (many batches =
        # many cancel safe-points); the surviving job runs the normal shape
        xml = _mk_project(tmp_path, "proj", tile_size=(128, 128, 32))
        proj = os.path.dirname(xml)
        cargs = ["-s", "ZARR", "-d", "UINT16", "--minIntensity", "0",
                 "--maxIntensity", "65535"]
        for out, bs in (("keep", "64,64,32"), ("doom", "16,16,16")):
            res = client.submit(sock, "create-fusion-container",
                                ["-x", xml, "-o", f"{proj}/{out}.zarr",
                                 "--blockSize", bs, *cargs])
            assert res["exit_code"] == 0

        cancelled_at = []

        def on_event(rec):
            # first sign of the doomed fusion actually running -> cancel
            if (rec.get("type") in ("stage.start", "stage.progress")
                    and not cancelled_at):
                cancelled_at.append(rec)
                client.cancel(sock, rec["job"])

        results = {}

        def go_doom():
            results["doom"] = client.submit(
                sock, "affine-fusion",
                ["-o", f"{proj}/doom.zarr", "--blockScale", "1,1,1"],
                on_event=on_event)

        def go_keep():
            results["keep"] = client.submit(
                sock, "affine-fusion", ["-o", f"{proj}/keep.zarr"])

        td = threading.Thread(target=go_doom)
        tk = threading.Thread(target=go_keep)
        td.start(); tk.start(); td.join(); tk.join()
        assert results["doom"]["state"] == "cancelled", results["doom"]
        assert results["keep"]["state"] == "done", results["keep"]
        assert cancelled_at, "cancel never fired mid-run"

        runner = CliRunner()
        xml_d = _mk_project(tmp_path, "direct", tile_size=(128, 128, 32))
        proj_d = os.path.dirname(xml_d)
        _cli_ok(runner, ["create-fusion-container", "-x", xml_d,
                         "-o", f"{proj_d}/keep.zarr",
                         "--blockSize", "64,64,32", *cargs])
        _cli_ok(runner, ["affine-fusion", "-o", f"{proj_d}/keep.zarr"])
        assert np.array_equal(_read_vol(f"{proj}/keep.zarr"),
                              _read_vol(f"{proj_d}/keep.zarr"))

    def test_shutdown_drain_cancels_queued_finishes_running(self, tmp_path,
                                                            daemon):
        sock = daemon.socket_path
        # saturate both slots, then queue one more and drain
        accepted = [client.submit(sock, "config", [], follow=False)
                    for _ in range(3)]
        client.shutdown(sock, drain=True)
        assert daemon.wait(timeout=60)
        states = {j.id: j.state for j in daemon.queue.jobs()}
        assert len(accepted) == 3
        assert set(states.values()) <= {"done", "cancelled"}
        # socket is gone: clients see a clear connection error
        with pytest.raises(OSError):
            client.ping(sock, timeout=1.0)


class TestWarmth:
    def test_compile_bucket_counters_move(self):
        from bigstitcher_spark_tpu.parallel.mesh import record_compile_bucket

        warm = metrics.counter("bst_compiled_fn_warm_hits_total")
        cold = metrics.counter("bst_compiled_fn_cold_builds_total")
        w0, c0 = warm.value, cold.value
        key = ("test-bucket", time.time())
        assert record_compile_bucket(key) is False
        assert record_compile_bucket(key) is True
        assert cold.value == c0 + 1 and warm.value == w0 + 1

    def test_bucket_mirror_tracks_lru_eviction(self):
        """The warm counter must not claim warmth for signatures the
        bounded factory lru_cache has already evicted (and will
        recompile)."""
        from bigstitcher_spark_tpu.parallel.mesh import record_compile_bucket

        stamp = time.time()
        first = ("sharded", "evict-test", stamp, 0)
        assert record_compile_bucket(first) is False
        for i in range(1, 70):   # > the sharded cache's 64-entry capacity
            record_compile_bucket(("sharded", "evict-test", stamp, i))
        assert record_compile_bucket(first) is False   # evicted: cold again

    def test_chunk_cache_stats_surface(self):
        from bigstitcher_spark_tpu.io.chunkcache import get_cache

        st = get_cache().stats()
        assert {"entries", "bytes", "hits", "misses"} <= set(st)

"""The installer's wrapper surface must track the CLI registry — the
reference's `install` is the documented entry point (install:103-139),
so a tool registered in cli/main.py but missing from ./install would be
invisible to users following the README."""

import os
import re
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def installed_bin(tmp_path_factory):
    """Run ./install once for the whole module (it rebuilds the native
    codec if stale, so sharing the run matters on clean checkouts)."""
    bin_dir = tmp_path_factory.mktemp("install") / "bin"
    out = subprocess.run([os.path.join(REPO, "install"), str(bin_dir)],
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    return bin_dir


def test_installer_covers_every_cli_tool(installed_bin):
    from bigstitcher_spark_tpu.cli.main import cli

    wrappers = set(os.listdir(installed_bin))
    # generic names install bst- prefixed (a bare `env`/`lint`/`config`
    # on PATH would shadow /usr/bin/env or unrelated same-named tools)
    renamed = {"env": "bst-env", "lint": "bst-lint", "config": "bst-config",
               "trace-report": "bst-trace-report",
               "serve": "bst-serve", "submit": "bst-submit",
               "jobs": "bst-jobs", "cancel": "bst-cancel",
               "pipeline": "bst-pipeline",
               "top": "bst-top", "trace-dump": "bst-trace-dump",
               "history": "bst-history", "perf-diff": "bst-perf-diff",
               "tune": "bst-tune"}
    expected = {renamed.get(t, t) for t in set(cli.commands)}
    missing = expected - wrappers
    assert not missing, f"installer missing wrappers for: {sorted(missing)}"


def test_wrapper_is_executable_and_targets_its_tool(installed_bin):
    w = installed_bin / "transform-points"
    assert os.access(w, os.X_OK)
    assert re.search(r"cli\.main transform-points", w.read_text())


def test_trace_report_wrapper(installed_bin):
    w = installed_bin / "bst-trace-report"
    assert os.access(w, os.X_OK)
    assert re.search(r"cli\.main trace-report", w.read_text())


def test_serve_wrappers(installed_bin):
    for name, tool in (("bst-serve", "serve"), ("bst-submit", "submit"),
                       ("bst-jobs", "jobs"), ("bst-cancel", "cancel")):
        w = installed_bin / name
        assert os.access(w, os.X_OK), name
        assert re.search(rf"cli\.main {tool}", w.read_text()), name


def test_pipeline_wrapper(installed_bin):
    w = installed_bin / "bst-pipeline"
    assert os.access(w, os.X_OK)
    assert re.search(r"cli\.main pipeline", w.read_text())


def test_live_observe_wrappers(installed_bin):
    for name, tool in (("bst-top", "top"),
                       ("bst-trace-dump", "trace-dump"),
                       ("bst-history", "history"),
                       ("bst-perf-diff", "perf-diff")):
        w = installed_bin / name
        assert os.access(w, os.X_OK), name
        assert re.search(rf"cli\.main {tool}", w.read_text()), name


def test_tune_wrapper(installed_bin):
    w = installed_bin / "bst-tune"
    assert os.access(w, os.X_OK)
    assert re.search(r"cli\.main tune", w.read_text())

"""Closing the telemetry loop: `bst tune` — the history-driven advisor,
the knob autotuner, the per-shape profile store, and the serve daemon's
`submit --profile` application path.

Advisor tests plant exactly ONE known bottleneck per record and assert
exactly that rule fires (and that a healthy record fires none) — the
rules' significance floors are load-bearing, not decoration. Autotuner
tests use synthetic workloads with a KNOWN optimal knob value, so
convergence is a correctness assertion, not a benchmark. The daemon test
asserts the acceptance contract end to end: a profile applied via
``config.overrides()`` changes only performance knobs, so job outputs
stay byte-identical."""

import json
import math
import os
import time

import numpy as np
import pytest
from click.testing import CliRunner

from bigstitcher_spark_tpu import config, tune
from bigstitcher_spark_tpu.cli.main import cli
from bigstitcher_spark_tpu.observe import history
from bigstitcher_spark_tpu.tune import profiles


def _cli_ok(runner, args):
    r = runner.invoke(cli, args, catch_exceptions=False)
    assert r.exit_code == 0, f"bst {' '.join(args)}\n{r.output}"
    return r


def _json_tail(output: str):
    """Parse the JSON document at the end of mixed CLI output (warnings
    ride on stderr but CliRunner merges streams)."""
    start = min(i for i in (output.find("{"), output.find("["))
                if i >= 0)
    return json.loads(output[start:])


# a run with NO recognizable bottleneck: high cache ratios, no
# evictions, warm compiles, no stalls/drops/saturation
def _healthy_record(**updates) -> dict:
    rec = {
        "id": "test-rec", "tool": "affine-fusion", "seconds": 10.0,
        "status": "ok", "params": {},
        "metrics": {
            "bst_chunk_cache_hits_total": 90.0,
            "bst_chunk_cache_misses_total": 10.0,
            "bst_chunk_cache_evictions_total": 0.0,
            "bst_tile_cache_hits_total": 90.0,
            "bst_tile_cache_misses_total": 10.0,
            "bst_tile_cache_evict_bytes_total": 0.0,
            "bst_compiled_fn_warm_hits_total": 50.0,
            "bst_compiled_fn_cold_builds_total": 2.0,
        },
    }
    rec["metrics"].update(updates.pop("metrics", {}))
    rec.update(updates)
    return rec


class TestAdvisorRules:
    def test_healthy_record_fires_nothing(self):
        assert tune.advise_record(_healthy_record()) == []

    def test_chunk_cache_thrash(self):
        rec = _healthy_record(metrics={
            "bst_chunk_cache_hits_total": 10.0,
            "bst_chunk_cache_misses_total": 90.0,
            "bst_chunk_cache_evictions_total": 40.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["chunk_cache_thrash"]
        d = diags[0]
        assert d.knob == "BST_CHUNK_CACHE_BYTES"
        assert int(d.suggested_value) > config.get_bytes(
            "BST_CHUNK_CACHE_BYTES")
        assert d.evidence["evictions"] == 40

    def test_tile_cache_thrash(self):
        rec = _healthy_record(metrics={
            "bst_tile_cache_hits_total": 5.0,
            "bst_tile_cache_misses_total": 95.0,
            "bst_tile_cache_evict_bytes_total": 1e9})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["tile_cache_thrash"]
        assert diags[0].knob == "BST_TILE_CACHE_BYTES"

    def test_labeled_metric_variants_sum(self):
        # the store flattens counters to name{label=...} keys; rules must
        # sum the variants, not miss them
        rec = _healthy_record(metrics={
            "bst_chunk_cache_hits_total": 0.0,
            "bst_chunk_cache_hits_total{store=a}": 5.0,
            "bst_chunk_cache_hits_total{store=b}": 5.0,
            "bst_chunk_cache_misses_total": 90.0,
            "bst_chunk_cache_evictions_total{store=a}": 12.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["chunk_cache_thrash"]
        assert diags[0].evidence["hits"] == 10

    def test_cold_compile_buckets(self):
        rec = _healthy_record(metrics={
            "bst_compiled_fn_warm_hits_total": 1.0,
            "bst_compiled_fn_cold_builds_total": 8.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["cold_compile_buckets"]
        # no single knob fixes cold starts — the advice is the daemon
        assert diags[0].knob is None
        assert "serve" in diags[0].detail

    def test_few_cold_builds_is_not_advice(self):
        rec = _healthy_record(metrics={
            "bst_compiled_fn_warm_hits_total": 0.0,
            "bst_compiled_fn_cold_builds_total": 3.0})
        assert tune.advise_record(rec) == []

    def test_inflight_saturated_uses_recorded_budget(self):
        rec = _healthy_record(
            params={"overrides": {"BST_INFLIGHT_BYTES": "1000000"}},
            metrics={"bst_inflight_bytes_highwater": 950000.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["inflight_budget_saturated"]
        d = diags[0]
        assert d.knob == "BST_INFLIGHT_BYTES"
        assert d.evidence["budget_source"] == "recorded-override"
        assert int(d.suggested_value) > 1000000

    def test_inflight_below_saturation_is_quiet(self):
        rec = _healthy_record(
            params={"overrides": {"BST_INFLIGHT_BYTES": "1000000"}},
            metrics={"bst_inflight_bytes_highwater": 500000.0})
        assert tune.advise_record(rec) == []

    def test_dag_backpressure(self):
        rec = _healthy_record(metrics={
            "bst_dag_producer_stall_seconds_total": 2.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["dag_producer_backpressure"]
        assert diags[0].knob == "BST_DAG_EXCHANGE_BYTES"

    def test_small_stall_is_quiet(self):
        rec = _healthy_record(metrics={
            "bst_dag_producer_stall_seconds_total": 0.3})
        assert tune.advise_record(rec) == []

    def test_multihost_pair_imbalance(self):
        rec = _healthy_record(metrics={
            'bst_pair_proc_busy_ms_total{process="0",stage="match"}':
                4000.0,
            'bst_pair_proc_busy_ms_total{process="1",stage="match"}':
                1000.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["multihost_pair_imbalance"]
        d = diags[0]
        # no single knob rebalances skewed work — the advice is the
        # cost-weighted split
        assert d.knob is None
        assert "cost-weighted" in d.detail
        assert d.evidence["busy_ms_by_process"] == {"0": 4000.0,
                                                    "1": 1000.0}
        assert d.evidence["spread"] == 0.75

    def test_balanced_pair_split_is_quiet(self):
        rec = _healthy_record(metrics={
            'bst_pair_proc_busy_ms_total{process="0",stage="match"}':
                2000.0,
            'bst_pair_proc_busy_ms_total{process="1",stage="match"}':
                1800.0})
        assert tune.advise_record(rec) == []

    def test_single_process_pair_busy_is_quiet(self):
        # one rank's busy time alone says nothing about a split
        rec = _healthy_record(metrics={
            'bst_pair_proc_busy_ms_total{process="0",stage="match"}':
                9000.0})
        assert tune.advise_record(rec) == []

    def test_xhost_backpressure(self):
        rec = _healthy_record(metrics={
            "bst_dag_xhost_stall_seconds_total": 2.5,
            "bst_dag_xhost_bytes_total": 1 << 20})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["xhost_exchange_backpressure"]
        d = diags[0]
        assert d.knob == "BST_DAG_EXCHANGE_BYTES"
        assert d.evidence["xhost_bytes"] == 1 << 20

    def test_small_xhost_stall_is_quiet(self):
        rec = _healthy_record(metrics={
            "bst_dag_xhost_stall_seconds_total": 0.2,
            "bst_dag_xhost_bytes_total": 1 << 20})
        assert tune.advise_record(rec) == []

    def test_relay_drops(self):
        rec = _healthy_record(metrics={
            "bst_relay_dropped_total": 5.0,
            "bst_relay_sent_total": 100.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["relay_drops"]
        assert diags[0].knob == "BST_RELAY_QUEUE"

    def test_remote_read_stall_prefetcher_idle(self):
        rec = _healthy_record(metrics={
            "bst_io_remote_read_bytes_total": float(512 << 20),
            "bst_io_read_bytes_total": float(600 << 20)})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["remote_read_stall"]
        d = diags[0]
        assert d.knob == "BST_PREFETCH_BYTES"
        assert d.evidence["remote_read_bytes"] == 512 << 20
        assert int(d.suggested_value) > 0

    def test_remote_read_stall_miss_heavy(self):
        rec = _healthy_record(metrics={
            "bst_io_remote_read_bytes_total": float(512 << 20),
            "bst_io_read_bytes_total": float(600 << 20),
            "bst_io_prefetch_bytes_total": float(256 << 20),
            "bst_io_prefetch_hit_total": 20.0,
            "bst_io_prefetch_miss_total": 80.0})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["remote_read_stall"]
        d = diags[0]
        assert d.knob == "BST_PREFETCH_BYTES"
        assert d.evidence["hit_ratio"] == 0.2
        assert int(d.suggested_value) > int(
            config.get_bytes("BST_PREFETCH_BYTES"))

    def test_remote_read_stall_quiet_when_local_dominated(self):
        rec = _healthy_record(metrics={
            "bst_io_remote_read_bytes_total": float(100 << 20),
            "bst_io_read_bytes_total": float(1 << 30)})
        assert tune.advise_record(rec) == []

    def test_remote_read_stall_quiet_when_prefetch_hits(self):
        rec = _healthy_record(metrics={
            "bst_io_remote_read_bytes_total": float(512 << 20),
            "bst_io_read_bytes_total": float(600 << 20),
            "bst_io_prefetch_bytes_total": float(512 << 20),
            "bst_io_prefetch_hit_total": 90.0,
            "bst_io_prefetch_miss_total": 10.0})
        assert tune.advise_record(rec) == []

    def test_disk_tier_thrash(self):
        rec = _healthy_record(metrics={
            "bst_io_disktier_spill_bytes_total": float(1 << 30),
            "bst_io_disktier_hit_bytes_total": float(100 << 20),
            "bst_io_disktier_evict_bytes_total": float(900 << 20)})
        diags = tune.advise_record(rec)
        assert [d.rule for d in diags] == ["disk_tier_thrash"]
        d = diags[0]
        assert d.knob == "BST_DISK_TIER_BYTES"
        assert d.evidence["spill_bytes"] == 1 << 30
        assert int(d.suggested_value) >= int(
            config.KNOBS["BST_DISK_TIER_BYTES"].tunable.lo)

    def test_disk_tier_serving_back_is_quiet(self):
        rec = _healthy_record(metrics={
            "bst_io_disktier_spill_bytes_total": float(200 << 20),
            "bst_io_disktier_hit_bytes_total": float(150 << 20)})
        assert tune.advise_record(rec) == []

    def test_small_disk_tier_spill_is_quiet(self):
        rec = _healthy_record(metrics={
            "bst_io_disktier_spill_bytes_total": float(10 << 20),
            "bst_io_disktier_hit_bytes_total": 0.0})
        assert tune.advise_record(rec) == []

    def test_low_overlap_needs_the_trace(self):
        trace_rep = {"stages": {"fusion": {
            "d2h_s": 2.0, "write_s": 3.0,
            "overlap": {"d2h_write": {"pct_of_d2h": 10.0}}}}}
        diags = tune.advise_record(_healthy_record(), trace_rep)
        assert [d.rule for d in diags] == ["low_d2h_write_overlap"]
        d = diags[0]
        assert d.knob == "BST_WRITE_THREADS"
        assert d.evidence["stage"] == "fusion"
        # without the trace decomposition the rule cannot fire
        assert tune.advise_record(_healthy_record()) == []

    def test_good_overlap_is_quiet(self):
        trace_rep = {"stages": {"fusion": {
            "d2h_s": 2.0, "write_s": 3.0,
            "overlap": {"d2h_write": {"pct_of_d2h": 85.0}}}}}
        assert tune.advise_record(_healthy_record(), trace_rep) == []

    def test_multiple_rules_sorted_by_confidence(self):
        rec = _healthy_record(metrics={
            "bst_chunk_cache_hits_total": 1.0,
            "bst_chunk_cache_misses_total": 99.0,
            "bst_chunk_cache_evictions_total": 50.0,
            "bst_relay_dropped_total": 1.0,
            "bst_relay_sent_total": 1000.0})
        diags = tune.advise_record(rec)
        assert {d.rule for d in diags} == {"chunk_cache_thrash",
                                           "relay_drops"}
        assert [d.confidence for d in diags] == sorted(
            (d.confidence for d in diags), reverse=True)

    def test_suggested_value_clamps_to_tunable_hi(self):
        hi = config.KNOBS["BST_CHUNK_CACHE_BYTES"].tunable.hi
        rec = _healthy_record(metrics={
            "bst_chunk_cache_hits_total": 10.0,
            "bst_chunk_cache_misses_total": 90.0,
            "bst_chunk_cache_evictions_total": 40.0})
        with config.overrides({"BST_CHUNK_CACHE_BYTES": str(int(hi))}):
            diags = tune.advise_record(rec)
        assert int(diags[0].suggested_value) == int(hi)


class TestAdviseCli:
    def _import_record(self, tmp_path, hist, manifest):
        mp = str(tmp_path / "manifest-planted.json")
        with open(mp, "w") as f:
            json.dump(manifest, f)
        runner = CliRunner()
        rid = _cli_ok(runner, ["history", "add", mp, "--history-dir",
                               hist]).output.strip()
        return runner, rid

    def test_advise_json_and_table(self, tmp_path):
        hist = str(tmp_path / "hist")
        man = _healthy_record(metrics={
            "bst_chunk_cache_hits_total": 10.0,
            "bst_chunk_cache_misses_total": 90.0,
            "bst_chunk_cache_evictions_total": 40.0})
        runner, rid = self._import_record(tmp_path, hist, man)
        # default REF = the latest record
        out = _cli_ok(runner, ["tune", "advise", "--history-dir",
                               hist]).output
        assert "chunk_cache_thrash" in out and "BST_CHUNK_CACHE_BYTES" in out
        doc = json.loads(_cli_ok(
            runner, ["tune", "advise", rid, "--history-dir", hist,
                     "--json"]).output)
        assert [d["rule"] for d in doc["diagnoses"]] == \
            ["chunk_cache_thrash"]
        d = doc["diagnoses"][0]
        assert d["knob"] and d["suggested_value"] and d["evidence"]

    def test_advise_healthy_says_so(self, tmp_path):
        hist = str(tmp_path / "hist")
        runner, rid = self._import_record(tmp_path, hist,
                                          _healthy_record())
        out = _cli_ok(runner, ["tune", "advise", rid, "--history-dir",
                               hist]).output
        assert "no rules fired" in out

    def test_advise_unknown_ref_is_clean_error(self, tmp_path):
        hist = str(tmp_path / "hist")
        self._import_record(tmp_path, hist, _healthy_record())
        r = CliRunner().invoke(cli, ["tune", "advise", "nope",
                                     "--history-dir", hist])
        assert r.exit_code != 0 and "nope" in r.output


def _sleep_for_knob(name="BST_WRITE_THREADS", optimum_log2=5.0):
    """A workload whose runtime has a KNOWN minimum: 10ms per pow2 step
    away from 2**optimum_log2, +10ms floor — far above timer noise."""
    def fn():
        v = config.get_int(name) or 1
        time.sleep(0.01 * abs(math.log2(v) - optimum_log2) + 0.01)
    return fn


class TestAutotuner:
    def test_converges_to_known_optimum(self, tmp_path):
        hist = str(tmp_path / "hist")
        wl = tune.CallableWorkload("synthetic-sleep", _sleep_for_knob())
        seed = tune.Diagnosis(rule="planted", detail="", confidence=1.0,
                              knob="BST_WRITE_THREADS",
                              suggested_value="16")
        res = tune.autotune(wl, diagnoses=[seed], trials_per_config=1,
                            max_trials=10, min_gain=0.05,
                            history_dir=hist, warmup=False)
        # default 8 -> seeded 16 -> hill-climbs to the optimum 32 within
        # a handful of trials (bounded, not exhaustive)
        assert res.best_overrides == {"BST_WRITE_THREADS": "32"}
        assert 3 <= len(res.trials) <= 6
        assert res.best_seconds < res.baseline_seconds
        # every trial is a first-class history record of tool tune-trial
        entries = history.list_records(hist, tool="tune-trial")
        assert len(entries) == len(res.trials)
        assert {e["id"] for e in entries} == \
            {t.record_id for t in res.trials}
        # ...and perf-diff works on trials like on production runs
        rep = history.diff(history.load_record(entries[0]["id"], hist),
                           history.load_record(entries[-1]["id"], hist))
        assert "wall_clock" in rep
        # the winner persisted under this host's backend axes
        backend, ndev = profiles.backend_signature()
        store = tune.load_store(hist)
        prof = tune.match_profile(store, backend=backend,
                                  device_count=ndev, shape=wl.shape)
        assert prof["overrides"] == {"BST_WRITE_THREADS": "32"}
        assert prof["speedup"] >= 1.0

    def test_insensitive_workload_keeps_defaults(self, tmp_path):
        """Never-a-regression: when no candidate clears the min-gain
        bar, the default configuration wins with an EMPTY override set
        (best == baseline, speedup exactly 1.0)."""
        hist = str(tmp_path / "hist")
        wl = tune.CallableWorkload("flat", lambda: time.sleep(0.005))
        res = tune.autotune(wl, force_knobs=("BST_WRITE_THREADS",),
                            diagnoses=[], trials_per_config=1,
                            max_trials=6, min_gain=0.5,
                            history_dir=hist, warmup=False)
        assert res.best_overrides == {}
        assert res.best_seconds == res.baseline_seconds
        prof = tune.load_store(hist)["profiles"][res.profile_key]
        assert prof["overrides"] == {} and prof["speedup"] == 1.0

    def test_crashing_candidate_never_adopted(self, tmp_path):
        def fn():
            if config.get_int("BST_WRITE_THREADS") == 4:
                raise RuntimeError("boom at 4 threads")
            time.sleep(0.005)

        seed = tune.Diagnosis(rule="planted", detail="", confidence=1.0,
                              knob="BST_WRITE_THREADS",
                              suggested_value="4")
        res = tune.autotune(tune.CallableWorkload("crashy", fn),
                            diagnoses=[seed], trials_per_config=1,
                            max_trials=6, min_gain=0.5,
                            history_dir=str(tmp_path / "h"),
                            warmup=False)
        bad = [t for t in res.trials
               if t.overrides.get("BST_WRITE_THREADS") == "4"]
        assert bad and all(t.status == "error" for t in bad)
        assert res.best_overrides.get("BST_WRITE_THREADS") != "4"
        # the failed trial still landed in history, status error
        rec = history.load_record(bad[0].record_id, str(tmp_path / "h"))
        assert rec["status"] == "error"

    def test_crashing_baseline_aborts(self, tmp_path):
        def fn():
            raise RuntimeError("always")

        with pytest.raises(RuntimeError, match="default"):
            tune.autotune(tune.CallableWorkload("dead", fn),
                          diagnoses=[], trials_per_config=1,
                          history_dir=str(tmp_path / "h"), warmup=False)

    def test_max_trials_is_a_hard_cap(self, tmp_path):
        seeds = [tune.Diagnosis(rule="p", detail="", confidence=1.0,
                                knob=k, suggested_value=None)
                 for k in ("BST_WRITE_THREADS", "BST_CHUNK_CACHE_BYTES",
                           "BST_TILE_CACHE_BYTES", "BST_INFLIGHT_BYTES")]
        res = tune.autotune(
            tune.CallableWorkload("flat", lambda: time.sleep(0.002)),
            diagnoses=seeds, trials_per_config=1, max_trials=4,
            min_gain=0.5, history_dir=str(tmp_path / "h"), warmup=False)
        assert len(res.trials) <= 4

    def test_bool_knob_enumerates_flip(self, tmp_path):
        seen = []

        def fn():
            seen.append(config.get_bool("BST_EARLY_DISPATCH"))
            time.sleep(0.002)

        seed = tune.Diagnosis(rule="p", detail="", confidence=1.0,
                              knob="BST_EARLY_DISPATCH",
                              suggested_value=None)
        tune.autotune(tune.CallableWorkload("boolish", fn),
                      diagnoses=[seed], trials_per_config=1,
                      max_trials=4, min_gain=0.5,
                      history_dir=str(tmp_path / "h"), warmup=False)
        # baseline saw the default, the candidate saw the flip
        assert len(set(seen)) == 2


class TestProfileStore:
    def _mk(self, shape, created_at=None, **kw):
        p = profiles.make_profile(
            backend=kw.pop("backend", "cpu"),
            device_count=kw.pop("device_count", 1), shape=shape,
            workload="t", overrides=kw.pop("overrides", {"BST_X": "1"}),
            baseline_seconds=2.0, best_seconds=1.0, trials=3)
        if created_at:
            p["created_at"] = created_at
        return p

    def test_save_load_roundtrip_and_overwrite(self, tmp_path):
        hist = str(tmp_path / "h")
        key = profiles.save_profile(
            self._mk("s1", overrides={"BST_WRITE_THREADS": "4"}), hist)
        assert key == "cpu/1/s1"
        store = profiles.load_store(hist)
        assert store["schema"] == profiles.SCHEMA
        assert store["profiles"][key]["overrides"] == \
            {"BST_WRITE_THREADS": "4"}
        # same key overwrites, store stays size 1
        profiles.save_profile(
            self._mk("s1", overrides={"BST_WRITE_THREADS": "8"}), hist)
        store = profiles.load_store(hist)
        assert len(store["profiles"]) == 1
        assert store["profiles"][key]["overrides"] == \
            {"BST_WRITE_THREADS": "8"}

    def test_match_explicit_key_prefix_ambiguous(self, tmp_path):
        hist = str(tmp_path / "h")
        profiles.save_profile(self._mk("t2x2-a"), hist)
        profiles.save_profile(self._mk("t2x2-b"), hist)
        store = profiles.load_store(hist)
        assert profiles.match_profile(
            store, backend="", device_count=0,
            ref="cpu/1/t2x2-a")["shape"] == "t2x2-a"
        # unique prefix resolves; ambiguous prefix refuses
        assert profiles.match_profile(
            store, backend="", device_count=0,
            ref="cpu/1/t2x2-b")["shape"] == "t2x2-b"
        with pytest.raises(KeyError, match="ambiguous"):
            profiles.match_profile(store, backend="", device_count=0,
                                   ref="cpu/1/t2x2")
        with pytest.raises(KeyError, match="no profile"):
            profiles.match_profile(store, backend="", device_count=0,
                                   ref="tpu/8/z")

    def test_match_auto_exact_then_newest_same_axes(self, tmp_path):
        hist = str(tmp_path / "h")
        profiles.save_profile(
            self._mk("old", created_at="2026-01-01T00:00:00"), hist)
        profiles.save_profile(
            self._mk("new", created_at="2026-06-01T00:00:00"), hist)
        profiles.save_profile(
            self._mk("other", backend="tpu", device_count=8,
                     created_at="2026-07-01T00:00:00"), hist)
        store = profiles.load_store(hist)
        # exact shape wins
        assert profiles.match_profile(
            store, backend="cpu", device_count=1, shape="old",
            ref="auto")["shape"] == "old"
        # no shape match -> newest on the same backend axes, never the
        # tpu profile
        assert profiles.match_profile(
            store, backend="cpu", device_count=1, shape="elsewhere",
            ref="auto")["shape"] == "new"
        # foreign axes -> None (auto is best-effort)
        assert profiles.match_profile(
            store, backend="gpu", device_count=4, ref="auto") is None

    def test_no_history_dir_raises(self, monkeypatch):
        monkeypatch.delenv("BST_HISTORY_DIR", raising=False)
        with pytest.raises(FileNotFoundError):
            profiles.load_store(None)


class TestTuneRunCli:
    def test_tiny_fusion_end_to_end(self, tmp_path):
        """Acceptance: `bst tune run` on the built-in workload produces
        a profile whose best is never worse than the default config, and
        every trial is a history record."""
        hist = str(tmp_path / "hist")
        runner = CliRunner()
        res = _json_tail(_cli_ok(
            runner, ["tune", "run", "--history-dir", hist,
                     "--trials", "1", "--max-trials", "3",
                     "--knob", "BST_WRITE_THREADS", "--json"]).output)
        assert res["workload"] == "tiny-fusion"
        assert 1 <= len(res["trials"]) <= 3
        assert res["best_seconds"] <= res["baseline_seconds"]
        assert res["speedup"] >= 1.0
        assert res["profile_key"]
        # trials are first-class history records, browsable by tool
        entries = json.loads(_cli_ok(
            runner, ["history", "list", "--history-dir", hist,
                     "--tool", "tune-trial", "--json"]).output)
        assert len(entries) == len(res["trials"])
        # the store lists/shows/applies the winner
        out = _cli_ok(runner, ["tune", "list", "--history-dir",
                               hist]).output
        assert res["profile_key"] in out
        prof = json.loads(_cli_ok(
            runner, ["tune", "show", res["profile_key"], "--history-dir",
                     hist]).output)
        assert prof["key"] == res["profile_key"]
        apply_out = _cli_ok(
            runner, ["tune", "apply", "--history-dir", hist,
                     res["profile_key"]]).output
        assert res["profile_key"] in apply_out

    def test_unknown_knob_and_missing_history_are_clean_errors(
            self, tmp_path, monkeypatch):
        runner = CliRunner()
        r = runner.invoke(cli, ["tune", "run", "--history-dir",
                                str(tmp_path / "h"), "--knob", "BST_NOPE"])
        assert r.exit_code != 0 and "BST_NOPE" in r.output
        monkeypatch.delenv("BST_HISTORY_DIR", raising=False)
        r = runner.invoke(cli, ["tune", "run"])
        assert r.exit_code != 0 and "history" in r.output

    def test_apply_runs_tool_under_profile_scope(self, tmp_path):
        hist = str(tmp_path / "hist")
        profiles.save_profile(profiles.make_profile(
            backend="cpu", device_count=1, shape="s", workload="t",
            overrides={"BST_WRITE_THREADS": "4"}, baseline_seconds=1.0,
            best_seconds=1.0, trials=1), hist)
        out = _cli_ok(CliRunner(), [
            "tune", "apply", "--history-dir", hist, "cpu/1/s",
            "config", "--json"]).output
        row = [r for r in _json_tail(out)
               if r["name"] == "BST_WRITE_THREADS"][0]
        assert row["value"] == 4 and row["source"] == "override"


class TestConfigTunableSurface:
    def test_tunable_metadata_in_config_json(self):
        out = _cli_ok(CliRunner(), ["config", "--json"]).output
        rows = {r["name"]: r for r in json.loads(out)}
        t = rows["BST_WRITE_THREADS"]["tunable"]
        assert t and t["lo"] == 1 and t["hi"] == 64
        assert rows["BST_PROFILE_AUTO"]["tunable"] is None

    def test_tunable_knobs_registry(self):
        tk = config.tunable_knobs()
        assert "BST_WRITE_THREADS" in tk
        assert "BST_CHUNK_CACHE_BYTES" in tk
        # correctness-affecting knobs are NOT tunable
        assert "BST_HISTORY_DIR" not in tk
        assert "BST_PROFILE_AUTO" not in tk
        for name, k in tk.items():
            if k.kind in ("int", "bytes"):
                assert k.tunable.lo is not None, name
                assert k.tunable.hi is not None, name


class TestDaemonProfileApplication:
    @pytest.fixture()
    def daemon(self, tmp_path, monkeypatch):
        from bigstitcher_spark_tpu.serve.daemon import Daemon

        monkeypatch.setenv("BST_HISTORY_DIR", str(tmp_path / "hist"))
        d = Daemon(str(tmp_path / "bst.sock"), slots=1,
                   jobs_root=str(tmp_path / "jobs")).start()
        try:
            yield d
        finally:
            if not d.wait(timeout=0):
                d.shutdown(drain=False, wait=True)

    def _store_profile(self, tmp_path, overrides):
        backend, ndev = profiles.backend_signature()
        return profiles.save_profile(profiles.make_profile(
            backend=backend, device_count=ndev, shape="daemon-test",
            workload="t", overrides=overrides, baseline_seconds=1.0,
            best_seconds=0.9, trials=2), str(tmp_path / "hist"))

    def test_profile_applies_as_override_not_env(self, tmp_path, daemon):
        from bigstitcher_spark_tpu.serve import client

        self._store_profile(tmp_path, {"BST_WRITE_THREADS": "4"})
        res = client.submit(daemon.socket_path, "config", ["--json"],
                            profile="auto")
        assert res["exit_code"] == 0
        rows = json.loads(open(os.path.join(
            res["telemetry_dir"], "output.log")).read())
        row = [r for r in rows if r["name"] == "BST_WRITE_THREADS"][0]
        assert row["value"] == 4 and row["source"] == "override"
        # the applied key is auditable on the job and in its manifest
        job = [j for j in client.list_jobs(daemon.socket_path)["jobs"]
               if j["id"] == res["job"]][0]
        assert job["profile"].endswith("daemon-test")
        # the daemon process itself never saw the knob
        assert "BST_WRITE_THREADS" not in os.environ

    def test_explicit_set_wins_over_profile(self, tmp_path, daemon):
        from bigstitcher_spark_tpu.serve import client

        self._store_profile(tmp_path, {"BST_WRITE_THREADS": "4"})
        res = client.submit(daemon.socket_path, "config", ["--json"],
                            profile="auto",
                            overrides={"BST_WRITE_THREADS": "2"})
        rows = json.loads(open(os.path.join(
            res["telemetry_dir"], "output.log")).read())
        row = [r for r in rows if r["name"] == "BST_WRITE_THREADS"][0]
        assert row["value"] == 2

    def test_explicit_missing_profile_is_an_error(self, tmp_path, daemon):
        from bigstitcher_spark_tpu.serve import client

        self._store_profile(tmp_path, {})
        with pytest.raises(RuntimeError, match="no profile"):
            client.submit(daemon.socket_path, "config", [],
                          profile="tpu/9/nothere")
        # auto with an empty/unmatched store is best-effort: job runs
        res = client.submit(daemon.socket_path, "config", ["--json"],
                            profile="auto")
        assert res["exit_code"] == 0

    def test_profile_auto_knob_applies_without_flag(self, tmp_path,
                                                    daemon, monkeypatch):
        from bigstitcher_spark_tpu.serve import client

        self._store_profile(tmp_path, {"BST_WRITE_THREADS": "4"})
        monkeypatch.setenv("BST_PROFILE_AUTO", "1")
        res = client.submit(daemon.socket_path, "config", ["--json"])
        rows = json.loads(open(os.path.join(
            res["telemetry_dir"], "output.log")).read())
        row = [r for r in rows if r["name"] == "BST_WRITE_THREADS"][0]
        assert row["value"] == 4 and row["source"] == "override"

    def test_fusion_output_bit_identical_under_profile(self, tmp_path,
                                                       daemon):
        """The acceptance contract: a tuned profile changes performance
        knobs only, so the fused bytes are identical with and without
        it."""
        from bigstitcher_spark_tpu.serve import client
        from bigstitcher_spark_tpu.utils.testdata import \
            make_synthetic_project

        self._store_profile(tmp_path, {"BST_WRITE_THREADS": "2"})
        proj = make_synthetic_project(
            str(tmp_path / "proj"), n_tiles=(2, 2, 1),
            tile_size=(64, 64, 32), overlap=16, jitter=0.0,
            n_beads_per_tile=20)
        runner = CliRunner()
        for out in ("plain.zarr", "tuned.zarr"):
            _cli_ok(runner, ["create-fusion-container",
                             "-x", proj.xml_path,
                             "-o", str(tmp_path / out),
                             "-s", "ZARR", "-d", "UINT16",
                             "--minIntensity", "0",
                             "--maxIntensity", "65535"])
        ra = client.submit(daemon.socket_path, "affine-fusion",
                           ["-o", str(tmp_path / "plain.zarr")])
        rb = client.submit(daemon.socket_path, "affine-fusion",
                           ["-o", str(tmp_path / "tuned.zarr")],
                           profile="auto")
        assert ra["exit_code"] == 0 and rb["exit_code"] == 0
        from bigstitcher_spark_tpu.io.chunkstore import ChunkStore

        def vol(path):
            ds = ChunkStore.open(path).open_dataset("0")
            size = tuple(ds.shape[:3]) + (1,) * (len(ds.shape) - 3)
            return np.asarray(ds.read((0,) * len(ds.shape), size))

        assert np.array_equal(vol(str(tmp_path / "plain.zarr")),
                              vol(str(tmp_path / "tuned.zarr")))


class TestHistorySatellites:
    def _seed_store(self, tmp_path, tools):
        """Import one minimal manifest per tool name, in order; returns
        (hist_dir, [record ids])."""
        hist = str(tmp_path / "hist")
        ids = []
        for i, tool in enumerate(tools):
            mp = str(tmp_path / f"manifest-{i}.json")
            with open(mp, "w") as f:
                json.dump({"tool": tool, "seconds": 1.0 + i,
                           "status": "ok", "spans": {}, "metrics": {}}, f)
            ids.append(history.record_manifest(mp, directory=hist))
        return hist, ids

    def test_list_records_tool_since_limit(self, tmp_path):
        hist, ids = self._seed_store(
            tmp_path, ["affine-fusion", "solver", "affine-fusion"])
        assert [e["id"] for e in history.list_records(hist)] == ids
        assert [e["id"] for e in
                history.list_records(hist, tool="solver")] == [ids[1]]
        # limit keeps the NEWEST N, still oldest-first
        assert [e["id"] for e in history.list_records(hist, limit=2)] == \
            ids[1:]
        assert history.list_records(hist, limit=0) == []
        # since: ISO-lexicographic, prefixes work
        assert history.list_records(hist, since="2000") and \
            history.list_records(hist, since="2999-01") == []
        # filters compose
        assert [e["id"] for e in history.list_records(
            hist, tool="affine-fusion", limit=1)] == [ids[2]]

    def test_history_list_cli_filters_and_json(self, tmp_path):
        hist, ids = self._seed_store(
            tmp_path, ["affine-fusion", "solver", "affine-fusion"])
        runner = CliRunner()
        entries = json.loads(_cli_ok(
            runner, ["history", "list", "--history-dir", hist,
                     "--tool", "affine-fusion", "--json"]).output)
        assert [e["id"] for e in entries] == [ids[0], ids[2]]
        # stable keys for scripting
        assert set(entries[0]) >= {"id", "ts", "tool", "job", "status",
                                   "seconds", "file"}
        entries = json.loads(_cli_ok(
            runner, ["history", "list", "--history-dir", hist,
                     "--limit", "1", "--json"]).output)
        assert [e["id"] for e in entries] == [ids[2]]

    def test_perf_diff_last_defaults_to_same_tool(self, tmp_path):
        """The satellite fix: --last 2 used to diff the two newest
        records regardless of tool. It now anchors on the latest
        record's tool — here fusion vs fusion, skipping the newer
        solver-adjacent record."""
        hist, ids = self._seed_store(
            tmp_path, ["affine-fusion", "solver", "affine-fusion"])
        rep = _json_tail(_cli_ok(
            CliRunner(), ["perf-diff", "--last", "2", "--history-dir",
                          hist, "--json"]).output)
        assert rep["a"] == ids[0] and rep["b"] == ids[2]

    def test_perf_diff_tool_pins_selection(self, tmp_path):
        hist, ids = self._seed_store(
            tmp_path, ["solver", "solver", "affine-fusion"])
        rep = _json_tail(_cli_ok(
            CliRunner(), ["perf-diff", "--last", "2", "--tool", "solver",
                          "--history-dir", hist, "--json"]).output)
        assert rep["a"] == ids[0] and rep["b"] == ids[1]
        # too few records of the pinned tool is a clean error
        r = CliRunner().invoke(cli, ["perf-diff", "--last", "3",
                                     "--tool", "solver",
                                     "--history-dir", hist])
        assert r.exit_code != 0 and "3" in r.output

    def test_perf_diff_cross_tool_warns_loudly(self, tmp_path):
        # only one record of the latest tool: --last 2 falls back to a
        # cross-tool diff but says so instead of silently comparing
        hist, ids = self._seed_store(tmp_path,
                                     ["solver", "affine-fusion"])
        runner = CliRunner()
        out = _cli_ok(runner, ["perf-diff", "--last", "2",
                               "--history-dir", hist, "--json"]).output
        assert "CROSS-TOOL" in out
        assert "cross-tool diff" in out
        rep = _json_tail(out)
        assert rep["a"] == ids[0] and rep["b"] == ids[1]
        # explicit cross-tool refs warn too
        out = _cli_ok(runner, ["perf-diff", ids[0], ids[1],
                               "--history-dir", hist, "--json"]).output
        assert "cross-tool diff" in out


class TestObservability:
    def test_tune_metrics_and_spans_declared(self):
        from bigstitcher_spark_tpu.observe import metric_names

        for m in ("bst_tune_trials_total", "bst_tune_rules_fired_total",
                  "bst_tune_profiles_applied_total"):
            assert m in metric_names.METRICS
        for s in ("tune.advise", "tune.trial"):
            assert s in metric_names.SPANS

    def test_advise_counts_rules_fired(self):
        from bigstitcher_spark_tpu.observe import metrics as _metrics

        c = _metrics.counter("bst_tune_rules_fired_total",
                             rule="chunk_cache_thrash")
        before = c.value
        tune.advise_record(_healthy_record(metrics={
            "bst_chunk_cache_hits_total": 10.0,
            "bst_chunk_cache_misses_total": 90.0,
            "bst_chunk_cache_evictions_total": 40.0}))
        assert c.value == before + 1

"""Multi-host execution world (ISSUE 18): global solve mesh, default-on
multihost pair split, and cross-host block streaming.

Acceptance contract:

- the psum-sharded relax under a global links axis is BITWISE equal to
  the local/single-device solve (any world shape); the intensity CG is
  bitwise equal across the ranks of one world and tolerance-equal
  (1e-6) across world shapes — the gloo cross-process allreduce orders
  its reduction differently from XLA's local all-reduce;
- the cost-weighted process partition covers every item exactly once,
  LPT-balances heavy tails, and degenerates cleanly (tail smaller than
  the world, world size 1);
- the rank-addressed block exchange fetches a remote-owned chunk ONCE
  over TCP into the decoded-chunk LRU (zero container re-reads), the
  chunk gate releases on remote producers-done, and a dead peer fails
  exactly the waiting read with ``ExchangeError``;
- :class:`TestMultiprocessWorld` runs all three tentpole pieces through
  a REAL 2-process jax.distributed CPU world (subprocess workers, gloo
  collectives, TCP exchange) and checks bitwise fusion parity against a
  single-process run of the same streamed pipeline.
"""

import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.dag import PipelineSpec, SpecError, example_spec
from bigstitcher_spark_tpu.dag import exchange, stream
from bigstitcher_spark_tpu.dag.executor import _Executor, run_pipeline
from bigstitcher_spark_tpu.io import chunkcache
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.spimdata import ViewId
from bigstitcher_spark_tpu.models import solver as S
from bigstitcher_spark_tpu.observe import metrics
from bigstitcher_spark_tpu.ops import models as M
from bigstitcher_spark_tpu.ops import solve as OS
from bigstitcher_spark_tpu.ops.intensity import (
    match_stats,
    solve_intensity_coefficients,
)
from bigstitcher_spark_tpu.parallel.distributed import (
    partition_indices_weighted,
    partition_items_weighted,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


# -- shared problem builders (imported by the subprocess workers too) ---------


def _mh_graph(n=(4, 3), jitter=3.0, seed=0, tile=(100, 100, 50), step=80.0):
    """Synthetic tile-grid link graph (the test_solve_device shape):
    truth-consistent 8-corner links with jittered nominal positions."""
    rng = np.random.default_rng(seed)
    tiles = [(ViewId(0, i),) for i in range(n[0] * n[1])]
    truth = {i: np.array([(i % n[0]) * step, (i // n[0]) * step, 0.0])
             for i in range(len(tiles))}
    nom = {i: truth[i] + (rng.uniform(-jitter, jitter, 3) if i else 0.0)
           for i in truth}
    corners = np.array([[x, y, z] for x in (0, tile[0]) for y in (0, tile[1])
                        for z in (0, tile[2])], float)
    links = []
    for i in range(len(tiles)):
        for j in (i + 1, i + n[0]):
            if j >= len(tiles):
                continue
            if j == i + 1 and (i % n[0]) == n[0] - 1:
                continue
            shift = (truth[i] - nom[i]) - (truth[j] - nom[j])
            links.append(S.MatchLink(tiles[i], tiles[j], corners,
                                     corners + shift, np.full(8, 0.9)))
    return tiles, links


def _mh_cg_system(n_coeffs=48, n_matches=150, seed=1):
    """Synthetic intensity match system for the coefficient CG."""
    rng = np.random.default_rng(seed)
    matches = []
    for _ in range(n_matches):
        ca, cb = rng.integers(0, n_coeffs, 2)
        if ca == cb:
            continue
        x = rng.uniform(100, 1000, 50)
        a, b = rng.uniform(0.8, 1.2), rng.uniform(-20, 20)
        y = a * x + b + rng.normal(0, 5, 50)
        matches.append((int(ca), int(cb), *match_stats(x / 500, y / 500)))
    return n_coeffs, matches


def _solve_sig(res) -> str:
    """Bitwise signature of a SolveResult: error history + corrections in
    a deterministic key order."""
    h = hashlib.sha256()
    h.update(np.asarray(res.history).tobytes())
    for k in sorted(res.corrections, key=repr):
        h.update(np.asarray(res.corrections[k]).tobytes())
    return h.hexdigest()


def _mh_pipeline_spec(proj: str) -> dict:
    """The streamed resave -> create -> fuse spec the multihost world
    runs SPMD: single-level resave (a pyramid would read peer-written s0
    chunks through the un-gated producer path), create pinned to rank 0
    (metadata-only; racing it corrupts the fusion container)."""
    xml = os.path.join(proj, "dataset.xml")
    rexml = os.path.join(proj, "re.xml")
    return {
        "name": "mh-pipe",
        "datasets": {
            "resaved": {"path": os.path.join(proj, "resaved.n5"),
                        "ephemeral": True},
            "fused": {"path": os.path.join(proj, "fused.n5")},
        },
        "stages": [
            {"id": "resave", "tool": "resave",
             "args": ["-x", xml, "-xo", rexml, "-o", "@resaved", "--N5",
                      "--blockSize", "32,32,16", "-ds", "1,1,1"],
             "writes": ["resaved"]},
            {"id": "create", "tool": "create-fusion-container",
             "args": ["-x", rexml, "-o", "@fused", "-s", "N5",
                      "-d", "UINT16", "--minIntensity", "0",
                      "--maxIntensity", "65535",
                      "--blockSize", "32,32,16"],
             "after": ["resave"], "ranks": [0]},
            {"id": "fuse", "tool": "affine-fusion", "args": ["-o", "@fused"],
             "after": ["create"], "reads": ["resaved"],
             "writes": ["fused"]},
        ],
    }


def _mk_project(root: str) -> str:
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(root, n_tiles=(2, 1, 1),
                                  tile_size=(64, 64, 32), overlap=16,
                                  jitter=1.0, n_beads_per_tile=20,
                                  seed=7).xml_path


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _fused_sha(proj: str) -> str:
    ds = ChunkStore.open(os.path.join(proj, "fused.n5")) \
        .open_dataset("ch0tp0/s0")
    arr = ds.read((0, 0, 0), ds.shape)
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


# -- cost-weighted process partition ------------------------------------------


class TestWeightedPartition:
    def test_covers_every_item_exactly_once(self):
        costs = [((i * 13) % 7) + 0.5 for i in range(23)]
        world = 3
        seen = []
        for pi in range(world):
            seen += partition_indices_weighted(costs, pi, world)
        assert sorted(seen) == list(range(len(costs)))

    def test_lpt_balances_heavy_tail(self):
        # one huge item + many small ones: round-robin would pair the
        # huge item with half the small ones on one rank; LPT gives the
        # huge item its own bin
        costs = [100.0] + [1.0] * 10
        a = partition_indices_weighted(costs, 0, 2)
        b = partition_indices_weighted(costs, 1, 2)
        loads = {0: sum(costs[i] for i in a), 1: sum(costs[i] for i in b)}
        heavy = 0 if 0 in a else 1
        assert loads[1 - heavy] == 10.0       # all small items together
        assert [i for i in (a if heavy == 0 else b)] == [0]

    def test_items_variant_preserves_order_and_alignment(self):
        items = [f"it{i}" for i in range(9)]
        costs = [float((i * 5) % 4 + 1) for i in range(9)]
        got = partition_items_weighted(items, costs, 1, 2)
        idx = partition_indices_weighted(costs, 1, 2)
        assert got == [items[i] for i in idx]
        assert idx == sorted(idx)

    def test_tail_smaller_than_world(self):
        # 2 items across a 4-process world: two ranks get one item each,
        # the others get an empty (not erroring) slice
        costs = [3.0, 1.0]
        slices = [partition_indices_weighted(costs, pi, 4)
                  for pi in range(4)]
        assert sorted(i for s in slices for i in s) == [0, 1]
        assert sum(1 for s in slices if not s) == 2

    def test_world_one_is_identity(self):
        assert partition_indices_weighted([5.0, 1.0], 0, 1) == [0, 1]

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError, match="length mismatch"):
            partition_items_weighted([1, 2, 3], [1.0], 0, 2)

    def test_out_of_range_rank_raises(self):
        with pytest.raises(ValueError, match="outside world"):
            partition_indices_weighted([1.0], 5, 2)


# -- global solve mesh layout -------------------------------------------------


class TestSolveLayout:
    def test_knob_forces_global_layout(self):
        with config.overrides({"BST_SOLVE_GLOBAL": "1",
                               "BST_SOLVE_SHARD": 1}):
            assert OS.global_enabled()
            n, g = OS.solve_layout(64)
            assert (n, g) == (8, True)
            ndev, nproc = OS.global_axis_span(n, g)
            assert ndev == 8 and nproc == 1   # single-process pytest world
        with config.overrides({"BST_SOLVE_GLOBAL": "0",
                               "BST_SOLVE_SHARD": 1}):
            assert not OS.global_enabled()
            n, g = OS.solve_layout(64)
            assert (n, g) == (8, False)

    def test_auto_follows_world(self):
        # pytest runs a 1-process world: auto must pin to local devices
        with config.overrides({"BST_SOLVE_GLOBAL": "auto"}):
            assert not OS.global_enabled()

    def test_global_relax_bitwise_equals_local(self):
        tiles, links = _mh_graph()
        fixed = {tiles[0]}
        params = S.SolverParams(model=M.TRANSLATION, backend="device")
        with config.overrides({"BST_SOLVE_SHARD": 1,
                               "BST_SOLVE_GLOBAL": "0"}):
            local = S.relax(links, tiles, fixed, params)
        with config.overrides({"BST_SOLVE_SHARD": 1,
                               "BST_SOLVE_GLOBAL": "1"}):
            glob = S.relax(links, tiles, fixed, params)
        assert local.iterations == glob.iterations
        assert _solve_sig(local) == _solve_sig(glob)

    def test_global_cg_matches_local_to_tolerance(self):
        C, matches = _mh_cg_system()
        with config.overrides({"BST_SOLVE_SHARD": 1,
                               "BST_SOLVE_GLOBAL": "0"}):
            local = solve_intensity_coefficients(C, matches, 0.1,
                                                 backend="device")
        with config.overrides({"BST_SOLVE_SHARD": 1,
                               "BST_SOLVE_GLOBAL": "1"}):
            glob = solve_intensity_coefficients(C, matches, 0.1,
                                                backend="device")
        np.testing.assert_allclose(np.asarray(glob), np.asarray(local),
                                   rtol=0, atol=1e-6)


# -- rank pinning -------------------------------------------------------------


class TestRankPinning:
    def _spec(self, ranks):
        d = _mh_pipeline_spec("/tmp/x")
        d["stages"][1]["ranks"] = ranks
        return d

    def test_spec_parses_and_validates_ranks(self):
        spec = PipelineSpec.from_dict(self._spec([0, 1]))
        assert {s.id: s.ranks for s in spec.stages}["create"] == [0, 1]
        with pytest.raises(SpecError, match="non-negative"):
            PipelineSpec.from_dict(self._spec([-1]))

    def test_example_spec_pins_create_to_rank_zero(self):
        d = example_spec("/tmp/does-not-matter.xml")
        create = {s["id"]: s for s in d["stages"]}["create"]
        assert create["ranks"] == [0]
        PipelineSpec.from_dict(d)   # still validates

    def test_owner_resolution(self):
        spec = PipelineSpec.from_dict(self._spec([0]))
        run = lambda ex: ex.runs["create"]  # noqa: E731
        # single-process worlds ignore pinning entirely
        ex1 = _Executor(spec, "r", rank=0, world=1)
        assert ex1._owners(run(ex1)) is None
        # the owner rank runs the tool itself
        ex0 = _Executor(spec, "r", rank=0, world=2)
        assert ex0._owners(run(ex0)) is None
        # a non-owner adopts the owners' outcome
        exn = _Executor(spec, "r", rank=1, world=2)
        assert exn._owners(run(exn)) == {0}
        # ranks entirely outside the world: every rank runs it
        spec2 = PipelineSpec.from_dict(self._spec([7]))
        exo = _Executor(spec2, "r", rank=1, world=2)
        assert exo._owners(exo.runs["create"]) is None

    def test_wait_remote_done_outcomes(self):
        reg = stream.StreamRegistry()
        reg.remote_done("st", 0, ok=True)
        assert reg.wait_remote_done("st", {0}) is True
        reg.remote_done("bad", 0, ok=False)
        assert reg.wait_remote_done("bad", {0}) is False
        reg.remote_rank_dead(2)
        assert reg.wait_remote_done("never", {2}) is False

    def test_wait_remote_done_blocks_until_broadcast(self):
        reg = stream.StreamRegistry()
        got = {}

        def waiter():
            got["ok"] = reg.wait_remote_done("late", {0, 1})

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.3)
        assert th.is_alive()
        reg.remote_done("late", 0)
        time.sleep(0.3)
        assert th.is_alive()          # still one owner outstanding
        reg.remote_done("late", 1)
        th.join(10)
        assert not th.is_alive() and got["ok"] is True


# -- exchange protocol (in-process two-rank world) ----------------------------


class TestExchangeProtocol:
    def test_parse_addresses(self):
        assert exchange.parse_addresses("a:1, b:2 ,127.0.0.1:3") == \
            [("a", 1), ("b", 2), ("127.0.0.1", 3)]
        assert exchange.parse_addresses(":4") == [("127.0.0.1", 4)]
        with pytest.raises(ValueError, match="host:port"):
            exchange.parse_addresses("nope")

    def test_ensure_started_none_when_unconfigured(self, monkeypatch):
        monkeypatch.delenv("BST_DAG_EXCHANGE_ADDR", raising=False)
        assert exchange.ensure_started() is None
        # configured but single-process world: still nothing to exchange
        monkeypatch.setenv("BST_DAG_EXCHANGE_ADDR", "127.0.0.1:1,127.0.0.1:2")
        assert exchange.ensure_started() is None

    def test_rank_outside_address_list_raises(self):
        with pytest.raises(ValueError, match="outside"):
            exchange.Exchange(3, [("127.0.0.1", _free_port())],
                              registry=stream.StreamRegistry())

    def test_stop_interrupts_inflight_fetch(self):
        """Teardown regression (found by `bst lint` blocking-under-lock):
        _close_fetch used to take _fetch_lock, which an in-flight fetch
        holds for up to the 30s round-trip timeout — a peer dying
        mid-fetch wedged stop() for the full timeout. Teardown now shuts
        the socket down under the separate ref lock, so the blocked
        reader unblocks with EOF and stop() returns promptly."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        conns = []

        def silent_server():
            # accept the fetch connection, then never reply: the fetch
            # round trip stays blocked in readline until interrupted
            while True:
                try:
                    c, _ = srv.accept()
                except OSError:
                    return
                conns.append(c)

        threading.Thread(target=silent_server, daemon=True).start()
        peer = exchange._Peer(1, srv.getsockname(), 0, queue_max=8)
        errs = []
        fetch_done = threading.Event()

        def do_fetch():
            try:
                peer.fetch("root", "s0", (0, 0, 0))
            except exchange.ExchangeError as e:
                errs.append(e)
            fetch_done.set()

        threading.Thread(target=do_fetch, daemon=True).start()
        deadline = time.monotonic() + 10
        while not conns:
            assert time.monotonic() < deadline, "fetch never connected"
            time.sleep(0.02)
        time.sleep(0.2)    # let the fetch enter its blocked readline
        t0 = time.monotonic()
        peer.stop()
        stop_s = time.monotonic() - t0
        # well under the 30s fetch timeout the old teardown waited out
        assert stop_s < 10.0, f"stop() wedged for {stop_s:.1f}s"
        assert fetch_done.wait(10.0), "interrupted fetch never returned"
        assert errs, "fetch must raise ExchangeError after teardown"
        srv.close()
        for c in conns:
            c.close()

    def test_two_rank_streaming_world(self, tmp_path):
        """The full exchange contract in one simulated two-rank world
        (two private registries + two TCP endpoints in one process):
        cover broadcast, fetch-once into the chunk LRU with zero
        container re-reads, producers-done release, dead-peer failure."""
        addrs = [("127.0.0.1", _free_port()), ("127.0.0.1", _free_port())]
        regA, regB = stream.StreamRegistry(), stream.StreamRegistry()
        xa = exchange.Exchange(0, addrs, regA)
        xb = exchange.Exchange(1, addrs, regB)
        regA.set_exchange(xa)
        regB.set_exchange(xb)
        edgeA = None
        try:
            store = ChunkStore.create(str(tmp_path / "edge.n5"),
                                      StorageFormat.N5)
            dsB = store.create_dataset("s0", (64, 32, 16), (16, 16, 16),
                                       "uint16")
            prodB = stream.StageToken("prod", "r")
            consB = stream.StageToken("cons", "r")
            edgeB = stream.EdgeState("e", store.root, {prodB}, {consB})
            regB.register([edgeB])
            data = np.arange(64 * 32 * 16,
                             dtype=np.uint16).reshape(64, 32, 16)
            # rank 1 produces only the first two x-chunk rows: positions
            # (3, y, z) stay uncovered so the gate phases below have
            # something to wait on
            with stream.stage_scope(prodB):
                dsB.write(data[:32], (0, 0, 0))
            # simulate process isolation: "rank 0" never decoded these
            chunkcache.get_cache().clear()

            prodA = stream.StageToken("prod", "r")
            consA = stream.StageToken("cons", "r")
            dsA = ChunkStore.open(store.root).open_dataset("s0")
            edgeA = stream.EdgeState("e", store.root, {prodA}, {consA})
            regA.register([edgeA])

            def covers():
                with regA._lock:
                    return sum(len(v)
                               for v in regA._remote_cov.values()) >= 4
            deadline = time.monotonic() + 20
            while not covers():
                assert time.monotonic() < deadline, "covers never arrived"
                time.sleep(0.05)

            fetched0 = metrics.counter("bst_dag_xhost_bytes_total").value
            with stream.stage_scope(consA):
                out = dsA.read((0, 0, 0), (32, 32, 16))
            np.testing.assert_array_equal(out, data[:32])
            db = metrics.counter("bst_dag_xhost_bytes_total").value - fetched0
            assert db > 0 and edgeA.bytes_xhost > 0
            assert edgeA.bytes_reread == 0

            # fetch-once: the same box again moves zero new xhost bytes
            before = metrics.counter("bst_dag_xhost_bytes_total").value
            with stream.stage_scope(consA):
                dsA.read((0, 0, 0), (32, 32, 16))
            assert metrics.counter("bst_dag_xhost_bytes_total").value \
                == before
            assert edgeA.bytes_reread == 0

            # producers-done release: a read of an unwritten box blocks
            # until EVERY rank's producer instance is terminal
            done = threading.Event()

            def late_read():
                with stream.stage_scope(consA):
                    dsA.read((48, 0, 0), (16, 16, 16))
                done.set()

            th = threading.Thread(target=late_read)
            th.start()
            time.sleep(0.4)
            assert not done.is_set()
            regA.stage_finished(prodA)
            time.sleep(0.4)
            assert not done.is_set()      # the remote producer still runs
            regB.stage_finished(prodB)
            th.join(15)
            assert done.is_set()

            # dead peer: drop rank 1's connections without a bye; a gate
            # waiting on its blocks raises instead of hanging
            err = {}

            def doomed_read():
                try:
                    with regA._lock:
                        regA._coverage.clear()
                        regA._remote_cov.clear()
                        regA._finished.clear()
                        regA._remote_done.clear()
                    with stream.stage_scope(consA):
                        dsA.read((48, 16, 0), (16, 16, 16))
                except Exception as e:  # noqa: BLE001 - asserted below
                    err["e"] = e

            xb._stop.set()
            for p in xb._peers.values():
                p._close()
                p._close_fetch()
            deadline = time.monotonic() + 15
            while 1 not in regA._dead_ranks:
                assert time.monotonic() < deadline, "peer death unnoticed"
                time.sleep(0.05)
            th2 = threading.Thread(target=doomed_read)
            th2.start()
            th2.join(20)
            assert isinstance(err.get("e"), exchange.ExchangeError)
        finally:
            if edgeA is not None:
                regA.unregister([edgeA])
            xa.stop()
            xb.stop()


# -- the real thing: a 2-process jax.distributed world ------------------------


_WORKER = """
import hashlib, json, os, sys
import numpy as np
sys.path.insert(0, os.environ["MH_TESTDIR"])
from bigstitcher_spark_tpu.parallel.distributed import init_distributed, world
assert init_distributed(), "worker failed to join the jax world"
import jax
from bigstitcher_spark_tpu import config
from bigstitcher_spark_tpu.dag.executor import run_pipeline
from bigstitcher_spark_tpu.models import solver as S
from bigstitcher_spark_tpu.ops import models as M
from bigstitcher_spark_tpu.ops import solve as OS
from bigstitcher_spark_tpu.ops.intensity import solve_intensity_coefficients
from bigstitcher_spark_tpu.parallel import pairsched
from test_multihost import (
    _fused_sha, _mh_cg_system, _mh_graph, _mh_pipeline_spec, _solve_sig,
)

rank, pc = world()
out = {"rank": rank, "world": pc,
       "local_devices": jax.local_device_count(),
       "global_devices": jax.device_count()}

# tentpole 1: the global solve mesh is on by default at world > 1 and
# its links axis really spans both processes
assert OS.global_enabled(), "global solve must be auto-on at world 2"
with config.overrides({"BST_SOLVE_SHARD": 1}):
    n, g = OS.solve_layout(64)
    out["layout"] = [int(n), bool(g)]
    out["span"] = list(OS.global_axis_span(n, g))
    tiles, links = _mh_graph()
    res = S.relax(links, tiles, {tiles[0]},
                  S.SolverParams(model=M.TRANSLATION, backend="device"))
    out["relax_iters"] = int(res.iterations)
    out["relax_sig"] = _solve_sig(res)
    C, matches = _mh_cg_system()
    co = solve_intensity_coefficients(C, matches, 0.1, backend="device")
    out["cg"] = np.asarray(co).ravel().tolist()

# tentpole 2: pair split is default-on; every rank returns the full
# result list while computing only its LPT slice
assert pairsched.multihost_active(), "pair split must be auto-on"
tasks = [pairsched.PairTask(index=i, cost=float(1 + (i * 7) % 5))
         for i in range(13)]
ran = []
def dispatch(t):
    ran.append(t.index)
    return t.index * t.index
vals = pairsched.run_pair_tasks(tasks, dispatch, stage="mh-e2e")
out["pair_results"] = [int(v) for v in vals]
out["pair_local"] = sorted(int(i) for i in ran)
util = pairsched.process_util_snapshot()
out["pair_util_recorded"] = "mh-e2e" in util
out["pair_util"] = util.get("mh-e2e")

# tentpole 3: the streamed pipeline SPMD across both ranks, remote
# chunks arriving over the exchange
proj = os.environ["MH_PROJECT"]
res = run_pipeline(_mh_pipeline_spec(proj), workdir=proj)
d = res.to_dict()
assert res.ok, d
edges = {e["edge"]: e for e in d["edges"]}
out["xhost_bytes"] = int(edges["resaved"]["bytes_xhost"])
out["reread"] = int(edges["resaved"]["bytes_reread"])
out["elided"] = bool(edges["resaved"]["elided"])
out["s0_sha"] = _fused_sha(proj)
print("RESULT " + json.dumps(out), flush=True)
"""


class TestMultiprocessWorld:
    def _spawn(self, tmp_path, rank, coord, xaddrs, proj):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
            "BST_COORDINATOR": coord,
            "BST_NUM_PROCESSES": "2",
            "BST_PROCESS_ID": str(rank),
            "BST_DAG_EXCHANGE_ADDR": xaddrs,
            "MH_TESTDIR": TESTS,
            "MH_PROJECT": proj,
        })
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        return subprocess.Popen([sys.executable, str(script)], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def test_two_process_world_end_to_end(self, tmp_path):
        """Acceptance: REAL 2-process CPU world (gloo collectives + TCP
        exchange). Global relax bitwise vs the single-process solve, CG
        identical across ranks and 1e-6 vs single-process, pair split
        exact-parity with per-process utilization recorded, and the
        streamed pipeline bitwise-equal to a 1-process run with xhost
        bytes > 0 and zero container re-reads."""
        proj = str(tmp_path / "world")
        _mk_project(proj)
        coord = f"127.0.0.1:{_free_port()}"
        xaddrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
        procs = {r: self._spawn(tmp_path, r, coord, xaddrs, proj)
                 for r in (0, 1)}
        outs = {}
        try:
            for r, p in procs.items():
                raw, _ = p.communicate(timeout=420)
                outs[r] = raw.decode()
                assert p.returncode == 0, f"rank {r}:\n{outs[r]}"
        finally:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
        reports = {}
        for r, txt in outs.items():
            lines = [ln for ln in txt.splitlines()
                     if ln.startswith("RESULT ")]
            assert lines, f"rank {r} produced no RESULT:\n{txt}"
            reports[r] = json.loads(lines[-1][len("RESULT "):])

        r0, r1 = reports[0], reports[1]
        assert (r0["world"], r1["world"]) == (2, 2)
        # the global links axis spans both processes' devices
        for r in (r0, r1):
            assert r["layout"] == [8, True]
            assert r["span"] == [8, 2]

        # relax: bitwise identical across ranks AND across world shapes
        tiles, links = _mh_graph()
        with config.overrides({"BST_SOLVE_SHARD": 1}):
            golden = S.relax(links, tiles, {tiles[0]},
                             S.SolverParams(model=M.TRANSLATION,
                                            backend="device"))
        assert r0["relax_sig"] == r1["relax_sig"] == _solve_sig(golden)
        assert r0["relax_iters"] == golden.iterations

        # CG: bitwise across the ranks of one world; tolerance-level vs
        # the single-process solve (gloo reduction order differs from
        # XLA's local all-reduce)
        assert r0["cg"] == r1["cg"]
        C, matches = _mh_cg_system()
        with config.overrides({"BST_SOLVE_SHARD": 1}):
            cg_golden = solve_intensity_coefficients(C, matches, 0.1,
                                                     backend="device")
        np.testing.assert_allclose(np.asarray(r0["cg"], dtype=np.float64),
                                   np.asarray(cg_golden).ravel(),
                                   rtol=0, atol=1e-6)

        # pair split: full results on every rank, disjoint+complete local
        # slices, per-process utilization recorded for the relay plane
        expect = [i * i for i in range(13)]
        assert r0["pair_results"] == expect
        assert r1["pair_results"] == expect
        assert set(r0["pair_local"]).isdisjoint(r1["pair_local"])
        assert sorted(r0["pair_local"] + r1["pair_local"]) == list(range(13))
        assert 0 < len(r0["pair_local"]) < 13   # both ranks really worked
        assert r0["pair_util_recorded"] and r1["pair_util_recorded"]

        # pipeline: remote chunks crossed the wire exactly once on each
        # rank, never re-read from the (elided) container
        for r in (r0, r1):
            assert r["elided"] is True
            assert r["xhost_bytes"] > 0
            assert r["reread"] == 0
        assert r0["s0_sha"] == r1["s0_sha"]

        # bitwise parity with a single-process run of the same spec
        gproj = str(tmp_path / "golden")
        _mk_project(gproj)
        gres = run_pipeline(_mh_pipeline_spec(gproj), workdir=gproj)
        assert gres.ok, gres.to_dict()
        assert _fused_sha(gproj) == r0["s0_sha"]

"""Multi-device (virtual 8-CPU mesh) fusion: the production sharded driver
must produce byte-identical output to the single-device per-block path for
both the shift and gather kernels (VERDICT r1 item 3; replaces the Spark map
at SparkAffineFusion.java:480-482)."""

import numpy as np
import pytest

from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
from bigstitcher_spark_tpu.io.spimdata import SpimData
from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box


@pytest.fixture(scope="module")
def project(tmp_path_factory):
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    return make_synthetic_project(
        str(tmp_path_factory.mktemp("mesh") / "proj"),
        n_tiles=(2, 2, 1), tile_size=(48, 48, 24), overlap=12,
        jitter=2.0, seed=13, block_size=(16, 16, 8), n_beads_per_tile=15,
    )


def _fuse(project, tmp_path, name, **kw):
    sd = SpimData.load(project.xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    store = ChunkStore.create(str(tmp_path / f"{name}.n5"), StorageFormat.N5)
    ds = store.create_dataset("fused", bbox.shape, (16, 16, 8), "uint16")
    stats = fuse_volume(
        sd, loader, views, ds, bbox, block_size=(16, 16, 8),
        block_scale=(2, 2, 1), out_dtype="uint16", **kw,
    )
    return ds.read_full(), stats


def test_sharded_equals_single_device_shift(project, tmp_path):
    import jax

    assert len(jax.devices()) >= 8, "conftest must provide the 8-device mesh"
    multi, ms = _fuse(project, tmp_path, "multi", devices=8)
    single, ss = _fuse(project, tmp_path, "single", devices=1,
                       device_resident=False)
    assert multi.std() > 0
    assert (multi == single).all()
    assert ms.voxels == ss.voxels > 0


def test_sharded_equals_single_device_gather(project, tmp_path):
    """anisotropy != 1 forces the general gather kernel on every block."""
    multi, _ = _fuse(project, tmp_path, "multi_g", devices=8,
                     anisotropy_factor=2.0)
    single, _ = _fuse(project, tmp_path, "single_g", devices=1,
                      device_resident=False, anisotropy_factor=2.0)
    assert multi.std() > 0
    assert (multi == single).all()


def test_sharded_masks_mode(project, tmp_path):
    multi, _ = _fuse(project, tmp_path, "multi_m", devices=8, masks=True)
    single, _ = _fuse(project, tmp_path, "single_m", devices=1,
                      device_resident=False, masks=True)
    assert set(np.unique(multi)) <= {0, 65535}
    assert (multi == single).all()


def test_composite_masks_with_mask_offset(project, tmp_path):
    """--maskOffset widens the inside test beyond the tile; the composite
    kernel's static slices must stay in bounds (pad = 1 + ceil(offset))
    and agree with the per-block path."""
    multi, _ = _fuse(project, tmp_path, "mo_pb", devices=1,
                     device_resident=False, masks=True,
                     mask_offset=(2.0, 2.0, 2.0))
    comp, st = _fuse(project, tmp_path, "mo_comp", devices=1, masks=True,
                     mask_offset=(2.0, 2.0, 2.0))
    assert any("composite" in str(k) for k in st.compile_keys)
    assert (comp == multi).all()
    # offset=2 must strictly grow coverage vs offset=0
    plain, _ = _fuse(project, tmp_path, "mo_plain", devices=1, masks=True)
    assert (comp > 0).sum() >= (plain > 0).sum()


def test_composite_intensity_coefficients(project, tmp_path):
    """Per-view intensity-correction grids applied inside the composite
    kernel (separable trilinear) agree with the per-block gather path
    (BlkAffineFusion.initWithIntensityCoefficients role)."""
    from bigstitcher_spark_tpu.io.spimdata import SpimData

    sd = SpimData.load(project.xml_path)
    rng = np.random.default_rng(5)
    coeffs = {}
    for v in sd.view_ids():
        g = np.ones((2, 2, 2, 2), np.float32)
        g[..., 0] = rng.uniform(0.8, 1.2, (2, 2, 2))   # scale
        g[..., 1] = rng.uniform(-30.0, 30.0, (2, 2, 2))  # offset
        coeffs[v] = g
    comp, st = _fuse(project, tmp_path, "ic_comp", devices=1,
                     coefficients=coeffs)
    assert any("composite" in str(k) for k in st.compile_keys), \
        "coefficient fusion should take the composite device path"
    blockwise, _ = _fuse(project, tmp_path, "ic_pb", devices=1,
                         device_resident=False, coefficients=coeffs)
    assert comp.std() > 0
    diff = np.abs(comp.astype(np.int64) - blockwise.astype(np.int64))
    assert diff.max() <= 1  # f32 rounding at accumulation-order boundaries


def test_sharded_device_composite_agrees(project, tmp_path):
    """The single-device whole-volume composite path and the sharded
    per-block path agree (same math, different dispatch)."""
    multi, _ = _fuse(project, tmp_path, "multi_s", devices=8)
    scan, st = _fuse(project, tmp_path, "scan", devices=1)
    assert any("composite" in str(k) for k in st.compile_keys), \
        "single-device run did not take the device-resident composite path"
    diff = np.abs(multi.astype(np.int64) - scan.astype(np.int64))
    assert diff.max() <= 1  # rounding at f32 accumulation order boundaries

#!/usr/bin/env python
"""Benchmark: affine-fusion voxels/sec (the BASELINE.md north-star metric),
plus pairwise phase-correlation pairs/sec and DoG detection voxels/sec.

Primary metric: fuses a 2x2-tile synthetic light-sheet project (256x256x128
per tile, uint16, AVG_BLEND) into an OME-ZARR container on the available
accelerator and reports fused output voxels per second for the steady-state
(warm compile-cache) run — best of 3 runs, because the TPU arrives through a
shared tunnel whose bandwidth fluctuates 3x between runs. The span breakdown
(h2d / kernel / d2h / write) for the reported run is emitted alongside so the
bottleneck is a recorded fact: on this rig, the tunnel wire time dominates
end-to-end. A kernel-only steady-state number (tiles resident in HBM, output
left on device) and the measured wire bandwidth are reported to separate the
framework's compute from the harness's transport.

vs_baseline: measured against REAL measurements of reference-equivalent CPU
implementations on this same host/fixture (numpy+scipy fusion; numpy FFT
phase correlation with 5-peak wrap disambiguation; scipy DoG + local maxima),
RE-MEASURED in the same run as the candidate (the shared host drifts 20-30%
day to day, so cross-day cached baselines distort the ratio); the cache in
BASELINE_MEASURED.json records provenance + the previous measurement. The
XLA output is validated against the baseline implementation before timing.

Robustness: measurements run in a CHILD process with a hard timeout and
bounded retries; if the accelerator can't be initialized the bench falls
back to a CPU run (reported with "platform": "cpu").
"""

import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def _load_config_module():
    """The knob registry WITHOUT the package __init__ (which imports jax):
    the bench parent is a jax-free watchdog — it probes the accelerator in
    a timeout-guarded subprocess precisely so a dead TPU tunnel can never
    hang it, and a module-level `from bigstitcher_spark_tpu import config`
    would drag the jax import (and TPU plugin discovery) into it."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_bst_bench_config",
        os.path.join(REPO, "bigstitcher_spark_tpu", "config.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod   # dataclasses resolves cls.__module__
    spec.loader.exec_module(mod)
    return mod


_cfg = _load_config_module()

FIXTURE = _cfg.get_str("BST_BENCH_DIR")
BASELINE_FILE = os.path.join(REPO, "BASELINE_MEASURED.json")
FIXTURE_SPEC = {
    "n_tiles": (2, 2, 1), "tile_size": (256, 256, 128), "overlap": 32,
    "jitter": 0.0, "seed": 11, "block_size": (128, 128, 64),
    "n_beads_per_tile": 120,
}
# optional fixture scaling for throughput-vs-volume experiments (PERF.md):
# BST_BENCH_TILE=384 runs the primary config with (384,384,192) tiles;
# the baseline cache keys on the full spec, so scales never cross-pollute
_t = _cfg.get_int("BST_BENCH_TILE")
if _t:
    FIXTURE_SPEC["tile_size"] = (_t, _t, max(64, _t // 2))
CHILD_TIMEOUT_S = _cfg.get_int("BST_BENCH_CHILD_TIMEOUT")
TPU_ATTEMPTS = 2
# same-process baseline memo (one measurement per bench child)
_RUN_BASELINES: dict = {}
# a device call that exceeds this is a tunnel stall, not a slow run: the
# timed fusion runs take seconds and every extra is <60 s warm, so 300 s
# means the accelerator went away mid-attempt
DEVICE_TIMEOUT_S = _cfg.get_int("BST_BENCH_DEVICE_TIMEOUT")
# best-of-N: wall-clock noise on a shared host (and tunnel weather on TPU)
# swings single runs ~30%; five runs stabilize the headline artifact
FUSION_RUNS = _cfg.get_int("BST_BENCH_RUNS")


def build_fixture():
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    marker = os.path.join(FIXTURE, "proj", "dataset.xml")
    if os.path.exists(marker):
        return marker
    shutil.rmtree(FIXTURE, ignore_errors=True)
    make_synthetic_project(os.path.join(FIXTURE, "proj"), **FIXTURE_SPEC)
    return marker


def run_fusion(xml_path, out_path, block_scale=(2, 2, 1)):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.io.container import create_fusion_container
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    shutil.rmtree(out_path, ignore_errors=True)
    create_fusion_container(
        out_path, StorageFormat.ZARR, xml_path, 1, 1, bbox,
        data_type="uint16", block_size=(128, 128, 64),
        min_intensity=0.0, max_intensity=65535.0,
    )
    store = ChunkStore.open(out_path)
    ds = store.open_dataset("0")
    stats = fuse_volume(
        sd, loader, views, ds, bbox, block_size=(128, 128, 64),
        block_scale=block_scale, fusion_type="AVG_BLEND",
        out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
        zarr_ct=(0, 0),
    )
    return stats, ds, bbox


# ---------------------------------------------------------------------------
# Reference-equivalent CPU baselines (numpy + scipy), measured + cached
# ---------------------------------------------------------------------------


def _baseline_cache_load():
    try:
        with open(BASELINE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        # a watchdog kill mid-store can truncate the cache; treat it as
        # absent rather than crashing the artifact-finalize path
        return {}


# Baselines are RE-MEASURED inside every bench run (BST_BENCH_FRESH_BASELINE
# defaults on): the shared host's throughput drifts 20-30% day to day, so a
# cached baseline from another day distorts vs_baseline (r4 verdict weak #7).
# The cache still records provenance + the previous measurement for
# comparison; vs_baseline always uses the same-run number.
def _fresh_baselines() -> bool:
    return _cfg.get_bool("BST_BENCH_FRESH_BASELINE")


def _baseline_cache_store(cache):
    tmp = BASELINE_FILE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(cache, f, indent=1)
    os.replace(tmp, BASELINE_FILE)  # atomic: a mid-write kill can't truncate


def _fixture_key(extra=""):
    import hashlib

    return hashlib.sha256(
        json.dumps({"spec": FIXTURE_SPEC, "extra": extra}, sort_keys=True,
                   default=str).encode()).hexdigest()[:16]


_SYNC_METHODOLOGY = ("chained dispatches ended by a one-element data fetch "
                     "(_kernel_rate); axon block_until_ready is an "
                     "enqueue-ack, not a completion barrier")


def _tiny_fetch(out):
    """Fetch ONE element of (the first array leaf of) `out` to the host.
    This is the only trustworthy completion sync under the axon tunnel:
    `block_until_ready` there acknowledges *enqueue*, not execution (it
    returns in ~0.2 ms for programs whose true execution time, bounded
    below by HBM bandwidth, is >2 ms — measured 2026-07-31), so any
    timing loop that relies on it measures dispatch latency, not compute.
    A 4-byte data read cannot resolve before the producing program ran.
    One fetch of one leaf keeps the constant identical between the k=1
    and k=reps runs of `_kernel_rate` (profiling.device_sync syncs every
    leaf; here the stream order makes the first leaf sufficient)."""
    import jax

    from bigstitcher_spark_tpu import profiling

    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "dtype") and getattr(x, "size", 0)]
    if not leaves:  # a no-op sync would silently re-open the timing bug
        raise ValueError("_tiny_fetch: no non-empty array leaf to sync on")
    return profiling.device_sync(leaves[0])


def _kernel_rate(dispatch_fn, reps=10, tries=3):
    """True steady-state seconds per execution of an async device program.

    Times `k` back-to-back dispatches (the single PJRT stream executes
    them in order) ended by one `_tiny_fetch`; the k=1 run cancels the
    tunnel round-trip + fetch constant:

        per_exec = (T(k=reps) - T(k=1)) / (reps - 1)

    `dispatch_fn()` must dispatch exactly one execution of the program
    under test and return its output. Identical on CPU/TPU backends;
    under axon it is the only methodology whose numbers respect the
    hardware's bandwidth bounds (see `_tiny_fetch`).

    Syncing only the LAST dispatch relies on the single PJRT stream
    executing the k dispatches in order — valid on one device only. With
    multiple visible devices (multi-chip hosts) one element of EVERY
    addressable shard of EVERY rep's first leaf is fetched in one
    pipelined device_get after the dispatch loop, so reps that landed on
    other streams/devices — including sharded outputs — cannot still be
    in flight when the clock stops (ADVICE r5). Only the first array
    leaf per rep is retained (a leaf's availability implies its whole
    program ran; holding full output tuples for k reps would multiply
    device residency by the rep count), and the single batched fetch
    keeps the round-trip constant comparable to the k=1 run."""
    import jax

    single_stream = len(jax.devices()) == 1

    def _first_leaf(out):
        return next(x for x in jax.tree_util.tree_leaves(out)
                    if hasattr(x, "dtype") and getattr(x, "size", 0))

    def run(k):
        t0 = time.time()
        leaves = []
        for _ in range(k):
            out = dispatch_fn()
            if not single_stream:
                leaves.append(_first_leaf(out))
        if single_stream:
            _tiny_fetch(out)
        else:
            probes = []
            for leaf in leaves:
                shards = getattr(leaf, "addressable_shards", None) or []
                datas = [s.data for s in shards] or [leaf]
                probes.extend(d.reshape(-1)[:1] for d in datas
                              if getattr(d, "size", 0))
            jax.device_get(probes)  # one pipelined multi-shard sync
        return time.time() - t0

    run(1)  # warm any residual compile/transfer
    t1 = min(run(1) for _ in range(tries))
    tk = min(run(reps) for _ in range(tries))
    per = (tk - t1) / (reps - 1)
    if per <= 0:
        # delta within timer noise: fall back to the k=reps total, which
        # still contains one round-trip constant — a conservative UNDER-
        # estimate of the rate, never a silently absurd overestimate
        per = tk / reps
    return per


def _baseline_fuse_block(sd, loader, views, block_global, blend_range=40.0):
    """One output block fused exactly the way the reference's BlkAffineFusion
    does it, in plain host code: per view, inverse-affine coordinates,
    trilinear sample (scipy.ndimage.map_coordinates order=1), cosine-edge
    blend weight, weighted average (AVG_BLEND)."""
    import numpy as np
    from scipy.ndimage import map_coordinates

    from bigstitcher_spark_tpu.utils.geometry import (
        Interval, invert_affine, transformed_interval,
    )

    shape = block_global.shape
    acc = np.zeros(shape, np.float32)
    wsum = np.zeros(shape, np.float32)
    axes = [
        (np.arange(shape[d], dtype=np.float32) + block_global.min[d]).reshape(
            [-1 if i == d else 1 for i in range(3)])
        for d in range(3)
    ]
    for v in views:
        inv = invert_affine(sd.model(v)).astype(np.float32)
        img_dim = np.asarray(sd.view_size(v), np.float32)
        src = transformed_interval(inv, block_global).expand(1)
        img_iv = Interval.from_shape(sd.view_size(v))
        if not src.overlaps(img_iv):
            continue
        clipped = src.intersect(img_iv)
        if clipped.is_empty():
            continue
        patch = loader.read_block(v, 0, tuple(clipped.min), clipped.shape
                                  ).astype(np.float32)
        w = None
        coords = []
        for i in range(3):
            li = (inv[i, 0] * axes[0] + inv[i, 1] * axes[1]
                  + inv[i, 2] * axes[2] + inv[i, 3])  # (X,Y,Z) level coords
            coords.append(li - np.float32(clipped.min[i]))
            d = np.minimum(li, (img_dim[i] - 1.0) - li)
            ramp = 0.5 * (np.cos((1.0 - d / np.float32(blend_range)) * np.pi)
                          + 1.0)
            wi = np.where(d < 0, np.float32(0),
                          np.where(d < blend_range, ramp, np.float32(1)))
            w = wi if w is None else w * wi
        val = map_coordinates(patch, coords, order=1, mode="constant",
                              cval=0.0, output=np.float32)
        acc += val * w
        wsum += w
    fused = np.where(wsum > 0, acc / np.maximum(wsum, np.float32(1e-20)), 0.0)
    return np.clip(np.round(fused), 0, 65535).astype("uint16")


def measure_baseline(xml_path, threads=None):
    """Measure the reference-equivalent CPU fusion on the bench fixture.

    Returns voxels/sec, cached in BASELINE_MEASURED.json keyed by the fixture
    spec. ``threads`` defaults to min(8, cpu_count) — the reference's
    local[8] deployment collapses to the actual core count on small hosts."""
    if threads is None:
        threads = max(1, min(8, os.cpu_count() or 1))
    import hashlib
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    key = _fixture_key(f"fusion-threads{threads}")
    cache = _baseline_cache_load()
    ent = cache.get("fusion")
    if (ent and ent.get("key") == key and ent.get("vox_per_sec", 0) > 0
            and not _fresh_baselines()):
        return float(ent["vox_per_sec"])

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.utils.geometry import Interval
    from bigstitcher_spark_tpu.utils.grid import create_grid
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    grid = create_grid(bbox.shape, (128, 128, 64), (128, 128, 64))

    def do_block(block):
        bg = Interval.from_shape(block.size, block.offset).translate(bbox.min)
        return _baseline_fuse_block(sd, loader, views, bg)

    do_block(grid[0])  # warm the OS page cache for IO parity
    t0 = time.time()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        outs = list(pool.map(do_block, grid))
    dt = time.time() - t0
    vox = int(np.prod(bbox.shape))
    cache["fusion"] = {
        "previous_vox_per_sec": (ent or {}).get("vox_per_sec"),
        "previous_key": (ent or {}).get("key"),
        "key": key,
        "vox_per_sec": round(vox / dt, 1),
        "voxels": vox,
        "seconds": round(dt, 3),
        "threads": threads,
        "method": (
            "reference-equivalent CPU affine fusion: numpy + "
            "scipy.ndimage.map_coordinates trilinear resample, cosine-edge "
            "AVG_BLEND weights, uint16 convert, over the reference's "
            "(128,128,64) block grid; ThreadPoolExecutor(min(8, cores)) "
            "approximates the reference's Spark local[8] deployment "
            "(BASELINE.md) at this host's actual core count."
        ),
        "fixture": {k: list(v) if isinstance(v, tuple) else v
                    for k, v in FIXTURE_SPEC.items()},
        "cpu_count": os.cpu_count(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "checksum_block0": hashlib.sha256(outs[0].tobytes()).hexdigest()[:16],
    }
    _baseline_cache_store(cache)
    return vox / dt


def _np_phasecorr_pair(a, b, n_peaks=5, min_overlap=32.0):
    """Reference-equivalent CPU pairwise stitching kernel: zero-padded FFT
    phase correlation, top-N peak extraction, per-peak wrap disambiguation
    (2^3 variants) scored by true Pearson cross-correlation of the shifted
    overlap (PairwiseStitching role, SparkPairwiseStitching.java:247-267)."""
    import numpy as np
    from scipy.ndimage import maximum_filter

    shp = tuple(1 << int(np.ceil(np.log2(max(sa, sb, 1))))
                for sa, sb in zip(a.shape, b.shape))
    pa = np.zeros(shp, np.float32)
    pb = np.zeros(shp, np.float32)
    pa[tuple(slice(0, s) for s in a.shape)] = a
    pb[tuple(slice(0, s) for s in b.shape)] = b
    fa = np.fft.rfftn(pa)
    fb = np.fft.rfftn(pb)
    cross = fa * np.conj(fb)
    pcm = np.fft.irfftn(cross / np.maximum(np.abs(cross), 1e-10), s=shp,
                        axes=tuple(range(len(shp))))
    loc = (pcm == maximum_filter(pcm, size=3, mode="wrap"))
    flat = np.where(loc.ravel(), pcm.ravel(), -np.inf)
    top = np.argsort(flat)[-n_peaks:][::-1]
    peaks = np.stack(np.unravel_index(top, shp), axis=-1)

    best_r, best_s = -1.0, np.zeros(3)
    for p in peaks:
        for wrap in range(8):
            s = np.array([
                p[d] - (shp[d] if (wrap >> d) & 1 else 0) for d in range(3)
            ], np.int64)
            lo = np.maximum(0, s)
            hi = np.minimum(np.array(a.shape), np.array(b.shape) + s)
            if np.any(hi - lo < 1) or np.prod(hi - lo) < min_overlap:
                continue
            av = a[tuple(slice(lo[d], hi[d]) for d in range(3))]
            bv = b[tuple(slice(lo[d] - s[d], hi[d] - s[d]) for d in range(3))]
            am, bm = av - av.mean(), bv - bv.mean()
            den = np.sqrt((am * am).sum() * (bm * bm).sum())
            r = float((am * bm).sum() / den) if den > 0 else -1.0
            if r > best_r:
                best_r, best_s = r, s.astype(np.float64)
    return best_s, best_r


def measure_phasecorr_baseline(jobs):
    """CPU pairs/sec over the fixture's overlap crops (kernel work only;
    crop extraction excluded for both sides)."""
    cache = _baseline_cache_load()
    key = _fixture_key("phasecorr")
    ent = cache.get("phasecorr")
    if (ent and ent.get("key") == key and ent.get("pairs_per_sec", 0) > 0
            and not _fresh_baselines()):
        return float(ent["pairs_per_sec"])
    _np_phasecorr_pair(jobs[0].crop_a, jobs[0].crop_b)  # warm numpy/scipy
    dt = float("inf")
    for _ in range(3):  # best-of-3 both sides: damp shared-host noise
        t0 = time.time()
        for j in jobs:
            _np_phasecorr_pair(j.crop_a, j.crop_b)
        dt = min(dt, time.time() - t0)
    cache["phasecorr"] = {
        "previous_pairs_per_sec": (ent or {}).get("pairs_per_sec"),
        "previous_key": (ent or {}).get("key"),
        "key": key,
        "pairs_per_sec": round(len(jobs) / dt, 3),
        "pairs": len(jobs),
        "seconds": round(dt, 3),
        "method": (
            "reference-equivalent CPU pairwise stitching: numpy rfftn phase "
            "correlation (power-of-two padding), scipy maximum_filter top-5 "
            "peaks, 8 wrap variants per peak scored by Pearson r of the "
            "shifted overlap. Same crops as the TPU kernel."
        ),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    _baseline_cache_store(cache)
    return len(jobs) / dt


def _spans_snapshot():
    from bigstitcher_spark_tpu import profiling

    return {k: {"count": s.count, "total_s": round(s.total_s, 3),
                "max_s": round(s.max_s, 3), "min_s": round(s.min_s, 3)}
            for k, s in profiling.get().stats().items()}


def _io_baseline():
    """Snapshot of the shared observe.metrics registry (the same registry
    the production drivers feed — chunk IO bytes by implementation path,
    h2d/d2h transfer bytes), for per-run deltas."""
    from bigstitcher_spark_tpu.observe import metrics

    return metrics.get_registry().snapshot()


def _io_snapshot(baseline):
    """This run's IO/transfer byte deltas (registry counters that moved)."""
    from bigstitcher_spark_tpu.observe import metrics

    delta = metrics.get_registry().snapshot_delta(baseline)
    return {k: (int(v) if float(v).is_integer() else round(float(v), 3))
            for k, v in delta.items()
            if k.startswith(("bst_io_", "bst_xfer_", "bst_chunk_cache_",
                             "bst_tile_cache_", "bst_inflight_",
                             "bst_pair_", "bst_trace_", "bst_epilogue_",
                             "bst_serve_", "bst_compiled_fn_", "bst_dag_"))
            and isinstance(v, (int, float)) and v}


def _best_timed(n, fn):
    """Run ``fn`` n times under span profiling; return (best_dt, result,
    spans, io) of the fastest run (same span schema as the fusion measure;
    ``io`` is the run's observe.metrics byte-counter delta). Profiling is
    always disabled on exit, even if ``fn`` raises.

    The CPU baselines run unprofiled; the asymmetry is accepted because the
    recorder costs one mutex + clock read per span and these runs have only
    a handful of spans (measured: best-of-5 stitching throughput identical
    to within noise with profiling on vs off on this host), matching the
    fusion measure's existing behavior."""
    from bigstitcher_spark_tpu import profiling

    best_dt, best_res, spans, io = float("inf"), None, {}, {}
    try:
        for _ in range(n):
            profiling.enable(True)
            profiling.get().reset()
            iob = _io_baseline()
            t0 = time.time()
            res = fn()
            dt = time.time() - t0
            if dt < best_dt:
                best_dt, best_res, spans = dt, res, _spans_snapshot()
                io = _io_snapshot(iob)
    finally:
        profiling.enable(False)
    return best_dt, best_res, spans, io


def _stitch_jobs(xml_path):
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.stitching import (
        StitchingParams, _extract_pair_job, build_groups, plan_pairs,
    )

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    params = StitchingParams()
    groups = build_groups(sd, sd.view_ids())
    pairs = plan_pairs(sd, groups)
    jobs = []
    for ga, gb, ov in pairs:
        j = _extract_pair_job(sd, loader, ga, gb, ov, params)
        if j is not None:
            jobs.append(j)
    return sd, jobs, params


def measure_phasecorr(xml_path):
    """TPU (or fallback-CPU XLA) pairs/sec on the same crops, steady state.
    Uses the production ``stitch_jobs`` pipeline: shape buckets group into
    memory-bounded segments, each drained by ONE pipelined fetch, with
    host refinement of segment k overlapping the device FFTs of k+1."""
    from bigstitcher_spark_tpu.models.stitching import stitch_jobs

    sd, jobs, params = _stitch_jobs(xml_path)

    stitch_jobs(sd, jobs, params)  # compile
    # best-of-3, matching the baseline's treatment
    dt, results, spans, io = _best_timed(
        3, lambda: stitch_jobs(sd, jobs, params))
    cpu = measure_phasecorr_baseline(jobs)
    return {
        "metric": "phasecorr_pairs_per_sec",
        "value": round(len(results) / dt, 3),
        "unit": "pair/s",
        "pairs": len(results),
        "vs_baseline": round(len(results) / dt / cpu, 3),
        "baseline_pairs_per_sec": round(cpu, 3),
        "spans": spans,
        "io": io,
    }


def measure_phasecorr_kernel(xml_path):
    """Device-resident phase correlation: the production PCM program
    (rfftn x2, normalized cross-power, irfftn, wrapped separable local-max,
    top-P peak extraction — ops/phasecorr.pcm_peaks_batch, the same program
    ``stitch_jobs`` dispatches) timed with the padded pair stacks already
    in HBM and only the small peak tables leaving the device. End-to-end
    stitching through the axon tunnel pays crop h2d on a shared wire; this
    isolates the framework's device compute rate (counterpart of
    affine_fusion_kernel_voxels_per_sec for the stitching stage). The
    baseline pairs/s is the full CPU pipeline (FFTs + Pearson refinement);
    the note records that the device program excludes the host refinement
    tail, which measure_phasecorr prices in."""
    import numpy as np

    import jax

    from bigstitcher_spark_tpu.models.stitching import _fft_shape
    from bigstitcher_spark_tpu.ops.phasecorr import pad_to, pcm_peaks_batch

    sd, jobs, params = _stitch_jobs(xml_path)
    buckets: dict[tuple, list] = {}
    for j in jobs:
        shp = tuple(_fft_shape(np.maximum(j.crop_a.shape, j.crop_b.shape)))
        buckets.setdefault(shp, []).append(j)
    shp, bjobs = max(buckets.items(), key=lambda kv: len(kv[1]))
    a = jax.device_put(np.stack([pad_to(j.crop_a, shp) for j in bjobs]))
    b = jax.device_put(np.stack([pad_to(j.crop_b, shp) for j in bjobs]))
    ea = jax.device_put(
        np.stack([np.array(j.crop_a.shape, np.int32) for j in bjobs]))
    eb = jax.device_put(
        np.stack([np.array(j.crop_b.shape, np.int32) for j in bjobs]))
    for arr in (a, b, ea, eb):  # force residency (h2d is async under axon)
        _tiny_fetch(arr)
    per_rep = _kernel_rate(
        lambda: pcm_peaks_batch(a, b, ea, eb, params.peaks_to_check, 0.25),
        reps=20)
    # CPU baseline over the SAME pair subset (buckets have different
    # orientations/costs, so the all-pairs baseline is a different
    # workload); measured inline so the all-pairs cache entry stays clean
    _np_phasecorr_pair(bjobs[0].crop_a, bjobs[0].crop_b)  # warm
    cpu_dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        for j in bjobs:
            _np_phasecorr_pair(j.crop_a, j.crop_b)
        cpu_dt = min(cpu_dt, time.time() - t0)
    cpu = len(bjobs) / cpu_dt
    value = len(bjobs) / per_rep
    return {
        "metric": "phasecorr_kernel_pairs_per_sec",
        "value": round(value, 3),
        "unit": "pair/s",
        "pairs": len(bjobs),
        "fft_shape": list(shp),
        "vs_baseline": round(value / cpu, 3),
        "baseline_pairs_per_sec": round(cpu, 3),
        "sync_methodology": _SYNC_METHODOLOGY,
        "note": ("pair stacks in HBM, dispatch+compute only, largest FFT "
                 "bucket; baseline is the full CPU pipeline incl. host "
                 "Pearson refinement over the SAME pairs (all pairs priced "
                 "end-to-end by phasecorr_pairs_per_sec)"),
    }


def measure_dog_baseline(xml_path):
    """CPU DoG detection vox/sec: scipy gaussian blurs, subtraction,
    3^3 local maxima, threshold, quadratic subpixel fit. Intensity bounds
    are explicit (0, 65535) on both sides — the reference makes
    --minIntensity/--maxIntensity REQUIRED options
    (SparkInterestPointDetection.java:140-144)."""
    import numpy as np

    # one measurement per process: measure_dog AND measure_dog_kernel both
    # need this number; re-measuring would burn ~3 full-volume CPU passes
    # and rotate the cache's previous_vox_per_sec cross-run history onto a
    # same-run intermediate
    if "dog" in _RUN_BASELINES:
        return _RUN_BASELINES["dog"]
    cache = _baseline_cache_load()
    key = _fixture_key("dog-explicit-minmax")
    ent = cache.get("dog")
    if (ent and ent.get("key") == key and ent.get("vox_per_sec", 0) > 0
            and not _fresh_baselines()):
        return float(ent["vox_per_sec"])

    from scipy.ndimage import gaussian_filter, maximum_filter

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, _ViewPlan,
    )
    from bigstitcher_spark_tpu.ops.dog import DOG_K

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    params = DetectionParams()
    s1, s2 = params.sigma, params.sigma * DOG_K

    def one_pass():
        total_vox = 0
        t_total = 0.0
        n_spots = 0
        for v in sd.view_ids():
            plan = _ViewPlan(loader, v, params.downsampling)
            # the timed region includes the volume read: the TPU side's
            # detect_interest_points also pays its IO inside the measurement
            t0 = time.time()
            img = plan.read_det_block(loader, (0, 0, 0), plan.det_dims)
            lo, hi = 0.0, 65535.0
            norm = (img - lo) / max(hi - lo, 1e-20)
            g1 = gaussian_filter(norm, s1, mode="nearest")
            g2 = gaussian_filter(norm, s2, mode="nearest")
            dog = (g1 - g2) / (DOG_K - 1.0)
            is_max = (dog == maximum_filter(dog, size=3, mode="nearest"))
            cand = is_max & (dog > params.threshold / 2)
            pts = np.argwhere(cand)
            for p in pts:  # quadratic subpixel refinement per spot
                if np.any(p == 0) or np.any(p == np.array(dog.shape) - 1):
                    continue
                for d in range(3):
                    lo_i = tuple(p + np.eye(3, dtype=int)[d] * -1)
                    hi_i = tuple(p + np.eye(3, dtype=int)[d])
                    _ = 0.5 * (dog[lo_i] - dog[hi_i])
            n_spots += len(pts)
            t_total += time.time() - t0
            total_vox += int(np.prod(plan.det_dims))
        return total_vox, t_total, n_spots

    # untimed warm pass: the candidate side gets an explicit warm call
    # before ITS best-of-3, so the baseline must not pay the cold page
    # cache in its first timed pass (asymmetry behind a 6x cross-run
    # baseline swing flagged by baseline_drift_flags)
    for v in sd.view_ids():
        plan = _ViewPlan(loader, v, params.downsampling)
        plan.read_det_block(loader, (0, 0, 0), plan.det_dims)
    total_vox, t_total, n_spots = one_pass()
    for _ in range(2):  # best-of-3 both sides: damp shared-host noise
        tv, tt, ns = one_pass()
        if tt < t_total:
            total_vox, t_total, n_spots = tv, tt, ns
    cache["dog"] = {
        "previous_vox_per_sec": (ent or {}).get("vox_per_sec"),
        "previous_key": (ent or {}).get("key"),
        "key": key,
        "vox_per_sec": round(total_vox / t_total, 1),
        "voxels": total_vox,
        "spots": int(n_spots),
        "seconds": round(t_total, 3),
        "method": (
            "reference-equivalent CPU DoG detection: scipy gaussian_filter "
            "x2 (computeSigmas), subtraction, 3^3 maximum_filter extrema, "
            "threshold, per-spot quadratic subpixel probe. Volume read "
            "included in the timed region (the TPU side pays its IO too); "
            "same detection-resolution volumes as the TPU path; explicit "
            "minIntensity=0/maxIntensity=65535 both sides (required "
            "options in the reference)."
        ),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    _baseline_cache_store(cache)
    _RUN_BASELINES["dog"] = total_vox / t_total
    return _RUN_BASELINES["dog"]


def measure_dog(xml_path):
    import numpy as np

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, _ViewPlan, detect_interest_points,
    )

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    params = DetectionParams(min_intensity=0.0, max_intensity=65535.0)
    total_vox = sum(
        int(np.prod(_ViewPlan(loader, v, params.downsampling).det_dims))
        for v in views)
    detect_interest_points(sd, loader, views, params, progress=False)  # warm
    # best-of-3, matching the baseline's treatment
    dt, dets, spans, io = _best_timed(
        3, lambda: detect_interest_points(sd, loader, views, params,
                                          progress=False))
    cpu = measure_dog_baseline(xml_path)
    n_spots = sum(len(d.points) for d in dets)
    return {
        "metric": "dog_detection_vox_per_sec",
        "value": round(total_vox / dt, 1),
        "unit": "voxel/s",
        "spots": int(n_spots),
        "vs_baseline": round(total_vox / dt / cpu, 3),
        "baseline_vox_per_sec": round(cpu, 1),
        "spans": spans,
        "io": io,
    }


def measure_dog_kernel(xml_path):
    """Device-resident DoG detection: the production device program
    (on-device pool-by-``rel`` + normalization, Toeplitz/FFT blurs,
    separable extrema, top-K compaction, vectorized quadratic subpixel —
    the same kernel ``detect_interest_points`` dispatches through
    ``_make_dog_kernel``) timed with its haloed level-res input blocks
    already in HBM and only the compacted (K,3)+(K,) outputs leaving the
    device. End-to-end detection through the axon tunnel pays block h2d on
    a shared wire; this isolates the framework's device compute rate
    (counterpart of affine_fusion_kernel_voxels_per_sec for the detection
    stage; reference device work: SparkInterestPointDetection.java:552-568)."""
    import numpy as np

    import jax

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, _ViewPlan, _make_dog_kernel,
    )
    from bigstitcher_spark_tpu.ops.dog import dog_halo
    from bigstitcher_spark_tpu.utils.grid import create_grid

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    params = DetectionParams(min_intensity=0.0, max_intensity=65535.0)
    halo = dog_halo(params.sigma)
    bs = tuple(int(b) for b in params.block_size)

    # bucket by geometry FIRST (mirrors detect_interest_points' shape/rel
    # bucketing), then read + stage only the winning bucket's haloed
    # level-res blocks (native dtype) — losing buckets are never read
    buckets: dict[tuple, list] = {}  # (lvl shape, rel) -> [(plan, off, core_vox)]
    for v in views:
        plan = _ViewPlan(loader, v, params.downsampling)
        for blk in create_grid(plan.det_dims, bs):
            off = [int(o) - halo for o in blk.offset]
            shape = tuple((int(s) + 2 * halo) * r
                          for s, r in zip(blk.size, plan.rel))
            buckets.setdefault((shape, plan.rel), []).append(
                (plan, off, int(np.prod(blk.size))))
    (shape, rel), picked = max(buckets.items(), key=lambda kv: len(kv[1]))
    blocks = []
    for plan, off, core in picked:
        raw = plan.read_raw_block(
            loader, off, [s // r for s, r in zip(shape, rel)])
        if raw.dtype.byteorder == ">":
            raw = raw.astype(raw.dtype.newbyteorder("="))
        blocks.append((raw[None], np.array(off, np.int32)[None], core))
    kernel = _make_dog_kernel(1, params, rel)
    # production per-device packing: run_sharded_batches groups
    # max(1, batch_size // prod(rel)) blocks per batch-axis dispatch
    # (models/detection.py per_dev scaling)
    per_dev = max(1, params.batch_size // int(np.prod(rel)))
    dev = []
    for i in range(0, len(blocks), per_dev):
        grp = blocks[i:i + per_dev]
        dev.append((jax.device_put(np.concatenate([b for b, _, _ in grp])),
                    jax.device_put(np.concatenate([o for _, o, _ in grp])),
                    np.full(len(grp), params.min_intensity, np.float32),
                    np.full(len(grp), params.max_intensity, np.float32),
                    np.full(len(grp), params.threshold, np.float32)))
    core_vox = sum(cv for _, _, cv in blocks)
    for b, o, lo, hi, thr in dev:  # warm compiles + force input residency
        _tiny_fetch(kernel(b, lo, hi, thr, o))

    def _dispatch_all():
        out = None
        for b, o, lo, hi, thr in dev:
            out = kernel(b, lo, hi, thr, o)
        return out

    per_rep = _kernel_rate(_dispatch_all, reps=10)
    cpu = measure_dog_baseline(xml_path)
    value = core_vox / per_rep
    return {
        "metric": "dog_kernel_voxels_per_sec",
        "value": round(value, 1),
        "unit": "voxel/s",
        "blocks": len(blocks),
        "blocks_per_dispatch": per_dev,
        "vs_baseline": round(value / cpu, 3),
        "baseline_vox_per_sec": round(cpu, 1),
        "sync_methodology": _SYNC_METHODOLOGY,
        "note": ("haloed level-res blocks in HBM, compacted top-K outputs "
                 "only; dispatch+compute, production per-device batch "
                 "packing; baseline includes its volume read (it prices "
                 "the full CPU stage — see dog_detection_vox_per_sec for "
                 "the like-for-like end-to-end comparison)"),
    }


def measure_kernel_only(xml_path):
    """Steady-state fusion with tiles resident in HBM and the output left on
    device: the framework's compute rate with the tunnel out of the picture
    (tiles are uploaded ONCE, outside the timed loop; each rep re-dispatches
    the compiled program). Also measures the wire: one timed D2H of the
    fused output."""
    import numpy as np

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models import affine_fusion as AF
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    cp = AF.plan_composite_volume(sd, loader, views, bbox, None,
                                  AF.BlendParams())
    assert cp is not None, "bench fixture must take the device path"
    tiles = AF.upload_composite_tiles(loader, cp)
    for tl in tiles:  # force residency: h2d is async under axon
        _tiny_fetch(tl)

    def _dispatch():
        return AF.dispatch_composite(cp, tiles, "AVG_BLEND", "uint16", False,
                                     0.0, 65535.0)

    t0 = time.time()
    out = _dispatch()
    _tiny_fetch(out)  # materialized: reused below for the wire timing
    first = time.time() - t0  # compile + first true execution + round-trip
    per_run = _kernel_rate(_dispatch, reps=10)
    vox = int(np.prod(bbox.shape))
    t0 = time.time()
    host = np.asarray(out)
    d2h_s = time.time() - t0
    return {
        "metric": "affine_fusion_kernel_voxels_per_sec",
        "value": round(vox / per_run, 1),
        "unit": "voxel/s",
        "sync_methodology": _SYNC_METHODOLOGY,
        "note": ("tiles in HBM, output on device, dispatch+compute only; "
                 "first(compile)={:.2f}s".format(first)),
        "wire_d2h_mb_per_sec": round(host.nbytes / d2h_s / 1e6, 1),
        "wire_d2h_bytes": int(host.nbytes),
    }


# isotropic 2x chain: the pyramid adds 1/8 + 1/64 ~= 14% extra voxels/wire
# bytes where the pre-epilogue flow re-read 100% of full res from disk
FUSION_PYRAMID_STEPS = [[1, 1, 1], [2, 2, 2], [4, 4, 4]]


def measure_fusion_pyramid(xml_path):
    """Fusion with the fused multiscale epilogue: full res + the whole
    downsample pyramid computed in HBM and shipped in ONE drain, vs the
    baseline fusion+downsample sequence (reference-equivalent numpy
    fusion, then numpy mean downsampling that re-reads the stored
    full-res container — the exact flow the epilogue eliminates).

    The headline ``value`` stays the FULL-RES-ONLY rate and the pyramid
    voxels are reported separately (``vox_per_sec_incl_pyramid``), so the
    epilogue can neither masquerade as a kernel regression (extra voxels
    hidden in the same wall clock) nor inflate the kernel rate."""
    import numpy as np

    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.io.container import (
        create_fusion_container, read_container_meta)
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.affine_fusion import (
        fuse_volume, pyramid_from_mr)
    from bigstitcher_spark_tpu.models.downsample_driver import (
        downsample_pyramid_level, read_padded)
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    out = os.path.join(FIXTURE, "fused_pyramid.ome.zarr")

    def make_container(path):
        shutil.rmtree(path, ignore_errors=True)
        create_fusion_container(
            path, StorageFormat.ZARR, xml_path, 1, 1, bbox,
            data_type="uint16", block_size=(128, 128, 64),
            downsamplings=FUSION_PYRAMID_STEPS,
            min_intensity=0.0, max_intensity=65535.0)
        store = ChunkStore.open(path)
        return store, read_container_meta(store).mr_infos[0]

    def run():
        store, mr = make_container(out)
        ds = store.open_dataset(mr[0].dataset.strip("/"))
        pyr = pyramid_from_mr(store, mr)
        stats = fuse_volume(
            sd, loader, views, ds, bbox, block_size=(128, 128, 64),
            block_scale=(2, 2, 1), fusion_type="AVG_BLEND",
            out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
            zarr_ct=(0, 0), pyramid=pyr)
        # levels a (sharded) epilogue could not align fall back to the
        # container-reread driver, exactly like the CLI
        for lvl in range(1 + stats.pyramid_levels, len(mr)):
            downsample_pyramid_level(store, mr[lvl - 1], mr[lvl], True,
                                     (0, 0))
        return store, mr, stats

    run()  # warm compiles
    # best-of-5, the primary metric's convention: shared-host IO weather
    # swings the write-bound runs ~30% window to window
    dt, (store, mr, stats), spans, io = _best_timed(5, run)
    vox = int(np.prod(bbox.shape))
    pyr_vox = sum(int(np.prod([int(v) for v in m.dimensions[:3]]))
                  for m in mr[1:])

    # baseline downsample leg: re-read the stored full-res container,
    # numpy reshape-mean each level, round/clip, write — measured on a
    # scratch container seeded (untimed) with the fused s0
    bstore, bmr = make_container(os.path.join(FIXTURE,
                                              "baseline_pyramid.ome.zarr"))
    s0 = store.open_dataset(mr[0].dataset.strip("/")).read_full()
    prev_ds = bstore.open_dataset(bmr[0].dataset.strip("/"))
    prev_ds.write(s0, (0,) * 5)
    t0 = time.time()
    for lvl in range(1, len(bmr)):
        rel = [int(v) for v in bmr[lvl].relativeDownsampling[:3]]
        dims = [int(v) for v in bmr[lvl].dimensions[:3]]

        def read3d(off, size, _p=prev_ds):
            return _p.read((*off, 0, 0), (*size, 1, 1))[..., 0, 0]

        needed = [d * f for d, f in zip(dims, rel)]
        x = read_padded(read3d, prev_ds.shape[:3], (0, 0, 0),
                        needed).astype(np.float32)
        for ax, f in enumerate(rel):
            if int(f) == 1:
                continue
            shp = list(x.shape)
            shp[ax] //= int(f)
            shp.insert(ax + 1, int(f))
            x = x.reshape(shp).mean(axis=ax + 1)
        ds_l = bstore.open_dataset(bmr[lvl].dataset.strip("/"))
        ds_l.write(np.clip(np.round(x), 0, 65535).astype(np.uint16)
                   [..., None, None], (0,) * 5)
        prev_ds = ds_l
    base_ds_s = time.time() - t0
    if "fusion" not in _RUN_BASELINES:
        _RUN_BASELINES["fusion"] = measure_baseline(xml_path)
    base_fusion_s = vox / _RUN_BASELINES["fusion"]
    base_total_s = base_fusion_s + base_ds_s
    return {
        "metric": "affine_fusion_pyramid_vox_per_sec",
        "value": round(vox / dt, 1),
        "unit": "voxel/s",
        "note": ("fusion + full multiscale pyramid in one device drain; "
                 "value is the FULL-RES-ONLY rate, pyramid voxels "
                 "reported separately"),
        "epilogue_levels": stats.pyramid_levels,
        "pyramid_voxels": pyr_vox,
        "vox_per_sec_incl_pyramid": round((vox + pyr_vox) / dt, 1),
        "vs_baseline": round(base_total_s / dt, 3),
        "baseline_seconds": {"fusion": round(base_fusion_s, 3),
                             "downsample_reread": round(base_ds_s, 3)},
        "baseline_provenance": (
            "same-run numpy fusion rate + same-run numpy container-reread "
            "downsample chain on this host"),
        "spans": spans,
        "io": io,
    }


def measure_pipeline(xml_path):
    """Staged vs streamed stage-DAG execution of the same workload
    (resave -> create -> affine-fusion -> downsample -> detect):

    - **staged** runs the five one-shot CLI commands in sequence with
      real containers between stages, clearing the decoded-chunk cache
      between commands so the leg prices what users actually run — one
      process per stage, cold caches each (the in-process invocation
      would otherwise smuggle the chunk cache across stages and
      understate the container round-trip);
    - **streamed** runs the identical commands through `bst pipeline`
      (dag/executor.py): consumers start on block completion, blocks
      hand over through the decoded-chunk cache, and the resaved
      intermediate is elided to a memory:// root.

    Reported: both wall clocks, the staged leg's consumer-stage
    container-read bytes (the round trip the executor attacks), and the
    streamed leg's elided-vs-reread byte split from the `bst_dag_*`
    counters (ROADMAP item 2's >=90%-elision acceptance bar)."""
    from bigstitcher_spark_tpu.dag import run_pipeline
    from bigstitcher_spark_tpu.dag.executor import _invoke_tool
    from bigstitcher_spark_tpu.io.chunkcache import get_cache
    from bigstitcher_spark_tpu.observe import metrics as _om

    def run_tool(args):
        rc = _invoke_tool(args[0], args[1:])
        if rc:
            raise RuntimeError(f"bst {' '.join(args)} exited {rc}")

    def stage_cmds(root, xml):
        rexml = os.path.join(root, "bench-pipeline-resaved.xml")
        resaved = os.path.join(root, "bench-pipeline-resaved.n5")
        fused = os.path.join(root, "bench-pipeline-fused.n5")
        return rexml, resaved, fused, [
            ["resave", "-x", xml, "-xo", rexml, "-o", resaved, "--N5"],
            ["create-fusion-container", "-x", rexml, "-o", fused,
             "-s", "N5", "-d", "UINT16", "--minIntensity", "0",
             "--maxIntensity", "65535"],
            ["affine-fusion", "-o", fused],
            ["downsample", "-i", fused, "-di", "ch0tp0/s0",
             "-ds", "2,2,1"],
            ["detect-interestpoints", "-x", rexml, "-l", "beads",
             "-s", "1.8", "-t", "0.008", "-dsxy", "1", "-dsz", "1"],
        ]

    def read_bytes_snapshot():
        # real container decodes only: the path="cache" series is bytes
        # served by the in-process chunk cache, which a process-per-stage
        # run would ALSO serve from memory within one stage — counting it
        # would inflate the round trip streaming is credited with killing
        return sum(v for k, v in _om.get_registry().snapshot().items()
                   if k.startswith("bst_io_read_bytes_total")
                   and '"cache"' not in k)

    # -- staged leg: one-shot CLIs, containers between stages --------------
    staged_root = os.path.join(FIXTURE, "pipeline-staged")
    shutil.rmtree(staged_root, ignore_errors=True)
    os.makedirs(staged_root, exist_ok=True)
    _, resaved, _, cmds = stage_cmds(staged_root, xml_path)
    t0 = time.time()
    consumer_reads = 0
    for i, cmd in enumerate(cmds):
        get_cache().clear()       # process-per-stage: no cross-stage cache
        before = read_bytes_snapshot()
        run_tool(cmd)
        if i >= 2:                # fuse / downsample / detect re-read
            consumer_reads += read_bytes_snapshot() - before
    staged_s = time.time() - t0

    # -- streamed leg: the DAG executor on an identical spec ---------------
    streamed_root = os.path.join(FIXTURE, "pipeline-streamed")
    shutil.rmtree(streamed_root, ignore_errors=True)
    os.makedirs(streamed_root, exist_ok=True)
    rexml, resaved, fused, _ = stage_cmds(streamed_root, xml_path)
    spec = {
        "name": "bench-streamed",
        "datasets": {"resaved": {"path": resaved, "ephemeral": True},
                     "fused": {"path": fused}},
        "stages": [
            {"id": "resave", "tool": "resave",
             "args": ["-x", xml_path, "-xo", rexml, "-o", "@resaved",
                      "--N5"],
             "writes": ["resaved"]},
            {"id": "create", "tool": "create-fusion-container",
             "args": ["-x", rexml, "-o", "@fused", "-s", "N5",
                      "-d", "UINT16", "--minIntensity", "0",
                      "--maxIntensity", "65535"],
             "after": ["resave"]},
            {"id": "fuse", "tool": "affine-fusion",
             "args": ["-o", "@fused"],
             "after": ["create"], "reads": ["resaved"],
             "writes": ["fused"]},
            {"id": "downsample", "tool": "downsample",
             "args": ["-i", "@fused", "-di", "ch0tp0/s0", "-ds", "2,2,1"],
             "reads": ["fused"], "writes": ["fused"]},
            {"id": "detect", "tool": "detect-interestpoints",
             "args": ["-x", rexml, "-l", "beads", "-s", "1.8",
                      "-t", "0.008", "-dsxy", "1", "-dsz", "1"],
             "after": ["resave"], "reads": ["resaved"]},
        ],
    }
    get_cache().clear()
    iob = _io_baseline()
    t0 = time.time()
    res = run_pipeline(spec, workdir=streamed_root)
    streamed_s = time.time() - t0
    io = _io_snapshot(iob)
    summary = res.to_dict()
    assert summary["ok"], summary
    elided = summary["bytes_elided"]
    reread = summary["bytes_reread"]
    elision_pct = round(100.0 * elided / max(elided + reread, 1), 2)

    # -- handoff leg: the same streamed spec with the HBM handoff cache
    # enabled (BST_DAG_HANDOFF_BYTES): producer blocks reach same-mesh
    # consumers as DEVICE arrays — no drain D2H, no host-LRU hop — with
    # the identical per-rep cache clear so the legs differ by exactly the
    # one knob
    handoff_root = os.path.join(FIXTURE, "pipeline-handoff")
    shutil.rmtree(handoff_root, ignore_errors=True)
    os.makedirs(handoff_root, exist_ok=True)
    rexml_h, resaved_h, fused_h, _ = stage_cmds(handoff_root, xml_path)
    spec_h = json.loads(json.dumps(spec).replace(streamed_root,
                                                 handoff_root))
    get_cache().clear()
    iob_h = _io_baseline()
    os.environ["BST_DAG_HANDOFF_BYTES"] = str(1 << 30)
    try:
        t0 = time.time()
        res_h = run_pipeline(spec_h, workdir=handoff_root)
        handoff_s = time.time() - t0
    finally:
        os.environ.pop("BST_DAG_HANDOFF_BYTES", None)
    io_h = _io_snapshot(iob_h)
    summary_h = res_h.to_dict()
    assert summary_h["ok"], summary_h
    assert summary_h["blocks_handoff"] > 0, summary_h

    return {
        "metric": "pipeline_staged_over_streamed",
        "value": round(staged_s / max(streamed_s, 1e-9), 3),
        "unit": "x",
        "note": ("same resave->create->fuse->downsample->detect workload "
                 "as five one-shot CLIs with containers between stages "
                 "(cache cleared per stage = process-per-stage flow) vs "
                 "one streamed `bst pipeline` run with the resaved "
                 "intermediate elided to memory; the handoff leg re-runs "
                 "the streamed spec with BST_DAG_HANDOFF_BYTES=1G so "
                 "same-mesh edges hand blocks over device-resident"),
        "staged_seconds": round(staged_s, 3),
        "streamed_seconds": round(streamed_s, 3),
        "handoff_seconds": round(handoff_s, 3),
        "streamed_over_handoff": round(streamed_s / max(handoff_s, 1e-9),
                                       3),
        "staged_consumer_read_bytes": int(consumer_reads),
        "streamed_bytes_elided": int(elided),
        "streamed_bytes_reread": int(reread),
        "elision_pct": elision_pct,
        "blocks_streamed": summary["blocks_streamed"],
        "containers_elided": summary["containers_elided"],
        "handoff_blocks": summary_h["blocks_handoff"],
        "handoff_bytes_served": summary_h["bytes_handoff"],
        "handoff_bytes_spilled": summary_h["bytes_spilled"],
        "handoff_bytes_reread": summary_h["bytes_reread"],
        "edges": summary["edges"],
        "handoff_edges": summary_h["edges"],
        "io": io,
        "io_handoff": io_h,
    }


def measure_solver(xml_path):
    """numpy vs device vs sharded global-solve wall time at growing
    synthetic tile grids (ROADMAP item 4: the last driver-side O(tiles)
    stage moved onto the mesh).

    Builds truth-consistent 8-corner stitching-style link graphs (no
    image IO — the solver's cost is the iteration, not the matches),
    then times `models.solver.relax` per backend: the host numpy
    reference, the jit-compiled device while_loop, and the psum-sharded
    layout forced on via BST_SOLVE_SHARD=1. AFFINE+RIGID regularization
    with damping 0.7 keeps the sweep count meaningfully >1 so the
    per-iteration cost dominates the compile-amortized call. Reported:
    per-grid seconds + sweep rates, the device/numpy speedup at the
    largest grid (the acceptance bar: >=1x on the CPU fallback), and the
    io/solve counter deltas."""
    import numpy as _np

    from bigstitcher_spark_tpu import config as _c
    from bigstitcher_spark_tpu.io.spimdata import ViewId
    from bigstitcher_spark_tpu.models import solver as S
    from bigstitcher_spark_tpu.ops import models as M

    def graph(n):
        rng = _np.random.default_rng(17)
        tiles = [(ViewId(0, i),) for i in range(n[0] * n[1])]
        truth = {i: _np.array([(i % n[0]) * 80.0, (i // n[0]) * 80.0, 0.0])
                 for i in range(len(tiles))}
        nom = {i: truth[i] + (rng.uniform(-3, 3, 3) if i else 0.0)
               for i in truth}
        corners = _np.array([[x, y, z] for x in (0, 100) for y in (0, 100)
                             for z in (0, 50)], float)
        links = []
        for i in range(len(tiles)):
            for j in (i + 1, i + n[0]):
                if j >= len(tiles):
                    continue
                if j == i + 1 and (i % n[0]) == n[0] - 1:
                    continue
                shift = (truth[i] - nom[i]) - (truth[j] - nom[j])
                # per-corner noise keeps the fixed point away from the
                # warm start so the solve genuinely iterates
                noise = rng.normal(0, 0.5, corners.shape)
                links.append(S.MatchLink(
                    tiles[i], tiles[j], corners, corners + shift + noise,
                    _np.full(8, 0.9)))
        return tiles, links

    import jax as _jax

    n_dev = len(_jax.local_devices())
    iob = _io_baseline()
    grids = []
    speedup = 0.0
    for n in ((12, 12), (24, 24)):
        tiles, links = graph(n)
        fixed = {tiles[0]}
        row = {"tiles": len(tiles), "links": len(links),
               "local_devices": n_dev}
        legs = [("numpy", "numpy", None),
                ("device", "device", {"BST_SOLVE_SHARD": 0})]
        if n_dev > 1:
            legs.append(("sharded", "device", {"BST_SOLVE_SHARD": 1}))
        else:
            # one local device: BST_SOLVE_SHARD=1 would silently run the
            # unsharded kernel — report the absence instead of a fake row
            row["sharded_skipped"] = "1 local device (shard_map not taken)"
        for label, backend, overrides in legs:
            params = S.SolverParams(model=M.AFFINE, regularization=M.RIGID,
                                    damping=0.7, backend=backend)
            import contextlib

            scope = (_c.overrides(overrides) if overrides
                     else contextlib.nullcontext())
            with scope:
                S.relax(links, tiles, fixed, params)  # warm/compile
                best = float("inf")
                iters = 0
                for _ in range(3):
                    t0 = time.time()
                    res = S.relax(links, tiles, fixed, params)
                    best = min(best, time.time() - t0)
                    iters = res.iterations
            row[f"{label}_s"] = round(best, 4)
            row[f"{label}_sweeps_per_s"] = round(iters / max(best, 1e-9), 1)
            row[f"{label}_iterations"] = iters
        row["device_speedup_vs_numpy"] = round(
            row["numpy_s"] / max(row["device_s"], 1e-9), 2)
        if "sharded_s" in row:
            row["sharded_speedup_vs_numpy"] = round(
                row["numpy_s"] / max(row["sharded_s"], 1e-9), 2)
        speedup = row["device_speedup_vs_numpy"]
        grids.append(row)
    return {
        "metric": "solver_device_speedup_vs_numpy",
        "value": speedup,
        "unit": "x",
        "note": ("best-of-3 relax() wall per backend on synthetic "
                 "tile-grid link graphs; device = one compiled "
                 "lax.while_loop, sharded = psum collective layout "
                 "forced via BST_SOLVE_SHARD=1; speedup at the largest "
                 "grid"),
        "grids": grids,
        "io": _io_snapshot(iob),
    }


def measure_submit_latency(xml_path):
    """Cold first-submit vs warm repeat-submit wall time through a `bst
    serve` daemon (in-process, one slot): the same affine-fusion job
    submitted twice into a container whose block size no other measure
    uses, so the first submit genuinely builds its compiled-fn bucket and
    the second genuinely reuses it — the amortized-compile + warm-cache
    win a resident daemon exists for, as a measured ratio instead of a
    claim. Reported in the io columns (`bst_serve_*` /
    `bst_compiled_fn_*` counter deltas ride along)."""
    from bigstitcher_spark_tpu.io.chunkstore import StorageFormat
    from bigstitcher_spark_tpu.io.container import create_fusion_container
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.serve import client
    from bigstitcher_spark_tpu.serve.daemon import Daemon
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    bbox = maximal_bounding_box(sd, sd.view_ids())
    out = os.path.join(FIXTURE, "served.ome.zarr")
    shutil.rmtree(out, ignore_errors=True)
    # 96x96x48 blocks: a compiled-fn bucket nothing else in this bench
    # compiles, so submit #1 is honestly cold inside this warm process
    create_fusion_container(
        out, StorageFormat.ZARR, xml_path, 1, 1, bbox,
        data_type="uint16", block_size=(96, 96, 48),
        min_intensity=0.0, max_intensity=65535.0)
    sock = os.path.join(FIXTURE, "bench-serve.sock")
    d = Daemon(sock, slots=1,
               jobs_root=os.path.join(FIXTURE, "bench-serve-jobs")).start()
    iob = _io_baseline()
    try:
        def submit_once():
            t0 = time.time()
            res = client.submit(sock, "affine-fusion", ["-o", out])
            assert res["exit_code"] == 0, res
            return time.time() - t0, res

        cold_s, cold = submit_once()
        warm_s, warm = submit_once()
    finally:
        try:
            client.shutdown(sock)
            d.wait(60)
        except Exception:
            pass
    io = _io_snapshot(iob)
    return {
        "metric": "serve_submit_warm_seconds",
        "value": round(warm_s, 3),
        "unit": "s",
        "note": ("same fusion job submitted twice through an in-process "
                 "bst serve daemon; cold pays the compiled-fn bucket "
                 "build + cache fill, warm reuses both"),
        "cold_submit_s": round(cold_s, 3),
        "warm_submit_s": round(warm_s, 3),
        "cold_over_warm": round(cold_s / max(warm_s, 1e-9), 3),
        "warm_compile_hits": warm.get("warm_compile_hits", 0),
        "cold_compile_hits": cold.get("warm_compile_hits", 0),
        "io": io,
    }


MULTITP_SPEC = {
    "n_tiles": (2, 2, 1), "tile_size": (128, 128, 64), "overlap": 32,
    "jitter": 0.0, "seed": 23, "block_size": (64, 64, 32),
    "n_beads_per_tile": 60, "n_channels": 2, "n_timepoints": 2,
}


def _slot_views(sd, c_idx, t_idx):
    """Views of the container slot (channel index, timepoint index) —
    mrInfos[c + t*numChannels] selection (SparkAffineFusion.java:426-441)."""
    channels = sorted({s.attributes.get("channel", 0)
                      for s in sd.setups.values()})
    tps = sorted(sd.timepoints)
    ch = channels[c_idx]
    tp = tps[t_idx]
    return [v for v in sd.view_ids()
            if v.timepoint == tp
            and sd.setups[v.setup].attributes.get("channel", 0) == ch]


def measure_multitp():
    """Multi-timepoint multi-channel affine fusion -> 5-D OME-ZARR
    (BASELINE.md config), all four (c,t) slots, vs the same numpy baseline
    fusion run per slot."""
    import numpy as np

    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.io.container import create_fusion_container
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
    from bigstitcher_spark_tpu.utils.geometry import Interval
    from bigstitcher_spark_tpu.utils.grid import create_grid
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    root = os.path.join(FIXTURE, "multitp")
    xml = os.path.join(root, "proj", "dataset.xml")
    if not os.path.exists(xml):
        make_synthetic_project(os.path.join(root, "proj"), **MULTITP_SPEC)
    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    bbox = maximal_bounding_box(sd, sd.view_ids())
    out = os.path.join(root, "fused.ome.zarr")
    n_ch = MULTITP_SPEC["n_channels"]
    n_tp = MULTITP_SPEC["n_timepoints"]

    def run():
        shutil.rmtree(out, ignore_errors=True)
        create_fusion_container(
            out, StorageFormat.ZARR, xml, n_tp, n_ch, bbox,
            data_type="uint16", block_size=(64, 64, 32),
            min_intensity=0.0, max_intensity=65535.0)
        ds = ChunkStore.open(out).open_dataset("0")
        for t in range(n_tp):
            for c in range(n_ch):
                fuse_volume(
                    sd, loader, _slot_views(sd, c, t), ds, bbox,
                    block_size=(64, 64, 32), block_scale=(2, 2, 1),
                    fusion_type="AVG_BLEND", out_dtype="uint16",
                    min_intensity=0.0, max_intensity=65535.0, zarr_ct=(c, t))
        return ds

    run()  # warm compiles
    # single timed run, span-profiled
    dt, ds, spans, io = _best_timed(1, run)
    vox = int(np.prod(bbox.shape)) * n_ch * n_tp

    # baseline: the same numpy fusion per slot (cached)
    cache = _baseline_cache_load()
    key = _fixture_key(f"multitp-{MULTITP_SPEC}")
    ent = cache.get("multitp")
    if (ent and ent.get("key") == key and ent.get("vox_per_sec", 0) > 0
            and not _fresh_baselines()):
        base = float(ent["vox_per_sec"])
    else:
        grid = create_grid(bbox.shape, (64, 64, 32), (64, 64, 32))
        t0 = time.time()
        for t in range(n_tp):
            for c in range(n_ch):
                vws = _slot_views(sd, c, t)
                for block in grid:
                    bg = Interval.from_shape(block.size, block.offset
                                             ).translate(bbox.min)
                    _baseline_fuse_block(sd, loader, vws, bg)
        bdt = time.time() - t0
        base = vox / bdt
        cache["multitp"] = {
            "previous_vox_per_sec": (ent or {}).get("vox_per_sec"),
            "previous_key": (ent or {}).get("key"),
            "key": key, "vox_per_sec": round(base, 1), "voxels": vox,
            "seconds": round(bdt, 3),
            "method": ("reference-equivalent numpy fusion "
                       "(_baseline_fuse_block) over all 4 (channel,"
                       "timepoint) slots of the 5-D OME-ZARR config"),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        _baseline_cache_store(cache)
    # sanity: every slot landed with data
    import numpy as _np
    for t in range(n_tp):
        for c in range(n_ch):
            blk = _np.asarray(ds.read((0, 0, 0, c, t), (32, 32, 32, 1, 1)))
            assert blk.std() > 0, f"slot c{c} t{t} empty"
    return {
        "metric": "multitp_omezarr_fusion_vox_per_sec",
        "value": round(vox / dt, 1),
        "unit": "voxel/s",
        "slots": n_ch * n_tp,
        "vs_baseline": round(vox / dt / base, 3),
        "baseline_vox_per_sec": round(base, 1),
        "spans": spans,
        "io": io,
    }


NONRIGID_SPEC = {
    "n_tiles": (2, 1, 1), "tile_size": (96, 96, 48), "overlap": 40,
    "jitter": 3.0, "seed": 13, "n_beads_per_tile": 40,
}


def _np_nonrigid_volume(sd, loader, views, unique, bbox, cpd=10.0):
    """Reference-equivalent CPU non-rigid fusion: per view, fit the
    control-point grid (shared host-side fit), then per voxel interpolate the
    12 model coefficients (scipy map_coordinates over the grid), deform the
    world coordinate, trilinear-sample the view, cosine-blend and average
    (NonRigidTools.fuseVirtualInterpolatedNonRigid role)."""
    import numpy as np
    from scipy.ndimage import map_coordinates

    from bigstitcher_spark_tpu.ops.nonrigid import fit_control_grid
    from bigstitcher_spark_tpu.utils.geometry import invert_affine

    shape = tuple(bbox.shape)
    origin = np.array(bbox.min, np.float64)
    gdims = tuple(int(np.ceil(shape[d] / cpd)) + 2 for d in range(3))
    gorigin = origin - cpd
    axes = np.meshgrid(*[np.arange(s, dtype=np.float64) for s in shape],
                       indexing="ij")
    world = np.stack([a + origin[d] for d, a in enumerate(axes)])  # (3,X,Y,Z)
    acc = np.zeros(shape, np.float64)
    wsum = np.zeros(shape, np.float64)
    for v in views:
        targets = unique.targets[v]
        vw = unique.view_world[v]
        grid = fit_control_grid(targets, vw, gorigin, gdims, cpd)  # (G...,12)
        gc = (world - gorigin[:, None, None, None]) / cpd
        coef = np.stack([
            map_coordinates(grid[..., k].astype(np.float64), gc, order=1,
                            mode="nearest")
            for k in range(12)
        ])  # (12,X,Y,Z)
        A = coef.reshape(3, 4, *shape)
        deformed = (np.einsum("ij...,j...->i...", A[:, :3], world)
                    + A[:, 3])
        inv = invert_affine(sd.model(v))
        local = (np.einsum("ij,j...->i...", inv[:, :3], deformed)
                 + inv[:, 3][:, None, None, None])
        img = loader.open(v, 0).read_full().astype(np.float64)
        val = map_coordinates(img, local, order=1, mode="constant", cval=0.0)
        dim = np.array(img.shape, np.float64)
        w = np.ones(shape)
        inside = np.ones(shape, bool)
        for d in range(3):
            dd = np.minimum(local[d], (dim[d] - 1.0) - local[d])
            ramp = 0.5 * (np.cos((1.0 - dd / 40.0) * np.pi) + 1.0)
            w = w * np.where(dd < 0, 0.0, np.where(dd < 40.0, ramp, 1.0))
            inside &= (local[d] >= 0) & (local[d] <= dim[d] - 1.0)
        w = w * inside
        acc += val * w
        wsum += w
    return np.where(wsum > 0, acc / np.maximum(wsum, 1e-20), 0.0)


def _nonrigid_setup():
    """Shared (memoized) staging for the nonrigid measures: synthesize the
    project, run detection + matching (untimed), build unique points."""
    if "nonrigid_setup" in _RUN_BASELINES:
        return _RUN_BASELINES["nonrigid_setup"]
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.interestpoints import InterestPointStore
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.detection import (
        DetectionParams, detect_interest_points, save_detections,
    )
    from bigstitcher_spark_tpu.models.matching import (
        MatchingParams, match_interest_points, save_matches,
    )
    from bigstitcher_spark_tpu.models.nonrigid_fusion import (
        build_unique_points,
    )
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    root = os.path.join(FIXTURE, "nonrigid")
    xml = os.path.join(root, "proj", "dataset.xml")
    if not os.path.exists(xml):
        make_synthetic_project(os.path.join(root, "proj"), **NONRIGID_SPEC)
    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    views = sorted(sd.registrations)
    store = InterestPointStore(os.path.join(root, "proj",
                                            "interestpoints.n5"))
    dets = detect_interest_points(
        sd, loader, views,
        DetectionParams(downsample_xy=1, downsample_z=1,
                        block_size=(96, 96, 48)),
        progress=False)
    save_detections(sd, store, dets, DetectionParams())
    mparams = MatchingParams(ransac_min_inliers=5, ransac_iterations=2000,
                             model="TRANSLATION", regularization="NONE")
    save_matches(sd, store,
                 match_interest_points(sd, views, mparams, store,
                                       progress=False),
                 mparams, views)
    unique = build_unique_points(sd, store, views, ["beads"])
    bbox = maximal_bounding_box(sd, views, None)
    _RUN_BASELINES["nonrigid_setup"] = (root, sd, loader, views, unique, bbox)
    return _RUN_BASELINES["nonrigid_setup"]


def measure_nonrigid():
    """Non-rigid fusion over the full volume (BASELINE.md config): detection
    + matching stage the correspondences (untimed), then time
    fuse_nonrigid_volume vs the numpy reference implementation."""
    import numpy as np

    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.models.nonrigid_fusion import (
        fuse_nonrigid_volume,
    )

    root, sd, loader, views, unique, bbox = _nonrigid_setup()
    out_path = os.path.join(root, "fused.n5")

    def run():
        shutil.rmtree(out_path, ignore_errors=True)
        cstore = ChunkStore.create(out_path, StorageFormat.N5)
        ds = cstore.create_dataset("fused", bbox.shape, (64, 64, 48),
                                   "float32")
        fuse_nonrigid_volume(
            sd, loader, views, unique, ds, bbox, block_size=(64, 64, 48),
            block_scale=(1, 1, 1), cpd=10.0, out_dtype="float32",
            min_intensity=0.0, max_intensity=1.0)
        return ds

    run()  # warm compiles
    # single timed run, span-profiled
    dt, ds, spans, io = _best_timed(1, run)
    vox = int(np.prod(bbox.shape))

    cache = _baseline_cache_load()
    key = _fixture_key(f"nonrigid-{NONRIGID_SPEC}")
    ent = cache.get("nonrigid")
    if (ent and ent.get("key") == key and ent.get("vox_per_sec", 0) > 0
            and not _fresh_baselines()):
        base = float(ent["vox_per_sec"])
    else:
        t0 = time.time()
        ref = _np_nonrigid_volume(sd, loader, views, unique, bbox)
        bdt = time.time() - t0
        base = vox / bdt
        # validate the XLA output against the independent implementation
        got = ds.read_full()
        diff = np.abs(got.astype(np.float64) - ref)
        assert float(np.median(diff)) < 0.02 * max(float(ref.max()), 1e-9), (
            f"nonrigid XLA disagrees with numpy baseline: "
            f"median|diff|={np.median(diff):.4f}")
        cache["nonrigid"] = {
            "previous_vox_per_sec": (ent or {}).get("vox_per_sec"),
            "previous_key": (ent or {}).get("key"),
            "key": key, "vox_per_sec": round(base, 1), "voxels": vox,
            "seconds": round(bdt, 3),
            "method": ("reference-equivalent numpy non-rigid fusion: shared "
                       "MLS control-grid fit, scipy map_coordinates "
                       "coefficient interpolation + deformation + trilinear "
                       "sampling + cosine blend (NonRigidTools role)"),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        _baseline_cache_store(cache)
    _RUN_BASELINES["nonrigid"] = base
    return {
        "metric": "nonrigid_fusion_vox_per_sec",
        "value": round(vox / dt, 1),
        "unit": "voxel/s",
        "vs_baseline": round(vox / dt / base, 3),
        "baseline_vox_per_sec": round(base, 1),
        "spans": spans,
        "io": io,
    }


def measure_nonrigid_kernel():
    """Device-resident non-rigid fusion: the production batched kernel
    (models/nonrigid_fusion._make_nonrigid_kernel — separable control-grid
    coefficient interpolation, deformation, trilinear sampling, cosine
    blend, intensity conversion) timed with its staged block inputs
    already in HBM and the fused blocks left on device — the nonrigid
    counterpart of affine_fusion_kernel_voxels_per_sec (reference device
    work: NonRigidTools.fuseVirtualInterpolatedNonRigid, called at
    SparkNonRigidFusion.java:388-402). The CPU baseline computes in
    memory (no writes), so this is compute-vs-compute."""
    import numpy as np

    import jax

    from bigstitcher_spark_tpu.models import nonrigid_fusion as NF
    from bigstitcher_spark_tpu.utils.grid import create_grid

    root, sd, loader, views, unique, bbox = _nonrigid_setup()
    compute_block, cpd, alpha = (64, 64, 48), 10.0, 1.0
    gdims = tuple(int(np.ceil(compute_block[d] / cpd)) + 3 for d in range(3))
    aniso = NF.anisotropy_transform(float("nan"))
    blend = NF.BlendParams()
    planned = []
    for block in create_grid(bbox.shape, compute_block, compute_block):
        res = NF._plan_nonrigid_block(sd, views, unique, block, bbox,
                                      compute_block, gdims, cpd, alpha,
                                      aniso)
        if res is not None:
            planned.append((block, *res))
    # production signature bucketing; largest bucket carries the rate
    buckets: dict[tuple, list] = {}
    for item in planned:
        plans = item[3]
        vb = NF.F.bucket_views(len(plans))
        pshape = NF.F.bucket_shape(
            np.max([p[3].shape for p in plans], axis=0), 32)
        buckets.setdefault((pshape, vb), []).append(item)
    (pshape, vb), items = max(buckets.items(), key=lambda kv: len(kv[1]))
    kernel = NF._make_nonrigid_kernel(1, compute_block, "AVG_BLEND",
                                      "float32")
    stacked = []
    vox = 0
    for block, block_global, grid_origin, plans in items:
        arrs = NF._stage_nonrigid(loader, plans, pshape, vb, blend, gdims)
        stacked.append((*arrs, np.asarray(block_global.min, np.float32),
                        np.asarray(grid_origin, np.float32),
                        np.full(3, cpd, np.float32)))
        vox += int(np.prod(block.size))
    dev = tuple(jax.device_put(np.stack([s[k] for s in stacked]))
                for k in range(len(stacked[0])))
    mi, ma = np.float32(0.0), np.float32(1.0)
    _tiny_fetch(kernel(mi, ma, *dev))  # warm + force input residency
    per_rep = _kernel_rate(lambda: kernel(mi, ma, *dev), reps=10)
    base = _RUN_BASELINES.get("nonrigid")
    if base is None:  # standalone invocation: measure the numpy baseline
        t0 = time.time()
        _np_nonrigid_volume(sd, loader, views, unique, bbox)
        base = int(np.prod(bbox.shape)) / (time.time() - t0)
    value = vox / per_rep
    return {
        "metric": "nonrigid_kernel_voxels_per_sec",
        "value": round(value, 1),
        "unit": "voxel/s",
        "blocks": len(items),
        "vs_baseline": round(value / base, 3),
        "baseline_vox_per_sec": round(base, 1),
        "sync_methodology": _SYNC_METHODOLOGY,
        "note": ("staged block inputs in HBM, fused blocks left on device; "
                 "dispatch+compute of the production batched kernel over "
                 "the largest signature bucket; baseline is the in-memory "
                 "numpy nonrigid fusion (no writes either side)"),
    }


def measure_tune(xml_path):
    """The closed telemetry loop as a measured ratio: `bst tune run` over
    the built-in tiny-fusion workload (1 timed execution per config, hard
    cap 3) against a scratch history store. baseline/best is >= 1.0 by
    construction — a candidate must beat the incumbent by min-gain or the
    default configuration wins with an empty override set — so the value
    reports how much headroom the autotuner found on this host, never a
    regression."""
    from bigstitcher_spark_tpu import tune

    root = os.path.join(FIXTURE, "tune-bench")
    shutil.rmtree(root, ignore_errors=True)
    hist = os.path.join(root, "history")
    os.makedirs(hist, exist_ok=True)
    wl = tune.resolve_workload("tiny-fusion", os.path.join(root, "work"))
    res = tune.autotune(wl, force_knobs=("BST_WRITE_THREADS",),
                        trials_per_config=1, max_trials=3,
                        history_dir=hist)
    speedup = res.baseline_seconds / max(res.best_seconds, 1e-9)
    return {
        "metric": "tune_speedup_vs_default",
        "value": round(speedup, 3),
        "unit": "x",
        "baseline_s": round(res.baseline_seconds, 3),
        "best_s": round(res.best_seconds, 3),
        "trials": len(res.trials),
        "rules_fired": [d.rule for d in res.diagnoses],
        "best_overrides": res.best_overrides,
        "profile_key": res.profile_key,
        "note": ("bst tune run over the tiny-fusion workload, 1 timed "
                 "execution per config (cap 3); every trial is a "
                 "tune-trial history record in the scratch store"),
    }


_MULTIHOST_WORKER = """
import hashlib, json, os, sys, time
import numpy as np
from bigstitcher_spark_tpu.parallel.distributed import init_distributed, world
init_distributed()   # no-op for the 1-process leg
from bigstitcher_spark_tpu.dag.executor import run_pipeline
from bigstitcher_spark_tpu.io.chunkstore import ChunkStore
from bigstitcher_spark_tpu.parallel import pairsched

proj = sys.argv[1]
rank, pc = world()
xml = os.path.join(proj, "dataset.xml")
rexml = os.path.join(proj, "re.xml")
spec = {
    "name": "bench-mh",
    "datasets": {
        "resaved": {"path": os.path.join(proj, "resaved.n5"),
                    "ephemeral": True},
        "fused": {"path": os.path.join(proj, "fused.n5")},
    },
    "stages": [
        {"id": "resave", "tool": "resave",
         "args": ["-x", xml, "-xo", rexml, "-o", "@resaved", "--N5",
                  "--blockSize", "32,32,16", "-ds", "1,1,1"],
         "writes": ["resaved"]},
        {"id": "create", "tool": "create-fusion-container",
         "args": ["-x", rexml, "-o", "@fused", "-s", "N5", "-d", "UINT16",
                  "--minIntensity", "0", "--maxIntensity", "65535",
                  "--blockSize", "32,32,16"],
         "after": ["resave"], "ranks": [0]},
        {"id": "fuse", "tool": "affine-fusion", "args": ["-o", "@fused"],
         "after": ["create"], "reads": ["resaved"], "writes": ["fused"]},
    ],
}
t0 = time.time()
res = run_pipeline(spec, workdir=proj)
dt = time.time() - t0
d = res.to_dict()
assert res.ok, d
# a pair stage so the leg reports per-process scheduler utilization
tasks = [pairsched.PairTask(index=i, cost=float(1 + i % 4))
         for i in range(16)]
pairsched.run_pair_tasks(
    tasks, lambda t: (time.sleep(0.002), t.index)[1], stage="bench-mh")
util = pairsched.process_util_snapshot().get("bench-mh") or {}
ds = ChunkStore.open(os.path.join(proj, "fused.n5")).open_dataset("ch0tp0/s0")
arr = ds.read((0, 0, 0), ds.shape)
print("RESULT " + json.dumps({
    "rank": rank, "world": pc, "seconds": round(dt, 3),
    "xhost_bytes": int(d.get("bytes_xhost", 0)),
    "bytes_reread": int(d.get("bytes_reread", 0)),
    "pair_util_pct": util.get("util_pct"),
    "pair_busy_s": util.get("busy_s"),
    "s0_sha": hashlib.sha256(
        np.ascontiguousarray(arr).tobytes()).hexdigest(),
}), flush=True)
"""


def measure_multihost(runs: int = 3):
    """The multi-host execution world, measured: the same streamed
    resave -> create(rank 0) -> fuse pipeline on a tiny fixture as a
    1-process run vs a REAL 2-process jax.distributed CPU world
    (subprocess workers, gloo collectives, TCP block exchange), best of
    ``runs`` each. Reports the wall ratio, the cross-host bytes/re-read
    split of the 2-process leg, per-process pair-scheduler utilization,
    and asserts bitwise fused-output parity across ranks AND legs.

    Both legs pin JAX_PLATFORMS=cpu with 4 forced host devices — the
    extra measures the execution-world overhead (collectives, exchange,
    split), not the accelerator, and a TPU tunnel cannot host two
    processes anyway."""
    import socket as _socket

    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    root = os.path.join(FIXTURE, "multihost-bench")
    worker_py = os.path.join(FIXTURE, "multihost_worker.py")
    with open(worker_py, "w") as f:
        f.write(_MULTIHOST_WORKER)

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    def mk_proj(path):
        shutil.rmtree(path, ignore_errors=True)
        make_synthetic_project(path, n_tiles=(2, 1, 1),
                               tile_size=(64, 64, 32), overlap=16,
                               jitter=1.0, n_beads_per_tile=20, seed=7)

    def base_env():
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    "PYTHONPATH": REPO + os.pathsep
                    + env.get("PYTHONPATH", "")})
        for k in ("BST_COORDINATOR", "BST_NUM_PROCESSES", "BST_PROCESS_ID",
                  "BST_DAG_EXCHANGE_ADDR"):
            env.pop(k, None)
        return env

    def report(txt):
        lines = [ln for ln in txt.splitlines() if ln.startswith("RESULT ")]
        if not lines:
            raise RuntimeError(f"multihost worker printed no RESULT:\n"
                               f"{txt[-2000:]}")
        return json.loads(lines[-1][len("RESULT "):])

    def run_leg(world):
        proj = os.path.join(root, f"w{world}")
        mk_proj(proj)
        if world == 1:
            out = subprocess.run(
                [sys.executable, worker_py, proj], env=base_env(),
                capture_output=True, text=True, timeout=300, check=True)
            return [report(out.stdout)]
        coord = f"127.0.0.1:{free_port()}"
        xaddrs = f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}"
        procs = []
        for r in range(world):
            env = base_env()
            env.update({"BST_COORDINATOR": coord,
                        "BST_NUM_PROCESSES": str(world),
                        "BST_PROCESS_ID": str(r),
                        "BST_DAG_EXCHANGE_ADDR": xaddrs})
            procs.append(subprocess.Popen(
                [sys.executable, worker_py, proj], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        reps = []
        for r, p in enumerate(procs):
            txt, _ = p.communicate(timeout=300)
            if p.returncode:
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                raise RuntimeError(f"multihost rank {r} exited "
                                   f"{p.returncode}:\n{txt[-2000:]}")
            reps.append(report(txt))
        return reps

    legs = {1: [], 2: []}
    for i in range(runs):
        for world in (1, 2):
            legs[world].append(run_leg(world))
            _log(f"multihost {world}p run {i + 1}/{runs}: "
                 f"{max(r['seconds'] for r in legs[world][-1]):.2f}s")

    # per-rep wall is the straggler rank (the legs barrier at dag-end)
    best1 = min(max(r["seconds"] for r in rep) for rep in legs[1])
    best2 = min(max(r["seconds"] for r in rep) for rep in legs[2])
    best2_rep = min(legs[2], key=lambda rep: max(r["seconds"] for r in rep))
    shas = {r["s0_sha"] for rep in legs[1] + legs[2] for r in rep}
    assert len(shas) == 1, f"fused output diverged across legs: {shas}"
    xhost = sum(r["xhost_bytes"] for r in best2_rep)
    assert xhost > 0, best2_rep
    assert all(r["bytes_reread"] == 0 for r in best2_rep), best2_rep
    return {
        "metric": "multihost_1p_over_2p",
        "value": round(best1 / max(best2, 1e-9), 3),
        "unit": "x",
        "seconds_1p": round(best1, 3),
        "seconds_2p": round(best2, 3),
        "best_of_runs": runs,
        "xhost_bytes_2p": int(xhost),
        "bytes_reread_2p": 0,
        "parity": "bitwise (fused s0 sha equal across ranks and legs)",
        "note": ("streamed resave->create->fuse on a tiny CPU fixture: "
                 "1 process vs a real 2-process jax.distributed world "
                 "with the TCP block exchange; >1x means the split beat "
                 "the exchange+collective overhead on this host, <1x "
                 "prices that overhead (the fixture is far below the "
                 "volumes the split targets)"),
        "io": {
            "pair_util_pct_by_process": {
                str(r["rank"]): r["pair_util_pct"] for r in best2_rep},
            "pair_busy_s_by_process": {
                str(r["rank"]): r["pair_busy_s"] for r in best2_rep},
        },
    }


def measure_cloud():
    """The tiered storage IO engine, measured: the same tiny resave->fuse
    workload against the in-repo S3-protocol fake with injected
    per-request latency (utils/s3_fake.py), three ways — cold synchronous
    reads (prefetch + disk tier + remote cache all off), async prefetch,
    and prefetch + NVMe spill tier under a deliberately undersized chunk
    LRU with a warm rerun. Reports the prefetch+tier speedup over
    cold-sync, the warm rerun's remote chunk-read bytes (must be zero:
    everything served from the memory LRU or the disk tier), and asserts
    bitwise output parity across all legs AND against the same fusion on
    a plain local root."""
    import hashlib

    import numpy as np
    from click.testing import CliRunner

    from bigstitcher_spark_tpu.cli.main import cli
    from bigstitcher_spark_tpu.io import chunkcache, prefetch, uris
    from bigstitcher_spark_tpu.io.chunkstore import (
        ChunkStore, bump_remote_pin,
    )
    from bigstitcher_spark_tpu.utils.s3_fake import S3FakeServer
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    root = os.path.join(FIXTURE, "cloud-bench")
    shutil.rmtree(root, ignore_errors=True)
    os.makedirs(root, exist_ok=True)
    proj = make_synthetic_project(
        os.path.join(root, "proj"), n_tiles=(2, 2, 1),
        tile_size=(96, 96, 48), overlap=24, jitter=0.0,
        n_beads_per_tile=15, seed=11)

    os.environ.setdefault("AWS_ACCESS_KEY_ID", "bench")
    os.environ.setdefault("AWS_SECRET_ACCESS_KEY", "benchsecret")
    srv = S3FakeServer().start()   # latency stays 0 through setup
    uris.set_s3_endpoint(srv.endpoint)
    uris.set_s3_region("us-east-1")
    runner = CliRunner()
    saved_env = {k: os.environ.get(k) for k in (
        "BST_PREFETCH_BYTES", "BST_PREFETCH_THREADS", "BST_REMOTE_CACHE",
        "BST_DISK_TIER_BYTES", "BST_DISK_TIER_DIR",
        "BST_CHUNK_CACHE_BYTES", "BST_TILE_CACHE_BYTES")}

    def set_env(**kv):
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)

    def ok(args):
        r = runner.invoke(cli, args, catch_exceptions=False)
        assert r.exit_code == 0, r.output

    def fresh():
        """Every leg starts storage-cold: empty LRU + disk tier, a new
        remote coherence window, an idle prefetcher."""
        prefetch.drain(timeout_s=10)
        prefetch.reset()
        chunkcache.get_cache().clear()
        bump_remote_pin()

    def sha_of(uri, dataset):
        data = np.asarray(ChunkStore.open(uri).open_dataset(
            dataset).read_full())
        return hashlib.sha256(np.ascontiguousarray(data).tobytes()
                              ).hexdigest()

    def make_fused(uri, xml):
        # fused blocks are coarse on purpose: the cold wall should be
        # dominated by the many small SOURCE chunk reads the prefetcher
        # can hide, not by output puts
        ok(["create-fusion-container", "-x", xml, "-o", uri, "-s", "ZARR",
            "-d", "UINT16", "--blockSize", "48,48,48",
            "--minIntensity", "0", "--maxIntensity", "65535"])

    def fuse_leg(uri, env, cold=True):
        set_env(**env)
        if cold:
            fresh()
        iob = _io_baseline()
        t0 = time.time()
        ok(["affine-fusion", "-o", uri])
        dt = time.time() - t0
        io = _io_snapshot(iob)
        prefetch.drain(timeout_s=10)
        return dt, io

    try:
        # setup at zero latency: the source container on s3 AND on a
        # plain local root (the parity reference), one fused container
        # per leg
        xml_s3 = os.path.join(root, "resaved-s3.xml")
        xml_local = os.path.join(root, "resaved-local.xml")
        local_n5 = os.path.join(root, "src.n5")
        resave_args = ["--N5", "--blockSize", "16,16,16",
                       "-ds", "1,1,1; 2,2,1"]
        ok(["resave", "-x", proj.xml_path, "-xo", xml_s3,
            "-o", "s3://bench/src.n5", *resave_args])
        ok(["resave", "-x", proj.xml_path, "-xo", xml_local,
            "-o", local_n5, *resave_args])
        s0 = "setup0/timepoint0/s0"
        assert sha_of("s3://bench/src.n5", s0) == sha_of(local_n5, s0), (
            "resaved s0 over s3 differs from the local root")
        legs = {"cold_sync": "s3://bench/fused-cold.zarr",
                "prefetch": "s3://bench/fused-pf.zarr",
                "tier": "s3://bench/fused-tier.zarr"}
        for uri in legs.values():
            make_fused(uri, xml_s3)
        local_fused = os.path.join(root, "fused-local.zarr")
        make_fused(local_fused, xml_local)
        # HBM tile cache off in every leg: it would serve warm tiles
        # straight from device memory and mask the chunk-tier path under
        # measurement
        off = {"BST_PREFETCH_BYTES": 0, "BST_DISK_TIER_BYTES": 0,
               "BST_REMOTE_CACHE": "off", "BST_DISK_TIER_DIR": None,
               "BST_CHUNK_CACHE_BYTES": None,
               "BST_PREFETCH_THREADS": None, "BST_TILE_CACHE_BYTES": 0}
        dt_local, _ = fuse_leg(local_fused, off)

        srv.latency_s = 0.05   # ~one-datacenter-hop object-store RTT
        dt_cold, io_cold = fuse_leg(legs["cold_sync"], off)
        _log(f"cloud cold-sync {dt_cold:.2f}s (local {dt_local:.2f}s)")
        pf = {"BST_PREFETCH_BYTES": 256 << 20, "BST_PREFETCH_THREADS": 8,
              "BST_REMOTE_CACHE": "run", "BST_DISK_TIER_BYTES": 0,
              "BST_DISK_TIER_DIR": None, "BST_CHUNK_CACHE_BYTES": None,
              "BST_TILE_CACHE_BYTES": 0}
        dt_pf, io_pf = fuse_leg(legs["prefetch"], pf)
        _log(f"cloud prefetch {dt_pf:.2f}s")
        # the tier leg undersizes the memory LRU far below the source
        # working set, so prefetched chunks spill to (and warm reruns
        # promote from) the NVMe tier
        tier = dict(pf, BST_DISK_TIER_BYTES=256 << 20,
                    BST_DISK_TIER_DIR=os.path.join(root, "tier"),
                    BST_CHUNK_CACHE_BYTES=256 << 10)
        dt_tier, io_tier = fuse_leg(legs["tier"], tier)
        _log(f"cloud prefetch+tier cold {dt_tier:.2f}s")
        dt_warm, io_warm = fuse_leg(legs["tier"], tier, cold=False)
        _log(f"cloud prefetch+tier warm {dt_warm:.2f}s")
        warm_remote = int(io_warm.get("bst_io_remote_read_bytes_total", 0))
        assert warm_remote == 0, (
            f"warm rerun re-read {warm_remote} chunk bytes from the "
            f"remote store — the memory LRU + disk tier should have "
            f"served everything")

        srv.latency_s = 0.0    # parity readback untimed
        shas = {name: sha_of(uri, "0") for name, uri in legs.items()}
        shas["local"] = sha_of(local_fused, "0")
        assert len(set(shas.values())) == 1, (
            f"fused output diverged across legs: {shas}")
        return {
            "metric": "cloud_tiered_io_speedup",
            "value": round(dt_cold / max(dt_warm, 1e-9), 3),
            "unit": "x",
            "seconds_cold_sync": round(dt_cold, 3),
            "seconds_prefetch": round(dt_pf, 3),
            "seconds_tier_cold": round(dt_tier, 3),
            "seconds_tier_warm": round(dt_warm, 3),
            "seconds_local_root": round(dt_local, 3),
            "prefetch_speedup": round(dt_cold / max(dt_pf, 1e-9), 3),
            "tier_cold_speedup": round(dt_cold / max(dt_tier, 1e-9), 3),
            "warm_remote_read_bytes": warm_remote,
            "request_latency_s": 0.05,
            "parity": ("bitwise (fused sha equal across cold-sync, "
                       "prefetch, prefetch+tier and local-root legs; "
                       "resaved s0 equal s3 vs local)"),
            "note": ("tiny resave->fuse against the in-repo S3 fake with "
                     "50ms injected per-request latency: synchronous "
                     "per-block reads vs the byte-budgeted async "
                     "prefetcher vs prefetch + NVMe spill tier under an "
                     "undersized chunk LRU; the headline ratio is the "
                     "tier leg's warm rerun, which serves every source "
                     "chunk from the memory LRU + disk tier without "
                     "touching the remote store"),
            "io": {"cold_sync": io_cold, "prefetch": io_pf,
                   "tier_cold": io_tier, "tier_warm": io_warm},
        }
    finally:
        srv.latency_s = 0.0
        set_env(**saved_env)
        try:
            prefetch.reset()
            chunkcache.get_cache().clear()
        except Exception:
            pass
        uris.set_s3_endpoint(None)
        uris.set_s3_region(None)
        srv.stop()


def _log(msg):
    print(f"[bench:{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def _checkpoint(result):
    """Write the current (possibly partial) result JSON atomically so the
    parent can salvage the primary metric if this child is killed by the
    timeout (tunnel-weather resilience)."""
    path = _cfg.get_str("BST_BENCH_PARTIAL")
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f)
    os.replace(tmp, path)


def _validate_fusion(xml, ds):
    """The XLA output must agree with the baseline implementation
    (same math, independent code path) on the first block."""
    import numpy as np

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.utils.geometry import Interval
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    bbox = maximal_bounding_box(sd, sd.view_ids())
    blk = (128, 128, 64)
    ref_blk = _baseline_fuse_block(
        sd, loader, sd.view_ids(), Interval.from_shape(blk).translate(bbox.min))
    got_blk = np.asarray(ds.read((0, 0, 0, 0, 0), (*blk, 1, 1)))[..., 0, 0]
    diff = np.abs(got_blk.astype(np.float64) - ref_blk.astype(np.float64))
    assert float(diff.mean()) < 1.0 and float(got_blk.std()) > 0.0, (
        f"XLA fusion disagrees with baseline: mean|diff|={diff.mean():.3f}")


def _primary_result(vox_per_sec, baseline, platform, spans,
                    runs_done=FUSION_RUNS, io=None):
    res = {
        "metric": "affine_fusion_voxels_per_sec",
        "value": round(vox_per_sec, 1),
        "unit": "voxel/s",
        "vs_baseline": round(vox_per_sec / baseline, 3),
        "platform": platform,
        "baseline_vox_per_sec": round(baseline, 1),
        "baseline_provenance": (
            "measured in this run (same host, same process weather); "
            "history in BASELINE_MEASURED.json"),
        "best_of_runs": runs_done,
        "spans": spans,
        "io": io or {},
        "extra_metrics": [],
    }
    if platform not in ("cpu",):
        res["note"] = (
            "end-to-end pays tile h2d + fused-output d2h over the axon "
            "tunnel (a cost the in-process CPU baseline does not have) "
            "plus the host-side chunk write; see spans and the *_kernel_* "
            "extra metrics for the on-device compute rates and "
            "wire_d2h_mb_per_sec for the measured wire")
    return res


class _DeviceStall(Exception):
    pass


def _run_with_watchdog(fn, timeout_s=None):
    """Run ``fn`` in a worker thread; raise _DeviceStall if it doesn't
    finish in time. A hung XLA device call blocks its thread forever (the
    tunnel drops without erroring), so the hung worker is simply abandoned
    (daemon) and the caller finalizes what it has instead of burning the
    rest of the child time budget waiting for SIGKILL."""
    import threading

    out: dict = {}

    def work():
        try:
            out["r"] = fn()
        except BaseException as e:  # noqa: BLE001 — reraised below
            out["e"] = e

    th = threading.Thread(target=work, daemon=True)
    th.start()
    th.join(timeout_s or DEVICE_TIMEOUT_S)
    if th.is_alive():
        raise _DeviceStall(f"device call stalled >{timeout_s or DEVICE_TIMEOUT_S}s")
    if "e" in out:
        raise out["e"]
    return out["r"]


def _baseline_drift_flags():
    """Same-fixture baselines that moved >1.4x against their previous
    measurement (beyond the 20-30% host drift _fresh_baselines documents).
    vs_baseline always divides by the SAME-RUN baseline, so each artifact
    is internally consistent — but a flagged entry warns that cross-run
    comparisons of that config ride very different host weather."""
    flags = {}
    for name, ent in _baseline_cache_load().items():
        if not isinstance(ent, dict):
            continue
        if ent.get("previous_key") != ent.get("key"):
            continue  # different fixture config, not host weather
        for k, prev in ent.items():
            if (k.startswith("previous_") and isinstance(prev, (int, float))
                    and prev):
                cur = ent.get(k[len("previous_"):])
                if (isinstance(cur, (int, float)) and cur
                        and max(cur / prev, prev / cur) > 1.4):
                    flags[name] = {"previous": prev, "current": cur,
                                   "ratio": round(cur / prev, 3)}
    return flags


def _finalize(result, truncated=None):
    """Print the artifact line and exit without waiting on wedged XLA
    threads (a normal interpreter exit can hang in runtime teardown)."""
    if truncated:
        result["truncated"] = truncated
        _log(f"finalizing early: {truncated}")
    try:  # BST_TELEMETRY_DIR runs also leave a manifest + metrics textfile
        from bigstitcher_spark_tpu import observe

        observe.finalize(tool="bench",
                         params={"platform": result.get("platform"),
                                 "truncated": truncated},
                         status="truncated" if truncated else "ok")
        # BST_TRACE without a telemetry dir: flush the ring ourselves
        # (with one, observe.finalize archived it next to the manifest)
        from bigstitcher_spark_tpu.observe import trace

        tp = trace.finalize(dir_hint=_cfg.get_str("BST_TELEMETRY_DIR"))
        if tp:
            _log(f"trace -> {tp}")
            # archive the rendered trace-report beside the trace/manifest
            # and lift the d2h<->write overlap into the artifact's io
            # columns — the 0.64x question answered by artifacts, not
            # console captures
            from bigstitcher_spark_tpu.analysis import tracereport

            evs, tmeta = tracereport.load_events(tp)
            rep = tracereport.build_report(evs, tmeta)
            rpt = os.path.join(os.path.dirname(tp), "trace-report.txt")
            with open(rpt, "w", encoding="utf-8") as f:
                f.write(tracereport.render_report(rep) + "\n")
            _log(f"trace report -> {rpt}")
            ov = (rep.get("stages", {}).get("fusion", {})
                  .get("overlap", {}).get("d2h_write"))
            if ov:
                io_cols = result.setdefault("io", {})
                io_cols["trace_d2h_write_overlap_s"] = ov.get("seconds")
                io_cols["trace_d2h_write_overlap_pct_of_d2h"] = \
                    ov.get("pct_of_d2h")
                io_cols["trace_d2h_write_overlap_pct_of_write"] = \
                    ov.get("pct_of_write")
    except Exception as e:  # telemetry must never void the artifact
        _log(f"telemetry finalize failed: {e!r}")
    drift = _baseline_drift_flags()
    if drift:
        result["baseline_drift_flags"] = drift
    _checkpoint(result)
    print(json.dumps(result))
    sys.stdout.flush()
    os._exit(0)


# the extras pipeline: salvage reporting derives its denominator from this
EXTRA_MEASURES = (
    ("kernel", lambda xml: measure_kernel_only(xml)),
    ("fusion_pyramid", lambda xml: measure_fusion_pyramid(xml)),
    ("pipeline", lambda xml: measure_pipeline(xml)),
    ("solver", lambda xml: measure_solver(xml)),
    ("submit_latency", lambda xml: measure_submit_latency(xml)),
    ("phasecorr", lambda xml: measure_phasecorr(xml)),
    ("phasecorr_kernel", lambda xml: measure_phasecorr_kernel(xml)),
    ("dog", lambda xml: measure_dog(xml)),
    ("dog_kernel", lambda xml: measure_dog_kernel(xml)),
    ("multitp", lambda xml: measure_multitp()),
    ("nonrigid", lambda xml: measure_nonrigid()),
    ("nonrigid_kernel", lambda xml: measure_nonrigid_kernel()),
    ("tune", lambda xml: measure_tune(xml)),
    ("multihost", lambda xml: measure_multihost()),
    ("cloud", lambda xml: measure_cloud()),
)


def child_main():
    _log("child start")
    if _cfg.get_str("BST_TELEMETRY_DIR"):
        from bigstitcher_spark_tpu import observe

        # same registry/event/manifest path as `bst ... --telemetry-dir`;
        # profiling stays under the bench's own enable/reset control
        observe.configure(_cfg.get_str("BST_TELEMETRY_DIR"), profile=False)
    if _cfg.get_bool("BST_TRACE"):
        from bigstitcher_spark_tpu.observe import trace

        # observe.finalize() archives the ring next to the run manifest
        # when BST_TELEMETRY_DIR is set; else it lands at BST_TRACE_PATH
        trace.configure()
    xml = build_fixture()
    _log("fixture ready")
    out = os.path.join(FIXTURE, "fused.ome.zarr")
    baseline = measure_baseline(xml)
    _RUN_BASELINES["fusion"] = baseline  # reused by measure_fusion_pyramid
    _log(f"baseline {baseline:.0f} vox/s")
    from bigstitcher_spark_tpu import profiling

    try:  # warm-up: compiles all kernel variants (first device contact —
        # a stall here means the tunnel died between probe and child)
        _run_with_watchdog(lambda: run_fusion(xml, out),
                           max(DEVICE_TIMEOUT_S, 600))
    except _DeviceStall as e:
        # os._exit: interpreter teardown can itself hang on the wedged
        # XLA runtime threads
        _log(f"warmup stalled ({e}); aborting attempt early")
        os._exit(1)
    _log("warmup fusion done")
    import jax

    platform = jax.devices()[0].platform
    best_v = 0.0
    best_spans = {}
    best_io = {}
    validated = False
    runs_done = 0
    try:
        for i in range(FUSION_RUNS):
            profiling.enable(True)
            profiling.get().reset()
            iob = _io_baseline()
            try:
                stats, ds, bbox = _run_with_watchdog(
                    lambda: run_fusion(xml, out))
            except _DeviceStall as e:
                if not validated:
                    _log(f"run {i + 1} stalled before validation ({e})")
                    os._exit(1)
                # completed validated runs survive the stall: finalize now
                # instead of burning the rest of the child time budget
                _finalize(_primary_result(best_v, baseline, platform,
                                          best_spans, runs_done=runs_done,
                                          io=best_io),
                          truncated=f"fusion run {i + 1}: {e}")
            v = stats.voxels / max(stats.seconds, 1e-9)
            runs_done = i + 1
            _log(f"fusion run {i + 1}/{FUSION_RUNS}: {v:,.0f} vox/s "
                 f"({stats.seconds:.2f}s)")
            if v > best_v:
                best_v, best_spans = v, _spans_snapshot()
                best_io = _io_snapshot(iob)
            profiling.enable(False)
            if not validated:
                _validate_fusion(xml, ds)
                _log("validation ok")
                validated = True
            # checkpoint after EVERY run: a tunnel hang mid-best-of must not
            # void the completed, validated runs (observed: attempt hung on
            # run 5/5 with four good runs that would otherwise be lost)
            _checkpoint(_primary_result(best_v, baseline, platform,
                                        best_spans, runs_done=runs_done,
                                        io=best_io))
    finally:
        profiling.enable(False)
    result = _primary_result(best_v, baseline, platform, best_spans,
                             io=best_io)
    _checkpoint(result)
    for name, fn in EXTRA_MEASURES:
        try:
            m = _run_with_watchdog(lambda: fn(xml))
        except _DeviceStall as e:
            # the tunnel is gone; remaining extras would stall too — ship
            # the primary + completed extras as a truncated artifact
            result["extra_metrics"].append(
                {"metric": name, "error": str(e)})
            _finalize(result, truncated=f"extra '{name}': {e}")
        except Exception as e:  # a failed extra must not void the primary
            _log(f"{name} failed: {e!r}")
            m = {"metric": name, "error": repr(e)[:200]}
        result["extra_metrics"].append(m)
        _log(f"{name}: {json.dumps(m)[:160]}")
        _checkpoint(result)
    _finalize(result)


def _salvage_partial(partial_path, label):
    """A timed-out child may still have checkpointed the primary metric."""
    try:
        with open(partial_path) as f:
            res = json.load(f)
    except (OSError, ValueError):
        return None
    if res.get("metric") and res.get("value"):
        res["partial"] = True
        print(f"[bench] {label}: salvaged partial result "
              f"(extras done: {len(res.get('extra_metrics', []))}"
              f"/{len(EXTRA_MEASURES)})",
              file=sys.stderr)
        return json.dumps(res)
    return None


def _spawn_child(env_extra, label):
    env = dict(os.environ)
    env.update(env_extra)
    env["BST_BENCH_CHILD"] = "1"
    tag = label.replace(" ", "_").replace("/", "-")
    # logs/partials live OUTSIDE the fixture dir: build_fixture rmtree's
    # FIXTURE on a fresh host, which used to unlink the live child log
    logdir = FIXTURE.rstrip("/") + "_logs"
    os.makedirs(logdir, exist_ok=True)
    partial_path = os.path.join(logdir, f"partial_{tag}.json")
    log_path = os.path.join(logdir, f"child_{tag}.log")
    env["BST_BENCH_PARTIAL"] = partial_path
    for p in (partial_path, log_path):
        try:
            os.remove(p)
        except OSError:
            pass
    os.makedirs(FIXTURE, exist_ok=True)
    t0 = time.time()
    # child stderr streams to a file so progress is observable mid-run
    # (tail -f <log_path>) and survives a timeout kill
    with open(log_path, "w") as logf:
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, cwd=REPO, timeout=CHILD_TIMEOUT_S,
                stdout=subprocess.PIPE, stderr=logf, text=True,
            )
        except subprocess.TimeoutExpired:
            print(f"[bench] {label}: timed out after {CHILD_TIMEOUT_S}s "
                  f"(log: {log_path})", file=sys.stderr)
            return None, _salvage_partial(partial_path, label)
    dt = time.time() - t0
    line = None
    for ln in (proc.stdout or "").splitlines():
        if ln.startswith("{") and '"metric"' in ln:
            line = ln
    if proc.returncode == 0 and line:
        print(f"[bench] {label}: ok in {dt:.0f}s", file=sys.stderr)
        return line, None
    try:
        with open(log_path) as f:
            tail = "\n".join((f.read() + (proc.stdout or "")).splitlines()[-15:])
    except OSError:
        tail = proc.stdout or ""
    print(f"[bench] {label}: rc={proc.returncode} in {dt:.0f}s\n{tail}",
          file=sys.stderr)
    return None, _salvage_partial(partial_path, label)


def _probe_tpu(timeout_s=300):
    """Quickly check that the accelerator backend can initialize at all
    before spending a full child timeout on it."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print('PROBE_OK', d[0].platform)"],
            env=dict(os.environ), cwd=REPO, timeout=timeout_s,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] tpu probe: timed out after {timeout_s}s",
              file=sys.stderr)
        return False
    ok = proc.returncode == 0 and "PROBE_OK" in (proc.stdout or "")
    if not ok:
        tail = "\n".join((proc.stderr or "").splitlines()[-5:])
        print(f"[bench] tpu probe failed rc={proc.returncode}\n{tail}",
              file=sys.stderr)
    return ok


def main():
    if _cfg.get_bool("BST_BENCH_CHILD"):
        child_main()
        return 0
    attempts = []
    tpu_only = _cfg.get_bool("BST_BENCH_TPU_ONLY")
    if _probe_tpu():
        for i in range(TPU_ATTEMPTS):
            attempts.append(({}, f"tpu attempt {i + 1}/{TPU_ATTEMPTS}"))
    elif tpu_only:
        print("[bench] accelerator unreachable (BST_BENCH_TPU_ONLY set)",
              file=sys.stderr)
        return 1
    else:
        print("[bench] accelerator unreachable, going straight to cpu",
              file=sys.stderr)
    if not tpu_only:
        attempts.append((
            {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
            "cpu fallback",
        ))
    partials = []
    i = 0
    while i < len(attempts):
        env_extra, label = attempts[i]
        line, partial = _spawn_child(env_extra, label)
        if line:  # complete result — done
            print(line)
            return 0
        if partial:  # keep as fallback, but let later attempts try for a
            partials.append(partial)  # complete artifact first
        elif label.startswith("tpu") and len(attempts) > i + 2:
            # a TPU attempt that died without even a checkpointed primary
            # means the tunnel is hung, not slow — don't burn another full
            # child timeout on it; drop straight to the cpu fallback
            print("[bench] tpu attempt produced no partial; skipping to "
                  "cpu fallback", file=sys.stderr)
            attempts = attempts[:i + 1] + attempts[-1:]
        i += 1
        if i < len(attempts):
            time.sleep(10)
    if partials:
        best = max(partials,
                   key=lambda p: len(json.loads(p).get("extra_metrics", [])))
        print("[bench] no complete run; reporting best partial",
              file=sys.stderr)
        print(best)
        return 0
    print("[bench] all attempts failed", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

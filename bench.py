#!/usr/bin/env python
"""Benchmark: affine-fusion voxels/sec (the BASELINE.md north-star metric).

Fuses a 2x2-tile synthetic light-sheet project (256x256x128 per tile,
uint16, AVG_BLEND) into an OME-ZARR container on the available accelerator
and reports fused output voxels per second for the steady-state (warm
compile-cache) run.

Robustness: the TPU backend arrives through a one-client tunnel that can be
busy or flaky, so the measurement runs in a CHILD process with a hard
timeout and bounded retries; if the accelerator can't be initialized the
bench falls back to a CPU run (reported with "platform": "cpu") rather than
producing no number at all (the round-1 failure mode).

vs_baseline: measured against a REAL measurement of a reference-equivalent
CPU implementation — plain numpy + scipy.ndimage trilinear affine fusion
over the same block grid, 8 host threads (the analogue of the reference's
Spark local[8] deployment, BASELINE.md) — on this same fixture, on this
machine. The measurement is cached with provenance in BASELINE_MEASURED.json
and validated against the XLA output before timing.
"""

import json
import os
import shutil
import subprocess
import sys
import time

FIXTURE = os.environ.get("BST_BENCH_DIR", "/tmp/bst_bench")
REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_FILE = os.path.join(REPO, "BASELINE_MEASURED.json")
FIXTURE_SPEC = {
    "n_tiles": (2, 2, 1), "tile_size": (256, 256, 128), "overlap": 32,
    "jitter": 0.0, "seed": 11, "block_size": (128, 128, 64),
    "n_beads_per_tile": 120,
}
CHILD_TIMEOUT_S = int(os.environ.get("BST_BENCH_CHILD_TIMEOUT", 1500))
TPU_ATTEMPTS = 2


def build_fixture():
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    marker = os.path.join(FIXTURE, "proj", "dataset.xml")
    if os.path.exists(marker):
        return marker
    shutil.rmtree(FIXTURE, ignore_errors=True)
    make_synthetic_project(os.path.join(FIXTURE, "proj"), **FIXTURE_SPEC)
    return marker


def run_fusion(xml_path, out_path, block_scale=(2, 2, 1)):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.io.container import create_fusion_container
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    shutil.rmtree(out_path, ignore_errors=True)
    create_fusion_container(
        out_path, StorageFormat.ZARR, xml_path, 1, 1, bbox,
        data_type="uint16", block_size=(128, 128, 64),
        min_intensity=0.0, max_intensity=65535.0,
    )
    store = ChunkStore.open(out_path)
    ds = store.open_dataset("0")
    stats = fuse_volume(
        sd, loader, views, ds, bbox, block_size=(128, 128, 64),
        block_scale=block_scale, fusion_type="AVG_BLEND",
        out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
        zarr_ct=(0, 0),
    )
    return stats, ds, bbox


# ---------------------------------------------------------------------------
# Reference-equivalent CPU baseline (numpy + scipy, 8 threads = "local[8]")
# ---------------------------------------------------------------------------


def _baseline_fuse_block(sd, loader, views, block_global, blend_range=40.0):
    """One output block fused exactly the way the reference's BlkAffineFusion
    does it, in plain host code: per view, inverse-affine coordinates,
    trilinear sample (scipy.ndimage.map_coordinates order=1), cosine-edge
    blend weight, weighted average (AVG_BLEND)."""
    import numpy as np
    from scipy.ndimage import map_coordinates

    from bigstitcher_spark_tpu.utils.geometry import (
        Interval, invert_affine, transformed_interval,
    )

    shape = block_global.shape
    acc = np.zeros(shape, np.float32)
    wsum = np.zeros(shape, np.float32)
    # world coords of block voxels, per axis broadcastable (X,1,1)/(1,Y,1)/(1,1,Z)
    axes = [
        (np.arange(shape[d], dtype=np.float32) + block_global.min[d]).reshape(
            [-1 if i == d else 1 for i in range(3)])
        for d in range(3)
    ]
    for v in views:
        inv = invert_affine(sd.model(v)).astype(np.float32)
        img_dim = np.asarray(sd.view_size(v), np.float32)
        src = transformed_interval(inv, block_global).expand(1)
        img_iv = Interval.from_shape(sd.view_size(v))
        if not src.overlaps(img_iv):
            continue
        clipped = src.intersect(img_iv)
        if clipped.is_empty():
            continue
        patch = loader.read_block(v, 0, tuple(clipped.min), clipped.shape
                                  ).astype(np.float32)
        w = None
        coords = []
        for i in range(3):
            li = (inv[i, 0] * axes[0] + inv[i, 1] * axes[1]
                  + inv[i, 2] * axes[2] + inv[i, 3])  # (X,Y,Z) level coords
            coords.append(li - np.float32(clipped.min[i]))
            # cosine edge ramp + inside mask along this level axis
            d = np.minimum(li, (img_dim[i] - 1.0) - li)
            ramp = 0.5 * (np.cos((1.0 - d / np.float32(blend_range)) * np.pi)
                          + 1.0)
            wi = np.where(d < 0, np.float32(0),
                          np.where(d < blend_range, ramp, np.float32(1)))
            w = wi if w is None else w * wi
        val = map_coordinates(patch, coords, order=1, mode="constant",
                              cval=0.0, output=np.float32)
        acc += val * w
        wsum += w
    fused = np.where(wsum > 0, acc / np.maximum(wsum, np.float32(1e-20)), 0.0)
    # uint16 convert at min=0, max=65535 (identity scale)
    return np.clip(np.round(fused), 0, 65535).astype("uint16")


def measure_baseline(xml_path, threads=None):
    """Measure the reference-equivalent CPU fusion on the bench fixture.

    Returns voxels/sec. The result is cached in BASELINE_MEASURED.json keyed
    by the fixture spec so the (slow) measurement runs once per machine.
    ``threads`` defaults to min(8, cpu_count) — the reference's local[8]
    deployment collapses to the actual core count on small hosts (measured:
    on a 1-core host 8 threads THRASH numpy to 4x slower, so claiming
    local[8] concurrency there would strawman the baseline)."""
    if threads is None:
        threads = max(1, min(8, os.cpu_count() or 1))
    import hashlib
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    key = hashlib.sha256(
        json.dumps({"spec": FIXTURE_SPEC, "threads": threads},
                   sort_keys=True, default=str).encode()).hexdigest()[:16]
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            cached = json.load(f)
        if cached.get("key") == key and cached.get("vox_per_sec", 0) > 0:
            return float(cached["vox_per_sec"])

    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.utils.geometry import Interval
    from bigstitcher_spark_tpu.utils.grid import create_grid
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    compute_block = (128, 128, 64)
    grid = create_grid(bbox.shape, compute_block, (128, 128, 64))

    def do_block(block):
        bg = Interval.from_shape(block.size, block.offset).translate(bbox.min)
        return _baseline_fuse_block(sd, loader, views, bg)

    # warm the OS page cache so IO parity matches the measured run
    do_block(grid[0])
    t0 = time.time()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        outs = list(pool.map(do_block, grid))
    dt = time.time() - t0
    vox = int(np.prod(bbox.shape))
    vox_per_sec = vox / dt
    with open(BASELINE_FILE, "w") as f:
        json.dump({
            "key": key,
            "vox_per_sec": round(vox_per_sec, 1),
            "voxels": vox,
            "seconds": round(dt, 3),
            "threads": threads,
            "method": (
                "reference-equivalent CPU affine fusion: numpy + "
                "scipy.ndimage.map_coordinates trilinear resample, cosine-edge "
                "AVG_BLEND weights, uint16 convert, over the reference's "
                "(128,128,64) block grid; ThreadPoolExecutor(min(8, cores)) "
                "approximates the reference's Spark local[8] deployment "
                "(BASELINE.md) at this host's actual core count. Measured on "
                "this machine, same fixture as the bench."
            ),
            "fixture": {k: list(v) if isinstance(v, tuple) else v
                        for k, v in FIXTURE_SPEC.items()},
            "cpu_count": os.cpu_count(),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "checksum_block0": hashlib.sha256(outs[0].tobytes()).hexdigest()[:16],
        }, f, indent=1)
    return vox_per_sec


def _log(msg):
    print(f"[bench:{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def child_main():
    import numpy as np

    _log("child start")
    xml = build_fixture()
    _log("fixture ready")
    out = os.path.join(FIXTURE, "fused.ome.zarr")
    baseline = measure_baseline(xml)
    _log(f"baseline {baseline:.0f} vox/s")
    # warm-up: compiles all (block,patch,view) bucket variants
    run_fusion(xml, out)
    _log("warmup fusion done")
    # measured steady-state run
    stats, ds, bbox = run_fusion(xml, out)
    _log(f"measured fusion done: {stats.voxels} vox in {stats.seconds:.2f}s")
    vox_per_sec = stats.voxels / max(stats.seconds, 1e-9)
    # validate: the XLA output must agree with the baseline implementation
    # (same math, independent code path) on the first block
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.utils.geometry import Interval
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml)
    loader = ViewLoader(sd)
    bbox = maximal_bounding_box(sd, sd.view_ids())
    blk = (128, 128, 64)
    ref_blk = _baseline_fuse_block(
        sd, loader, sd.view_ids(), Interval.from_shape(blk).translate(bbox.min))
    got_blk = np.asarray(ds.read((0, 0, 0, 0, 0), (*blk, 1, 1)))[..., 0, 0]
    diff = np.abs(got_blk.astype(np.float64) - ref_blk.astype(np.float64))
    assert float(diff.mean()) < 1.0 and float(got_blk.std()) > 0.0, (
        f"XLA fusion disagrees with baseline: mean|diff|={diff.mean():.3f}")
    import jax

    print(json.dumps({
        "metric": "affine_fusion_voxels_per_sec",
        "value": round(vox_per_sec, 1),
        "unit": "voxel/s",
        "vs_baseline": round(vox_per_sec / baseline, 3),
        "platform": jax.devices()[0].platform,
        "baseline_vox_per_sec": round(baseline, 1),
        "baseline_provenance": "BASELINE_MEASURED.json (measured, this host)",
    }))


def _spawn_child(env_extra, label):
    env = dict(os.environ)
    env.update(env_extra)
    env["BST_BENCH_CHILD"] = "1"
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, cwd=REPO, timeout=CHILD_TIMEOUT_S,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] {label}: timed out after {CHILD_TIMEOUT_S}s",
              file=sys.stderr)
        return None
    dt = time.time() - t0
    line = None
    for ln in (proc.stdout or "").splitlines():
        if ln.startswith("{") and '"metric"' in ln:
            line = ln
    if proc.returncode == 0 and line:
        print(f"[bench] {label}: ok in {dt:.0f}s", file=sys.stderr)
        return line
    tail = "\n".join(((proc.stderr or "") + (proc.stdout or "")).splitlines()[-15:])
    print(f"[bench] {label}: rc={proc.returncode} in {dt:.0f}s\n{tail}",
          file=sys.stderr)
    return None


def _probe_tpu(timeout_s=300):
    """Quickly check that the accelerator backend can initialize at all
    before spending a full child timeout on it."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print('PROBE_OK', d[0].platform)"],
            env=dict(os.environ), cwd=REPO, timeout=timeout_s,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        print(f"[bench] tpu probe: timed out after {timeout_s}s",
              file=sys.stderr)
        return False
    ok = proc.returncode == 0 and "PROBE_OK" in (proc.stdout or "")
    if not ok:
        tail = "\n".join((proc.stderr or "").splitlines()[-5:])
        print(f"[bench] tpu probe failed rc={proc.returncode}\n{tail}",
              file=sys.stderr)
    return ok


def main():
    if os.environ.get("BST_BENCH_CHILD"):
        child_main()
        return 0
    attempts = []
    if _probe_tpu():
        for i in range(TPU_ATTEMPTS):
            attempts.append(({}, f"tpu attempt {i + 1}/{TPU_ATTEMPTS}"))
    else:
        print("[bench] accelerator unreachable, going straight to cpu",
              file=sys.stderr)
    attempts.append((
        {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""},
        "cpu fallback",
    ))
    for i, (env_extra, label) in enumerate(attempts):
        line = _spawn_child(env_extra, label)
        if line:
            print(line)
            return 0
        if i + 1 < len(attempts):
            time.sleep(10)
    print("[bench] all attempts failed", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())

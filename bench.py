#!/usr/bin/env python
"""Benchmark: affine-fusion voxels/sec (the BASELINE.md north-star metric).

Fuses a 2x2-tile synthetic light-sheet project (256x256x128 per tile,
uint16, AVG_BLEND) into an OME-ZARR container on the available accelerator
and reports fused output voxels per second for the steady-state (warm
compile-cache) run.

vs_baseline: the reference publishes no numbers (BASELINE.json.published={}),
so the comparison point is the documented estimate of BigStitcher-Spark on
Spark local[8] CPU for this workload: ~2e7 fused voxels/sec (order of
magnitude from the reference's own stage self-timing hooks; BASELINE.md §
"Metrics"). vs_baseline = measured / 2e7, i.e. the ≥4x north-star target is
vs_baseline >= 4.
"""

import json
import os
import shutil
import sys
import time

BASELINE_VOX_PER_SEC = 2.0e7
FIXTURE = os.environ.get("BST_BENCH_DIR", "/tmp/bst_bench")


def build_fixture():
    from bigstitcher_spark_tpu.utils.testdata import make_synthetic_project

    marker = os.path.join(FIXTURE, "proj", "dataset.xml")
    if os.path.exists(marker):
        return marker
    shutil.rmtree(FIXTURE, ignore_errors=True)
    make_synthetic_project(
        os.path.join(FIXTURE, "proj"),
        n_tiles=(2, 2, 1), tile_size=(256, 256, 128), overlap=32,
        jitter=0.0, seed=11, block_size=(128, 128, 64),
        n_beads_per_tile=120,
    )
    return marker


def run_fusion(xml_path, out_path, block_scale=(2, 2, 1)):
    from bigstitcher_spark_tpu.io.chunkstore import ChunkStore, StorageFormat
    from bigstitcher_spark_tpu.io.container import create_fusion_container
    from bigstitcher_spark_tpu.io.dataset_io import ViewLoader
    from bigstitcher_spark_tpu.io.spimdata import SpimData
    from bigstitcher_spark_tpu.models.affine_fusion import fuse_volume
    from bigstitcher_spark_tpu.utils.viewselect import maximal_bounding_box

    sd = SpimData.load(xml_path)
    loader = ViewLoader(sd)
    views = sd.view_ids()
    bbox = maximal_bounding_box(sd, views)
    shutil.rmtree(out_path, ignore_errors=True)
    create_fusion_container(
        out_path, StorageFormat.ZARR, xml_path, 1, 1, bbox,
        data_type="uint16", block_size=(128, 128, 64),
        min_intensity=0.0, max_intensity=65535.0,
    )
    store = ChunkStore.open(out_path)
    ds = store.open_dataset("0")
    stats = fuse_volume(
        sd, loader, views, ds, bbox, block_size=(128, 128, 64),
        block_scale=block_scale, fusion_type="AVG_BLEND",
        out_dtype="uint16", min_intensity=0.0, max_intensity=65535.0,
        zarr_ct=(0, 0),
    )
    return stats


def main():
    xml = build_fixture()
    out = os.path.join(FIXTURE, "fused.ome.zarr")
    # warm-up: compiles all (block,patch,view) bucket variants
    run_fusion(xml, out)
    # measured steady-state run
    stats = run_fusion(xml, out)
    vox_per_sec = stats.voxels / max(stats.seconds, 1e-9)
    print(json.dumps({
        "metric": "affine_fusion_voxels_per_sec",
        "value": round(vox_per_sec, 1),
        "unit": "voxel/s",
        "vs_baseline": round(vox_per_sec / BASELINE_VOX_PER_SEC, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())

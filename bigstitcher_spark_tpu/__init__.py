"""bigstitcher_spark_tpu — a TPU-native distributed stitching & fusion framework.

A from-scratch reimplementation of the capabilities of BigStitcher-Spark
(JaneliaSciComp/BigStitcher-Spark) designed for TPU hardware: JAX/XLA compute
kernels sharded over a ``jax.sharding.Mesh``, tensorstore-backed chunked IO
(N5 / OME-ZARR / HDF5), and a BigStitcher-compatible SpimData XML project model
so every stage's output remains verifiable with the BigStitcher GUI.

Layer map (mirrors reference SURVEY.md §1, redesigned TPU-first):
  L5  cli/       typed click commands, one per pipeline stage
  L4  io/spimdata + utils/viewselect: project model & view selection
  L3  parallel/  work-list sharding over devices, retry tracking
  L2  ops/       XLA kernels: fusion, DoG, phase correlation, RANSAC, solver
  L1  io/        tensorstore N5/zarr/HDF5 chunk IO, interestpoints.n5 store
"""

__version__ = "0.1.0"

import jax as _jax

# Coordinate math (affine resampling, distance matrices, model fits) needs
# full f32: TPU matmuls otherwise default to bf16 passes whose ~0.2% relative
# error is pixels at volume scale. This is imaging, not ML training — always
# run matmuls/einsums at highest precision (f32 on MXU via 3-pass bf16).
_jax.config.update("jax_default_matmul_precision", "highest")

"""Context-propagating thread primitives.

Python threads start from an EMPTY ``contextvars`` context, so every
ambient this package scopes through context variables — per-job config
overrides (:func:`config.overrides`), the per-job event-log scope
(:mod:`observe.events`), the cooperative cancellation token
(:mod:`utils.cancel`) — silently vanishes inside a bare
``threading.Thread`` or ``ThreadPoolExecutor`` worker. Before the serve
daemon that never mattered (one process = one job = one ambient); with
multiple jobs resident in one process it is the difference between a
worker honoring ITS job's byte budget and it reading some other job's.

These wrappers capture the caller's context at submit/spawn time and run
the target inside a private copy (a ``Context`` object may only be
entered by one thread at a time, so every task gets its own copy — the
copy is cheap, contexts are copy-on-write).
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import ThreadPoolExecutor


class CtxThreadPool(ThreadPoolExecutor):
    """``ThreadPoolExecutor`` whose tasks run under a copy of the
    SUBMITTER's contextvars context instead of the worker thread's empty
    one. Drop-in for the driver pools (build/prefetch, write drains,
    refinement) so job-scoped ambients survive the hop."""

    def submit(self, fn, /, *args, **kwargs):
        ctx = contextvars.copy_context()
        return super().submit(ctx.run, fn, *args, **kwargs)

    def map(self, fn, *iterables, timeout=None, chunksize=1):
        # the parent's map would capture the WORKER's (empty) context;
        # routing through submit() snapshots the caller's context per task
        futures = [self.submit(fn, *args) for args in zip(*iterables)]

        def gen():
            for f in futures:
                yield f.result(timeout)

        return gen()


def ctx_thread(target, args=(), *, name: str | None = None,
               daemon: bool = True) -> threading.Thread:
    """A ``threading.Thread`` whose target runs under a copy of the
    CREATOR's contextvars context (captured now, not at start())."""
    ctx = contextvars.copy_context()
    return threading.Thread(target=ctx.run, args=(target, *args),
                            name=name, daemon=daemon)

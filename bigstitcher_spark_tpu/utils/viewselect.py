"""View-subset selection from CLI flags (AbstractSelectableViews equivalent,
abstractcmdline/AbstractSelectableViews.java:38-112 + util/Import.java:94-202):
filter the project's views by angle/channel/illumination/tile/timepoint ids or
explicit ``-vi 'tp,setup'`` pairs."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..io.spimdata import SpimData, ViewId
from .geometry import Interval, transformed_interval


def parse_id_list(s: str | None) -> list[int] | None:
    if s is None or s == "":
        return None
    return [int(v) for v in s.split(",") if v.strip() != ""]


def parse_view_ids(items: Sequence[str] | None) -> list[ViewId] | None:
    """Parse ``-vi`` entries of the form 'tp,setup' (Import.java:303-310)."""
    if not items:
        return None
    out = []
    for it in items:
        tp, setup = it.split(",")
        out.append(ViewId(int(tp), int(setup)))
    return out


def select_views(
    sd: SpimData,
    angle_ids: str | None = None,
    channel_ids: str | None = None,
    illumination_ids: str | None = None,
    tile_ids: str | None = None,
    timepoint_ids: str | None = None,
    vi: Sequence[str] | None = None,
) -> list[ViewId]:
    explicit = parse_view_ids(vi)
    if explicit is not None:
        unknown = [v for v in explicit if v.setup not in sd.setups
                   or v.timepoint not in sd.timepoints]
        if unknown:
            raise ValueError(f"unknown view ids: {unknown}")
        views = [v for v in explicit if v not in sd.missing_views]
        if not views:
            raise ValueError(
                f"all requested views are flagged missing: {explicit}"
            )
        return views
    filters = {
        "angle": parse_id_list(angle_ids),
        "channel": parse_id_list(channel_ids),
        "illumination": parse_id_list(illumination_ids),
        "tile": parse_id_list(tile_ids),
    }
    tps = parse_id_list(timepoint_ids)
    out = []
    for v in sd.view_ids():
        if tps is not None and v.timepoint not in tps:
            continue
        setup = sd.setups[v.setup]
        ok = all(
            ids is None or setup.attributes.get(attr, 0) in ids
            for attr, ids in filters.items()
        )
        if ok:
            out.append(v)
    if not out:
        raise ValueError("no views left after filtering")
    return out


def maximal_bounding_box(sd: SpimData, views: list[ViewId],
                         anisotropy: np.ndarray | None = None) -> Interval:
    """Smallest interval containing all transformed views
    (Import.java:39-66 maximal bounding box)."""
    from .geometry import concatenate

    bbox: Interval | None = None
    for v in views:
        m = sd.model(v)
        if anisotropy is not None:
            m = concatenate(anisotropy, m)
        b = transformed_interval(m, Interval.from_shape(sd.view_size(v)))
        bbox = b if bbox is None else bbox.union(b)
    if bbox is None:
        raise ValueError("no views")
    return bbox


def anisotropy_factor_from_voxel_sizes(sd: SpimData, views: list[ViewId]) -> float:
    """Average z/xy calibration ratio (CreateFusionContainer.java:184-211)."""
    ratios = []
    for v in views:
        vs = sd.setups[v.setup].voxel_size
        if vs[0] > 0:
            ratios.append(vs[2] / vs[0])
    return float(np.mean(ratios)) if ratios else 1.0


def keller_mirror_scope_map(
    row_count: int, column_count: int, parallel_rows: int = 4
) -> dict[int, int]:
    """Old->new ViewSetup id map for parallel-row mirror-scope acquisitions
    (SetupIDMapper.java:36-107): grid ids run bottom-right lowest, row-first
    leftwards then up; acquisition order completes every ``parallel_rows``-th
    row right-to-left before the next row offset."""
    mapping: dict[int, int] = {}
    new_id = 0
    for row_offset in range(parallel_rows):
        for col in range(column_count - 1, -1, -1):
            for row in range(row_offset, row_count, parallel_rows):
                old_id = row * column_count + (column_count - 1 - col)
                mapping[old_id] = new_id
                new_id += 1
    return mapping

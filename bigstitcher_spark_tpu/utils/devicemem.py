"""Device-memory budget for in-flight dispatch windows.

Every multi-dispatch driver (parallel/mesh.run_sharded_batches, the tiled
descriptor matcher, the segmented stitching drain) bounds how many programs
it keeps in flight by BYTES — inputs + outputs + a workspace multiplier —
instead of a fixed batch count: a fixed window sized for one block shape
either under-fills small problems or OOMs big ones. The budget derives
from the backend's real memory stats when the runtime exposes them
(TPU/GPU PJRT ``memory_stats``), with ``BST_INFLIGHT_BYTES`` as the
explicit override and a conservative constant for backends (XLA:CPU) that
report nothing.
"""

from __future__ import annotations

import threading

from .. import config
from ..observe import metrics as _metrics

# fallback when the backend reports no memory stats: two batches at the
# historical 1e9 per-device staging budget (the pre-window heuristic kept
# at most two batches resident — see BST_PER_DEV_BUDGET in the fusion
# driver), so CPU behavior matches the old fixed double-buffering
DEFAULT_BUDGET = int(2e9)

# of the device memory the runtime says is free, keep this fraction for
# in-flight dispatch work; the rest covers compiled-program workspace the
# estimate cannot see
_FREE_FRACTION = 0.6

_INFLIGHT = _metrics.gauge("bst_inflight_bytes")
_HIGHWATER = _metrics.gauge("bst_inflight_bytes_highwater")
_LOCK = threading.Lock()


def _derived_budget(device=None) -> tuple[int, str]:
    """(budget bytes, source) with source ``"env"`` (the process-wide
    ``BST_INFLIGHT_BYTES``), ``"stats"`` (the device's own
    ``memory_stats``, genuinely per device) or ``"fallback"`` (the
    backend reported nothing)."""
    env = config.get_bytes("BST_INFLIGHT_BYTES")
    if env is not None:
        return env, "env"
    try:
        import jax

        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            free = limit - int(stats.get("bytes_in_use", 0))
            return max(256 << 20, int(_FREE_FRACTION * free)), "stats"
    except Exception:
        pass
    return DEFAULT_BUDGET, "fallback"


def dispatch_budget_bytes(device=None) -> int:
    """Byte budget for dispatched-but-not-drained device work.

    ``BST_INFLIGHT_BYTES`` wins when set; otherwise ``device``'s (default:
    the first local device's) ``memory_stats`` (free = limit - in_use)
    scaled by a safety fraction; otherwise ``DEFAULT_BUDGET``. Per-device
    callers (the pair scheduler's one-window-per-device workers) pass
    their own device so each window sizes to its own HBM."""
    return _derived_budget(device)[0]


def pair_budget_bytes(device=None, n_local: int = 1) -> int:
    """Per-device in-flight budget for one of ``n_local`` concurrent pair
    scheduler workers: ``BST_PAIR_INFLIGHT_BYTES`` wins verbatim (it is
    defined per device); a ``memory_stats``-derived budget is genuinely
    per device and used as is; the process-wide knobs (the
    ``BST_INFLIGHT_BYTES`` env, the no-stats fallback) are SPLIT across
    the workers — N workers must not each claim the whole process
    budget."""
    env = config.get_bytes("BST_PAIR_INFLIGHT_BYTES")
    if env is not None:
        return env
    budget, source = _derived_budget(device)
    if source != "stats":
        budget = max(1, budget // max(n_local, 1))
    return budget


class InflightWindow:
    """Byte ledger for one driver's in-flight dispatches.

    ``charge``/``release`` keep a per-window total and feed the
    process-wide current/high-water gauges, so artifacts record how close
    the window ran to its budget."""

    def __init__(self, budget: int | None = None):
        self.budget = dispatch_budget_bytes() if budget is None else budget
        self.inflight = 0

    def fits(self, nbytes: int) -> bool:
        """Whether one more dispatch of ``nbytes`` stays inside the budget.
        An empty window always fits (forward progress must never block)."""
        return self.inflight == 0 or self.inflight + nbytes <= self.budget

    def charge(self, nbytes: int) -> None:
        self.inflight += nbytes
        with _LOCK:
            _INFLIGHT.inc(nbytes)
            cur = _INFLIGHT.value
            if cur > _HIGHWATER.value:
                _HIGHWATER.set(cur)

    def release(self, nbytes: int) -> None:
        self.inflight = max(0, self.inflight - nbytes)
        # under _LOCK like charge(): a bare dec racing a charge's
        # read-modify-write of the high-water pair could under-record it
        with _LOCK:
            _INFLIGHT.inc(-nbytes)

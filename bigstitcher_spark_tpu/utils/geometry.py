"""Interval and 3-D affine geometry, xyz axis order throughout.

Conventions (matching the reference's imglib2/N5 world so on-disk artifacts
stay BigStitcher-compatible):
  * Intervals are integer, min/max INCLUSIVE, axis order (x, y, z).
  * Affines are 3x4 float64 row-major matrices ``[R | t]`` acting on column
    vectors: ``world = R @ p + t`` — same layout as the 12-number
    ``<affine>`` rows in SpimData XML.
  * Composition ``concatenate(A, B)`` applies B first, then A (imglib2
    ``AffineTransform3D.concatenate`` semantics).

Reference behavior covered here: interval overlap tests and transformed
bounding boxes (ViewUtil.java:102-105,154-159), grid-block geometry helpers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np


@dataclass(frozen=True)
class Interval:
    """Integer interval with inclusive min/max, axis order xyz."""

    min: tuple[int, ...]
    max: tuple[int, ...]

    def __post_init__(self):
        object.__setattr__(self, "min", tuple(int(v) for v in self.min))
        object.__setattr__(self, "max", tuple(int(v) for v in self.max))
        if len(self.min) != len(self.max):
            raise ValueError(f"rank mismatch: {self.min} vs {self.max}")

    @staticmethod
    def from_shape(shape: Sequence[int], offset: Sequence[int] | None = None) -> "Interval":
        off = tuple(offset) if offset is not None else (0,) * len(shape)
        return Interval(off, tuple(o + s - 1 for o, s in zip(off, shape)))

    @property
    def ndim(self) -> int:
        return len(self.min)

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(mx - mn + 1 for mn, mx in zip(self.min, self.max))

    @property
    def num_elements(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def is_empty(self) -> bool:
        return any(mx < mn for mn, mx in zip(self.min, self.max))

    def overlaps(self, other: "Interval") -> bool:
        return all(
            amn <= bmx and bmn <= amx
            for amn, amx, bmn, bmx in zip(self.min, self.max, other.min, other.max)
        )

    def intersect(self, other: "Interval") -> "Interval":
        return Interval(
            tuple(max(a, b) for a, b in zip(self.min, other.min)),
            tuple(min(a, b) for a, b in zip(self.max, other.max)),
        )

    def union(self, other: "Interval") -> "Interval":
        return Interval(
            tuple(min(a, b) for a, b in zip(self.min, other.min)),
            tuple(max(a, b) for a, b in zip(self.max, other.max)),
        )

    def expand(self, border: int | Sequence[int]) -> "Interval":
        if isinstance(border, int):
            border = (border,) * self.ndim
        return Interval(
            tuple(mn - b for mn, b in zip(self.min, border)),
            tuple(mx + b for mx, b in zip(self.max, border)),
        )

    def translate(self, offset: Sequence[int]) -> "Interval":
        return Interval(
            tuple(mn + o for mn, o in zip(self.min, offset)),
            tuple(mx + o for mx, o in zip(self.max, offset)),
        )

    def contains_point(self, p: Sequence[float]) -> bool:
        return all(mn <= v <= mx for mn, v, mx in zip(self.min, p, self.max))

    def slices(self, origin: Sequence[int] | None = None) -> tuple[slice, ...]:
        """Slices into an array whose [0,...] corresponds to ``origin`` (default 0)."""
        org = tuple(origin) if origin is not None else (0,) * self.ndim
        return tuple(
            slice(mn - o, mx - o + 1) for mn, mx, o in zip(self.min, self.max, org)
        )


# ---------------------------------------------------------------------------
# Affine 3x4 helpers
# ---------------------------------------------------------------------------

def identity_affine() -> np.ndarray:
    return np.hstack([np.eye(3), np.zeros((3, 1))])


def affine_from_flat(values: Iterable[float]) -> np.ndarray:
    """12 row-major numbers (the SpimData ``<affine>`` element) -> 3x4."""
    a = np.asarray(list(values), dtype=np.float64)
    if a.size != 12:
        raise ValueError(f"expected 12 affine values, got {a.size}")
    return a.reshape(3, 4)


def affine_to_flat(a: np.ndarray) -> list[float]:
    return [float(v) for v in np.asarray(a, dtype=np.float64).reshape(-1)]


def translation_affine(t: Sequence[float]) -> np.ndarray:
    m = identity_affine()
    m[:, 3] = np.asarray(t, dtype=np.float64)
    return m


def scale_affine(s: Sequence[float]) -> np.ndarray:
    m = identity_affine()
    m[0, 0], m[1, 1], m[2, 2] = float(s[0]), float(s[1]), float(s[2])
    return m


def concatenate(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Apply ``b`` first, then ``a`` (imglib2 concatenate / preConcatenate dual)."""
    r = np.empty((3, 4), dtype=np.float64)
    r[:, :3] = a[:, :3] @ b[:, :3]
    r[:, 3] = a[:, :3] @ b[:, 3] + a[:, 3]
    return r


def concatenate_all(transforms: Sequence[np.ndarray]) -> np.ndarray:
    """Full model of a SpimData transform chain: first list element is the
    OUTERMOST (last applied) transform, matching ViewRegistration.getModel()."""
    m = identity_affine()
    for t in transforms:
        m = concatenate(m, t)
    return m


def invert_affine(a: np.ndarray) -> np.ndarray:
    rinv = np.linalg.inv(a[:, :3])
    out = np.empty((3, 4), dtype=np.float64)
    out[:, :3] = rinv
    out[:, 3] = -rinv @ a[:, 3]
    return out


def apply_affine(a: np.ndarray, points: np.ndarray) -> np.ndarray:
    """Apply 3x4 affine to points of shape (..., 3)."""
    p = np.asarray(points, dtype=np.float64)
    return p @ a[:, :3].T + a[:, 3]


def estimate_bounds(a: np.ndarray, interval: Interval) -> tuple[np.ndarray, np.ndarray]:
    """Float min/max of the transformed corners of ``interval``
    (TransformationTools bounding-box logic, ViewUtil.java:154-159)."""
    mn = np.asarray(interval.min, dtype=np.float64)
    mx = np.asarray(interval.max, dtype=np.float64)
    corners = np.array(
        [
            [(mn[0], mx[0])[(i >> 0) & 1], (mn[1], mx[1])[(i >> 1) & 1], (mn[2], mx[2])[(i >> 2) & 1]]
            for i in range(8)
        ],
        dtype=np.float64,
    )
    tc = apply_affine(a, corners)
    return tc.min(axis=0), tc.max(axis=0)


def transformed_interval(a: np.ndarray, interval: Interval) -> Interval:
    """Smallest integer interval containing the transformed interval
    (imglib2 ``Intervals.smallestContainingInterval`` of the estimated bounds)."""
    lo, hi = estimate_bounds(a, interval)
    return Interval(tuple(np.floor(lo).astype(np.int64)), tuple(np.ceil(hi).astype(np.int64)))

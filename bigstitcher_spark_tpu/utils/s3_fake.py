"""Minimal in-process S3-protocol server (GET/PUT/HEAD/DELETE +
ListObjectsV2 + Range reads) for driving tensorstore's REAL s3 kvstore
driver end-to-end without network egress — the role the reference fills
with actual S3 (cloud/TestCloudFunctions.java:42-181).

Auth headers (SigV4) are accepted and ignored; objects live in a dict.
Promoted from the test tree so the bench's ``measure_cloud`` extra and
the cloud smoke script share one fixture with the test suite
(tests/s3_fake.py is a re-export shim).

Fault/latency injection for tiered-IO experiments:

- ``latency_s``: per-request sleep — a dialable stand-in for
  object-store round-trip time, what makes prefetch overlap measurable
  on localhost.
- ``fail_puts``: fail the next N PUT requests with HTTP 500 — drives
  the multipart upload retry path (parallel.retry) without network
  flakes.
"""

from __future__ import annotations

import hashlib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse
from xml.sax.saxutils import escape


class S3FakeServer:
    def __init__(self, latency_s: float = 0.0):
        self.objects: dict[str, bytes] = {}
        self.lock = threading.Lock()
        self.requests: list[str] = []  # method + path log (assertable)
        self.latency_s = float(latency_s)
        self.fail_puts = 0             # next N PUTs answer HTTP 500
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _key(self):
                # path: /<bucket>/<key>  (path-style addressing)
                parts = unquote(urlparse(self.path).path).lstrip("/")
                return parts.split("/", 1)[1] if "/" in parts else ""

            def _respond(self, code, body=b"", headers=None):
                self.send_response(code)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _not_found(self):
                body = (b'<?xml version="1.0"?><Error><Code>NoSuchKey'
                        b"</Code><Message>absent</Message></Error>")
                self._respond(404, body,
                              {"Content-Type": "application/xml"})

            def _lag(self):
                if server.latency_s > 0:
                    time.sleep(server.latency_s)

            def do_GET(self):
                server.requests.append(f"GET {self.path}")
                self._lag()
                q = parse_qs(urlparse(self.path).query)
                if "list-type" in q:
                    return self._list(q)
                key = self._key()
                with server.lock:
                    data = server.objects.get(key)
                if data is None:
                    return self._not_found()
                etag = hashlib.md5(data).hexdigest()
                rng = self.headers.get("Range")
                if rng and rng.startswith("bytes="):
                    lo_s, _, hi_s = rng[6:].partition("-")
                    lo = int(lo_s) if lo_s else 0
                    hi = int(hi_s) if hi_s else len(data) - 1
                    hi = min(hi, len(data) - 1)
                    part = data[lo:hi + 1]
                    return self._respond(206, part, {
                        "ETag": f'"{etag}"',
                        "Content-Range":
                            f"bytes {lo}-{hi}/{len(data)}",
                        "Content-Type": "application/octet-stream"})
                self._respond(200, data, {
                    "ETag": f'"{etag}"',
                    "Content-Type": "application/octet-stream"})

            def _list(self, q):
                prefix = q.get("prefix", [""])[0]
                start_after = q.get("start-after", [""])[0]
                token = q.get("continuation-token", [""])[0]
                with server.lock:
                    keys = sorted(k for k in server.objects
                                  if k.startswith(prefix)
                                  and k > max(start_after, token))
                max_keys = int(q.get("max-keys", ["1000"])[0])
                page, rest = keys[:max_keys], keys[max_keys:]
                parts = ['<?xml version="1.0" encoding="UTF-8"?>',
                         "<ListBucketResult>",
                         f"<KeyCount>{len(page)}</KeyCount>",
                         f"<IsTruncated>{'true' if rest else 'false'}"
                         "</IsTruncated>"]
                if rest:
                    parts.append("<NextContinuationToken>"
                                 f"{escape(page[-1])}"
                                 "</NextContinuationToken>")
                with server.lock:
                    for k in page:
                        parts.append(
                            f"<Contents><Key>{escape(k)}</Key>"
                            f"<Size>{len(server.objects[k])}</Size>"
                            "</Contents>")
                parts.append("</ListBucketResult>")
                self._respond(200, "".join(parts).encode(),
                              {"Content-Type": "application/xml"})

            def do_HEAD(self):
                server.requests.append(f"HEAD {self.path}")
                self._lag()
                key = self._key()
                if not key:  # HeadBucket
                    return self._respond(200)
                with server.lock:
                    data = server.objects.get(key)
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                etag = hashlib.md5(data).hexdigest()
                self.send_response(200)
                self.send_header("ETag", f'"{etag}"')
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()

            def do_PUT(self):
                server.requests.append(f"PUT {self.path}")
                self._lag()
                n = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(n)
                with server.lock:
                    if server.fail_puts > 0:
                        server.fail_puts -= 1
                        body = (b'<?xml version="1.0"?><Error><Code>'
                                b"InternalError</Code><Message>injected"
                                b"</Message></Error>")
                        return self._respond(
                            500, body,
                            {"Content-Type": "application/xml"})
                    server.objects[self._key()] = data
                etag = hashlib.md5(data).hexdigest()
                self._respond(200, b"", {"ETag": f'"{etag}"'})

            def do_DELETE(self):
                server.requests.append(f"DELETE {self.path}")
                self._lag()
                key = self._key()
                with server.lock:
                    server.objects.pop(key, None)
                self._respond(204)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        # raw daemon thread on purpose: test-fixture HTTP server, no job
        # context exists to carry into it
        self.thread = threading.Thread(target=self.httpd.serve_forever,  # bst-lint: off=thread-spawn
                                       daemon=True)

    def start(self):
        self.thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    def remote_request_count(self, method: str | None = None) -> int:
        """Requests seen so far, optionally one HTTP method's — the
        warm-rerun "zero remote rereads" assertion reads the delta."""
        with self.lock:
            if method is None:
                return len(self.requests)
            return sum(1 for r in self.requests
                       if r.startswith(method + " "))

"""Cooperative job cancellation.

A long-lived ``bst serve`` daemon must be able to stop ONE in-flight job
without touching the others or the device mesh: killing threads is not a
thing, and abandoning a dispatch loop mid-run leaks in-flight windows and
half-written state. Instead a :class:`CancelToken` travels with the job
in a context variable (propagated into worker threads/pools by
:mod:`utils.threads`), and the shared work loops — the retry layer, the
sharded batch loop, the pair scheduler — poll :func:`check` at their
natural safe points (between work items, never inside a device call).

Raising :class:`Cancelled` unwinds through the loops' normal error paths
with one crucial exception: it is NEVER retried or re-dispatched — a
cancelled task failing over to the next device would turn cancellation
into a tour of the mesh.

Outside any token scope every call here is a no-op (one contextvar read),
so the one-shot CLI tools pay nothing.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading


class Cancelled(RuntimeError):
    """The current job's cancel token was set; unwind, don't retry."""


class CancelToken:
    """One job's cancellation flag (set once, never cleared)."""

    def __init__(self):
        self._event = threading.Event()

    def cancel(self) -> None:
        self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()


_current: contextvars.ContextVar[CancelToken | None] = \
    contextvars.ContextVar("bst-cancel-token", default=None)


def current() -> CancelToken | None:
    return _current.get()


@contextlib.contextmanager
def scope(token: CancelToken):
    """Make ``token`` the ambient cancel token for this context (and, via
    utils.threads, every worker spawned under it)."""
    tok = _current.set(token)
    try:
        yield token
    finally:
        _current.reset(tok)


def cancelled() -> bool:
    """Whether the ambient token (if any) has been cancelled."""
    t = _current.get()
    return t is not None and t.cancelled


def check(where: str | None = None) -> None:
    """Raise :class:`Cancelled` when the ambient token is set; no-op
    otherwise (and always outside any token scope)."""
    if cancelled():
        raise Cancelled(f"job cancelled{f' at {where}' if where else ''}")

"""Output-grid enumeration for block-parallel stages.

Equivalent of the reference's ``Grid.create(dims, computeBlockSize, blockSize)``
(used at SparkAffineFusion.java:456-463, SparkResaveN5.java:192-198): tile an
n-D volume into *compute blocks* that are integer multiples of the *storage
block* size, so that concurrent writers always own disjoint storage chunks —
the reference's central race-freedom invariant (SURVEY.md §5).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence


@dataclass(frozen=True)
class GridBlock:
    """One work item of block-grid data parallelism (strategy P1).

    offset/size are in voxels relative to the dataset origin; grid_pos is the
    block position in units of STORAGE blocks (what N5 block writing needs).
    """

    offset: tuple[int, ...]
    size: tuple[int, ...]
    grid_pos: tuple[int, ...]

    @property
    def ndim(self) -> int:
        return len(self.offset)


def create_grid(
    dims: Sequence[int],
    compute_block_size: Sequence[int],
    storage_block_size: Sequence[int] | None = None,
) -> list[GridBlock]:
    """Enumerate compute blocks covering ``dims``.

    ``compute_block_size`` should be an integer multiple of
    ``storage_block_size`` per axis (the reference's ``blockSize * blockScale``);
    edge blocks are clipped to the volume.
    """
    dims = tuple(int(d) for d in dims)
    cbs = tuple(int(b) for b in compute_block_size)
    sbs = tuple(int(b) for b in (storage_block_size or compute_block_size))
    for c, s in zip(cbs, sbs):
        if c % s != 0:
            raise ValueError(
                f"compute block {cbs} must be a multiple of storage block {sbs}"
            )
    ndim = len(dims)
    counts = [(dims[d] + cbs[d] - 1) // cbs[d] for d in range(ndim)]

    blocks: list[GridBlock] = []
    idx = [0] * ndim
    total = 1
    for c in counts:
        total *= c
    for flat in range(total):
        rem = flat
        for d in range(ndim):
            idx[d] = rem % counts[d]
            rem //= counts[d]
        offset = tuple(idx[d] * cbs[d] for d in range(ndim))
        size = tuple(min(cbs[d], dims[d] - offset[d]) for d in range(ndim))
        grid_pos = tuple(offset[d] // sbs[d] for d in range(ndim))
        blocks.append(GridBlock(offset, size, grid_pos))
    return blocks

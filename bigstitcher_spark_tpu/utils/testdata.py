"""Synthetic tiled-acquisition generator for tests and benchmarks.

The reference tests against a public Janelia example dataset fetched from S3
(TestSparkResave.java:24-38); with zero egress we instead generate an
equivalent fixture: a global bead phantom, cropped into overlapping tiles with
KNOWN ground-truth offsets, written as a bdv.n5 BigStitcher project. The
nominal grid positions stored in the XML are perturbed so stitching /
registration have real error to recover.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from ..io.chunkstore import ChunkStore, StorageFormat
from ..io.dataset_io import create_bdv_view_datasets
from ..io.spimdata import (
    AttributeEntity,
    ImageLoader,
    SpimData,
    ViewId,
    ViewSetup,
    ViewTransform,
)
from .geometry import translation_affine


@dataclass
class SyntheticProject:
    spimdata: SpimData
    xml_path: str
    true_offsets: dict[int, np.ndarray]  # setup id -> true tile offset (xyz float)
    nominal_offsets: dict[int, np.ndarray]
    bead_positions: np.ndarray  # (N,3) in global coords


def make_bead_volume(shape, n_beads=150, sigma=1.8, seed=0, background=100.0,
                     amplitude=3000.0, min_separation=8.0,
                     smooth_field=0.0) -> tuple[np.ndarray, np.ndarray]:
    """Global phantom: Gaussian beads on constant background (float32).

    Beads keep ``min_separation`` px apart (closer blobs merge under the DoG
    and break localization-based assertions). ``smooth_field`` > 0 adds a
    low-frequency random intensity field of that amplitude — dynamic range in
    every region, which intensity matching needs."""
    rng = np.random.default_rng(seed)
    shape = tuple(int(s) for s in shape)
    pos_list: list[np.ndarray] = []
    for _ in range(n_beads * 50):
        if len(pos_list) >= n_beads:
            break
        p = rng.uniform(low=[4, 4, 4], high=[s - 4 for s in shape])
        if pos_list and np.min(
            np.linalg.norm(np.array(pos_list) - p, axis=1)
        ) < min_separation:
            continue
        pos_list.append(p)
    pos = np.array(pos_list)
    vol = np.full(shape, background, dtype=np.float32)
    if smooth_field > 0:
        coarse = rng.uniform(0, 1, (5, 5, 5)).astype(np.float32)
        for d, s in enumerate(shape):
            idx = np.linspace(0, coarse.shape[d] - 1, s)
            lo = np.floor(idx).astype(int)
            hi = np.minimum(lo + 1, coarse.shape[d] - 1)
            f = (idx - lo).astype(np.float32)
            sl_lo = [slice(None)] * 3
            sl_hi = [slice(None)] * 3
            sl_lo[d] = lo
            sl_hi[d] = hi
            shape_f = [1, 1, 1]
            shape_f[d] = s
            coarse = (coarse[tuple(sl_lo)] * (1 - f.reshape(shape_f))
                      + coarse[tuple(sl_hi)] * f.reshape(shape_f))
        vol += smooth_field * coarse
    r = int(np.ceil(3 * sigma))
    ax = np.arange(-r, r + 1, dtype=np.float32)
    gx = np.exp(-(ax ** 2) / (2 * sigma ** 2))
    for p in pos:
        ip = np.round(p).astype(int)
        fr = p - ip
        lo = ip - r
        hi = ip + r + 1
        if np.any(lo < 0) or np.any(hi > np.array(shape)):
            continue
        bx = np.exp(-((ax - fr[0]) ** 2) / (2 * sigma ** 2))
        by = np.exp(-((ax - fr[1]) ** 2) / (2 * sigma ** 2))
        bz = np.exp(-((ax - fr[2]) ** 2) / (2 * sigma ** 2))
        blob = amplitude * bx[:, None, None] * by[None, :, None] * bz[None, None, :]
        vol[lo[0]:hi[0], lo[1]:hi[1], lo[2]:hi[2]] += blob
    return vol, pos


def make_synthetic_project(
    out_dir: str,
    n_tiles=(2, 1, 1),
    tile_size=(96, 96, 48),
    overlap=24,
    jitter=3.0,
    n_channels=1,
    n_timepoints=1,
    dtype="uint16",
    seed=0,
    block_size=(64, 64, 32),
    n_beads_per_tile=40,
    downsampling_factors=((1, 1, 1),),
    smooth_field=0.0,
) -> SyntheticProject:
    """Write ``dataset.xml`` + ``dataset.n5`` under ``out_dir``."""
    rng = np.random.default_rng(seed + 1)
    n_tiles = tuple(int(v) for v in n_tiles)
    tile_size = tuple(int(v) for v in tile_size)
    step = tuple(ts - overlap for ts in tile_size)
    global_shape = tuple(
        step[d] * (n_tiles[d] - 1) + tile_size[d] + 8 for d in range(3)
    )
    total_tiles = n_tiles[0] * n_tiles[1] * n_tiles[2]
    vol, beads = make_bead_volume(
        global_shape, n_beads=n_beads_per_tile * total_tiles, seed=seed,
        smooth_field=smooth_field,
    )

    os.makedirs(out_dir, exist_ok=True)
    store = ChunkStore.create(os.path.join(out_dir, "dataset.n5"), StorageFormat.N5)

    sd = SpimData()
    sd.image_loader = ImageLoader(format="bdv.n5", path="dataset.n5")
    sd.timepoints = list(range(n_timepoints))
    sd.attributes["illumination"][0] = AttributeEntity(0, "0")
    sd.attributes["angle"][0] = AttributeEntity(0, "0")
    for c in range(n_channels):
        sd.attributes["channel"][c] = AttributeEntity(c, str(c))

    true_offsets: dict[int, np.ndarray] = {}
    nominal_offsets: dict[int, np.ndarray] = {}
    setup_id = 0
    info = np.iinfo(dtype) if np.issubdtype(np.dtype(dtype), np.integer) else None
    for tz in range(n_tiles[2]):
        for ty in range(n_tiles[1]):
            for tx in range(n_tiles[0]):
                tile_id = tx + n_tiles[0] * (ty + n_tiles[1] * tz)
                true_off = np.array(
                    [tx * step[0], ty * step[1], tz * step[2]], dtype=np.float64
                )
                true_off += rng.uniform(0, 4, 3).round()  # integer true offsets
                nominal = np.array(
                    [tx * step[0], ty * step[1], tz * step[2]], dtype=np.float64
                )
                if jitter > 0 and tile_id > 0:
                    nominal = true_off + rng.uniform(-jitter, jitter, 3).round()
                if tile_id not in {e.id for e in sd.attributes["tile"].values()}:
                    sd.attributes["tile"][tile_id] = AttributeEntity(
                        tile_id, str(tile_id),
                        {"location": " ".join(repr(v) for v in nominal)},
                    )
                io = np.round(true_off).astype(int)
                crop = vol[
                    io[0]:io[0] + tile_size[0],
                    io[1]:io[1] + tile_size[1],
                    io[2]:io[2] + tile_size[2],
                ]
                for c in range(n_channels):
                    img = crop * (1.0 + 0.15 * c)
                    noise = rng.normal(0, 8.0, img.shape)
                    img = img + noise
                    if info is not None:
                        img = np.clip(img, info.min, info.max).astype(dtype)
                    else:
                        img = img.astype(dtype)
                    vs = ViewSetup(
                        id=setup_id,
                        name=f"tile{tile_id}_ch{c}",
                        size=tile_size,
                        attributes={
                            "illumination": 0, "channel": c,
                            "tile": tile_id, "angle": 0,
                        },
                    )
                    sd.setups[setup_id] = vs
                    true_offsets[setup_id] = io.astype(np.float64)
                    nominal_offsets[setup_id] = nominal.copy()
                    for t in range(n_timepoints):
                        dss = create_bdv_view_datasets(
                            store, setup_id, t, tile_size, block_size, dtype,
                            downsampling_factors=downsampling_factors,
                        )
                        dss[0].write(img, (0, 0, 0))
                        for lvl in range(1, len(downsampling_factors)):
                            f = downsampling_factors[lvl]
                            ds_img = _downsample_avg(img, f)
                            dss[lvl].write(ds_img, (0, 0, 0))
                        sd.registrations[ViewId(t, setup_id)] = [
                            ViewTransform(
                                "Translation to Regular Grid",
                                translation_affine(nominal),
                            ),
                            ViewTransform("calibration", translation_affine((0, 0, 0))),
                        ]
                    setup_id += 1

    xml_path = os.path.join(out_dir, "dataset.xml")
    sd.save(xml_path)
    return SyntheticProject(sd, xml_path, true_offsets, nominal_offsets, beads)


def _downsample_avg(img: np.ndarray, factors) -> np.ndarray:
    out = img.astype(np.float64)
    for d, f in enumerate(factors):
        f = int(f)
        if f == 1:
            continue
        n = (out.shape[d] // f) * f
        sl = [slice(None)] * out.ndim
        sl[d] = slice(0, n)
        out = out[tuple(sl)]
        shape = list(out.shape)
        shape[d] = shape[d] // f
        shape.insert(d + 1, f)
        out = out.reshape(shape).mean(axis=d + 1)
    return out.astype(img.dtype)

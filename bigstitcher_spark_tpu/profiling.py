"""Lightweight tracing/profiling: named spans with aggregate wall-clock.

The reference only prints per-stage ``currentTimeMillis`` deltas
(SparkAffineFusion.java:424,470,698); we keep per-span aggregates
(count/total/min/max) queryable in-process and printable per stage.
Zero overhead when disabled.

``span`` is also the begin/end source for the timeline flight recorder
(:mod:`.observe.trace`): when tracing is on, every span forwards its
begin/end (plus optional device/stage/item/byte attribution) to the
ring buffer under the SAME name, so the trace and the aggregates can
never disagree about what was measured.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from dataclasses import dataclass

from .observe import trace as _trace


@dataclass
class SpanStat:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0
    min_s: float = 0.0


class Profiler:
    def __init__(self):
        self.enabled = False
        self._stats: dict[str, SpanStat] = defaultdict(SpanStat)
        self._lock = threading.Lock()

    def reset(self):
        with self._lock:
            self._stats.clear()

    def record(self, name: str, dt: float):
        with self._lock:
            s = self._stats[name]
            s.min_s = dt if s.count == 0 else min(s.min_s, dt)
            s.count += 1
            s.total_s += dt
            s.max_s = max(s.max_s, dt)

    def stats(self) -> dict[str, SpanStat]:
        with self._lock:
            return {k: SpanStat(v.count, v.total_s, v.max_s, v.min_s)
                    for k, v in self._stats.items()}

    def report(self) -> str:
        # stats() snapshots under the lock — iterating self._stats directly
        # here raced with concurrent record() calls mutating the dict.
        # Sorted by total_s DESC so the hot span is the first line.
        stats = self.stats()
        lines = ["span                            count    total_s     "
                 "mean_s      min_s      max_s"]
        for k in sorted(stats, key=lambda k: (-stats[k].total_s, k)):
            s = stats[k]
            lines.append(
                f"{k:<30} {s.count:>6} {s.total_s:>10.3f} "
                f"{s.total_s / max(s.count, 1):>10.3f} "
                f"{s.min_s:>10.3f} {s.max_s:>10.3f}")
        return "\n".join(lines)


_global = Profiler()


def enable(on: bool = True):
    _global.enabled = on


def get() -> Profiler:
    return _global


@contextlib.contextmanager
def span(name: str, *, device: int | None = None, stage: str | None = None,
         item=None, nbytes: int | None = None):
    """Aggregate-profiled (and, when tracing, timeline-recorded) span.

    The attribution kwargs cost nothing off the hot path: disabled, the
    whole call is two truthiness checks and an immediate yield."""
    tracing = _trace.enabled()
    if not _global.enabled and not tracing:
        yield
        return
    if tracing:
        _trace.record("B", name, device=device, stage=stage, item=item,
                      nbytes=nbytes)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if _global.enabled:
            _global.record(name, time.perf_counter() - t0)
        if tracing:
            _trace.record("E", name, device=device, stage=stage, item=item,
                          nbytes=nbytes)


def device_sync(x):
    """Block until the device array(s) in `x` have truly been computed.

    `jax.Array.block_until_ready` is NOT a completion barrier on every
    backend: the axon-tunneled TPU client acknowledges *enqueue* (it
    returns in ~0.2 ms for programs whose execution, bounded below by HBM
    bandwidth, takes >2 ms). Fetching one element is a data dependency no
    transport can fake, so span attribution around kernels stays honest.
    On ordinary local backends the extra fetch costs microseconds."""
    import jax
    import numpy as np

    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "dtype") and hasattr(leaf, "ndim") and leaf.size:
            # direct one-element index: no full-size ravel intermediate
            np.asarray(leaf[(0,) * leaf.ndim] if leaf.ndim else leaf)
    return x

"""Multi-host scale-out: process bootstrap + deterministic work partition.

The reference scales by launching Spark executors on many nodes (LSF/SGE via
flintstone, EMR/Dataproc — src/main/scripts/flintstone-sge-example.sh:29-119,
pom.xml:200-260); work items are distributed by the Spark driver. The TPU
analogue (SURVEY §2.5) is SPMD: every host runs the SAME driver program,
``jax.distributed.initialize`` wires the processes into one runtime (ICI
within a pod slice, DCN across), and each process takes a deterministic
slice of the same host-side work list, sharding it over its LOCAL devices.
Block writers own disjoint output chunks (the reference's no-shuffle
invariant), so no cross-host communication is needed for fusion / resave /
downsample / nonrigid — exactly like the reference's executors.

Launch recipe (two hosts):

    # host 0                                           # host 1
    BST_COORDINATOR=host0:8476 \
    BST_NUM_PROCESSES=2 BST_PROCESS_ID=0 \
    bst affine-fusion -o out.zarr                      ... BST_PROCESS_ID=1 ...

(or on Cloud TPU pods just run the command on every worker —
``jax.distributed.initialize()`` autodetects the topology there).

Stages that COLLECT results to the project XML (detection, matching,
stitching, solver) follow the reference's driver-side-collect design and
should run single-process; the block-writing stages are where the volume is.
"""

from __future__ import annotations

from typing import Sequence

_initialized = [False]


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    start_relay: bool = True,
) -> bool:
    """Initialize the multi-host runtime (jax.distributed) once per process.

    Arguments default to ``BST_COORDINATOR`` / ``BST_NUM_PROCESSES`` /
    ``BST_PROCESS_ID``; returns True when a multi-process runtime was set up,
    False for the ordinary single-process case (no env, no args).

    The telemetry relay (observe/relay.py) brings up beside the runtime
    whenever ``BST_TELEMETRY_RELAY`` is set — rank 0 collects, everyone
    else pushes — so the pod's live plane exists from the first stage.
    ``start_relay=False`` skips it (short management/client tools that
    have nothing live to report)."""
    try:
        if _initialized[0]:
            return True
        from .. import config

        coordinator_address = (coordinator_address
                               or config.get_str("BST_COORDINATOR"))
        # topology knobs parse via raw_value + int() so a malformed value
        # aborts the launch loudly — config.get's unparseable-falls-back
        # rule would silently run this host single-process while the rest
        # of the pod blocks at the first barrier
        raw_np = config.raw_value("BST_NUM_PROCESSES")
        if num_processes is None and raw_np is not None:
            num_processes = int(raw_np)
        raw_pid = config.raw_value("BST_PROCESS_ID")
        if process_id is None and raw_pid is not None:
            process_id = int(raw_pid)
        import jax

        if coordinator_address is None and num_processes is None:
            if config.get_bool("BST_DISTRIBUTED"):
                # Cloud TPU pod / SLURM: topology autodetected by jax
                jax.distributed.initialize()
                _initialized[0] = True
                return True
            return False
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized[0] = True
        return True
    finally:
        if start_relay:
            _relay_bringup()


def _relay_bringup() -> None:
    """Knob-gated, idempotent, and never fatal: losing the pod's live
    view must not block the launch it observes."""
    from ..observe import relay

    try:
        relay.ensure_started()
    except Exception as e:
        from ..observe import log

        log(f"telemetry relay disabled: {e!r}", stage="observe")


def barrier(name: str = "bst") -> None:
    """Cross-host barrier for read-after-write stage boundaries (e.g. s0
    copy -> pyramid level 1, level k -> level k+1): a later stage may read
    chunks another process wrote, so all processes must pass the boundary
    together. No-op at world size 1 (the reference gets the same ordering
    from Spark's stage-by-stage collect).

    Wait time is recorded per barrier name — it is the straggler signal of
    a pod run (a process stuck in IO shows up as everyone else's barrier
    seconds)."""
    if world()[1] <= 1:
        return
    import time

    from jax.experimental import multihost_utils

    from ..observe import events, metrics, trace

    t0 = time.perf_counter()
    # the trace span doubles as the multihost clock-alignment anchor: all
    # processes leave sync_global_devices together, so telemetry-merge can
    # shift per-process traces onto one timeline via equal-named exits
    with trace.span("barrier", stage=name):
        multihost_utils.sync_global_devices(name)
    dt = time.perf_counter() - t0
    metrics.histogram("bst_barrier_seconds", name=name).observe(dt)
    events.emit("barrier", name=name, seconds=round(dt, 4))


def world() -> tuple[int, int]:
    """(process_index, process_count) of the current runtime."""
    import jax

    return jax.process_index(), jax.process_count()


def partition_items(
    items: Sequence,
    process_index: int | None = None,
    process_count: int | None = None,
) -> list:
    """This process's slice of a work list: strided round-robin
    ``items[i::count]`` — deterministic, covers every item exactly once
    across processes, degenerates to the full list at world size 1, and
    interleaves neighbouring (similar-cost) blocks across hosts for balance.
    """
    if process_index is None or process_count is None:
        pi, pc = world()
        process_index = pi if process_index is None else process_index
        process_count = pc if process_count is None else process_count
    if process_count <= 1:
        return list(items)
    if not (0 <= process_index < process_count):
        raise ValueError(
            f"process_index {process_index} outside world size {process_count}")
    return list(items[process_index::process_count])

"""Multi-host scale-out: process bootstrap + deterministic work partition.

The reference scales by launching Spark executors on many nodes (LSF/SGE via
flintstone, EMR/Dataproc — src/main/scripts/flintstone-sge-example.sh:29-119,
pom.xml:200-260); work items are distributed by the Spark driver. The TPU
analogue (SURVEY §2.5) is SPMD: every host runs the SAME driver program,
``jax.distributed.initialize`` wires the processes into one runtime (ICI
within a pod slice, DCN across), and each process takes a deterministic
slice of the same host-side work list, sharding it over its LOCAL devices.
Block writers own disjoint output chunks (the reference's no-shuffle
invariant), so no cross-host communication is needed for fusion / resave /
downsample / nonrigid — exactly like the reference's executors.

Launch recipe (two hosts):

    # host 0                                           # host 1
    BST_COORDINATOR=host0:8476 \
    BST_NUM_PROCESSES=2 BST_PROCESS_ID=0 \
    bst affine-fusion -o out.zarr                      ... BST_PROCESS_ID=1 ...

(or on Cloud TPU pods just run the command on every worker —
``jax.distributed.initialize()`` autodetects the topology there).

Stages that COLLECT results to the project XML (detection, matching,
stitching, solver) historically ran single-process; with the global
execution mesh they join the scale-out too: the pair-parallel stages
split across processes and :func:`allgather_object` merges the results so
every rank still holds the full list (parallel/pairsched.py), and the
sharded device solves span every process's devices over one global
"links" mesh axis (ops/solve.py, BST_SOLVE_GLOBAL).
"""

from __future__ import annotations

from typing import Sequence

_initialized = [False]


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    start_relay: bool = True,
) -> bool:
    """Initialize the multi-host runtime (jax.distributed) once per process.

    Arguments default to ``BST_COORDINATOR`` / ``BST_NUM_PROCESSES`` /
    ``BST_PROCESS_ID``; returns True when a multi-process runtime was set up,
    False for the ordinary single-process case (no env, no args).

    The telemetry relay (observe/relay.py) brings up beside the runtime
    whenever ``BST_TELEMETRY_RELAY`` is set — rank 0 collects, everyone
    else pushes — so the pod's live plane exists from the first stage.
    ``start_relay=False`` skips it (short management/client tools that
    have nothing live to report)."""
    try:
        if _initialized[0]:
            return True
        from .. import config

        coordinator_address = (coordinator_address
                               or config.get_str("BST_COORDINATOR"))
        # topology knobs parse via raw_value + int() so a malformed value
        # aborts the launch loudly — config.get's unparseable-falls-back
        # rule would silently run this host single-process while the rest
        # of the pod blocks at the first barrier
        raw_np = config.raw_value("BST_NUM_PROCESSES")
        if num_processes is None and raw_np is not None:
            num_processes = int(raw_np)
        raw_pid = config.raw_value("BST_PROCESS_ID")
        if process_id is None and raw_pid is not None:
            process_id = int(raw_pid)
        import jax

        if coordinator_address is None and num_processes is None:
            if config.get_bool("BST_DISTRIBUTED"):
                # Cloud TPU pod / SLURM: topology autodetected by jax
                _enable_cpu_collectives(jax)
                jax.distributed.initialize()
                _initialized[0] = True
                return True
            return False
        _enable_cpu_collectives(jax)
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized[0] = True
        return True
    finally:
        if start_relay:
            _relay_bringup()


def _enable_cpu_collectives(jax_mod) -> None:
    """Select the gloo cross-process collectives for the CPU backend
    BEFORE it initializes — without it a multi-process CPU world raises
    "Multiprocess computations aren't implemented on the CPU backend" at
    the first psum. Harmless on accelerator platforms (the flag only
    affects XLA:CPU) and on jax builds without the option."""
    try:
        jax_mod.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def _relay_bringup() -> None:
    """Knob-gated, idempotent, and never fatal: losing the pod's live
    view must not block the launch it observes."""
    from ..observe import relay

    try:
        relay.ensure_started()
    except Exception as e:
        from ..observe import log

        log(f"telemetry relay disabled: {e!r}", stage="observe")


def barrier(name: str = "bst") -> None:
    """Cross-host barrier for read-after-write stage boundaries (e.g. s0
    copy -> pyramid level 1, level k -> level k+1): a later stage may read
    chunks another process wrote, so all processes must pass the boundary
    together. No-op at world size 1 (the reference gets the same ordering
    from Spark's stage-by-stage collect).

    Wait time is recorded per barrier name — it is the straggler signal of
    a pod run (a process stuck in IO shows up as everyone else's barrier
    seconds)."""
    if world()[1] <= 1:
        return
    import time

    from jax.experimental import multihost_utils

    from ..observe import events, metrics, trace

    t0 = time.perf_counter()
    # the trace span doubles as the multihost clock-alignment anchor: all
    # processes leave sync_global_devices together, so telemetry-merge can
    # shift per-process traces onto one timeline via equal-named exits
    with trace.span("barrier", stage=name):
        multihost_utils.sync_global_devices(name)
    dt = time.perf_counter() - t0
    metrics.histogram("bst_barrier_seconds", name=name).observe(dt)
    events.emit("barrier", name=name, seconds=round(dt, 4))


def world() -> tuple[int, int]:
    """(process_index, process_count) of the current runtime."""
    import jax

    return jax.process_index(), jax.process_count()


def partition_items(
    items: Sequence,
    process_index: int | None = None,
    process_count: int | None = None,
) -> list:
    """This process's slice of a work list: strided round-robin
    ``items[i::count]`` — deterministic, covers every item exactly once
    across processes, degenerates to the full list at world size 1, and
    interleaves neighbouring (similar-cost) blocks across hosts for balance.
    """
    if process_index is None or process_count is None:
        pi, pc = world()
        process_index = pi if process_index is None else process_index
        process_count = pc if process_count is None else process_count
    if process_count <= 1:
        return list(items)
    if not (0 <= process_index < process_count):
        raise ValueError(
            f"process_index {process_index} outside world size {process_count}")
    return list(items[process_index::process_count])


def partition_indices_weighted(
    costs: Sequence[float],
    process_index: int | None = None,
    process_count: int | None = None,
) -> list[int]:
    """Cost-aware process partition: LPT over the whole world, same
    greedy as pairsched's device placement (heaviest first into the
    least-loaded bin, ties by index / lowest bin) so a heavy-tailed pair
    list doesn't straggle one process the way strided round-robin can.
    Deterministic: every process computes the SAME assignment from the
    same costs. Returns THIS process's item indices in ascending
    (original) order; degenerates to range(len) at world size 1."""
    if process_index is None or process_count is None:
        pi, pc = world()
        process_index = pi if process_index is None else process_index
        process_count = pc if process_count is None else process_count
    n = len(costs)
    if process_count <= 1:
        return list(range(n))
    if not (0 <= process_index < process_count):
        raise ValueError(
            f"process_index {process_index} outside world size {process_count}")
    order = sorted(range(n), key=lambda i: (-max(float(costs[i]), 0.0), i))
    loads = [0.0] * process_count
    mine: list[int] = []
    for i in order:
        b = loads.index(min(loads))
        loads[b] += max(float(costs[i]), 1e-9)
        if b == process_index:
            mine.append(i)
    mine.sort()
    return mine


def partition_items_weighted(
    items: Sequence,
    costs: Sequence[float],
    process_index: int | None = None,
    process_count: int | None = None,
) -> list:
    """:func:`partition_items` with LPT cost balancing: this process's
    slice of ``items`` (original relative order preserved), where slices
    are chosen so per-process total cost is near-equal. ``costs`` must
    align with ``items``; cost-free callers should keep the round-robin
    :func:`partition_items`."""
    if len(items) != len(costs):
        raise ValueError(
            f"items/costs length mismatch: {len(items)} != {len(costs)}")
    idx = partition_indices_weighted(costs, process_index, process_count)
    return [items[i] for i in idx]


def allgather_object(obj):
    """Gather one picklable object per process; every rank returns the
    rank-ordered list ``[obj_0, ..., obj_{pc-1}]``. This is the merge
    primitive behind the multihost pair split (each process computes its
    slice, everyone ends with the full result list — the SPMD analogue
    of Spark's driver-side collect). World size 1 returns ``[obj]``
    without touching the runtime. Collective: every process must call it
    the same number of times, in the same order."""
    pi, pc = world()
    if pc <= 1:
        return [obj]
    import pickle

    import numpy as np
    from jax.experimental import multihost_utils

    blob = np.frombuffer(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), dtype=np.uint8)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.array([blob.size], dtype=np.int64))).reshape(pc)
    buf = np.zeros(int(sizes.max()), dtype=np.uint8)
    buf[:blob.size] = blob
    rows = np.asarray(multihost_utils.process_allgather(buf))
    return [pickle.loads(rows[i, :int(sizes[i])].tobytes())
            for i in range(pc)]

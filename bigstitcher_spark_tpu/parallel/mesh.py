"""Device-mesh sharding of the block work list.

TPU-native replacement of the reference's Spark data parallelism (§2.4 P1):
a batch of output blocks becomes the leading axis of the stacked kernel
inputs, sharded over a 1-D ``jax.sharding.Mesh`` — each device fuses its
shard of blocks; no collectives are needed because block writes are disjoint
(the reference's no-shuffle property, the Spark map at
SparkAffineFusion.java:480-482). Multi-host scale-out uses the same mesh
spanning hosts (ICI within pod, DCN across — jax.distributed).

``make_sharded_fuser`` serves the production per-block fusion driver
(models/affine_fusion.fuse_volume with devices > 1): both the general
gather kernel and the translation shifted-slice kernel batch over blocks,
with intensity conversion fused into the same device computation so each
block crosses the host boundary exactly twice (patch in, converted block
out).
"""

from __future__ import annotations

import functools
import threading

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import fusion as F
from ..observe import metrics as _metrics
from .. import config, observe, profiling

BLOCK_AXIS = "blocks"

# host<->device transfer accounting (the tunnel/PCIe wire is the scarce
# resource on remote accelerators — PERF.md §3h): stacked batch inputs are
# the h2d side, fetched outputs the d2h side. The *_saved counters record
# bytes the native-dtype transport kept OFF the wire versus shipping
# float32 (uint8/uint16 stacks cast to f32 on device, integer outputs
# converted to storage dtype on device) so artifacts can prove the
# reduction without a counterfactual run.
_H2D_BYTES = _metrics.counter("bst_xfer_h2d_bytes_total")
_D2H_BYTES = _metrics.counter("bst_xfer_d2h_bytes_total")
_H2D_SAVED = _metrics.counter("bst_xfer_h2d_bytes_saved_total")
_D2H_SAVED = _metrics.counter("bst_xfer_d2h_bytes_saved_total")


# which device's shard the current thread is draining (set by the
# per-device drain workers of run_sharded_batches); consumers use it to
# attribute their spans — e.g. models/affine_fusion's `fusion.write` — to
# the owning device's trace track instead of an anonymous host thread
_DRAIN_TLS = threading.local()


def drain_device() -> int | None:
    """Device ordinal whose shard the calling thread is draining, or None
    outside a per-device drain worker."""
    return getattr(_DRAIN_TLS, "device", None)


def narrow_dtype_savings(arrays) -> int:
    """Wire bytes saved by shipping sub-float32-width integer arrays
    natively instead of as the float32 the kernels compute in."""
    return sum(a.size * 4 - a.nbytes for a in arrays
               if getattr(a, "dtype", None) is not None
               and a.dtype.kind in "iu" and a.dtype.itemsize < 4)


def _commit_host_args(fn, shardings):
    """Multi-process runtimes refuse host numpy args to a jit with
    non-replicated shardings (JAX cannot tell host-local data from
    global); commit them onto their shardings explicitly first — all
    devices here are local, so the device_put is an ordinary H2D.
    Single-process dispatch passes through untouched."""
    def dispatch(*args, **kwargs):
        if jax.process_count() > 1:
            args = tuple(
                jax.device_put(a, s)
                if not isinstance(a, jax.Array)
                and not s.is_fully_replicated else a
                for a, s in zip(args, shardings))
        return fn(*args, **kwargs)
    return dispatch


@functools.lru_cache(maxsize=8)
def _cached_mesh(n_devices: int | None) -> Mesh:
    # LOCAL devices only: under jax.distributed each process works an
    # independent slice of the grid (partition_items), so its mesh must not
    # span other hosts' devices — a global mesh fed different per-process
    # inputs violates the multi-controller SPMD contract (all collectives /
    # cross-host programs here go through barrier() instead)
    devs = list(jax.local_devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (BLOCK_AXIS,))


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    # cached per device count: a stable Mesh identity lets the jitted fuser
    # cache (make_sharded_fuser) hit across volumes/runs instead of
    # recompiling per call
    if devices is not None:
        return Mesh(np.array(list(devices)), (BLOCK_AXIS,))
    return _cached_mesh(n_devices)


# warm-vs-cold accounting for the compiled-fn bucket tables (this one and
# the composite factory in ops.fusion): a resident `bst serve` process
# amortizes compiles across jobs, and these counters are how that claim
# becomes a recorded per-job delta instead of an anecdote
_COMPILE_WARM = _metrics.counter("bst_compiled_fn_warm_hits_total")
_COMPILE_COLD = _metrics.counter("bst_compiled_fn_cold_builds_total")
# per-namespace LRU MIRRORS of the lru_caches being fronted, same
# capacity and same request sequence (record runs right before the
# factory call), so eviction here tracks eviction there — an unbounded
# seen-set would keep reporting "warm" for signatures the bounded
# lru_cache already dropped and must recompile
_BUCKET_CAPS = {"sharded": 64, "composite": 32, "solve": 32,
                "solve_cg": 16}
_BUCKET_LRU: dict[str, "OrderedDict"] = {}
_BUCKET_LOCK = threading.Lock()


def record_compile_bucket(key) -> bool:
    """Register one compiled-fn bucket request; returns True (and counts a
    warm hit) when ``key`` is still resident in its factory's bounded
    cache, else counts a cold build. ``key[0]`` names the factory
    namespace. Shared by every lru_cache'd kernel-factory call site."""
    from collections import OrderedDict

    ns = key[0] if isinstance(key, tuple) and key \
        and isinstance(key[0], str) else "default"
    cap = _BUCKET_CAPS.get(ns, 64)
    with _BUCKET_LOCK:
        lru = _BUCKET_LRU.setdefault(ns, OrderedDict())
        warm = key in lru
        lru[key] = True
        lru.move_to_end(key)
        while len(lru) > cap:
            lru.popitem(last=False)
    (_COMPILE_WARM if warm else _COMPILE_COLD).inc()
    return warm


def make_sharded_fuser(
    mesh: Mesh,
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    kernel: str = "gather",           # gather | shift
    with_coeffs: bool = False,
    out_dtype: str | None = None,     # fuse intensity conversion on device
    masks: bool = False,
    pyramid: tuple = (),              # per-level relative factors: the
                                      # fused multiscale epilogue
):
    """The compiled-fn bucket table's front door: resolve (building if
    needed) the sharded fuser for this signature and record whether the
    request was warm. See :func:`_build_sharded_fuser` for the kernel
    semantics."""
    key = (mesh, block_shape, fusion_type, kernel, with_coeffs, out_dtype,
           masks, pyramid)
    record_compile_bucket(("sharded",) + key)
    return _build_sharded_fuser(*key)


@functools.lru_cache(maxsize=64)
def _build_sharded_fuser(
    mesh: Mesh,
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
    kernel: str = "gather",
    with_coeffs: bool = False,
    out_dtype: str | None = None,
    masks: bool = False,
    pyramid: tuple = (),
):
    """Compile a fuser for a BATCH of blocks sharded over the mesh.

    lru_cache'd so repeated volumes (multi-channel/timepoint loops, repeated
    runs) reuse the jitted callable instead of recompiling per call.

    Inputs get a leading batch axis B (a multiple of mesh size; pad with
    valid=0 blocks). Returns ``fn(*arrays) -> (out (B,*block_shape), wsum[,
    level1, ...])`` where ``out`` is already intensity-converted when
    ``out_dtype`` is given (min_i/max_i are appended scalar args in that
    case). ``pyramid`` chains per-block downsample levels as a kernel
    epilogue — each a strided f32 mean of the previous level quantized to
    the storage dtype between steps (ops.downsample.convert_storage), the
    exact container-reread semantics — so the whole pyramid ships in the
    block's one drain; callers must pre-check divisibility
    (models.affine_fusion.eligible_epilogue_levels)."""
    if kernel == "gather":
        def core(p, a, o, d, b, r, v, io, c=None, ca=None):
            return F.fuse_block_impl(
                p, a, o, d, b, r, v, block_shape=block_shape,
                fusion_type=fusion_type, inside_offs=io, coeffs=c,
                coeff_affines=ca,
            )

        n_in = 10 if with_coeffs else 8
    elif kernel == "sep":
        def core(p, dg, t, o, d, b, r, v, io):
            return F.fuse_block_sep_impl(
                p, dg, t, o, d, b, r, v, block_shape=block_shape,
                fusion_type=fusion_type, inside_offs=io,
            )

        n_in = 9
    elif kernel == "shift":
        def core(p, f, l, d, b, r, v, io):  # noqa: E741
            return F.fuse_block_shift_impl(
                p, f, l, d, b, r, v, block_shape=block_shape,
                fusion_type=fusion_type, inside_offs=io,
            )

        n_in = 8
    else:
        raise ValueError(f"unknown kernel {kernel}")

    def one(args, min_i, max_i):
        fused, wsum = core(*args)
        if masks:
            fused = (wsum > 0).astype(jnp.float32)
            if out_dtype is not None and out_dtype != "float32":
                fused = (fused * float(np.iinfo(np.dtype(out_dtype)).max)
                         ).astype(np.dtype(out_dtype))
        elif out_dtype is not None:
            fused = F._convert_intensity_expr(fused, min_i, max_i, out_dtype)
        levels = []
        if pyramid:
            from ..ops.downsample import convert_storage, downsample_block

            cur = fused
            dt = out_dtype or "float32"
            for rel in pyramid:
                cur = convert_storage(
                    downsample_block(cur, tuple(int(f) for f in rel)), dt)
                levels.append(cur)
        return (fused, wsum, *levels)

    def batched(min_i, max_i, *arrays):
        return jax.vmap(lambda *a: one(a, min_i, max_i))(*arrays)

    shard = NamedSharding(mesh, P(BLOCK_AXIS))
    repl = NamedSharding(mesh, P())
    in_shardings = (repl, repl) + (shard,) * n_in
    return _commit_host_args(jax.jit(
        batched,
        in_shardings=in_shardings,
        out_shardings=(shard,) * (2 + len(pyramid)),
    ), in_shardings)


def pad_batch(arrays: Sequence[np.ndarray], batch: int) -> list[np.ndarray]:
    """Pad each stacked input along axis 0 to ``batch`` (extra entries are
    all-zero => valid mask 0 => no-op blocks). Device-resident inputs
    (a streaming handoff edge feeding this stage) pad on device — they
    must never round-trip through host memory here."""
    out = []
    for a in arrays:
        if a.shape[0] == batch:
            out.append(a)
        elif isinstance(a, jax.Array):
            import jax.numpy as jnp

            pad = jnp.zeros((batch - a.shape[0],) + a.shape[1:], a.dtype)
            out.append(jnp.concatenate([a, pad], axis=0))
        else:
            pad = np.zeros((batch - a.shape[0],) + a.shape[1:], a.dtype)
            out.append(np.concatenate([a, pad], axis=0))
    return out


def stack_inputs(inputs: Sequence, j: int):
    """Stack input ``j`` of every build result along a new batch axis —
    on host for numpy inputs, ON DEVICE when any item arrived as a jax
    array (a device-resident handoff read): ``np.stack`` over jax arrays
    would silently device_get every one of them."""
    parts = [inp[j] for inp in inputs]
    if any(isinstance(p, jax.Array) for p in parts):
        import jax.numpy as jnp

        # handoff chunks arrive committed to their PRODUCER's device;
        # stacking mixed placements is an error, so gather onto one
        # device first (D2D for device parts). Host-origin parts of a
        # mixed batch DO cross the wire — account them here, since the
        # dispatch-side H2D counter sees only the final device stack.
        dev0 = jax.local_devices()[0]
        _H2D_BYTES.inc(sum(int(p.nbytes) for p in parts
                           if not isinstance(p, jax.Array)))
        return jnp.stack([jax.device_put(jnp.asarray(p), dev0)
                          for p in parts])
    return np.stack(parts)


def run_sharded_batches(
    items: Sequence,
    build,
    kernel,
    consume,
    n_dev: int,
    pool,
    label: str = "batch",
    progress: bool = False,
    per_dev: int = 1,
    multihost: bool = False,
    out_bytes_per_item: int = 0,
    workspace_mult: float = 2.0,
    device_drain: bool = False,
    device_consume=None,
    prefetch_boxes=None,
):
    """The shared multi-device work loop: every sharded stage driver (fusion,
    detection, nonrigid, downsample) is this pattern — the TPU replacement of
    the reference's ``sc.parallelize(workItems).map`` (§2.4 P1/P3).

    ``items`` are grouped ``n_dev`` at a time; ``build(item)`` stages one
    item's kernel inputs on the host (a tuple of equally-shaped numpy arrays
    within one call site's bucket); the stacked + padded batch runs through
    ``kernel(*stacked) -> array | tuple`` (a jit with batch-axis in/out
    shardings, one block per device); ``consume(item, *outs_i)`` handles item
    ``i``'s slice of each output (e.g. disjoint chunk writes — no locks
    needed, the reference's no-shuffle invariant).

    Host prefetch for batch k+1 overlaps device compute for batch k, and
    staged batches are dispatched AHEAD of batch k's fetch, as many as a
    BYTE budget allows: each dispatch is charged real bytes — stacked
    inputs x ``workspace_mult`` (kernel intermediates/FFT workspace) plus
    ``out_bytes_per_item`` per item for device-resident outputs — against
    the backend's free-memory budget (utils.devicemem: ``memory_stats``
    when the runtime reports them, ``BST_INFLIGHT_BYTES`` override,
    conservative constant otherwise). The device computes ahead while
    outputs cross the wire and write; a window that does not fit stops
    growing, and the CURRENT batch always dispatches so progress never
    blocks (``BST_EARLY_DISPATCH=0`` opts out of dispatch-ahead entirely,
    degenerating to strict one-batch-at-a-time). Batches are resubmitted
    on failure via run_with_retry, and completed batches are tracked so
    retry rounds neither re-run them nor leak prefetch futures;
    early-dispatched results are keyed per batch and rebuilt on retry, so
    failure granularity is unchanged. ``per_dev`` packs that many items
    per device per batch (compute-light kernels amortize dispatch by
    batching more).

    ``multihost=True`` (block-writing stages only — outputs must be disjoint
    chunks) first takes this process's deterministic slice of ``items``, so
    the same driver run on N hosts covers the grid exactly once
    (parallel.distributed; the reference's executor model, SURVEY §2.5).

    ``device_drain=True`` replaces the driver's single batched
    ``jax.device_get`` + consume fan-out with PER-DEVICE drain workers:
    each device's shard of the batch outputs is fetched by its own thread
    (one pipelined ``device_get`` per device, ``mesh.d2h`` span attributed
    to that device's trace track) which then runs ``consume`` for exactly
    the items that computed on that device — so the driver thread performs
    zero D2H and zero writes, one device's wire transfer overlaps another
    device's chunk writes, and writers still own disjoint chunks (the
    no-shuffle invariant, now per device; ROADMAP item 3b). Callers must
    only enable it when ``consume`` tolerates ``n_dev``-way concurrency —
    h5py-backed containers (single-writer) must keep the default path.

    ``device_consume(item, *device_rows) -> bool`` is an optional
    pre-fetch hook: it sees each item's output rows as DEVICE arrays
    before any D2H, and returning True claims the item — its rows are
    never fetched and ``consume`` never runs for it (the streaming
    handoff publish path: the row stays in HBM for the downstream
    stage). Rows it declines are fetched lazily, so a batch it fully
    claims does zero D2H.

    ``prefetch_boxes(item) -> [(dataset, offset, shape), ...]`` names the
    source boxes ``build(item)`` will read. When the async prefetcher is
    enabled (io/prefetch.py) the loop feeds it batches ahead of the build
    frontier — roughly batch k+2's boxes while batch k runs — so remote
    chunk fetches overlap device compute instead of serializing inside
    ``build``. Purely advisory: with the prefetcher off (the knobs' zero
    defaults) nothing is enqueued and no code path changes."""
    from .retry import run_with_retry

    if multihost:
        from .distributed import partition_items

        items = partition_items(items)
    from ..utils.devicemem import InflightWindow

    group = n_dev * max(1, per_dev)
    batches = [list(items[i:i + group]) for i in range(0, len(items), group)]
    if not batches:
        return
    drain_pool = None
    if device_drain:
        from ..utils.threads import CtxThreadPool

        # context-propagating: drain workers read job-scoped config
        # (write knobs) and emit into the job's event scope
        drain_pool = CtxThreadPool(max_workers=max(1, n_dev),
                                   thread_name_prefix="bst-dev-drain")
    window = InflightWindow()

    fed = [0]  # batches [0, fed) already submitted to the async prefetcher

    def feed_prefetch(upto: int) -> None:
        if prefetch_boxes is None:
            return
        from ..io import prefetch as _prefetch

        if not _prefetch.enabled():
            return
        upto = min(upto, len(batches))
        while fed[0] < upto:
            b = batches[fed[0]]
            fed[0] += 1
            _prefetch.submit(lambda b=b: [box for it in b
                                          for box in prefetch_boxes(it)])

    feed_prefetch(2)
    prefetched = {0: [pool.submit(build, it) for it in batches[0]]}
    dispatched: dict[int, tuple] = {}   # bi -> (outs, charged bytes)
    completed: set[int] = set()

    def batch_cost(input_bytes: int, n_items: int) -> int:
        return (int(input_bytes * max(workspace_mult, 1.0))
                + n_items * int(out_bytes_per_item))

    def stack_and_dispatch(inputs, n_items):
        # pad to a multiple of n_dev (the sharding constraint), NOT to the
        # full group size: a tail batch of 4 on 1 device must not run as 8
        # blocks of which half are zero work (the jit re-specializes once
        # per distinct tail size; full batches all share one shape)
        stacked = pad_batch(
            [stack_inputs(inputs, j) for j in range(len(inputs[0]))],
            -(-len(inputs) // max(n_dev, 1)) * max(n_dev, 1),
        )
        if n_dev > 1 and any(isinstance(a, jax.Array) for a in stacked):
            # a handoff-fed input is committed to ONE device; the sharded
            # kernels pin batch-leading args to the block mesh, so re-place
            # it there (same-mesh D2D — the bytes never revisit the host)
            spread = NamedSharding(make_mesh(n_dev), P(BLOCK_AXIS))
            stacked = [jax.device_put(a, spread) if isinstance(a, jax.Array)
                       else a for a in stacked]
        nbytes = sum(a.nbytes for a in stacked)
        # only HOST-origin inputs cross the wire: a device-stacked input
        # (handoff-fed stage) contributes zero H2D
        host = [a for a in stacked if not isinstance(a, jax.Array)]
        _H2D_BYTES.inc(sum(a.nbytes for a in host))
        _H2D_SAVED.inc(narrow_dtype_savings(host))
        outs = kernel(*stacked)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        cost = batch_cost(nbytes, n_items)
        window.charge(cost)
        return outs, cost

    def dispatch_ahead(bi):
        """Dispatch every staged later batch that fits the byte budget, so
        the device computes ahead while batch ``bi`` drains; keep host
        prefetch one batch past the dispatch frontier."""
        if not config.get_bool("BST_EARLY_DISPATCH"):
            # opting out of dispatch-ahead must NOT kill host-side build
            # prefetch — the next batch still stages while this one drains
            nxt = bi + 1
            if (nxt < len(batches) and nxt not in prefetched
                    and nxt not in dispatched and nxt not in completed):
                prefetched[nxt] = [pool.submit(build, it)
                                   for it in batches[nxt]]
            return
        for j in range(bi + 1, len(batches)):
            if j in completed or j in dispatched:
                continue
            futs = prefetched.get(j)
            if futs is None:
                # stage TWO batches deep: j's futures are checked next
                # turn, so without j+1 already building the check would
                # always land on a just-submitted batch and the window
                # could never grow past one
                for k in (j, j + 1):
                    if (k < len(batches) and k not in prefetched
                            and k not in dispatched and k not in completed):
                        prefetched[k] = [pool.submit(build, it)
                                         for it in batches[k]]
                return
            if not all(f.done() for f in futs):
                return
            if any(f.exception() is not None for f in futs):
                # a build error belongs to batch j: its own process_batch
                # re-stages and raises so retry accounting blames it
                return
            est = batch_cost(sum(sum(int(a.nbytes) for a in f.result())
                                 for f in futs), len(batches[j]))
            if not window.fits(est):
                return
            del prefetched[j]
            try:
                dispatched[j] = stack_and_dispatch(
                    [f.result() for f in futs], len(batches[j]))
            except Exception:
                # stacking/dispatch error: same blame rule as above
                return
            nxt = j + 1
            if (nxt < len(batches) and nxt not in prefetched
                    and nxt not in dispatched and nxt not in completed):
                prefetched[nxt] = [pool.submit(build, it)
                                   for it in batches[nxt]]

    def process_batch(bi_batch):
        from ..utils import cancel as _cancel

        # between batches is the loop's safe point: a `bst cancel` poisons
        # the NEXT dispatch, in-flight device work drains normally and the
        # Cancelled unwinds through the retry layer without re-dispatch
        _cancel.check(label)
        bi, batch = bi_batch
        if bi in completed:
            return
        ent = dispatched.pop(bi, None)
        if ent is None:
            futs = prefetched.pop(bi, None)
            if futs is None:  # retry round: prefetch again
                futs = [pool.submit(build, it) for it in batch]
            # the CURRENT batch dispatches regardless of the window budget
            # (forward progress must never block on the ledger)
            ent = stack_and_dispatch([f.result() for f in futs], len(batch))
        outs, cost = ent
        # grow the in-flight window BEFORE fetching: the device computes
        # ahead while this batch's outputs cross the wire and write (the
        # fetch below only waits on THIS batch's buffers — a data
        # dependency)
        dispatch_ahead(bi)
        # read-ahead stays two batches past the build frontier (which
        # dispatch_ahead just advanced to ~bi+2)
        feed_prefetch(bi + 4)
        keep = list(range(len(batch)))
        try:
            if drain_pool is not None:
                _drain_per_device(outs, batch, consume, drain_pool, label, bi,
                                  device_consume)
            elif device_consume is None:
                # device-array nbytes are free to read pre-fetch: the span
                # carries the batch's wire payload for the trace-report D2H
                # decomposition
                d2h_nbytes = sum(int(getattr(o, "nbytes", 0)) for o in outs)
                with profiling.span("mesh.d2h", stage=label, item=int(bi),
                                    nbytes=d2h_nbytes):
                    outs = jax.device_get(list(outs))  # pipelined batch fetch
            else:
                # handoff publish first: claimed rows stay in HBM and are
                # never fetched; only the declined remainder crosses D2H
                keep = [i for i, it in enumerate(batch)
                        if not device_consume(it, *(o[i] for o in outs))]
                if keep:
                    rows = [[o[i] for i in keep] for o in outs]
                    d2h_nbytes = sum(int(getattr(r, "nbytes", 0))
                                     for rs in rows for r in rs)
                    with profiling.span("mesh.d2h", stage=label, item=int(bi),
                                        nbytes=d2h_nbytes):
                        outs = jax.device_get(rows)
                else:
                    outs = None
        finally:
            # drained or dead, the buffers leave the ledger either way —
            # a fetch error must not shrink the window for the whole run
            window.release(cost)
        if drain_pool is None and outs is not None:
            flat = (list(outs) if device_consume is None
                    else [d for ds_ in outs for d in ds_])
            _D2H_BYTES.inc(sum(int(getattr(d, "nbytes", 0)) for d in flat))
            _D2H_SAVED.inc(narrow_dtype_savings(flat))
            # with device_consume unset keep == range(len(batch)) and the
            # outputs are whole batch arrays, so row k IS item gi; with it
            # set the outputs were gathered per kept row
            wfuts = [
                pool.submit(consume, batch[gi], *(o[k] for o in outs))
                for k, gi in enumerate(keep)
            ]
            for w in wfuts:
                w.result()
        completed.add(bi)
        if progress:
            observe.log(f"  {label}: batch {bi + 1}/{len(batches)} done",
                        stage=label)

    try:
        run_with_retry(list(enumerate(batches)), process_batch, label=label)
    finally:
        if drain_pool is not None:
            drain_pool.shutdown(wait=True)
        for _outs, cost in dispatched.values():
            window.release(cost)  # keep the process-wide gauge honest


def _drain_per_device(outs, batch, consume, drain_pool, label, bi,
                      device_consume=None):
    """Fetch + consume one dispatched batch with one drain worker per
    device shard. Shards are grouped by their batch-axis row start (the
    1-D block sharding is contiguous, so row start order == mesh device
    order); each worker fetches its device's shard of every output in one
    pipelined ``device_get`` and consumes exactly the rows that device
    computed, writes included. Errors propagate to the caller (the retry
    layer re-runs the whole batch; chunk writes are idempotent).
    ``device_consume`` (see run_sharded_batches) is offered each row as
    device arrays before the shard fetch; a shard whose rows are all
    claimed does zero D2H."""
    per_dev: dict[int, list] = {}
    for oi, o in enumerate(outs):
        shards = getattr(o, "addressable_shards", None) or []
        if not shards:   # already-committed single-device array
            per_dev.setdefault(0, [None] * len(outs))[oi] = o
            continue
        for sh in shards:
            r0 = int(sh.index[0].start or 0) if sh.index else 0
            per_dev.setdefault(r0, [None] * len(outs))[oi] = sh.data

    def drain_rows(di, r0):
        _DRAIN_TLS.device = di
        try:
            parts = per_dev[r0]
            if device_consume is None:
                nb = sum(int(getattr(p, "nbytes", 0)) for p in parts)
                with profiling.span("mesh.d2h", stage=label, item=int(bi),
                                    device=di, nbytes=nb):
                    datas = jax.device_get(parts)
                _D2H_BYTES.inc(sum(int(getattr(d, "nbytes", 0))
                                   for d in datas))
                _D2H_SAVED.inc(narrow_dtype_savings(datas))
                for li in range(int(datas[0].shape[0])):
                    gi = r0 + li
                    if gi >= len(batch):
                        break    # batch-axis padding rows carry no work
                    consume(batch[gi], *(d[li] for d in datas))
                return
            todo = []
            for li in range(int(parts[0].shape[0])):
                gi = r0 + li
                if gi >= len(batch):
                    break        # batch-axis padding rows carry no work
                if device_consume(batch[gi], *(p[li] for p in parts)):
                    continue     # claimed: the row stays in HBM
                todo.append(li)
            if not todo:
                return           # whole shard claimed on device: zero D2H
            rows = [[p[li] for li in todo] for p in parts]
            nb = sum(int(getattr(r, "nbytes", 0)) for rs in rows for r in rs)
            with profiling.span("mesh.d2h", stage=label, item=int(bi),
                                device=di, nbytes=nb):
                datas = jax.device_get(rows)
            flat = [d for ds_ in datas for d in ds_]
            _D2H_BYTES.inc(sum(int(getattr(d, "nbytes", 0)) for d in flat))
            _D2H_SAVED.inc(narrow_dtype_savings(flat))
            for k, li in enumerate(todo):
                consume(batch[r0 + li], *(d[k] for d in datas))
        finally:
            _DRAIN_TLS.device = None

    futs = [drain_pool.submit(drain_rows, di, r0)
            for di, r0 in enumerate(sorted(per_dev))]
    for f in futs:
        f.result()


def shard_jit(fn, mesh: Mesh, n_in: int, n_repl: int = 0, n_out=None,
              static_argnames=()):
    """jit ``fn`` with the first ``n_repl`` args replicated and the remaining
    ``n_in`` batch-leading args (and all outputs) sharded over the mesh's
    block axis."""
    shard = NamedSharding(mesh, P(BLOCK_AXIS))
    repl = NamedSharding(mesh, P())
    out_shardings = shard if n_out is None else (shard,) * n_out
    in_shardings = (repl,) * n_repl + (shard,) * n_in
    return _commit_host_args(jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        static_argnames=static_argnames,
    ), in_shardings)

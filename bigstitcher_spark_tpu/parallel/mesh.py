"""Device-mesh sharding of the block work list.

TPU-native replacement of the reference's Spark data parallelism (§2.4 P1):
a batch of output blocks becomes the leading axis of the stacked kernel
inputs, sharded over a 1-D ``jax.sharding.Mesh`` — each device fuses its
shard of blocks; no collectives are needed because block writes are disjoint
(the reference's no-shuffle property). Multi-host scale-out uses the same
mesh spanning hosts (ICI within pod, DCN across — jax.distributed).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.fusion import fuse_block_impl

BLOCK_AXIS = "blocks"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (BLOCK_AXIS,))


def make_sharded_fuser(
    mesh: Mesh,
    block_shape: tuple[int, int, int],
    fusion_type: str = "AVG_BLEND",
):
    """Compile a fuser for a BATCH of blocks sharded over the mesh.

    Inputs get a leading batch axis B (must be a multiple of mesh size; pad
    with valid=0 blocks). Returns (fused (B,*block_shape), weights)."""
    shard = NamedSharding(mesh, P(BLOCK_AXIS))
    core = functools.partial(
        fuse_block_impl, block_shape=block_shape, fusion_type=fusion_type
    )
    batched = jax.vmap(core)
    return jax.jit(
        batched,
        in_shardings=(shard,) * 7,
        out_shardings=(shard, shard),
    )


def pad_batch(arrays: Sequence[np.ndarray], batch: int) -> list[np.ndarray]:
    """Pad each stacked input along axis 0 to ``batch`` (extra entries are
    all-zero => valid mask 0 => no-op blocks)."""
    out = []
    for a in arrays:
        if a.shape[0] == batch:
            out.append(a)
        else:
            pad = np.zeros((batch - a.shape[0],) + a.shape[1:], a.dtype)
            out.append(np.concatenate([a, pad], axis=0))
    return out

"""App-level block retry (RetryTrackerSpark equivalent).

The reference resubmits failed grid blocks ≤5 times with a 2 s delay, then
gives up hard (RetryTrackerSpark.java:28-61; loops at
SparkAffineFusion.java:467-479,682-696). Block writes are idempotent, so
resubmission is always safe.

Every run feeds the observability layer: a per-stage progress heartbeat
(done/total, rate, ETA), ``block.fail`` / ``retry.round`` events carrying
the exception class, and retry/failure counters — the Spark retry
accounting this port previously only ``print``ed.
"""

from __future__ import annotations

import time
import traceback
from typing import Callable, Sequence, TypeVar

from ..observe import events, metrics, progress, trace
from ..utils.cancel import Cancelled
from ..utils.threads import CtxThreadPool

T = TypeVar("T")


def _item_key(it):
    """JSON-safe work-item identity for trace attribution: grid blocks
    carry their offset (matching the fusion spans' item key), scalars pass
    through, anything else stays anonymous."""
    off = getattr(it, "offset", None)
    if off is not None:
        try:
            return tuple(int(v) for v in off)
        except (TypeError, ValueError):
            return None
    return it if isinstance(it, (int, str)) else None


class RetryError(RuntimeError):
    pass


def run_with_retry(
    items: Sequence[T],
    process: Callable[[T], None],
    max_retries: int = 5,
    delay_s: float = 2.0,
    label: str = "block",
    verbose: bool = True,
    threads: int = 1,
) -> int:
    """Process all items; collect failures and resubmit only those.

    ``threads > 1`` runs items on a host thread pool — safe for IO-bound
    chunk copy work (tensorstore releases the GIL; writers own disjoint
    chunks by construction). Returns the number of retry rounds used. Raises
    RetryError when items still fail after ``max_retries`` rounds (reference
    exits the JVM); its message includes the per-exception-class failure
    breakdown accumulated across ALL rounds, not just the first traceback."""
    pending: list[T] = list(items)
    rounds = 0
    err_counts: dict[str, int] = {}
    hb = progress.Heartbeat(label, len(pending))
    while pending:
        failed: list[tuple[T, Exception]] = []

        def attempt(it: T):
            try:
                with trace.span("retry.attempt", stage=label,
                                item=_item_key(it)):
                    process(it)
                hb.tick()
                return None
            except Cancelled:
                # cancellation is not a block failure: resubmitting a
                # cancelled item would defeat the cancel — unwind now
                raise
            except Exception as e:  # noqa: BLE001 - any task failure is retryable
                trace.instant("block.fail", stage=label, item=_item_key(it))
                return (it, e)

        if threads > 1:
            # context-propagating pool: items processed on workers keep the
            # caller's job scope (config overrides, event sink, cancel token)
            with CtxThreadPool(max_workers=threads) as pool:
                failed = [r for r in pool.map(attempt, pending) if r is not None]
        else:
            failed = [r for r in map(attempt, pending) if r is not None]
        for _, e in failed:
            exc = type(e).__name__
            err_counts[exc] = err_counts.get(exc, 0) + 1
            metrics.counter("bst_blocks_failed_total", stage=label,
                            exception=exc).inc()
        if events.enabled():
            for it, e in failed:
                events.emit("block.fail", stage=label,
                            exception=type(e).__name__,
                            error=repr(e)[:300], round=rounds)
        if not failed:
            break
        rounds += 1
        hb.retry_round()
        metrics.counter("bst_retry_rounds_total", stage=label).inc()
        if rounds > max_retries:
            hb.finish(failed=len(failed))
            events.emit("retry.exhausted", stage=label,
                        failures=len(failed), rounds=rounds - 1,
                        by_exception=err_counts)
            tb = "".join(traceback.format_exception(failed[0][1]))
            breakdown = ", ".join(
                f"{k} x{v}" for k, v in sorted(err_counts.items(),
                                               key=lambda kv: -kv[1]))
            raise RetryError(
                f"{len(failed)} {label}(s) still failing after "
                f"{max_retries} retries; failure breakdown across rounds: "
                f"{breakdown}; first error:\n{tb}"
            )
        events.emit("retry.round", stage=label, round=rounds,
                    max_retries=max_retries, failures=len(failed),
                    by_exception=err_counts, delay_s=delay_s)
        if verbose:
            print(
                f"[retry] {len(failed)} {label}(s) failed "
                f"(round {rounds}/{max_retries}), resubmitting in {delay_s}s: "
                f"{failed[0][1]!r}"
            )
        time.sleep(delay_s)
        pending = [it for it, _ in failed]
    hb.finish()
    return rounds

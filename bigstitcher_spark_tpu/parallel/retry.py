"""App-level block retry (RetryTrackerSpark equivalent).

The reference resubmits failed grid blocks ≤5 times with a 2 s delay, then
gives up hard (RetryTrackerSpark.java:28-61; loops at
SparkAffineFusion.java:467-479,682-696). Block writes are idempotent, so
resubmission is always safe.
"""

from __future__ import annotations

import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence, TypeVar

T = TypeVar("T")


class RetryError(RuntimeError):
    pass


def run_with_retry(
    items: Sequence[T],
    process: Callable[[T], None],
    max_retries: int = 5,
    delay_s: float = 2.0,
    label: str = "block",
    verbose: bool = True,
    threads: int = 1,
) -> int:
    """Process all items; collect failures and resubmit only those.

    ``threads > 1`` runs items on a host thread pool — safe for IO-bound
    chunk copy work (tensorstore releases the GIL; writers own disjoint
    chunks by construction). Returns the number of retry rounds used. Raises
    RetryError when items still fail after ``max_retries`` rounds (reference
    exits the JVM)."""
    pending: list[T] = list(items)
    rounds = 0
    while pending:
        failed: list[tuple[T, Exception]] = []

        def attempt(it: T):
            try:
                process(it)
                return None
            except Exception as e:  # noqa: BLE001 - any task failure is retryable
                return (it, e)

        if threads > 1:
            with ThreadPoolExecutor(max_workers=threads) as pool:
                failed = [r for r in pool.map(attempt, pending) if r is not None]
        else:
            failed = [r for r in map(attempt, pending) if r is not None]
        if not failed:
            return rounds
        rounds += 1
        if rounds > max_retries:
            tb = "".join(traceback.format_exception(failed[0][1]))
            raise RetryError(
                f"{len(failed)} {label}(s) still failing after "
                f"{max_retries} retries; first error:\n{tb}"
            )
        if verbose:
            print(
                f"[retry] {len(failed)} {label}(s) failed "
                f"(round {rounds}/{max_retries}), resubmitting in {delay_s}s: "
                f"{failed[0][1]!r}"
            )
        time.sleep(delay_s)
        pending = [it for it, _ in failed]
    return rounds

"""Pair-work mesh scheduler: spread shape-bucketed pair batches over every
local device.

The block-parallel stages (fusion/detection/downsample/resave) scale via
``run_sharded_batches`` — a stacked batch axis sharded over a 1-D mesh. The
PAIR-parallel stages (stitching phase correlation, descriptor matching,
intensity matching) cannot take that shape: their work items are whole
per-pair programs (an FFT over one bucket's padded crop stack, a kNN +
RANSAC cascade over one pair's descriptors, one pair's cell-sample fits)
with host post-processing between device calls. Before this module they all
ran on the default device — batched and pipelined, but leaving every other
chip idle (the round-5 VERDICT's first open item; JAMPI/SparkCL make the
same move for Spark matmul / heterogeneous accelerator clusters).

Design:

- **Placement** is cost-weighted greedy (LPT): tasks sorted by descending
  cost (FFT volume for PCM, descriptor count for kNN/RANSAC, sample count
  for intensity) land on the least-loaded device; ties break by task order
  so placement is deterministic. Greedy-on-min guarantees
  ``max_load - min_load <= max task cost``.
- **Affinity** is per-thread: one worker thread per device runs its queue
  under ``jax.default_device(dev)`` (thread-local in jax), so every
  dispatch a task makes — including multi-step host/device cascades like
  RANSAC — lands on its device with no caller changes.
- **Windows** are per device: each worker bounds dispatched-but-undrained
  bytes with its own ``InflightWindow`` whose budget derives from THAT
  device's ``memory_stats`` (``BST_PAIR_INFLIGHT_BYTES`` overrides,
  ``utils.devicemem`` fallback divided by the local device count
  otherwise).
- **Drains** are device-affine, segmented and pipelined: with a split
  ``dispatch``/``drain``, a worker groups its dispatches into segments of
  up to half its byte budget and hands each WHOLE segment to one batched
  ``drain`` call (one pipelined ``jax.device_get`` per segment — the
  round-trip economics of the r5 stitching drain, now per device), always
  dispatching the next segment before draining the previous so the device
  computes while outputs cross the wire. At most two segments (~the
  budget) are pinned per device, and devices never wait on each other.
- **Failures** re-dispatch: a task whose device call dies is retried on
  the OTHER devices (round-robin, the observed device excluded) so one
  poisoned chip degrades capacity instead of killing the run.
- **Drains may write**: a ``drain`` callback runs on its device's own
  worker thread and may write its tasks' disjoint output chunks directly
  (the chunkstore is thread-safe and write-generation-aware) instead of
  collecting results back to the caller — the same device-owns-its-output
  rule the sharded work loop's ``device_drain`` mode (parallel.mesh)
  applies to the block-parallel fusion/downsample drivers, keeping every
  result's D2H and write on the worker track that computed it.

Instrumented through ``observe.metrics``: per-device dispatch/busy
counters (``bst_pair_dispatch_total`` / ``bst_pair_busy_ms_total``,
labeled ``stage``+``device``) and a per-stage utilization gauge
(``bst_pair_device_util_pct`` = busy time over devices x wall) — the
MULTICHIP dryrun and the bench ``"io"`` columns read these to prove the
spread without a tunnel window.

``BST_PAIR_SHARD=0`` opts out (single-device, today's pipelined path);
one local device degrades to the same thing automatically.
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Sequence

from .. import config
from ..observe import events, metrics as _metrics, progress as _progress
from ..observe import trace as _trace
from ..utils import cancel as _cancel
from ..utils.threads import ctx_thread
from .retry import RetryError

# placement treats zero-cost tasks as infinitesimally heavy so they still
# spread round-robin instead of piling onto one bin
_MIN_COST = 1e-9

# failed tasks are re-attempted on this many OTHER devices before the
# stage gives up (one poisoned device must not kill the run; a task that
# fails everywhere is genuinely broken)
_MAX_REDISPATCH = 3


def pair_devices(n_devices: int | None = None, devices=None) -> list:
    """The devices a pair stage may schedule on: local devices, optionally
    limited to the first ``n_devices`` (the dryrun's single-device control
    runs), or collapsed to one by the ``BST_PAIR_SHARD=0`` opt-out."""
    import jax

    devs = list(devices) if devices is not None else list(jax.local_devices())
    # only explicit falsy spellings opt out (config.get_bool's rule) — a
    # stray BST_PAIR_SHARD=2 or =true must not silently collapse every
    # pair stage to one device
    if not config.get_bool("BST_PAIR_SHARD"):
        devs = devs[:1]
    if n_devices is not None:
        devs = devs[: max(1, int(n_devices))]
    return devs


@dataclass
class PairTask:
    """One schedulable unit of pair work.

    ``index`` is the result slot (callers number tasks 0..N-1; outputs come
    back in that order regardless of placement). ``cost`` drives placement
    (any stage-appropriate proxy: FFT volume, descriptor count, sample
    count). ``nbytes`` is the device-resident estimate charged against the
    owning device's in-flight window while the task is dispatched but not
    yet drained (0 for tasks that run dispatch-to-result in one step)."""

    index: int
    cost: float = 1.0
    nbytes: int = 0
    tag: Any = None


def assign_tasks(tasks: Sequence[PairTask], n_bins: int) -> list[list[PairTask]]:
    """Cost-weighted greedy (LPT) placement: heaviest task first onto the
    least-loaded bin; deterministic (ties by bin index, stable task order).
    Guarantees ``max_load - min_load <= max task cost``."""
    bins: list[list[PairTask]] = [[] for _ in range(max(n_bins, 1))]
    loads = [0.0] * len(bins)
    for t in sorted(tasks, key=lambda t: (-max(t.cost, 0.0), t.index)):
        b = min(range(len(bins)), key=lambda i: (loads[i], i))
        bins[b].append(t)
        loads[b] += max(t.cost, _MIN_COST)
    return bins


_TLS = threading.local()


def concurrent_pair_workers() -> int:
    """Number of device workers in THIS thread's scheduler run (1 outside
    a worker thread) — shared host-side resources sized per drain (e.g.
    the stitching refinement thread budget) divide by actual concurrency,
    not the host's device count."""
    return getattr(_TLS, "n_workers", 1)


class _StageMeters:
    """Per-(stage, device) dispatch/busy counters + the stage utilization
    gauge, shared by every worker of one run."""

    def __init__(self, stage: str, n_dev: int):
        self.stage = stage
        self.dispatch = [
            _metrics.counter("bst_pair_dispatch_total", stage=stage,
                             device=str(i)) for i in range(n_dev)
        ]
        self.busy_ms = [
            _metrics.counter("bst_pair_busy_ms_total", stage=stage,
                             device=str(i)) for i in range(n_dev)
        ]
        self.redispatch = _metrics.counter("bst_pair_redispatch_total",
                                           stage=stage)
        self.util = _metrics.gauge("bst_pair_device_util_pct", stage=stage)
        self._busy_s = [0.0] * n_dev
        self._lock = threading.Lock()

    def add_busy(self, di: int, seconds: float) -> None:
        # float increment: many sub-ms tasks must not truncate to 0
        self.busy_ms[di].inc(seconds * 1000.0)
        with self._lock:
            self._busy_s[di] += seconds

    def finish(self, wall_s: float) -> None:
        n = len(self._busy_s)
        if n and wall_s > 0:
            busy = sum(self._busy_s)
            self.util.set(round(100.0 * busy / (n * wall_s), 1))
            _record_process_util(self.stage, busy, wall_s, n)


# last-run per-stage busy/util of THIS process's pair scheduler, keyed by
# stage — the relay snapshot payload behind `bst top --cluster`'s PAIR
# column and the bench multihost extra's per-process io numbers
_PROC_UTIL: dict[str, dict] = {}
_PROC_UTIL_LOCK = threading.Lock()


def _record_process_util(stage: str, busy_s: float, wall_s: float,
                         n_dev: int) -> None:
    try:
        from .distributed import world

        pi, pc = world()
    except Exception:  # pragma: no cover - backend not initialized
        pi, pc = 0, 1
    util = round(100.0 * busy_s / (n_dev * wall_s), 1) if wall_s > 0 else 0.0
    _metrics.counter("bst_pair_proc_busy_ms_total", stage=stage,
                     process=str(pi)).inc(busy_s * 1000.0)
    _metrics.gauge("bst_pair_proc_util_pct", stage=stage,
                   process=str(pi)).set(util)
    with _PROC_UTIL_LOCK:
        _PROC_UTIL[stage] = {
            "process": pi, "world": pc, "n_dev": n_dev,
            "busy_s": round(busy_s, 3), "wall_s": round(wall_s, 3),
            "util_pct": util,
        }


def process_util_snapshot() -> dict:
    """Per-stage {busy_s, wall_s, util_pct, ...} of this process's last
    pair-scheduler runs — merged into the telemetry relay snapshot so the
    collector can show cross-process imbalance live."""
    with _PROC_UTIL_LOCK:
        return {k: dict(v) for k, v in _PROC_UTIL.items()}


def _run_queue(queue, di, dispatch, drain, window, results, failures,
               meters: _StageMeters, hb: _progress.Heartbeat):
    """One device's pipelined loop. Without ``drain``, tasks run
    dispatch-to-result in order. With ``drain``, dispatches accumulate
    into SEGMENTS of up to half the device's byte budget; each segment
    drains in ONE batched call, and the next segment always dispatches
    before the previous one drains — so at most two segments (~the
    budget) are pinned while the device computes ahead of the fetch.
    Failures are collected, never raised (the caller re-dispatches them
    on other devices)."""
    if drain is None:
        for t in queue:
            if _cancel.cancelled():
                # abandon the queue quietly: the caller's post-join cancel
                # check raises ONE Cancelled for the stage instead of a
                # missing-results RetryError per abandoned task
                return
            try:
                t0 = time.perf_counter()
                with _trace.span("pair.dispatch", device=di,
                                 stage=meters.stage, item=t.index,
                                 nbytes=t.nbytes or None):
                    results[t.index] = (True, dispatch(t))
                meters.add_busy(di, time.perf_counter() - t0)
                meters.dispatch[di].inc()
                hb.tick()
            except Exception as e:  # noqa: BLE001 - re-dispatched by caller
                failures.append((t, di, e))
        return

    half = max(1, window.budget // 2)
    seg: list[tuple[PairTask, Any]] = []
    seg_bytes = 0
    prev: list[tuple[PairTask, Any]] | None = None

    def flush(group):
        tasks = [t for t, _ in group]
        try:
            t0 = time.perf_counter()
            with _trace.span("pair.drain", device=di, stage=meters.stage,
                             nbytes=sum(t.nbytes for t in tasks) or None):
                outs = drain(tasks, [h for _, h in group])
            meters.add_busy(di, time.perf_counter() - t0)
            for t, r in zip(tasks, outs):
                results[t.index] = (True, r)
                hb.tick()
        except Exception:  # noqa: BLE001 - isolate, then re-dispatch
            # a batched-drain error usually belongs to ONE task's host
            # post-processing: drain each task singly so its healthy
            # neighbours keep their (already computed) results and only
            # the offender re-dispatches; a dead device fails every
            # single drain too and the whole group re-dispatches as
            # before
            for t, h in group:
                try:
                    results[t.index] = (True, drain([t], [h])[0])
                    hb.tick()
                except Exception as e:  # noqa: BLE001
                    failures.append((t, di, e))
        finally:
            for t in tasks:
                window.release(t.nbytes)

    for t in queue:
        if _cancel.cancelled():
            # release what is pinned, then abandon (see above)
            for group in (prev, seg):
                for pt, _ in (group or ()):
                    window.release(pt.nbytes)
            return
        if seg and seg_bytes + t.nbytes > half:
            if prev is not None:
                flush(prev)
            prev, seg, seg_bytes = seg, [], 0
        try:
            t0 = time.perf_counter()
            with _trace.span("pair.dispatch", device=di, stage=meters.stage,
                             item=t.index, nbytes=t.nbytes or None):
                out = dispatch(t)
            meters.add_busy(di, time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001 - re-dispatched by caller
            failures.append((t, di, e))
            continue
        meters.dispatch[di].inc()
        window.charge(t.nbytes)
        seg.append((t, out))
        seg_bytes += t.nbytes
    if prev is not None:
        flush(prev)
    if seg:
        flush(seg)


def multihost_active(explicit: bool | None = None) -> bool:
    """Whether the pair stages split their task lists across the
    processes of the execution world before local device placement. An
    explicit ``multihost=`` argument wins; the ``BST_PAIR_MULTIHOST``
    knob (default ``auto``) otherwise turns the split ON exactly when
    the jax world has more than one process. A single-process world
    never splits — there is nothing to split."""
    try:
        from .distributed import world

        pc = world()[1]
    except Exception:  # pragma: no cover - backend not initializable
        pc = 1
    if pc <= 1:
        return False
    if explicit is not None:
        return bool(explicit)
    return (config.get_str("BST_PAIR_MULTIHOST") or "auto") != "0"


def _merge_multihost(stage: str, results: list,
                     err: BaseException | None, pi: int, pc: int) -> list:
    """Exchange per-process pair results so every rank returns the FULL
    task-index-ordered list (the SPMD analogue of the reference's
    driver-side collect). A failing rank reports its error INTO the
    gather, so healthy peers raise a ``RetryError`` naming it instead of
    deadlocking on a collective that will never complete."""
    from .distributed import allgather_object

    if err is not None:
        payload = ("err", f"{type(err).__name__}: {err}")
    else:
        payload = ("ok", {i: r[1] for i, r in enumerate(results)
                          if r is not None})
    # the gather doubles as the stage barrier: time spent here is the
    # straggler signal of an imbalanced split
    with _trace.span("pair.allgather", stage=stage):
        gathered = allgather_object(payload)
    if err is not None:
        raise err
    bad = [f"rank {r}: {p[1]}" for r, p in enumerate(gathered)
           if p[0] == "err"]
    if bad:
        raise RetryError(
            f"{stage}: multihost pair split failed on peer process(es) — "
            f"{'; '.join(bad[:3])}")
    merged = list(results)
    for r, (_, vals) in enumerate(gathered):
        if r == pi:
            continue
        for i, v in vals.items():
            if merged[i] is None:
                merged[i] = (True, v)
    return merged


def run_pair_tasks(
    tasks: Sequence[PairTask],
    dispatch: Callable[[PairTask], Any],
    drain: Callable[[PairTask, Any], Any] | None = None,
    *,
    devices=None,
    n_devices: int | None = None,
    stage: str = "pairs",
    budget_bytes: int | None = None,
    multihost: bool | None = None,
    prefetch_boxes=None,
) -> list:
    """Run pair tasks across the execution world; results in task-index
    order.

    ``dispatch(task)`` runs under the task's assigned device
    (``jax.default_device``); with ``drain`` it returns un-fetched device
    handles and ``drain(tasks, handles)`` later fetches + post-processes a
    whole SEGMENT of them in one batched call (the pipelined segmented
    mode the stitching PCM uses — one ``jax.device_get`` round-trip per
    memory-bounded segment, the device computing the next segment while
    this one's peak tables cross the wire); without ``drain`` it returns
    the final result directly (the mode for host/device cascades like
    descriptor matching and intensity fits).

    One local device (or ``BST_PAIR_SHARD=0``) runs the same pipelined loop
    inline on the caller's thread — no placement, no extra threads, the
    pre-sharding behavior. Tasks whose device call fails are re-dispatched
    on the other devices (round-robin) before the stage raises
    ``RetryError``.

    In a multi-process world the task list splits across PROCESSES first
    (cost-aware LPT via ``distributed.partition_indices_weighted``) and
    this process's local devices second; after the local slice completes,
    the per-process results allgather back so EVERY rank returns the full
    list — callers keep the single-process contract unchanged. This is
    the default whenever ``jax.process_count() > 1``
    (:func:`multihost_active`, knob ``BST_PAIR_MULTIHOST``); pass
    ``multihost=False`` to pin a call to every-rank-computes-everything,
    or ``True`` to split even when the knob says 0.

    ``prefetch_boxes(task) -> [(dataset, offset, shape), ...]`` names the
    source crops ``dispatch(task)`` will read; when the async prefetcher
    (io/prefetch.py) is enabled this process's local queue is fed to it
    up front — its byte budget paces how far ahead of dispatch order the
    remote fetches actually run. Advisory only; off by default."""
    tasks = list(tasks)
    n_slots = max((t.index for t in tasks), default=-1) + 1
    covered = {t.index for t in tasks}
    if multihost_active(multihost):
        from .distributed import partition_indices_weighted, world

        pi, pc = world()
        mine = set(partition_indices_weighted(
            [max(t.cost, 0.0) for t in tasks], pi, pc))
        local = [t for k, t in enumerate(tasks) if k in mine]
        events.emit("pair.multihost", stage=stage, process=pi, world=pc,
                    local=len(local), total=len(tasks))
        err: BaseException | None = None
        results: list = [None] * n_slots
        try:
            results = _run_local(local, dispatch, drain, devices,
                                 n_devices, stage, budget_bytes, n_slots,
                                 prefetch_boxes)
        except BaseException as e:  # noqa: BLE001 - reported into gather
            err = e
        results = _merge_multihost(stage, results, err, pi, pc)
    else:
        results = _run_local(tasks, dispatch, drain, devices, n_devices,
                             stage, budget_bytes, n_slots, prefetch_boxes)
    missing = [i for i, r in enumerate(results)
               if r is None and i in covered]
    if missing:
        raise RetryError(
            f"{stage}: {len(missing)} pair task(s) produced no result "
            f"(indices {missing[:8]}...)")
    return [None if r is None else r[1] for r in results]


def _feed_pair_prefetch(tasks, prefetch_boxes) -> None:
    """Submit every queued task's source crops to the async prefetcher
    (io/prefetch.py) before the device workers start: box enumeration
    runs on the prefetch workers and the prefetch byte budget paces how
    far ahead of dispatch order the remote fetches actually get."""
    if prefetch_boxes is None:
        return
    from ..io import prefetch as _prefetch

    if not _prefetch.enabled():
        return
    for t in tasks:
        _prefetch.submit(lambda t=t: prefetch_boxes(t))


def _run_local(
    tasks: list[PairTask],
    dispatch: Callable[[PairTask], Any],
    drain,
    devices,
    n_devices: int | None,
    stage: str,
    budget_bytes: int | None,
    n_slots: int,
    prefetch_boxes=None,
) -> list:
    """This process's share of a pair run over its local devices; returns
    the raw slot list (``(True, value)`` at completed indices, ``None``
    elsewhere) for :func:`run_pair_tasks` to merge/unwrap."""
    if not tasks:
        return [None] * n_slots
    _feed_pair_prefetch(tasks, prefetch_boxes)
    devs = pair_devices(n_devices, devices)
    n_dev = len(devs)
    results: list = [None] * n_slots
    failures: list[tuple[PairTask, int, Exception]] = []
    meters = _StageMeters(stage, n_dev)
    # live done/total heartbeat (PR-1 progress events): long pair stages
    # must be distinguishable from hung ones while workers run
    hb = _progress.Heartbeat(f"pairs-{stage}", len(tasks))
    t_start = time.perf_counter()

    if n_dev <= 1:
        import jax

        from ..utils.devicemem import InflightWindow, pair_budget_bytes

        budget = (budget_bytes if budget_bytes is not None
                  else pair_budget_bytes(devs[0] if devs else None, 1))
        window = InflightWindow(budget)
        # pin to the RESOLVED device: an explicit devices=[...] selection
        # must route work there, not to the process default
        with jax.default_device(devs[0] if devs else None):
            _run_queue(tasks, 0, dispatch, drain, window, results, failures,
                       meters, hb)
    else:
        import jax

        queues = assign_tasks(tasks, n_dev)
        n_active = sum(1 for q in queues if q)

        def worker(di: int):
            from ..utils.devicemem import InflightWindow, pair_budget_bytes

            _TLS.n_workers = n_active
            budget = (budget_bytes if budget_bytes is not None
                      else pair_budget_bytes(devs[di], n_active))
            window = InflightWindow(budget)
            with jax.default_device(devs[di]):
                _run_queue(queues[di], di, dispatch, drain, window, results,
                           failures, meters, hb)

        threads = [
            # ctx_thread: workers inherit the caller's job scope (config
            # overrides size their windows, events land in the job's log,
            # the cancel token can poison their queues)
            ctx_thread(worker, (di,), name=f"bst-pair-{stage}-{di}")
            for di in range(n_dev) if queues[di]
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()

    # a cancelled stage abandons its queues above; raise the ONE Cancelled
    # here (the existing re-dispatch path is the poison point: a cancelled
    # task must never fail over to the next device)
    _cancel.check(f"pairs-{stage}")

    # re-dispatch failed tasks on devices OTHER than the one observed
    # failing (single-device runs retry in place — there is nowhere else).
    # This runs serially on the caller's thread after the workers join: a
    # device that dies early turns its queue's tail into sequential work,
    # a deliberate simplicity/size tradeoff — device death is rare and
    # capacity (not latency) is what must survive it.
    if failures:
        import jax

        for t, bad_di, err in list(failures):
            _cancel.check(f"pairs-{stage}")
            last = err
            retried = False
            for k in range(1, max(n_dev, 2)):
                di = (bad_di + k) % n_dev
                if k > _MAX_REDISPATCH:
                    break
                meters.redispatch.inc()
                events.emit("pair.redispatch", stage=stage, task=t.index,
                            from_device=bad_di, to_device=di,
                            error=repr(err)[:200])
                _trace.instant("pair.redispatch", device=di, stage=stage,
                               item=t.index)
                try:
                    with jax.default_device(devs[di]):
                        out = dispatch(t)
                        meters.dispatch[di].inc()
                        results[t.index] = (
                            True,
                            drain([t], [out])[0] if drain is not None
                            else out)
                    hb.tick()
                    retried = True
                    break
                except Exception as e:  # noqa: BLE001 - try next device
                    last = e
            if not retried:
                meters.finish(time.perf_counter() - t_start)
                hb.finish(failed=1)
                raise RetryError(
                    f"pair task {t.index} ({stage}) failed on device "
                    f"{bad_di} and every re-dispatch target: {last!r}"
                ) from last

    meters.finish(time.perf_counter() - t_start)
    hb.finish()
    return results

"""Rule engine over recorded telemetry: evidence in, knob advice out.

PRs 1/6/13/15 built the recording substrate — manifests, flight-recorder
traces with the trace-report decomposition, the history store, the pod
relay — but interpreting any of it stayed a human job: read the overlap
percentages and cache ratios, then guess which of the declared ``BST_*``
knobs to turn. This module encodes those readings as explicit rules in
the performance-portability spirit of SparkCL (PAPERS.md, arXiv
1505.01120): measure the backend, don't assume it.

Every rule consumes only evidence the substrate already emits (a history
record's metric deltas + optionally its trace-report decomposition) and
returns a structured :class:`Diagnosis` — ``{rule, evidence, knob,
suggested_value, confidence}`` — never a free-form string, so the
autotuner (tune/search.py) can seed its search from the implicated knobs
and ``bst tune advise --json`` is scriptable. Rules are deliberately
conservative: each has a significance floor (a 3-line run with a 40%
cache miss ratio is noise, not a bottleneck) and fires at most once.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field

from .. import config, profiling
from ..analysis import tracereport
from ..observe import history
from ..observe import metrics as _metrics
from ..observe.history import _flat_metrics

# significance floors: below these the evidence is noise, not a signal
_MIN_CACHE_OPS = 64          # cache lookups before a ratio means anything
_MIN_COLD_BUILDS = 4         # compiles before cold-start advice fires
_MIN_CAT_SECONDS = 0.05      # seconds in a trace category worth overlapping
_OVERLAP_FLOOR_PCT = 40.0    # d2h/write overlap below this is serialized
_INFLIGHT_SATURATION = 0.92  # high-water / budget ratio that means capped
_STALL_FRACTION = 0.05       # producer-stall seconds vs wall clock


@dataclass
class Diagnosis:
    """One fired advisor rule. ``knob`` is None for advice that has no
    single-knob remedy (e.g. cold compile buckets want a resident
    daemon, not a value change); ``suggested_value`` is the raw override
    string ``config.overrides`` accepts."""

    rule: str
    detail: str
    confidence: float
    knob: str | None = None
    suggested_value: str | None = None
    evidence: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return asdict(self)


def _sum(flat: dict[str, float], base: str) -> float:
    """Sum a metric over its label variants (``name{label=...}`` keys)."""
    return sum(v for k, v in flat.items() if k.split("{")[0] == base)


def _clamped_double(knob_name: str, current) -> int:
    k = config.KNOBS[knob_name]
    v = int(current) if current else int(k.tunable.lo if k.tunable else 1)
    v = max(1, v) * 2
    if k.tunable is not None:
        if k.tunable.lo is not None:
            v = max(v, int(k.tunable.lo))
        if k.tunable.hi is not None:
            v = min(v, int(k.tunable.hi))
    return v


def _recorded_budget(rec: dict, knob_name: str):
    """The byte budget a recorded run ACTUALLY ran under: its own
    override (daemon jobs and tune trials record theirs in params) wins;
    otherwise the advise-time resolved knob; for the in-flight window a
    last resort asks devicemem for the derived budget (same host ⇒ same
    derivation; cross-host the evidence dict flags the assumption)."""
    ov = ((rec.get("params") or {}).get("overrides") or {})
    raw = ov.get(knob_name)
    if raw:
        try:
            return int(float(raw)), "recorded-override"
        except (TypeError, ValueError):
            pass
    v = config.get_bytes(knob_name)
    if v is not None:
        return int(v), "config"
    if knob_name == "BST_INFLIGHT_BYTES":
        try:
            from ..utils import devicemem

            return int(devicemem.dispatch_budget_bytes()), "derived"
        except Exception:
            return None, "unavailable"
    return None, "unavailable"


# -- rules ------------------------------------------------------------------
# each: (record, flat_metrics, trace_report|None, wall_seconds) ->
# Diagnosis | None

def _rule_low_overlap(rec, flat, trace_rep, wall):
    if not trace_rep:
        return None
    worst = None
    for group, entry in (trace_rep.get("stages") or {}).items():
        d2h = float(entry.get("d2h_s") or 0.0)
        wr = float(entry.get("write_s") or 0.0)
        if d2h < _MIN_CAT_SECONDS or wr < _MIN_CAT_SECONDS:
            continue
        ov = (entry.get("overlap") or {}).get("d2h_write")
        if not ov:
            continue
        pct = float(ov.get("pct_of_d2h") or 0.0)
        if pct < _OVERLAP_FLOOR_PCT and (worst is None or pct < worst[1]):
            worst = (group, pct, d2h, wr)
    if worst is None:
        return None
    group, pct, d2h, wr = worst
    cur = config.get_int("BST_WRITE_THREADS") or 8
    return Diagnosis(
        rule="low_d2h_write_overlap",
        detail=(f"stage {group!r}: only {pct:.0f}% of device-to-host "
                f"fetch time overlaps container writes ({d2h:.2f}s d2h, "
                f"{wr:.2f}s write run mostly back-to-back) — more drain "
                f"writer threads pipeline the two"),
        confidence=round(min(0.9, 0.4 + (_OVERLAP_FLOOR_PCT - pct) / 100),
                         2),
        knob="BST_WRITE_THREADS",
        suggested_value=str(_clamped_double("BST_WRITE_THREADS", cur)),
        evidence={"stage": group, "overlap_pct_of_d2h": round(pct, 1),
                  "d2h_s": round(d2h, 3), "write_s": round(wr, 3)})


def _rule_cold_buckets(rec, flat, trace_rep, wall):
    warm = _sum(flat, "bst_compiled_fn_warm_hits_total")
    cold = _sum(flat, "bst_compiled_fn_cold_builds_total")
    if cold < _MIN_COLD_BUILDS or warm + cold <= 0:
        return None
    ratio = warm / (warm + cold)
    if ratio >= 0.5:
        return None
    return Diagnosis(
        rule="cold_compile_buckets",
        detail=(f"{int(cold)} kernel buckets compiled cold vs "
                f"{int(warm)} warm hits ({ratio:.0%} warm) — run under a "
                f"resident `bst serve` daemon (or submit with a tuned "
                f"profile) so repeat shapes reuse compiled fns"),
        confidence=round(min(0.9, 0.4 + (0.5 - ratio)), 2),
        evidence={"cold_builds": int(cold), "warm_hits": int(warm),
                  "warm_ratio": round(ratio, 3)})


def _cache_rule(rule, hits_m, misses_m, evict_m, knob):
    def _run(rec, flat, trace_rep, wall):
        hits = _sum(flat, hits_m)
        misses = _sum(flat, misses_m)
        evict = _sum(flat, evict_m)
        total = hits + misses
        if total < _MIN_CACHE_OPS or evict <= 0:
            return None
        ratio = hits / total
        if ratio >= 0.5:
            return None
        cur = config.get_bytes(knob)
        return Diagnosis(
            rule=rule,
            detail=(f"{ratio:.0%} hit ratio over {int(total)} lookups "
                    f"with {int(evict)} evictions — the working set "
                    f"does not fit; a larger {knob} stops the thrash"),
            confidence=round(min(0.9, 0.4 + (0.5 - ratio)), 2),
            knob=knob,
            suggested_value=str(_clamped_double(knob, cur)),
            evidence={"hits": int(hits), "misses": int(misses),
                      "evictions": int(evict),
                      "hit_ratio": round(ratio, 3)})
    return _run


_rule_chunk_cache = _cache_rule(
    "chunk_cache_thrash", "bst_chunk_cache_hits_total",
    "bst_chunk_cache_misses_total", "bst_chunk_cache_evictions_total",
    "BST_CHUNK_CACHE_BYTES")

_rule_tile_cache = _cache_rule(
    "tile_cache_thrash", "bst_tile_cache_hits_total",
    "bst_tile_cache_misses_total", "bst_tile_cache_evict_bytes_total",
    "BST_TILE_CACHE_BYTES")


def _rule_inflight_saturated(rec, flat, trace_rep, wall):
    hw = _sum(flat, "bst_inflight_bytes_highwater")
    if hw <= 0:
        return None
    budget, src = _recorded_budget(rec, "BST_INFLIGHT_BYTES")
    if not budget or hw < _INFLIGHT_SATURATION * budget:
        return None
    return Diagnosis(
        rule="inflight_budget_saturated",
        detail=(f"in-flight high-water {int(hw)} is "
                f"{hw / budget:.0%} of the {int(budget)}-byte dispatch "
                f"window ({src}) — the work loop runs budget-capped; a "
                f"wider window keeps more batches in flight"),
        confidence=0.6,
        knob="BST_INFLIGHT_BYTES",
        suggested_value=str(_clamped_double("BST_INFLIGHT_BYTES", budget)),
        evidence={"highwater_bytes": int(hw), "budget_bytes": int(budget),
                  "budget_source": src,
                  "saturation": round(hw / budget, 3)})


def _rule_dag_backpressure(rec, flat, trace_rep, wall):
    stall = _sum(flat, "bst_dag_producer_stall_seconds_total")
    if stall < max(1.0, _STALL_FRACTION * (wall or 0.0)):
        return None
    cur = config.get_bytes("BST_DAG_EXCHANGE_BYTES")
    return Diagnosis(
        rule="dag_producer_backpressure",
        detail=(f"streamed-pipeline producers stalled {stall:.1f}s on "
                f"block-exchange backpressure"
                + (f" ({stall / wall:.0%} of the {wall:.1f}s wall clock)"
                   if wall else "")
                + " — a larger exchange ledger lets producers run ahead"),
        confidence=round(min(0.9, 0.4 + (stall / wall if wall else 0.2)),
                         2),
        knob="BST_DAG_EXCHANGE_BYTES",
        suggested_value=str(_clamped_double("BST_DAG_EXCHANGE_BYTES", cur)),
        evidence={"stall_seconds": round(stall, 2),
                  "wall_seconds": round(wall or 0.0, 2)})


_MIN_HANDOFF_BLOCKS = 8      # streamed blocks before handoff advice fires
_SPILL_FRACTION = 0.25       # spilled vs served bytes that means undersized


def _rule_dag_handoff_miss(rec, flat, trace_rep, wall):
    """Same-mesh streamed edges resolving through the host chunk LRU (or
    spilling out of HBM) while BST_DAG_HANDOFF_BYTES is off/undersized:
    those blocks could have been served as device arrays — zero D2H and
    zero container re-decode on the edge."""
    streamed = _sum(flat, "bst_dag_blocks_streamed_total")
    if streamed < _MIN_HANDOFF_BLOCKS:
        return None
    served = _sum(flat, "bst_dag_handoff_blocks_total")
    served_b = _sum(flat, "bst_dag_handoff_bytes_served_total")
    spilled = _sum(flat, "bst_dag_handoff_spill_bytes_total")
    elided = _sum(flat, "bst_dag_bytes_elided_total")
    budget, src = _recorded_budget(rec, "BST_DAG_HANDOFF_BYTES")
    tun = config.KNOBS["BST_DAG_HANDOFF_BYTES"].tunable
    lo = int(tun.lo) if tun and tun.lo is not None else 64 << 20
    hi = int(tun.hi) if tun and tun.hi is not None else 8 << 30
    if not budget:
        if served > 0:   # enabled mid-run; nothing to advise
            return None
        # bound the suggestion by what actually flowed over streamed edges
        want = int(min(hi, max(lo, elided)))
        return Diagnosis(
            rule="dag_handoff_miss",
            detail=(f"{int(streamed)} same-mesh streamed blocks resolved "
                    f"through the host chunk LRU with the HBM handoff "
                    f"cache off — a bounded BST_DAG_HANDOFF_BYTES serves "
                    f"them to consumers as device arrays (zero D2H, zero "
                    f"re-decode on those edges)"),
            confidence=0.7,
            knob="BST_DAG_HANDOFF_BYTES",
            suggested_value=str(want),
            evidence={"blocks_streamed": int(streamed),
                      "handoff_blocks": int(served),
                      "bytes_elided": int(elided),
                      "budget_source": src})
    if spilled >= _SPILL_FRACTION * max(served_b, 1.0):
        return Diagnosis(
            rule="dag_handoff_miss",
            detail=(f"{int(spilled)} handoff bytes spilled to the host "
                    f"LRU vs {int(served_b)} served from device under the "
                    f"{int(budget)}-byte HBM budget ({src}) — the handoff "
                    f"working set does not fit; a larger budget keeps "
                    f"those blocks device-resident"),
            confidence=round(min(0.9, 0.4 + spilled
                                  / max(served_b + spilled, 1.0)), 2),
            knob="BST_DAG_HANDOFF_BYTES",
            suggested_value=str(_clamped_double("BST_DAG_HANDOFF_BYTES",
                                                budget)),
            evidence={"spill_bytes": int(spilled),
                      "served_bytes": int(served_b),
                      "handoff_blocks": int(served),
                      "budget_bytes": int(budget),
                      "budget_source": src})
    return None


_PAIR_SPREAD = 0.25          # per-process busy spread that means imbalance
_MIN_PAIR_BUSY_MS = 500.0    # total pair busy before split advice fires


def _by_label(flat: dict[str, float], base: str,
              label: str) -> dict[str, float]:
    """Sum a metric per value of one label (keys look like
    ``name{process="0",stage="match"}``)."""
    out: dict[str, float] = {}
    for k, v in flat.items():
        name, _, rest = k.partition("{")
        if name != base or not rest:
            continue
        for part in rest.rstrip("}").split(","):
            lk, _, lv = part.partition("=")
            if lk.strip() == label:
                lv = lv.strip().strip('"')
                out[lv] = out.get(lv, 0.0) + v
    return out


def _rule_multihost_pair_imbalance(rec, flat, trace_rep, wall):
    """Processes-first pair split where one rank's devices stayed busy
    far longer than another's: the round-robin (count-balanced) split
    handed one process the expensive pairs. No single knob fixes a skew
    in the work itself — the remedy is the cost-weighted split
    (``partition_items_weighted``) with real per-pair costs, so this
    fires knob-less like the cold-bucket rule."""
    busy = _by_label(flat, "bst_pair_proc_busy_ms_total", "process")
    if len(busy) < 2:
        return None
    total = sum(busy.values())
    if total < _MIN_PAIR_BUSY_MS:
        return None
    hi, lo = max(busy.values()), min(busy.values())
    spread = (hi - lo) / hi if hi > 0 else 0.0
    if spread < _PAIR_SPREAD:
        return None
    hot = max(busy, key=busy.get)
    cold = min(busy, key=busy.get)
    return Diagnosis(
        rule="multihost_pair_imbalance",
        detail=(f"multihost pair split is {spread:.0%} imbalanced: "
                f"process {hot} stayed busy {hi:.0f}ms vs {lo:.0f}ms on "
                f"process {cold} — the count-balanced split handed one "
                f"rank the expensive pairs; pass per-pair costs "
                f"(overlap voxels) through the cost-weighted LPT split "
                f"so ranks finish together"),
        confidence=round(min(0.9, 0.4 + spread / 2), 2),
        evidence={"busy_ms_by_process":
                  {k: round(v, 1) for k, v in sorted(busy.items())},
                  "spread": round(spread, 3)})


def _rule_xhost_backpressure(rec, flat, trace_rep, wall):
    stall = _sum(flat, "bst_dag_xhost_stall_seconds_total")
    if stall < max(1.0, _STALL_FRACTION * (wall or 0.0)):
        return None
    fetched = _sum(flat, "bst_dag_xhost_bytes_total")
    cur = config.get_bytes("BST_DAG_EXCHANGE_BYTES")
    return Diagnosis(
        rule="xhost_exchange_backpressure",
        detail=(f"producers stalled {stall:.1f}s on peers' bounded "
                f"cross-host exchange queues"
                + (f" ({stall / wall:.0%} of the {wall:.1f}s wall clock)"
                   if wall else "")
                + " — a larger exchange ledger lets ranks run further "
                "ahead of their slowest consumer"),
        confidence=round(min(0.9, 0.4 + (stall / wall if wall else 0.2)),
                         2),
        knob="BST_DAG_EXCHANGE_BYTES",
        suggested_value=str(_clamped_double("BST_DAG_EXCHANGE_BYTES", cur)),
        evidence={"stall_seconds": round(stall, 2),
                  "xhost_bytes": int(fetched),
                  "wall_seconds": round(wall or 0.0, 2)})


_MIN_REMOTE_READ_BYTES = 64 << 20   # remote bytes before stall advice fires
_REMOTE_READ_DOMINANCE = 0.5        # remote vs total read bytes = "remote run"
_MIN_DISKTIER_SPILL_BYTES = 64 << 20  # spilled bytes before thrash advice
_DISKTIER_SPILL_RATIO = 2.0         # spill vs hit bytes that means write-only


def _rule_remote_read_stall(rec, flat, trace_rep, wall):
    """Remote-dominated reads with the prefetcher off or miss-heavy: the
    read path paid object-store latency synchronously when the drivers
    already announce upcoming boxes — a byte-budgeted read-ahead pool
    (BST_PREFETCH_BYTES, io/prefetch.py) overlaps those fetches with
    compute."""
    remote = _sum(flat, "bst_io_remote_read_bytes_total")
    if remote < _MIN_REMOTE_READ_BYTES:
        return None
    total = _sum(flat, "bst_io_read_bytes_total")
    if total > 0 and remote < _REMOTE_READ_DOMINANCE * total:
        return None
    hits = _sum(flat, "bst_io_prefetch_hit_total")
    misses = _sum(flat, "bst_io_prefetch_miss_total")
    fetched = _sum(flat, "bst_io_prefetch_bytes_total")
    budget, src = _recorded_budget(rec, "BST_PREFETCH_BYTES")
    if fetched <= 0 and hits + misses <= 0:
        # prefetcher never ran: off (budget 0) or starved of feeds
        cur = budget or 0
        return Diagnosis(
            rule="remote_read_stall",
            detail=(f"{int(remote)} bytes read synchronously from a "
                    f"remote object store with the async prefetcher idle "
                    f"— a nonzero BST_PREFETCH_BYTES read-ahead budget "
                    f"overlaps those fetches with compute instead of "
                    f"paying object-store latency per block"),
            confidence=0.7,
            knob="BST_PREFETCH_BYTES",
            suggested_value=str(_clamped_double("BST_PREFETCH_BYTES", cur)),
            evidence={"remote_read_bytes": int(remote),
                      "read_bytes_total": int(total),
                      "prefetch_bytes": int(fetched),
                      "budget_source": src})
    lookups = hits + misses
    if lookups < _MIN_CACHE_OPS:
        return None
    ratio = hits / lookups
    if ratio >= 0.5:
        return None
    return Diagnosis(
        rule="remote_read_stall",
        detail=(f"prefetcher ran miss-heavy on a remote-read-dominated "
                f"run: only {ratio:.0%} of {int(lookups)} tracked chunks "
                f"were consumed before aging out of the "
                f"{int(budget) if budget else 0}-byte read-ahead window "
                f"({src}) — a larger BST_PREFETCH_BYTES keeps announced "
                f"boxes resident until their consumer arrives"),
        confidence=round(min(0.9, 0.4 + (0.5 - ratio)), 2),
        knob="BST_PREFETCH_BYTES",
        suggested_value=str(_clamped_double("BST_PREFETCH_BYTES", budget)),
        evidence={"remote_read_bytes": int(remote),
                  "prefetch_hits": int(hits),
                  "prefetch_misses": int(misses),
                  "hit_ratio": round(ratio, 3),
                  "budget_source": src})


def _rule_disk_tier_thrash(rec, flat, trace_rep, wall):
    """NVMe spill tier writing far more than it serves back: evicted
    chunks cycle through the tier without being re-read before falling
    off its LRU end — disk bandwidth spent for no hit traffic. A larger
    BST_DISK_TIER_BYTES keeps the spilled working set resident long
    enough to be promoted."""
    spill = _sum(flat, "bst_io_disktier_spill_bytes_total")
    if spill < _MIN_DISKTIER_SPILL_BYTES:
        return None
    hit = _sum(flat, "bst_io_disktier_hit_bytes_total")
    if spill < _DISKTIER_SPILL_RATIO * max(hit, 1.0):
        return None
    evict = _sum(flat, "bst_io_disktier_evict_bytes_total")
    budget, src = _recorded_budget(rec, "BST_DISK_TIER_BYTES")
    return Diagnosis(
        rule="disk_tier_thrash",
        detail=(f"disk tier spilled {int(spill)} bytes but served only "
                f"{int(hit)} back ({int(evict)} evicted unread) under "
                f"the {int(budget) if budget else 0}-byte budget ({src}) "
                f"— chunks age out before their re-read; a larger "
                f"BST_DISK_TIER_BYTES stops the write-only churn"),
        confidence=round(min(0.9, 0.4 + min(0.5, spill
                                            / max(hit + spill, 1.0))), 2),
        knob="BST_DISK_TIER_BYTES",
        suggested_value=str(_clamped_double("BST_DISK_TIER_BYTES", budget)),
        evidence={"spill_bytes": int(spill), "hit_bytes": int(hit),
                  "evict_bytes": int(evict),
                  "budget_bytes": int(budget or 0),
                  "budget_source": src})


def _rule_relay_drops(rec, flat, trace_rep, wall):
    drops = _sum(flat, "bst_relay_dropped_total")
    sent = _sum(flat, "bst_relay_sent_total")
    if drops <= 0:
        return None
    cur = config.get_int("BST_RELAY_QUEUE")
    return Diagnosis(
        rule="relay_drops",
        detail=(f"{int(drops)} relay messages dropped"
                + (f" vs {int(sent)} sent" if sent else "")
                + " — the collector falls behind this rank; a deeper "
                "outbound queue absorbs the bursts"),
        confidence=round(min(0.9, 0.3 + min(0.5, drops / max(sent, 1.0))),
                         2),
        knob="BST_RELAY_QUEUE",
        suggested_value=str(_clamped_double("BST_RELAY_QUEUE", cur)),
        evidence={"dropped": int(drops), "sent": int(sent)})


_RULES = (_rule_low_overlap, _rule_cold_buckets, _rule_chunk_cache,
          _rule_tile_cache, _rule_inflight_saturated,
          _rule_dag_backpressure, _rule_dag_handoff_miss,
          _rule_multihost_pair_imbalance, _rule_xhost_backpressure,
          _rule_remote_read_stall, _rule_disk_tier_thrash,
          _rule_relay_drops)


def advise_record(rec: dict,
                  trace_report: dict | None = None) -> list[Diagnosis]:
    """Run every rule over one history record (or manifest doc) plus its
    optional trace-report decomposition; returns fired diagnoses sorted
    by descending confidence."""
    with profiling.span("tune.advise"):
        flat = _flat_metrics(rec)
        wall = float(rec.get("seconds") or 0.0)
        out: list[Diagnosis] = []
        for rule in _RULES:
            d = rule(rec, flat, trace_report, wall)
            if d is not None:
                _metrics.counter("bst_tune_rules_fired_total",
                                 rule=d.rule).inc()
                out.append(d)
        out.sort(key=lambda d: -d.confidence)
        return out


def resolve_evidence(ref: str, *, history_dir: str | None = None,
                     trace: str | None = None
                     ) -> tuple[dict, dict | None, str | None]:
    """Load the evidence behind a reference: the history record (or a
    manifest file), plus the trace-report decomposition when the record
    points at a reachable trace (``trace`` overrides the pointer)."""
    rec = history.load_record(ref, history_dir)
    trace_path = trace
    if trace_path is None:
        tf = rec.get("trace_file")
        if tf:
            if os.path.isabs(tf):
                trace_path = tf
            else:
                base = rec.get("source_manifest")
                if base is None and os.path.exists(ref):
                    base = os.path.abspath(ref)
                if base:
                    trace_path = os.path.join(
                        os.path.dirname(os.path.abspath(base)), tf)
    trace_rep = None
    if trace_path and os.path.exists(trace_path):
        try:
            trace_rep = tracereport.analyze(trace_path)
        except (OSError, ValueError):
            trace_rep = None
    return rec, trace_rep, trace_path


def advise(ref: str, *, history_dir: str | None = None,
           trace: str | None = None) -> tuple[list[Diagnosis], dict]:
    """``bst tune advise``'s engine: resolve evidence, run the rules."""
    rec, trace_rep, _ = resolve_evidence(ref, history_dir=history_dir,
                                         trace=trace)
    return advise_record(rec, trace_rep), rec


def render(diags: list[Diagnosis], rec: dict | None = None) -> str:
    """Human table for ``bst tune advise``."""
    lines = []
    if rec is not None:
        lines.append(f"run {rec.get('id') or rec.get('tool')} "
                     f"({rec.get('tool')}, {rec.get('seconds')}s, "
                     f"status {rec.get('status')})")
    if not diags:
        lines.append("no rules fired — the recorded run shows no "
                     "bottleneck the advisor recognizes")
        return "\n".join(lines)
    lines.append(f"{len(diags)} rule(s) fired:")
    for d in diags:
        knob = (f"{d.knob}={d.suggested_value}" if d.knob
                else "(no single knob)")
        lines.append(f"  [{d.confidence:4.2f}] {d.rule:<26} -> {knob}")
        lines.append(f"         {d.detail}")
    return "\n".join(lines)

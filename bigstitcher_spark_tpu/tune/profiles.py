"""Tuned-profile store: winning knob sets keyed by what they were won on.

A tuned configuration is only portable along the axes it was measured
on — SparkCL's core observation (PAPERS.md, arXiv 1505.01120): the same
kernel wants different shapes per backend. So a profile is keyed by
``backend/device_count/shape-signature`` and lives next to the history
records that justified it, in ``BST_HISTORY_DIR/profiles.json``.

Consumers: ``bst tune list|show|apply`` browse and print; the ``bst
serve`` daemon resolves ``submit --profile auto`` (or the
``BST_PROFILE_AUTO`` knob) against this store and applies the winner's
overrides through ``config.overrides()`` — per job, never the process
environment, the same isolation mechanism every daemon job already uses.
Writes are atomic whole-file replaces (profiles are few and small;
last-writer-wins is acceptable where the index.jsonl's O_APPEND
interleaving is not).
"""

from __future__ import annotations

import json
import os
import time

from ..observe import history

SCHEMA = "bst-tune-profiles/1"


def profiles_path(directory: str | None = None) -> str | None:
    d = history.history_dir(directory)
    return os.path.join(d, "profiles.json") if d else None


def profile_key(backend: str, device_count: int, shape: str) -> str:
    return f"{backend}/{int(device_count)}/{shape}"


def backend_signature() -> tuple[str, int]:
    """(backend platform, local device count) of THIS process — the
    match axes a tuned profile is valid along. Falls back to ("cpu", 1)
    when no accelerator runtime is importable (the jax-free bench
    parent, a bare client host)."""
    try:
        import jax

        return jax.default_backend(), jax.local_device_count()
    except Exception:
        return "cpu", 1


def load_store(directory: str | None = None) -> dict:
    """The whole store; an empty one when the file does not exist yet.
    Raises FileNotFoundError when no history dir is configured at all."""
    path = profiles_path(directory)
    if path is None:
        raise FileNotFoundError(
            "no history dir: set BST_HISTORY_DIR or pass --history-dir")
    if not os.path.exists(path):
        return {"schema": SCHEMA, "profiles": {}}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    doc.setdefault("schema", SCHEMA)
    doc.setdefault("profiles", {})
    return doc


def make_profile(*, backend: str, device_count: int, shape: str,
                 workload: str, overrides: dict[str, str],
                 baseline_seconds: float, best_seconds: float,
                 trials: int, source: str = "tune-run") -> dict:
    return {
        "key": profile_key(backend, device_count, shape),
        "backend": backend,
        "device_count": int(device_count),
        "shape": shape,
        "workload": workload,
        "overrides": dict(overrides),
        "baseline_seconds": round(float(baseline_seconds), 4),
        "best_seconds": round(float(best_seconds), 4),
        "speedup": round(float(baseline_seconds) / float(best_seconds), 4)
        if best_seconds else None,
        "trials": int(trials),
        "source": source,
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }


def save_profile(profile: dict, directory: str | None = None) -> str:
    """Insert/replace the profile under its key; returns the key. The
    write is an atomic whole-file replace."""
    path = profiles_path(directory)
    if path is None:
        raise FileNotFoundError(
            "no history dir: set BST_HISTORY_DIR or pass --history-dir")
    store = load_store(directory)
    key = profile.get("key") or profile_key(
        profile["backend"], profile["device_count"], profile["shape"])
    profile = {**profile, "key": key}
    store["profiles"][key] = profile
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(store, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return key


def match_profile(store: dict, *, backend: str, device_count: int,
                  shape: str | None = None,
                  ref: str = "auto") -> dict | None:
    """Resolve a submit-time profile reference.

    ``ref="auto"``: exact (backend, device_count, shape) key first, then
    the newest profile tuned on the same backend + device count (shape
    drifts between datasets; the backend axes do not). Anything else is
    an explicit key or unique key prefix — explicit requests never fall
    back silently (KeyError instead), because the operator named a
    specific profile."""
    profs: dict[str, dict] = store.get("profiles") or {}
    if ref and ref != "auto":
        if ref in profs:
            return profs[ref]
        hits = [p for k, p in profs.items() if k.startswith(ref)]
        if len(hits) == 1:
            return hits[0]
        if hits:
            raise KeyError(f"profile ref {ref!r} is ambiguous: "
                           f"{sorted(p['key'] for p in hits)[:5]}")
        raise KeyError(f"no profile matching {ref!r}")
    if shape:
        exact = profs.get(profile_key(backend, device_count, shape))
        if exact is not None:
            return exact
    same_axes = [p for p in profs.values()
                 if p.get("backend") == backend
                 and int(p.get("device_count") or 0) == int(device_count)]
    if not same_axes:
        return None
    return max(same_axes, key=lambda p: p.get("created_at") or "")

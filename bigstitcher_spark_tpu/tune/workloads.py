"""Repeatable workloads the autotuner can time.

A tuning trial needs a workload that (a) runs entirely in-process so
``config.overrides()`` reaches it (the env-mutation lint ban stays —
trials must never leak configuration into the process environment), and
(b) is idempotent under re-execution so N trials measure configuration,
not state drift. Three shapes cover the surface:

- ``tiny-fusion`` — the built-in CPU-fallback bench workload: a small
  synthetic project fused through the real CLI path (container create
  once, ``affine-fusion`` per trial, overwriting the same chunks).
- a pipeline-spec path (``*.json``) — replays a ``bst pipeline`` spec,
  so a production pipeline tunes on its own definition.
- :class:`CallableWorkload` — any python callable; the test suite's
  synthetic knob-response workloads use this.
"""

from __future__ import annotations

import os


def _invoke_cli(args: list[str]) -> None:
    """Run a CLI tool in-process (the daemon's execution idiom): the
    ambient config.overrides scope applies, no subprocess fork, and a
    nonzero exit raises instead of killing the tuner."""
    import click

    from ..cli.main import cli as _cli

    try:
        _cli(args=args, prog_name="bst", standalone_mode=False)
    except click.exceptions.Exit as e:
        if e.exit_code != 0:
            raise RuntimeError(f"bst {args[0]} exited {e.exit_code}")
    except SystemExit as e:
        if e.code not in (0, None):
            raise RuntimeError(f"bst {args[0]} exited {e.code}")


class CallableWorkload:
    """Wrap any zero-arg callable as a workload (tests, ad-hoc tuning)."""

    def __init__(self, name: str, fn, shape: str = "synthetic"):
        self.name = name
        self.shape = shape
        self._fn = fn

    def setup(self) -> None:
        pass

    def run(self) -> None:
        self._fn()


class TinyFusionWorkload:
    """The CPU-fallback bench workload: synthetic tiles fused through
    the real container path. ``setup`` builds the project + fusion
    container once; every ``run`` re-executes ``affine-fusion`` into the
    same container (same chunks, deterministic bytes)."""

    name = "tiny-fusion"

    def __init__(self, workdir: str, *, n_tiles=(2, 2, 1),
                 tile_size=(64, 64, 32), overlap=16, n_beads_per_tile=20):
        self.workdir = os.path.abspath(workdir)
        self.n_tiles = tuple(n_tiles)
        self.tile_size = tuple(tile_size)
        self.overlap = overlap
        self.n_beads = n_beads_per_tile
        self.shape = ("t" + "x".join(map(str, self.n_tiles))
                      + "-s" + "x".join(map(str, self.tile_size))
                      + f"-o{overlap}")
        self._ready = False

    @property
    def _proj(self) -> str:
        return os.path.join(self.workdir, "proj")

    @property
    def _out(self) -> str:
        return os.path.join(self.workdir, "fused.ome.zarr")

    def setup(self) -> None:
        if self._ready:
            return
        from ..utils.testdata import make_synthetic_project

        os.makedirs(self.workdir, exist_ok=True)
        if not os.path.exists(os.path.join(self._proj, "dataset.xml")):
            make_synthetic_project(
                self._proj, n_tiles=self.n_tiles,
                tile_size=self.tile_size, overlap=self.overlap,
                jitter=0.0, n_beads_per_tile=self.n_beads)
        _invoke_cli(["create-fusion-container",
                     "-x", os.path.join(self._proj, "dataset.xml"),
                     "-o", self._out, "-s", "ZARR", "-d", "UINT16",
                     "--minIntensity", "0", "--maxIntensity", "65535"])
        self._ready = True

    def run(self) -> None:
        self.setup()
        _invoke_cli(["affine-fusion", "-o", self._out])


class PipelineWorkload:
    """Replay a ``bst pipeline`` spec file per trial — a production
    pipeline tunes against its own definition."""

    def __init__(self, spec_path: str):
        self.spec = os.path.abspath(spec_path)
        self.name = f"pipeline-{os.path.basename(spec_path)}"
        self.shape = f"pipeline-{os.path.basename(spec_path)}"

    def setup(self) -> None:
        if not os.path.exists(self.spec):
            raise FileNotFoundError(self.spec)

    def run(self) -> None:
        _invoke_cli(["pipeline", "run", self.spec])


def resolve_workload(spec: str, workdir: str):
    """``--workload`` resolution: the built-in ``tiny-fusion`` bench
    workload, or a path to a pipeline spec JSON."""
    if spec == "tiny-fusion":
        return TinyFusionWorkload(os.path.join(workdir, "tiny-fusion"))
    if spec.endswith(".json") or os.path.exists(spec):
        return PipelineWorkload(spec)
    raise ValueError(f"unknown workload {spec!r} — expected 'tiny-fusion' "
                     f"or a pipeline spec path")

"""Close the telemetry loop: advisor + autotuner + profile store.

The recording substrate (manifests, traces, history, the relay) answers
"what happened"; this package answers "so what do I change". Three
pieces, composed by the ``bst tune`` CLI:

- :mod:`advisor` — rules over recorded evidence → structured diagnoses.
- :mod:`search` — coordinate descent over advisor-implicated knobs,
  every trial a first-class history record.
- :mod:`profiles` — winners persisted per (backend, device count,
  dataset shape) and applied per job by the serve daemon
  (``bst submit --profile auto`` / ``BST_PROFILE_AUTO``).
"""

from .advisor import Diagnosis, advise, advise_record, render  # noqa: F401
from .profiles import (backend_signature, load_store,  # noqa: F401
                       match_profile, profile_key, save_profile)
from .search import Trial, TuneResult, autotune  # noqa: F401
from .workloads import (CallableWorkload, PipelineWorkload,  # noqa: F401
                        TinyFusionWorkload, resolve_workload)

"""Coordinate-descent knob autotuner over advisor-implicated knobs.

The search space is deliberately small: only knobs the advisor implicated
(or the operator forced with ``--knob``) are searched, each within its
declared ``Tunable`` bounds, by hill-climbing from the advisor's
suggested value (pow2 or linear steps per the metadata). Every timed
execution — a *trial* — runs the workload IN-PROCESS under
``config.overrides(candidate)``: the same contextvars isolation layer
daemon jobs use, never the process environment (the env-mutation lint
ban stays load-bearing here). Each trial is wrapped in an
:class:`observe.JobRun`, so it lands in the history store as a first-
class record (tool ``tune-trial``) and ``bst perf-diff`` works on trials
exactly like on production runs.

The winner can never regress the default: the baseline configuration is
measured with the same best-of-N protocol first, and a candidate only
displaces it by beating it by ``min_gain`` — ties and noise keep the
empty override set.
"""

from __future__ import annotations

import contextlib
import math
import os
import time
from dataclasses import asdict, dataclass, field

from .. import config, observe, profiling
from ..observe import history
from ..observe import metrics as _metrics
from . import advisor as _advisor
from . import profiles as _profiles


@dataclass
class Trial:
    """One timed workload execution under one override set."""

    n: int
    overrides: dict
    seconds: float
    record_id: str | None = None
    status: str = "ok"

    def as_dict(self) -> dict:
        return asdict(self)


@dataclass
class TuneResult:
    workload: str
    shape: str
    backend: str
    device_count: int
    baseline_seconds: float
    best_seconds: float
    best_overrides: dict
    trials: list[Trial] = field(default_factory=list)
    diagnoses: list = field(default_factory=list)
    profile_key: str | None = None

    def as_dict(self) -> dict:
        return {
            "workload": self.workload, "shape": self.shape,
            "backend": self.backend, "device_count": self.device_count,
            "baseline_seconds": round(self.baseline_seconds, 4),
            "best_seconds": round(self.best_seconds, 4),
            "speedup": round(self.baseline_seconds / self.best_seconds, 4)
            if self.best_seconds else None,
            "best_overrides": dict(self.best_overrides),
            "trials": [t.as_dict() for t in self.trials],
            "diagnoses": [d.as_dict() for d in self.diagnoses],
            "profile_key": self.profile_key,
        }


def _current_raw(name: str) -> str | None:
    """The resolved knob value as the raw override string it would take
    to pin it there."""
    v = config.get(name)
    if v is None:
        return None
    if isinstance(v, bool):
        return "1" if v else "0"
    return str(v)


def _step_value(knob: config.Knob, raw: str | None,
                direction: int) -> str | None:
    """One tunable step up/down from ``raw``; None at a bound (or for
    non-numeric kinds, which enumerate choices instead of walking)."""
    t = knob.tunable
    if t is None or knob.kind not in ("int", "bytes"):
        return None
    try:
        v = int(float(raw)) if raw is not None else None
    except (TypeError, ValueError):
        v = None
    if v is None or v <= 0:
        v = int(t.lo) if t.lo else 1
        return str(v) if direction > 0 else None
    if t.scale == "linear":
        nv = v + direction * int(t.step or 1)
    else:
        nv = v * 2 if direction > 0 else v // 2
    if t.lo is not None:
        nv = max(nv, int(t.lo))
    if t.hi is not None:
        nv = min(nv, int(t.hi))
    return str(nv) if nv > 0 and nv != v else None


def _discrete_candidates(knob: config.Knob,
                         base_raw: str | None) -> list[str]:
    if knob.kind == "bool":
        cur = (base_raw or ("1" if knob.default else "0"))
        truthy = cur.strip().lower() not in config._FALSY
        return ["0" if truthy else "1"]
    if knob.choices:
        return [c for c in knob.choices if c != base_raw]
    return []


def autotune(workload, *, diagnoses=None, force_knobs=(),
             trials_per_config: int = 2, max_trials: int = 12,
             min_gain: float = 0.02, history_dir: str | None = None,
             workdir: str | None = None, warmup: bool = True,
             save: bool = True) -> TuneResult:
    """Tune ``workload``: measure the baseline, advise on it (unless
    ``diagnoses`` is given), hill-climb each implicated knob, and — with
    ``save`` — persist the winner as a profile for this (backend,
    device count, workload shape).

    ``max_trials`` caps total timed executions; the baseline is always
    fully measured, and the search stops early once the remaining budget
    cannot fit another best-of-``trials_per_config`` configuration."""
    workdir = os.path.abspath(workdir or os.path.join(
        history.history_dir(history_dir) or ".", "tune-work"))
    os.makedirs(workdir, exist_ok=True)
    trials_per_config = max(1, int(trials_per_config))
    max_trials = max(trials_per_config, int(max_trials))

    scope = {"BST_HISTORY_DIR": history_dir} if history_dir else {}
    with config.overrides(scope):
        return _autotune_inner(workload, diagnoses, force_knobs,
                               trials_per_config, max_trials, min_gain,
                               workdir, warmup, save)


def _last_record_id() -> str | None:
    try:
        entries = history.list_records(None, tool="tune-trial", limit=1)
    except FileNotFoundError:
        return None
    return entries[-1]["id"] if entries else None


def _autotune_inner(workload, diagnoses, force_knobs, trials_per_config,
                    max_trials, min_gain, workdir, warmup,
                    save) -> TuneResult:
    trials: list[Trial] = []

    def budget_left() -> int:
        return max_trials - len(trials)

    def measure(cfg: dict[str, str], label: str) -> float:
        """Best-of-N timed executions under ``cfg``; each execution is a
        history-recorded trial. A crashing CANDIDATE reads as infinitely
        slow (the search simply never adopts it); a crashing baseline
        aborts the tune."""
        best = math.inf
        for _ in range(trials_per_config):
            n = len(trials) + 1
            t_dir = os.path.join(workdir, "trials", f"{n:03d}")
            os.makedirs(t_dir, exist_ok=True)
            _metrics.counter("bst_tune_trials_total",
                             workload=workload.name).inc()
            status, err = "ok", None
            with config.overrides(cfg):
                with profiling.span("tune.trial", stage=workload.name,
                                    item=n):
                    jr = observe.JobRun(f"tune-{n:03d}", t_dir,
                                        tool="tune-trial")
                    t0 = time.perf_counter()
                    try:
                        # workload chatter goes to the trial's own
                        # output.log (the daemon's per-job idiom), so
                        # `bst tune run --json` stays machine-readable
                        with open(os.path.join(t_dir, "output.log"), "w",
                                  encoding="utf-8") as lf, \
                                contextlib.redirect_stdout(lf), \
                                contextlib.redirect_stderr(lf):
                            with jr:
                                workload.run()
                    except Exception as e:   # noqa: BLE001 — see docstring
                        status, err = "error", repr(e)
                    dt = time.perf_counter() - t0
                    jr.finalize(status=status, error=err,
                                params={"trial": n, "config": label,
                                        "workload": workload.name,
                                        "overrides": dict(cfg)},
                                argv=["tune-trial", workload.name])
            rid = _last_record_id()
            trials.append(Trial(n=n, overrides=dict(cfg),
                                seconds=round(dt, 4), record_id=rid,
                                status=status))
            if status == "ok":
                best = min(best, dt)
        if math.isinf(best) and label == "baseline":
            raise RuntimeError(
                f"workload {workload.name!r} failed under the default "
                f"configuration: {err}")
        return best

    with open(os.path.join(workdir, "setup.log"), "w",
              encoding="utf-8") as lf, \
            contextlib.redirect_stdout(lf), contextlib.redirect_stderr(lf):
        workload.setup()
        if warmup:
            # one untimed, unrecorded execution: page cache + jit warmup
            # so the baseline is not penalized for going first
            workload.run()

    baseline_s = measure({}, "baseline")
    if diagnoses is None:
        rec_id = _last_record_id()
        rec = history.load_record(rec_id) if rec_id else None
        diagnoses = _advisor.advise_record(rec) if rec else []

    tunables = config.tunable_knobs()
    targets: list[tuple[str, str | None]] = []
    seen = set()
    for name in force_knobs:
        if name in tunables and name not in seen:
            targets.append((name, None))
            seen.add(name)
    for d in diagnoses:
        if d.knob and d.knob in tunables and d.knob not in seen:
            targets.append((d.knob, d.suggested_value))
            seen.add(d.knob)

    best_cfg: dict[str, str] = {}
    best_s = baseline_s
    for name, seed in targets:
        if budget_left() < trials_per_config:
            break
        knob = tunables[name]
        base_raw = best_cfg.get(name, _current_raw(name))
        tried = {base_raw}
        if knob.kind in ("int", "bytes"):
            start = seed if (seed and seed not in tried) \
                else _step_value(knob, base_raw, +1)
            if start is None or start in tried:
                continue
            s = measure({**best_cfg, name: start}, name)
            tried.add(start)
            knob_best: tuple[str, float] | None = \
                (start, s) if s < best_s else None
            for direction in (+1, -1):
                v, vs = start, s
                while budget_left() >= trials_per_config:
                    nv = _step_value(knob, v, direction)
                    if nv is None or nv in tried:
                        break
                    ns = measure({**best_cfg, name: nv}, name)
                    tried.add(nv)
                    if ns < vs:
                        v, vs = nv, ns
                        if knob_best is None or ns < knob_best[1]:
                            knob_best = (nv, ns)
                    else:
                        break
            if knob_best and knob_best[1] < best_s * (1 - min_gain):
                best_cfg = {**best_cfg, name: knob_best[0]}
                best_s = knob_best[1]
        else:
            for cand in _discrete_candidates(knob, base_raw):
                if budget_left() < trials_per_config:
                    break
                s = measure({**best_cfg, name: cand}, name)
                if s < best_s * (1 - min_gain):
                    best_cfg = {**best_cfg, name: cand}
                    best_s = s

    backend, n_dev = _profiles.backend_signature()
    result = TuneResult(
        workload=workload.name, shape=workload.shape,
        backend=backend, device_count=n_dev,
        baseline_seconds=baseline_s, best_seconds=best_s,
        best_overrides=best_cfg, trials=trials,
        diagnoses=list(diagnoses))
    if save:
        prof = _profiles.make_profile(
            backend=backend, device_count=n_dev, shape=workload.shape,
            workload=workload.name, overrides=best_cfg,
            baseline_seconds=baseline_s, best_seconds=best_s,
            trials=len(trials))
        result.profile_key = _profiles.save_profile(prof)
    return result
